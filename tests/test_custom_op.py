"""Custom python-callback ops (ref: python/mxnet/operator.py surface;
tests/python/unittest/test_operator.py:test_custom_op patterns).

The VERDICT gap: eager-only autograd.Function existed, but no python op
usable from jit/hybridize/Symbol. These tests pin all three paths.
"""
import numpy as np
import pytest

import mxtpu as mx
from mxtpu import gluon
from mxtpu.base import MXNetError
from mxtpu.gluon import nn


@mx.operator.register("sqr")
class SqrProp(mx.operator.CustomOpProp):
    def __init__(self):
        super().__init__(need_top_grad=True)

    def create_operator(self, ctx, in_shapes, in_dtypes):
        return Sqr()


class Sqr(mx.operator.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        self.assign(out_data[0], req[0], in_data[0].asnumpy() ** 2)

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        self.assign(in_grad[0], req[0],
                    2 * in_data[0].asnumpy() * out_grad[0].asnumpy())


def test_custom_eager_forward_backward():
    x = mx.nd.array([[1.0, 2.0], [3.0, 4.0]])
    x.attach_grad()
    with mx.autograd.record():
        y = mx.nd.Custom(x, op_type="sqr")
        loss = y.sum()
    loss.backward()
    np.testing.assert_allclose(y.asnumpy(), x.asnumpy() ** 2)
    np.testing.assert_allclose(x.grad.asnumpy(), 2 * x.asnumpy())


def test_custom_unregistered_raises():
    with pytest.raises(MXNetError, match="not registered"):
        mx.nd.Custom(mx.nd.ones((2,)), op_type="nope")


class _CustomBlock(gluon.HybridBlock):
    def __init__(self, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.dense = nn.Dense(4, in_units=3)

    def hybrid_forward(self, F, x):
        return F.Custom(self.dense(x), op_type="sqr")


def test_custom_trains_inside_hybridized_block():
    """The VERDICT item verbatim: a python-defined op trains inside a
    hybridized (jit-compiled) block."""
    np.random.seed(0)
    net = _CustomBlock()
    net.initialize()
    net.hybridize()
    x = mx.nd.array(np.random.uniform(0.5, 1.5, (8, 3)))
    y = mx.nd.array(np.random.uniform(0.5, 1.5, (8, 4)))
    loss_fn = gluon.loss.L2Loss()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05})
    first = None
    for _ in range(15):
        with mx.autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        trainer.step(8)
        v = float(loss.mean().asnumpy())
        first = first if first is not None else v
    assert v < first * 0.8, (first, v)


def test_custom_from_symbol():
    data = mx.sym.var("data")
    out = mx.sym.Custom(data, op_type="sqr", name="sq")
    exe = out.simple_bind(data=(2, 3))
    r = exe.forward(data=mx.nd.full((2, 3), 3.0))[0]
    np.testing.assert_allclose(r.asnumpy(), np.full((2, 3), 9.0))
    exe.backward(out_grads=mx.nd.ones((2, 3)))
    np.testing.assert_allclose(exe.grad_dict["data"].asnumpy(),
                               np.full((2, 3), 6.0))


@mx.operator.register("twoout")
class TwoOutProp(mx.operator.CustomOpProp):
    def list_outputs(self):
        return ["sum", "diff"]

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0], in_shape[0]], []

    def create_operator(self, ctx, in_shapes, in_dtypes):
        return TwoOut()


class TwoOut(mx.operator.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        a = in_data[0].asnumpy()
        self.assign(out_data[0], req[0], a + 1.0)
        self.assign(out_data[1], req[1], a - 1.0)

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        self.assign(in_grad[0], req[0],
                    out_grad[0].asnumpy() + out_grad[1].asnumpy())


def test_custom_multi_output():
    x = mx.nd.array([1.0, 2.0])
    outs = mx.nd.Custom(x, op_type="twoout")
    np.testing.assert_allclose(outs[0].asnumpy(), [2.0, 3.0])
    np.testing.assert_allclose(outs[1].asnumpy(), [0.0, 1.0])
