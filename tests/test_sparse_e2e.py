"""Sparse end-to-end (VERDICT r2 item 7 / BASELINE config 5):
LibSVMIter, gather/segment-sum csr x dense dot, row-sparse gradients with
lazy Adam, and the linear-classification example converging.

Reference: src/io/iter_libsvm.cc, src/operator/tensor/dot-inl.h sparse
paths, example/sparse/linear_classification/.
"""
import importlib.util
import os

import numpy as np
import pytest
import scipy.sparse

import mxtpu as mx
from mxtpu.io import LibSVMIter
from mxtpu.ndarray.sparse import CSRNDArray

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _example():
    spec = importlib.util.spec_from_file_location(
        "sparse_lc", os.path.join(REPO, "examples", "sparse",
                                  "linear_classification.py"))
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)
    return m


@pytest.fixture(scope="module")
def libsvm_file(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("svm") / "data.libsvm")
    _example().make_synthetic_libsvm(path, num_rows=300, num_features=500,
                                     nnz_per_row=12)
    return path


def test_libsvm_iter_parses(libsvm_file):
    it = LibSVMIter(data_libsvm=libsvm_file, data_shape=(500,),
                    batch_size=64)
    nb = 0
    for batch in it:
        x = batch.data[0]
        assert isinstance(x, CSRNDArray)
        assert x.shape == (64, 500)
        assert batch.label[0].shape == (64,)
        dense = x.asnumpy()
        # every row has exactly 12 nonzeros (last batch wraps, same rows)
        assert (np.count_nonzero(dense, axis=1) == 12).all()
        nb += 1
    assert nb == (300 + 63) // 64


def test_libsvm_iter_values_roundtrip(tmp_path):
    path = str(tmp_path / "tiny.libsvm")
    with open(path, "w") as f:
        f.write("1 0:0.5 3:2.0\n0 1:1.5\n1 2:-1.0 4:0.25\n")
    it = LibSVMIter(data_libsvm=path, data_shape=(5,), batch_size=3)
    batch = next(iter(it))
    dense = batch.data[0].asnumpy()
    expect = np.array([[0.5, 0, 0, 2.0, 0],
                       [0, 1.5, 0, 0, 0],
                       [0, 0, -1.0, 0, 0.25]], np.float32)
    np.testing.assert_allclose(dense, expect)
    np.testing.assert_allclose(batch.label[0].asnumpy(), [1, 0, 1])


def test_libsvm_iter_sharding(libsvm_file):
    full = LibSVMIter(data_libsvm=libsvm_file, data_shape=(500,),
                      batch_size=10)
    part0 = LibSVMIter(data_libsvm=libsvm_file, data_shape=(500,),
                       batch_size=10, num_parts=2, part_index=0)
    part1 = LibSVMIter(data_libsvm=libsvm_file, data_shape=(500,),
                       batch_size=10, num_parts=2, part_index=1)
    assert part0.num_data + part1.num_data == full.num_data
    assert abs(part0.num_data - part1.num_data) <= 1


def test_csr_dot_matches_scipy():
    r = np.random.RandomState(0)
    sp = scipy.sparse.random(50, 400, density=0.03, random_state=r,
                             format="csr", dtype=np.float32)
    rhs = r.uniform(-1, 1, (400, 7)).astype(np.float32)
    x = CSRNDArray(sp.data, sp.indptr, sp.indices, sp.shape)
    got = mx.nd.sparse.dot(x, mx.nd.array(rhs)).asnumpy()
    np.testing.assert_allclose(got, sp @ rhs, rtol=1e-4, atol=1e-5)


def test_csr_dot_avoids_densification():
    """The csr x dense hot path must do O(nnz*C) work — probe by checking
    the jaxpr contains no op with the dense (rows, features) shape."""
    import jax

    r = np.random.RandomState(0)
    sp = scipy.sparse.random(8, 100000, density=0.0002, random_state=r,
                             format="csr", dtype=np.float32)
    rhs = r.uniform(-1, 1, (100000, 4)).astype(np.float32)
    from mxtpu.ndarray.sparse import _csr_dns_dot

    jaxpr = jax.make_jaxpr(
        lambda d, ip, ix, rh: _csr_dns_dot(d, ip, ix, 8, rh))(
        sp.data, sp.indptr.astype(np.int32), sp.indices.astype(np.int32),
        rhs)
    for eqn in jaxpr.eqns:
        for v in eqn.outvars:
            shape = getattr(v.aval, "shape", ())
            assert shape != (8, 100000), "densified inside csr dot"


def test_linear_classification_example_converges(libsvm_file):
    m = _example()
    acc, losses = m.train(libsvm_file, 500, batch_size=50, epochs=4)
    assert losses[-1] < losses[0] * 0.7, losses
    assert acc > 0.8, acc


def test_linear_classification_with_kvstore_row_sparse_pull(libsvm_file):
    m = _example()
    kv = mx.kv.create("local")
    acc, losses = m.train(libsvm_file, 500, batch_size=50, epochs=3, kv=kv)
    assert losses[-1] < losses[0], losses


def test_csr_dot_gradient_taped():
    """sparse.dot's csr fast path must be autograd-visible: grads flow to
    the dense rhs under record() (review finding: the raw-jnp path was
    untaped)."""
    from mxtpu import autograd

    r = np.random.RandomState(0)
    sp = scipy.sparse.random(6, 40, density=0.2, random_state=r,
                             format="csr", dtype=np.float32)
    w = mx.nd.array(r.uniform(-1, 1, (40, 3)).astype(np.float32))
    w.attach_grad()
    x = CSRNDArray(sp.data, sp.indptr, sp.indices, sp.shape)
    with autograd.record():
        out = mx.nd.sparse.dot(x, w)
        loss = out.sum()
    loss.backward()
    g = w.grad.asnumpy()
    # d(sum(x@w))/dw = x^T @ ones
    expect = np.asarray(sp.sum(axis=0)).ravel()[:, None].repeat(3, 1)
    np.testing.assert_allclose(g, expect, rtol=1e-4, atol=1e-5)


def test_row_sparse_pull_into_row_sparse_out():
    """row_sparse_pull with a RowSparseNDArray out (the reference's primary
    use, kvstore.h PullRowSparse)."""
    from mxtpu.ndarray.sparse import RowSparseNDArray

    kv = mx.kv.create("local")
    w = mx.nd.array(np.arange(20, dtype=np.float32).reshape(10, 2))
    kv.init("w", w)
    out = RowSparseNDArray(np.zeros((2, 2), np.float32),
                           np.array([0, 1], np.int32), (10, 2))
    kv.row_sparse_pull("w", out=out, row_ids=mx.nd.array([3, 7]))
    got = out.asnumpy()
    expect = np.zeros((10, 2), np.float32)
    expect[3] = [6, 7]
    expect[7] = [14, 15]
    np.testing.assert_allclose(got, expect)


def test_libsvm_iter_rejects_out_of_range_indices(tmp_path):
    path = str(tmp_path / "onebased.libsvm")
    with open(path, "w") as f:
        f.write("1 1:0.5 5:2.0\n")  # 1-based, max idx == data_shape[0]
    with pytest.raises(Exception, match="1-based"):
        LibSVMIter(data_libsvm=path, data_shape=(5,), batch_size=1)
