"""Flash attention tests. On the CPU test mesh the Pallas path is skipped
(`_supported` is False) — these validate the fallback and the blockwise
backward math; the Pallas kernel itself is validated on the TPU chip
(same comparisons, run via bench/verify flows)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mxtpu.ops.pallas.flash_attention import (_fa_backward_blockwise,
                                              _xla_attention, flash_attention)


def _rand(shape, seed):
    return jnp.asarray(np.random.RandomState(seed).normal(
        size=shape).astype(np.float32))


@pytest.mark.parametrize("causal", [False, True])
def test_flash_fallback_matches_xla(causal):
    q, k, v = (_rand((2, 3, 64, 16), s) for s in range(3))
    out = flash_attention(q, k, v, causal)
    ref = _xla_attention(q, k, v, causal, 1.0 / 4.0)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("causal", [False, True])
def test_blockwise_backward_math(causal):
    """The O(T*D)-memory backward equations must match autodiff exactly."""
    b, h, t, d = 1, 2, 64, 16
    q, k, v = (_rand((b, h, t, d), s) for s in range(3))
    scale = 1.0 / (d ** 0.5)
    g = _rand((b, h, t, d), 99)

    out, vjp = jax.vjp(lambda q_, k_, v_:
                       _xla_attention(q_, k_, v_, causal, scale), q, k, v)
    dq_ref, dk_ref, dv_ref = vjp(g)

    # lse as the pallas kernel would save it
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        mask = jnp.arange(t)[:, None] >= jnp.arange(t)[None, :]
        s = jnp.where(mask[None, None], s, -1e30)
    lse = jax.scipy.special.logsumexp(s, axis=-1)

    dq, dk, dv = _fa_backward_blockwise(q, k, v, out, lse, g, causal, scale,
                                        block_k=16)
    np.testing.assert_allclose(np.asarray(dq), np.asarray(dq_ref),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(dk), np.asarray(dk_ref),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(dv), np.asarray(dv_ref),
                               rtol=1e-4, atol=1e-5)


def test_flash_grad_through_custom_vjp():
    q, k, v = (_rand((1, 2, 32, 8), s) for s in range(3))

    def loss(q, k, v):
        return jnp.sum(flash_attention(q, k, v, True) ** 2)

    g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    assert all(jnp.all(jnp.isfinite(x)) for x in g)
    assert float(jnp.abs(g[0]).sum()) > 0


def test_flash_attention_with_lse_matches_dense():
    """(out, lse) fallback pair vs direct logsumexp + softmax, and the
    custom_vjp with a NONZERO lse cotangent vs jax.vjp of the plain XLA
    implementation (pins the g_lse term in the blockwise backward)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from mxtpu.ops.pallas.flash_attention import (_xla_attention_lse,
                                                  flash_attention_with_lse)

    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(2, 2, 16, 8).astype(np.float32))
    k = jnp.asarray(rng.randn(2, 2, 16, 8).astype(np.float32))
    v = jnp.asarray(rng.randn(2, 2, 16, 8).astype(np.float32))
    for causal in (False, True):
        out, lse = flash_attention_with_lse(q, k, v, causal, None, 8, 8)
        ref_out, ref_lse = _xla_attention_lse(q, k, v, causal,
                                              1.0 / (8 ** 0.5))
        np.testing.assert_allclose(out, ref_out, rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(lse, ref_lse, rtol=1e-5, atol=1e-5)

        g = jnp.asarray(rng.randn(*out.shape).astype(np.float32))
        g_lse = jnp.asarray(rng.randn(*lse.shape).astype(np.float32))

        def fa(q_, k_, v_):
            return flash_attention_with_lse(q_, k_, v_, causal, None, 8, 8)

        def ref(q_, k_, v_):
            return _xla_attention_lse(q_, k_, v_, causal, 1.0 / (8 ** 0.5))

        _, vjp_fa = jax.vjp(fa, q, k, v)
        _, vjp_ref = jax.vjp(ref, q, k, v)
        for a, b in zip(vjp_fa((g, g_lse)), vjp_ref((g, g_lse))):
            np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-4)


def test_blockwise_backward_g_lse_term():
    """_fa_backward_blockwise with a g_lse cotangent must equal jax.vjp of
    the XLA (out, lse) pair — pins the TPU backward's lse math on CPU."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from mxtpu.ops.pallas.flash_attention import (_fa_backward_blockwise,
                                                  _xla_attention_lse)

    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.randn(1, 2, 16, 8).astype(np.float32))
    k = jnp.asarray(rng.randn(1, 2, 16, 8).astype(np.float32))
    v = jnp.asarray(rng.randn(1, 2, 16, 8).astype(np.float32))
    scale = 1.0 / (8 ** 0.5)
    for causal in (False, True):
        out, lse = _xla_attention_lse(q, k, v, causal, scale)
        g = jnp.asarray(rng.randn(*out.shape).astype(np.float32))
        g_lse = jnp.asarray(rng.randn(*lse.shape).astype(np.float32))
        dq, dk, dv = _fa_backward_blockwise(q, k, v, out, lse, g, causal,
                                            scale, block_k=8, g_lse=g_lse)
        _, vjp = jax.vjp(lambda q_, k_, v_:
                         _xla_attention_lse(q_, k_, v_, causal, scale),
                         q, k, v)
        for a, b in zip((dq, dk, dv), vjp((g, g_lse))):
            np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-4)
