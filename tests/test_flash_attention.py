"""Flash attention tests. On the CPU test mesh the Pallas path is skipped
(`_supported` is False) — these validate the fallback and the blockwise
backward math; the Pallas kernel itself is validated on the TPU chip
(same comparisons, run via bench/verify flows)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mxtpu.ops.pallas.flash_attention import (_fa_backward_blockwise,
                                              _xla_attention, flash_attention)


def _rand(shape, seed):
    return jnp.asarray(np.random.RandomState(seed).normal(
        size=shape).astype(np.float32))


@pytest.mark.parametrize("causal", [False, True])
def test_flash_fallback_matches_xla(causal):
    q, k, v = (_rand((2, 3, 64, 16), s) for s in range(3))
    out = flash_attention(q, k, v, causal)
    ref = _xla_attention(q, k, v, causal, 1.0 / 4.0)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("causal", [False, True])
def test_blockwise_backward_math(causal):
    """The O(T*D)-memory backward equations must match autodiff exactly."""
    b, h, t, d = 1, 2, 64, 16
    q, k, v = (_rand((b, h, t, d), s) for s in range(3))
    scale = 1.0 / (d ** 0.5)
    g = _rand((b, h, t, d), 99)

    out, vjp = jax.vjp(lambda q_, k_, v_:
                       _xla_attention(q_, k_, v_, causal, scale), q, k, v)
    dq_ref, dk_ref, dv_ref = vjp(g)

    # lse as the pallas kernel would save it
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        mask = jnp.arange(t)[:, None] >= jnp.arange(t)[None, :]
        s = jnp.where(mask[None, None], s, -1e30)
    lse = jax.scipy.special.logsumexp(s, axis=-1)

    dq, dk, dv = _fa_backward_blockwise(q, k, v, out, lse, g, causal, scale,
                                        block_k=16)
    np.testing.assert_allclose(np.asarray(dq), np.asarray(dq_ref),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(dk), np.asarray(dk_ref),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(dv), np.asarray(dv_ref),
                               rtol=1e-4, atol=1e-5)


def test_flash_grad_through_custom_vjp():
    q, k, v = (_rand((1, 2, 32, 8), s) for s in range(3))

    def loss(q, k, v):
        return jnp.sum(flash_attention(q, k, v, True) ** 2)

    g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    assert all(jnp.all(jnp.isfinite(x)) for x in g)
    assert float(jnp.abs(g[0]).sum()) > 0


def test_flash_attention_with_lse_matches_dense():
    """(out, lse) fallback pair vs direct logsumexp + softmax, and the
    custom_vjp with a NONZERO lse cotangent vs jax.vjp of the plain XLA
    implementation (pins the g_lse term in the blockwise backward)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from mxtpu.ops.pallas.flash_attention import (_xla_attention_lse,
                                                  flash_attention_with_lse)

    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(2, 2, 16, 8).astype(np.float32))
    k = jnp.asarray(rng.randn(2, 2, 16, 8).astype(np.float32))
    v = jnp.asarray(rng.randn(2, 2, 16, 8).astype(np.float32))
    for causal in (False, True):
        out, lse = flash_attention_with_lse(q, k, v, causal, None, 8, 8)
        ref_out, ref_lse = _xla_attention_lse(q, k, v, causal,
                                              1.0 / (8 ** 0.5))
        np.testing.assert_allclose(out, ref_out, rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(lse, ref_lse, rtol=1e-5, atol=1e-5)

        g = jnp.asarray(rng.randn(*out.shape).astype(np.float32))
        g_lse = jnp.asarray(rng.randn(*lse.shape).astype(np.float32))

        def fa(q_, k_, v_):
            return flash_attention_with_lse(q_, k_, v_, causal, None, 8, 8)

        def ref(q_, k_, v_):
            return _xla_attention_lse(q_, k_, v_, causal, 1.0 / (8 ** 0.5))

        _, vjp_fa = jax.vjp(fa, q, k, v)
        _, vjp_ref = jax.vjp(ref, q, k, v)
        for a, b in zip(vjp_fa((g, g_lse)), vjp_ref((g, g_lse))):
            np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-4)


def test_blockwise_backward_g_lse_term():
    """_fa_backward_blockwise with a g_lse cotangent must equal jax.vjp of
    the XLA (out, lse) pair — pins the TPU backward's lse math on CPU."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from mxtpu.ops.pallas.flash_attention import (_fa_backward_blockwise,
                                                  _xla_attention_lse)

    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.randn(1, 2, 16, 8).astype(np.float32))
    k = jnp.asarray(rng.randn(1, 2, 16, 8).astype(np.float32))
    v = jnp.asarray(rng.randn(1, 2, 16, 8).astype(np.float32))
    scale = 1.0 / (8 ** 0.5)
    for causal in (False, True):
        out, lse = _xla_attention_lse(q, k, v, causal, scale)
        g = jnp.asarray(rng.randn(*out.shape).astype(np.float32))
        g_lse = jnp.asarray(rng.randn(*lse.shape).astype(np.float32))
        dq, dk, dv = _fa_backward_blockwise(q, k, v, out, lse, g, causal,
                                            scale, block_k=8, g_lse=g_lse)
        _, vjp = jax.vjp(lambda q_, k_, v_:
                         _xla_attention_lse(q_, k_, v_, causal, scale),
                         q, k, v)
        for a, b in zip((dq, dk, dv), vjp((g, g_lse))):
            np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-4)


def test_pick_block_divisor_selection():
    import importlib
    fa = importlib.import_module("mxtpu.ops.pallas.flash_attention")
    # 768 not divisible by 512: largest 128-multiple divisor is 384
    assert fa._pick_block(768, 512, 128) == 384
    assert fa._pick_block(1536, 512, 128) == 512
    assert fa._pick_block(1000, 512, 8) == 200
    assert fa._pick_block(100, 512, 8) is None      # no 8-multiple divisor
    assert fa._pick_block(4096, 512, 128) == 512
    assert fa._pick_block(256, 512, 128) == 256     # clamp to T


def test_tpu_shaped_fallback_warns_once_and_stays_correct(monkeypatch):
    """VERDICT r4 weak #7: the memory-cliff fallback must be loud. A
    'TPU' platform with an untileable shape warns ONCE per shape and
    still computes the exact XLA result."""
    import warnings as _warnings
    import importlib
    fa = importlib.import_module("mxtpu.ops.pallas.flash_attention")
    monkeypatch.setattr(fa, "_platform", lambda: "tpu")
    fa._warned_fallbacks.clear()
    rng = np.random.RandomState(0)
    # T=16 has no 128-lane k block -> fallback on "TPU" (head dim 64 no
    # longer falls back: it pads to the lane granule, r5)
    q = jnp.asarray(rng.randn(1, 2, 16, 64), jnp.float32)
    k = jnp.asarray(rng.randn(1, 2, 16, 64), jnp.float32)
    v = jnp.asarray(rng.randn(1, 2, 16, 64), jnp.float32)
    with pytest.warns(UserWarning, match="falling back to the XLA softmax"):
        out = fa.flash_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(fa._xla_attention(q, k, v, False,
                                                            64 ** -0.5)),
                               rtol=1e-5, atol=1e-5)
    # same shape again: silent (warned once)
    with _warnings.catch_warnings():
        _warnings.simplefilter("error")
        fa.flash_attention(q, k, v)
    # a different offending shape warns again
    q2 = jnp.asarray(rng.randn(1, 2, 100, 128), jnp.float32)
    k2 = jnp.asarray(rng.randn(1, 2, 100, 128), jnp.float32)
    v2 = jnp.asarray(rng.randn(1, 2, 100, 128), jnp.float32)
    with pytest.warns(UserWarning, match="no TPU-tileable block"):
        fa.flash_attention(q2, k2, v2)


def test_off_tpu_fallback_is_silent():
    import warnings as _warnings
    import importlib
    fa = importlib.import_module("mxtpu.ops.pallas.flash_attention")
    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.randn(1, 1, 12, 16), jnp.float32)
    with _warnings.catch_warnings():
        _warnings.simplefilter("error")
        fa.flash_attention(q, q, q)  # CPU platform: expected fallback


def test_backward_block_divides_ragged_tk():
    """Gradients must cover ALL keys when tk is not divisible by the
    default 512 (regression: the backward clamp dropped the ragged tail)."""
    import importlib
    fa = importlib.import_module("mxtpu.ops.pallas.flash_attention")
    rng = np.random.RandomState(2)
    shape = (1, 1, 24, 8)   # tk=24; old clamp min(512,24)=24 ok, but use
    q = jnp.asarray(rng.randn(*shape), jnp.float32)
    k = jnp.asarray(rng.randn(*shape), jnp.float32)
    v = jnp.asarray(rng.randn(*shape), jnp.float32)
    scale = 8 ** -0.5
    out, lse = fa._xla_attention_lse(q, k, v, False, scale)
    g = jnp.ones_like(out)
    # explicit ragged block request: 16 does not divide 24; resolver picks 12
    dq, dk, dv = fa._fa_backward_blockwise(q, k, v, out, lse, g, False,
                                           scale, fa._pick_block(24, 16, 1))
    ref = jax.vjp(lambda a, b, c: fa._xla_attention(a, b, c, False, scale),
                  q, k, v)[1](g)
    np.testing.assert_allclose(np.asarray(dq), np.asarray(ref[0]), rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(dk), np.asarray(ref[1]), rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(dv), np.asarray(ref[2]), rtol=1e-4,
                               atol=1e-5)


def test_pick_block_rounds_small_requests_up_to_granule():
    import importlib
    fa = importlib.import_module("mxtpu.ops.pallas.flash_attention")
    # user asks for block_k=64 (< the 128-lane granule): round UP, don't
    # fall back (regression: returned None and warned misleadingly)
    assert fa._pick_block(512, 64, 128) == 128
    assert fa._pick_block(512, 4, 8) == 8
    assert fa._pick_block(64, 64, 128) is None  # n itself below granule


def test_head_dim_64_pads_instead_of_falling_back(monkeypatch):
    """BERT-base head dim (64) must take the fused kernel via zero-padding
    to the 128-lane granule, not the HBM-cliff fallback (r5)."""
    import importlib
    fa = importlib.import_module("mxtpu.ops.pallas.flash_attention")
    monkeypatch.setattr(fa, "_platform", lambda: "tpu")
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(1, 2, 512, 64), jnp.float32)
    blocks = fa._resolve_blocks(q, q, 512, 512)
    assert blocks is not None  # no fallback for D=64
    # padding invariance of the attention math the kernel relies on:
    # zero-padded q/k leave scores unchanged, zero-padded v adds zero
    # output columns
    k = jnp.asarray(rng.randn(1, 2, 512, 64), jnp.float32)
    v = jnp.asarray(rng.randn(1, 2, 512, 64), jnp.float32)
    scale = 64 ** -0.5
    qp, kp, vp, d = fa._pad_head_dim(q, k, v)
    assert d == 64 and qp.shape[-1] == 128
    base = fa._xla_attention(q, k, v, False, scale)
    padded = fa._xla_attention(qp, kp, vp, False, scale)[..., :64]
    np.testing.assert_allclose(np.asarray(base), np.asarray(padded),
                               rtol=1e-5, atol=1e-5)
    # lse is invariant too (ring attention merges on it)
    _, lse_base = fa._xla_attention_lse(q, k, v, False, scale)
    _, lse_pad = fa._xla_attention_lse(qp, kp, vp, False, scale)
    np.testing.assert_allclose(np.asarray(lse_base), np.asarray(lse_pad),
                               rtol=1e-5, atol=1e-5)


def test_pad_head_dim_noop_on_granule():
    import importlib
    fa = importlib.import_module("mxtpu.ops.pallas.flash_attention")
    q = jnp.zeros((1, 1, 8, 128), jnp.float32)
    qp, kp, vp, d = fa._pad_head_dim(q, q, q)
    assert qp is q and kp is q and vp is q and d == 128
