"""Gluon layer tests (ref: tests/python/unittest/test_gluon.py, test_loss.py).

Covers: parameter registration & sharing, Dense/Conv/Pooling/BatchNorm/LayerNorm
layers, deferred shape inference, hybridize (compiled forward/backward parity with
eager), Trainer+optimizer end-to-end, losses, save/load round-trips, RNN layers.
"""
import numpy as np
import pytest

import mxtpu as mx
from mxtpu import autograd, gluon
from mxtpu.gluon import nn, rnn


def test_parameter():
    p = gluon.Parameter("weight", shape=(10, 10))
    p.initialize(init="xavier")
    assert p.data().shape == (10, 10)
    assert p.grad().shape == (10, 10)


def test_paramdict_save_load(tmp_path):
    params = gluon.ParameterDict("net_")
    w = params.get("weight", shape=(4, 5))
    params.initialize()
    fname = str(tmp_path / "pd.params")
    params.save(fname)
    params2 = gluon.ParameterDict("net_")
    w2 = params2.get("weight", shape=(4, 5))
    params2.load(fname)
    np.testing.assert_allclose(w.data().asnumpy(), w2.data().asnumpy())


def test_parameter_sharing():
    class Net(gluon.Block):
        def __init__(self, **kwargs):
            super().__init__(**kwargs)
            with self.name_scope():
                self.dense0 = nn.Dense(5, in_units=5)
                self.dense1 = nn.Dense(5, in_units=5)

        def forward(self, x):
            return self.dense1(self.dense0(x))

    net1 = Net(prefix="net1_")
    net2 = Net(prefix="net2_", params=net1.collect_params())
    net1.collect_params().initialize()
    net2(mx.nd.zeros((3, 5)))
    net1.save_parameters("/tmp/net1.params")
    net3 = Net(prefix="net3_")
    net3.load_parameters("/tmp/net1.params")


def test_dense_flatten():
    net = nn.Dense(8, flatten=True, in_units=12)
    net.initialize()
    x = mx.nd.ones((4, 3, 4))
    assert net(x).shape == (4, 8)
    net2 = nn.Dense(8, flatten=False, in_units=4)
    net2.initialize()
    assert net2(x).shape == (4, 3, 8)


def test_deferred_init_and_infer_shape():
    net = nn.Dense(8)
    net.initialize()
    x = mx.nd.ones((4, 7))
    net(x)
    assert net.weight.shape == (8, 7)


@pytest.mark.parametrize("hybridize", [False, True])
def test_mlp_training_decreases_loss(hybridize):
    np.random.seed(0)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(32, activation="relu"), nn.Dense(2))
    net.initialize(init="xavier")
    if hybridize:
        net.hybridize()
    # separable toy data
    x = mx.nd.array(np.random.randn(64, 8).astype("float32"))
    w_true = np.random.randn(8).astype("float32")
    y = mx.nd.array((x.asnumpy() @ w_true > 0).astype("float32"))
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.5})
    losses = []
    for _ in range(20):
        with autograd.record():
            l = loss_fn(net(x), y)
        l.backward()
        trainer.step(64)
        losses.append(float(l.asnumpy().mean()))
    assert losses[-1] < losses[0] * 0.7, losses


def test_hybrid_eager_parity():
    """Compiled forward must equal eager forward (the reference's
    check_consistency pattern, SURVEY §4)."""
    np.random.seed(0)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Conv2D(4, 3, padding=1, in_channels=3),
                nn.BatchNorm(in_channels=4),
                nn.Activation("relu"),
                nn.MaxPool2D(),
                nn.Flatten(),
                nn.Dense(6, in_units=4 * 8 * 8))
    net.initialize()
    x = mx.nd.array(np.random.randn(2, 3, 16, 16).astype("float32"))
    eager = net(x).asnumpy()
    net.hybridize()
    compiled = net(x).asnumpy()
    np.testing.assert_allclose(eager, compiled, rtol=1e-5, atol=1e-5)


def test_hybrid_grad_parity():
    np.random.seed(0)
    x_np = np.random.randn(4, 5).astype("float32")

    def run(hybridize):
        mx.random.seed(7)
        net = nn.Dense(3, in_units=5)
        net.initialize(init="one")
        if hybridize:
            net.hybridize()
        x = mx.nd.array(x_np)
        x.attach_grad()
        with autograd.record():
            out = net(x)
            l = (out * out).sum()
        l.backward()
        return x.grad.asnumpy(), net.weight.grad().asnumpy()

    xg_e, wg_e = run(False)
    xg_h, wg_h = run(True)
    np.testing.assert_allclose(xg_e, xg_h, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(wg_e, wg_h, rtol=1e-5, atol=1e-5)


def test_batchnorm_moving_stats_update_hybrid():
    net = nn.BatchNorm(in_channels=3)
    net.initialize()
    net.hybridize()
    x = mx.nd.array(np.random.randn(8, 3, 4, 4).astype("float32") * 3 + 1)
    before = net.running_mean.data().asnumpy().copy()
    with autograd.record():
        net(x)
    after = net.running_mean.data().asnumpy()
    assert not np.allclose(before, after)


def test_pool_layers():
    x = mx.nd.array(np.random.randn(2, 3, 8, 8).astype("float32"))
    assert nn.MaxPool2D()(x).shape == (2, 3, 4, 4)
    assert nn.AvgPool2D(pool_size=2, strides=1)(x).shape == (2, 3, 7, 7)
    assert nn.GlobalAvgPool2D()(x).shape == (2, 3, 1, 1)
    assert nn.GlobalMaxPool2D()(x).shape == (2, 3, 1, 1)
    x1 = mx.nd.array(np.random.randn(2, 3, 8).astype("float32"))
    assert nn.MaxPool1D()(x1).shape == (2, 3, 4)
    x3 = mx.nd.array(np.random.randn(2, 3, 8, 8, 8).astype("float32"))
    assert nn.MaxPool3D()(x3).shape == (2, 3, 4, 4, 4)


def test_conv_layers():
    x = mx.nd.array(np.random.randn(2, 3, 10, 10).astype("float32"))
    c = nn.Conv2D(8, 3, padding=1)
    c.initialize()
    assert c(x).shape == (2, 8, 10, 10)
    ct = nn.Conv2DTranspose(4, 2, strides=2, in_channels=8)
    ct.initialize()
    assert ct(c(x)).shape == (2, 4, 20, 20)
    c1 = nn.Conv1D(6, 3)
    c1.initialize()
    x1 = mx.nd.array(np.random.randn(2, 3, 10).astype("float32"))
    assert c1(x1).shape == (2, 6, 8)
    # grouped conv
    cg = nn.Conv2D(8, 3, groups=2, in_channels=4)
    cg.initialize()
    xg = mx.nd.array(np.random.randn(2, 4, 6, 6).astype("float32"))
    assert cg(xg).shape == (2, 8, 4, 4)


def test_layernorm_instancenorm():
    x = mx.nd.array(np.random.randn(2, 5, 4).astype("float32"))
    ln = nn.LayerNorm(in_channels=4)
    ln.initialize()
    out = ln(x).asnumpy()
    np.testing.assert_allclose(out.mean(-1), 0, atol=1e-5)
    inorm = nn.InstanceNorm(in_channels=5)
    inorm.initialize()
    assert inorm(x).shape == x.shape


def test_embedding():
    emb = nn.Embedding(10, 4)
    emb.initialize()
    idx = mx.nd.array(np.array([[1, 2], [3, 4]]))
    assert emb(idx).shape == (2, 2, 4)
    # gradient flows into rows
    with autograd.record():
        out = emb(idx).sum()
    out.backward()
    g = emb.weight.grad().asnumpy()
    assert g[1].sum() != 0 and g[0].sum() == 0


def test_activations_layers():
    x = mx.nd.array(np.array([-2.0, -0.5, 0.5, 2.0], dtype="float32"))
    for act in [nn.LeakyReLU(0.1), nn.ELU(), nn.SELU(), nn.Swish(), nn.GELU()]:
        act.initialize()
        y = act(x).asnumpy()
        assert y.shape == x.shape
    prelu = nn.PReLU()
    prelu.initialize()
    y = prelu(x).asnumpy()
    np.testing.assert_allclose(y[0], -0.5, rtol=1e-5)


def test_sequential_slicing():
    net = nn.HybridSequential()
    net.add(nn.Dense(4), nn.Dense(5), nn.Dense(6))
    assert len(net) == 3
    assert isinstance(net[1], nn.Dense)
    assert len(net[1:]) == 2


def test_losses_numeric():
    pred = mx.nd.array(np.array([[1.0, 2.0, 3.0], [3.0, 2.0, 1.0]], dtype="float32"))
    label = mx.nd.array(np.array([2, 0], dtype="float32"))
    l = gluon.loss.SoftmaxCrossEntropyLoss()(pred, label).asnumpy()
    ref = -np.log(np.exp(3) / np.exp([1, 2, 3]).sum())
    np.testing.assert_allclose(l, [ref, ref], rtol=1e-5)

    pred = mx.nd.array(np.array([[0.5, -0.5]], dtype="float32"))
    label = mx.nd.array(np.array([[1.0, 0.0]], dtype="float32"))
    l2 = gluon.loss.L2Loss()(pred, label).asnumpy()
    np.testing.assert_allclose(l2, [((0.5 - 1) ** 2 + 0.5 ** 2) / 2 / 2], rtol=1e-5)
    l1 = gluon.loss.L1Loss()(pred, label).asnumpy()
    np.testing.assert_allclose(l1, [(0.5 + 0.5) / 2], rtol=1e-5)

    bce = gluon.loss.SigmoidBinaryCrossEntropyLoss()(pred, label).asnumpy()
    p = 1 / (1 + np.exp(-np.array([0.5, -0.5])))
    ref_bce = -(np.log(p[0]) + np.log(1 - p[1])) / 2
    np.testing.assert_allclose(bce, [ref_bce], rtol=1e-4)

    hu = gluon.loss.HuberLoss()(pred, label).asnumpy()
    assert hu.shape == (1,)
    hi = gluon.loss.HingeLoss()(pred, mx.nd.array(np.array([[1.0, -1.0]]))).asnumpy()
    np.testing.assert_allclose(hi, [(0.5 + 0.5) / 2], rtol=1e-5)


def test_save_load_parameters_roundtrip(tmp_path):
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(5, in_units=4), nn.Dense(3, in_units=5))
    net.initialize()
    fname = str(tmp_path / "m.params")
    net.save_parameters(fname)
    x = mx.nd.ones((2, 4))
    expected = net(x).asnumpy()
    net2 = nn.HybridSequential()
    with net2.name_scope():
        net2.add(nn.Dense(5, in_units=4), nn.Dense(3, in_units=5))
    net2.load_parameters(fname)
    np.testing.assert_allclose(net2(x).asnumpy(), expected, rtol=1e-6)


@pytest.mark.parametrize("mode", ["lstm", "gru", "rnn"])
def test_rnn_layers(mode):
    T, N, C, H = 5, 3, 4, 6
    x = mx.nd.array(np.random.randn(T, N, C).astype("float32"))
    layer = {"lstm": rnn.LSTM, "gru": rnn.GRU, "rnn": rnn.RNN}[mode](H, 2)
    layer.initialize()
    out = layer(x)
    assert out.shape == (T, N, H)
    states = layer.begin_state(batch_size=N)
    out, new_states = layer(x, states)
    assert out.shape == (T, N, H)
    assert new_states[0].shape == (2, N, H)
    # gradient flows
    with autograd.record():
        loss = layer(x).sum()
    loss.backward()
    g = layer.l0_i2h_weight.grad().asnumpy()
    assert np.abs(g).sum() > 0


def test_rnn_bidirectional():
    T, N, C, H = 4, 2, 3, 5
    x = mx.nd.array(np.random.randn(T, N, C).astype("float32"))
    layer = rnn.LSTM(H, 1, bidirectional=True)
    layer.initialize()
    assert layer(x).shape == (T, N, 2 * H)


def test_rnn_ntc_layout():
    N, T, C, H = 2, 4, 3, 5
    x = mx.nd.array(np.random.randn(N, T, C).astype("float32"))
    layer = rnn.GRU(H, 1, layout="NTC")
    layer.initialize()
    assert layer(x).shape == (N, T, H)


def test_trainer_save_load_states(tmp_path):
    net = nn.Dense(3, in_units=4)
    net.initialize()
    tr = gluon.Trainer(net.collect_params(), "adam", {"learning_rate": 0.01})
    x = mx.nd.ones((2, 4))
    with autograd.record():
        l = net(x).sum()
    l.backward()
    tr.step(2)
    fname = str(tmp_path / "t.states")
    tr.save_states(fname)
    tr.load_states(fname)


def test_clip_global_norm():
    arrays = [mx.nd.ones((2, 2)) * 3, mx.nd.ones((3,)) * 4]
    total = gluon.utils.clip_global_norm(arrays, 1.0)
    assert total > 1.0
    new_total = float(np.sqrt(sum((a.asnumpy() ** 2).sum() for a in arrays)))
    np.testing.assert_allclose(new_total, 1.0, rtol=1e-4)


def test_block_repr_and_summary(capsys):
    net = nn.HybridSequential()
    net.add(nn.Dense(4, in_units=3))
    net.initialize()
    repr(net)
    net.summary(mx.nd.ones((1, 3)))
    out = capsys.readouterr().out
    assert "Total params" in out


def test_hooks():
    net = nn.Dense(2, in_units=2)
    net.initialize()
    calls = []
    h1 = net.register_forward_pre_hook(lambda blk, inp: calls.append("pre"))
    h2 = net.register_forward_hook(lambda blk, inp, out: calls.append("post"))
    net(mx.nd.ones((1, 2)))
    assert calls == ["pre", "post"]
    h1.detach()
    net(mx.nd.ones((1, 2)))
    assert calls == ["pre", "post", "post"]
