"""Subgraph/partition framework tests
(ref: src/operator/subgraph/subgraph_property.h, partition_graph.cc;
tests/python/unittest test patterns for default_subgraph_property)."""
import numpy as np

import mxtpu as mx
from mxtpu.symbol import partition
from mxtpu.symbol.symbol import _topo


def _ops_of(sym):
    return [n.op for n in _topo(sym._heads) if not n.is_var()]


def _mlp_symbol():
    data = mx.sym.Variable("data")
    h = mx.sym.FullyConnected(data, weight=mx.sym.Variable("w1"),
                              bias=mx.sym.Variable("b1"), num_hidden=8,
                              name="fc1")
    h = mx.sym.Activation(h, act_type="relu")
    out = mx.sym.FullyConnected(h, weight=mx.sym.Variable("w2"),
                                bias=mx.sym.Variable("b2"), num_hidden=4,
                                name="fc2")
    return out


def _feed(sym, shapes, seed=0):
    r = np.random.RandomState(seed)
    arg_shapes, _, aux_shapes = sym.infer_shape(**shapes)
    args = {n: mx.nd.array(r.uniform(-1, 1, s).astype(np.float32))
            for n, s in zip(sym.list_arguments(), arg_shapes)}
    aux = {n: mx.nd.array(r.uniform(0.1, 1, s).astype(np.float32))
           for n, s in zip(sym.list_auxiliary_states(), aux_shapes)}
    return args, aux


def test_default_property_single_node():
    sym = _mlp_symbol()
    part = partition(sym, "default")
    ops = _ops_of(part)
    assert ops == ["_subgraph_exec"], ops


def test_default_property_outputs_match():
    sym = _mlp_symbol()
    args, aux = _feed(sym, {"data": (3, 6)})
    ref = sym.bind(args=args, aux_states=aux, grad_req="null") \
        .forward(is_train=False)[0].asnumpy()
    part = partition(sym, "default")
    # same arguments, same order: the partitioned graph exposes the same
    # variable surface
    assert sorted(part.list_arguments()) == sorted(sym.list_arguments())
    got = part.bind(args=args, aux_states=aux, grad_req="null") \
        .forward(is_train=False)[0].asnumpy()
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


def test_default_property_zoo_model():
    """Partition a real model-zoo network (VERDICT r2 item 5's bar)."""
    from mxtpu.gluon.model_zoo import vision
    from mxtpu.symbol.symbol import trace_block

    net = vision.get_model("squeezenet1_0", classes=10)
    net.initialize()
    x = mx.nd.array(np.random.RandomState(0)
                    .uniform(-1, 1, (1, 3, 64, 64)).astype(np.float32))
    ref = net(x).asnumpy()
    sym, _ = trace_block(net)
    args, aux = {}, {}
    for name, p in net.collect_params().items():
        (aux if p.grad_req == "null" else args)[name] = p.data()
    args["data"] = x
    part = partition(sym, "default")
    assert _ops_of(part) == ["_subgraph_exec"]
    got = part.bind(args=args, aux_states=aux, grad_req="null") \
        .forward(is_train=False)[0].asnumpy()
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)


def test_partition_leaves_original_intact():
    sym = _mlp_symbol()
    n_before = len(_ops_of(sym))
    partition(sym, "default")
    assert len(_ops_of(sym)) == n_before


def test_flash_attention_property():
    """The attention chain softmax(QK^T * scale) @ V is swapped for the
    Pallas flash kernel node and numerics match the unfused graph."""
    B, T, D = 2, 8, 16
    q = mx.sym.Variable("q")
    k = mx.sym.Variable("k")
    v = mx.sym.Variable("v")
    scores = mx.sym.batch_dot(q, k, transpose_b=True) * (1.0 / D ** 0.5)
    probs = mx.sym.softmax(scores, axis=-1)
    out = mx.sym.batch_dot(probs, v)

    part = partition(out, "flash_attention")
    ops = _ops_of(part)
    assert ops == ["_sg_flash_attention"], ops

    r = np.random.RandomState(0)
    feed = {n: mx.nd.array(r.uniform(-1, 1, (B, T, D)).astype(np.float32))
            for n in ("q", "k", "v")}
    ref = out.bind(args=feed, grad_req="null") \
        .forward(is_train=False)[0].asnumpy()
    got = part.bind(args=feed, grad_req="null") \
        .forward(is_train=False)[0].asnumpy()
    np.testing.assert_allclose(got, ref, rtol=2e-3, atol=2e-3)


def test_flash_attention_property_no_false_positive():
    """A softmax that is not part of an attention chain must be left
    completely untouched (no opaque wrapper, no flash node)."""
    x = mx.sym.Variable("x")
    out = mx.sym.softmax(x, axis=-1)
    part = partition(out, "flash_attention")
    ops = _ops_of(part)
    assert ops == ["softmax"], ops
    feed = {"x": mx.nd.array(np.random.RandomState(0)
                             .uniform(-1, 1, (2, 5)).astype(np.float32))}
    ref = out.bind(args=feed, grad_req="null").forward()[0].asnumpy()
    got = part.bind(args=feed, grad_req="null").forward()[0].asnumpy()
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


def test_custom_property_registration():
    """User-defined properties register and partition (ref:
    MXNET_REGISTER_SUBGRAPH_PROPERTY)."""
    from mxtpu.symbol import (SubgraphProperty, SubgraphSelector,
                              register_subgraph_property)

    class _FCSel(SubgraphSelector):
        def select(self, node):
            return node.op == "FullyConnected"

        def select_output(self, node, output_node):
            return output_node.op == "Activation"

    class FCActProperty(SubgraphProperty):
        name = "test_fc_act"

        def create_selector(self):
            return _FCSel()

    register_subgraph_property(FCActProperty())
    sym = _mlp_symbol()
    part = partition(sym, "test_fc_act")
    ops = _ops_of(part)
    # fc1+relu fuse into one region; fc2 seeds its own region
    assert ops.count("_subgraph_exec") == 2 and len(ops) == 2
    args, aux = _feed(sym, {"data": (3, 6)})
    ref = sym.bind(args=args, aux_states=aux, grad_req="null") \
        .forward()[0].asnumpy()
    got = part.bind(args=args, aux_states=aux, grad_req="null") \
        .forward()[0].asnumpy()
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


def test_flash_attention_rejects_noncanonical_transposes():
    """transpose_a on scores or transposes on probs@v change the math: the
    matcher must refuse and leave the graph alone."""
    B, T, D = 2, 4, 8
    q, k, v = (mx.sym.Variable(n) for n in "qkv")
    scores = mx.sym.batch_dot(q, k, transpose_a=True)
    probs = mx.sym.softmax(scores, axis=-1)
    out = mx.sym.batch_dot(probs, v)
    part = partition(out, "flash_attention")
    assert "_sg_flash_attention" not in _ops_of(part)


def test_subgraph_training_mode_uses_batch_stats():
    """Inside a partitioned region, training-mode BatchNorm must normalize
    by batch stats (mode resolved at call time, not baked at jit time)."""
    from mxtpu import autograd as ag
    data = mx.sym.Variable("data")
    out = mx.sym.BatchNorm(data, gamma=mx.sym.Variable("g"),
                           beta=mx.sym.Variable("b"),
                           moving_mean=mx.sym.Variable("mm_moving_mean"),
                           moving_var=mx.sym.Variable("mv_moving_var"),
                           fix_gamma=False)
    part = partition(out, "default")
    x = np.random.RandomState(0).uniform(5, 6, (8, 3)).astype(np.float32)
    feed = {"data": mx.nd.array(x), "g": mx.nd.ones((3,)),
            "b": mx.nd.zeros((3,))}
    aux = {"mm_moving_mean": mx.nd.zeros((3,)),
           "mv_moving_var": mx.nd.ones((3,))}
    exe = part.bind(args=feed, aux_states=aux, grad_req="null")
    got_train = exe.forward(is_train=True)[0].asnumpy()
    # batch stats -> near zero mean; moving stats (0/1) -> near x itself
    assert abs(got_train.mean()) < 0.1
    got_eval = exe.forward(is_train=False)[0].asnumpy()
    assert abs(got_eval.mean() - x.mean()) < 0.1
