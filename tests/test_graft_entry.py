"""The driver contract (__graft_entry__.py) must keep compiling: entry()
single-device and dryrun_multichip at a NON-power-of-two device count
(the driver itself runs n=8; n=6 catches the even/composite
generalizations). Runs in a subprocess because the dryrun must own jax
backend initialization."""
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.multidevice
def test_dryrun_multichip_n6():
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    env["PYTHONPATH"] = REPO
    out = subprocess.run(
        [sys.executable, "-c",
         "import __graft_entry__ as g; g.dryrun_multichip(6); print('OK6')"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=900)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "OK6" in out.stdout


def test_mesh_axes_factoring():
    from __graft_entry__ import _mesh_axes, _spf
    assert _spf(6) == 2 and _spf(7) == 7 and _spf(9) == 3
    for n in (1, 2, 3, 4, 6, 8, 9, 12):
        ax = _mesh_axes(n)
        assert ax["data"] * ax["sp"] * ax["model"] == n, (n, ax)
    assert _mesh_axes(6) == {"data": 3, "sp": 1, "model": 2}
    assert _mesh_axes(8) == {"data": 2, "sp": 2, "model": 2}
