"""Oracle tests for the round-3 parity ops: deformable conv family, RPN
proposals, bipartite matching, ravel/unravel, reshape_like, getnnz,
quantized flatten/pooling, legacy v1 aliases, KL sparse reg,
SparseEmbedding, GroupAdaGrad."""
import numpy as np
import jax.numpy as jnp
import pytest

import mxtpu as mx
from mxtpu.ops.contrib_ops import (DeformableConvolution,
                                   DeformablePSROIPooling, MultiProposal,
                                   Proposal, PSROIPooling,
                                   bipartite_matching)
from mxtpu.ops.legacy_vision import IdentityAttachKLSparseReg
from mxtpu.ops.matrix import (SparseEmbedding, _ravel_multi_index,
                              _unravel_index, getnnz, reshape_like)
from mxtpu.ops.nn import Convolution
from mxtpu.ops.quantization import quantized_flatten, quantized_pooling


def test_bipartite_matching_reference_example():
    # the exact doc example from bounding_box.cc:162
    s = jnp.array([[0.5, 0.6], [0.1, 0.2], [0.3, 0.4]])
    x, y = bipartite_matching(s, threshold=1e-12)
    assert list(x.asnumpy()) == [1, -1, 0]
    assert list(y.asnumpy()) == [2, 0]
    # ascending mode picks smallest first
    x2, _y2 = bipartite_matching(s, is_ascend=True, threshold=1e6)
    assert x2.asnumpy()[1] == 0  # smallest score 0.1 at row1/col0 matched


def test_psroipooling_position_sensitive_mapping():
    g, p, od = 2, 2, 3
    c = od * g * g
    data = jnp.broadcast_to(
        jnp.arange(c, dtype=jnp.float32)[None, :, None, None], (1, c, 8, 8))
    rois = jnp.array([[0, 0, 0, 7, 7]], jnp.float32)
    out = PSROIPooling(data, rois, spatial_scale=1.0, output_dim=od,
                       pooled_size=p, group_size=g)
    np.testing.assert_allclose(out.asnumpy()[0],
                               np.arange(c).reshape(od, g, g), atol=1e-5)


def test_deformable_psroipooling_zero_and_const_offsets():
    g, p, od = 2, 2, 2
    c = od * g * g
    data = jnp.broadcast_to(
        jnp.arange(c, dtype=jnp.float32)[None, :, None, None], (1, c, 8, 8))
    rois = jnp.array([[0, 0, 0, 7, 7]], jnp.float32)
    out = DeformablePSROIPooling(data, rois, None, spatial_scale=1.0,
                                 output_dim=od, group_size=g, pooled_size=p,
                                 no_trans=True)
    np.testing.assert_allclose(out.asnumpy()[0],
                               np.arange(c).reshape(od, g, g), atol=1e-5)
    # constant-per-channel input is shift-invariant under learned offsets
    tr = jnp.ones((1, 2, p, p), jnp.float32)
    out2 = DeformablePSROIPooling(data, rois, tr, spatial_scale=1.0,
                                  output_dim=od, group_size=g,
                                  pooled_size=p, trans_std=0.1)
    np.testing.assert_allclose(out2.asnumpy()[0],
                               np.arange(c).reshape(od, g, g), atol=1e-5)


@pytest.mark.parametrize("stride,dilate,pad,groups", [
    ((1, 1), (1, 1), (1, 1), 1),
    ((2, 2), (2, 2), (2, 2), 1),
    ((1, 1), (1, 1), (1, 1), 2),
])
def test_deformable_conv_zero_offset_equals_conv(stride, dilate, pad, groups):
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(2, 4, 9, 9), jnp.float32)
    wt = jnp.asarray(rng.randn(6, 4 // groups, 3, 3) * 0.2, jnp.float32)
    hout = (9 + 2 * pad[0] - dilate[0] * 2 - 1) // stride[0] + 1
    off = jnp.zeros((2, 2 * 9, hout, hout), jnp.float32)
    dc = DeformableConvolution(x, off, wt, kernel=(3, 3), stride=stride,
                               dilate=dilate, pad=pad, num_filter=6,
                               num_group=groups, no_bias=True)
    ref = Convolution(x, wt, kernel=(3, 3), stride=stride, dilate=dilate,
                      pad=pad, num_filter=6, num_group=groups, no_bias=True)
    np.testing.assert_allclose(dc.asnumpy(), ref.asnumpy(), rtol=1e-4,
                               atol=1e-4)


def test_deformable_conv_integer_offset_shifts_input():
    # offset of exactly (0, +1) on every tap == conv of x shifted left by 1
    rng = np.random.RandomState(1)
    x = np.zeros((1, 1, 6, 6), np.float32)
    x[0, 0] = rng.randn(6, 6)
    wt = jnp.asarray(rng.randn(1, 1, 1, 1), jnp.float32)  # 1x1 kernel
    off = np.zeros((1, 2, 6, 6), np.float32)
    off[0, 1] = 1.0  # x-offset +1
    dc = DeformableConvolution(jnp.asarray(x), jnp.asarray(off), wt,
                               kernel=(1, 1), num_filter=1, no_bias=True)
    shifted = np.zeros_like(x)
    shifted[0, 0, :, :-1] = x[0, 0, :, 1:]
    expect = shifted * np.asarray(wt)[0, 0, 0, 0]
    np.testing.assert_allclose(dc.asnumpy(), expect, rtol=1e-4, atol=1e-5)


def test_proposal_and_multiproposal():
    rng = np.random.RandomState(0)
    N, A, H, W = 2, 3, 4, 4
    import jax
    cls = jax.nn.softmax(jnp.asarray(rng.randn(N, 2 * A, H, W), jnp.float32),
                         axis=1)
    bbox = jnp.asarray(rng.randn(N, 4 * A, H, W) * 0.1, jnp.float32)
    info = jnp.asarray([[64, 64, 1.0]] * N, jnp.float32)
    rois = MultiProposal(cls, bbox, info, rpn_pre_nms_top_n=12,
                         rpn_post_nms_top_n=5, scales=(8,),
                         ratios=(0.5, 1, 2), feature_stride=16)
    r = rois.asnumpy()
    assert r.shape == (10, 5)
    assert set(r[:, 0]) == {0.0, 1.0}
    assert (r[:, 1] >= 0).all() and (r[:, 3] <= 63).all()
    assert (r[:, 1] <= r[:, 3]).all() and (r[:, 2] <= r[:, 4]).all()
    rois1, scores1 = Proposal(cls[:1], bbox[:1], info[:1],
                              rpn_pre_nms_top_n=12, rpn_post_nms_top_n=4,
                              scales=(8,), ratios=(0.5, 1, 2),
                              feature_stride=16, output_score=True)
    assert rois1.shape == (4, 5) and scores1.shape == (4, 1)
    # scores are sorted descending (greedy NMS preserves score order)
    s = scores1.asnumpy().ravel()
    assert (np.diff(s) <= 1e-6).all()


def test_ravel_unravel_roundtrip():
    coords = jnp.array([[0, 1, 2], [1, 0, 3]])
    flat = _ravel_multi_index(coords, shape=(3, 4))
    assert list(flat.asnumpy()) == [1, 4, 11]
    back = _unravel_index(flat, shape=(3, 4))
    np.testing.assert_array_equal(back.asnumpy(), np.asarray(coords))


def test_reshape_like_and_getnnz():
    a = jnp.arange(12.0).reshape(3, 4)
    assert reshape_like(a, jnp.zeros((2, 6))).shape == (2, 6)
    assert reshape_like(a, jnp.zeros((4, 3)), lhs_begin=0, lhs_end=2,
                        rhs_begin=0, rhs_end=2).shape == (4, 3)
    m = jnp.array([[1.0, 0.0], [2.0, 3.0]])
    assert int(getnnz(m).asnumpy()) == 3
    np.testing.assert_array_equal(getnnz(m, axis=0).asnumpy(), [2, 1])


def test_quantized_flatten_and_pooling():
    d = jnp.asarray(np.arange(-8, 8, dtype=np.int8).reshape(1, 1, 4, 4))
    mn, mx_ = jnp.float32(-1.0), jnp.float32(1.0)
    f, fmn, fmx = quantized_flatten(d, mn, mx_)
    assert f.shape == (1, 16)
    assert float(fmn.asnumpy()) == -1.0 and float(fmx.asnumpy()) == 1.0
    p, pmn, pmx = quantized_pooling(d, mn, mx_, kernel=(2, 2), stride=(2, 2),
                                    pool_type="max")
    assert p.asnumpy().dtype == np.int8
    np.testing.assert_array_equal(p.asnumpy()[0, 0],
                                  [[-3, -1], [5, 7]])
    pa, _, _ = quantized_pooling(d, mn, mx_, kernel=(2, 2), stride=(2, 2),
                                 pool_type="avg")
    assert pa.asnumpy().dtype == np.int8


def test_v1_aliases_match_modern_ops():
    rng = np.random.RandomState(0)
    x = mx.nd.array(rng.randn(2, 3, 8, 8).astype(np.float32))
    w = mx.nd.array(rng.randn(4, 3, 3, 3).astype(np.float32) * 0.1)
    b = mx.nd.array(np.zeros(4, np.float32))
    v1 = mx.nd.Convolution_v1(x, w, b, kernel=(3, 3), pad=(1, 1),
                              num_filter=4)
    v2 = mx.nd.Convolution(x, w, b, kernel=(3, 3), pad=(1, 1), num_filter=4)
    np.testing.assert_allclose(v1.asnumpy(), v2.asnumpy(), rtol=1e-5)
    p1 = mx.nd.Pooling_v1(x, kernel=(2, 2), stride=(2, 2), pool_type="avg")
    p2 = mx.nd.Pooling(x, kernel=(2, 2), stride=(2, 2), pool_type="avg")
    np.testing.assert_allclose(p1.asnumpy(), p2.asnumpy(), rtol=1e-6)
    g = mx.nd.array(np.ones(3, np.float32))
    be = mx.nd.array(np.zeros(3, np.float32))
    mm = mx.nd.array(np.zeros(3, np.float32))
    mv = mx.nd.array(np.ones(3, np.float32))
    b1 = mx.nd.BatchNorm_v1(x, g, be, mm, mv, fix_gamma=False)
    b2 = mx.nd.BatchNorm(x, g, be, mm, mv, fix_gamma=False, axis=1)
    np.testing.assert_allclose(b1.asnumpy(), b2.asnumpy(), rtol=1e-5)


def test_identity_attach_kl_sparse_reg_gradient():
    rng = np.random.RandomState(0)
    x = mx.nd.array(rng.uniform(0.2, 0.8, (4, 3)).astype(np.float32))
    x.attach_grad()
    with mx.autograd.record():
        y = IdentityAttachKLSparseReg(x, sparseness_target=0.1, penalty=0.01)
        s = y.sum()
    s.backward()
    np.testing.assert_allclose(y.asnumpy(), x.asnumpy())  # identity fwd
    rho_hat = x.asnumpy().mean(0)
    reg = 0.01 * (-0.1 / rho_hat + 0.9 / (1 - rho_hat))
    np.testing.assert_allclose(x.grad.asnumpy(),
                               1.0 + np.broadcast_to(reg, x.shape),
                               rtol=1e-5)


def test_sparse_embedding_forward():
    w = jnp.asarray(np.eye(5, 3, dtype=np.float32))
    idx = jnp.asarray([0, 2, 4])
    out = SparseEmbedding(idx, w, input_dim=5, output_dim=3)
    np.testing.assert_allclose(out.asnumpy(), np.eye(5, 3)[[0, 2, 4]])


def test_group_adagrad_dense_and_sparse():
    opt = mx.optimizer.create("groupadagrad", learning_rate=0.1)
    w = mx.nd.array(np.ones((3, 4), np.float32))
    g = mx.nd.array(np.full((3, 4), 0.5, np.float32))
    st = opt.create_state(0, w)
    assert st.shape == (3,)  # one slot per row, not per element
    opt.update(0, w, g, st)
    exp = 1 - 0.1 * 0.5 / np.sqrt(0.25 + 1e-5)
    np.testing.assert_allclose(w.asnumpy(), np.full((3, 4), exp), rtol=1e-5)


def test_reshape_like_negative_end_reference_convention():
    # reference matrix_op.cc: negative end means ndim + end (last axis),
    # e.g. (30, 7) with rhs (15, 2, 4), ends = -1 -> (15, 2, 7)
    a = jnp.zeros((30, 7))
    b = jnp.zeros((15, 2, 4))
    out = reshape_like(a, b, lhs_begin=0, lhs_end=-1, rhs_begin=0,
                       rhs_end=-1)
    assert out.shape == (15, 2, 7)


def test_bipartite_matching_topk_limit():
    s = jnp.asarray(np.random.RandomState(0).rand(6, 6), jnp.float32)
    x, _ = bipartite_matching(s, threshold=1e-9, topk=2)
    assert int((x.asnumpy() >= 0).sum()) == 2


def test_deformable_conv_fractional_border_fades_to_zero():
    # tap at y = -0.5 must contribute HALF the row-0 value (zero padding),
    # not the full clipped value (ref deformable_im2col.h im2col_bilinear)
    x = np.zeros((1, 1, 4, 4), np.float32)
    x[0, 0, 0, :] = 8.0
    wt = jnp.ones((1, 1, 1, 1), jnp.float32)
    off = np.zeros((1, 2, 4, 4), np.float32)
    off[0, 0] = -0.5  # y-offset
    out = DeformableConvolution(jnp.asarray(x), jnp.asarray(off), wt,
                                kernel=(1, 1), num_filter=1, no_bias=True)
    np.testing.assert_allclose(out.asnumpy()[0, 0, 0], [4.0] * 4, atol=1e-5)
    # fully outside (y = -1.5) -> exactly zero
    off[0, 0] = -1.5
    out2 = DeformableConvolution(jnp.asarray(x), jnp.asarray(off), wt,
                                 kernel=(1, 1), num_filter=1, no_bias=True)
    np.testing.assert_allclose(out2.asnumpy()[0, 0, 0], [0.0] * 4, atol=1e-6)


def _greedy_nms_oracle(boxes_scores, thresh):
    """O(n^2) python greedy NMS: returns indices kept, in score order."""
    idx = np.argsort(-boxes_scores[:, 0])
    kept = []
    for i in idx:
        si, bi = boxes_scores[i, 0], boxes_scores[i, 1:]
        if si <= 0:
            continue
        ok = True
        for j in kept:
            bj = boxes_scores[j, 1:]
            tl = np.maximum(bi[:2], bj[:2])
            br = np.minimum(bi[2:], bj[2:])
            wh = np.maximum(br - tl, 0)
            inter = wh[0] * wh[1]
            area_i = (bi[2] - bi[0]) * (bi[3] - bi[1])
            area_j = (bj[2] - bj[0]) * (bj[3] - bj[1])
            iou = inter / max(area_i + area_j - inter, 1e-12)
            if iou > thresh:
                ok = False
                break
        if ok:
            kept.append(i)
    return set(kept)


def test_box_nms_matches_bruteforce_oracle():
    """The fixed-iteration lax NMS must keep exactly the boxes an O(n^2)
    python greedy reference keeps, across random inputs."""
    from mxtpu.ops.contrib_ops import box_nms

    rng = np.random.RandomState(0)
    for trial in range(5):
        n = 24
        xy = rng.uniform(0, 8, (n, 2))
        wh = rng.uniform(0.5, 4, (n, 2))
        scores = rng.uniform(0.01, 1, (n, 1))
        data = np.concatenate(
            [np.zeros((n, 1)), scores, xy, xy + wh], 1).astype(np.float32)
        out = box_nms(jnp.asarray(data), overlap_thresh=0.5,
                      valid_thresh=0.0, coord_start=2,
                      score_index=1).asnumpy()
        kept_scores = sorted(s for s in out[:, 1] if s >= 0)
        oracle = _greedy_nms_oracle(data[:, 1:6], 0.5)
        oracle_scores = sorted(data[j, 1] for j in oracle)
        np.testing.assert_allclose(kept_scores, oracle_scores, rtol=1e-6,
                                   err_msg="trial %d" % trial)


def test_multiproposal_reference_defaults_memory_bounded():
    """VERDICT r4 weak #8: at the reference's rpn_pre_nms_top_n=6000 the
    NMS must stay O(k) live memory — a k x k IoU matrix would be 144 MB
    f32 per image (x batch under vmap). Pin it at the compiler level:
    XLA's temp allocation for the compiled op must stay far below the
    quadratic footprint, and the op must actually execute."""
    import time

    import jax
    import jax.numpy as jnp

    from mxtpu.ops.contrib_ops import MultiProposal

    n, a, h, w = 2, 12, 23, 23          # 12*23*23 = 6348 anchors > 6000
    rng = np.random.RandomState(0)
    cls_prob = jnp.asarray(rng.rand(n, 2 * a, h, w).astype(np.float32))
    bbox_pred = jnp.asarray(
        rng.randn(n, 4 * a, h, w).astype(np.float32) * 0.1)
    im_info = jnp.asarray(
        np.tile([368.0, 368.0, 1.0], (n, 1)).astype(np.float32))

    def run(cp, bp, ii):
        out = MultiProposal(cp, bp, ii, rpn_pre_nms_top_n=6000,
                            rpn_post_nms_top_n=300)
        return out._data if hasattr(out, "_data") else out

    lowered = jax.jit(run).lower(cls_prob, bbox_pred, im_info)
    t0 = time.perf_counter()
    compiled = lowered.compile()
    compile_s = time.perf_counter() - t0
    mem = compiled.memory_analysis()
    temp = getattr(mem, "temp_size_in_bytes", None)
    if temp is not None:
        # O(k) NMS needs a few k-length rows (~6000*4B each); one k*k
        # matrix alone would be 144 MB. 64 MB total temp is a loose pin
        # that still catches any quadratic regression (incl. batch=2).
        assert temp < 64 * 1024 * 1024, (
            "MultiProposal temp memory %.1f MB suggests a quadratic IoU "
            "buffer regressed in" % (temp / 1e6))
    rois = np.asarray(compiled(cls_prob, bbox_pred, im_info))
    assert rois.shape == (n * 300, 5)
    assert np.isfinite(rois).all()
    # compile should be routine, not a combinatorial unroll
    assert compile_s < 300, compile_s
