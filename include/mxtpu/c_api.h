/*
 * mxtpu C ABI — flat C surface over the TPU-native runtime.
 *
 * Reference parity: include/mxnet/c_api.h (~194 MX* functions) and
 * include/mxnet/c_predict_api.h in /root/reference. The reference's C ABI
 * fronts its C++ engine; here the runtime orchestrator is the Python/JAX
 * layer (XLA:TPU does the computing), so this ABI embeds — or attaches to —
 * a CPython interpreter and routes calls through mxtpu.c_api_impl. That
 * keeps the layering SURVEY §2.6 asks for: any frontend that can speak C
 * can drive the framework without knowing it is JAX underneath.
 *
 * Conventions (mirroring the reference):
 *   - every function returns 0 on success, -1 on failure;
 *   - MXTPUGetLastError() returns the failure message for this thread;
 *   - handles are opaque; free them with the matching *Free call.
 */
#ifndef MXTPU_C_API_H_
#define MXTPU_C_API_H_

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef void *NDArrayHandle;
typedef void *PredictorHandle;

/* Last error message for the calling thread (never NULL). */
const char *MXTPUGetLastError(void);

/* Optional eager runtime bring-up (first API call does this lazily).
 * platform may be "tpu", "cpu", or NULL for the environment default. */
int MXTPURuntimeInit(const char *platform);

/* ---- NDArray (ref: MXNDArrayCreate* / MXNDArraySyncCopy*) ---- */

/* Create from a float32 host blob. */
int MXTPUNDArrayCreateFromBlob(const float *data, const int64_t *shape,
                               int ndim, NDArrayHandle *out);

/* ndim/shape of the array; shape must have room for 8 dims. */
int MXTPUNDArrayShape(NDArrayHandle handle, int *ndim, int64_t *shape);

/* Synchronous device->host copy as float32 (the deferred-exception sync
 * point: async errors surface here, ref threaded_engine.cc:472). */
int MXTPUNDArraySyncCopyToCPU(NDArrayHandle handle, float *dst, int64_t size);

int MXTPUNDArrayFree(NDArrayHandle handle);

/* ---- imperative invoke (ref: MXImperativeInvokeEx) ----
 * Invokes a registered operator by name. String attrs are parsed as Python
 * literals where possible. outputs must have capacity *num_outputs; the
 * actual count is written back. */
int MXTPUImperativeInvoke(const char *op_name, NDArrayHandle *inputs,
                          int num_inputs, const char **attr_keys,
                          const char **attr_vals, int num_attrs,
                          NDArrayHandle *outputs, int *num_outputs);

/* ---- predict API (ref: c_predict_api.h MXPred*) ----
 * Loads "<prefix>-symbol.json" + "<prefix>-%04d.params" (the checkpoint
 * format of mxtpu.model.save_checkpoint / Block.export). */
int MXTPUPredCreate(const char *prefix, int epoch, const char *input_name,
                    const int64_t *shape, int ndim, PredictorHandle *out);

int MXTPUPredSetInput(PredictorHandle handle, const float *data,
                      int64_t size);

int MXTPUPredForward(PredictorHandle handle);

int MXTPUPredGetOutputShape(PredictorHandle handle, int index, int *ndim,
                            int64_t *shape);

int MXTPUPredGetOutput(PredictorHandle handle, int index, float *dst,
                       int64_t size);

int MXTPUPredFree(PredictorHandle handle);

#ifdef __cplusplus
}
#endif

#endif /* MXTPU_C_API_H_ */
