/*
 * mxtpu C ABI — flat C surface over the TPU-native runtime.
 *
 * Reference parity: include/mxnet/c_api.h (~194 MX* functions) and
 * include/mxnet/c_predict_api.h in /root/reference. The reference's C ABI
 * fronts its C++ engine; here the runtime orchestrator is the Python/JAX
 * layer (XLA:TPU does the computing), so this ABI embeds — or attaches to —
 * a CPython interpreter and routes calls through mxtpu.c_api_impl. That
 * keeps the layering SURVEY §2.6 asks for: any frontend that can speak C
 * can drive the framework without knowing it is JAX underneath.
 *
 * Conventions (mirroring the reference):
 *   - every function returns 0 on success, -1 on failure;
 *   - MXTPUGetLastError() returns the failure message for this thread;
 *   - handles are opaque; free them with the matching *Free call.
 */
#ifndef MXTPU_C_API_H_
#define MXTPU_C_API_H_

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef void *NDArrayHandle;
typedef void *PredictorHandle;
typedef void *KVStoreHandle;
typedef void *SymbolHandle;
typedef void *ExecutorHandle;

/* Last error message for the calling thread (never NULL). */
const char *MXTPUGetLastError(void);

/* Optional eager runtime bring-up (first API call does this lazily).
 * platform may be "tpu", "cpu", or NULL for the environment default. */
int MXTPURuntimeInit(const char *platform);

/* Library version as MAJOR*10000 + MINOR*100 + PATCH (ref MXGetVersion). */
int MXTPUGetVersion(int *out);

/* Every registered operator name (ref MXListAllOpNames). The returned
 * pointers stay valid until the next MXTPUListAllOpNames on this
 * thread. */
int MXTPUListAllOpNames(int *out_num, const char ***out_names);

/* Block until all queued async work has completed (ref MXNDArrayWaitAll;
 * deferred async errors surface here). */
int MXTPUNDArrayWaitAll(void);

/* ---- NDArray (ref: MXNDArrayCreate* / MXNDArraySyncCopy*) ---- */

/* Create from a float32 host blob. */
int MXTPUNDArrayCreateFromBlob(const float *data, const int64_t *shape,
                               int ndim, NDArrayHandle *out);

/* Create with an explicit dtype (mshadow flags: 0 f32, 1 f64, 2 f16,
 * 3 u8, 4 i32, 5 i8, 6 i64; ref MXNDArrayCreateEx). data points at
 * packed little-endian elements of that dtype. */
int MXTPUNDArrayCreateFromBlobEx(const void *data, int dtype_flag,
                                 const int64_t *shape, int ndim,
                                 NDArrayHandle *out);

/* ndim/shape of the array; shape must have room for 8 dims. */
int MXTPUNDArrayShape(NDArrayHandle handle, int *ndim, int64_t *shape);

/* mshadow dtype flag of the array (ref MXNDArrayGetDType). */
int MXTPUNDArrayGetDType(NDArrayHandle handle, int *out_flag);

/* Save arrays to a reference-format .params file (0x112 layout real
 * MXNet reads; ref MXNDArraySave). keys may be NULL for a nameless
 * list. */
int MXTPUNDArraySave(const char *fname, int num, NDArrayHandle *handles,
                     const char **keys);

/* Load a .params file (either format; ref MXNDArrayLoad). Returned
 * arrays are new handles owned by the caller (free each); the
 * *out_handles ARRAY itself and the name pointers are only valid until
 * the next MXTPUNDArrayLoad on this thread — copy the handle pointers
 * out before loading again. *out_names is NULL for a nameless list. */
int MXTPUNDArrayLoad(const char *fname, int *out_num,
                     NDArrayHandle **out_handles, int *out_num_names,
                     const char ***out_names);

/* Synchronous device->host copy as float32 (the deferred-exception sync
 * point: async errors surface here, ref threaded_engine.cc:472). */
int MXTPUNDArraySyncCopyToCPU(NDArrayHandle handle, float *dst, int64_t size);

int MXTPUNDArrayFree(NDArrayHandle handle);

/* ---- imperative invoke (ref: MXImperativeInvokeEx) ----
 * Invokes a registered operator by name. String attrs are parsed as Python
 * literals where possible. outputs must have capacity *num_outputs; the
 * actual count is written back. */
int MXTPUImperativeInvoke(const char *op_name, NDArrayHandle *inputs,
                          int num_inputs, const char **attr_keys,
                          const char **attr_vals, int num_attrs,
                          NDArrayHandle *outputs, int *num_outputs);

/* ---- autograd (ref: MXAutogradSetIsRecording / MXAutogradMarkVariables
 * / MXAutogradBackward). Record imperative invokes, then backward from a
 * scalar loss; gradients land on arrays that called AttachGrad. ---- */

int MXTPUAutogradSetRecording(int is_recording, int *prev);
int MXTPUAutogradSetTraining(int is_training, int *prev);
int MXTPUNDArrayAttachGrad(NDArrayHandle handle);
int MXTPUNDArrayGetGrad(NDArrayHandle handle, NDArrayHandle *out);
int MXTPUNDArrayBackward(NDArrayHandle handle, int retain_graph);

/* ---- KVStore (ref: MXKVStoreCreate / Init / PushEx / PullEx /
 * SetOptimizer). With an optimizer set, push(grad) applies the update
 * server-side and pull returns refreshed weights — the reference's
 * data-parallel training loop from C. ---- */

int MXTPUKVStoreCreate(const char *type, KVStoreHandle *out);
int MXTPUKVStoreInit(KVStoreHandle handle, int num, const char **keys,
                     NDArrayHandle *vals);
int MXTPUKVStorePush(KVStoreHandle handle, int num, const char **keys,
                     NDArrayHandle *vals, int priority);
int MXTPUKVStorePull(KVStoreHandle handle, int num, const char **keys,
                     NDArrayHandle *outs, int priority);
int MXTPUKVStoreSetOptimizer(KVStoreHandle handle, const char *optimizer,
                             const char **attr_keys, const char **attr_vals,
                             int num_attrs);
int MXTPUKVStoreFree(KVStoreHandle handle);

/* ---- Symbol (ref: MXSymbolCreateVariable / CreateAtomicSymbol +
 * Compose / CreateFromJSON / ListArguments / SaveToJSON). Compose is
 * atomic-create + compose in one call. Returned strings stay valid until
 * the next MXTPUSymbol* call on the same thread. ---- */

int MXTPUSymbolCreateVariable(const char *name, SymbolHandle *out);
int MXTPUSymbolCreateFromJSON(const char *json, SymbolHandle *out);
int MXTPUSymbolCreateFromFile(const char *path, SymbolHandle *out);
int MXTPUSymbolCompose(const char *op_name, const char *name,
                       SymbolHandle *inputs, int num_inputs,
                       const char **attr_keys, const char **attr_vals,
                       int num_attrs, SymbolHandle *out);
int MXTPUSymbolListArguments(SymbolHandle sym, int *num,
                             const char ***out_names);
int MXTPUSymbolToJSON(SymbolHandle sym, const char **out_json);
int MXTPUSymbolFree(SymbolHandle sym);

/* ---- Executor (ref: MXExecutorBindEX / Forward / Backward /
 * Outputs). Bind allocates gradient arrays (grad_req "write"); after
 * Backward, per-argument gradients come from ArgGrad. ---- */

int MXTPUExecutorBind(SymbolHandle sym, int num_args,
                      const char **arg_names, NDArrayHandle *arg_vals,
                      const char *grad_req, ExecutorHandle *out);
int MXTPUExecutorForward(ExecutorHandle handle, int is_train);
int MXTPUExecutorNumOutputs(ExecutorHandle handle, int *num);
int MXTPUExecutorOutput(ExecutorHandle handle, int index,
                        NDArrayHandle *out);
int MXTPUExecutorBackward(ExecutorHandle handle);
/* Backward with explicit head gradients; NULL ograds = ones-like seeds
 * (ref MXExecutorBackwardEx). */
int MXTPUExecutorBackwardEx(ExecutorHandle handle, int num_ograds,
                            NDArrayHandle *ograds);
int MXTPUExecutorArgGrad(ExecutorHandle handle, const char *arg_name,
                         NDArrayHandle *out);
int MXTPUExecutorFree(ExecutorHandle handle);

/* ---- predict API (ref: c_predict_api.h MXPred*) ----
 * Loads "<prefix>-symbol.json" + "<prefix>-%04d.params" (the checkpoint
 * format of mxtpu.model.save_checkpoint / Block.export). */
int MXTPUPredCreate(const char *prefix, int epoch, const char *input_name,
                    const int64_t *shape, int ndim, PredictorHandle *out);

int MXTPUPredSetInput(PredictorHandle handle, const float *data,
                      int64_t size);

int MXTPUPredForward(PredictorHandle handle);

int MXTPUPredGetOutputShape(PredictorHandle handle, int index, int *ndim,
                            int64_t *shape);

int MXTPUPredGetOutput(PredictorHandle handle, int index, float *dst,
                       int64_t size);

int MXTPUPredFree(PredictorHandle handle);

/* ---- DataIter (ref: MXListDataIters / MXDataIterCreateIter /
 * MXDataIterNext / MXDataIterGetData / MXDataIterGetLabel /
 * MXDataIterGetPadNum). Attr values are strings, parsed like op attrs
 * (python literals: "(3,224,224)", "32", "data.rec"). ---- */

typedef void *DataIterHandle;

/* Registered iterator names; pointers valid until the next call on this
 * thread. */
int MXTPUListDataIters(int *out_num, const char ***out_names);

int MXTPUDataIterCreate(const char *name, int num_attrs,
                        const char **attr_keys, const char **attr_vals,
                        DataIterHandle *out);

/* Rewind to the epoch start (ref MXDataIterBeforeFirst). */
int MXTPUDataIterBeforeFirst(DataIterHandle handle);

/* Advance; *out = 1 if a batch is available, 0 at epoch end. */
int MXTPUDataIterNext(DataIterHandle handle, int *out);

/* Current batch's data / label as fresh NDArray handles (free them). */
int MXTPUDataIterGetData(DataIterHandle handle, NDArrayHandle *out);
int MXTPUDataIterGetLabel(DataIterHandle handle, NDArrayHandle *out);

/* Trailing filler rows in the current batch (ref MXDataIterGetPadNum). */
int MXTPUDataIterGetPadNum(DataIterHandle handle, int *out);

int MXTPUDataIterFree(DataIterHandle handle);

/* ---- RecordIO (ref: MXRecordIOWriter* / MXRecordIOReader*; wire format
 * identical to the reference: magic 0xced7230a + LRecord header). ---- */

typedef void *RecordIOHandle;

int MXTPURecordIOWriterCreate(const char *path, RecordIOHandle *out);
int MXTPURecordIOWriterWriteRecord(RecordIOHandle handle, const char *buf,
                                   size_t size);
int MXTPURecordIOWriterTell(RecordIOHandle handle, size_t *out);
int MXTPURecordIOWriterFree(RecordIOHandle handle);

int MXTPURecordIOReaderCreate(const char *path, RecordIOHandle *out);
/* Next record; *out_buf == NULL at EOF (a zero-length RECORD returns a
 * non-NULL pointer with *out_size == 0). Pointer valid until the next
 * read on this thread. */
int MXTPURecordIOReaderReadRecord(RecordIOHandle handle, const char **out_buf,
                                  size_t *out_size);
int MXTPURecordIOReaderSeek(RecordIOHandle handle, size_t pos);
int MXTPURecordIOReaderTell(RecordIOHandle handle, size_t *out);
int MXTPURecordIOReaderFree(RecordIOHandle handle);

/* ---- Symbol attributes + breadth (ref: MXSymbolSetAttr / GetAttr /
 * ListAttr / ListOutputs / ListAuxiliaryStates / MXSymbolInferShape /
 * MXSymbolSaveToFile / MXSymbolCopy). String/list results are valid until
 * the next such call on this thread. ---- */

int MXTPUSymbolSetAttr(SymbolHandle handle, const char *key,
                       const char *value);
int MXTPUSymbolGetAttr(SymbolHandle handle, const char *key,
                       const char **out);
/* Flattened (key, value, key, value, ...); *out_num counts entries. */
int MXTPUSymbolListAttr(SymbolHandle handle, int *out_num,
                        const char ***out_kv);
/* Name-parity alias: this runtime's ListAttr is already shallow. */
int MXTPUSymbolListAttrShallow(SymbolHandle handle, int *out_num,
                               const char ***out_kv);
int MXTPUSymbolListOutputs(SymbolHandle handle, int *out_num,
                           const char ***out_names);
int MXTPUSymbolListAuxiliaryStates(SymbolHandle handle, int *out_num,
                                   const char ***out_names);
int MXTPUSymbolSaveToFile(SymbolHandle handle, const char *path);
int MXTPUSymbolCopy(SymbolHandle handle, SymbolHandle *out);

/* Output shapes from known input shapes. arg_shape_data packs each arg's
 * dims back-to-back (arg_shape_ndim[i] dims each). *out_flat packs each
 * output as (ndim, dims...); valid until the next call on this thread. */
int MXTPUSymbolInferOutputShape(SymbolHandle handle, int num_args,
                                const char **arg_names,
                                const int64_t *arg_shape_data,
                                const int *arg_shape_ndim, int *out_num,
                                const int64_t **out_flat);

/* ---- Executor monitor (ref: MXExecutorSetMonitorCallback). The callback
 * fires for EVERY node output on monitored forwards; the NDArrayHandle is
 * borrowed — valid only for the duration of the callback. ---- */

typedef void (*ExecutorMonitorCallback)(const char *name,
                                        NDArrayHandle array, void *ctx);

int MXTPUExecutorSetMonitorCallback(ExecutorHandle handle,
                                    ExecutorMonitorCallback callback,
                                    void *callback_ctx);

/* ---- KVStore breadth (ref: MXKVStoreGetRank / GetGroupSize / Barrier /
 * PushPull). ---- */

int MXTPUKVStoreGetRank(KVStoreHandle handle, int *out);
int MXTPUKVStoreGetGroupSize(KVStoreHandle handle, int *out);
int MXTPUKVStoreBarrier(KVStoreHandle handle);
int MXTPUKVStorePushPull(KVStoreHandle handle, int num, const char **keys,
                         NDArrayHandle *vals, NDArrayHandle *outs,
                         int priority);

/* ---- misc (ref: MXRandomSeed, MXNDArraySlice / Reshape /
 * SyncCopyFromCPU / GetContext). ---- */

/* ---- autograd breadth (ref: MXAutogradIsRecording / IsTraining /
 * MarkVariables / MXAutogradBackwardEx). grad_reqs flags: 0 null,
 * 1 write, 2 add (the reference's OpReqType subset for leaves). ---- */

int MXTPUAutogradIsRecording(int *out);
int MXTPUAutogradIsTraining(int *out);
int MXTPUAutogradMarkVariables(int num, NDArrayHandle *vars,
                               const int *grad_reqs);
/* Backward from several heads; ograds may be NULL (ones-like seeds). */
int MXTPUAutogradBackward(int num, NDArrayHandle *heads,
                          NDArrayHandle *ograds, int retain_graph);

/* ---- CachedOp (ref: MXCreateCachedOpEx / MXInvokeCachedOpEx /
 * MXFreeCachedOp — gluon hybridize from C). Inputs are positional in
 * symbol.list_inputs() order; each distinct input signature jit-compiles
 * once and is reused (the XLA analog of cached_op.cc's static plan). ---- */

typedef void *CachedOpHandle;

int MXTPUCreateCachedOp(SymbolHandle sym, int num_flags,
                        const char **flag_keys, const char **flag_vals,
                        CachedOpHandle *out);
int MXTPUInvokeCachedOp(CachedOpHandle handle, int num_inputs,
                        NDArrayHandle *inputs, int *num_outputs,
                        NDArrayHandle *outputs);
int MXTPUFreeCachedOp(CachedOpHandle handle);

/* ---- NDArray breadth (ref: MXNDArrayCreateNone / At / Detach /
 * WaitToRead / WaitToWrite / GetStorageType / SaveRawBytes /
 * LoadFromRawBytes / LoadFromBuffer / SyncCopyFromNDArray /
 * SyncCheckFormat / CreateSparseEx / GetAux* / GetDataNDArray). ---- */

int MXTPUNDArrayCreateNone(NDArrayHandle *out);
int MXTPUNDArrayAt(NDArrayHandle handle, int64_t idx, NDArrayHandle *out);
int MXTPUNDArrayDetach(NDArrayHandle handle, NDArrayHandle *out);
int MXTPUNDArrayWaitToRead(NDArrayHandle handle);
int MXTPUNDArrayWaitToWrite(NDArrayHandle handle);
/* storage type flags: 0 default(dense), 1 row_sparse, 2 csr
 * (ref include/mxnet/ndarray.h:61 NDArrayStorageType). */
int MXTPUNDArrayGetStorageType(NDArrayHandle handle, int *out);
/* One dense array as a single V2 record (no 0x112 list header). Buffer
 * valid until the next SaveRawBytes on this thread. */
int MXTPUNDArraySaveRawBytes(NDArrayHandle handle, size_t *out_size,
                             const char **out_buf);
int MXTPUNDArrayLoadFromRawBytes(const void *buf, size_t size,
                                 NDArrayHandle *out);
/* A whole .params file image from memory; same output contract as
 * MXTPUNDArrayLoad. */
int MXTPUNDArrayLoadFromBuffer(const void *buf, size_t size, int *out_num,
                               NDArrayHandle **out_handles,
                               int *out_num_names, const char ***out_names);
int MXTPUNDArraySyncCopyFromNDArray(NDArrayHandle dst, NDArrayHandle src);
int MXTPUNDArraySyncCheckFormat(NDArrayHandle handle, int full_check);
/* Sparse create: stype 1 (row_sparse) takes aux = {indices}; stype 2
 * (csr) takes aux = {indptr, indices}. */
int MXTPUNDArrayCreateSparseEx(int stype, NDArrayHandle data, int num_aux,
                               NDArrayHandle *aux, const int64_t *shape,
                               int ndim, NDArrayHandle *out);
int MXTPUNDArrayGetDataNDArray(NDArrayHandle handle, NDArrayHandle *out);
/* fresh-grad bookkeeping bit (ref MXNDArraySetGradState/GetGradState —
 * the NDArray.fresh_grad frontend flag, stored verbatim). */
int MXTPUNDArraySetGradState(NDArrayHandle handle, int state);
int MXTPUNDArrayGetGradState(NDArrayHandle handle, int *out);
int MXTPUNDArrayGetAuxNDArray(NDArrayHandle handle, int i,
                              NDArrayHandle *out);
int MXTPUNDArrayGetAuxType(NDArrayHandle handle, int i, int *out_flag);

/* ---- Symbol breadth II (ref: MXSymbolCreateAtomicSymbol / CreateGroup /
 * GetInternals / GetOutput / GetNumOutputs / GetName / GetChildren /
 * InferType / InferShapePartial / ListAtomicSymbolCreators / Print /
 * SaveToJSON). ---- */

/* Uncomposed atomic op symbol; missing inputs become auto-created
 * argument variables at bind time. */
int MXTPUSymbolCreateAtomicSymbol(const char *op_name, int num_attrs,
                                  const char **attr_keys,
                                  const char **attr_vals, SymbolHandle *out);
int MXTPUSymbolCreateGroup(int num, SymbolHandle *syms, SymbolHandle *out);
int MXTPUSymbolGetInternals(SymbolHandle handle, SymbolHandle *out);
int MXTPUSymbolGetOutput(SymbolHandle handle, int index, SymbolHandle *out);
int MXTPUSymbolGetNumOutputs(SymbolHandle handle, int *out);
/* *success = 0 for multi-output groups (they have no single name). */
int MXTPUSymbolGetName(SymbolHandle handle, const char **out, int *success);
int MXTPUSymbolGetChildren(SymbolHandle handle, SymbolHandle *out);
/* Type inference. dtype flags as in CreateFromBlobEx; unknown = -1.
 * The three out arrays live until the next InferType on this thread. */
int MXTPUSymbolInferType(SymbolHandle handle, int num_args,
                         const char **arg_names, const int *arg_type_flags,
                         int *out_arg_num, const int **out_arg_flags,
                         int *out_out_num, const int **out_out_flags,
                         int *out_aux_num, const int **out_aux_flags);
/* Tolerant shape inference: unknowable outputs come back with ndim 0
 * instead of failing (ref MXSymbolInferShapePartial). Same packing as
 * MXTPUSymbolInferOutputShape. */
int MXTPUSymbolInferShapePartial(SymbolHandle handle, int num_args,
                                 const char **arg_names,
                                 const int64_t *arg_shape_data,
                                 const int *arg_shape_ndim, int *out_num,
                                 const int64_t **out_flat);
int MXTPUSymbolListAtomicSymbolCreators(int *out_num,
                                        const char ***out_names);
/* Human-readable description (ref MXSymbolPrint). */
int MXTPUSymbolPrint(SymbolHandle handle, const char **out);
/* Name-parity alias of MXTPUSymbolToJSON (ref MXSymbolSaveToJSON). */
int MXTPUSymbolSaveToJSON(SymbolHandle handle, const char **out_json);

/* ---- Executor breadth (ref: MXExecutorSimpleBind / Reshape / Print /
 * Outputs). SimpleBind infers every shape from the named input shapes and
 * allocates args/auxs itself (grad_req applies to all arguments). ---- */

int MXTPUExecutorSimpleBind(SymbolHandle sym, int num_inputs,
                            const char **input_names,
                            const int64_t *shape_data, const int *shape_ndim,
                            const char *grad_req, ExecutorHandle *out);
/* Rebind to new input shapes; returns a NEW executor sharing nothing
 * (XLA recompiles per shape; ref MXExecutorReshape). */
int MXTPUExecutorReshape(ExecutorHandle handle, int num_inputs,
                         const char **input_names, const int64_t *shape_data,
                         const int *shape_ndim, ExecutorHandle *out);
int MXTPUExecutorPrint(ExecutorHandle handle, const char **out);
/* All outputs at once; *num is the capacity in, count out. */
int MXTPUExecutorOutputs(ExecutorHandle handle, int *num,
                         NDArrayHandle *outs);

/* ---- KVStore breadth II (ref: MXKVStoreGetType / SetUpdater /
 * SetGradientCompression / PullRowSparse / GetNumDeadNode /
 * IsWorkerNode / IsServerNode / IsSchedulerNode). ---- */

typedef void (*MXTPUKVStoreUpdater)(int key, NDArrayHandle recv,
                                    NDArrayHandle local, void *ctx);
typedef void (*MXTPUKVStoreStrUpdater)(const char *key, NDArrayHandle recv,
                                       NDArrayHandle local, void *ctx);

int MXTPUKVStoreGetType(KVStoreHandle handle, const char **out);
/* The updater runs on every push-merge; recv/local handles are BORROWED
 * (valid only during the call). The int-key variant requires numeric
 * keys — a push with a named key (e.g. "fc1_weight") fails loudly; use
 * SetUpdaterEx for string keys (ref MXKVStoreSetUpdaterEx). */
int MXTPUKVStoreSetUpdater(KVStoreHandle handle, MXTPUKVStoreUpdater updater,
                           void *ctx);
int MXTPUKVStoreSetUpdaterEx(KVStoreHandle handle,
                             MXTPUKVStoreStrUpdater updater, void *ctx);
int MXTPUKVStoreSetGradientCompression(KVStoreHandle handle, int num,
                                       const char **keys, const char **vals);
int MXTPUKVStorePullRowSparse(KVStoreHandle handle, int num,
                              const char **keys, NDArrayHandle *outs,
                              NDArrayHandle *row_ids, int priority);
int MXTPUKVStoreGetNumDeadNode(KVStoreHandle handle, int node_id, int *out);
/* Role queries (DMLC_ROLE env; symmetric-worker runtime: every process
 * is a worker unless the env says otherwise). */
int MXTPUKVStoreIsWorkerNode(int *out);
int MXTPUKVStoreIsServerNode(int *out);
int MXTPUKVStoreIsSchedulerNode(int *out);

/* ---- profiler (ref: MXSetProfilerConfig / MXSetProfilerState /
 * MXDumpProfile / MXProfilePause — mx.profiler chrome-trace capture). ---- */

int MXTPUSetProfilerConfig(int num, const char **keys, const char **vals);
int MXTPUSetProfilerState(int state); /* 1 run, 0 stop */
int MXTPUDumpProfile(int finished);
int MXTPUProfilePause(int paused);

/* ---- profiler object family (ref: MXProfileCreateDomain / CreateTask /
 * CreateFrame / CreateEvent / CreateCounter / MXProfileDestroyHandle /
 * DurationStart / DurationStop / SetCounter / AdjustCounter / SetMarker /
 * MXAggregateProfileStatsPrint). Scoped user timing: create an object,
 * bracket work with DurationStart/Stop (or fire SetMarker), and read the
 * aggregate table. Counter values appear in the aggregate stream as
 * zero-duration "name=value" instants. Free objects with
 * MXTPUProfileDestroyHandle. ---- */

typedef void *ProfileHandle;

int MXTPUProfileCreateDomain(const char *name, ProfileHandle *out);
int MXTPUProfileCreateTask(ProfileHandle domain, const char *name,
                           ProfileHandle *out);
int MXTPUProfileCreateFrame(ProfileHandle domain, const char *name,
                            ProfileHandle *out);
int MXTPUProfileCreateEvent(const char *name, ProfileHandle *out);
int MXTPUProfileCreateCounter(ProfileHandle domain, const char *name,
                              ProfileHandle *out);
int MXTPUProfileDestroyHandle(ProfileHandle handle);
int MXTPUProfileDurationStart(ProfileHandle handle);
int MXTPUProfileDurationStop(ProfileHandle handle);
int MXTPUProfileSetCounter(ProfileHandle handle, uint64_t value);
int MXTPUProfileAdjustCounter(ProfileHandle handle, int64_t delta);
/* scope may be NULL (= "process"). */
int MXTPUProfileSetMarker(ProfileHandle domain, const char *name,
                          const char *scope);
/* Aggregate stats table (ref MXAggregateProfileStatsPrint); string valid
 * until the next string-returning call on this thread. reset=1 clears
 * the accumulated events. */
int MXTPUAggregateProfileStatsPrint(const char **out_str, int reset);

/* Process-variant aliases (ref: MXSetProcessProfilerConfig / State /
 * MXDumpProcessProfile / MXProcessProfilePause). Symmetric single-role
 * runtime: profile_process selects nothing (README ADR — no server
 * processes exist); these alias the worker-profiler calls. */
int MXTPUSetProcessProfilerConfig(int num, const char **keys,
                                  const char **vals, int profile_process);
int MXTPUSetProcessProfilerState(int state, int profile_process);
int MXTPUDumpProcessProfile(int finished, int profile_process);
int MXTPUProcessProfilePause(int paused, int profile_process);

/* ---- runtime kernel compilation (ref: MXRtcCudaModuleCreate /
 * MXRtcCudaKernelCreate / MXRtcCudaKernelCall / MXRtcCudaModuleFree /
 * MXRtcCudaKernelFree over NVRTC). TPU-native reinterpretation: `source`
 * is PYTHON text defining Pallas kernel function(s) over Refs
 * (mxtpu/rtc.py PallasModule); the kernel compiles per launch signature
 * and runs on the accelerator. exports may be NULL (= every function in
 * the source). Output arrays are fresh caller-owned handles. ---- */

typedef void *RtcHandle;

int MXTPURtcModuleCreate(const char *source, int num_exports,
                         const char **exports, RtcHandle *out);
int MXTPURtcModuleFree(RtcHandle handle);
int MXTPURtcKernelCreate(RtcHandle module, const char *name,
                         int num_outputs, RtcHandle *out);
int MXTPURtcKernelFree(RtcHandle handle);
/* out_shape_data packs each output's dims back-to-back
 * (out_shape_ndim[i] dims each); dtype flags as in CreateFromBlobEx. */
int MXTPURtcKernelCall(RtcHandle kernel, int num_inputs,
                       NDArrayHandle *inputs, int num_outputs,
                       const int64_t *out_shape_data,
                       const int *out_shape_ndim,
                       const int *out_dtype_flags, NDArrayHandle *outputs);

/* ---- runtime/introspection breadth (ref: MXGetGPUCount /
 * MXGetGPUMemoryInformation64 / MXNotifyShutdown / MXEngineSetBulkSize /
 * MXSetNumOMPThreads / MXRandomSeedContext). ---- */

/* Visible accelerator count (the reference counts GPUs; here PJRT
 * devices). */
int MXTPUGetDeviceCount(int *out);
/* (free, total) HBM bytes; fails honestly when the backend exposes no
 * memory stats. */
int MXTPUGetMemoryInformation(int dev_id, uint64_t *free_bytes,
                              uint64_t *total_bytes);
/* Flush pending async work before exit (ref MXNotifyShutdown tears the
 * engine down; PJRT clients close at process exit). */
int MXTPUNotifyShutdown(void);
/* Engine bulking is subsumed by XLA fusion — the call is the documented
 * no-op of mxtpu/engine.py and returns the previous size. */
int MXTPUEngineSetBulkSize(int size, int *prev);
/* XLA:CPU fixes its pool at backend init; accepted for compatibility. */
int MXTPUSetNumOMPThreads(int num);
/* Seed one device's stream (one functional PRNG: equivalent to
 * MXTPURandomSeed; ref MXRandomSeedContext). */
int MXTPURandomSeedContext(int seed, int dev_type, int dev_id);

/* ---- DLPack interchange (ref: MXNDArrayToDLPack / MXNDArrayFromDLPack
 * / MXNDArrayCallDLPackDeleter). The void* is a standard
 * DLManagedTensor*; any DLPack consumer (torch, numpy, tvm) accepts it.
 * ToDLPack transfers ownership to the caller: hand it to a consumer or
 * release with CallDLPackDeleter. FromDLPack CONSUMES the tensor on
 * success (its deleter fires when the runtime drops it). ---- */

int MXTPUNDArrayToDLPack(NDArrayHandle handle, void **out_dlmanaged);
int MXTPUNDArrayFromDLPack(void *dlmanaged, NDArrayHandle *out);
int MXTPUNDArrayCallDLPackDeleter(void *dlmanaged);

/* ---- shared-memory NDArrays (ref: MXNDArrayCreateFromSharedMem /
 * MXNDArrayGetSharedMemHandle). POSIX shared memory is NAME-addressed,
 * so this ABI exchanges segment names where the reference exchanges
 * (pid, fd) ints. GetSharedMemHandle copies into a fresh segment whose
 * ownership transfers to the receiver; CreateFromSharedMem attaches,
 * copies out, and unlinks (one-shot transfer). The name pointer is
 * valid until the next call on this thread. ---- */

int MXTPUNDArrayGetSharedMemHandle(NDArrayHandle handle,
                                   const char **out_name);
int MXTPUNDArrayCreateFromSharedMem(const char *name, int dtype_flag,
                                    const int64_t *shape, int ndim,
                                    NDArrayHandle *out);

/* ---- DataIter breadth (ref: MXDataIterGetIndex / GetIterInfo). ---- */

/* Sample indices of the current batch; array valid until the next call
 * on this thread. */
int MXTPUDataIterGetIndex(DataIterHandle handle, uint64_t **out_index,
                          uint64_t *out_size);
int MXTPUDataIterGetIterInfo(const char *name, const char **out_name,
                             const char **out_desc);

int MXTPURandomSeed(int seed);
int MXTPUNDArraySlice(NDArrayHandle handle, int64_t begin, int64_t end,
                      NDArrayHandle *out);
int MXTPUNDArrayReshape(NDArrayHandle handle, const int64_t *shape, int ndim,
                        NDArrayHandle *out);
/* Name-parity alias of Reshape (this ABI is int64 throughout; ref
 * MXNDArrayReshape64). */
int MXTPUNDArrayReshape64(NDArrayHandle handle, const int64_t *shape,
                          int ndim, NDArrayHandle *out);
/* Overwrite the array's contents from packed host bytes of its dtype. */
int MXTPUNDArraySyncCopyFromCPU(NDArrayHandle handle, const void *data,
                                size_t size);
int MXTPUNDArrayGetContext(NDArrayHandle handle, const char **out);

#ifdef __cplusplus
}
#endif

#endif /* MXTPU_C_API_H_ */
