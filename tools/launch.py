#!/usr/bin/env python
"""Local multi-process launcher (ref: tools/launch.py, local mode).

The reference's launcher boots a scheduler + parameter servers + workers and
exports the DMLC_* env contract. Here there are no servers: every worker is
symmetric, joining one jax.distributed runtime whose coordinator is worker 0.
This launcher runs N workers on this machine (the analog of the reference's
``launch.py -n N --launcher local``) — on a real TPU pod each host runs one
process and jax.distributed autodetects, so no launcher is needed there.

Usage::

    python tools/launch.py -n 2 python my_train_script.py

Each worker gets MXTPU_COORDINATOR / MXTPU_NUM_PROCESSES / MXTPU_PROCESS_ID
(and the reference-compatible DMLC_* names), which ``mxtpu.distributed.init()``
reads. CPU workers additionally get JAX_PLATFORMS=cpu so the N processes
don't fight over one accelerator.
"""
import argparse
import os
import socket
import subprocess
import sys


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("-n", "--num-workers", type=int, required=True)
    ap.add_argument("--cpu", action=argparse.BooleanOptionalAction,
                    default=True,
                    help="force JAX_PLATFORMS=cpu in workers (default; "
                         "--no-cpu lets workers use the accelerator — only "
                         "sane when each process owns its own device)")
    ap.add_argument("command", nargs=argparse.REMAINDER)
    args = ap.parse_args()
    if not args.command:
        ap.error("no command given")

    port = _free_port()
    procs = []
    for rank in range(args.num_workers):
        env = dict(os.environ)
        env.update({
            "MXTPU_COORDINATOR": "127.0.0.1:%d" % port,
            "MXTPU_NUM_PROCESSES": str(args.num_workers),
            "MXTPU_PROCESS_ID": str(rank),
            # reference-compatible spellings (tools/launch.py env contract)
            "DMLC_PS_ROOT_URI": "127.0.0.1",
            "DMLC_PS_ROOT_PORT": str(port),
            "DMLC_NUM_WORKER": str(args.num_workers),
            "DMLC_WORKER_ID": str(rank),
            "DMLC_ROLE": "worker",
        })
        if args.cpu:
            env["JAX_PLATFORMS"] = "cpu"
            # the axon sitecustomize activates on PALLAS_AXON_POOL_IPS and
            # programmatically overrides JAX_PLATFORMS — CPU workers must
            # not inherit it, or N processes dial the one TPU (and hang
            # outright when the tunnel is wedged)
            env.pop("PALLAS_AXON_POOL_IPS", None)
        procs.append(subprocess.Popen(args.command, env=env))

    rc = 0
    try:
        for p in procs:
            p.wait()
            rc = rc or p.returncode
            if rc:
                break  # one worker failed: take the rest down (a partial
                       # world would hang in the next collective anyway)
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
    sys.exit(rc)


if __name__ == "__main__":
    main()
