"""Does f32 accumulation (preferred_element_type) speed up bf16 convs the
way it does matmuls (perf_peak.py: 102 -> 140 TFLOP/s)?

Times a resnet-like chained conv stack fwd and fwd+bwd, scan-fused into one
dispatch, with (a) plain bf16 conv, (b) f32-accumulate + cast back to bf16.
Sync is a host fetch (see perf_peak.py docstring).
"""
import time

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

DN = ("NHWC", "HWIO", "NHWC")


def timed(name, jfn, *args, K):
    y = jfn(*args)
    _ = np.asarray(jax.device_get(jax.tree_util.tree_leaves(y)[0].ravel()[:2]))
    t0 = time.perf_counter()
    y = jfn(*args)
    _ = np.asarray(jax.device_get(jax.tree_util.tree_leaves(y)[0].ravel()[:2]))
    dt = (time.perf_counter() - t0) / K
    print("%-38s %8.2f ms" % (name, dt * 1e3), flush=True)
    return dt


def stack(acc_f32, bwd, batch=128, hw=28, c=256, depth=8, K=5):
    x = jax.random.normal(jax.random.PRNGKey(0), (batch, hw, hw, c),
                          jnp.bfloat16)
    w = jax.random.normal(jax.random.PRNGKey(1), (3, 3, c, c), jnp.bfloat16)
    pet = jnp.float32 if acc_f32 else None

    def f(x, w):
        for _ in range(depth):
            x = lax.conv_general_dilated(x, w, (1, 1), "SAME",
                                         dimension_numbers=DN,
                                         preferred_element_type=pet)
            x = x.astype(jnp.bfloat16) * jnp.bfloat16(0.1)
        return x

    if bwd:
        def lossf(x, w):
            return jnp.sum(f(x, w).astype(jnp.float32)) * 1e-30
        g = jax.grad(lossf, argnums=(0, 1))

        def body(c_, _):
            gx, gw = g(c_[0], c_[1])
            return (c_[0] + gx.astype(c_[0].dtype) * 0,
                    c_[1] + gw.astype(c_[1].dtype) * 0), None
    else:
        def body(c_, _):
            return (f(c_[0], c_[1]) * 0 + c_[0], c_[1]), None

    jfn = jax.jit(lambda x, w: lax.scan(body, (x, w), None, length=K)[0])
    # per-conv flops (fwd): 2 * batch*hw*hw*c * 3*3*c  per layer
    fl = 2 * batch * hw * hw * c * 9 * c * depth * (3 if bwd else 1)
    dt = timed("conv%d %dx%dx%d acc=%s %s" % (depth, hw, hw, c,
                                              "f32" if acc_f32 else "bf16",
                                              "fwd+bwd" if bwd else "fwd"),
               jfn, x, w, K=K)
    print("    -> %6.1f TFLOP/s" % (fl / dt / 1e12), flush=True)


def main():
    for bwd in (False, True):
        for acc in (False, True):
            stack(acc, bwd)


if __name__ == "__main__":
    main()
