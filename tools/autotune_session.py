#!/usr/bin/env python
"""Run measured Pallas block-plan searches from the ledger's work order.

The closing arc of the observe → tune → persist → serve loop
(docs/autotune.md): ``telemetry_report.py --tuning-queue`` ranks the
memory-bound jit sites by executed FLOPs; this CLI consumes that queue
top-down, maps each site onto the registered tunable kernels, and runs
:func:`mxtpu.ops.pallas.autotune.search` over each kernel's declared
representative shape classes. Winning plans are installed AND persisted
under ``MXTPU_COMPILE_CACHE_DIR`` (set it, or the session tunes into
thin air), so the NEXT process — a restarted trainer, a fresh replica —
serves them with zero warm-start searches.

The queue carries jit *sites* (e.g. ``trainer.step``) while plans key on
kernel *shape classes*; the mapping is deliberately honest: a queue
entry establishes that tuning a kernel family is warranted and in what
order, and the shape classes swept are the family's own declared
representatives (``TunableKernel.classes``), scaled down on the host
tier so interpret-mode candidates stay inside a CI budget.

One JSON line per search on stdout (kernel, class, default vs best plan,
speedup, persisted path) and a final ``AUTOTUNE_SESSION`` summary line —
the perf-battery artifact grammar.

Usage::

    python tools/autotune_session.py [--queue tuning_queue.json]
        [--kernels pallas_conv,pallas_flash] [--budget-s S] [--rounds N]
        [--limit K]
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# site keywords -> kernel family, for ordering kernels by the queue's
# ranked sites; an unmatched site leaves the registry order untouched
_SITE_HINTS = (("conv", "pallas_conv"), ("stem", "pallas_conv"),
               ("resnet", "pallas_conv"), ("attention", "pallas_flash"),
               ("flash", "pallas_flash"), ("transformer", "pallas_flash"))


def _kernel_order(queue, registered):
    """Registered kernel ids, queue-ranked first. The queue's top site
    pulls its kernel family to the front; families the queue never
    mentions keep registry order at the back."""
    ranked = []
    for entry in queue:
        site = str(entry.get("site", "")).lower()
        for word, kid in _SITE_HINTS:
            if word in site and kid in registered and kid not in ranked:
                ranked.append(kid)
    return ranked + [k for k in sorted(registered) if k not in ranked]


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="measured Pallas block-plan tuning session")
    ap.add_argument("--queue", default=None,
                    help="tuning_queue.json from telemetry_report.py "
                         "--tuning-queue (orders the kernel families)")
    ap.add_argument("--kernels", default=None,
                    help="comma-separated kernel ids (default: all "
                         "registered, queue-ranked)")
    ap.add_argument("--budget-s", type=float, default=None,
                    help="wall budget per search (default "
                         "MXTPU_AUTOTUNE_BUDGET_S or 30)")
    ap.add_argument("--rounds", type=int, default=None,
                    help="timed rounds per candidate (default "
                         "MXTPU_AUTOTUNE_ROUNDS or 3)")
    ap.add_argument("--limit", type=int, default=None,
                    help="max searches this session (bounds CI time)")
    args = ap.parse_args(argv)

    from mxtpu.ops.pallas import autotune
    from mxtpu.ops.pallas.flash_attention import _platform

    queue = []
    if args.queue:
        with open(args.queue, encoding="utf-8") as f:
            doc = json.load(f)
        if doc.get("format") != 1:
            print("unsupported tuning-queue format: %r"
                  % doc.get("format"), file=sys.stderr)
            return 1
        queue = doc.get("queue") or []

    registered = autotune.kernels()
    if args.kernels:
        kids = [k.strip() for k in args.kernels.split(",") if k.strip()]
        unknown = [k for k in kids if k not in registered]
        if unknown:
            print("unknown kernel id(s): %s (registered: %s)"
                  % (", ".join(unknown), ", ".join(sorted(registered))),
                  file=sys.stderr)
            return 1
    else:
        kids = _kernel_order(queue, registered)

    if not os.environ.get("MXTPU_COMPILE_CACHE_DIR"):
        print("warning: MXTPU_COMPILE_CACHE_DIR is unset — winning "
              "plans will be installed in-process but NOT persisted",
              file=sys.stderr)

    host_tier = _platform() != "tpu"
    ran = improved = 0
    for kid in kids:
        tk = registered[kid]
        for sc in tk.classes(host_tier):
            if args.limit is not None and ran >= args.limit:
                break
            res = autotune.search(kid, sc, rounds=args.rounds,
                                  budget_s=args.budget_s)
            ran += 1
            improved += int(res["improved"])
            line = {k: res[k] for k in
                    ("kernel", "class", "device", "candidates", "timed",
                     "budget_exhausted", "default_plan_id", "default_s",
                     "best_plan_id", "best_s", "speedup_vs_default",
                     "improved", "persisted")}
            print(json.dumps(line, sort_keys=True), flush=True)
    print("AUTOTUNE_SESSION " + json.dumps(
        {"searches": ran, "improved": improved,
         "host_tier": host_tier,
         "queue_sites": len(queue),
         "kernels": kids,
         "cache_dir": os.environ.get("MXTPU_COMPILE_CACHE_DIR")},
        sort_keys=True), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
