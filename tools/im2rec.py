#!/usr/bin/env python
"""im2rec: pack an image folder / .lst file into RecordIO shards.

Reference: ``tools/im2rec.py`` (and the C++ tools/im2rec.cc). Usage parity for
the common flows:

  python tools/im2rec.py --list prefix image_root   # build prefix.lst
  python tools/im2rec.py prefix image_root          # pack prefix.rec/.idx
"""
from __future__ import annotations

import argparse
import os
import random
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def list_images(root, recursive, exts):
    cat = {}
    for path, dirs, files in sorted(os.walk(root, followlinks=True)):
        dirs.sort()
        files.sort()
        for fname in files:
            fpath = os.path.join(path, fname)
            suffix = os.path.splitext(fname)[1].lower()
            if os.path.isfile(fpath) and suffix in exts:
                label_dir = os.path.relpath(path, root)
                if label_dir not in cat:
                    cat[label_dir] = len(cat)
                yield os.path.relpath(fpath, root), cat[label_dir]
        if not recursive:
            break


def write_list(prefix, root, args):
    entries = list(list_images(root, args.recursive, args.exts))
    if args.shuffle:
        random.seed(100)
        random.shuffle(entries)
    n_total = len(entries)
    chunk = n_total // args.chunks
    for i in range(args.chunks):
        name = prefix + ("_%d" % i if args.chunks > 1 else "") + ".lst"
        with open(name, "w") as f:
            for j, (path, label) in enumerate(
                    entries[i * chunk:(i + 1) * chunk
                            if i + 1 < args.chunks else n_total]):
                f.write("%d\t%f\t%s\n" % (i * chunk + j, label, path))


def read_list(path):
    with open(path) as f:
        for line in f:
            parts = line.strip().split("\t")
            if len(parts) < 3:
                continue
            yield int(parts[0]), [float(x) for x in parts[1:-1]], parts[-1]


def pack(prefix, root, args):
    import cv2
    import numpy as np
    from mxtpu import recordio

    lst = prefix + ".lst"
    if not os.path.exists(lst):
        write_list(prefix, root, args)
    w = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    count = 0
    for idx, label, rel_path in read_list(lst):
        img = cv2.imread(os.path.join(root, rel_path), args.color)
        if img is None:
            print("imread failed for %s, skipping" % rel_path)
            continue
        if args.resize:
            h, w_ = img.shape[:2]
            if min(h, w_) > args.resize:
                scale = args.resize / min(h, w_)
                img = cv2.resize(img, (int(w_ * scale), int(h * scale)))
        header = recordio.IRHeader(
            0, label[0] if len(label) == 1 else np.asarray(label, np.float32),
            idx, 0)
        w.write_idx(idx, recordio.pack_img(
            header, img, quality=args.quality, img_fmt=args.encoding))
        count += 1
    w.close()
    print("packed %d images into %s.rec" % (count, prefix))


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("prefix")
    parser.add_argument("root")
    parser.add_argument("--list", action="store_true",
                        help="only build the .lst file")
    parser.add_argument("--exts", nargs="+",
                        default=[".jpeg", ".jpg", ".png"])
    parser.add_argument("--chunks", type=int, default=1)
    parser.add_argument("--recursive", action=argparse.BooleanOptionalAction,
                        default=True)
    parser.add_argument("--shuffle", action=argparse.BooleanOptionalAction,
                        default=True)
    parser.add_argument("--resize", type=int, default=0)
    parser.add_argument("--quality", type=int, default=95)
    parser.add_argument("--encoding", default=".jpg")
    parser.add_argument("--color", type=int, default=1)
    args = parser.parse_args()
    if args.list:
        write_list(args.prefix, args.root, args)
    else:
        pack(args.prefix, args.root, args)


if __name__ == "__main__":
    main()
