#!/usr/bin/env python
"""Crash-resume supervisor CLI (mxtpu.resilience.TrainSupervisor).

Respawns a training entrypoint on nonzero exit with decorrelated-jitter
exponential backoff under a crash-loop budget, and refuses with a
diagnosis when the same checkpoint step crashes twice in a row (a
deterministic poison-crash — respawning would replay it forever). The
child resumes itself from the integrity-verified newest intact
checkpoint (ResilientLoop.resume's tiered restore); pass the same
checkpoint directory here so the supervisor can tell progress (transient
fault) from no-progress (poison) between crashes::

    python tools/train_supervisor.py --ckpt-dir /ckpt/run1 -- \
        python train.py --ckpt-dir /ckpt/run1 ...

Exit codes: 0 = the child exited cleanly; 3 = refusal (the diagnosis is
on stderr: poison-crash or crash-loop budget spent). Knobs:
MXTPU_SUPERVISOR_RESTARTS (crash-loop budget, default 8) and
MXTPU_SUPERVISOR_BACKOFF_S (initial backoff, default 2.0), overridable
by the flags below. Every respawn counts into the telemetry registry as
``supervisor.restarts{reason}``.
"""
from __future__ import annotations

import argparse
import os
import sys


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="respawn a training entrypoint on crashes, with "
                    "jittered backoff, a crash-loop budget, and a "
                    "poison-crash refusal diagnosis")
    parser.add_argument("--ckpt-dir", default=None,
                        help="the run's checkpoint directory (the same "
                             "one the child resumes from) — how the "
                             "supervisor distinguishes transient crashes "
                             "(checkpoint advanced) from a poison-crash "
                             "(same step twice)")
    parser.add_argument("--max-restarts", type=int, default=None,
                        help="crash-loop budget (default "
                             "MXTPU_SUPERVISOR_RESTARTS, 8)")
    parser.add_argument("--backoff-s", type=float, default=None,
                        help="initial respawn backoff in seconds (default "
                             "MXTPU_SUPERVISOR_BACKOFF_S, 2.0); later "
                             "waits use decorrelated jitter")
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="the training entrypoint, after `--`")
    args = parser.parse_args(argv)
    cmd = list(args.command)
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        parser.error("no training command given (append: -- <cmd> ...)")

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), ".."))
    from mxtpu.resilience import SupervisorRefusal, TrainSupervisor
    sup = TrainSupervisor(cmd, ckpt_dir=args.ckpt_dir,
                          max_restarts=args.max_restarts,
                          backoff_s=args.backoff_s)
    try:
        return sup.run()
    except SupervisorRefusal as e:
        print("train_supervisor: REFUSING to respawn: %s" % e,
              file=sys.stderr)
        return 3


if __name__ == "__main__":
    sys.exit(main())
