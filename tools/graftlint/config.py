"""graftlint configuration: scopes, doc locations, and the jit allowlist.

Everything here is overridable per-``LintConfig`` so the fixture tests can
point the rules at synthetic trees (tests/fixtures/graftlint/)."""
from __future__ import annotations

from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Tuple

# Paths (repo-root-relative, posix) whose env reads are treated as
# trace-time for policy-key-coverage: these trees hold the op/policy gates
# that execute under jax tracing, so an MXTPU_* read here is baked into
# compiled executables unless it is in registry.policy_key (or explicitly
# suppressed as host-side at the read site).
DEFAULT_TRACE_SCOPES: Tuple[str, ...] = (
    "mxtpu/ops",
    "mxtpu/contrib",
    "mxtpu/parallel",
    "mxtpu/resilience.py",
)

DEFAULT_POLICY_KEY_MODULE = "mxtpu/ops/registry.py"
DEFAULT_ENV_DOC = "docs/env_vars.md"
DEFAULT_METRIC_DOC = "docs/observability.md"

# Trees whose telemetry writer calls feed metric-name-catalog: the
# runtime package is the metric namespace the catalog documents (bench /
# tools consume metrics, they do not declare new names).
DEFAULT_METRIC_SCOPES: Tuple[str, ...] = ("mxtpu",)

# Extra roots scanned (read-only) by env-var-catalog beyond the CLI paths:
# docs/env_vars.md is a repo-global catalog, so BENCH_* rows read only by
# the bench/tooling layer must not look stale when linting mxtpu/ alone.
DEFAULT_ENV_EXTRA_ROOTS: Tuple[str, ...] = ("bench.py", "tools", "tests")

# Never analyzed / never scanned: the lint fixtures are deliberately-bad
# code, and would otherwise convict themselves in the self-clean gate.
DEFAULT_EXCLUDE: Tuple[str, ...] = ("tests/fixtures/graftlint",)

# Trees where every jax.jit site must resolve through the compile
# service (mxtpu/compile_service.py): a registered-but-out-of-band cache
# here is a finding — it would miss the LRU bound, the persistent
# executable cache, and AOT warmup. Fixture trees (paths outside these
# prefixes) keep exercising the plain record_retrace discipline.
DEFAULT_SERVICE_SCOPES: Tuple[str, ...] = ("mxtpu/",)

# retrace-site-registration allowlist: (repo-relative file, enclosing
# function of the jax.jit call) -> entry. An entry declares WHERE the
# site's compiles are actually counted and what its cache key is, so the
# jit-surface inventory stays complete even for sites whose
# record_retrace lives in a caller.
JIT_ALLOWLIST: Dict[Tuple[str, str], Dict[str, str]] = {
    ("mxtpu/optimizer_fused.py", "_build"): {
        "site": "fused_optimizer",
        "service": True,
        "reason": "FusedUpdater._cached_jit is the single cache front door "
                  "for this builder; every executable-cache miss resolves "
                  "through compile_service.get_or_build (canonical key, "
                  "retrace reporting, LRU, persistent disk cache) before "
                  "invoking _build",
        "cache_key": "(optimizer class, static config, per-param specs "
                     "incl. sharding tokens, MeshPlan fingerprint) + "
                     "registry.policy_key — FusedUpdater._cached_jit; the "
                     "mesh-native Trainer shares this cache",
    },
    ("mxtpu/serving/engine.py", "_build_for"): {
        "site": "serving.predict",
        "service": True,
        "reason": "Predictor._build_for only BUILDS the bucket jit; the "
                  "cache front door is Predictor._get_jit / "
                  "warmup_entries, which resolve every miss through "
                  "compile_service.get_or_build with a canonical key at "
                  "site self._site (per-INSTANCE, so each ReplicaSet "
                  "member gets its own watchdog site "
                  "serving.predict.r<i>) and group-dedup identical "
                  "replica lowerings — the static rule sees no seam in "
                  "the build closure and this entry declares it",
        "cache_key": "(bucket padded shapes+dtypes) + registry.policy_key "
                     "— one executable cache per Predictor instance; "
                     "per-replica caches (sites serving.predict.r<i>, "
                     "mxtpu/serving/replicas.py) are each bounded by "
                     "#buckets, total compiles <= buckets x replicas; "
                     "elastic members (ReplicaSet.add_replica — scale-up "
                     "and dead-replica replacement) extend the same "
                     "family with fresh never-reused indices, warmed "
                     "AOT before joining dispatch",
    },
    ("mxtpu/serving/decode.py", "_build_jit"): {
        "site": "serving.decode",
        "reason": "DecodeEngine._build_jit is the single compile front "
                  "door for the decode cache (step executables per cohort "
                  "capacity bucket + insert executables per prefill seq "
                  "bucket, and in paged mode the verify/extend family "
                  "over the same buckets); it calls "
                  "telemetry.record_retrace(self._site, "
                  "...) on every miss before jax.jit — the site name is "
                  "per-INSTANCE (default serving.decode) so the static "
                  "rule sees '<dynamic>' and this entry declares the base "
                  "site for the inventory",
        "cache_key": "(kind step|insert|verify|extend, "
                     "cohort-capacity-or-seq bucket, int8 flag, "
                     "page_tokens, pool_pages, spec_k, draft kv layout) "
                     "+ registry.policy_key — one executable "
                     "cache per DecodeEngine instance at site "
                     "serving.decode; post-warmup compiles are ZERO by "
                     "construction (every bucket AOT-compiled in "
                     "warmup()), carry state donated per step so replay "
                     "never allocates; the page table rides as a TRACED "
                     "gather/scatter index argument, never a new shape",
    },
    ("mxtpu/serving/decode.py", "_build_draft_jit"): {
        "site": "serving.draft",
        "reason": "DecodeEngine._build_draft_jit is the compile front "
                  "door for the speculative-decoding DRAFT executables "
                  "(k-token proposal loop per cohort capacity bucket); "
                  "it resolves every miss through "
                  "compile_service.get_or_build at the engine's draft "
                  "site (default serving.draft — per-INSTANCE, so the "
                  "static rule sees '<dynamic>') and is AOT-warmed by "
                  "warmup() exactly like the target-family buckets; an "
                  "out-of-band draft jit anywhere else is a finding",
        "cache_key": "(kind draft, cohort capacity bucket, spec_k, draft "
                     "kv layout, vocab, draft param specs) + "
                     "registry.policy_key — the sixth entry in the "
                     "caches inventory; post-warmup compiles at "
                     "serving.draft are ZERO (watchdog-pinned by the "
                     "decode bench gate)",
    },
    ("mxtpu/ops/pallas/autotune.py", "_time_plan"): {
        "site": "autotune.search",
        "service": True,
        "reason": "the measured-search candidate probe: each tuning "
                  "candidate compiles ONCE as a deliberately ephemeral "
                  "throwaway jit (timed with warmup-discarded "
                  "median-of-rounds dispatches, then dropped — caching "
                  "a losing candidate's executable would be waste), "
                  "registered via record_retrace('autotune.search') so "
                  "the xprof ledger covers the site; probe volume is "
                  "accounted by the autotune.searches counter and "
                  "bounded by MXTPU_AUTOTUNE_BUDGET_S, far under the "
                  "retrace watchdog budget per class. The persisted "
                  "artifact is the PLAN, and the serving-path "
                  "executables that embed a winning plan resolve "
                  "through compile_service.get_or_build at their own "
                  "sites with the plan digest riding "
                  "registry.policy_key",
        "cache_key": "none by design (ephemeral measurement probes, "
                     "never cached, never served) — plan identity "
                     "reaches real caches via the policy_key digest "
                     "component (registry._autotune_plans_entry)",
    },
    ("mxtpu/optimizer_fused.py", "_build_guarded"): {
        "site": "fused_optimizer",
        "service": True,
        "reason": "same compile-service front door as _build; the guard "
                  "bit and scaler_cfg join the cache key in _cached_jit",
        "cache_key": "(optimizer class, static config, per-param specs "
                     "incl. sharding tokens, MeshPlan fingerprint, "
                     "guard bit, scaler_cfg) + registry.policy_key — "
                     "FusedUpdater._cached_jit; the mesh-native Trainer "
                     "shares this cache",
    },
}


@dataclass
class LintConfig:
    """Resolved analyzer configuration. ``root`` anchors every relative
    path in this object (CLI paths, policy_key_module, env_doc, scopes)."""

    root: Path
    policy_key_module: str = DEFAULT_POLICY_KEY_MODULE
    trace_scopes: Tuple[str, ...] = DEFAULT_TRACE_SCOPES
    env_doc: str = DEFAULT_ENV_DOC
    env_extra_roots: Tuple[str, ...] = DEFAULT_ENV_EXTRA_ROOTS
    metric_doc: str = DEFAULT_METRIC_DOC
    metric_scopes: Tuple[str, ...] = DEFAULT_METRIC_SCOPES
    exclude: Tuple[str, ...] = DEFAULT_EXCLUDE
    service_scopes: Tuple[str, ...] = DEFAULT_SERVICE_SCOPES
    jit_allowlist: Dict[Tuple[str, str], Dict[str, str]] = field(
        default_factory=lambda: dict(JIT_ALLOWLIST))

    def __post_init__(self):
        self.root = Path(self.root).resolve()

    def is_excluded(self, rel: str) -> bool:
        return any(rel == e or rel.startswith(e.rstrip("/") + "/")
                   for e in self.exclude)

    def in_trace_scope(self, rel: str) -> bool:
        for s in self.trace_scopes:
            if s in ("", "."):
                return True
            if rel == s or rel.startswith(s.rstrip("/") + "/"):
                return True
        return False
