"""graftlint core: file model, inline suppressions, and the rule runner."""
from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

from .config import LintConfig

# inline suppression: `# graftlint: disable=<rule>[,<rule>...]` (or `all`)
# on the physical line the finding anchors to
_SUPPRESS_RE = re.compile(r"#\s*graftlint:\s*disable=([A-Za-z0-9_,\- ]+)")


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str          # repo-root-relative, posix
    line: int
    message: str

    def format(self) -> str:
        return "%s:%d: [%s] %s" % (self.path, self.line, self.rule,
                                   self.message)

    def as_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "message": self.message}


class FileContext:
    """One parsed source file: AST + per-line suppression sets."""

    def __init__(self, path: Path, rel: str, source: str):
        self.path = path
        self.rel = rel
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=str(path))
        self._suppress: Dict[int, set] = {}
        for i, ln in enumerate(self.lines, 1):
            m = _SUPPRESS_RE.search(ln)
            if m:
                self._suppress[i] = {t.strip()
                                     for t in m.group(1).split(",")
                                     if t.strip()}

    def suppressed(self, rule: str, line: int) -> bool:
        rules = self._suppress.get(line, ())
        return rule in rules or "all" in rules


class Project:
    """Shared file loader/cache so cross-file rules (env-var-catalog's
    extra-root scan) parse each file at most once."""

    def __init__(self, config: LintConfig):
        self.config = config
        self._ctxs: Dict[str, Optional[FileContext]] = {}

    def ctx_for(self, rel: str) -> Optional[FileContext]:
        """FileContext for a repo-relative path, or None if the file is
        missing or unparseable (generated/vendored files must not crash
        the lint)."""
        if rel not in self._ctxs:
            path = self.config.root / rel
            try:
                source = path.read_text(encoding="utf-8", errors="replace")
                self._ctxs[rel] = FileContext(path, rel, source)
            except (OSError, SyntaxError, ValueError):
                self._ctxs[rel] = None
        return self._ctxs[rel]


class Rule:
    """Base rule: accumulate (Finding, ctx) pairs via :meth:`report`; the
    runner partitions them into active vs suppressed using the ctx."""

    id = "?"

    def __init__(self, config: LintConfig):
        self.config = config
        self.results: List[Tuple[Finding, Optional[FileContext]]] = []

    def report(self, ctx: Optional[FileContext], path: str, line: int,
               message: str):
        self.results.append(
            (Finding(self.id, path, line, message), ctx))

    def visit(self, ctx: FileContext, project: Project):
        """Called once per analyzed python file."""

    def finalize(self, project: Project):
        """Called once after all files were visited (cross-file checks)."""


@dataclass
class LintResult:
    findings: List[Finding] = field(default_factory=list)
    suppressed: List[Finding] = field(default_factory=list)
    jit_inventory: List[dict] = field(default_factory=list)
    files: int = 0

    @property
    def ok(self) -> bool:
        return not self.findings


def _collect_files(config: LintConfig, paths: Sequence[str]) -> List[str]:
    """Expand CLI paths (files or directories) into a sorted list of
    repo-relative .py paths, honoring config.exclude."""
    rels = []
    seen = set()
    for p in paths:
        ap = Path(p)
        if not ap.is_absolute():
            ap = config.root / p
        ap = ap.resolve()
        if ap.is_dir():
            cands = sorted(ap.rglob("*.py"))
        else:
            cands = [ap]
        for c in cands:
            if "__pycache__" in c.parts:
                continue
            try:
                rel = c.relative_to(config.root).as_posix()
            except ValueError:
                rel = c.as_posix()
            if config.is_excluded(rel) or rel in seen:
                continue
            seen.add(rel)
            rels.append(rel)
    return rels


def run(config: LintConfig, paths: Sequence[str],
        rule_ids: Optional[Sequence[str]] = None) -> LintResult:
    """Run the (selected) rules over ``paths``; returns a LintResult with
    active findings, suppressed findings, and the jit-surface inventory."""
    from .rules import ALL_RULES

    selected = []
    known = {cls.id for cls in ALL_RULES}
    if rule_ids is not None:
        unknown = set(rule_ids) - known
        if unknown:
            raise ValueError("unknown rule id(s): %s (known: %s)"
                             % (", ".join(sorted(unknown)),
                                ", ".join(sorted(known))))
    for cls in ALL_RULES:
        if rule_ids is None or cls.id in rule_ids:
            selected.append(cls(config))

    project = Project(config)
    rels = _collect_files(config, paths)
    ctxs = []
    for rel in rels:
        ctx = project.ctx_for(rel)
        if ctx is not None:
            ctxs.append(ctx)

    for rule in selected:
        for ctx in ctxs:
            rule.visit(ctx, project)
        rule.finalize(project)

    result = LintResult(files=len(ctxs))
    for rule in selected:
        for finding, ctx in rule.results:
            if ctx is not None and ctx.suppressed(finding.rule,
                                                  finding.line):
                result.suppressed.append(finding)
            else:
                result.findings.append(finding)
        inv = getattr(rule, "inventory", None)
        if inv:
            result.jit_inventory.extend(inv)
    result.findings.sort(key=lambda f: (f.path, f.line, f.rule))
    result.suppressed.sort(key=lambda f: (f.path, f.line, f.rule))
    result.jit_inventory.sort(key=lambda e: (e["file"], e["line"]))
    return result
