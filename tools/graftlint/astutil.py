"""Shared AST pattern matchers: env-var reads, jax.jit call sites, scopes."""
from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Dict, Iterator, List, Optional, Tuple

# sentinels for EnvRead.default
MISSING = object()      # .get(name) with no default / environ[name]
NONCONST = object()     # default present but not a literal


@dataclass
class EnvRead:
    name: str
    line: int
    default: object     # str literal, None literal, MISSING, or NONCONST
    node: ast.AST


def _is_environ_expr(node: ast.AST) -> bool:
    """True for expressions that textually resolve to os.environ (os.environ,
    _os.environ, bare ``environ`` from a from-import)."""
    try:
        text = ast.unparse(node)
    except Exception:
        return False
    return text.endswith("environ") or text == "os.environ"


def _const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def iter_env_reads(tree: ast.AST,
                   prefixes: Tuple[str, ...] = ("MXTPU_", "BENCH_")
                   ) -> Iterator[EnvRead]:
    """Yield env-var READ sites (writes — ``os.environ[k] = v`` — do not
    count). Recognized forms:

    * ``os.environ.get(name[, default])`` (any spelling ending in
      ``environ``, incl. ``env = os.environ; env.get(...)`` — any ``.get``
      whose key literal matches a prefix is treated as an env read)
    * ``os.getenv(name[, default])`` / bare ``getenv(...)``
    * ``os.environ[name]`` in Load context
    * ``name in os.environ``
    """
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            func = node.func
            if isinstance(func, ast.Attribute) and func.attr == "get" \
                    and node.args:
                name = _const_str(node.args[0])
                if name is None:
                    continue
                env_recv = _is_environ_expr(func.value)
                if not env_recv and not name.startswith(tuple(prefixes)):
                    continue
                if len(node.args) > 1:
                    d = _const_str(node.args[1])
                    default = d if d is not None else (
                        node.args[1].value
                        if isinstance(node.args[1], ast.Constant)
                        else NONCONST)
                else:
                    default = MISSING
                yield EnvRead(name, node.lineno, default, node)
            elif ((isinstance(func, ast.Attribute) and func.attr == "getenv")
                  or (isinstance(func, ast.Name) and func.id == "getenv")) \
                    and node.args:
                name = _const_str(node.args[0])
                if name is None:
                    continue
                if len(node.args) > 1:
                    d = _const_str(node.args[1])
                    default = d if d is not None else (
                        node.args[1].value
                        if isinstance(node.args[1], ast.Constant)
                        else NONCONST)
                else:
                    default = MISSING
                yield EnvRead(name, node.lineno, default, node)
        elif isinstance(node, ast.Subscript) \
                and isinstance(node.ctx, ast.Load) \
                and _is_environ_expr(node.value):
            name = _const_str(node.slice)
            if name is not None:
                yield EnvRead(name, node.lineno, MISSING, node)
        elif isinstance(node, ast.Compare) and len(node.ops) == 1 \
                and isinstance(node.ops[0], (ast.In, ast.NotIn)) \
                and _is_environ_expr(node.comparators[0]):
            name = _const_str(node.left)
            if name is not None:
                yield EnvRead(name, node.lineno, MISSING, node)


# ------------------------------------------------------------------ jit sites
def is_jit_func_expr(node: ast.AST) -> bool:
    """``jax.jit`` (or a bare ``jit`` from-import) as an expression."""
    if isinstance(node, ast.Attribute) and node.attr == "jit":
        return True
    if isinstance(node, ast.Name) and node.id == "jit":
        return True
    return False


def is_jit_call(node: ast.AST) -> bool:
    return isinstance(node, ast.Call) and is_jit_func_expr(node.func)


def jit_in_decorator(dec: ast.AST) -> bool:
    """@jax.jit / @jit / @partial(jax.jit, ...) / @functools.partial(...)"""
    if is_jit_func_expr(dec):
        return True
    if isinstance(dec, ast.Call):
        if is_jit_func_expr(dec.func):
            return True
        fname = dec.func.attr if isinstance(dec.func, ast.Attribute) else (
            dec.func.id if isinstance(dec.func, ast.Name) else "")
        if fname == "partial":
            return any(is_jit_func_expr(a) for a in dec.args)
    return False


def build_parent_map(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
    parents: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


def enclosing_functions(node: ast.AST,
                        parents: Dict[ast.AST, ast.AST]
                        ) -> List[ast.AST]:
    """FunctionDef/AsyncFunctionDef ancestors, innermost first."""
    chain = []
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            chain.append(cur)
        cur = parents.get(cur)
    return chain


def qualname_of(node: ast.AST, parents: Dict[ast.AST, ast.AST]) -> str:
    """Dotted path of enclosing ClassDef/FunctionDef names, e.g.
    ``Predictor._get_jit`` — for the jit-surface inventory."""
    names = []
    cur = node
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.ClassDef)):
            names.append(cur.name)
        cur = parents.get(cur)
    return ".".join(reversed(names)) or "<module>"


def iter_scope_nodes(scope_body: List[ast.stmt]) -> Iterator[ast.AST]:
    """Walk statements of one scope WITHOUT descending into nested
    function/class bodies (their execution timing is unknown)."""
    stack: List[ast.AST] = list(scope_body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def find_traced_functions(tree: ast.AST) -> List[ast.AST]:
    """Function/Lambda nodes whose bodies execute under jax tracing:
    arguments of ``jax.jit(...)`` calls, ``@jax.jit``-class decorators, and
    (transitively) any function nested inside one of those."""
    by_name: Dict[str, List[ast.AST]] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            by_name.setdefault(node.name, []).append(node)

    roots: List[ast.AST] = []
    for node in ast.walk(tree):
        if is_jit_call(node) and node.args:
            target = node.args[0]
            if isinstance(target, ast.Name):
                roots.extend(by_name.get(target.id, ()))
            elif isinstance(target, ast.Lambda):
                roots.append(target)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(jit_in_decorator(d) for d in node.decorator_list):
                roots.append(node)
    # dedupe, outermost roots are enough: ast.walk covers nested defs
    seen = set()
    out = []
    for r in roots:
        if id(r) not in seen:
            seen.add(id(r))
            out.append(r)
    return out
