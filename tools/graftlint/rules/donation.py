"""use-after-donate: a buffer donated to a jit call must not be read after.

``donate_argnums``/``donate_argnames`` hand the argument's buffer to XLA
for in-place reuse — after the call the donated array is DELETED; reading
it raises (or on some backends returns garbage). The runtime protects its
own donation sites with defensive copies (kvstore grouped push, Predictor
exact-fit inputs); this rule catches the raw pattern in new code:

    f = jax.jit(step, donate_argnums=(0,))
    out = f(params, batch)
    params.block_until_ready()   # <-- flagged: params was donated

Scope is intraprocedural (one function / module body at a time, matching
the issue contract): a donated-jit binding and a call through it in the
same scope, followed by a load of a Name that was passed at a donated
position before it is rebound."""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from ..astutil import is_jit_call, iter_scope_nodes
from ..core import Rule

# events are emitted in EVALUATION order (not line order — a donated call
# may span lines): a call's own arg loads precede its donation, and an
# assignment's value is evaluated before its targets are bound, so the
# call's RESULT (a fresh buffer) clears the donation — `a = f(a, b)` is
# legal, even wrapped across lines, while `f(a, b); use(a)` is not
_LOAD, _DONATE, _STORE = 0, 1, 2


def _donated_positions(call: ast.Call) -> Optional[Tuple[List[int],
                                                         List[str]]]:
    """(argnums, argnames) literals of a jax.jit(...) call, or None if the
    call donates nothing / non-literally."""
    nums: List[int] = []
    names: List[str] = []
    found = False
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            found = True
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                nums.append(v.value)
            elif isinstance(v, (ast.Tuple, ast.List)):
                for el in v.elts:
                    if isinstance(el, ast.Constant) \
                            and isinstance(el.value, int):
                        nums.append(el.value)
        elif kw.arg == "donate_argnames":
            found = True
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                names.append(v.value)
            elif isinstance(v, (ast.Tuple, ast.List)):
                for el in v.elts:
                    if isinstance(el, ast.Constant) \
                            and isinstance(el.value, str):
                        names.append(el.value)
    return (nums, names) if found else None


def _param_names(fn: ast.AST) -> List[str]:
    if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
        return [a.arg for a in fn.args.args]
    return []


class UseAfterDonate(Rule):
    id = "use-after-donate"

    def visit(self, ctx, project):
        scopes = [("<module>", ctx.tree.body)]
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scopes.append((node.name, node.body))
        for _name, body in scopes:
            self._check_scope(ctx, body)

    def _check_scope(self, ctx, body):
        # pass 1: donated-jit bindings in this scope (name -> (nums, names,
        # param names of the traced fn if statically known))
        donated_fns: Dict[str, Tuple[List[int], List[str], List[str]]] = {}
        local_defs: Dict[str, ast.AST] = {}
        for node in iter_scope_nodes(body):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                local_defs[node.name] = node
        for node in iter_scope_nodes(body):
            if isinstance(node, ast.Assign) and is_jit_call(node.value):
                don = _donated_positions(node.value)
                if don is None:
                    continue
                nums, names = don
                params: List[str] = []
                if node.value.args:
                    tgt = node.value.args[0]
                    if isinstance(tgt, ast.Lambda):
                        params = _param_names(tgt)
                    elif isinstance(tgt, ast.Name) \
                            and tgt.id in local_defs:
                        params = _param_names(local_defs[tgt.id])
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        donated_fns[t.id] = (nums, names, params)

        # pass 2: evaluation-order load/store/donate events over plain Names
        events = self._events(body, donated_fns)
        live: Dict[str, int] = {}  # name -> donation line
        for kind, name, line in events:
            if kind == _DONATE:
                live[name] = line
            elif kind == _STORE:
                live.pop(name, None)
            elif kind == _LOAD and name in live:
                self.report(
                    ctx, ctx.rel, line,
                    "'%s' was donated to the jit call on line %d — its "
                    "buffer is deleted by XLA; reading it here is "
                    "use-after-free. Use the call's result, or copy "
                    "before donating" % (name, live[name]))
                del live[name]  # one finding per donation

    def _donations_of_call(self, node: ast.Call, donated_fns):
        """(name, line) donation events of one Call, if it calls a
        donated jit (bound name or direct ``jax.jit(...)(...)`` form)."""
        don = None
        if isinstance(node.func, ast.Name) and node.func.id in donated_fns:
            don = donated_fns[node.func.id]
        elif is_jit_call(node.func):
            d = _donated_positions(node.func)
            if d is not None:
                params = []
                if node.func.args \
                        and isinstance(node.func.args[0], ast.Lambda):
                    params = _param_names(node.func.args[0])
                don = (d[0], d[1], params)
        if don is None:
            return []
        nums, argnames, params = don
        positions = list(nums)
        for an in argnames:
            if an in params:
                positions.append(params.index(an))
        out = []
        for p in positions:
            if p < len(node.args) and isinstance(node.args[p], ast.Name):
                out.append((node.args[p].id, node.lineno))
        for kw in node.keywords:
            if kw.arg in argnames and isinstance(kw.value, ast.Name):
                out.append((kw.value.id, node.lineno))
        return out

    def _events(self, body, donated_fns):
        """Flatten one scope into (kind, name, line) events in evaluation
        order: assignment values before their targets, a call's arguments
        before its donation. Nested function/class bodies are opaque
        (their execution timing is unknown)."""
        events: List[Tuple[int, str, int]] = []

        def visit(node):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef, ast.Lambda)):
                return
            if isinstance(node, ast.Assign):
                visit(node.value)
                for t in node.targets:
                    visit(t)
                return
            if isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                if node.value is not None:
                    visit(node.value)
                visit(node.target)
                return
            if isinstance(node, ast.NamedExpr):
                visit(node.value)
                visit(node.target)
                return
            if isinstance(node, ast.Call):
                visit(node.func)
                for a in node.args:
                    visit(a)
                for kw in node.keywords:
                    visit(kw.value)
                for name, line in self._donations_of_call(node,
                                                          donated_fns):
                    events.append((_DONATE, name, line))
                return
            if isinstance(node, ast.Name):
                if isinstance(node.ctx, ast.Load):
                    events.append((_LOAD, node.id, node.lineno))
                elif isinstance(node.ctx, (ast.Store, ast.Del)):
                    events.append((_STORE, node.id, node.lineno))
                return
            for child in ast.iter_child_nodes(node):
                visit(child)

        for stmt in body:
            visit(stmt)
        return events
