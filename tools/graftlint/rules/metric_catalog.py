"""metric-name-catalog: docs/observability.md and the code agree, both
directions — the env-var-catalog rule's twin for the telemetry registry.

Every counter/gauge/histogram/span/stage name LITERAL recorded through
``telemetry.{inc,gauge,observe,span,add_stage}`` (and
``record_retrace(site)``, counted as ``retrace.<site>``) in the metric
scopes (``mxtpu/``) must have a table row in the observability catalog
(first cell, backticked), and every cataloged row must have a surviving
record site — a stale row is flagged at its doc line. Without this rule a
new metric ships invisible to anyone reading the catalog, and a renamed
one leaves dashboards silently flat; the runtime can never notice either.

Dynamic names are handled structurally, not ignored: a ``"%s.wait" %
site`` / ``"retrace." + site`` / f-string name becomes a PATTERN, so doc
rows it can produce (``data.wait``, ``retrace.fused_optimizer``) are not
stale, and doc rows with ``<i>``-style placeholders are probed against
the code side with the placeholder instantiated. A ``span(..., d2h=True)``
literal additionally declares its ``<name>.d2h`` attribution counter.

Doc-row grammar (the catalog's own idiom): backticked names in the first
table cell; ``{a,b,c}`` comma groups expand to alternatives,
``{reason}``-style single-word groups are tag annotations and drop,
``<i>`` placeholders match any suffix."""
from __future__ import annotations

import ast
import re

from ..core import Rule

# writer -> index of the name argument
_WRITERS = {"inc": 0, "gauge": 0, "observe": 0, "span": 0,
            "add_stage": 1}
# declared metric-writing WRAPPERS (any receiver): the name literal lives
# at the given positional index of the wrapper call, not in a direct
# telemetry.* call — MicroBatcher._share_stage fans one stage duration
# out to every cohort member's breakdown
_WRAPPER_WRITERS = {"_share_stage": 1}
_RETRACE = "record_retrace"
_TELEMETRY_NAMES = ("telemetry", "_telemetry")
_FMT_RE = re.compile(r"%[sdrxif]")
_NAME_RE = re.compile(r"^[a-z0-9_.]+$")
_TOKEN_RE = re.compile(r"`([^`]+)`")


def call_keywords(node):
    return node.keywords or ()


def _resolve_name(node):
    """(kind, value) where kind is 'lit' (exact string), 'pat' (regex
    source), or None (statically unresolvable, skipped)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return "lit", node.value
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mod):
        left = node.left
        if isinstance(left, ast.Constant) and isinstance(left.value, str):
            # escape the literal text, then turn %s/%d placeholders
            # into wildcards
            pat = re.escape(_FMT_RE.sub("\0", left.value)).replace(
                re.escape("\0"), ".*")
            return "pat", pat
        return None, None
    if isinstance(node, ast.JoinedStr):
        parts = []
        for v in node.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                parts.append(re.escape(v.value))
            else:
                parts.append(".*")
        pat = "".join(parts)
        return ("pat", pat) if pat.strip(".*") else (None, None)
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        lk, lv = _resolve_name(node.left)
        rk, rv = _resolve_name(node.right)
        lpat = re.escape(lv) if lk == "lit" else (lv if lk == "pat"
                                                  else ".*")
        rpat = re.escape(rv) if rk == "lit" else (rv if rk == "pat"
                                                  else ".*")
        if lk is None and rk is None:
            return None, None
        return "pat", lpat + rpat
    return None, None


def parse_doc_rows(text):
    """{literal_name: line} + [(pattern, line)] from the first cells of
    the catalog's markdown table rows."""
    names, patterns = {}, []
    for i, line in enumerate(text.splitlines(), 1):
        stripped = line.lstrip()
        if not stripped.startswith("|"):
            continue
        cells = stripped.split("|")
        if len(cells) < 3:
            continue
        for token in _TOKEN_RE.findall(cells[1]):
            for name in _expand_token(token):
                if "\0" in name:
                    patterns.append((re.escape(name).replace(
                        re.escape("\0"), ".*"), i))
                elif _NAME_RE.match(name):
                    names.setdefault(name, i)
    return names, patterns


def _expand_token(token):
    """Expand one backticked doc token into candidate metric names;
    non-metric tokens (env vars, code fragments) expand to nothing."""
    token = token.strip()
    if not token or " " in token or "=" in token:
        return []
    # placeholders like <i> become wildcard marks before brace handling
    token = re.sub(r"<[^>]*>", "\0", token)
    out = [""]
    pos = 0
    for m in re.finditer(r"\{([^{}]*)\}", token):
        chunk = token[pos:m.start()]
        body = m.group(1)
        if "," in body:
            alts = [a.strip() for a in body.split(",") if a.strip()]
            out = [o + chunk + a for o in out for a in alts]
        else:
            # single-word group = tag annotation ({reason}, {r<i>}): the
            # base name is the metric; the tag dimension is not a name
            out = [o + chunk for o in out]
        pos = m.end()
    out = [o + token[pos:] for o in out]
    return [o for o in out
            if o and _NAME_RE.match(o.replace("\0", "x"))]


class MetricNameCatalog(Rule):
    id = "metric-name-catalog"

    def __init__(self, config):
        super().__init__(config)
        self._lits = {}      # name -> (ctx, line) of first record site
        self._pats = []      # (regex-source, ctx, line)

    # ------------------------------------------------------------ collection
    def _in_scope(self, rel):
        for s in getattr(self.config, "metric_scopes", ("mxtpu",)):
            if s in ("", "."):
                return True
            if rel == s or rel.startswith(s.rstrip("/") + "/"):
                return True
        return False

    def visit(self, ctx, project):
        if not self._in_scope(ctx.rel):
            return
        telemetry_module = ctx.rel.endswith("telemetry.py")
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if isinstance(fn, ast.Attribute) and \
                    isinstance(fn.value, ast.Name) and \
                    fn.value.id in _TELEMETRY_NAMES:
                attr = fn.attr
            elif telemetry_module and isinstance(fn, ast.Name):
                # inside mxtpu/telemetry.py the writers are module-local
                # (inc("transfer.d2h"), span(...) the class)
                attr = fn.id
            elif isinstance(fn, (ast.Name, ast.Attribute)) and \
                    (fn.id if isinstance(fn, ast.Name)
                     else fn.attr) == "with_retries":
                # resilience.with_retries(metric="retry.<site>") is a
                # declared counter writer — the literal lives in the
                # kwarg, not in a telemetry.inc call
                for kw in call_keywords(node):
                    if kw.arg == "metric":
                        k, v = _resolve_name(kw.value)
                        if k == "lit":
                            self._lits.setdefault(v, (ctx, node.lineno))
                        elif k == "pat":
                            self._pats.append((v, ctx, node.lineno))
                continue
            elif isinstance(fn, ast.Attribute) and \
                    fn.attr in _WRAPPER_WRITERS:
                self._take(node, _WRAPPER_WRITERS[fn.attr], ctx)
                continue
            else:
                continue
            if attr == _RETRACE:
                self._take(node, 0, ctx, prefix="retrace.")
                continue
            if attr not in _WRITERS:
                continue
            self._take(node, _WRITERS[attr], ctx,
                       d2h_twin=(attr == "span"))

    def _take(self, call, argpos, ctx, prefix="", d2h_twin=False):
        if len(call.args) <= argpos:
            return
        kind, v = _resolve_name(call.args[argpos])
        line = call.lineno
        if kind == "lit":
            self._lits.setdefault(prefix + v, (ctx, line))
            if d2h_twin and any(
                    kw.arg == "d2h" and
                    isinstance(kw.value, ast.Constant) and
                    kw.value.value is True for kw in call.keywords):
                self._lits.setdefault(v + ".d2h", (ctx, line))
        elif kind == "pat":
            self._pats.append((re.escape(prefix) + v, ctx, line))

    # ------------------------------------------------------------- verdicts
    def finalize(self, project):
        if not self._lits and not self._pats:
            return  # nothing scanned (rule scoped out) — no doc verdicts
        doc_rel = getattr(self.config, "metric_doc",
                          "docs/observability.md")
        doc_path = self.config.root / doc_rel
        try:
            doc_text = doc_path.read_text(encoding="utf-8")
        except OSError:
            self.report(None, doc_rel, 1,
                        "metric catalog %s is missing — every telemetry "
                        "metric/span name needs a documented row" % doc_rel)
            return
        doc_names, doc_pats = parse_doc_rows(doc_text)
        doc_regexes = [re.compile(p + "$") for p, _ in doc_pats]

        for name in sorted(self._lits):
            if name in doc_names or \
                    any(rx.match(name) for rx in doc_regexes):
                continue
            ctx, line = self._lits[name]
            self.report(
                ctx, ctx.rel, line,
                "metric/span name '%s' is recorded here but has no row in "
                "%s — add one (meaning + source) to the metric catalog"
                % (name, doc_rel))

        code_regexes = [re.compile(p + "$") for p, _, _ in self._pats]

        def covered(probe):
            return probe in self._lits or \
                any(rx.match(probe) for rx in code_regexes)

        for name in sorted(doc_names):
            if not covered(name):
                self.report(
                    None, doc_rel, doc_names[name],
                    "metric '%s' is cataloged here but no record site "
                    "survives in the scanned tree — stale row; delete it "
                    "or restore the metric" % name)
        for pat, line in doc_pats:
            # instantiate the placeholder with a probe value: the row is
            # alive iff SOME code site can produce a matching name
            probe = pat.replace("\\", "")
            probe = probe.replace(".*", "0")
            if not covered(probe):
                self.report(
                    None, doc_rel, line,
                    "metric family row (pattern %r) has no surviving "
                    "record site — stale row" % pat)
