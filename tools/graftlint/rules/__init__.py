"""graftlint rules, one module per rule."""
from .policy_key import PolicyKeyCoverage
from .host_sync import HostSyncInTracedRegion
from .donation import UseAfterDonate
from .retrace import RetraceSiteRegistration
from .env_catalog import EnvVarCatalog
from .metric_catalog import MetricNameCatalog

ALL_RULES = [
    PolicyKeyCoverage,
    HostSyncInTracedRegion,
    UseAfterDonate,
    RetraceSiteRegistration,
    EnvVarCatalog,
    MetricNameCatalog,
]

ALL_RULE_IDS = [cls.id for cls in ALL_RULES]

__all__ = ["ALL_RULES", "ALL_RULE_IDS"]
