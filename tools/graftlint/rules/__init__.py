"""graftlint rules, one module per rule."""
from .policy_key import PolicyKeyCoverage
from .host_sync import HostSyncInTracedRegion
from .donation import UseAfterDonate
from .retrace import RetraceSiteRegistration
from .env_catalog import EnvVarCatalog

ALL_RULES = [
    PolicyKeyCoverage,
    HostSyncInTracedRegion,
    UseAfterDonate,
    RetraceSiteRegistration,
    EnvVarCatalog,
]

ALL_RULE_IDS = [cls.id for cls in ALL_RULES]

__all__ = ["ALL_RULES", "ALL_RULE_IDS"]
