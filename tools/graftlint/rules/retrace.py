"""retrace-site-registration: every jax.jit site reports its compiles.

The retrace watchdog (docs/observability.md) can only see compiles that
are reported to it: a ``jax.jit`` call site must either call
``telemetry.record_retrace(site, provenance)`` in an enclosing function
(the cache-miss path) or carry an entry in
``tools/graftlint/config.py:JIT_ALLOWLIST`` naming where its compiles ARE
counted. An unregistered site is a blind spot — a recompile storm there
serializes training behind the compiler with no watchdog warning.

This rule is also the scout for ROADMAP item 5 (one compile-cache engine
under all jit surfaces): it emits a **jit-surface inventory** — one JSON
record per site with its enclosing qualname, donation discipline, cache-key
expression (the ``key = ...`` assignment in the enclosing function, when
present), and retrace site name — via ``--inventory`` / ``--json``."""
from __future__ import annotations

import ast
from typing import Optional

from ..astutil import (build_parent_map, enclosing_functions, is_jit_call,
                       qualname_of)
from ..core import Rule


def _find_record_retrace(fn: ast.AST) -> Optional[str]:
    """First telemetry.record_retrace(...) call in ``fn``; returns the
    site-name literal (or '<dynamic>' for a computed site)."""
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "record_retrace":
            if node.args and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                return node.args[0].value
            return "<dynamic>"
    return None


def _donation_of(call: ast.Call) -> Optional[str]:
    parts = []
    for kw in call.keywords:
        if kw.arg in ("donate_argnums", "donate_argnames"):
            parts.append("%s=%s" % (kw.arg, ast.unparse(kw.value)))
    return ", ".join(parts) or None


def _cache_key_of(fn: Optional[ast.AST]) -> Optional[str]:
    """The ``key = <expr>`` assignment in the enclosing function — by
    convention every cache site builds its cache key under that name."""
    if fn is None:
        return None
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == "key":
            return ast.unparse(node.value)
    return None


class RetraceSiteRegistration(Rule):
    id = "retrace-site-registration"

    def __init__(self, config):
        super().__init__(config)
        self.inventory = []

    def visit(self, ctx, project):
        parents = build_parent_map(ctx.tree)
        for node in ast.walk(ctx.tree):
            if not is_jit_call(node):
                continue
            chain = enclosing_functions(node, parents)
            site = None
            for fn in chain:
                site = _find_record_retrace(fn)
                if site is not None:
                    break
            enclosing_name = chain[0].name if chain else "<module>"
            allow = self.config.jit_allowlist.get((ctx.rel, enclosing_name))
            # a "<dynamic>" site IS registered (record_retrace runs with a
            # computed name — e.g. the serving Predictor's per-replica
            # serving.predict.r<i> sites), but the static name is unknown;
            # an allowlist entry resolves it for the inventory so the
            # scouting report never shows an anonymous cache
            unresolved = site in (None, "<dynamic>")
            entry = {
                "file": ctx.rel,
                "line": node.lineno,
                "function": qualname_of(node, parents),
                "donation": _donation_of(node),
                "cache_key": _cache_key_of(chain[0] if chain else None),
                "retrace_site": (allow["site"] if allow and unresolved
                                 else site),
                "allowlisted": bool(allow and unresolved),
            }
            if allow and unresolved and allow.get("cache_key"):
                entry["cache_key"] = allow["cache_key"]
            self.inventory.append(entry)
            if site is None and allow is None:
                self.report(
                    ctx, ctx.rel, node.lineno,
                    "jax.jit site (in %s) reports no compiles: call "
                    "telemetry.record_retrace('<site>', provenance) on "
                    "the cache-miss path, or add ('%s', '%s') to "
                    "tools/graftlint/config.py:JIT_ALLOWLIST naming where "
                    "its compiles are counted — unregistered sites are "
                    "invisible to the retrace watchdog"
                    % (entry["function"], ctx.rel, enclosing_name))
