"""retrace-site-registration: every jax.jit site reports its compiles.

The retrace watchdog (docs/observability.md) can only see compiles that
are reported to it: a ``jax.jit`` call site must either call
``telemetry.record_retrace(site, provenance)`` in an enclosing function
(the cache-miss path) or carry an entry in
``tools/graftlint/config.py:JIT_ALLOWLIST`` naming where its compiles ARE
counted. An unregistered site is a blind spot — a recompile storm there
serializes training behind the compiler with no watchdog warning.

This rule is also the scout for ROADMAP item 5 (one compile-cache engine
under all jit surfaces): it emits a **jit-surface inventory** — one JSON
record per site with its enclosing qualname, donation discipline, cache-key
expression (the ``key = ...`` assignment in the enclosing function, when
present), and retrace site name — via ``--inventory`` / ``--json``."""
from __future__ import annotations

import ast
from typing import Optional

from ..astutil import (build_parent_map, enclosing_functions, is_jit_call,
                       qualname_of)
from ..core import Rule


def _find_record_retrace(fn: ast.AST) -> Optional[str]:
    """First telemetry.record_retrace(...) call in ``fn``; returns the
    site-name literal (or '<dynamic>' for a computed site)."""
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "record_retrace":
            if node.args and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                return node.args[0].value
            return "<dynamic>"
    return None


def _call_name(node: ast.Call) -> Optional[str]:
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return None


def _find_service_seam(fn: ast.AST) -> bool:
    """True when ``fn`` resolves its executables through the compile
    service (a ``compile_service.get_or_build`` / ``WarmupEntry`` /
    ``canonical_key`` call) — the ISSUE-15 seam every jit cache must
    speak."""
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and _call_name(node) in (
                "get_or_build", "canonical_key", "WarmupEntry"):
            return True
    return False


def _find_canonical_site(fn: ast.AST):
    """First ``canonical_key(site=...)`` call in ``fn``: returns
    (site-literal-or-'<dynamic>', unparsed call) — the call expression
    IS the cache-key declaration of a service-routed site."""
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) \
                and _call_name(node) == "canonical_key":
            for kw in node.keywords:
                if kw.arg == "site":
                    if isinstance(kw.value, ast.Constant) \
                            and isinstance(kw.value.value, str):
                        return kw.value.value, ast.unparse(node)
                    break
            return "<dynamic>", ast.unparse(node)
    return None


def _donation_of(call: ast.Call) -> Optional[str]:
    parts = []
    for kw in call.keywords:
        if kw.arg in ("donate_argnums", "donate_argnames"):
            parts.append("%s=%s" % (kw.arg, ast.unparse(kw.value)))
    return ", ".join(parts) or None


def _cache_key_of(fn: Optional[ast.AST]) -> Optional[str]:
    """The ``key = <expr>`` assignment in the enclosing function — by
    convention every cache site builds its cache key under that name."""
    if fn is None:
        return None
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == "key":
            return ast.unparse(node.value)
    return None


class RetraceSiteRegistration(Rule):
    id = "retrace-site-registration"

    def __init__(self, config):
        super().__init__(config)
        self.inventory = []

    def visit(self, ctx, project):
        parents = build_parent_map(ctx.tree)
        for node in ast.walk(ctx.tree):
            if not is_jit_call(node):
                continue
            chain = enclosing_functions(node, parents)
            site = None
            service = False
            ck_expr = None
            for fn in chain:
                if _find_service_seam(fn):
                    service = True
                cs = _find_canonical_site(fn)
                if cs is not None and site is None:
                    site, ck_expr = cs
                if site is None:
                    site = _find_record_retrace(fn)
            # the allowlist key may name ANY enclosing function (the
            # jax.jit call often lives in a nested build closure since
            # the compile-service migration)
            allow = None
            allow_name = chain[0].name if chain else "<module>"
            for fn in chain:
                a = self.config.jit_allowlist.get((ctx.rel, fn.name))
                if a is not None:
                    allow, allow_name = a, fn.name
                    break
            # a "<dynamic>" site IS registered (record_retrace /
            # canonical_key runs with a computed name — e.g. the serving
            # Predictor's per-replica serving.predict.r<i> sites), but
            # the static name is unknown; an allowlist entry resolves it
            # for the inventory so the scouting report never shows an
            # anonymous cache
            unresolved = site in (None, "<dynamic>")
            cache_key = ck_expr
            if cache_key is None:
                for fn in chain:
                    cache_key = _cache_key_of(fn)
                    if cache_key is not None:
                        break
            entry = {
                "file": ctx.rel,
                "line": node.lineno,
                "function": qualname_of(node, parents),
                "donation": _donation_of(node),
                "cache_key": cache_key,
                "retrace_site": (allow["site"] if allow and unresolved
                                 else site),
                "allowlisted": bool(allow and unresolved),
                "service": bool(service or (allow or {}).get("service")),
            }
            if allow and unresolved and allow.get("cache_key"):
                entry["cache_key"] = allow["cache_key"]
            self.inventory.append(entry)
            if site is None and allow is None:
                self.report(
                    ctx, ctx.rel, node.lineno,
                    "jax.jit site (in %s) reports no compiles: call "
                    "telemetry.record_retrace('<site>', provenance) on "
                    "the cache-miss path, or add ('%s', '%s') to "
                    "tools/graftlint/config.py:JIT_ALLOWLIST naming where "
                    "its compiles are counted — unregistered sites are "
                    "invisible to the retrace watchdog"
                    % (entry["function"], ctx.rel, allow_name))
            elif not entry["service"] and any(
                    ctx.rel.startswith(scope)
                    for scope in self.config.service_scopes):
                self.report(
                    ctx, ctx.rel, node.lineno,
                    "jax.jit site (in %s) keeps an out-of-band cache: "
                    "every runtime jit surface must resolve through "
                    "mxtpu/compile_service.py (get_or_build with a "
                    "canonical_key) so it shares the LRU bound, the "
                    "persistent executable cache, and AOT warmup — or "
                    "declare 'service': True in its JIT_ALLOWLIST entry "
                    "naming the front door that routes it "
                    "(docs/compile_cache.md)"
                    % (entry["function"],))
