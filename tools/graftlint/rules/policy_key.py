"""policy-key-coverage: every trace-time MXTPU_* lever is in registry.policy_key
with a read-site default that MIRRORS the key entry.

The hazard (documented at the key itself, mxtpu/ops/registry.py:90): every
jit cache in the runtime keys on ``registry.policy_key()``. A trace-time
``MXTPU_*`` read that is absent from the key tuple means flipping that
lever mid-process silently reuses executables traced under the old policy;
a read-site default that differs from the key entry's default means *unset*
and the non-default value alias onto one cache key — an A/B measurement
would then compare a lever with itself.

Scope: reads inside ``config.trace_scopes`` (mxtpu/ops/, mxtpu/contrib/,
mxtpu/parallel/, mxtpu/resilience.py — the trees whose code executes under
jax tracing) must be key members; default-mismatch checks apply to key
members read ANYWHERE in the analyzed files. Genuinely host-side reads in
a trace scope carry ``# graftlint: disable=policy-key-coverage`` with a
reason at the read site.

Runtime twin: the retrace watchdog (docs/observability.md) — it catches
the recompile storm a *present* key member causes when flipped; this rule
catches the silent aliasing of an *absent* one, which the watchdog by
construction never sees."""
from __future__ import annotations

import ast

from ..astutil import MISSING, NONCONST, iter_env_reads
from ..core import Rule


def parse_policy_key(tree: ast.AST):
    """Extract ``[(env_name, default_literal), ...]`` from the
    ``policy_key()`` function of the registry module."""
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and node.name == "policy_key":
            return [(r.name, r.default) for r in iter_env_reads(node)]
    return []


class PolicyKeyCoverage(Rule):
    id = "policy-key-coverage"

    def __init__(self, config):
        super().__init__(config)
        self._key = None  # name -> default (loaded lazily via project)

    def _key_map(self, project):
        if self._key is None:
            ctx = project.ctx_for(self.config.policy_key_module)
            entries = parse_policy_key(ctx.tree) if ctx is not None else []
            self._key = dict(entries)
        return self._key

    def visit(self, ctx, project):
        skip_span = None
        if ctx.rel == self.config.policy_key_module:
            # the policy_key() function's own reads ARE the key — but the
            # REST of the registry module gets no special treatment
            for node in ast.walk(ctx.tree):
                if isinstance(node, ast.FunctionDef) \
                        and node.name == "policy_key":
                    skip_span = (node.lineno, node.end_lineno)
                    break
        key = self._key_map(project)
        in_scope = self.config.in_trace_scope(ctx.rel)
        for read in iter_env_reads(ctx.tree):
            if not read.name.startswith("MXTPU_"):
                continue
            if skip_span and skip_span[0] <= read.line <= skip_span[1]:
                continue
            if read.name not in key:
                if in_scope:
                    self.report(
                        ctx, ctx.rel, read.line,
                        "trace-time lever %s is read here but absent from "
                        "registry.policy_key — executables compiled under "
                        "different settings of it alias onto one cache "
                        "key; add it to the key tuple, or mark this read "
                        "host-side with '# graftlint: "
                        "disable=policy-key-coverage' plus a reason"
                        % read.name)
                continue
            kd = key[read.name]
            if read.default is NONCONST or kd is NONCONST:
                continue  # can't judge computed defaults statically
            if read.default is MISSING:
                self.report(
                    ctx, ctx.rel, read.line,
                    "%s is read without a default here but "
                    "registry.policy_key defaults it to %r — when unset, "
                    "this site sees None while the cache key records %r, "
                    "aliasing unset and non-default runs; mirror the key "
                    "default at this read site" % (read.name, kd, kd))
            elif read.default != kd:
                self.report(
                    ctx, ctx.rel, read.line,
                    "%s default %r here vs %r in registry.policy_key — "
                    "defaults must MIRROR the key entry or unset-vs-set "
                    "runs alias onto one compiled executable"
                    % (read.name, read.default, kd))
