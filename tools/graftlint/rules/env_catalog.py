"""env-var-catalog: docs/env_vars.md and the code agree, both directions.

Every ``MXTPU_*``/``BENCH_*`` env read must have a table row in the
catalog (first cell, backticked), and every cataloged row must have a
surviving read site — stale rows are flagged at their doc line. Because
the catalog is repo-global while a lint run usually targets ``mxtpu/``,
the rule additionally scans ``config.env_extra_roots`` (bench.py, tools/,
tests/) for reads, so BENCH_* rows consumed only by the bench layer are
neither stale nor invisible.

Writes (``os.environ[k] = v``, monkeypatch.setenv) do not count as reads:
a variable that is only ever SET is either dead or consumed elsewhere —
the read site is what the row documents."""
from __future__ import annotations

import re
from pathlib import Path

from ..astutil import iter_env_reads
from ..core import Rule

PREFIXES = ("MXTPU_", "BENCH_")
_ROW_NAME_RE = re.compile(r"`((?:MXTPU|BENCH)_[A-Z0-9_]+)`")


def parse_doc_rows(text: str):
    """{name: line} for every prefixed, backticked name in the FIRST cell
    of a markdown table row (names mentioned in prose or in the meaning
    cell of another row do not count as documented)."""
    rows = {}
    for i, line in enumerate(text.splitlines(), 1):
        stripped = line.lstrip()
        if not stripped.startswith("|"):
            continue
        cells = stripped.split("|")
        if len(cells) < 3:
            continue
        for name in _ROW_NAME_RE.findall(cells[1]):
            rows.setdefault(name, i)
    return rows


class EnvVarCatalog(Rule):
    id = "env-var-catalog"

    def __init__(self, config):
        super().__init__(config)
        self._reads = {}    # name -> (ctx, line) of first read seen
        self._visited = set()

    def visit(self, ctx, project):
        self._visited.add(ctx.rel)
        self._collect(ctx)

    def _collect(self, ctx):
        for read in iter_env_reads(ctx.tree):
            if read.name.startswith(PREFIXES):
                self._reads.setdefault(read.name, (ctx, read.line))

    def _extra_files(self):
        for root in self.config.env_extra_roots:
            base = self.config.root / root
            if base.is_file():
                yield Path(root).as_posix()
            elif base.is_dir():
                for p in sorted(base.rglob("*.py")):
                    if "__pycache__" in p.parts:
                        continue
                    yield p.relative_to(self.config.root).as_posix()

    def finalize(self, project):
        for rel in self._extra_files():
            if rel in self._visited or self.config.is_excluded(rel):
                continue
            ctx = project.ctx_for(rel)
            if ctx is not None:
                self._collect(ctx)

        doc_rel = self.config.env_doc
        doc_path = self.config.root / doc_rel
        try:
            doc_text = doc_path.read_text(encoding="utf-8")
        except OSError:
            self.report(None, doc_rel, 1,
                        "env-var catalog %s is missing — every MXTPU_*/"
                        "BENCH_* read needs a documented row" % doc_rel)
            return
        rows = parse_doc_rows(doc_text)

        for name in sorted(self._reads):
            if name not in rows:
                ctx, line = self._reads[name]
                self.report(
                    ctx, ctx.rel, line,
                    "%s is read here but has no row in %s — add one "
                    "(meaning, default, and whether it is in "
                    "registry.policy_key)" % (name, doc_rel))
        for name in sorted(rows):
            if name not in self._reads:
                self.report(
                    None, doc_rel, rows[name],
                    "%s is cataloged here but no read site survives in "
                    "the scanned tree — stale row; delete it or restore "
                    "the read" % name)
