"""host-sync-in-traced-region: no device->host syncs inside jitted bodies.

Static twin of the d2h transfer watchdog (docs/observability.md): the
watchdog counts ``transfer.d2h`` at runtime and warns on a steady-state
hot-loop sync; this rule convicts the construct at review time. Inside a
function passed to ``jax.jit`` (or decorated with it, or nested in one —
FusedUpdater step fns, CachedOp ``pure``/``bwd``, executor bodies,
Predictor bucket fns), the flagged constructs either force a trace-time
transfer or fail outright on tracers:

* ``x.asnumpy()`` / ``x.item()`` / ``x.tolist()``
* ``np.asarray(x)`` / ``np.array(x)``
* ``jax.device_get(x)``
* ``float(x)`` / ``int(x)`` / ``bool(x)`` on a non-constant (scalar
  coercion syncs; ``bool`` on a traced predicate is the classic
  ConcretizationTypeError). Shape arithmetic — args mentioning ``.shape``
  or ``len(...)`` — is static under trace and NOT flagged.
"""
from __future__ import annotations

import ast

from ..astutil import find_traced_functions
from ..core import Rule

SYNC_METHODS = {"asnumpy", "item", "tolist"}
NP_MODULE_NAMES = {"np", "numpy", "onp", "_np"}
NP_SYNC_FUNCS = {"asarray", "array"}
COERCIONS = {"float", "int", "bool"}


def _is_shape_like(node: ast.AST) -> bool:
    """len(...)/x.shape[...] style expressions are static under trace."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in ("shape", "ndim",
                                                           "size", "dtype"):
            return True
        if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Name) \
                and sub.func.id == "len":
            return True
    return False


class HostSyncInTracedRegion(Rule):
    id = "host-sync-in-traced-region"

    def visit(self, ctx, project):
        traced = find_traced_functions(ctx.tree)
        seen = set()
        for root in traced:
            for node in ast.walk(root):
                if not isinstance(node, ast.Call):
                    continue
                key = (node.lineno, node.col_offset)
                if key in seen:
                    continue
                msg = self._check_call(node)
                if msg is not None:
                    seen.add(key)
                    self.report(ctx, ctx.rel, node.lineno, msg)

    def _check_call(self, node: ast.Call):
        func = node.func
        if isinstance(func, ast.Attribute):
            if func.attr in SYNC_METHODS:
                return ("'.%s()' inside a jit-traced function is a "
                        "device->host sync at trace time (the d2h "
                        "watchdog's static twin) — hoist it out of the "
                        "traced region or keep the value on device"
                        % func.attr)
            if func.attr in NP_SYNC_FUNCS \
                    and isinstance(func.value, ast.Name) \
                    and func.value.id in NP_MODULE_NAMES:
                return ("'%s.%s(...)' inside a jit-traced function "
                        "materializes the operand on host — use jnp.%s "
                        "or move this out of the traced region"
                        % (func.value.id, func.attr, func.attr))
            if func.attr == "device_get":
                return ("'device_get' inside a jit-traced function is a "
                        "device->host sync — hoist it out of the traced "
                        "region")
        elif isinstance(func, ast.Name) and func.id in COERCIONS \
                and len(node.args) == 1 and not node.keywords:
            arg = node.args[0]
            if isinstance(arg, ast.Constant) or _is_shape_like(arg):
                return None
            return ("'%s(...)' scalar coercion inside a jit-traced "
                    "function syncs (or raises ConcretizationTypeError) "
                    "on a traced value — keep it as a 0-d array, or "
                    "compute it host-side before the jit" % func.id)
        return None
