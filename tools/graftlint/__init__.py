"""graftlint: trace-discipline static analysis for the mxtpu runtime.

The runtime watchdogs (mxtpu/telemetry.py) catch trace-discipline bugs
*after* they have cost a recompile or a hot-loop sync; graftlint is their
static twin — it convicts the same classes of bug at review time, before
a chip session is burned on them:

====================================  =====================================
rule                                  runtime twin / contract
====================================  =====================================
policy-key-coverage                   retrace watchdog: a trace-time
                                      ``MXTPU_*`` lever missing from
                                      ``registry.policy_key`` (or whose
                                      read-site default differs from the
                                      key entry) silently aliases
                                      executables compiled under different
                                      policies (mxtpu/ops/registry.py:90)
host-sync-in-traced-region            d2h transfer watchdog: ``.asnumpy``/
                                      ``.item``/``float()``/``np.asarray``
                                      inside a jitted function is a
                                      trace-time host sync
use-after-donate                      donated buffers are deleted by XLA —
                                      reading one after the call is UB
retrace-site-registration             every ``jax.jit`` site must report
                                      compiles via
                                      ``telemetry.record_retrace`` (or be
                                      allowlisted); also emits the
                                      jit-surface inventory JSON
env-var-catalog                       every ``MXTPU_*``/``BENCH_*`` read
                                      has a row in docs/env_vars.md and
                                      vice versa
====================================  =====================================

Usage::

    python -m tools.graftlint mxtpu/                  # lint, exit 1 on findings
    python -m tools.graftlint mxtpu/ --json out.json  # findings + inventory
    python -m tools.graftlint mxtpu/ --inventory jit_surfaces.json

Inline suppression (same line as the finding)::

    x = os.environ.get("MXTPU_HOST_ONLY")  # graftlint: disable=policy-key-coverage

No dependencies beyond the stdlib ``ast`` module — safe to run as a
pre-flight gate anywhere (no jax import, no device).
"""
from .core import Finding, LintResult, run  # noqa: F401
from .config import LintConfig  # noqa: F401

__all__ = ["Finding", "LintResult", "LintConfig", "run"]
