"""graftlint CLI: ``python -m tools.graftlint [paths...]``.

Exit status: 0 = no unsuppressed findings, 1 = findings, 2 = usage error.
Stdout carries one ``file:line: [rule] message`` per finding; the summary
and artifact paths go to stderr so stdout stays machine-parseable."""
from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from .config import LintConfig
from .core import run
from .rules import ALL_RULES, ALL_RULE_IDS


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m tools.graftlint",
        description="Trace-discipline static analyzer for the mxtpu "
                    "runtime (policy-key coverage, host-sync, donation "
                    "safety, retrace registration, env-var catalog).")
    p.add_argument("paths", nargs="*", default=["mxtpu"],
                   help="files or directories to lint (default: mxtpu)")
    p.add_argument("--root", default=".",
                   help="repo root anchoring relative paths and the "
                        "policy-key/env-doc lookups (default: cwd)")
    p.add_argument("--rules", default=None,
                   help="comma-separated rule ids (default: all)")
    p.add_argument("--json", dest="json_path", default=None,
                   help="write findings + jit-surface inventory as JSON")
    p.add_argument("--inventory", dest="inventory_path", default=None,
                   help="write ONLY the jit-surface inventory JSON "
                        "(ROADMAP item 5's scouting report)")
    p.add_argument("--list-rules", action="store_true",
                   help="print rule ids + one-line summaries and exit")
    args = p.parse_args(argv)

    if args.list_rules:
        for cls in ALL_RULES:
            doc = (cls.__module__ and sys.modules[cls.__module__].__doc__
                   or "").strip().splitlines()
            print("%-28s %s" % (cls.id, doc[0] if doc else ""))
        return 0

    rule_ids = None
    if args.rules:
        rule_ids = [r.strip() for r in args.rules.split(",") if r.strip()]

    config = LintConfig(root=Path(args.root))
    try:
        result = run(config, args.paths, rule_ids)
    except ValueError as e:
        print("graftlint: %s" % e, file=sys.stderr)
        return 2

    for f in result.findings:
        print(f.format())

    if args.json_path:
        payload = {
            "findings": [f.as_dict() for f in result.findings],
            "suppressed": [f.as_dict() for f in result.suppressed],
            "jit_inventory": result.jit_inventory,
            "files": result.files,
        }
        Path(args.json_path).write_text(json.dumps(payload, indent=2))
        print("graftlint: wrote %s" % args.json_path, file=sys.stderr)
    if args.inventory_path:
        Path(args.inventory_path).write_text(
            json.dumps(result.jit_inventory, indent=2))
        print("graftlint: wrote %s" % args.inventory_path, file=sys.stderr)

    print("graftlint: %d finding(s), %d suppressed, %d file(s), "
          "%d jit site(s)"
          % (len(result.findings), len(result.suppressed), result.files,
             len(result.jit_inventory)), file=sys.stderr)
    return 1 if result.findings else 0


if __name__ == "__main__":
    sys.exit(main())
