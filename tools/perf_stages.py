"""Localize the slow resnet50 forward (PERF.md gap #1): time truncated
prefixes of the exact bench model — stem only, stem+stage1, ... — fwd and
fwd+bwd, scan-fused into one dispatch. The per-stage *increments* attribute
step time to layer groups without needing the (tunnel-hostile) profiler."""
import os
import sys
import time

import numpy as np
import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def timed_scan(fn, args, K=8):
    """One jit dispatch of K chained applications; host-fetch sync."""
    def body(c, _):
        out = fn(c[0], *args[1:])
        # keep shapes: fold output back into the carry input cheaply
        return (c[0] + 0 * jnp.mean(out.astype(jnp.float32)).astype(c[0].dtype),
                ), None

    @jax.jit
    def run(x):
        c, _ = jax.lax.scan(body, (x,), None, length=K)
        return c[0]

    y = run(args[0])
    _ = np.asarray(jax.device_get(y.ravel()[:2]))
    t0 = time.perf_counter()
    y = run(args[0])
    _ = np.asarray(jax.device_get(y.ravel()[:2]))
    return (time.perf_counter() - t0) / K


def main():
    from mxtpu.parallel import pure_forward
    from perf_common import build_resnet

    batch = int(os.environ.get("BENCH_BATCH", "128"))
    net, x, _y = build_resnet(batch)
    # resnet v1 body (mxtpu zoo): features = [stem convs..., stage1..4, pool]
    feats = list(net.features._children.values())
    # group prefix cut points: after stem (first 4 blocks: conv/bn/act/pool),
    # then after each residual stage
    names = [type(b).__name__ for b in feats]
    print("feature blocks:", names, flush=True)
    cuts = []
    seen_stage = 0
    for i, b in enumerate(feats):
        if type(b).__name__ in ("HybridSequential",):
            seen_stage += 1
            cuts.append((i + 1, "through stage%d" % seen_stage))
    if not cuts:
        cuts = [(len(feats), "full features")]
    cuts.insert(0, (cuts[0][0] - 1 if cuts else 4, "stem"))

    import mxtpu as mx
    prev = 0.0
    for upto, label in cuts + [(None, "full net (incl. dense)")]:
        if upto is None:
            fn, params = pure_forward(net, train=True)
        else:
            sub = mx.gluon.nn.HybridSequential()
            for b in feats[:upto]:
                sub.add(b)
            fn, params = pure_forward(sub, train=True)

        def f(xd, fn=fn, params=params):
            return fn(params, xd)

        dt = timed_scan(f, (x._data,))
        print("%-28s %7.2f ms  (+%.2f ms)" % (label, dt * 1e3,
                                              (dt - prev) * 1e3), flush=True)
        prev = dt

        def floss(xd, fn=fn, params=params):
            return jnp.sum(fn(params, xd).astype(jnp.float32)) * 1e-6

        g = jax.grad(lambda xd: floss(xd))
        dtb = timed_scan(lambda xd: g(xd), (x._data,))
        print("%-28s %7.2f ms fwd+bwd(x)" % ("", dtb * 1e3), flush=True)


if __name__ == "__main__":
    main()
