"""One-shot post-fix validation on the real chip (run when the tunnel is
up): tunnel RTT + scan-fused on-chip step time. Run ``python bench.py``
separately for the full scoring numbers; append both to PERF.md."""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import jax

    print("devices:", jax.devices(), flush=True)

    # 1) scan-fused on-chip step (the round-3 diagnosis method)
    import jax.numpy as jnp
    from mxtpu import gluon
    from mxtpu.ndarray import NDArray
    from mxtpu.parallel import pure_forward
    from perf_common import build_resnet, measure_rtt

    print("tunnel RTT: %.1f ms" % (measure_rtt() * 1e3), flush=True)
    net, x, yl = build_resnet()
    fn_t, params_t = pure_forward(net, train=True)
    loss_blk = gluon.loss.SoftmaxCrossEntropyLoss()

    def loss_of(p, xd, yd):
        return jnp.mean(loss_blk(NDArray(fn_t(p, xd)), NDArray(yd))._data)

    def one_step(p, _):
        l, g = jax.value_and_grad(loss_of)(p, x._data, yl._data)
        return [(w - 0.01 * gw.astype(w.dtype)) for w, gw in zip(p, g)], l

    K = 10

    @jax.jit
    def multi(p):
        _, ls = jax.lax.scan(one_step, p, None, length=K)
        return ls[-1]

    float(multi(params_t))  # compile + run
    t0 = time.perf_counter()
    float(multi(params_t))
    dt = time.perf_counter() - t0
    batch = x.shape[0]
    print("scan(%d) fwd+bwd+sgd: %.2f ms/step -> %.0f img/s"
          % (K, dt / K * 1e3, batch * K / dt), flush=True)


if __name__ == "__main__":
    main()
