"""BatchNorm cost attribution (PERF.md round-4 plan item #2).

Two halves:

1. HLO fusion analysis (works anywhere, incl. CPU): jit a
   conv->BN->relu training block, dump the OPTIMIZED HLO, and report
   (a) whether mean and variance share ONE input-reading fusion
   (two sibling reduces fused = one stats read; separate = two),
   (b) whether the normalize arithmetic fused into the convolution's
   consumer fusion (no standalone elementwise pass over the activation),
   (c) total kFusion count and any naked (unfused) elementwise ops.
   Run with MXTPU_BN_ONEPASS=0 vs =1 to compare the staged lever.

2. On-chip timing (needs the real device): steps/sec of the block with
   BN vs without BN at resnet50 stage shapes — the measured per-BN cost
   the PERF.md table wants. Scan-fused, host-fetch synced (tunnel-safe).

Usage:
    python tools/perf_bn.py [--platform cpu] [--hlo-only]
"""
import argparse
import os
import re
import time

import numpy as np


def build_block(with_bn=True, train=True):
    import jax
    import jax.numpy as jnp

    from mxtpu.ops.registry import get_op

    conv = get_op("Convolution").fn

    # resnet50 stage-2 spatial/channel shape at batch 32 (a quarter of
    # the b128 bench batch, so CPU runs stay tractable; scale linearly)
    N, H, W, C = 32, 28, 28, 128
    x = jnp.ones((N, H, W, C), jnp.bfloat16)
    w = jnp.ones((3, 3, C, C), jnp.bfloat16) * 0.01  # HWIO (NHWC)
    g = jnp.ones((C,), jnp.float32)
    b = jnp.zeros((C,), jnp.float32)
    mm = jnp.zeros((C,), jnp.float32)
    mv = jnp.ones((C,), jnp.float32)

    def fwd(x, w):
        y = conv(x, w, None, kernel=(3, 3), num_filter=C, pad=(1, 1),
                 no_bias=True, layout="NHWC")
        if with_bn:
            # THE shipped stats implementation (shared helper), so this
            # tool can never drift from what BatchNorm compiles
            from mxtpu.ops.nn import bn_batch_stats
            shape = [1, 1, 1, C]
            xf = y.astype(jnp.float32)
            if train:
                mean, var = bn_batch_stats(xf, (0, 1, 2))
            else:
                mean, var = mm, mv
            inv = jax.lax.rsqrt(var + 1e-3)
            y = ((xf - mean.reshape(shape)) * (inv * g).reshape(shape)
                 + b.reshape(shape)).astype(y.dtype)
        return jax.nn.relu(y)

    return fwd, (x, w)


def analyze_hlo(train=True):
    import jax

    fwd, args = build_block(with_bn=True, train=train)
    lowered = jax.jit(fwd).lower(*args)
    hlo = lowered.compile().as_text()

    fusions = re.findall(r"^\s*(?:ROOT\s+)?%?\S+ = \S+ fusion\(", hlo,
                         re.M)
    reduces = re.findall(r" reduce\(|reduce-window\(", hlo)
    convs = re.findall(r"convolution\(|custom-call.*conv", hlo)
    # count fusion COMPUTATIONS containing a reduce (stats passes)
    stat_fusions = 0
    for m in re.finditer(r"^%?fused_[\w.]+ \([^)]*\) -> .*?\{(.*?)^\}",
                         hlo, re.S | re.M):
        if "reduce(" in m.group(1):
            stat_fusions += 1
    print("optimized-HLO summary (%s, MXTPU_BN_ONEPASS=%s):"
          % ("train" if train else "eval",
             # default mirrors ops/nn.py:_bn_onepass (1 as of round 5)
             os.environ.get("MXTPU_BN_ONEPASS", "1")))
    print("  fusion ops:          %d" % len(fusions))
    print("  fusions w/ reduce:   %d  (1 = mean+var share one stats read)"
          % stat_fusions)
    print("  conv calls:          %d" % len(convs))
    print("  raw reduce mentions: %d" % len(reduces))
    return hlo


def time_block(reps=20):
    import jax
    import jax.numpy as jnp

    for with_bn in (False, True):
        fwd, args = build_block(with_bn=with_bn)

        # scan over the forward so K iterations cost ONE dispatch
        f = jax.jit(lambda x, w: jax.lax.scan(
            lambda c, _: (fwd(c, w).astype(c.dtype), None), x, None,
            length=reps)[0])
        y = f(*args)
        np.asarray(jax.device_get(y.ravel()[:2]))  # warm + sync
        t0 = time.perf_counter()
        y = f(*args)
        np.asarray(jax.device_get(y.ravel()[:2]))
        dt = (time.perf_counter() - t0) / reps
        print("%-10s %.3f ms/iter" % ("conv+bn" if with_bn else "conv",
                                      dt * 1e3))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--platform", default=None)
    ap.add_argument("--hlo-only", action="store_true")
    ns = ap.parse_args()
    if ns.platform:
        import jax
        jax.config.update("jax_platforms", ns.platform)
    analyze_hlo(train=True)
    if not ns.hlo_only:
        time_block()


if __name__ == "__main__":
    main()
