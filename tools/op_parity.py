"""Generate docs/op_parity.md: every operator name registered by the
reference (NNVM_REGISTER_OP / MXNET_REGISTER_OP_PROPERTY under
/root/reference/src/operator) classified against this framework's registry.

Categories:
  implemented   - present in mxtpu.ops.REGISTRY (exact name or alias)
  autodiff      - `_backward_*`: the reference registers explicit backward
                  ops; jax autodiff derives them, so no registry entry exists
                  by design
  subsumed      - internal machinery replaced by the XLA/PJRT stack
                  (cross-device copies, cuDNN/MKLDNN/TensorRT variants,
                  slice-assign kernels behind __setitem__, storage casts that
                  live on NDArray.sparse, optimizer update kernels that live
                  in mxtpu.optimizer)
  missing       - anything else (a real gap)

Usage: python tools/op_parity.py [--write]   (--write refreshes the doc)
"""
import os
import re
import subprocess
import sys

REF = "/root/reference/src/operator"
DOC = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "docs", "op_parity.md")

# name -> (category, note)
SUBSUMED = {
    "_CrossDeviceCopy": "GSPMD/jax.device_put moves data; no graph op needed",
    "_NDArray": "legacy python-callback op: mxtpu.operator CustomOp covers it",
    "_Native": "legacy python-callback op: mxtpu.operator CustomOp covers it",
    "CuDNNBatchNorm": "cuDNN variant; XLA lowers the one BatchNorm",
    "_sg_mkldnn_conv": "MKLDNN subgraph kernel; XLA fusion + mxtpu subgraph",
    "_trt_op": "TensorRT offload; mxtpu.symbol.subgraph partitions instead",
    "_slice_assign": "NDArray.__setitem__ lowers to lax scatter directly",
    "_slice_assign_scalar": "NDArray.__setitem__ scalar path",
    "_identity_with_attr_like_rhs": "nnvm graph-pass helper; no graph IR here",
    "_zeros_without_dtype": "nnvm infer helper; jnp.zeros covers",
    "_rnn_param_concat": "fused-RNN packing helper; mxtpu packs in rnn_ops",
    "cast_storage": "NDArray.tostype / mxtpu.ndarray.sparse.cast_storage",
    "_sparse_retain": "RowSparseNDArray.retain (mxtpu/ndarray/sparse.py)",
    "_sparse_adagrad_update": "lazy sparse update inside mxtpu.optimizer.AdaGrad",
    "_contrib_group_adagrad_update": "mxtpu.optimizer.GroupAdaGrad.update",
    "ftml_update": "mxtpu.optimizer.FTML.update (pure update fns)",
    "mp_sgd_update": "multi-precision master weights in mxtpu.optimizer.SGD",
    "mp_sgd_mom_update": "multi-precision momentum in mxtpu.optimizer.SGD",
    "_contrib_SyncBatchNorm": "gluon.contrib.nn.SyncBatchNorm (cross-device "
                             "stats are a psum inside the jitted step)",
    "_broadcast_backward": "jax autodiff reduces broadcast grads",
}


def reference_ops():
    # Three registration spellings (VERDICT r4 weak #3: the original scan
    # missed ~145 ops registered through MXNET_OPERATOR_REGISTER_* wrapper
    # macros, e.g. src/operator/tensor/elemwise_unary_op_basic.cc:109
    # `MXNET_OPERATOR_REGISTER_UNARY(hard_sigmoid)`):
    #   NNVM_REGISTER_OP(name)                   - direct
    #   MXNET_REGISTER_OP_PROPERTY(name, ...)    - legacy v1 ops
    #   MXNET_OPERATOR_REGISTER_<KIND>(name)     - wrapper macros whose bodies
    #       token-paste into NNVM_REGISTER_OP; call sites live in .cc files
    out = subprocess.run(
        ["grep", "-rhoE",
         r"(NNVM_REGISTER_OP|MXNET_REGISTER_OP_PROPERTY"
         r"|MXNET_OPERATOR_REGISTER_[A-Z0-9_]+)\(([A-Za-z0-9_]+)",
         REF, "--include=*.cc"],
        capture_output=True, text=True).stdout
    names = set()
    for line in out.splitlines():
        m = re.search(r"\((\w+)$", line.strip())
        if m:
            names.add(m.group(1))
    # token-pasting macro bodies register via ##-substitution (e.g.
    # NNVM_REGISTER_OP(name) inside MXNET_OPERATOR_REGISTER_SAMPLE in
    # random/sample_op.cc:41) — the placeholder itself is not an op; the
    # concrete instantiations (sample_uniform, ...) are picked up from the
    # macro call sites the widened grep now sees
    names -= {"name", "__name", "_sample_", "distr", "fullname"}
    # *_BACKWARD / *_BWD wrapper macros register _backward_<x> twins that the
    # `_backward_` prefix rule already classifies; sampling macros register
    # `_sample_<distr>` via nested pasting handled by the concrete names
    return sorted(names)


def classify(names):
    from mxtpu.ops import registry

    have = set(registry.list_ops())

    def present(n):
        if n in have or n.lstrip("_") in have:
            return True
        try:
            registry.get_op(n)
            return True
        except Exception:
            return False

    rows = []
    for n in names:
        if present(n):
            rows.append((n, "implemented", ""))
        elif n.startswith("_backward_") or n.startswith("_contrib_backward_"):
            rows.append((n, "autodiff", "jax.vjp derives the backward"))
        elif n in SUBSUMED:
            rows.append((n, "subsumed", SUBSUMED[n]))
        else:
            rows.append((n, "missing", ""))
    return rows


def main():
    names = reference_ops()
    rows = classify(names)
    counts = {}
    for _n, cat, _ in rows:
        counts[cat] = counts.get(cat, 0) + 1
    lines = [
        "# Operator parity audit",
        "",
        "Generated by `python tools/op_parity.py --write` — every op name the",
        "reference registers (`NNVM_REGISTER_OP`/`MXNET_REGISTER_OP_PROPERTY`",
        "under `src/operator/`) classified against `mxtpu.ops.REGISTRY`.",
        "",
        "| category | count |",
        "|---|---|",
    ]
    for cat in ("implemented", "autodiff", "subsumed", "missing"):
        lines.append("| %s | %d |" % (cat, counts.get(cat, 0)))
    lines += ["", "| reference op | status | note |", "|---|---|---|"]
    for n, cat, note in rows:
        lines.append("| `%s` | %s | %s |" % (n, cat, note))
    text = "\n".join(lines) + "\n"
    if "--write" in sys.argv:
        with open(DOC, "w") as f:
            f.write(text)
        print("wrote", DOC)
    missing = [n for n, c, _ in rows if c == "missing"]
    print("total %d  implemented %d  autodiff %d  subsumed %d  missing %d"
          % (len(rows), counts.get("implemented", 0),
             counts.get("autodiff", 0), counts.get("subsumed", 0),
             len(missing)))
    if missing:
        print("missing:", missing)
    return missing


if __name__ == "__main__":
    main()
