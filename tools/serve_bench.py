#!/usr/bin/env python
"""Load generator for the serving subsystem (mxtpu/serving) — in-process.

Three phases against one AOT-warmed Predictor on the bench MLP, one JSON
line each (stamped with platform + policy_key like every bench artifact):

* ``sweep``  — direct Predictor batch-size sweep, items/s per bucket.
  The acceptance criterion rides this line: throughput must be
  monotonically non-decreasing from batch 1 to the max bucket (batching
  exists to fill the MXU; a bucket that serves SLOWER per item than a
  smaller one should simply not be declared).
* ``closed`` — closed-loop: N workers submit mixed-size requests
  back-to-back through the MicroBatcher (offered load == capacity).
  Reports items/s, req/s, client p50/p99, the compile count at retrace
  site ``serving.predict`` (must stay <= #buckets) and watchdog trips
  (must stay 0).
* ``open``   — open-loop: paced arrivals at each offered QPS with a
  per-request deadline. Reports achieved QPS, shed rate, deadline-expiry
  rate, p50/p99, and mean batch fill — the overload-behaviour curve
  (shed rate should rise and p99 should stay bounded once offered QPS
  exceeds capacity; an unbounded p99 means admission control is broken).
* ``replicas`` — ISSUE 8: closed-loop through a ReplicaSet router
  (``--replicas N``, 0 = one per device) with a kill-one-replica-mid-run
  sweep: halfway through, replica 0 is quarantined as if its chip died.
  Reports per-replica dispatch counts, throughput, shed/expired counts,
  and a **hang count** — futures that never completed. The acceptance
  gate: hangs == 0 through the replica loss (requests re-route, shed, or
  expire; none strand).

Usage::

    python tools/serve_bench.py [--mode sweep,closed,open,replicas]
        [--requests 500] [--max-batch 8] [--dim 256] [--width 512]
        [--depth 3] [--max-wait-ms 2] [--workers 4]
        [--qps 100,300,1000] [--deadline-ms 100]
        [--replicas 0] [--kill-replica 0]

``bench.py``'s ``serving`` config drives the same functions in-process,
and ``tools/perf_battery.sh`` runs this script as its serving phase.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _stamp(rec):
    """Platform + active policy levers on every line (bench.py contract:
    a CPU-fallback artifact must be distinguishable from a chip run)."""
    try:
        import jax
        rec.setdefault("platform", jax.devices()[0].platform)
    except Exception:  # noqa: BLE001
        rec.setdefault("platform", "unknown")
    try:
        from mxtpu.ops.registry import policy_key
        rec.setdefault("policy_key", list(policy_key()))
    except Exception:  # noqa: BLE001
        rec.setdefault("policy_key", None)
    return rec


def _emit(rec):
    print(json.dumps(_stamp(rec)), flush=True)


def build_predictor(dim=256, width=512, depth=3, out_dim=64, max_batch=8,
                    dtype="float32"):
    """The bench model: a depth-layer MLP — small enough that dispatch
    overhead is visible (the regime micro-batching exists for), wide
    enough that per-item math grows with batch fill."""
    from mxtpu.gluon import nn
    from mxtpu.serving import BucketSpec, Predictor

    net = nn.HybridSequential(prefix="servebench_")
    with net.name_scope():
        for _ in range(max(1, depth - 1)):
            net.add(nn.Dense(width, activation="relu"))
        net.add(nn.Dense(out_dim))
    net.initialize()
    if dtype != "float32":
        example = np.zeros((1, dim), np.float32)
        net(_as_nd(example))  # settle shapes before the cast
        net.cast(dtype)
    spec = BucketSpec.pow2(max_batch)
    pred = Predictor(net, spec, example=np.zeros((1, dim), np.float32),
                     warmup=True, name="serve_bench")
    return pred, spec


def _as_nd(a):
    import mxtpu as mx
    return mx.nd.array(a)


def build_replica_set(dim=256, width=512, depth=3, out_dim=64, max_batch=8,
                      replicas=2, dtype="float32"):
    """The bench model behind a ReplicaSet: one warmed Predictor per
    device (``replicas=0`` = every visible device)."""
    from mxtpu.gluon import nn
    from mxtpu.serving import BucketSpec, ReplicaSet

    net = nn.HybridSequential(prefix="servebench_")
    with net.name_scope():
        for _ in range(max(1, depth - 1)):
            net.add(nn.Dense(width, activation="relu"))
        net.add(nn.Dense(out_dim))
    net.initialize()
    spec = BucketSpec.pow2(max_batch)
    rset = ReplicaSet(net, spec, n=replicas,
                      example=np.zeros((1, dim), np.float32),
                      warmup=True, name="serve_bench")
    return rset, spec


def _dim(pred):
    return pred.input_templates[0][0][0]


def run_sweep(pred, spec, iters=50, repeats=3, emit=_emit):
    """Items/s per batch bucket, direct Predictor calls (no batcher).
    Each bucket is timed ``repeats`` times and takes its BEST round — a
    single round on a shared host measures scheduler noise, not the
    dispatch+compute cost the monotonicity gate judges. Returns
    (rates, monotonic); monotonic allows a further 5% residual noise."""
    dim = _dim(pred)
    rng = np.random.RandomState(0)
    rates = []
    for b in spec.batch_sizes:
        x = rng.randn(b, dim).astype(np.float32)
        pred.predict(x).asnumpy()  # warm (compiled at warmup; prime caches)
        best_dt = None
        for _ in range(max(1, repeats)):
            t0 = time.perf_counter()
            out = None
            for _ in range(iters):
                out = pred.predict(x)
            out.asnumpy()  # one sync closes the async tail
            dt = time.perf_counter() - t0
            best_dt = dt if best_dt is None else min(best_dt, dt)
        rate = b * iters / best_dt
        rates.append(rate)
        emit({"metric": "serve_sweep_b%d" % b, "value": round(rate, 1),
              "unit": "items/sec",
              "ms_per_batch": round(best_dt / iters * 1e3, 3)})
    monotonic = all(rates[i + 1] >= rates[i] * 0.95
                    for i in range(len(rates) - 1))
    emit({"metric": "serve_sweep", "value": round(rates[-1], 1),
          "unit": "items/sec", "monotonic_non_decreasing": monotonic,
          "rates": [round(r, 1) for r in rates]})
    return rates, monotonic


def run_closed(pred, spec, n_requests=500, workers=4, max_wait_ms=2.0,
               sizes=(1, 2, 3), emit=_emit):
    """Closed-loop mixed-shape run through the MicroBatcher; the
    acceptance record: compiles <= #buckets, zero watchdog trips — and,
    with causal tracing on (MXTPU_TRACE, default 1), the per-request
    latency BREAKDOWN: p99 per stage (queue-wait vs pad vs device vs
    fetch vs deliver) plus the honesty gate that each request's stages
    sum to within 5% of its measured end-to-end latency (median ratio
    error across the run; ``breakdown_ok``)."""
    from mxtpu import telemetry
    from mxtpu.serving import MicroBatcher

    dim = _dim(pred)
    st0 = telemetry.retrace_stats("serving.predict") or {}
    compiles0, trips0 = st0.get("compiles", 0), st0.get("trips", 0)
    shed0 = telemetry.value("serving.shed")  # deltas, like compiles/trips
    bat = MicroBatcher(pred, max_batch_size=spec.max_batch,
                       max_wait_ms=max_wait_ms, max_queue=4096)
    lat, lock = [], threading.Lock()
    items = [0]
    breakdowns = []   # (breakdown dict, e2e_s) per traced request

    def client(k, n):
        rng = np.random.RandomState(100 + k)
        for _ in range(n):
            sz = int(sizes[rng.randint(len(sizes))])
            x = rng.randn(sz, dim).astype(np.float32)
            t0 = time.perf_counter()
            fut = bat.submit(x)
            fut.result(timeout=60)
            dt = time.perf_counter() - t0
            with lock:
                lat.append(dt)
                items[0] += sz
                if fut.breakdown is not None:
                    breakdowns.append((fut.breakdown, fut.e2e_s))
    per = [n_requests // workers] * workers
    per[0] += n_requests - sum(per)
    threads = [threading.Thread(target=client, args=(k, n))
               for k, n in enumerate(per)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    bat.close()
    st = telemetry.retrace_stats("serving.predict") or {}
    lat_ms = np.array(lat) * 1e3
    rec = {"metric": "serve_closed", "value": round(items[0] / wall, 1),
           "unit": "items/sec",
           "req_per_s": round(len(lat) / wall, 1),
           "requests": len(lat), "workers": workers,
           "p50_ms": round(float(np.percentile(lat_ms, 50)), 3),
           "p99_ms": round(float(np.percentile(lat_ms, 99)), 3),
           "compiles": st.get("compiles", 0) - compiles0,
           "buckets": len(spec),
           "watchdog_trips": st.get("trips", 0) - trips0,
           "shed": telemetry.value("serving.shed") - shed0}
    rec.update(_breakdown_summary(breakdowns))
    emit(rec)
    return rec


def _breakdown_summary(breakdowns):
    """p99 per breakdown stage + the sum-vs-e2e honesty gate. Empty dict
    when tracing was off (no breakdowns to judge)."""
    if not breakdowns:
        return {"stage_p99_ms": None, "breakdown_err_median": None,
                "breakdown_ok": None}
    stages = {}
    errs = []
    for bd, e2e in breakdowns:
        for name, v in bd.items():
            stages.setdefault(name, []).append(v)
        if e2e and e2e > 1e-6:
            errs.append(abs(sum(bd.values()) - e2e) / e2e)
    p99 = {name: round(float(np.percentile(np.array(v) * 1e3, 99)), 4)
           for name, v in sorted(stages.items())}
    med = float(np.median(errs)) if errs else None
    return {"stage_p99_ms": p99,
            "breakdown_err_median": round(med, 4) if med is not None
            else None,
            # the ISSUE-10 acceptance bound: a request's returned stages
            # sum to within 5% of its measured end-to-end latency
            "breakdown_ok": (med is not None and med <= 0.05)}


def run_open(pred, spec, qps_list=(100.0, 300.0, 1000.0), n_requests=200,
             deadline_ms=100.0, max_wait_ms=2.0, emit=_emit):
    """Open-loop offered-QPS sweep: paced arrivals, per-request deadline.
    One line per offered rate with shed/expired rates and batch fill."""
    from mxtpu import telemetry
    from mxtpu.serving import MicroBatcher, QueueFull

    dim = _dim(pred)
    recs = []
    for qps in qps_list:
        telemetry.reset_metric("serving.batch_fill")
        # per-request latency comes from the batcher's own enqueue->deliver
        # histogram (client-side "wait on every future after the run" would
        # credit the whole run's tail to the earliest requests)
        telemetry.reset_metric("serving.latency_s")
        bat = MicroBatcher(pred, max_batch_size=spec.max_batch,
                           max_wait_ms=max_wait_ms,
                           max_queue=max(2 * spec.max_batch, 32))
        rng = np.random.RandomState(7)
        futures, shed = [], 0
        interval = 1.0 / float(qps)
        t0 = time.perf_counter()
        for i in range(n_requests):
            target = t0 + i * interval
            now = time.perf_counter()
            if target > now:
                time.sleep(target - now)
            x = rng.randn(1, dim).astype(np.float32)
            try:
                futures.append(bat.submit(x, deadline_ms=deadline_ms))
            except QueueFull:
                shed += 1
        ok, expired = 0, 0
        for fut in futures:
            try:
                fut.result(timeout=30)
                ok += 1
            except Exception:  # noqa: BLE001 — DeadlineExceeded
                expired += 1
        wall = time.perf_counter() - t0
        bat.close()
        snap = telemetry.snapshot()["histograms"]
        fill = snap.get("serving.batch_fill")
        lat = snap.get("serving.latency_s")
        rec = {"metric": "serve_open_qps%g" % qps, "offered_qps": qps,
               "value": round(ok / wall, 1), "unit": "ok_req/sec",
               "shed_rate": round(shed / n_requests, 4),
               "expired_rate": round(expired / n_requests, 4),
               "p50_ms": round(lat["p50"] * 1e3, 3) if lat else None,
               "p99_ms": round(lat["p99"] * 1e3, 3) if lat else None,
               "batch_fill_mean": round(fill["mean"], 4) if fill else None}
        emit(rec)
        recs.append(rec)
    return recs


def run_replicas(rset, spec, n_requests=400, workers=4, max_wait_ms=2.0,
                 kill_frac=0.5, kill_replica=0, result_timeout=60.0,
                 emit=_emit):
    """The kill-one-replica-mid-run sweep (ISSUE 8 acceptance): a
    closed-loop burst through the ReplicaDispatcher; at ``kill_frac`` of
    the run, ``kill_replica`` is quarantined with an hour-long backoff —
    a dead chip, as far as this run is concerned. Emits per-replica
    dispatch counts and a hang count (futures that never completed
    within ``result_timeout``): the gate is hangs == 0 — every request
    re-routes, sheds, or expires, none strand."""
    from mxtpu import telemetry
    from mxtpu.serving import DeadlineExceeded, QueueFull
    from mxtpu.serving.replicas import ReplicaDispatcher

    n_rep = len(rset.replicas)
    disp0 = dict(telemetry.tagged("serving.replica.dispatches"))
    bat = ReplicaDispatcher(rset, max_batch_size=spec.max_batch,
                            max_wait_ms=max_wait_ms, max_queue=4096)
    dim = rset.input_templates[0][0][0]
    lock = threading.Lock()
    stats = {"completed": 0, "items": 0, "shed": 0, "expired": 0,
             "errors": 0, "hangs": 0, "submitted": 0}
    kill_at = max(1, int(n_requests * kill_frac))

    def client(k, n):
        rng = np.random.RandomState(300 + k)
        for _ in range(n):
            with lock:
                stats["submitted"] += 1
                fire_kill = stats["submitted"] == kill_at
            if fire_kill and n_rep > 1:
                bat.quarantine_replica(kill_replica, backoff_s=3600.0)
            sz = int(rng.randint(1, max(2, spec.max_batch // 2)))
            x = rng.randn(sz, dim).astype(np.float32)
            try:
                fut = bat.submit(x, deadline_ms=result_timeout * 1e3)
            except QueueFull:
                with lock:
                    stats["shed"] += 1
                continue
            try:
                fut.result(timeout=result_timeout)
            except DeadlineExceeded:
                with lock:
                    # a future that timed out WITHOUT completing is a
                    # hang — the exact failure this subsystem exists to
                    # prevent; a completed-with-expiry is bounded behavior
                    stats["hangs" if not fut.done() else "expired"] += 1
            except Exception:  # noqa: BLE001 — shed-at-dispatch etc.
                with lock:
                    stats["errors" if fut.done() and not isinstance(
                        fut._error, QueueFull) else "shed"] += 1
            else:
                with lock:
                    stats["completed"] += 1
                    stats["items"] += sz

    per = [n_requests // workers] * workers
    per[0] += n_requests - sum(per)
    threads = [threading.Thread(target=client, args=(k, n))
               for k, n in enumerate(per)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join(result_timeout + 60)
    wall = time.perf_counter() - t0
    bat.close(timeout=10)
    per_rep = {}
    for tag, v in telemetry.tagged("serving.replica.dispatches").items():
        d = v - disp0.get(tag, 0)
        if d:
            per_rep[tag] = d
    rec = {"metric": "serve_replicas", "replicas": n_rep,
           "value": round(stats["items"] / wall, 1), "unit": "items/sec",
           "requests": n_requests,
           "killed_replica": kill_replica if n_rep > 1 else None,
           "killed_at_request": kill_at if n_rep > 1 else None,
           "hangs": stats["hangs"], "errors": stats["errors"],
           "completed": stats["completed"], "shed": stats["shed"],
           "expired": stats["expired"],
           "per_replica_dispatches": per_rep,
           "wedges": telemetry.value("serving.replica.wedges"),
           "final_states": [s["state"] for s in bat.replica_states()]}
    emit(rec)
    return rec


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--mode", default="sweep,closed,open")
    ap.add_argument("--requests", type=int,
                    default=int(os.environ.get("BENCH_SERVE_REQUESTS", 500)))
    ap.add_argument("--max-batch", type=int,
                    default=int(os.environ.get("BENCH_SERVE_MAX_BATCH", 8)))
    ap.add_argument("--dim", type=int, default=256)
    ap.add_argument("--width", type=int, default=512)
    ap.add_argument("--depth", type=int, default=3)
    ap.add_argument("--workers", type=int, default=4)
    ap.add_argument("--max-wait-ms", type=float, default=2.0)
    ap.add_argument("--qps", default="100,300,1000")
    ap.add_argument("--deadline-ms", type=float, default=100.0)
    ap.add_argument("--sweep-iters", type=int, default=50)
    ap.add_argument("--replicas", type=int, default=1,
                    help="replica count for --mode replicas (0 = one per "
                         "visible device)")
    ap.add_argument("--kill-replica", type=int, default=0,
                    help="replica quarantined mid-run by --mode replicas "
                         "(-1 = no kill)")
    args = ap.parse_args(argv)

    modes = {m.strip() for m in args.mode.split(",") if m.strip()}
    ok = True
    single = modes - {"replicas"}
    if single:
        pred, spec = build_predictor(dim=args.dim, width=args.width,
                                     depth=args.depth,
                                     max_batch=args.max_batch)
        _emit({"metric": "serve_warmup", "buckets": len(spec),
               "value": len(spec), "unit": "compiled_buckets"})
        if "sweep" in modes:
            _, monotonic = run_sweep(pred, spec, iters=args.sweep_iters)
            ok = ok and monotonic
        if "closed" in modes:
            rec = run_closed(pred, spec, n_requests=args.requests,
                             workers=args.workers,
                             max_wait_ms=args.max_wait_ms)
            ok = ok and rec["compiles"] <= rec["buckets"] \
                and rec["watchdog_trips"] == 0
            if rec["breakdown_ok"] is not None:
                ok = ok and rec["breakdown_ok"]
        if "open" in modes:
            run_open(pred, spec,
                     qps_list=[float(q) for q in args.qps.split(",") if q],
                     n_requests=args.requests, deadline_ms=args.deadline_ms,
                     max_wait_ms=args.max_wait_ms)
    if "replicas" in modes:
        import jax
        n = args.replicas or len(jax.devices())
        if n > len(jax.devices()):
            _emit({"metric": "serve_replicas", "error":
                   "%d replicas > %d devices" % (n, len(jax.devices()))})
            return 1
        if args.kill_replica >= n:
            # an out-of-range kill would IndexError inside a client
            # thread and let the gate pass on a truncated run
            _emit({"metric": "serve_replicas", "error":
                   "--kill-replica %d out of range for %d replicas"
                   % (args.kill_replica, n)})
            return 1
        rset, spec = build_replica_set(dim=args.dim, width=args.width,
                                       depth=args.depth,
                                       max_batch=args.max_batch, replicas=n)
        _emit({"metric": "serve_replicas_warmup", "replicas": n,
               "value": n * len(spec), "unit": "compiled_buckets"})
        rec = run_replicas(rset, spec, n_requests=args.requests,
                           workers=args.workers,
                           max_wait_ms=args.max_wait_ms,
                           kill_replica=args.kill_replica,
                           kill_frac=0.5 if args.kill_replica >= 0
                           else 2.0)  # >1.0 frac: the kill never fires
        ok = ok and rec["hangs"] == 0 and rec["errors"] == 0
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
