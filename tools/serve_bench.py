#!/usr/bin/env python
"""Load generator for the serving subsystem (mxtpu/serving) — in-process.

Three phases against one AOT-warmed Predictor on the bench MLP, one JSON
line each (stamped with platform + policy_key like every bench artifact):

* ``sweep``  — direct Predictor batch-size sweep, items/s per bucket.
  The acceptance criterion rides this line: throughput must be
  monotonically non-decreasing from batch 1 to the max bucket (batching
  exists to fill the MXU; a bucket that serves SLOWER per item than a
  smaller one should simply not be declared).
* ``closed`` — closed-loop: N workers submit mixed-size requests
  back-to-back through the MicroBatcher (offered load == capacity).
  Reports items/s, req/s, client p50/p99, the compile count at retrace
  site ``serving.predict`` (must stay <= #buckets) and watchdog trips
  (must stay 0).
* ``open``   — open-loop: paced arrivals at each offered QPS with a
  per-request deadline. Reports achieved QPS, shed rate, deadline-expiry
  rate, p50/p99, and mean batch fill — the overload-behaviour curve
  (shed rate should rise and p99 should stay bounded once offered QPS
  exceeds capacity; an unbounded p99 means admission control is broken).
* ``replicas`` — ISSUE 8: closed-loop through a ReplicaSet router
  (``--replicas N``, 0 = one per device) with a kill-one-replica-mid-run
  sweep: halfway through, replica 0 is quarantined as if its chip died.
  Reports per-replica dispatch counts, throughput, shed/expired counts,
  and a **hang count** — futures that never completed. The acceptance
  gate: hangs == 0 through the replica loss (requests re-route, shed, or
  expire; none strand).
* ``decode`` — ISSUE 11: the continuous-batching autoregressive decode
  engine (``mxtpu/serving/decode.py``) on a tiny causal-attention LM.
  Phase 1 is the acceptance A/B: continuous batching vs restart-per-
  batch at EQUAL cohort capacity, identical workload and executables —
  gates: strictly higher tokens/s, zero post-warmup compiles at
  ``serving.decode``, zero d2h inside the armed decode span, int8
  logits-parity vs f32 with the accountant reporting at most ~half the
  KV bytes per slot. Phase 2 is the open-loop overload curve: paced
  submits, tokens/s + time-to-first-token p50/p99 per offered QPS, with
  the PR-10 per-stage breakdown splitting prefill from decode time.

Usage::

    python tools/serve_bench.py [--mode sweep,closed,open,replicas,decode]
        [--requests 500] [--max-batch 8] [--dim 256] [--width 512]
        [--depth 3] [--max-wait-ms 2] [--workers 4]
        [--qps 100,300,1000] [--deadline-ms 100]
        [--replicas 0] [--kill-replica 0]
        [--decode-requests 80] [--decode-slots 8] [--decode-max-new 32]
        [--decode-qps 20,60,200]

``bench.py``'s ``serving`` config drives the same functions in-process,
and ``tools/perf_battery.sh`` runs this script as its serving phase.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _stamp(rec):
    """Platform + active policy levers on every line (bench.py contract:
    a CPU-fallback artifact must be distinguishable from a chip run)."""
    try:
        import jax
        rec.setdefault("platform", jax.devices()[0].platform)
    except Exception:  # noqa: BLE001
        rec.setdefault("platform", "unknown")
    try:
        from mxtpu.ops.registry import policy_key
        rec.setdefault("policy_key", list(policy_key()))
    except Exception:  # noqa: BLE001
        rec.setdefault("policy_key", None)
    return rec


def _emit(rec):
    print(json.dumps(_stamp(rec)), flush=True)


def build_predictor(dim=256, width=512, depth=3, out_dim=64, max_batch=8,
                    dtype="float32"):
    """The bench model: a depth-layer MLP — small enough that dispatch
    overhead is visible (the regime micro-batching exists for), wide
    enough that per-item math grows with batch fill."""
    from mxtpu.gluon import nn
    from mxtpu.serving import BucketSpec, Predictor

    net = nn.HybridSequential(prefix="servebench_")
    with net.name_scope():
        for _ in range(max(1, depth - 1)):
            net.add(nn.Dense(width, activation="relu"))
        net.add(nn.Dense(out_dim))
    net.initialize()
    if dtype != "float32":
        example = np.zeros((1, dim), np.float32)
        net(_as_nd(example))  # settle shapes before the cast
        net.cast(dtype)
    spec = BucketSpec.pow2(max_batch)
    pred = Predictor(net, spec, example=np.zeros((1, dim), np.float32),
                     warmup=True, name="serve_bench")
    return pred, spec


def _as_nd(a):
    import mxtpu as mx
    return mx.nd.array(a)


def build_replica_set(dim=256, width=512, depth=3, out_dim=64, max_batch=8,
                      replicas=2, dtype="float32"):
    """The bench model behind a ReplicaSet: one warmed Predictor per
    device (``replicas=0`` = every visible device)."""
    from mxtpu.gluon import nn
    from mxtpu.serving import BucketSpec, ReplicaSet

    net = nn.HybridSequential(prefix="servebench_")
    with net.name_scope():
        for _ in range(max(1, depth - 1)):
            net.add(nn.Dense(width, activation="relu"))
        net.add(nn.Dense(out_dim))
    net.initialize()
    spec = BucketSpec.pow2(max_batch)
    rset = ReplicaSet(net, spec, n=replicas,
                      example=np.zeros((1, dim), np.float32),
                      warmup=True, name="serve_bench")
    return rset, spec


def _dim(pred):
    return pred.input_templates[0][0][0]


def build_decode_model(vocab=96, dim=32, max_len=96, seed=0):
    """The decode-bench model: a single-head causal-attention LM — the
    executable reference for the :class:`mxtpu.serving.decode.DecodeModel`
    contract. Prefill (``hybrid_forward``) returns ``(logits[b, s, V],
    k[b, s, d], v[b, s, d])``; ``decode_step`` writes this token's k/v at
    ``pos`` into its OWN attention view and returns the entries for the
    engine to persist. Small enough that the per-step dispatch overhead
    dominates — exactly the regime continuous batching exists for."""
    import mxtpu as mx
    from mxtpu.gluon import HybridBlock
    from mxtpu.ndarray import NDArray
    from mxtpu.serving.decode import DecodeModel

    class TinyCausalLM(HybridBlock, DecodeModel):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.embed = self.params.get("embed", shape=(vocab, dim))
                self.posemb = self.params.get("posemb",
                                              shape=(max_len, dim))
                self.wq = self.params.get("wq", shape=(dim, dim))
                self.wk = self.params.get("wk", shape=(dim, dim))
                self.wv = self.params.get("wv", shape=(dim, dim))
                self.wo = self.params.get("wo", shape=(dim, dim))
                self.wout = self.params.get("wout", shape=(dim, vocab))

        def hybrid_forward(self, F, tokens, embed, posemb, wq, wk, wv,
                           wo, wout):
            import jax
            import jax.numpy as jnp
            t = tokens._data.astype(jnp.int32)
            s = t.shape[1]
            x = embed._data[t] + posemb._data[:s][None]
            q = x @ wq._data
            k = x @ wk._data
            v = x @ wv._data
            scores = jnp.einsum("bsd,btd->bst", q, k) / float(dim) ** 0.5
            mask = jnp.tril(jnp.ones((s, s), jnp.bool_))
            scores = jnp.where(mask[None], scores, -1e30)
            h = jnp.einsum("bst,btd->bsd",
                           jax.nn.softmax(scores, axis=-1), v) @ wo._data
            logits = (x + h) @ wout._data
            return NDArray(logits), NDArray(k), NDArray(v)

        def decode_step(self, kv, tok, pos):
            import jax
            import jax.numpy as jnp
            k_cache, v_cache = kv                       # [c, L, d]
            c, L = k_cache.shape[0], k_cache.shape[1]
            x = self.embed.data()._data[tok] \
                + self.posemb.data()._data[pos]         # [c, d]
            q = x @ self.wq.data()._data
            k_new = x @ self.wk.data()._data
            v_new = x @ self.wv.data()._data
            idx = jnp.arange(c)
            kf = k_cache.at[idx, pos].set(k_new)
            vf = v_cache.at[idx, pos].set(v_new)
            scores = jnp.einsum("cd,cld->cl", q, kf) / float(dim) ** 0.5
            mask = jnp.arange(L)[None, :] <= pos[:, None]
            scores = jnp.where(mask, scores, -1e30)
            h = jnp.einsum("cl,cld->cd",
                           jax.nn.softmax(scores, axis=-1), vf) \
                @ self.wo.data()._data
            logits = (x + h) @ self.wout.data()._data
            return logits, [k_new, v_new]

    net = TinyCausalLM(prefix="decodebench_")
    # seeded init: the int8 logits-parity numbers must be a property of
    # the quantization path, not of this run's weight draw
    mx.random.seed(seed)
    net.initialize(mx.init.Normal(0.5))
    return net


def build_decode_engine(model, slots=4, max_prompt=24, max_new=24,
                        int8=False, continuous=True, accountant=None,
                        start=False, clock=time.monotonic):
    """A warmed DecodeEngine over the bench LM: prefill seq buckets up to
    ``max_prompt``, a pow2 cohort-capacity ladder up to ``slots``, cache
    length sized for the longest prompt + generation budget."""
    from mxtpu.serving import BucketSpec, DecodeEngine

    pspec = BucketSpec([1], seq_lens=[max(4, max_prompt // 2), max_prompt])
    dspec = BucketSpec.pow2(decode_slots=slots)
    return DecodeEngine(model, pspec, dspec, max_len=max_prompt + max_new,
                        int8=int8, continuous=continuous,
                        accountant=accountant, warmup=True, start=start,
                        clock=clock)


def _decode_workload(n_requests, vocab, max_prompt, max_new, seed=11):
    """(prompt, max_new) pairs with VARIED lengths — the regime where
    continuous batching wins: a restart-per-batch cohort burns steps on
    slots whose sequence already finished, a continuous cohort refills
    them between steps."""
    rng = np.random.RandomState(seed)
    reqs = []
    for _ in range(n_requests):
        prompt = rng.randint(0, vocab,
                             size=rng.randint(3, max_prompt)).astype(np.int32)
        # the full 2..max_new spread: restart-per-batch pays max(cohort)
        # steps per cohort, continuous pays ~mean — the wider the spread,
        # the bigger the idle-slot bill the gate measures
        reqs.append((prompt, int(rng.randint(2, max_new + 1))))
    return reqs


def run_decode(n_requests=80, slots=8, max_new=32, vocab=256, dim=128,
               max_prompt=48, emit=_emit):
    """The ISSUE-11 acceptance phase: continuous batching vs
    restart-per-batch decode at EQUAL cohort capacity, identical
    workload, identical executables. Gates (summary line ``ok``):
    strictly higher tokens/s continuous, ZERO post-warmup compiles at
    ``serving.decode`` (<= #cohort-buckets by construction —
    watchdog-pinned), zero d2h inside the armed decode span, and the
    int8 path passing logits parity vs f32 while the accountant reports
    about half (or less) the KV bytes per sequence."""
    from mxtpu import telemetry
    from mxtpu.serving import KVCacheAccountant

    model = build_decode_model(vocab=vocab, dim=dim,
                               max_len=max_prompt + max_new)
    reqs = _decode_workload(n_requests, vocab, max_prompt, max_new)

    def drive(continuous, int8=False, rounds=2):
        # ledger KV bytes but never shed: the closed-loop burst queues the
        # whole workload up front by design (the kv_residency shed path
        # has its own default-overcommit coverage in tests/test_decode.py)
        acct = KVCacheAccountant(overcommit=float(n_requests))
        eng = build_decode_engine(model, slots=slots, max_prompt=max_prompt,
                                  max_new=max_new, int8=int8,
                                  continuous=continuous, accountant=acct)
        st0 = telemetry.retrace_stats(eng._site) or {}
        steps0 = telemetry.value("serving.decode.steps")
        toks0 = telemetry.value("serving.decode.tokens")
        d2h0 = telemetry.value("serving.decode.d2h")
        best = None
        # best-of-rounds, like run_sweep: one round on a shared host
        # measures scheduler noise, not the replay cost the gate judges
        # (step counts are identical per round; the compile/d2h deltas
        # below span ALL rounds, so a lazy compile can't hide)
        for _ in range(max(1, rounds)):
            r_steps0 = telemetry.value("serving.decode.steps")
            r_toks0 = telemetry.value("serving.decode.tokens")
            t0 = time.perf_counter()
            futs = [eng.submit(p, max_new=m) for p, m in reqs]
            guard = 0
            while not all(f.done() for f in futs) and guard < 100000:
                eng.poll()
                guard += 1
            wall = time.perf_counter() - t0
            outs = [f.result(timeout=5) for f in futs]
            round_rec = {
                "tokens": telemetry.value("serving.decode.tokens")
                - r_toks0,
                "steps": telemetry.value("serving.decode.steps") - r_steps0,
                "wall_s": wall,
                "tok_per_s": (telemetry.value("serving.decode.tokens")
                              - r_toks0) / wall,
                "ttft_p50_ms": round(float(np.percentile(
                    [f.ttft_s for f in futs], 50)) * 1e3, 3),
                "ttft_p99_ms": round(float(np.percentile(
                    [f.ttft_s for f in futs], 99)) * 1e3, 3),
            }
            if best is None or round_rec["tok_per_s"] > best["tok_per_s"]:
                best = round_rec
        st = telemetry.retrace_stats(eng._site) or {}
        best.update({
            "compiles_post_warmup": st.get("compiles", 0)
            - st0.get("compiles", 0),
            "watchdog_trips": st.get("trips", 0) - st0.get("trips", 0),
            "per_slot_kv_bytes": eng.per_slot_kv_bytes(),
            "total_steps": telemetry.value("serving.decode.steps") - steps0,
            "total_tokens": telemetry.value("serving.decode.tokens")
            - toks0,
            # delta like every sibling gate: a cumulative read would fail
            # forever after any earlier in-process sync
            "d2h": telemetry.value("serving.decode.d2h") - d2h0,
        })
        eng.close(timeout=5)
        return best, outs, eng

    cont, cont_outs, _ = drive(True)
    emit({"metric": "serve_decode_continuous",
          "value": round(cont["tok_per_s"], 1), "unit": "tokens/sec",
          **{k: cont[k] for k in ("tokens", "steps", "ttft_p50_ms",
                                  "ttft_p99_ms", "compiles_post_warmup",
                                  "watchdog_trips")}})
    rest, rest_outs, _ = drive(False)
    emit({"metric": "serve_decode_restart",
          "value": round(rest["tok_per_s"], 1), "unit": "tokens/sec",
          **{k: rest[k] for k in ("tokens", "steps", "ttft_p50_ms",
                                  "ttft_p99_ms", "compiles_post_warmup",
                                  "watchdog_trips")}})
    parity_tokens = all(len(a) == len(b) and (a == b).all()
                        for a, b in zip(cont_outs, rest_outs))

    # int8 phase on the SAME weights: throughput line + the logits-parity
    # and KV-bytes gates (probes run on fresh single-purpose engines —
    # the throughput engines are closed)
    q, _q_outs, _ = drive(True, int8=True)
    probe = reqs[0][0]
    eng_f = build_decode_engine(model, slots=2, max_prompt=max_prompt,
                                max_new=max_new)
    eng_q = build_decode_engine(model, slots=2, max_prompt=max_prompt,
                                max_new=max_new, int8=True)
    lf, lq = eng_f.prefill_logits(probe), eng_q.prefill_logits(probe)
    sf, sq = eng_f.step_logits_probe(probe), eng_q.step_logits_probe(probe)
    prefill_err = float(np.abs(lf - lq).mean() / (np.abs(lf).mean() + 1e-9))
    step_err = float(np.abs(sf - sq).mean() / (np.abs(sf).mean() + 1e-9))
    kv_ratio = q["per_slot_kv_bytes"] / float(cont["per_slot_kv_bytes"])
    eng_f.close(timeout=2)
    eng_q.close(timeout=2)
    int8_ok = prefill_err <= 0.05 and step_err <= 0.05 and kv_ratio <= 0.55
    emit({"metric": "serve_decode_int8",
          "value": round(q["tok_per_s"], 1), "unit": "tokens/sec",
          "prefill_logits_rel_err": round(prefill_err, 5),
          "step_logits_rel_err": round(step_err, 5),
          "kv_bytes_per_slot_f32": cont["per_slot_kv_bytes"],
          "kv_bytes_per_slot_int8": q["per_slot_kv_bytes"],
          "kv_bytes_ratio": round(kv_ratio, 4),
          # the residency dividend: sequences admissible at equal memory
          "admit_multiplier": round(1.0 / kv_ratio, 2),
          "int8_ok": int8_ok})

    speedup = cont["tok_per_s"] / rest["tok_per_s"] \
        if rest["tok_per_s"] > 0 else 0.0
    ok = (cont["tok_per_s"] > rest["tok_per_s"]
          and parity_tokens
          and cont["compiles_post_warmup"] == 0
          and cont["watchdog_trips"] == 0
          and cont["d2h"] == 0 and rest["d2h"] == 0 and q["d2h"] == 0
          and int8_ok)
    emit({"metric": "serve_decode", "value": round(speedup, 3),
          "unit": "continuous_vs_restart_speedup",
          "continuous_tok_per_s": round(cont["tok_per_s"], 1),
          "restart_tok_per_s": round(rest["tok_per_s"], 1),
          "continuous_steps": cont["steps"],
          "restart_steps": rest["steps"],
          "token_parity_continuous_vs_restart": parity_tokens,
          "compiles_post_warmup": cont["compiles_post_warmup"],
          "decode_d2h": cont["d2h"] + rest["d2h"] + q["d2h"],
          "ok": ok})
    return {"ok": ok, "speedup": speedup, "continuous": cont,
            "restart": rest, "int8": q, "prefill_logits_rel_err": prefill_err,
            "step_logits_rel_err": step_err, "kv_bytes_ratio": kv_ratio}


def run_decode_open(qps_list=(20.0, 60.0, 200.0), n_requests=60, slots=4,
                    max_new=16, vocab=96, dim=32, max_prompt=24,
                    deadline_ms=2000.0, emit=_emit):
    """Open-loop decode overload curve: paced submits against a THREADED
    engine, one line per offered rate — achieved tokens/s,
    time-to-first-token p50/p99, shed rate, and the per-stage split the
    PR-10 breakdown makes possible: prefill vs decode milliseconds per
    request (p50), so a TTFT regression is attributable to the right
    phase from the artifact alone."""
    from mxtpu import telemetry
    from mxtpu.serving import QueueFull

    model = build_decode_model(vocab=vocab, dim=dim,
                               max_len=max_prompt + max_new)
    reqs = _decode_workload(n_requests, vocab, max_prompt, max_new, seed=23)
    recs = []
    for qps in qps_list:
        eng = build_decode_engine(model, slots=slots, max_prompt=max_prompt,
                                  max_new=max_new, start=True)
        interval = 1.0 / float(qps)
        futs, shed = [], 0
        t0 = time.perf_counter()
        for i, (p, m) in enumerate(reqs):
            target = t0 + i * interval
            now = time.perf_counter()
            if target > now:
                time.sleep(target - now)
            try:
                futs.append(eng.submit(p, max_new=m,
                                       deadline_ms=deadline_ms))
            except QueueFull:
                shed += 1
        done, expired = [], 0
        for f in futs:
            try:
                toks = f.result(timeout=30)
                done.append((f, len(toks)))
            except Exception:  # noqa: BLE001 — DeadlineExceeded
                expired += 1
        wall = time.perf_counter() - t0
        eng.close(timeout=10)
        ttfts = [f.ttft_s for f, _n in done if f.ttft_s is not None]
        stage = {"serving.prefill": [], "serving.decode": []}
        for f, _n in done:
            if f.breakdown:
                for name in stage:
                    if name in f.breakdown:
                        stage[name].append(f.breakdown[name])
        rec = {"metric": "serve_decode_qps%g" % qps, "offered_qps": qps,
               "value": round(sum(n for _f, n in done) / wall, 1),
               "unit": "tokens/sec",
               "completed": len(done),
               "shed_rate": round(shed / float(n_requests), 4),
               "expired_rate": round(expired / float(n_requests), 4),
               "ttft_p50_ms": round(float(np.percentile(ttfts, 50)) * 1e3,
                                    3) if ttfts else None,
               "ttft_p99_ms": round(float(np.percentile(ttfts, 99)) * 1e3,
                                    3) if ttfts else None,
               "prefill_p50_ms": round(float(np.percentile(
                   stage["serving.prefill"], 50)) * 1e3, 3)
               if stage["serving.prefill"] else None,
               "decode_p50_ms": round(float(np.percentile(
                   stage["serving.decode"], 50)) * 1e3, 3)
               if stage["serving.decode"] else None}
        emit(rec)
        recs.append(rec)
    return recs


def run_sweep(pred, spec, iters=50, repeats=3, emit=_emit):
    """Items/s per batch bucket, direct Predictor calls (no batcher).
    Each bucket is timed ``repeats`` times and takes its BEST round — a
    single round on a shared host measures scheduler noise, not the
    dispatch+compute cost the monotonicity gate judges. Returns
    (rates, monotonic); monotonic allows a further 5% residual noise."""
    dim = _dim(pred)
    rng = np.random.RandomState(0)
    rates = []
    for b in spec.batch_sizes:
        x = rng.randn(b, dim).astype(np.float32)
        pred.predict(x).asnumpy()  # warm (compiled at warmup; prime caches)
        best_dt = None
        for _ in range(max(1, repeats)):
            t0 = time.perf_counter()
            out = None
            for _ in range(iters):
                out = pred.predict(x)
            out.asnumpy()  # one sync closes the async tail
            dt = time.perf_counter() - t0
            best_dt = dt if best_dt is None else min(best_dt, dt)
        rate = b * iters / best_dt
        rates.append(rate)
        emit({"metric": "serve_sweep_b%d" % b, "value": round(rate, 1),
              "unit": "items/sec",
              "ms_per_batch": round(best_dt / iters * 1e3, 3)})
    monotonic = all(rates[i + 1] >= rates[i] * 0.95
                    for i in range(len(rates) - 1))
    emit({"metric": "serve_sweep", "value": round(rates[-1], 1),
          "unit": "items/sec", "monotonic_non_decreasing": monotonic,
          "rates": [round(r, 1) for r in rates]})
    return rates, monotonic


def run_closed(pred, spec, n_requests=500, workers=4, max_wait_ms=2.0,
               sizes=(1, 2, 3), emit=_emit):
    """Closed-loop mixed-shape run through the MicroBatcher; the
    acceptance record: compiles <= #buckets, zero watchdog trips — and,
    with causal tracing on (MXTPU_TRACE, default 1), the per-request
    latency BREAKDOWN: p99 per stage (queue-wait vs pad vs device vs
    fetch vs deliver) plus the honesty gate that each request's stages
    sum to within 5% of its measured end-to-end latency (median ratio
    error across the run; ``breakdown_ok``)."""
    from mxtpu import telemetry
    from mxtpu.serving import MicroBatcher

    dim = _dim(pred)
    st0 = telemetry.retrace_stats("serving.predict") or {}
    compiles0, trips0 = st0.get("compiles", 0), st0.get("trips", 0)
    shed0 = telemetry.value("serving.shed")  # deltas, like compiles/trips
    bat = MicroBatcher(pred, max_batch_size=spec.max_batch,
                       max_wait_ms=max_wait_ms, max_queue=4096)
    lat, lock = [], threading.Lock()
    items = [0]
    breakdowns = []   # (breakdown dict, e2e_s) per traced request

    def client(k, n):
        rng = np.random.RandomState(100 + k)
        for _ in range(n):
            sz = int(sizes[rng.randint(len(sizes))])
            x = rng.randn(sz, dim).astype(np.float32)
            t0 = time.perf_counter()
            fut = bat.submit(x)
            fut.result(timeout=60)
            dt = time.perf_counter() - t0
            with lock:
                lat.append(dt)
                items[0] += sz
                if fut.breakdown is not None:
                    breakdowns.append((fut.breakdown, fut.e2e_s))
    per = [n_requests // workers] * workers
    per[0] += n_requests - sum(per)
    threads = [threading.Thread(target=client, args=(k, n))
               for k, n in enumerate(per)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    bat.close()
    st = telemetry.retrace_stats("serving.predict") or {}
    lat_ms = np.array(lat) * 1e3
    rec = {"metric": "serve_closed", "value": round(items[0] / wall, 1),
           "unit": "items/sec",
           "req_per_s": round(len(lat) / wall, 1),
           "requests": len(lat), "workers": workers,
           "p50_ms": round(float(np.percentile(lat_ms, 50)), 3),
           "p99_ms": round(float(np.percentile(lat_ms, 99)), 3),
           "compiles": st.get("compiles", 0) - compiles0,
           "buckets": len(spec),
           "watchdog_trips": st.get("trips", 0) - trips0,
           "shed": telemetry.value("serving.shed") - shed0}
    rec.update(_breakdown_summary(breakdowns))
    emit(rec)
    return rec


def _breakdown_summary(breakdowns):
    """p99 per breakdown stage + the sum-vs-e2e honesty gate. Empty dict
    when tracing was off (no breakdowns to judge)."""
    if not breakdowns:
        return {"stage_p99_ms": None, "breakdown_err_median": None,
                "breakdown_ok": None}
    stages = {}
    errs = []
    for bd, e2e in breakdowns:
        for name, v in bd.items():
            stages.setdefault(name, []).append(v)
        if e2e and e2e > 1e-6:
            errs.append(abs(sum(bd.values()) - e2e) / e2e)
    p99 = {name: round(float(np.percentile(np.array(v) * 1e3, 99)), 4)
           for name, v in sorted(stages.items())}
    med = float(np.median(errs)) if errs else None
    return {"stage_p99_ms": p99,
            "breakdown_err_median": round(med, 4) if med is not None
            else None,
            # the ISSUE-10 acceptance bound: a request's returned stages
            # sum to within 5% of its measured end-to-end latency
            "breakdown_ok": (med is not None and med <= 0.05)}


def run_open(pred, spec, qps_list=(100.0, 300.0, 1000.0), n_requests=200,
             deadline_ms=100.0, max_wait_ms=2.0, emit=_emit):
    """Open-loop offered-QPS sweep: paced arrivals, per-request deadline.
    One line per offered rate with shed/expired rates and batch fill."""
    from mxtpu import telemetry
    from mxtpu.serving import MicroBatcher, QueueFull

    dim = _dim(pred)
    recs = []
    for qps in qps_list:
        telemetry.reset_metric("serving.batch_fill")
        # per-request latency comes from the batcher's own enqueue->deliver
        # histogram (client-side "wait on every future after the run" would
        # credit the whole run's tail to the earliest requests)
        telemetry.reset_metric("serving.latency_s")
        bat = MicroBatcher(pred, max_batch_size=spec.max_batch,
                           max_wait_ms=max_wait_ms,
                           max_queue=max(2 * spec.max_batch, 32))
        rng = np.random.RandomState(7)
        futures, shed = [], 0
        interval = 1.0 / float(qps)
        t0 = time.perf_counter()
        for i in range(n_requests):
            target = t0 + i * interval
            now = time.perf_counter()
            if target > now:
                time.sleep(target - now)
            x = rng.randn(1, dim).astype(np.float32)
            try:
                futures.append(bat.submit(x, deadline_ms=deadline_ms))
            except QueueFull:
                shed += 1
        ok, expired = 0, 0
        for fut in futures:
            try:
                fut.result(timeout=30)
                ok += 1
            except Exception:  # noqa: BLE001 — DeadlineExceeded
                expired += 1
        wall = time.perf_counter() - t0
        bat.close()
        snap = telemetry.snapshot()["histograms"]
        fill = snap.get("serving.batch_fill")
        lat = snap.get("serving.latency_s")
        rec = {"metric": "serve_open_qps%g" % qps, "offered_qps": qps,
               "value": round(ok / wall, 1), "unit": "ok_req/sec",
               "shed_rate": round(shed / n_requests, 4),
               "expired_rate": round(expired / n_requests, 4),
               "p50_ms": round(lat["p50"] * 1e3, 3) if lat else None,
               "p99_ms": round(lat["p99"] * 1e3, 3) if lat else None,
               "batch_fill_mean": round(fill["mean"], 4) if fill else None}
        emit(rec)
        recs.append(rec)
    return recs


def run_replicas(rset, spec, n_requests=400, workers=4, max_wait_ms=2.0,
                 kill_frac=0.5, kill_replica=0, result_timeout=60.0,
                 emit=_emit):
    """The kill-one-replica-mid-run sweep (ISSUE 8 acceptance): a
    closed-loop burst through the ReplicaDispatcher; at ``kill_frac`` of
    the run, ``kill_replica`` is quarantined with an hour-long backoff —
    a dead chip, as far as this run is concerned. Emits per-replica
    dispatch counts and a hang count (futures that never completed
    within ``result_timeout``): the gate is hangs == 0 — every request
    re-routes, sheds, or expires, none strand."""
    from mxtpu import telemetry
    from mxtpu.serving import DeadlineExceeded, QueueFull
    from mxtpu.serving.replicas import ReplicaDispatcher

    n_rep = len(rset.replicas)
    disp0 = dict(telemetry.tagged("serving.replica.dispatches"))
    bat = ReplicaDispatcher(rset, max_batch_size=spec.max_batch,
                            max_wait_ms=max_wait_ms, max_queue=4096)
    dim = rset.input_templates[0][0][0]
    lock = threading.Lock()
    stats = {"completed": 0, "items": 0, "shed": 0, "expired": 0,
             "errors": 0, "hangs": 0, "submitted": 0}
    kill_at = max(1, int(n_requests * kill_frac))

    def client(k, n):
        rng = np.random.RandomState(300 + k)
        for _ in range(n):
            with lock:
                stats["submitted"] += 1
                fire_kill = stats["submitted"] == kill_at
            if fire_kill and n_rep > 1:
                bat.quarantine_replica(kill_replica, backoff_s=3600.0)
            sz = int(rng.randint(1, max(2, spec.max_batch // 2)))
            x = rng.randn(sz, dim).astype(np.float32)
            try:
                fut = bat.submit(x, deadline_ms=result_timeout * 1e3)
            except QueueFull:
                with lock:
                    stats["shed"] += 1
                continue
            try:
                fut.result(timeout=result_timeout)
            except DeadlineExceeded:
                with lock:
                    # a future that timed out WITHOUT completing is a
                    # hang — the exact failure this subsystem exists to
                    # prevent; a completed-with-expiry is bounded behavior
                    stats["hangs" if not fut.done() else "expired"] += 1
            except Exception:  # noqa: BLE001 — shed-at-dispatch etc.
                with lock:
                    stats["errors" if fut.done() and not isinstance(
                        fut._error, QueueFull) else "shed"] += 1
            else:
                with lock:
                    stats["completed"] += 1
                    stats["items"] += sz

    per = [n_requests // workers] * workers
    per[0] += n_requests - sum(per)
    threads = [threading.Thread(target=client, args=(k, n))
               for k, n in enumerate(per)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join(result_timeout + 60)
    wall = time.perf_counter() - t0
    bat.close(timeout=10)
    per_rep = {}
    for tag, v in telemetry.tagged("serving.replica.dispatches").items():
        d = v - disp0.get(tag, 0)
        if d:
            per_rep[tag] = d
    rec = {"metric": "serve_replicas", "replicas": n_rep,
           "value": round(stats["items"] / wall, 1), "unit": "items/sec",
           "requests": n_requests,
           "killed_replica": kill_replica if n_rep > 1 else None,
           "killed_at_request": kill_at if n_rep > 1 else None,
           "hangs": stats["hangs"], "errors": stats["errors"],
           "completed": stats["completed"], "shed": stats["shed"],
           "expired": stats["expired"],
           "per_replica_dispatches": per_rep,
           "wedges": telemetry.value("serving.replica.wedges"),
           "final_states": [s["state"] for s in bat.replica_states()]}
    emit(rec)
    return rec


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--mode", default="sweep,closed,open")
    ap.add_argument("--requests", type=int,
                    default=int(os.environ.get("BENCH_SERVE_REQUESTS", 500)))
    ap.add_argument("--max-batch", type=int,
                    default=int(os.environ.get("BENCH_SERVE_MAX_BATCH", 8)))
    ap.add_argument("--dim", type=int, default=256)
    ap.add_argument("--width", type=int, default=512)
    ap.add_argument("--depth", type=int, default=3)
    ap.add_argument("--workers", type=int, default=4)
    ap.add_argument("--max-wait-ms", type=float, default=2.0)
    ap.add_argument("--qps", default="100,300,1000")
    ap.add_argument("--deadline-ms", type=float, default=100.0)
    ap.add_argument("--sweep-iters", type=int, default=50)
    ap.add_argument("--replicas", type=int, default=1,
                    help="replica count for --mode replicas (0 = one per "
                         "visible device)")
    ap.add_argument("--kill-replica", type=int, default=0,
                    help="replica quarantined mid-run by --mode replicas "
                         "(-1 = no kill)")
    ap.add_argument("--decode-requests", type=int,
                    default=int(os.environ.get("BENCH_DECODE_REQUESTS",
                                               80)),
                    help="--mode decode sequence count per phase")
    ap.add_argument("--decode-slots", type=int,
                    default=int(os.environ.get("BENCH_DECODE_SLOTS", 8)),
                    help="--mode decode cohort capacity (pow2 ladder)")
    ap.add_argument("--decode-max-new", type=int,
                    default=int(os.environ.get("BENCH_DECODE_MAX_NEW", 32)),
                    help="--mode decode per-sequence generation budget cap")
    ap.add_argument("--decode-qps", default="20,60,200",
                    help="--mode decode open-loop offered request rates")
    args = ap.parse_args(argv)

    modes = {m.strip() for m in args.mode.split(",") if m.strip()}
    ok = True
    if "decode" in modes:
        rec = run_decode(n_requests=args.decode_requests,
                         slots=args.decode_slots,
                         max_new=args.decode_max_new)
        ok = ok and rec["ok"]
        run_decode_open(
            qps_list=[float(q) for q in args.decode_qps.split(",") if q],
            n_requests=min(args.decode_requests, 60),
            slots=args.decode_slots,
            max_new=min(args.decode_max_new, 16))
    single = modes - {"replicas", "decode"}
    if single:
        pred, spec = build_predictor(dim=args.dim, width=args.width,
                                     depth=args.depth,
                                     max_batch=args.max_batch)
        _emit({"metric": "serve_warmup", "buckets": len(spec),
               "value": len(spec), "unit": "compiled_buckets"})
        if "sweep" in modes:
            _, monotonic = run_sweep(pred, spec, iters=args.sweep_iters)
            ok = ok and monotonic
        if "closed" in modes:
            rec = run_closed(pred, spec, n_requests=args.requests,
                             workers=args.workers,
                             max_wait_ms=args.max_wait_ms)
            ok = ok and rec["compiles"] <= rec["buckets"] \
                and rec["watchdog_trips"] == 0
            if rec["breakdown_ok"] is not None:
                ok = ok and rec["breakdown_ok"]
        if "open" in modes:
            run_open(pred, spec,
                     qps_list=[float(q) for q in args.qps.split(",") if q],
                     n_requests=args.requests, deadline_ms=args.deadline_ms,
                     max_wait_ms=args.max_wait_ms)
    if "replicas" in modes:
        import jax
        n = args.replicas or len(jax.devices())
        if n > len(jax.devices()):
            _emit({"metric": "serve_replicas", "error":
                   "%d replicas > %d devices" % (n, len(jax.devices()))})
            return 1
        if args.kill_replica >= n:
            # an out-of-range kill would IndexError inside a client
            # thread and let the gate pass on a truncated run
            _emit({"metric": "serve_replicas", "error":
                   "--kill-replica %d out of range for %d replicas"
                   % (args.kill_replica, n)})
            return 1
        rset, spec = build_replica_set(dim=args.dim, width=args.width,
                                       depth=args.depth,
                                       max_batch=args.max_batch, replicas=n)
        _emit({"metric": "serve_replicas_warmup", "replicas": n,
               "value": n * len(spec), "unit": "compiled_buckets"})
        rec = run_replicas(rset, spec, n_requests=args.requests,
                           workers=args.workers,
                           max_wait_ms=args.max_wait_ms,
                           kill_replica=args.kill_replica,
                           kill_frac=0.5 if args.kill_replica >= 0
                           else 2.0)  # >1.0 frac: the kill never fires
        ok = ok and rec["hangs"] == 0 and rec["errors"] == 0
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
