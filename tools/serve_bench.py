#!/usr/bin/env python
"""Load generator for the serving subsystem (mxtpu/serving) — in-process.

Three phases against one AOT-warmed Predictor on the bench MLP, one JSON
line each (stamped with platform + policy_key like every bench artifact):

* ``sweep``  — direct Predictor batch-size sweep, items/s per bucket.
  The acceptance criterion rides this line: throughput must be
  monotonically non-decreasing from batch 1 to the max bucket (batching
  exists to fill the MXU; a bucket that serves SLOWER per item than a
  smaller one should simply not be declared).
* ``closed`` — closed-loop: N workers submit mixed-size requests
  back-to-back through the MicroBatcher (offered load == capacity).
  Reports items/s, req/s, client p50/p99, the compile count at retrace
  site ``serving.predict`` (must stay <= #buckets) and watchdog trips
  (must stay 0).
* ``open``   — open-loop: paced arrivals at each offered QPS with a
  per-request deadline. Reports achieved QPS, shed rate, deadline-expiry
  rate, p50/p99, and mean batch fill — the overload-behaviour curve
  (shed rate should rise and p99 should stay bounded once offered QPS
  exceeds capacity; an unbounded p99 means admission control is broken).
* ``replicas`` — ISSUE 8: closed-loop through a ReplicaSet router
  (``--replicas N``, 0 = one per device) with a kill-one-replica-mid-run
  sweep: halfway through, replica 0 is quarantined as if its chip died.
  Reports per-replica dispatch counts, throughput, shed/expired counts,
  and a **hang count** — futures that never completed. The acceptance
  gate: hangs == 0 through the replica loss (requests re-route, shed, or
  expire; none strand).
* ``slo`` — ISSUE 13: the SLO control plane A/B. Phase 1 drives an
  overload curve (paced open-loop at multiples of calibrated capacity,
  per-request deadline = the SLO) through the static depth-shed router
  and through the same router with a ``ServingController`` attached
  (predictive admission; scaling pinned min == max so replicas are
  EQUAL) — the gate is strictly higher goodput-at-SLO (completions
  within deadline / offered) for the controller on >= 1 overload point.
  Phase 2 (>= 2 devices) kills a replica mid-run (hour-long-backoff
  quarantine) and gates that the controller REPLACES it and windowed
  p99 recovers within a bounded window, with zero hung futures.
* ``decode`` — ISSUE 11: the continuous-batching autoregressive decode
  engine (``mxtpu/serving/decode.py``) on a tiny causal-attention LM.
  Phase 1 is the acceptance A/B: continuous batching vs restart-per-
  batch at EQUAL cohort capacity, identical workload and executables —
  gates: strictly higher tokens/s, zero post-warmup compiles at
  ``serving.decode``, zero d2h inside the armed decode span, int8
  logits-parity vs f32 with the accountant reporting at most ~half the
  KV bytes per slot. Phase 2 is the open-loop overload curve: paced
  submits, tokens/s + time-to-first-token p50/p99 per offered QPS, with
  the PR-10 per-stage breakdown splitting prefill from decode time.

``--mode zoo`` (ISSUE 20) is the multi-tenant model-zoo acceptance run:
  K models over a smaller device pool under skewed mixed-tenant load,
  with a mid-run canary deploy+promote AND deploy+rollback cycle.
  Gates: per-tenant goodput-at-SLO (priority isolation), page-in
  compiles == 0 (disk/memory-warm residency), zero hung futures across
  the rollout, bounded eviction/page-in churn.

Usage::

    python tools/serve_bench.py [--mode sweep,closed,open,replicas,decode,
                                 slo,zoo]
        [--requests 500] [--max-batch 8] [--dim 256] [--width 512]
        [--depth 3] [--max-wait-ms 2] [--workers 4]
        [--qps 100,300,1000] [--deadline-ms 100]
        [--replicas 0] [--kill-replica 0]
        [--decode-requests 80] [--decode-slots 8] [--decode-max-new 32]
        [--decode-qps 20,60,200]

``bench.py``'s ``serving`` config drives the same functions in-process,
and ``tools/perf_battery.sh`` runs this script as its serving phase.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _paged_page_tokens_default():
    """``BENCH_DECODE_PAGED_PAGE_TOKENS``: page size for the decode
    bench's paged phases (pow2 tokens per page)."""
    return int(os.environ.get("BENCH_DECODE_PAGED_PAGE_TOKENS", "4"))


def _paged_spec_k_default():
    """``BENCH_DECODE_PAGED_SPEC_K``: draft proposal depth for the
    decode bench's speculative phase."""
    return int(os.environ.get("BENCH_DECODE_PAGED_SPEC_K", "3"))


def _stamp(rec):
    """Platform + active policy levers on every line (bench.py contract:
    a CPU-fallback artifact must be distinguishable from a chip run).
    Since the paged-KV phases, the page size and speculation depth ride
    every line too — a regression hunt must know which layout produced
    a number without joining against the summary line."""
    try:
        import jax
        rec.setdefault("platform", jax.devices()[0].platform)
    except Exception:  # noqa: BLE001
        rec.setdefault("platform", "unknown")
    try:
        from mxtpu.ops.registry import policy_key
        rec.setdefault("policy_key", list(policy_key()))
    except Exception:  # noqa: BLE001
        rec.setdefault("policy_key", None)
    rec.setdefault("page_tokens", _paged_page_tokens_default())
    rec.setdefault("spec_k", _paged_spec_k_default())
    return rec


def _emit(rec):
    print(json.dumps(_stamp(rec)), flush=True)


def build_predictor(dim=256, width=512, depth=3, out_dim=64, max_batch=8,
                    dtype="float32"):
    """The bench model: a depth-layer MLP — small enough that dispatch
    overhead is visible (the regime micro-batching exists for), wide
    enough that per-item math grows with batch fill."""
    from mxtpu.gluon import nn
    from mxtpu.serving import BucketSpec, Predictor

    net = nn.HybridSequential(prefix="servebench_")
    with net.name_scope():
        for _ in range(max(1, depth - 1)):
            net.add(nn.Dense(width, activation="relu"))
        net.add(nn.Dense(out_dim))
    net.initialize()
    if dtype != "float32":
        example = np.zeros((1, dim), np.float32)
        net(_as_nd(example))  # settle shapes before the cast
        net.cast(dtype)
    spec = BucketSpec.pow2(max_batch)
    pred = Predictor(net, spec, example=np.zeros((1, dim), np.float32),
                     warmup=True, name="serve_bench")
    return pred, spec


def _as_nd(a):
    import mxtpu as mx
    return mx.nd.array(a)


def build_replica_set(dim=256, width=512, depth=3, out_dim=64, max_batch=8,
                      replicas=2, dtype="float32"):
    """The bench model behind a ReplicaSet: one warmed Predictor per
    device (``replicas=0`` = every visible device)."""
    from mxtpu.gluon import nn
    from mxtpu.serving import BucketSpec, ReplicaSet

    net = nn.HybridSequential(prefix="servebench_")
    with net.name_scope():
        for _ in range(max(1, depth - 1)):
            net.add(nn.Dense(width, activation="relu"))
        net.add(nn.Dense(out_dim))
    net.initialize()
    spec = BucketSpec.pow2(max_batch)
    rset = ReplicaSet(net, spec, n=replicas,
                      example=np.zeros((1, dim), np.float32),
                      warmup=True, name="serve_bench")
    return rset, spec


def _dim(pred):
    return pred.input_templates[0][0][0]


def build_decode_model(vocab=96, dim=32, max_len=96, seed=0):
    """The decode-bench model: a single-head causal-attention LM — the
    executable reference for the :class:`mxtpu.serving.decode.DecodeModel`
    contract. Prefill (``hybrid_forward``) returns ``(logits[b, s, V],
    k[b, s, d], v[b, s, d])``; ``decode_step`` writes this token's k/v at
    ``pos`` into its OWN attention view and returns the entries for the
    engine to persist. Small enough that the per-step dispatch overhead
    dominates — exactly the regime continuous batching exists for."""
    import mxtpu as mx
    from mxtpu.gluon import HybridBlock
    from mxtpu.ndarray import NDArray
    from mxtpu.serving.decode import DecodeModel

    class TinyCausalLM(HybridBlock, DecodeModel):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.embed = self.params.get("embed", shape=(vocab, dim))
                self.posemb = self.params.get("posemb",
                                              shape=(max_len, dim))
                self.wq = self.params.get("wq", shape=(dim, dim))
                self.wk = self.params.get("wk", shape=(dim, dim))
                self.wv = self.params.get("wv", shape=(dim, dim))
                self.wo = self.params.get("wo", shape=(dim, dim))
                self.wout = self.params.get("wout", shape=(dim, vocab))

        def hybrid_forward(self, F, tokens, embed, posemb, wq, wk, wv,
                           wo, wout):
            import jax
            import jax.numpy as jnp
            t = tokens._data.astype(jnp.int32)
            s = t.shape[1]
            x = embed._data[t] + posemb._data[:s][None]
            q = x @ wq._data
            k = x @ wk._data
            v = x @ wv._data
            scores = jnp.einsum("bsd,btd->bst", q, k) / float(dim) ** 0.5
            mask = jnp.tril(jnp.ones((s, s), jnp.bool_))
            scores = jnp.where(mask[None], scores, -1e30)
            h = jnp.einsum("bst,btd->bsd",
                           jax.nn.softmax(scores, axis=-1), v) @ wo._data
            logits = (x + h) @ wout._data
            return NDArray(logits), NDArray(k), NDArray(v)

        def decode_step(self, kv, tok, pos):
            import jax
            import jax.numpy as jnp
            k_cache, v_cache = kv                       # [c, L, d]
            c, L = k_cache.shape[0], k_cache.shape[1]
            x = self.embed.data()._data[tok] \
                + self.posemb.data()._data[pos]         # [c, d]
            q = x @ self.wq.data()._data
            k_new = x @ self.wk.data()._data
            v_new = x @ self.wv.data()._data
            idx = jnp.arange(c)
            kf = k_cache.at[idx, pos].set(k_new)
            vf = v_cache.at[idx, pos].set(v_new)
            scores = jnp.einsum("cd,cld->cl", q, kf) / float(dim) ** 0.5
            mask = jnp.arange(L)[None, :] <= pos[:, None]
            scores = jnp.where(mask, scores, -1e30)
            h = jnp.einsum("cl,cld->cd",
                           jax.nn.softmax(scores, axis=-1), vf) \
                @ self.wo.data()._data
            logits = (x + h) @ self.wout.data()._data
            return logits, [k_new, v_new]

        def decode_chunk(self, kv, toks, pos):
            # speculative verify fast path: all t chained tokens in one
            # causal forward — queries attend cache rows < pos plus the
            # chunk's own earlier rows (two-block concat softmax, so the
            # chunk never scatters into the cache view)
            import jax
            import jax.numpy as jnp
            k_cache, v_cache = kv                       # [c, L, d]
            L, t = k_cache.shape[1], toks.shape[1]
            p = pos[:, None] + jnp.arange(t)[None]      # [c, t]
            wp = jnp.minimum(p, max_len - 1)
            x = self.embed.data()._data[toks] \
                + self.posemb.data()._data[wp]          # [c, t, d]
            q = x @ self.wq.data()._data
            k_new = x @ self.wk.data()._data
            v_new = x @ self.wv.data()._data
            sc = jnp.einsum("ctd,cld->ctl", q, k_cache) \
                / float(dim) ** 0.5
            sc = jnp.where(
                jnp.arange(L)[None, None, :] < pos[:, None, None],
                sc, -1e30)
            sn = jnp.einsum("ctd,cud->ctu", q, k_new) \
                / float(dim) ** 0.5
            sn = jnp.where(jnp.tril(jnp.ones((t, t), jnp.bool_))[None],
                           sn, -1e30)
            attn = jax.nn.softmax(
                jnp.concatenate([sc, sn], axis=-1), axis=-1)
            h = (jnp.einsum("ctl,cld->ctd", attn[..., :L], v_cache)
                 + jnp.einsum("ctu,cud->ctd", attn[..., L:], v_new)) \
                @ self.wo.data()._data
            logits = (x + h) @ self.wout.data()._data
            return logits, [k_new, v_new]

    net = TinyCausalLM(prefix="decodebench_")
    # seeded init: the int8 logits-parity numbers must be a property of
    # the quantization path, not of this run's weight draw
    mx.random.seed(seed)
    net.initialize(mx.init.Normal(0.5))
    return net


def build_decode_engine(model, slots=4, max_prompt=24, max_new=24,
                        int8=False, continuous=True, accountant=None,
                        start=False, clock=time.monotonic, page_tokens=0,
                        pool_pages=None, prefix_cache=None,
                        draft_model=None, spec_k=None):
    """A warmed DecodeEngine over the bench LM: prefill seq buckets up to
    ``max_prompt``, a pow2 cohort-capacity ladder up to ``slots``, cache
    length sized for the longest prompt + generation budget.
    ``page_tokens`` > 0 selects the paged-KV layout (with optional
    ``pool_pages`` budget, prefix cache, or a speculative draft)."""
    from mxtpu.serving import BucketSpec, DecodeEngine

    pspec = BucketSpec([1], seq_lens=[max(4, max_prompt // 2), max_prompt])
    dspec = BucketSpec.pow2(decode_slots=slots)
    return DecodeEngine(model, pspec, dspec, max_len=max_prompt + max_new,
                        int8=int8, continuous=continuous,
                        accountant=accountant, warmup=True, start=start,
                        clock=clock, page_tokens=page_tokens,
                        pool_pages=pool_pages, prefix_cache=prefix_cache,
                        draft_model=draft_model, spec_k=spec_k)


def _decode_workload(n_requests, vocab, max_prompt, max_new, seed=11):
    """(prompt, max_new) pairs with VARIED lengths — the regime where
    continuous batching wins: a restart-per-batch cohort burns steps on
    slots whose sequence already finished, a continuous cohort refills
    them between steps."""
    rng = np.random.RandomState(seed)
    reqs = []
    for _ in range(n_requests):
        prompt = rng.randint(0, vocab,
                             size=rng.randint(3, max_prompt)).astype(np.int32)
        # the full 2..max_new spread: restart-per-batch pays max(cohort)
        # steps per cohort, continuous pays ~mean — the wider the spread,
        # the bigger the idle-slot bill the gate measures
        reqs.append((prompt, int(rng.randint(2, max_new + 1))))
    return reqs


def run_decode(n_requests=80, slots=8, max_new=32, vocab=256, dim=128,
               max_prompt=48, emit=_emit, page_tokens=None, spec_k=None):
    """The ISSUE-11 acceptance phase: continuous batching vs
    restart-per-batch decode at EQUAL cohort capacity, identical
    workload, identical executables. Gates (summary line ``ok``):
    strictly higher tokens/s continuous, ZERO post-warmup compiles at
    ``serving.decode`` (<= #cohort-buckets by construction —
    watchdog-pinned), zero d2h inside the armed decode span, and the
    int8 path passing logits parity vs f32 while the accountant reports
    about half (or less) the KV bytes per sequence."""
    from mxtpu import telemetry
    from mxtpu.serving import KVCacheAccountant

    model = build_decode_model(vocab=vocab, dim=dim,
                               max_len=max_prompt + max_new)
    reqs = _decode_workload(n_requests, vocab, max_prompt, max_new)

    def drive(continuous, int8=False, rounds=2, reqs_use=None,
              slots_use=None, page_tokens=0, pool_pages=None,
              prefix=False, spec_k=0, track_residency=False):
        # ledger KV bytes but never shed: the closed-loop burst queues the
        # whole workload up front by design (the kv_residency shed path
        # has its own default-overcommit coverage in tests/test_decode.py)
        my_reqs = reqs if reqs_use is None else reqs_use
        acct = KVCacheAccountant(overcommit=float(n_requests) * 64)
        eng = build_decode_engine(model,
                                  slots=slots if slots_use is None
                                  else slots_use,
                                  max_prompt=max_prompt,
                                  max_new=max_new, int8=int8,
                                  continuous=continuous, accountant=acct,
                                  page_tokens=page_tokens,
                                  pool_pages=pool_pages,
                                  prefix_cache=prefix or None,
                                  draft_model=model if spec_k else None,
                                  spec_k=spec_k or None)
        st0 = telemetry.retrace_stats(eng._site) or {}
        std0 = telemetry.retrace_stats(eng._draft_site) or {} \
            if spec_k else {}
        steps0 = telemetry.value("serving.decode.steps")
        toks0 = telemetry.value("serving.decode.tokens")
        d2h0 = telemetry.value("serving.decode.d2h")
        live_high = shared_high = 0
        best = None
        # best-of-rounds, like run_sweep: one round on a shared host
        # measures scheduler noise, not the replay cost the gate judges
        # (step counts are identical per round; the compile/d2h deltas
        # below span ALL rounds, so a lazy compile can't hide)
        for _ in range(max(1, rounds)):
            r_steps0 = telemetry.value("serving.decode.steps")
            r_toks0 = telemetry.value("serving.decode.tokens")
            t0 = time.perf_counter()
            futs = [eng.submit(p, max_new=m) for p, m in my_reqs]
            guard = 0
            while not all(f.done() for f in futs) and guard < 100000:
                eng.poll()
                if track_residency:
                    live_high = max(live_high, eng._live)
                    shared_high = max(
                        shared_high,
                        telemetry.gauge_value("serving.kv_page_shared")
                        or 0)
                guard += 1
            wall = time.perf_counter() - t0
            outs = [f.result(timeout=5) for f in futs]
            round_rec = {
                "tokens": telemetry.value("serving.decode.tokens")
                - r_toks0,
                "steps": telemetry.value("serving.decode.steps") - r_steps0,
                "wall_s": wall,
                "tok_per_s": (telemetry.value("serving.decode.tokens")
                              - r_toks0) / wall,
                "ttft_p50_ms": round(float(np.percentile(
                    [f.ttft_s for f in futs], 50)) * 1e3, 3),
                "ttft_p99_ms": round(float(np.percentile(
                    [f.ttft_s for f in futs], 99)) * 1e3, 3),
            }
            if best is None or round_rec["tok_per_s"] > best["tok_per_s"]:
                best = round_rec
        st = telemetry.retrace_stats(eng._site) or {}
        std = telemetry.retrace_stats(eng._draft_site) or {} \
            if spec_k else {}
        best.update({
            "compiles_post_warmup": st.get("compiles", 0)
            - st0.get("compiles", 0),
            "draft_compiles_post_warmup": std.get("compiles", 0)
            - std0.get("compiles", 0),
            "watchdog_trips": st.get("trips", 0) - st0.get("trips", 0),
            "per_slot_kv_bytes": eng.per_slot_kv_bytes(),
            "total_steps": telemetry.value("serving.decode.steps") - steps0,
            "total_tokens": telemetry.value("serving.decode.tokens")
            - toks0,
            # delta like every sibling gate: a cumulative read would fail
            # forever after any earlier in-process sync
            "d2h": telemetry.value("serving.decode.d2h") - d2h0,
            "live_high": live_high,
            "shared_pages_high": shared_high,
        })
        eng.close(timeout=5)
        return best, outs, eng

    cont, cont_outs, _ = drive(True)
    emit({"metric": "serve_decode_continuous",
          "value": round(cont["tok_per_s"], 1), "unit": "tokens/sec",
          **{k: cont[k] for k in ("tokens", "steps", "ttft_p50_ms",
                                  "ttft_p99_ms", "compiles_post_warmup",
                                  "watchdog_trips")}})
    rest, rest_outs, _ = drive(False)
    emit({"metric": "serve_decode_restart",
          "value": round(rest["tok_per_s"], 1), "unit": "tokens/sec",
          **{k: rest[k] for k in ("tokens", "steps", "ttft_p50_ms",
                                  "ttft_p99_ms", "compiles_post_warmup",
                                  "watchdog_trips")}})
    parity_tokens = all(len(a) == len(b) and (a == b).all()
                        for a, b in zip(cont_outs, rest_outs))

    # int8 phase on the SAME weights: throughput line + the logits-parity
    # and KV-bytes gates (probes run on fresh single-purpose engines —
    # the throughput engines are closed)
    q, _q_outs, _ = drive(True, int8=True)
    probe = reqs[0][0]
    eng_f = build_decode_engine(model, slots=2, max_prompt=max_prompt,
                                max_new=max_new)
    eng_q = build_decode_engine(model, slots=2, max_prompt=max_prompt,
                                max_new=max_new, int8=True)
    lf, lq = eng_f.prefill_logits(probe), eng_q.prefill_logits(probe)
    sf, sq = eng_f.step_logits_probe(probe), eng_q.step_logits_probe(probe)
    prefill_err = float(np.abs(lf - lq).mean() / (np.abs(lf).mean() + 1e-9))
    step_err = float(np.abs(sf - sq).mean() / (np.abs(sf).mean() + 1e-9))
    kv_ratio = q["per_slot_kv_bytes"] / float(cont["per_slot_kv_bytes"])
    eng_f.close(timeout=2)
    eng_q.close(timeout=2)
    int8_ok = prefill_err <= 0.05 and step_err <= 0.05 and kv_ratio <= 0.55
    emit({"metric": "serve_decode_int8",
          "value": round(q["tok_per_s"], 1), "unit": "tokens/sec",
          "prefill_logits_rel_err": round(prefill_err, 5),
          "step_logits_rel_err": round(step_err, 5),
          "kv_bytes_per_slot_f32": cont["per_slot_kv_bytes"],
          "kv_bytes_per_slot_int8": q["per_slot_kv_bytes"],
          "kv_bytes_ratio": round(kv_ratio, 4),
          # the residency dividend: sequences admissible at equal memory
          "admit_multiplier": round(1.0 / kv_ratio, 2),
          "int8_ok": int8_ok})

    # ---- ISSUE-16 paged phases: A/B at equal HBM, prefix reuse, spec --
    pt = int(page_tokens if page_tokens is not None
             else _paged_page_tokens_default())
    k = int(spec_k if spec_k is not None else _paged_spec_k_default())
    max_len = max_prompt + max_new
    rng = np.random.RandomState(29)
    # equal-HBM A/B: the paged pool holds EXACTLY the rowed engine's
    # bytes (slots_r worst-case rows, repaginated), and the cohort table
    # offers as many lanes as that pool can carry at the A/B workload's
    # worst-case footprint (+1 page of speculative-lookahead headroom) —
    # short sequences against a long max_len is precisely the regime
    # where rowed residency pays for pessimism and paging does not
    slots_r = 2
    pool_pages = slots_r * max_len // pt
    ab_p_max, ab_g_max = 8, 8
    pages_worst = -(-min(ab_p_max - 1 + ab_g_max, max_len) // pt) + 1
    slots_p = min(3 * slots_r, max(slots_r, pool_pages // pages_worst))
    ab_reqs = _decode_workload(min(n_requests, 24), vocab,
                               max_prompt=ab_p_max, max_new=ab_g_max,
                               seed=13)
    row_ab, row_outs, _ = drive(True, reqs_use=ab_reqs, slots_use=slots_r,
                                track_residency=True)
    pag_ab, pag_outs, _ = drive(True, reqs_use=ab_reqs, slots_use=slots_p,
                                page_tokens=pt, pool_pages=pool_pages,
                                track_residency=True)
    ab_parity = all(len(a) == len(b) and (a == b).all()
                    for a, b in zip(row_outs, pag_outs))
    residency_x = pag_ab["live_high"] / float(max(1, row_ab["live_high"]))
    ab_ok = (residency_x >= 2.0 and ab_parity
             and pag_ab["compiles_post_warmup"] == 0
             and pag_ab["d2h"] == 0)
    emit({"metric": "serve_decode_paged_ab", "value": round(residency_x, 2),
          "unit": "residency_multiplier_at_equal_hbm",
          "rowed_live_high": row_ab["live_high"],
          "paged_live_high": pag_ab["live_high"],
          "pool_pages": pool_pages,
          "hbm_budget_bytes": slots_r * row_ab["per_slot_kv_bytes"],
          "rowed_tok_per_s": round(row_ab["tok_per_s"], 1),
          "paged_tok_per_s": round(pag_ab["tok_per_s"], 1),
          "token_parity_paged_vs_rowed": ab_parity,
          "compiles_post_warmup": pag_ab["compiles_post_warmup"],
          "d2h": pag_ab["d2h"], "ok_ab": ab_ok})

    # prefix reuse under a templated-prompt cohort: one shared system
    # template, short novel suffixes — the hit path skips the template's
    # prefill and shares its pages read-only
    tmpl_len = max(1, (max_prompt // 2) // pt) * pt
    sfx_hi = min(7, max_prompt - tmpl_len + 1)
    tmpl = rng.randint(0, vocab, size=tmpl_len).astype(np.int32)
    pre_reqs = [(np.concatenate([
        tmpl, rng.randint(0, vocab,
                          size=rng.randint(2, sfx_hi)).astype(np.int32)]),
        int(rng.randint(2, 9))) for _ in range(min(n_requests, 16))]
    hits0 = telemetry.value("serving.prefix.hits") or 0
    miss0 = telemetry.value("serving.prefix.misses") or 0
    ref_pre, ref_pre_outs, _ = drive(True, reqs_use=pre_reqs,
                                     slots_use=slots_r)
    pre, pre_outs, _ = drive(True, reqs_use=pre_reqs, slots_use=slots_p,
                             page_tokens=pt, prefix=True,
                             track_residency=True)
    hits = (telemetry.value("serving.prefix.hits") or 0) - hits0
    misses = (telemetry.value("serving.prefix.misses") or 0) - miss0
    hit_rate = hits / float(max(1, hits + misses))
    pre_parity = all(len(a) == len(b) and (a == b).all()
                     for a, b in zip(ref_pre_outs, pre_outs))
    prefix_ok = (hit_rate > 0 and pre["shared_pages_high"] > 0
                 and pre_parity and pre["compiles_post_warmup"] == 0
                 and pre["d2h"] == 0)
    emit({"metric": "serve_decode_prefix", "value": round(hit_rate, 3),
          "unit": "prefix_hit_rate", "prefix_hits": hits,
          "prefix_misses": misses,
          "shared_pages_high": pre["shared_pages_high"],
          "token_parity_prefix_vs_rowed": pre_parity,
          "compiles_post_warmup": pre["compiles_post_warmup"],
          "d2h": pre["d2h"], "ok_prefix": prefix_ok})

    # speculative decoding on a decode-heavy cohort: short prompts, the
    # run's full generation budget.  Speculation pays per DECODE token
    # (prefill is identical on both sides and speculation cannot help
    # it), so the honest A/B drives BOTH engines — a plain paged
    # baseline and the draft+verify pair — with the same
    # decode-dominated request set.  draft == target, so acceptance is
    # bounded only by per-sequence stop truncation and the tokens/step
    # win is pure dispatch arithmetic (2 dispatches commit up to k+1
    # tokens).
    sp_reqs = [(rng.randint(0, vocab, size=rng.randint(3, 9))
                .astype(np.int32), max_new)
               for _ in range(min(n_requests, 16))]
    sp_base, sp_base_outs, _ = drive(True, reqs_use=sp_reqs,
                                     slots_use=slots_p, page_tokens=pt,
                                     rounds=3)
    prop0 = telemetry.value("serving.decode.spec_proposed") or 0
    acc0 = telemetry.value("serving.decode.spec_accepted") or 0
    spec, spec_outs, _ = drive(True, reqs_use=sp_reqs, slots_use=slots_p,
                               page_tokens=pt, spec_k=k, rounds=3)
    proposed = (telemetry.value("serving.decode.spec_proposed") or 0) - prop0
    accepted = (telemetry.value("serving.decode.spec_accepted") or 0) - acc0
    accept_rate = accepted / float(max(1, proposed))
    spec_parity = all(len(a) == len(b) and (a == b).all()
                      for a, b in zip(sp_base_outs, spec_outs))
    spec_tps = spec["tokens"] / float(max(1, spec["steps"]))
    pag_tps = sp_base["tokens"] / float(max(1, sp_base["steps"]))
    spec_ok = (spec_parity and spec_tps > pag_tps
               and spec["tok_per_s"] > sp_base["tok_per_s"]
               and spec["compiles_post_warmup"] == 0
               and spec["draft_compiles_post_warmup"] == 0
               and spec["d2h"] == 0)
    emit({"metric": "serve_decode_spec", "value": round(spec_tps, 3),
          "unit": "tokens_per_step", "accept_rate": round(accept_rate, 3),
          "spec_tok_per_s": round(spec["tok_per_s"], 1),
          "paged_tok_per_s": round(sp_base["tok_per_s"], 1),
          "paged_tokens_per_step": round(pag_tps, 3),
          "token_parity_spec_vs_paged": spec_parity,
          "compiles_post_warmup": spec["compiles_post_warmup"],
          "draft_compiles_post_warmup": spec["draft_compiles_post_warmup"],
          "d2h": spec["d2h"], "ok_spec": spec_ok})

    speedup = cont["tok_per_s"] / rest["tok_per_s"] \
        if rest["tok_per_s"] > 0 else 0.0
    ok = (cont["tok_per_s"] > rest["tok_per_s"]
          and parity_tokens
          and cont["compiles_post_warmup"] == 0
          and cont["watchdog_trips"] == 0
          and cont["d2h"] == 0 and rest["d2h"] == 0 and q["d2h"] == 0
          and int8_ok and ab_ok and prefix_ok and spec_ok)
    emit({"metric": "serve_decode", "value": round(speedup, 3),
          "unit": "continuous_vs_restart_speedup",
          "continuous_tok_per_s": round(cont["tok_per_s"], 1),
          "restart_tok_per_s": round(rest["tok_per_s"], 1),
          "continuous_steps": cont["steps"],
          "restart_steps": rest["steps"],
          "token_parity_continuous_vs_restart": parity_tokens,
          "compiles_post_warmup": cont["compiles_post_warmup"],
          "decode_d2h": cont["d2h"] + rest["d2h"] + q["d2h"],
          "paged_residency_x": round(residency_x, 2),
          "prefix_hit_rate": round(hit_rate, 3),
          "spec_accept_rate": round(accept_rate, 3),
          "spec_tokens_per_step": round(spec_tps, 3),
          "ok": ok})
    return {"ok": ok, "speedup": speedup, "continuous": cont,
            "restart": rest, "int8": q, "prefill_logits_rel_err": prefill_err,
            "step_logits_rel_err": step_err, "kv_bytes_ratio": kv_ratio,
            "residency_x": residency_x, "ab_ok": ab_ok,
            "prefix_hit_rate": hit_rate, "prefix_ok": prefix_ok,
            "accept_rate": accept_rate, "spec_tokens_per_step": spec_tps,
            "spec_ok": spec_ok}


def run_decode_open(qps_list=(20.0, 60.0, 200.0), n_requests=60, slots=4,
                    max_new=16, vocab=96, dim=32, max_prompt=24,
                    deadline_ms=2000.0, emit=_emit):
    """Open-loop decode overload curve: paced submits against a THREADED
    engine, one line per offered rate — achieved tokens/s,
    time-to-first-token p50/p99, shed rate, and the per-stage split the
    PR-10 breakdown makes possible: prefill vs decode milliseconds per
    request (p50), so a TTFT regression is attributable to the right
    phase from the artifact alone."""
    from mxtpu import telemetry
    from mxtpu.serving import QueueFull

    model = build_decode_model(vocab=vocab, dim=dim,
                               max_len=max_prompt + max_new)
    reqs = _decode_workload(n_requests, vocab, max_prompt, max_new, seed=23)
    recs = []
    for qps in qps_list:
        eng = build_decode_engine(model, slots=slots, max_prompt=max_prompt,
                                  max_new=max_new, start=True)
        interval = 1.0 / float(qps)
        futs, shed = [], 0
        t0 = time.perf_counter()
        for i, (p, m) in enumerate(reqs):
            target = t0 + i * interval
            now = time.perf_counter()
            if target > now:
                time.sleep(target - now)
            try:
                futs.append(eng.submit(p, max_new=m,
                                       deadline_ms=deadline_ms))
            except QueueFull:
                shed += 1
        done, expired = [], 0
        for f in futs:
            try:
                toks = f.result(timeout=30)
                done.append((f, len(toks)))
            except Exception:  # noqa: BLE001 — DeadlineExceeded
                expired += 1
        wall = time.perf_counter() - t0
        eng.close(timeout=10)
        ttfts = [f.ttft_s for f, _n in done if f.ttft_s is not None]
        stage = {"serving.prefill": [], "serving.decode": []}
        for f, _n in done:
            if f.breakdown:
                for name in stage:
                    if name in f.breakdown:
                        stage[name].append(f.breakdown[name])
        rec = {"metric": "serve_decode_qps%g" % qps, "offered_qps": qps,
               "value": round(sum(n for _f, n in done) / wall, 1),
               "unit": "tokens/sec",
               "completed": len(done),
               "shed_rate": round(shed / float(n_requests), 4),
               "expired_rate": round(expired / float(n_requests), 4),
               "ttft_p50_ms": round(float(np.percentile(ttfts, 50)) * 1e3,
                                    3) if ttfts else None,
               "ttft_p99_ms": round(float(np.percentile(ttfts, 99)) * 1e3,
                                    3) if ttfts else None,
               "prefill_p50_ms": round(float(np.percentile(
                   stage["serving.prefill"], 50)) * 1e3, 3)
               if stage["serving.prefill"] else None,
               "decode_p50_ms": round(float(np.percentile(
                   stage["serving.decode"], 50)) * 1e3, 3)
               if stage["serving.decode"] else None}
        emit(rec)
        recs.append(rec)
    return recs


def run_sweep(pred, spec, iters=50, repeats=3, emit=_emit):
    """Items/s per batch bucket, direct Predictor calls (no batcher).
    Each bucket is timed ``repeats`` times and takes its BEST round — a
    single round on a shared host measures scheduler noise, not the
    dispatch+compute cost the monotonicity gate judges. Returns
    (rates, monotonic); monotonic allows a further 5% residual noise."""
    dim = _dim(pred)
    rng = np.random.RandomState(0)
    rates = []
    for b in spec.batch_sizes:
        x = rng.randn(b, dim).astype(np.float32)
        pred.predict(x).asnumpy()  # warm (compiled at warmup; prime caches)
        best_dt = None
        for _ in range(max(1, repeats)):
            t0 = time.perf_counter()
            out = None
            for _ in range(iters):
                out = pred.predict(x)
            out.asnumpy()  # one sync closes the async tail
            dt = time.perf_counter() - t0
            best_dt = dt if best_dt is None else min(best_dt, dt)
        rate = b * iters / best_dt
        rates.append(rate)
        emit({"metric": "serve_sweep_b%d" % b, "value": round(rate, 1),
              "unit": "items/sec",
              "ms_per_batch": round(best_dt / iters * 1e3, 3)})
    monotonic = all(rates[i + 1] >= rates[i] * 0.95
                    for i in range(len(rates) - 1))
    emit({"metric": "serve_sweep", "value": round(rates[-1], 1),
          "unit": "items/sec", "monotonic_non_decreasing": monotonic,
          "rates": [round(r, 1) for r in rates]})
    return rates, monotonic


def run_closed(pred, spec, n_requests=500, workers=4, max_wait_ms=2.0,
               sizes=(1, 2, 3), emit=_emit):
    """Closed-loop mixed-shape run through the MicroBatcher; the
    acceptance record: compiles <= #buckets, zero watchdog trips — and,
    with causal tracing on (MXTPU_TRACE, default 1), the per-request
    latency BREAKDOWN: p99 per stage (queue-wait vs pad vs device vs
    fetch vs deliver) plus the honesty gate that each request's stages
    sum to within 5% of its measured end-to-end latency (median ratio
    error across the run; ``breakdown_ok``)."""
    from mxtpu import telemetry
    from mxtpu.serving import MicroBatcher

    dim = _dim(pred)
    st0 = telemetry.retrace_stats("serving.predict") or {}
    compiles0, trips0 = st0.get("compiles", 0), st0.get("trips", 0)
    shed0 = telemetry.value("serving.shed")  # deltas, like compiles/trips
    bat = MicroBatcher(pred, max_batch_size=spec.max_batch,
                       max_wait_ms=max_wait_ms, max_queue=4096)
    lat, lock = [], threading.Lock()
    items = [0]
    breakdowns = []   # (breakdown dict, e2e_s) per traced request

    def client(k, n):
        rng = np.random.RandomState(100 + k)
        for _ in range(n):
            sz = int(sizes[rng.randint(len(sizes))])
            x = rng.randn(sz, dim).astype(np.float32)
            t0 = time.perf_counter()
            fut = bat.submit(x)
            fut.result(timeout=60)
            dt = time.perf_counter() - t0
            with lock:
                lat.append(dt)
                items[0] += sz
                if fut.breakdown is not None:
                    breakdowns.append((fut.breakdown, fut.e2e_s))
    per = [n_requests // workers] * workers
    per[0] += n_requests - sum(per)
    threads = [threading.Thread(target=client, args=(k, n))
               for k, n in enumerate(per)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    bat.close()
    st = telemetry.retrace_stats("serving.predict") or {}
    lat_ms = np.array(lat) * 1e3
    rec = {"metric": "serve_closed", "value": round(items[0] / wall, 1),
           "unit": "items/sec",
           "req_per_s": round(len(lat) / wall, 1),
           "requests": len(lat), "workers": workers,
           "p50_ms": round(float(np.percentile(lat_ms, 50)), 3),
           "p99_ms": round(float(np.percentile(lat_ms, 99)), 3),
           "compiles": st.get("compiles", 0) - compiles0,
           "buckets": len(spec),
           "watchdog_trips": st.get("trips", 0) - trips0,
           "shed": telemetry.value("serving.shed") - shed0}
    rec.update(_breakdown_summary(breakdowns))
    emit(rec)
    return rec


def _breakdown_summary(breakdowns):
    """p99 per breakdown stage + the sum-vs-e2e honesty gate. Empty dict
    when tracing was off (no breakdowns to judge)."""
    if not breakdowns:
        return {"stage_p99_ms": None, "breakdown_err_median": None,
                "breakdown_ok": None}
    stages = {}
    errs = []
    for bd, e2e in breakdowns:
        for name, v in bd.items():
            stages.setdefault(name, []).append(v)
        if e2e and e2e > 1e-6:
            errs.append(abs(sum(bd.values()) - e2e) / e2e)
    p99 = {name: round(float(np.percentile(np.array(v) * 1e3, 99)), 4)
           for name, v in sorted(stages.items())}
    med = float(np.median(errs)) if errs else None
    return {"stage_p99_ms": p99,
            "breakdown_err_median": round(med, 4) if med is not None
            else None,
            # the ISSUE-10 acceptance bound: a request's returned stages
            # sum to within 5% of its measured end-to-end latency
            "breakdown_ok": (med is not None and med <= 0.05)}


def run_open(pred, spec, qps_list=(100.0, 300.0, 1000.0), n_requests=200,
             deadline_ms=100.0, max_wait_ms=2.0, emit=_emit):
    """Open-loop offered-QPS sweep: paced arrivals, per-request deadline.
    One line per offered rate with shed/expired rates and batch fill."""
    from mxtpu import telemetry
    from mxtpu.serving import MicroBatcher, QueueFull

    dim = _dim(pred)
    recs = []
    for qps in qps_list:
        telemetry.reset_metric("serving.batch_fill")
        # per-request latency comes from the batcher's own enqueue->deliver
        # histogram (client-side "wait on every future after the run" would
        # credit the whole run's tail to the earliest requests)
        telemetry.reset_metric("serving.latency_s")
        bat = MicroBatcher(pred, max_batch_size=spec.max_batch,
                           max_wait_ms=max_wait_ms,
                           max_queue=max(2 * spec.max_batch, 32))
        rng = np.random.RandomState(7)
        futures, shed = [], 0
        interval = 1.0 / float(qps)
        t0 = time.perf_counter()
        for i in range(n_requests):
            target = t0 + i * interval
            now = time.perf_counter()
            if target > now:
                time.sleep(target - now)
            x = rng.randn(1, dim).astype(np.float32)
            try:
                futures.append(bat.submit(x, deadline_ms=deadline_ms))
            except QueueFull:
                shed += 1
        ok, expired = 0, 0
        for fut in futures:
            try:
                fut.result(timeout=30)
                ok += 1
            except Exception:  # noqa: BLE001 — DeadlineExceeded
                expired += 1
        wall = time.perf_counter() - t0
        bat.close()
        snap = telemetry.snapshot()["histograms"]
        fill = snap.get("serving.batch_fill")
        lat = snap.get("serving.latency_s")
        rec = {"metric": "serve_open_qps%g" % qps, "offered_qps": qps,
               "value": round(ok / wall, 1), "unit": "ok_req/sec",
               "shed_rate": round(shed / n_requests, 4),
               "expired_rate": round(expired / n_requests, 4),
               "p50_ms": round(lat["p50"] * 1e3, 3) if lat else None,
               "p99_ms": round(lat["p99"] * 1e3, 3) if lat else None,
               "batch_fill_mean": round(fill["mean"], 4) if fill else None}
        emit(rec)
        recs.append(rec)
    return recs


def _slo_point(bat, dim, qps, n_requests, slo_ms, seed=0,
               result_timeout=30.0, priority="interactive"):
    """One open-loop point: paced single-item submits with the SLO as
    the per-request deadline. Returns the outcome census — ``good`` is
    the goodput numerator (completed WITHIN the SLO)."""
    from mxtpu.serving import DeadlineExceeded, QueueFull

    rng = np.random.RandomState(seed)
    slo_s = slo_ms / 1e3
    futs, out = [], {"offered": n_requests, "shed": 0, "good": 0,
                     "late": 0, "expired": 0, "errors": 0, "hangs": 0}
    interval = 1.0 / float(qps) if qps > 0 else 0.0
    t0 = time.perf_counter()
    for i in range(n_requests):
        if interval:
            target = t0 + i * interval
            now = time.perf_counter()
            if target > now:
                time.sleep(target - now)
            elif i % 16 == 0:
                # behind schedule (offered > this host can even submit):
                # still yield the GIL periodically so the dispatch
                # workers run — a pure submit spin on a small host would
                # starve the very queue it is measuring
                time.sleep(5e-4)
        x = rng.randn(1, dim).astype(np.float32)
        try:
            futs.append(bat.submit(x, deadline_ms=slo_ms,
                                   priority=priority))
        except QueueFull:
            out["shed"] += 1
    lat = []
    for fut in futs:
        try:
            fut.result(timeout=result_timeout)
        except DeadlineExceeded:
            out["expired" if fut.done() else "hangs"] += 1
        except Exception:  # noqa: BLE001 — shed-at-dispatch etc.
            out["errors"] += 1
        else:
            e2e = fut.e2e_s
            lat.append(e2e if e2e is not None else 0.0)
            if e2e is not None and e2e > slo_s:
                out["late"] += 1
            else:
                out["good"] += 1
    out["wall_s"] = time.perf_counter() - t0
    out["p99_ms"] = round(float(np.percentile(
        np.array(lat) * 1e3, 99)), 3) if lat else None
    out["goodput"] = out["good"] / float(n_requests)
    return out


def run_slo(dim=128, width=256, depth=3, replicas=None, max_batch=8,
            n_requests=200, slo_ms=None, qps_factors=(1.5, 3.0, 8.0),
            max_wait_ms=2.0, kill=True, recover_window_s=15.0,
            emit=_emit):
    """ISSUE 13 acceptance: the SLO control plane vs the static
    depth-shed router, at EQUAL replicas.

    Phase 1 (overload curve): calibrate capacity with a short closed
    burst, then drive paced open-loop points at ``qps_factors`` x
    capacity through (a) a plain ReplicaDispatcher shedding only at the
    depth bound and (b) the same dispatcher with a
    :class:`ServingController` attached (predictive admission; scaling
    pinned ``min == max`` so the comparison is capacity-neutral). The
    queue bound is sized ~8 SLOs deep for BOTH — the static router's
    exact production failure mode: a depth bound that does not know the
    service rate admits work it already cannot finish in time. Gate:
    the controller's goodput-at-SLO (completions within deadline /
    offered) strictly beats the static router's on >= 1 overload point.

    Phase 2 (kill/restore, >= 2 devices): threaded serving at ~0.5 x
    capacity; replica 0 is quarantined with an hour-long backoff (a
    dead chip), and the controller — ``replace_after_ms`` = 500 — must
    REPLACE it on a fresh device. Gate: windowed p99 recovers within
    ``recover_window_s`` of the kill, zero hung futures, healthy count
    restored."""
    import jax

    from mxtpu.serving import ReplicaDispatcher, ServingController

    n_dev = len(jax.devices())
    if replicas is None:
        replicas = min(2, n_dev)
    replicas = max(1, min(replicas, n_dev))

    # ---- calibration: capacity + an SLO this host can actually meet.
    # Concurrent closed-loop clients (serial submit-and-wait measures
    # per-request LATENCY, not the coalesced service rate the queue
    # drains at); the first wave is dropped from the latency sample so
    # cold-path stragglers cannot inflate the auto-SLO.
    rset_cal, spec = build_replica_set(dim=dim, width=width, depth=depth,
                                       max_batch=max_batch,
                                       replicas=replicas)
    cal = ReplicaDispatcher(rset_cal, max_batch_size=spec.max_batch,
                            max_wait_ms=max_wait_ms, max_queue=4096)
    lat, lock = [], threading.Lock()
    n_workers, per_worker = 8, 40

    def _cal_client(k):
        rng = np.random.RandomState(50 + k)
        for j in range(per_worker):
            fut = cal.submit(rng.randn(1, dim).astype(np.float32))
            fut.result(timeout=30)
            if j >= 5 and fut.e2e_s is not None:
                with lock:
                    lat.append(fut.e2e_s)
    threads = [threading.Thread(target=_cal_client, args=(k,))
               for k in range(n_workers)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    capacity_rps = n_workers * per_worker / (time.perf_counter() - t0)
    cal.close(timeout=5)
    if slo_ms is None:
        # ~6x the loaded median: comfortably feasible off-overload, and
        # far shallower than the mis-sized depth bound below
        slo_ms = float(min(150.0, max(
            20.0, np.percentile(np.array(lat) * 1e3, 50) * 6.0)))
    slo_s = slo_ms / 1e3
    # the mis-sized static depth bound: ~12 SLOs of work at capacity —
    # exactly the production failure mode (MXTPU_SERVE_QUEUE is a static
    # item count that does not know the service rate), applied to BOTH
    # routers; each point offers enough requests to actually fill it
    max_queue = int(min(4096, max(64, capacity_rps * slo_s * 12)))
    # long enough that the queue-fill TRANSIENT (which flatters the
    # static router: its first max_queue admits ride an empty queue)
    # is a small fraction of each point
    n_requests = max(n_requests, 8 * max_queue)
    emit({"metric": "serve_slo_calibration", "value": round(capacity_rps, 1),
          "unit": "req/sec", "slo_ms": round(slo_ms, 2),
          "max_queue": max_queue, "requests_per_point": n_requests,
          "replicas": replicas})

    # ---- phase 1: goodput-at-SLO curve, static vs controller
    def build(router):
        rset, _spec = build_replica_set(dim=dim, width=width, depth=depth,
                                        max_batch=max_batch,
                                        replicas=replicas)
        bat = ReplicaDispatcher(rset, max_batch_size=spec.max_batch,
                                max_wait_ms=max_wait_ms,
                                max_queue=max_queue)
        if router == "controller":
            ServingController(bat, min_replicas=replicas,
                              max_replicas=replicas, min_samples=8,
                              quantile=0.9)
        # identical closed-loop warm traffic for both, cycling through
        # every batch bucket: primes each bucket's dispatch path (and
        # the controller's latency model) past the cold-start stragglers
        # before the measured points — a model whose window is mostly
        # first-dispatch outliers would predict misses forever
        rng = np.random.RandomState(7)
        sizes = list(spec.batch_sizes)
        for j in range(16 * len(sizes)):
            b = sizes[j % len(sizes)]
            bat.submit(rng.randn(b, dim).astype(np.float32)).result(
                timeout=30)
        return bat

    curve, hangs = {}, 0
    for router in ("static", "controller"):
        bat = build(router)
        curve[router] = []
        for f in qps_factors:
            pt = _slo_point(bat, dim, qps=capacity_rps * f,
                            n_requests=n_requests, slo_ms=slo_ms,
                            seed=int(100 * f))
            hangs += pt["hangs"]
            rec = {"metric": "serve_slo_%s_x%g" % (router, f),
                   "value": round(pt["goodput"], 4), "unit": "goodput_at_slo",
                   "offered_factor": f,
                   "offered_qps": round(capacity_rps * f, 1),
                   **{k: pt[k] for k in ("good", "late", "shed", "expired",
                                         "errors", "hangs", "p99_ms")}}
            emit(rec)
            curve[router].append(pt)
        bat.close(timeout=10)
    gains = [c["goodput"] - s["goodput"]
             for s, c in zip(curve["static"], curve["controller"])]
    ok_curve = any(g > 0 for g in gains)

    # ---- phase 2: kill/restore — the self-healing path
    kill_rec = None
    if kill and replicas >= 2:
        kill_rec = _run_killrestore(dim, width, depth, replicas, max_batch,
                                    spec, capacity_rps, slo_ms, max_wait_ms,
                                    recover_window_s, emit)
        hangs += kill_rec["hangs"]
    ok = ok_curve and hangs == 0 and \
        (kill_rec is None or kill_rec["ok"])
    emit({"metric": "serve_slo", "value": round(max(gains), 4),
          "unit": "goodput_gain_at_best_point",
          "slo_ms": round(slo_ms, 2),
          "goodput_static": [round(p["goodput"], 4)
                             for p in curve["static"]],
          "goodput_controller": [round(p["goodput"], 4)
                                 for p in curve["controller"]],
          "curve_ok": ok_curve, "hangs": hangs,
          "killrestore_ok": kill_rec["ok"] if kill_rec else None,
          "ok": ok})
    return {"ok": ok, "curve_ok": ok_curve, "gains": gains,
            "hangs": hangs, "slo_ms": slo_ms, "curve": curve,
            "killrestore": kill_rec}


def _run_killrestore(dim, width, depth, replicas, max_batch, spec,
                     capacity_rps, slo_ms, max_wait_ms, recover_window_s,
                     emit):
    """Threaded kill/restore sweep: quarantine replica 0 as a dead chip
    mid-run; the controller must replace it and windowed p99 must come
    back within ``recover_window_s``."""
    from mxtpu.serving import DeadlineExceeded, QueueFull, ReplicaDispatcher, \
        ServingController

    rset, _ = build_replica_set(dim=dim, width=width, depth=depth,
                                max_batch=max_batch, replicas=replicas)
    bat = ReplicaDispatcher(rset, max_batch_size=spec.max_batch,
                            max_wait_ms=max_wait_ms, max_queue=4096)
    ServingController(bat, min_replicas=replicas, max_replicas=replicas,
                      replace_after_ms=500, scale_cooldown_ms=300,
                      min_samples=8)
    rng = np.random.RandomState(13)
    qps = max(20.0, capacity_rps * 0.5)
    interval = 1.0 / qps
    pre_s, window_s = 2.0, 0.5
    total_s = pre_s + recover_window_s
    futs = []               # (submit_t_rel, future)
    shed = 0
    killed_at = None
    t0 = time.perf_counter()
    i = 0
    while True:
        rel = time.perf_counter() - t0
        if rel >= total_s:
            break
        if killed_at is None and rel >= pre_s:
            bat.quarantine_replica(rset.replicas[0].index, backoff_s=3600.0)
            killed_at = rel
        target = t0 + i * interval
        now = time.perf_counter()
        if target > now:
            time.sleep(min(target - now, 0.05))
            continue
        i += 1
        try:
            futs.append((rel, bat.submit(
                rng.randn(1, dim).astype(np.float32), deadline_ms=5000.0)))
        except QueueFull:
            shed += 1
    hangs = expired = 0
    windows = {}
    for rel, fut in futs:
        try:
            fut.result(timeout=30)
        except DeadlineExceeded:
            if fut.done():
                expired += 1
            else:
                hangs += 1
            continue
        except Exception:  # noqa: BLE001
            expired += 1
            continue
        if fut.e2e_s is not None:
            windows.setdefault(int(rel / window_s), []).append(fut.e2e_s)
    healthy = sum(1 for r in rset.replicas if r.state == "healthy")
    states = [(r.index, r.state) for r in rset.replicas]
    bat.close(timeout=10)
    p99 = {w: float(np.percentile(np.array(v) * 1e3, 99))
           for w, v in sorted(windows.items()) if v}
    pre_windows = [v for w, v in p99.items() if (w + 1) * window_s <= pre_s]
    baseline_ms = float(np.median(pre_windows)) if pre_windows else slo_ms
    thresh_ms = max(3.0 * baseline_ms, slo_ms)
    recovered_in = None
    if killed_at is not None:
        for w in sorted(p99):
            if w * window_s < killed_at:
                continue
            if p99[w] <= thresh_ms:
                recovered_in = round(w * window_s - killed_at + window_s, 2)
                break
    ok = (killed_at is not None and recovered_in is not None
          and recovered_in <= recover_window_s and hangs == 0
          and healthy >= replicas)
    rec = {"metric": "serve_slo_killrestore", "replicas": replicas,
           "value": recovered_in if recovered_in is not None else -1.0,
           "unit": "p99_recovery_seconds",
           "killed_at_s": round(killed_at, 2) if killed_at else None,
           "baseline_p99_ms": round(baseline_ms, 3),
           "threshold_ms": round(thresh_ms, 3),
           "windows_p99_ms": {("%.1fs" % (w * window_s)): round(v, 2)
                              for w, v in p99.items()},
           "hangs": hangs, "expired": expired, "shed": shed,
           "healthy_final": healthy, "final_states": states,
           "replaced": any(r.index >= replicas for r in rset.replicas),
           "ok": ok}
    emit(rec)
    return rec


def run_replicas(rset, spec, n_requests=400, workers=4, max_wait_ms=2.0,
                 kill_frac=0.5, kill_replica=0, result_timeout=60.0,
                 emit=_emit):
    """The kill-one-replica-mid-run sweep (ISSUE 8 acceptance): a
    closed-loop burst through the ReplicaDispatcher; at ``kill_frac`` of
    the run, ``kill_replica`` is quarantined with an hour-long backoff —
    a dead chip, as far as this run is concerned. Emits per-replica
    dispatch counts and a hang count (futures that never completed
    within ``result_timeout``): the gate is hangs == 0 — every request
    re-routes, sheds, or expires, none strand."""
    from mxtpu import telemetry
    from mxtpu.serving import DeadlineExceeded, QueueFull
    from mxtpu.serving.replicas import ReplicaDispatcher

    n_rep = len(rset.replicas)
    disp0 = dict(telemetry.tagged("serving.replica.dispatches"))
    bat = ReplicaDispatcher(rset, max_batch_size=spec.max_batch,
                            max_wait_ms=max_wait_ms, max_queue=4096)
    dim = rset.input_templates[0][0][0]
    lock = threading.Lock()
    stats = {"completed": 0, "items": 0, "shed": 0, "expired": 0,
             "errors": 0, "hangs": 0, "submitted": 0}
    kill_at = max(1, int(n_requests * kill_frac))

    def client(k, n):
        rng = np.random.RandomState(300 + k)
        for _ in range(n):
            with lock:
                stats["submitted"] += 1
                fire_kill = stats["submitted"] == kill_at
            if fire_kill and n_rep > 1:
                bat.quarantine_replica(kill_replica, backoff_s=3600.0)
            sz = int(rng.randint(1, max(2, spec.max_batch // 2)))
            x = rng.randn(sz, dim).astype(np.float32)
            try:
                fut = bat.submit(x, deadline_ms=result_timeout * 1e3)
            except QueueFull:
                with lock:
                    stats["shed"] += 1
                continue
            try:
                fut.result(timeout=result_timeout)
            except DeadlineExceeded:
                with lock:
                    # a future that timed out WITHOUT completing is a
                    # hang — the exact failure this subsystem exists to
                    # prevent; a completed-with-expiry is bounded behavior
                    stats["hangs" if not fut.done() else "expired"] += 1
            except Exception:  # noqa: BLE001 — shed-at-dispatch etc.
                with lock:
                    stats["errors" if fut.done() and not isinstance(
                        fut._error, QueueFull) else "shed"] += 1
            else:
                with lock:
                    stats["completed"] += 1
                    stats["items"] += sz

    per = [n_requests // workers] * workers
    per[0] += n_requests - sum(per)
    threads = [threading.Thread(target=client, args=(k, n))
               for k, n in enumerate(per)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join(result_timeout + 60)
    wall = time.perf_counter() - t0
    bat.close(timeout=10)
    per_rep = {}
    for tag, v in telemetry.tagged("serving.replica.dispatches").items():
        d = v - disp0.get(tag, 0)
        if d:
            per_rep[tag] = d
    rec = {"metric": "serve_replicas", "replicas": n_rep,
           "value": round(stats["items"] / wall, 1), "unit": "items/sec",
           "requests": n_requests,
           "killed_replica": kill_replica if n_rep > 1 else None,
           "killed_at_request": kill_at if n_rep > 1 else None,
           "hangs": stats["hangs"], "errors": stats["errors"],
           "completed": stats["completed"], "shed": stats["shed"],
           "expired": stats["expired"],
           "per_replica_dispatches": per_rep,
           "wedges": telemetry.value("serving.replica.wedges"),
           "final_states": [s["state"] for s in bat.replica_states()]}
    emit(rec)
    return rec


def _zoo_models_default():
    """``BENCH_ZOO_MODELS``: distinct models registered by the zoo
    bench (the K in "K models over one device pool")."""
    return int(os.environ.get("BENCH_ZOO_MODELS", "4"))


def _zoo_devices_default():
    """``BENCH_ZOO_DEVICES``: device-pool size for the zoo bench
    (clamped to the visible devices)."""
    return int(os.environ.get("BENCH_ZOO_DEVICES", "2"))


def _zoo_requests_default():
    """``BENCH_ZOO_REQUESTS``: open-loop request count for the zoo
    bench's mixed-tenant load phase."""
    return int(os.environ.get("BENCH_ZOO_REQUESTS", "240"))


def _zoo_qps_default():
    """``BENCH_ZOO_QPS``: offered request rate for the zoo bench."""
    return float(os.environ.get("BENCH_ZOO_QPS", "60"))


def run_zoo(n_models=None, n_devices=None, n_requests=None, qps=None,
            deadline_ms=2000.0, dim=64, max_resident=None, emit=_emit):
    """The multi-tenant model-zoo acceptance run (ISSUE 20): K models
    multiplexed over a smaller device pool (``max_resident`` per device
    forces real paging pressure), skewed mixed-tenant open-loop load
    (gold=interactive, free=batch), and a mid-run rollout cycle —
    deploy a canary on the hottest model and PROMOTE it, deploy one on
    the second model and ROLL IT BACK — while traffic is in flight.

    Gates:

    * per-tenant goodput-at-SLO — gold attains >= 60% and is never
      materially worse than free (priority isolation held under churn);
    * page-in compiles == 0 — every post-warmup page-in (and both
      canary arm builds) is served from the compile cache: the
      ``retrace.serving.predict.zoo.*`` counters do not move;
    * zero hung futures — every submitted request resolves (result or
      accounted shed), including the canary cohorts that were in flight
      across the promote and the rollback;
    * bounded churn — page-ins stay proportional to cold misses
      (coalescing held: no page-in storm), evictions <= page-ins.
    """
    import jax
    import mxtpu as mx
    from mxtpu import telemetry
    from mxtpu.gluon import nn
    from mxtpu.serving import BucketSpec, ModelZoo, QueueFull, ZooScheduler

    n_models = n_models or _zoo_models_default()
    n_devices = n_devices or _zoo_devices_default()
    n_requests = n_requests or _zoo_requests_default()
    qps = qps or _zoo_qps_default()
    devs = jax.devices()[:max(1, min(n_devices, len(jax.devices())))]
    if max_resident is None:
        # pool capacity 2: K models page through 2 resident slots — the
        # paging pressure the bench exists to measure — without the
        # capacity-1 degenerate case where the hot model itself thrashes
        max_resident = max(1, -(-2 // len(devs)))
    # evictions release executables (csvc.drop); the disk cache is what
    # makes the page-in BACK a no-compile event, so give the run one
    if not os.environ.get("MXTPU_COMPILE_CACHE_DIR"):
        import tempfile
        os.environ["MXTPU_COMPILE_CACHE_DIR"] = tempfile.mkdtemp(
            prefix="zoo_bench_cache_")

    zoo = ModelZoo()
    spec = BucketSpec.pow2(8)
    names = ["m%d" % i for i in range(n_models)]
    example = np.zeros((1, dim), np.float32)
    for i, name in enumerate(names):
        net = nn.HybridSequential(prefix="zoobench%d_" % i)
        with net.name_scope():
            net.add(nn.Dense(64, activation="relu"))
            net.add(nn.Dense(16))
        net.initialize()
        net(_as_nd(example))
        zoo.register(name, net, spec, example=example)
    sched = ZooScheduler(
        zoo, devices=devs, start=True, max_resident=max_resident,
        tenants={"gold": {"priority": "interactive",
                          "deadline_ms": deadline_ms},
                 "free": {"priority": "batch",
                          "deadline_ms": deadline_ms * 2}})
    try:
        t0 = time.perf_counter()
        for name in names:  # populate the compile cache once per model
            sched.ensure_resident(name)
        warm_s = time.perf_counter() - t0
        sites = ["retrace.serving.predict.zoo." + n for n in names]
        sites += [s + ".canary" for s in sites]
        compiles0 = sum(telemetry.value(s) for s in sites)
        emit({"metric": "zoo_warmup", "models": n_models,
              "devices": len(devs), "pool_capacity":
              max_resident * len(devs), "value": round(warm_s, 3),
              "unit": "s", "compiles": compiles0})

        # skewed popularity (head models hot, tail cold -> paging) and
        # a deterministic tenant mix
        weights = np.array([1.0 / (i + 1) ** 1.5 for i in range(n_models)])
        weights /= weights.sum()
        rng = np.random.RandomState(7)
        futs, sheds, cold_targets = [], {"zoo_cold": 0, "other": 0}, 0
        rollout = {"deploys": 0, "promotes": 0, "rollbacks": 0,
                   "errors": 0}

        def rollout_step(k):
            try:
                if k == n_requests // 4:
                    zoo.add_version(names[0], "v2")
                    sched.ensure_resident(names[0])
                    sched.deploy(names[0], "v2", canary_frac=0.5)
                    rollout["deploys"] += 1
                elif k == n_requests // 2:
                    sched.promote(names[0])
                    rollout["promotes"] += 1
                    zoo.add_version(names[1], "v2")
                    sched.ensure_resident(names[1])
                    sched.deploy(names[1], "v2", canary_frac=0.5)
                    rollout["deploys"] += 1
                elif k == (3 * n_requests) // 4:
                    # regress the live canary deterministically: the
                    # gate tick rules it a regression and the FULL
                    # auto-rollback drain runs under live traffic
                    os.environ["MXTPU_FAULT_INJECT"] = "canary_rollback@0"
                    deadline = time.monotonic() + 10.0
                    while (sched._residents[names[1]].canary is not None
                           and time.monotonic() < deadline):
                        time.sleep(0.01)
                    rollout["rollbacks"] += int(
                        telemetry.value("zoo.rollbacks", tag="injected"))
            except Exception as e:  # noqa: BLE001 — gate counts these
                rollout["errors"] += 1
                emit({"metric": "zoo_rollout_error", "at": k,
                      "error": "%s: %s" % (type(e).__name__, e)})

        interval = 1.0 / qps
        next_t = time.perf_counter()
        t_load = time.perf_counter()
        for k in range(n_requests):
            now = time.perf_counter()
            if now < next_t:
                time.sleep(next_t - now)
            next_t += interval
            rollout_step(k)
            model = names[int(rng.choice(n_models, p=weights))]
            if model not in sched._residents:
                cold_targets += 1
            tenant = "gold" if rng.rand() < 0.5 else "free"
            x = rng.randn(int(rng.randint(1, 5)), dim).astype(np.float32)
            try:
                futs.append((tenant, sched.submit(model, x, tenant=tenant)))
            except QueueFull as e:
                key = "zoo_cold" if "zoo_cold" in str(e) else "other"
                sheds[key] += 1
        load_s = time.perf_counter() - t_load

        deadline = time.monotonic() + 60.0
        while (any(not f.done() for _, f in futs)
               and time.monotonic() < deadline):
            time.sleep(0.02)
        hung = sum(1 for _, f in futs if not f.done())
        per_tenant = {"gold": [0, 0], "free": [0, 0]}
        for tenant, f in futs:
            hm = per_tenant[tenant]
            try:
                f.result(timeout=0.001)
                hm[0] += 1
            except Exception:  # noqa: BLE001 — miss/shed/hang all count
                hm[1] += 1
        att = {t: (hm[0] / max(1, hm[0] + hm[1]))
               for t, hm in per_tenant.items()}
        compile_delta = sum(telemetry.value(s) for s in sites) - compiles0
        pageins = sum(telemetry.tagged("zoo.pageins").values())
        evictions = sum(telemetry.tagged("zoo.evictions").values())
        churn_bound = n_models + rollout["deploys"] + \
            rollout["promotes"] + cold_targets + sheds["zoo_cold"]

        gates = {
            "tenant_slo": att["gold"] >= 0.6
            and att["gold"] >= att["free"] - 0.05,
            "pagein_compiles": compile_delta == 0,
            "no_hangs": hung == 0,
            "bounded_churn": evictions <= pageins <= churn_bound,
            "rollout": (rollout["errors"] == 0
                        and rollout["promotes"] >= 1
                        and rollout["rollbacks"] >= 1),
        }
        rec = {"metric": "zoo_load", "models": n_models,
               "devices": len(devs), "requests": n_requests,
               "offered_qps": qps,
               "value": round(sum(hm[0] for hm in per_tenant.values())
                              / max(load_s, 1e-9), 1),
               "unit": "goodput_rps",
               "attainment_gold": round(att["gold"], 4),
               "attainment_free": round(att["free"], 4),
               "pageins": pageins, "evictions": evictions,
               "rollbacks": sum(
                   telemetry.tagged("zoo.rollbacks").values()),
               "sheds": sheds, "hung": hung,
               "pagein_compiles": compile_delta,
               "churn_bound": churn_bound,
               "gates": gates, "ok": all(gates.values())}
        emit(rec)
        return rec
    finally:
        sched.close(timeout=30.0)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--mode", default="sweep,closed,open")
    ap.add_argument("--requests", type=int,
                    default=int(os.environ.get("BENCH_SERVE_REQUESTS", 500)))
    ap.add_argument("--max-batch", type=int,
                    default=int(os.environ.get("BENCH_SERVE_MAX_BATCH", 8)))
    ap.add_argument("--dim", type=int, default=256)
    ap.add_argument("--width", type=int, default=512)
    ap.add_argument("--depth", type=int, default=3)
    ap.add_argument("--workers", type=int, default=4)
    ap.add_argument("--max-wait-ms", type=float, default=2.0)
    ap.add_argument("--qps", default="100,300,1000")
    ap.add_argument("--deadline-ms", type=float, default=100.0)
    ap.add_argument("--sweep-iters", type=int, default=50)
    ap.add_argument("--replicas", type=int, default=1,
                    help="replica count for --mode replicas (0 = one per "
                         "visible device)")
    ap.add_argument("--kill-replica", type=int, default=0,
                    help="replica quarantined mid-run by --mode replicas "
                         "(-1 = no kill)")
    ap.add_argument("--decode-requests", type=int,
                    default=int(os.environ.get("BENCH_DECODE_REQUESTS",
                                               80)),
                    help="--mode decode sequence count per phase")
    ap.add_argument("--decode-slots", type=int,
                    default=int(os.environ.get("BENCH_DECODE_SLOTS", 8)),
                    help="--mode decode cohort capacity (pow2 ladder)")
    ap.add_argument("--decode-max-new", type=int,
                    default=int(os.environ.get("BENCH_DECODE_MAX_NEW", 32)),
                    help="--mode decode per-sequence generation budget cap")
    ap.add_argument("--decode-qps", default="20,60,200",
                    help="--mode decode open-loop offered request rates")
    ap.add_argument("--slo-requests", type=int, default=200,
                    help="--mode slo requests per overload point")
    ap.add_argument("--slo-ms", type=float, default=0.0,
                    help="--mode slo deadline (0 = auto-calibrate to ~6x "
                         "the calibration run's loaded median)")
    ap.add_argument("--slo-replicas", type=int, default=0,
                    help="--mode slo replica count for BOTH routers "
                         "(0 = min(2, visible devices))")
    ap.add_argument("--slo-factors", default="1.5,3,8",
                    help="--mode slo offered-load multiples of calibrated "
                         "capacity")
    ap.add_argument("--slo-no-kill", action="store_true",
                    help="--mode slo: skip the kill/restore sweep")
    ap.add_argument("--zoo-models", type=int, default=0,
                    help="--mode zoo model count (0 = BENCH_ZOO_MODELS)")
    ap.add_argument("--zoo-requests", type=int, default=0,
                    help="--mode zoo open-loop request count "
                         "(0 = BENCH_ZOO_REQUESTS)")
    ap.add_argument("--zoo-qps", type=float, default=0.0,
                    help="--mode zoo offered rate (0 = BENCH_ZOO_QPS)")
    args = ap.parse_args(argv)

    modes = {m.strip() for m in args.mode.split(",") if m.strip()}
    ok = True
    if "zoo" in modes:
        rec = run_zoo(n_models=args.zoo_models or None,
                      n_requests=args.zoo_requests or None,
                      qps=args.zoo_qps or None)
        ok = ok and rec["ok"]
    if "slo" in modes:
        rec = run_slo(
            replicas=args.slo_replicas or None,
            n_requests=args.slo_requests,
            slo_ms=args.slo_ms or None,
            qps_factors=tuple(float(f) for f in
                              args.slo_factors.split(",") if f),
            kill=not args.slo_no_kill)
        ok = ok and rec["ok"]
    if "decode" in modes:
        rec = run_decode(n_requests=args.decode_requests,
                         slots=args.decode_slots,
                         max_new=args.decode_max_new)
        ok = ok and rec["ok"]
        run_decode_open(
            qps_list=[float(q) for q in args.decode_qps.split(",") if q],
            n_requests=min(args.decode_requests, 60),
            slots=args.decode_slots,
            max_new=min(args.decode_max_new, 16))
    single = modes - {"replicas", "decode", "slo", "zoo"}
    if single:
        pred, spec = build_predictor(dim=args.dim, width=args.width,
                                     depth=args.depth,
                                     max_batch=args.max_batch)
        _emit({"metric": "serve_warmup", "buckets": len(spec),
               "value": len(spec), "unit": "compiled_buckets"})
        if "sweep" in modes:
            _, monotonic = run_sweep(pred, spec, iters=args.sweep_iters)
            ok = ok and monotonic
        if "closed" in modes:
            rec = run_closed(pred, spec, n_requests=args.requests,
                             workers=args.workers,
                             max_wait_ms=args.max_wait_ms)
            ok = ok and rec["compiles"] <= rec["buckets"] \
                and rec["watchdog_trips"] == 0
            if rec["breakdown_ok"] is not None:
                ok = ok and rec["breakdown_ok"]
        if "open" in modes:
            run_open(pred, spec,
                     qps_list=[float(q) for q in args.qps.split(",") if q],
                     n_requests=args.requests, deadline_ms=args.deadline_ms,
                     max_wait_ms=args.max_wait_ms)
    if "replicas" in modes:
        import jax
        n = args.replicas or len(jax.devices())
        if n > len(jax.devices()):
            _emit({"metric": "serve_replicas", "error":
                   "%d replicas > %d devices" % (n, len(jax.devices()))})
            return 1
        if args.kill_replica >= n:
            # an out-of-range kill would IndexError inside a client
            # thread and let the gate pass on a truncated run
            _emit({"metric": "serve_replicas", "error":
                   "--kill-replica %d out of range for %d replicas"
                   % (args.kill_replica, n)})
            return 1
        rset, spec = build_replica_set(dim=args.dim, width=args.width,
                                       depth=args.depth,
                                       max_batch=args.max_batch, replicas=n)
        _emit({"metric": "serve_replicas_warmup", "replicas": n,
               "value": n * len(spec), "unit": "compiled_buckets"})
        rec = run_replicas(rset, spec, n_requests=args.requests,
                           workers=args.workers,
                           max_wait_ms=args.max_wait_ms,
                           kill_replica=args.kill_replica,
                           kill_frac=0.5 if args.kill_replica >= 0
                           else 2.0)  # >1.0 frac: the kill never fires
        ok = ok and rec["hangs"] == 0 and rec["errors"] == 0
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
