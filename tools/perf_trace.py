"""Capture a jax.profiler device trace of the resnet50 train step and print
per-op time aggregates (PERF.md evidence).

WARNING: device profiling through the axon tunnel can WEDGE THE CHIP for
every subsequent process if this script is killed mid-trace (observed: a
timeout during jax.profiler.trace left even trivial jit dispatches hanging
until the server-side lease recovered, ~hours). Prefer the scan-fusion
timing tools (perf_peak/perf_stages/perf_bisect); run this only when
nothing else needs the chip and never under a watchdog that SIGKILLs."""
import glob
import gzip
import os
import sys
import time
from collections import defaultdict

import numpy as np
import jax
import jax.numpy as jnp

LOGDIR = "/tmp/mxtpu_trace"


def build_step():
    from mxtpu import gluon
    from mxtpu.parallel import pure_forward
    from mxtpu.ndarray import NDArray
    from perf_common import build_resnet

    batch = int(os.environ.get("BENCH_BATCH", "128"))
    net, x, yl = build_resnet(batch)
    fn_t, params_t = pure_forward(net, train=True)
    loss_blk = gluon.loss.SoftmaxCrossEntropyLoss()

    def loss_of(p, xd, yd):
        out = fn_t(p, xd)
        return jnp.mean(loss_blk(NDArray(out), NDArray(yd))._data)

    @jax.jit
    def step(p, xd, yd):
        l, g = jax.value_and_grad(loss_of)(p, xd, yd)
        return [(w - 0.01 * gw.astype(w.dtype)) for w, gw in zip(p, g)], l

    return step, params_t, x._data, yl._data


def main():
    step, p, xd, yd = build_step()
    newp, l = step(p, xd, yd)
    float(l)  # ensure compiled + executed

    os.system("rm -rf %s" % LOGDIR)
    with jax.profiler.trace(LOGDIR):
        for _ in range(3):
            newp, l = step(p, xd, yd)
        float(l)

    # parse the xplane protobuf with the tensorboard plugin
    from tensorboard_plugin_profile.convert import raw_to_tool_data

    files = glob.glob(LOGDIR + "/**/*.xplane.pb", recursive=True)
    print("xplane files:", files)
    if not files:
        return
    data, _ = raw_to_tool_data.xspace_to_tool_data(files, "framework_op_stats",
                                                   {})
    out = LOGDIR + "/op_stats.csv"
    blob = data if isinstance(data, (bytes, str)) else data[0]
    if isinstance(blob, bytes):
        blob = blob.decode()
    with open(out, "w") as f:
        f.write(blob)
    print("wrote", out)
    # print top rows
    import csv
    rows = list(csv.DictReader(blob.splitlines()))
    rows.sort(key=lambda r: -float(r.get("total_self_time_in_us") or
                                   r.get("self_time.2c_us") or 0))
    for r in rows[:25]:
        print(r)


if __name__ == "__main__":
    main()
