"""Capture a jax.profiler device trace of the resnet50 train step and print
per-op time aggregates (PERF.md evidence).

Device profiling through the axon tunnel WEDGED THE CHIP in round 3 when a
watchdog killed the process mid-trace (every later dispatch hung for hours).
The capture now goes through mxtpu.profiler's guarded path — bounded
duration (TRACE_MAX_S), atexit/SIGTERM stop — and the recommended launch is

    python tools/safe_trace.py tools/perf_trace.py

which adds child-process isolation + an orphan guard, so no single SIGKILL
can leave the trace running. Prefer the scan-fusion timing tools
(perf_peak/perf_stages/perf_bisect) when per-HLO data isn't needed."""
import glob
import gzip
import os
import sys
import time
from collections import defaultdict

import numpy as np
import jax
import jax.numpy as jnp

LOGDIR = "/tmp/mxtpu_trace"


def build_step():
    from mxtpu import gluon
    from mxtpu.parallel import pure_forward
    from mxtpu.ndarray import NDArray
    from perf_common import build_resnet

    batch = int(os.environ.get("BENCH_BATCH", "128"))
    net, x, yl = build_resnet(batch)
    fn_t, params_t = pure_forward(net, train=True)
    loss_blk = gluon.loss.SoftmaxCrossEntropyLoss()

    def loss_of(p, xd, yd):
        out = fn_t(p, xd)
        return jnp.mean(loss_blk(NDArray(out), NDArray(yd))._data)

    @jax.jit
    def step(p, xd, yd):
        l, g = jax.value_and_grad(loss_of)(p, xd, yd)
        return [(w - 0.01 * gw.astype(w.dtype)) for w, gw in zip(p, g)], l

    return step, params_t, x._data, yl._data


def main():
    step, p, xd, yd = build_step()
    newp, l = step(p, xd, yd)
    float(l)  # ensure compiled + executed

    os.system("rm -rf %s" % LOGDIR)
    from mxtpu import profiler
    profiler.set_config(filename=LOGDIR + "/host.json", profile_xla=True,
                        xla_trace_dir=LOGDIR,
                        xla_trace_max_s=float(os.environ.get("TRACE_MAX_S",
                                                             "120")))
    profiler.start()
    try:
        for _ in range(3):
            newp, l = step(p, xd, yd)
        float(l)
    finally:
        profiler.stop()

    files = glob.glob(LOGDIR + "/**/*.xplane.pb", recursive=True)
    print("xplane files:", files)
    if not files:
        return
    print_op_aggregates(files)


def print_op_aggregates(files, top=30):
    """Aggregate per-op device time straight from the xplane protobuf
    (tensorflow's bundled schema; the tensorboard-plugin converter in this
    image is broken against the installed protobuf/TF pair, and the schema
    itself — planes > lines > timed events — is all we need)."""
    os.environ.setdefault("PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION", "python")
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    agg = defaultdict(lambda: [0, 0.0])  # name -> [count, total_us]
    for path in files:
        xs = xplane_pb2.XSpace()
        with open(path, "rb") as f:
            xs.ParseFromString(f.read())
        # prefer device planes (/device:TPU:0 ...); fall back to the host
        # XLA executor lines when there is no device plane (CPU runs)
        planes = [p for p in xs.planes if "/device:" in p.name] or \
                 [p for p in xs.planes if any("XLA" in ln.name
                                              for ln in p.lines)]
        for p in planes:
            is_dev = "/device:" in p.name
            # a device plane carries envelope lines ('XLA Modules' spans
            # all its ops, 'Steps' spans the step) on top of the per-op
            # line — summing every line would count each us ~3x
            dev_lines = [ln for ln in p.lines if "XLA Ops" in ln.name] or \
                        [ln for ln in p.lines
                         if "Modules" not in ln.name and
                         "Steps" not in ln.name and "Source" not in ln.name]
            for ln in (dev_lines if is_dev else p.lines):
                if not is_dev and "XLA" not in ln.name:
                    continue
                for ev in ln.events:
                    name = p.event_metadata[ev.metadata_id].name
                    a = agg[name]
                    a[0] += 1
                    a[1] += ev.duration_ps / 1e6
    rows = sorted(agg.items(), key=lambda kv: -kv[1][1])[:top]
    total = sum(v[1] for _, v in agg.items())
    print("%-72s %8s %12s %6s" % ("op", "calls", "total_us", "%"))
    for name, (cnt, us) in rows:
        print("%-72s %8d %12.1f %6.2f"
              % (name[:72], cnt, us, 100 * us / max(total, 1e-9)))
    print("total device-time us:", round(total, 1))


if __name__ == "__main__":
    main()
