#!/bin/bash
# The on-chip measurement backlog, ONE PJRT SESSION edition.
#
# Round-4 on-chip evidence: the tunnel wedged on the 4th-6th client
# session of the morning (probe + 2x bench each spawning a preflight
# subprocess = ~6 sessions in 10 min; the next process hung at its
# first dispatch and the wedge persisted for hours). Sessions are the
# scarce resource, so the whole battery now runs inside a single
# process (tools/perf_session.py) that prints+flushes each result as
# it lands — a mid-session wedge costs the tail, not the data already
# taken. Serialize: never run two TPU processes at once.
set -u
cd "$(dirname "$0")/.."
LOG=${1:-perf_battery.log}
export MXTPU_COMPILE_CACHE=${MXTPU_COMPILE_CACHE:-/tmp/mxtpu_compile_cache}
# runtime telemetry (mxtpu/telemetry.py): every phase's spans/counters
# stream to one JSONL artifact; tools/telemetry_report.py folds it into
# the aggregate table after each session below. The periodic off-thread
# flush matters HERE specifically: every session runs under `timeout`,
# whose SIGTERM skips python atexit — without it a wedged/overrun session
# (exactly the failure the timeouts exist for) would lose its telemetry.
TELEMETRY_JSONL=${TELEMETRY_JSONL:-telemetry_battery.jsonl}
export MXTPU_TELEMETRY="$TELEMETRY_JSONL"
export MXTPU_TELEMETRY_FLUSH_S=${MXTPU_TELEMETRY_FLUSH_S:-30}

telemetry_report() {
  # --ledger (ISSUE 12): the per-jit-site roofline table — cost-model
  # intensity vs the chip ridge, memory-bound Pallas candidates ranked —
  # dumped after every session so each battery artifact carries the
  # standing fusion-gap report next to the latency table
  [ -s "$TELEMETRY_JSONL" ] && \
    python tools/telemetry_report.py "$TELEMETRY_JSONL" --ledger \
      2>&1 | tee -a "$LOG"
}

# -1. trace-discipline gate (pure-AST, no jax import, no TPU session): an
#     unkeyed policy lever or an unregistered jit cache invalidates every
#     A/B below — fail fast before burning a scarce chip session on it.
python -m tools.graftlint mxtpu/ 2>&1 | tee -a "$LOG"
[ "${PIPESTATUS[0]}" -eq 0 ] || {
  echo "GRAFTLINT FAILED — fix findings before spending a TPU session" \
    | tee -a "$LOG"; exit 1; }

# -0.5. measured block-plan tuning session (ISSUE 17, docs/autotune.md),
#    AHEAD of the kernel benches so conv_class/flash_class and any
#    MXTPU_AUTOTUNE=1 phase can serve the persisted plans. Pinned to the
#    CPU host tier (interpret-mode candidates, chip-safe: zero TPU
#    sessions burned) and wall-bounded per search. If a previous
#    battery's ledger JSONL is still on disk, it is folded into a
#    ranked tuning queue first (observe -> tune -> persist -> serve);
#    a missing ledger just means registry-ordered kernels.
export MXTPU_COMPILE_CACHE_DIR=${MXTPU_COMPILE_CACHE_DIR:-/tmp/mxtpu_compile_cache_dir}
AUTOTUNE_QUEUE=""
[ -s "$TELEMETRY_JSONL" ] && {
  python tools/telemetry_report.py "$TELEMETRY_JSONL" --ledger \
    --tuning-queue tuning_queue.json >/dev/null 2>&1 \
    && AUTOTUNE_QUEUE="--queue tuning_queue.json"
}
timeout 600 env JAX_PLATFORMS=cpu \
  MXTPU_AUTOTUNE_BUDGET_S=${MXTPU_AUTOTUNE_BUDGET_S:-20} \
  python tools/autotune_session.py $AUTOTUNE_QUEUE --limit 8 \
  2>&1 | grep --line-buffered -v WARNING | tee -a "$LOG"

# 0. is the chip alive? (90 s; bail early if wedged). This is the ONLY
#    extra session besides the battery itself.
timeout 90 python -c "
import jax, jax.numpy as jnp, numpy as np
np.asarray(jax.device_get(jax.jit(lambda v: v+1)(jnp.ones(2))))
print('chip alive')" || { echo "CHIP WEDGED — aborting battery"; exit 1; }

# grace: let the probe's session release fully before the battery claims
sleep 20

# 1. everything, one session, most valuable phases first (resnet50
#    control → each lever → stage attribution → BN microtiming → peak →
#    eager/lstm/bert). stdbuf keeps the tee line-live so a killed run
#    still shows where it died.
# explicit value-ranked phase order (arg order = run order): the new
# staged lever and the headline configs first, known-stable re-checks
# last, so a mid-session wedge costs the least valuable tail. The
# trailing 'rest' sentinel expands to any phase not named above, so a
# phase added to perf_session.py is never silently unmeasured.
timeout "${SESSION_TIMEOUT:-3600}" stdbuf -oL -eL \
  python -u tools/perf_session.py \
    probe resnet_pallas conv_class resnet_s2d2 resnet_pallas_s2d2 resnet_im2col resnet_s2d2_im2col resnet_best bert_pad_ab flash_pad lstm_hoist_ab \
    resnet_control resnet_bn_onepass resnet_all_levers stem_breakdown \
    rest \
    2>&1 | grep --line-buffered -v WARNING | tee -a "$LOG"
telemetry_report

# 2. lower-priority extras, each its own session, spaced by a release
#    grace period (observed: back-to-back claims correlate with wedges)
sleep 60
timeout 1200 python tools/benchmark_score.py 2>&1 | grep --line-buffered -v WARNING | tee -a "$LOG"
telemetry_report
sleep 60
timeout 900 env PYTHONPATH=.:/root/.axon_site python tools/bandwidth.py \
  --sizes-mb 16,64 2>&1 | grep --line-buffered -v WARNING | tee -a "$LOG"
telemetry_report

# 3. serving phase (ISSUE 5): batch-bucket sweep + closed-loop + offered-QPS
#    overload curve against the in-process Predictor — the inference-side
#    numbers (items/s per bucket, p99 under load, shed behaviour; ISSUE 10:
#    the closed-loop line carries the per-stage p99 breakdown + the
#    stages-sum-to-e2e 5% gate)
sleep 60
timeout 600 python tools/serve_bench.py --requests 500 \
  2>&1 | grep --line-buffered -v WARNING | tee -a "$LOG"
telemetry_report

# 3b. telemetry/tracing overhead phase (ISSUE 4 + ISSUE 10): steps/s with
#     the layer off vs spans-on vs spans+causal-tracing-on, alternating
#     rounds — the <1% budget judged where it matters, on the chip. The
#     per-trace critical-path view of the battery's own artifact follows.
sleep 60
timeout 900 env BENCH_CONFIG=telemetry_overhead BENCH_PREFLIGHT=0 \
  python bench.py 2>&1 | grep --line-buffered -v WARNING | tee -a "$LOG"
[ -s "$TELEMETRY_JSONL" ] && \
  python tools/telemetry_report.py "$TELEMETRY_JSONL" --traces 10 --ledger \
    2>&1 | tee -a "$LOG"

# 3c. training survivability overhead phase (ISSUE 14): steps/s with the
#     full integrity stack on (step-wedge watchdog + divergence sentinel
#     + health monitor, alternating off/on rounds) vs off — the <2%
#     guard budget judged on-chip, with a JSON gate summary (overhead
#     budget, retrace-flat, sentinel-really-checked, zero wedges).
sleep 60
timeout 900 env BENCH_CONFIG=integrity_overhead BENCH_PREFLIGHT=0 \
  python bench.py 2>&1 | grep --line-buffered -v WARNING | tee -a "$LOG"
telemetry_report

# 4. multichip scaling phase (ISSUE 7): mesh-native gluon Trainer items/s
#    per device count (strong scaling, ZeRO-1 on). Only meaningful with
#    >1 device; on a single chip the check below skips the session. The
#    scaling-number gate applies on-chip; the forced-host-device tier
#    gates on parity + compile budget instead (see bench_multichip_resnet).
sleep 60
if timeout 90 python -c "import jax,sys; sys.exit(0 if len(jax.devices())>1 else 1)"; then
  timeout 900 env BENCH_CONFIG=multichip_resnet BENCH_PREFLIGHT=0 \
    python bench.py 2>&1 | grep --line-buffered -v WARNING | tee -a "$LOG"
  telemetry_report
else
  echo "multichip_resnet skipped: single device" | tee -a "$LOG"
fi

# 5. replica serving phase (ISSUE 8): ReplicaSet router under a
#    kill-one-replica-mid-run sweep — per-replica throughput + hang count
#    JSON (the gate: hangs == 0 through the replica loss). Only
#    meaningful with >1 device; a single chip has nothing to fail over to.
sleep 60
if timeout 90 python -c "import jax,sys; sys.exit(0 if len(jax.devices())>1 else 1)"; then
  timeout 600 python tools/serve_bench.py --mode replicas --replicas 0 \
    --requests 400 2>&1 | grep --line-buffered -v WARNING | tee -a "$LOG"
  telemetry_report
else
  echo "replica serving skipped: single device" | tee -a "$LOG"
fi

# 5b. continuous-batching decode phase (ISSUE 11): prefill/decode split +
#     KV-slot cohort on the tiny causal LM — continuous vs restart-per-batch
#     tokens/s at equal capacity, int8 parity + KV-bytes gates, then the
#     open-loop TTFT overload curve with the prefill/decode stage split.
#     Runs on whatever platform is live (the decode loop is pure replay, so
#     it is chip-safe: compiles all happen in one warmup block up front).
sleep 60
timeout 600 python tools/serve_bench.py --mode decode \
  2>&1 | grep --line-buffered -v WARNING | tee -a "$LOG"
telemetry_report

# 5c. SLO control-plane phase (ISSUE 13): goodput-at-SLO overload curve —
#     predictive-admission controller vs the static depth-shed router at
#     equal replicas (gate: the controller strictly wins >= 1 overload
#     point) — plus the kill/restore sweep: a replica quarantined as a
#     dead chip must be REPLACED by the autoscaler with windowed p99
#     recovering inside the bounded window and zero hung futures (the
#     script itself skips the kill sweep on a single device).
sleep 60
timeout 600 python tools/serve_bench.py --mode slo \
  2>&1 | grep --line-buffered -v WARNING | tee -a "$LOG"
telemetry_report

# 5c2. model-zoo phase (ISSUE 20): K models multiplexed over a smaller
#      device pool under skewed mixed-tenant load with a mid-run canary
#      deploy+promote and deploy+rollback cycle (gates: per-tenant
#      goodput-at-SLO with priority isolation, page-in compiles == 0 off
#      the warm cache, zero hung futures across the rollout, bounded
#      eviction/page-in churn). Compiles happen once in the warmup
#      block; later page-ins are cache replays — chip-safe on any pool.
sleep 60
timeout 600 python tools/serve_bench.py --mode zoo \
  2>&1 | grep --line-buffered -v WARNING | tee -a "$LOG"
telemetry_report

# 5d. startup-time phase (ISSUE 15): cold-start vs warm-disk-cache wall
#     time for a Trainer first step and a Predictor replica warmup, each
#     in a fresh process against one MXTPU_COMPILE_CACHE_DIR (gates:
#     warm start compiles == 0 watchdog-pinned, the disk served, and the
#     warm wall is strictly lower; vs_baseline = worst-scenario
#     cold/warm speedup). Host work + child processes — chip-safe.
sleep 60
timeout 900 env BENCH_CONFIG=startup_time BENCH_PREFLIGHT=0 \
  python bench.py 2>&1 | grep --line-buffered -v WARNING | tee -a "$LOG"
telemetry_report

# 5e. elastic fleet phase (ISSUE 18): kill-one-host restore parity +
#     warm rejoin, every host a forced-CPU subprocess (gates: loud
#     41/42 kill detection, resume-at-K parity vs the uninterrupted
#     oracle, divergence sentinel green, rejoin zero compiles with the
#     disk cache serving every host). Host work + children — chip-safe.
sleep 60
timeout 900 env BENCH_CONFIG=fleet_resume BENCH_PREFLIGHT=0 \
  python bench.py 2>&1 | grep --line-buffered -v WARNING | tee -a "$LOG"
telemetry_report

# 6. input pipeline phase (ISSUE 9): device-resident streaming reader +
#    double-buffered prefetch-to-device vs the synchronous loop — batches/s
#    and the data.wait fraction both ways (gate: parity + wait-frac drop;
#    vs_baseline = overlapped speedup where the host has cores to overlap
#    on). Host work dominates, so this phase is chip-safe even when the
#    tunnel is suspect.
sleep 60
timeout 600 env BENCH_CONFIG=input_pipeline BENCH_PREFLIGHT=0 \
  python bench.py 2>&1 | grep --line-buffered -v WARNING | tee -a "$LOG"
telemetry_report

echo "battery complete -> $LOG"
