#!/bin/bash
# The ordered on-chip measurement backlog (PERF.md "staged levers").
# Run FIRST THING in a session with a healthy chip; each step is
# independently useful and the order front-loads the headline numbers.
# Serialize: never run two TPU processes at once (see PERF.md outage note).
set -u
cd "$(dirname "$0")/.."
LOG=${1:-perf_battery.log}
# warm compiles across the battery's processes (tunnel compiles cost minutes)
export MXTPU_COMPILE_CACHE=${MXTPU_COMPILE_CACHE:-/tmp/mxtpu_compile_cache}
run() {
  echo "=== $* ($(date +%H:%M:%S)) ===" | tee -a "$LOG"
  timeout "${STEP_TIMEOUT:-1200}" "$@" 2>&1 | grep -v WARNING | tee -a "$LOG"
}

# 0. is the chip alive? (90s; bail early if wedged)
timeout 90 python -c "
import jax, jax.numpy as jnp, numpy as np
np.asarray(jax.device_get(jax.jit(lambda v: v+1)(jnp.ones(2))))
print('chip alive')" || { echo "CHIP WEDGED — aborting battery"; exit 1; }

# 1. headline: resnet50 with the f32-accumulate conv path (round-3 change)
BENCH_CONFIG=resnet50 run python bench.py

# 2. the space-to-depth stem variant (exactly-equivalent; compare to #1)
BENCH_CONFIG=resnet50 BENCH_S2D_STEM=1 run python bench.py

# 3. localize the slow forward (stage-by-stage attribution)
run env PYTHONPATH=.:tools:/root/.axon_site python tools/perf_stages.py

# 4. BatchNorm attribution (round-4 lever): TPU HLO fusion structure +
#    measured conv vs conv+bn cost, two-pass vs one-pass stats
run env PYTHONPATH=.:/root/.axon_site python tools/perf_bn.py
MXTPU_BN_ONEPASS=1 run env PYTHONPATH=.:/root/.axon_site python tools/perf_bn.py

# 5. resnet50 with one-pass BN stats end-to-end (compare to #1)
BENCH_CONFIG=resnet50 MXTPU_BN_ONEPASS=1 run python bench.py

# 6. all scoring configs (lstm/bert should gain from dot f32-accumulate;
#    includes the never-yet-measured eager number — VERDICT r3 #9)
run python bench.py

# 7. validate the ceiling numbers post-fix
run env PYTHONPATH=.:tools:/root/.axon_site python tools/perf_peak.py
run env PYTHONPATH=.:tools:/root/.axon_site python tools/perf_conv_acc.py

# 8. zoo inference scoring sweep (reference benchmark_score tables)
BENCH_BATCHES=1,32,128 run python tools/benchmark_score.py

# 9. communication bandwidth (tools/bandwidth kit; single chip: h2d/d2h)
run env PYTHONPATH=.:/root/.axon_site python tools/bandwidth.py --sizes-mb 16,64

echo "battery complete -> $LOG"
