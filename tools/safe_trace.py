"""Supervise a device-profiling workload so an interrupted capture cannot
wedge the chip.

A jax device trace whose client dies mid-capture can leave a remote TPU
unresponsive server-side for hours (observed through the axon tunnel; the
reference's C++ profiler is always-stoppable — src/profiler/profiler.h:256-437
— and never has this failure mode). This tool is the TPU analog: it runs the
workload in a CHILD process wired so that every way the capture can be
interrupted still sends ``stop_trace``:

* normal completion           -> the workload's own profiler.stop()
* workload hangs              -> mxtpu.profiler's bounded-duration watchdog
                                 (``xla_trace_max_s``, default 120 s)
* supervisor timeout          -> SIGTERM to the child; the profiler's signal
                                 handler stops the trace before exiting
* supervisor itself SIGKILLed -> the child's orphan guard notices the parent
                                 change and stops the trace
* child SIGKILLed externally  -> the one unguardable route; the bounded
                                 watchdog has usually already fired by then

Usage::

    python tools/safe_trace.py [--timeout S] script.py [args...]

The script runs unmodified (``runpy``, ``__name__ == "__main__"``); use
``mxtpu.profiler`` with ``profile_xla=True`` (e.g. tools/perf_trace.py) so
the capture goes through the guarded start/stop path.
"""
import argparse
import os
import signal
import subprocess
import sys
import time

_BOOTSTRAP = (
    "import runpy, sys;"
    "sys.path.insert(0, %(repo)r);"
    "from mxtpu import profiler;"
    "profiler.install_orphan_guard();"
    "sys.argv = sys.argv[1:];"
    "runpy.run_path(sys.argv[0], run_name='__main__')"
)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--timeout", type=float, default=600.0,
                    help="seconds before the child is asked to stop")
    ap.add_argument("--grace", type=float, default=60.0,
                    help="seconds between SIGTERM and SIGKILL")
    ap.add_argument("script")
    ap.add_argument("args", nargs=argparse.REMAINDER)
    ns = ap.parse_args(argv)

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    child = subprocess.Popen(
        [sys.executable, "-u", "-c", _BOOTSTRAP % {"repo": repo},
         ns.script] + ns.args)
    deadline = time.time() + ns.timeout
    try:
        while child.poll() is None and time.time() < deadline:
            time.sleep(0.5)
        if child.poll() is None:
            print("safe_trace: timeout after %.0fs — SIGTERM (trace stops "
                  "in the child's handler)" % ns.timeout, file=sys.stderr)
            child.send_signal(signal.SIGTERM)
            t0 = time.time()
            while child.poll() is None and time.time() - t0 < ns.grace:
                time.sleep(0.5)
            if child.poll() is None:
                # by now the bounded-duration watchdog and the SIGTERM
                # handler have both had their chance; SIGKILL is safe
                print("safe_trace: SIGKILL after %.0fs grace" % ns.grace,
                      file=sys.stderr)
                child.kill()
    except KeyboardInterrupt:
        # forward ^C as SIGTERM so the child's handler stops the trace
        child.send_signal(signal.SIGTERM)
        child.wait()
        raise
    return child.wait()


if __name__ == "__main__":
    sys.exit(main())
