"""Parse a training log into a table (ref: tools/parse_log.py).

Works on this framework's logs, whose lines use the reference's exact
formats (callback.py Speedometer "Epoch[N] Batch [B]\tSpeed: S
samples/sec", base_module "Epoch[N] Train-metric=V" /
"Epoch[N] Validation-metric=V", and "Time cost=T").

Usage:
    python tools/parse_log.py train.log [--format markdown|csv|none]
"""
import argparse
import re
import sys
from collections import defaultdict


def parse(lines):
    """-> (sorted epoch list, {epoch: {column: value}}) with mean speed."""
    rows = defaultdict(dict)
    speeds = defaultdict(list)
    for line in lines:
        m = re.search(r"Epoch\[(\d+)\]", line)
        if m is None:
            continue
        epoch = int(m.group(1))
        s = re.search(r"Speed: ([\d.]+) samples/sec", line)
        if s:
            speeds[epoch].append(float(s.group(1)))
        for name, val in re.findall(
                r"(Train-[^=\s]+|Validation-[^=\s]+)=([\d.eE+-]+|-?nan|-?inf)", line):
            rows[epoch][name] = float(val)
        t = re.search(r"Time cost=([\d.]+)", line)
        if t:
            rows[epoch]["time"] = float(t.group(1))
    for epoch, vals in speeds.items():
        rows[epoch]["speed"] = sum(vals) / len(vals)
    return sorted(rows), dict(rows)


def render(epochs, rows, fmt):
    cols = sorted({c for r in rows.values() for c in r})
    header = ["epoch"] + cols
    out = []
    if fmt == "markdown":
        out.append("| " + " | ".join(header) + " |")
        out.append("|" + "---|" * len(header))
        pat = "| {} |"
        join = " | "
    elif fmt == "csv":
        out.append(",".join(header))
        pat = "{}"
        join = ","
    else:
        return ""
    for e in epochs:
        cells = [str(e)] + [("%g" % rows[e][c]) if c in rows[e] else ""
                            for c in cols]
        out.append(pat.format(join.join(cells)))
    return "\n".join(out)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("logfile", nargs=1)
    ap.add_argument("--format", default="markdown",
                    choices=("markdown", "csv", "none"))
    ns = ap.parse_args(argv)
    with open(ns.logfile[0]) as f:
        epochs, rows = parse(f)
    if not epochs:
        print("no Epoch[...] lines found", file=sys.stderr)
        return 1
    print(render(epochs, rows, ns.format))
    return 0


if __name__ == "__main__":
    sys.exit(main())
