"""Input-pipeline throughput benchmark (VERDICT r4 missing #1): can the
host decode+augment fast enough to feed the chip?

Measures, in decoded+augmented 224x224 images/sec:
  1. raw cv2 JPEG decode                      (the floor every pipeline shares)
  2. decode + standard training augmentation  (resize/crop/mirror/normalize,
     the ImageRecordIter v2 work: reference src/io/iter_image_recordio_2.cc:672)
  3. the same through ImageIter over an in-memory RecordIO pack
  4. gluon DataLoader with N multiprocess workers over a jpeg dataset

Prints one JSON line per measurement plus a feed-rate verdict against the
ResNet-50 north star (4,015 img/s needs ~0.6 GB/s of decoded pixels). On a
1-core host the per-core rate and the measured worker-scaling efficiency
are the honest numbers; the verdict extrapolates linearly with a measured
overlap coefficient, because decode parallelism across processes is what
the architecture provides (reference runs the same pipeline with
decode threads on a many-core trainer host).

Usage: python tools/perf_input_pipeline.py [--n 256] [--workers 4]
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
# This is a HOST pipeline benchmark: pin jax to CPU unconditionally (via
# jax.config — the axon sitecustomize overrides mere env vars), or a wedged
# TPU tunnel hangs the first array creation. Override only via
# MXTPU_BENCH_PLATFORM if you really want device arrays in the loop.
os.environ["JAX_PLATFORMS"] = os.environ.get("MXTPU_BENCH_PLATFORM", "cpu")
import jax  # noqa: E402

jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import numpy as np  # noqa: E402


def _jpegs(n, size=224, quality=90):
    import cv2
    rng = np.random.RandomState(0)
    bufs = []
    # natural-ish images (smooth gradients + noise) so jpeg work is realistic
    for i in range(8):
        yy, xx = np.mgrid[0:size, 0:size].astype(np.float32) / size
        img = np.stack([
            128 + 100 * np.sin(3 * yy + i) + rng.normal(0, 12, (size, size)),
            128 + 100 * np.cos(2 * xx + i) + rng.normal(0, 12, (size, size)),
            128 + 80 * np.sin(4 * (xx + yy)) + rng.normal(0, 12, (size, size)),
        ], axis=2).clip(0, 255).astype(np.uint8)
        ok, buf = cv2.imencode(".jpg", img,
                               [cv2.IMWRITE_JPEG_QUALITY, quality])
        assert ok
        bufs.append(buf.tobytes())
    return [bufs[i % len(bufs)] for i in range(n)]


def _bench(label, fn, n, unit="img/s"):
    t0 = time.perf_counter()
    fn()
    dt = time.perf_counter() - t0
    rate = n / dt
    print(json.dumps({"metric": "input_pipeline/%s" % label,
                      "value": round(rate, 1), "unit": unit,
                      "n": n, "seconds": round(dt, 3)}), flush=True)
    return rate


class JpegDataset:
    """Decode+augment dataset for DataLoader workers (module-level: spawn
    pickles it by value)."""

    def __init__(self, bufs, train=True):
        self.bufs = bufs
        self.train = train

    def __len__(self):
        return len(self.bufs)

    def __getitem__(self, i):
        import cv2
        img = cv2.imdecode(np.frombuffer(self.bufs[i], np.uint8),
                           cv2.IMREAD_COLOR)
        img = cv2.cvtColor(img, cv2.COLOR_BGR2RGB)
        rng = np.random.RandomState(i)
        if self.train:
            # random crop to 200 then resize back + mirror: the standard
            # augmenter stack's work profile
            y0, x0 = rng.randint(0, 24), rng.randint(0, 24)
            img = img[y0:y0 + 200, x0:x0 + 200]
            img = cv2.resize(img, (224, 224))
            if rng.rand() < 0.5:
                img = img[:, ::-1]
        out = img.astype(np.float32)
        out -= np.array([123.68, 116.779, 103.939], np.float32)
        return np.ascontiguousarray(out.transpose(2, 0, 1)), np.float32(i % 10)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=256)
    ap.add_argument("--workers", type=int, default=4)
    ap.add_argument("--batch", type=int, default=32)
    args = ap.parse_args()
    import cv2

    bufs = _jpegs(args.n)
    ds = JpegDataset(bufs)

    # 1. decode only
    def decode_all():
        for b in bufs:
            cv2.imdecode(np.frombuffer(b, np.uint8), cv2.IMREAD_COLOR)
    decode_rate = _bench("decode", decode_all, args.n)

    # 2. decode + augment (the full per-image host work)
    def aug_all():
        for i in range(len(ds)):
            ds[i]
    aug_rate = _bench("decode_augment", aug_all, args.n)

    # 3. ImageIter over an in-memory RecordIO pack
    import tempfile
    import mxtpu as mx
    from mxtpu import recordio
    with tempfile.TemporaryDirectory() as td:
        rec_path = os.path.join(td, "bench.rec")
        idx_path = os.path.join(td, "bench.idx")
        w = recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
        for i, b in enumerate(bufs):
            hdr = recordio.IRHeader(0, float(i % 10), i, 0)
            w.write_idx(i, recordio.pack(hdr, b))
        w.close()
        it = mx.image.ImageIter(batch_size=args.batch,
                                data_shape=(3, 224, 224),
                                path_imgrec=rec_path, path_imgidx=idx_path,
                                shuffle=False)

        def iter_all():
            it.reset()
            for _ in it:
                pass
        imgiter_rate = _bench("imageiter_recordio", iter_all,
                              (args.n // args.batch) * args.batch)

        itt = mx.image.ImageIter(batch_size=args.batch,
                                 data_shape=(3, 224, 224),
                                 path_imgrec=rec_path,
                                 path_imgidx=idx_path, shuffle=False,
                                 preprocess_threads=args.workers)

        def iter_all_threaded():
            itt.reset()
            for _ in itt:
                pass
        _bench("imageiter_recordio_%dthreads" % args.workers,
               iter_all_threaded, (args.n // args.batch) * args.batch)

    # 4. DataLoader with multiprocess workers
    from mxtpu.gluon.data import DataLoader
    dl = DataLoader(ds, batch_size=args.batch, num_workers=args.workers)
    list(dl)  # warm the spawned pool (not measured)
    mp_rate = _bench("dataloader_%dproc" % args.workers,
                     lambda: list(dl), args.n)
    dl.close()
    dl0 = DataLoader(ds, batch_size=args.batch, num_workers=0)
    serial_rate = _bench("dataloader_serial", lambda: list(dl0), args.n)

    ncore = os.cpu_count() or 1
    overlap = mp_rate / serial_rate
    # feed-rate verdict: linear scaling at the measured per-core augment
    # rate times the measured process-overlap efficiency per added core
    eff = min(overlap / min(args.workers, max(ncore, 1)), 1.0) if ncore > 1 \
        else 1.0
    need = 4015.0
    cores_needed = need / (aug_rate * eff)
    print(json.dumps({
        "metric": "input_pipeline/feed_verdict",
        "per_core_decode_augment_img_s": round(aug_rate, 1),
        "host_cores": ncore,
        "measured_process_overlap_x": round(overlap, 2),
        "cores_for_4015_img_s": round(cores_needed, 1),
        "unit": "summary"}), flush=True)


if __name__ == "__main__":
    main()
