"""Rebuild the .idx file for an existing RecordIO pack
(ref: tools/rec2idx.py — needed to use a .rec with MXIndexedRecordIO /
ImageRecordIter when the index was lost or never written).

Usage: python tools/rec2idx.py data.rec [data.idx]
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_index(rec_path, idx_path=None):
    from mxtpu.recordio import MXRecordIO

    idx_path = idx_path or os.path.splitext(rec_path)[0] + ".idx"
    reader = MXRecordIO(rec_path, "r")
    n = 0
    with open(idx_path, "w") as f:
        while True:
            pos = reader.tell()
            if reader.read() is None:
                break
            f.write("%d\t%d\n" % (n, pos))
            n += 1
    reader.close()
    size = os.path.getsize(rec_path)
    # readers return None for lost-sync/truncation exactly as for EOF; the
    # distinguishing fact is WHERE the failed read began — a clean EOF
    # starts exactly at the end of the file. A partial index over a corrupt
    # pack must not look like success.
    if pos < size:
        raise RuntimeError(
            "pack %s: record at byte %d of %d unreadable (corrupt/"
            "truncated?) — index covering only the first %d records was "
            "left at %s for inspection" % (rec_path, pos, size, n, idx_path))
    return idx_path, n


def main(argv=None):
    argv = argv if argv is not None else sys.argv[1:]
    if not argv:
        print(__doc__)
        return 1
    idx_path, n = build_index(argv[0], argv[1] if len(argv) > 1 else None)
    print("wrote %s (%d records)" % (idx_path, n))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
