"""startup_time bench: cold-start vs warm-disk-cache wall time (ISSUE 15).

Measures what the persistent compile cache actually buys: the wall time
from PROCESS START to (a) a gluon Trainer's first completed step and
(b) a Predictor replica finishing warmup — each run in a FRESH python
process (``--child``), because the thing being measured is process
restart. The orchestrator runs each scenario once against an empty
``MXTPU_COMPILE_CACHE_DIR`` (cold: every executable compiles + spills)
and again against the now-warm dir (warm: every executable
deserializes), and gates:

* warm ``compiles == 0`` — the retrace counters across every jit site
  stay at zero (watchdog-pinned: a disk load is not a compile),
* warm ``disk_hits > 0`` — the zero is because the disk served, not
  because nothing ran,
* warm wall < cold wall — ``vs_baseline`` is the cold/warm speedup.

JSON lines ride ``bench.py startup_time`` (tools/perf_battery.sh phase).
Knobs: ``BENCH_STARTUP_HIDDEN`` / ``BENCH_STARTUP_LAYERS`` size the
model, ``BENCH_STARTUP_ROUNDS`` extra warm rounds (min taken),
``BENCH_STARTUP_CACHE_DIR`` pins the dir (default: fresh tempdir).
"""
from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _hidden():
    return int(os.environ.get("BENCH_STARTUP_HIDDEN", "256"))


def _layers():
    return int(os.environ.get("BENCH_STARTUP_LAYERS", "4"))


# --------------------------------------------------------------- child side
def _build_net(nn):
    net = nn.HybridSequential()
    for _ in range(_layers()):
        net.add(nn.Dense(_hidden(), activation="relu"))
    net.add(nn.Dense(10))
    net.initialize()
    net.hybridize()
    return net


def _snapshot_counts():
    from mxtpu import telemetry
    snap = telemetry.snapshot()["counters"]
    compiles = sum(v for k, v in snap.items()
                   if isinstance(v, (int, float)) and k.startswith("retrace.")
                   and k != "retrace.watchdog_trips")
    def total(name):
        v = snap.get(name, 0)
        return sum(v.values()) if isinstance(v, dict) else v
    return {"compiles": int(compiles),
            "disk_hits": int(total("compile.disk.hits")),
            "disk_writes": int(total("compile.disk.writes")),
            "disk_drops": int(total("compile.disk.drops"))}


def child_trainer(t0):
    import numpy as np

    import mxtpu as mx
    from mxtpu import autograd, gluon
    from mxtpu.gluon import nn

    net = _build_net(nn)
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 1e-3})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    rng = np.random.RandomState(0)
    x = mx.nd.array(rng.randn(32, 64).astype(np.float32))
    y = mx.nd.array(rng.randint(0, 10, size=(32,)))
    with autograd.record():
        loss = loss_fn(net(x), y)
    loss.backward()
    trainer.step(32)
    first = float(loss.asnumpy().mean())  # sync: the step truly completed
    rec = {"scenario": "trainer", "wall_s": time.time() - t0,
           "loss": first}
    rec.update(_snapshot_counts())
    return rec


def child_predictor(t0):
    import numpy as np

    import mxtpu as mx
    from mxtpu.gluon import nn
    from mxtpu.serving import BucketSpec, Predictor

    net = _build_net(nn)
    example = mx.nd.array(np.zeros((1, 64), np.float32))
    pred = Predictor(net, BucketSpec.pow2(max_batch=8), example=example,
                     warmup=True)
    out = pred.predict(mx.nd.array(
        np.random.RandomState(0).randn(3, 64).astype(np.float32)))
    np.asarray(out.asnumpy())  # a served request really ran
    rec = {"scenario": "predictor", "wall_s": time.time() - t0,
           "buckets": len(pred.spec)}
    rec.update(_snapshot_counts())
    return rec


def run_child(scenario, t0):
    rec = child_trainer(t0) if scenario == "trainer" \
        else child_predictor(t0)
    print("STARTUP_BENCH " + json.dumps(rec), flush=True)


# ---------------------------------------------------------- orchestrator side
def _spawn(scenario, cache_dir, timeout_s=600):
    env = dict(os.environ)
    env["MXTPU_COMPILE_CACHE_DIR"] = cache_dir
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    t0 = time.time()
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--child", scenario,
         "--t0", repr(t0)],
        env=env, cwd=REPO, capture_output=True, text=True,
        timeout=timeout_s)
    for line in proc.stdout.splitlines():
        if line.startswith("STARTUP_BENCH "):
            return json.loads(line[len("STARTUP_BENCH "):])
    raise RuntimeError(
        "startup child (%s) produced no record: rc=%d\nstdout:\n%s\n"
        "stderr:\n%s" % (scenario, proc.returncode,
                         proc.stdout[-2000:], proc.stderr[-2000:]))


def run_startup(emit=None):
    """Cold vs warm process starts for both scenarios; returns the gate
    summary (and emits one JSON line per child run)."""
    if emit is None:
        def emit(rec):
            print(json.dumps(rec), flush=True)
    pinned = os.environ.get("BENCH_STARTUP_CACHE_DIR")
    root = pinned or tempfile.mkdtemp(prefix="mxtpu-startup-bench-")
    rounds = max(1, int(os.environ.get("BENCH_STARTUP_ROUNDS", "1")))
    out = {"scenarios": {}, "ok": True}
    try:
        for scenario in ("trainer", "predictor"):
            cdir = os.path.join(root, scenario)
            shutil.rmtree(cdir, ignore_errors=True)
            os.makedirs(cdir, exist_ok=True)
            cold = _spawn(scenario, cdir)
            cold["mode"] = "cold"
            emit(dict(cold, metric="startup_time"))
            warms = [_spawn(scenario, cdir) for _ in range(rounds)]
            warm = min(warms, key=lambda r: r["wall_s"])
            warm["mode"] = "warm"
            emit(dict(warm, metric="startup_time"))
            gates = {
                # the acceptance pin: a warm start reaches the first
                # step / finished warmup with ZERO compiles...
                "zero_compiles": warm["compiles"] == 0,
                # ...BECAUSE the disk served (not because nothing ran)
                "disk_served": warm["disk_hits"] > 0,
                "faster": warm["wall_s"] < cold["wall_s"],
            }
            speedup = cold["wall_s"] / max(warm["wall_s"], 1e-9)
            out["scenarios"][scenario] = {
                "cold_s": round(cold["wall_s"], 3),
                "warm_s": round(warm["wall_s"], 3),
                "speedup": round(speedup, 3),
                "cold_compiles": cold["compiles"],
                "warm_compiles": warm["compiles"],
                "warm_disk_hits": warm["disk_hits"],
                "gates": gates,
            }
            out["ok"] = out["ok"] and all(gates.values())
    finally:
        if not pinned:
            shutil.rmtree(root, ignore_errors=True)
    out["speedup"] = min(s["speedup"] for s in out["scenarios"].values())
    return out


def main(argv=None):
    import argparse
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--child", choices=("trainer", "predictor"))
    ap.add_argument("--t0", type=float, default=None)
    args = ap.parse_args(argv)
    if args.child:
        run_child(args.child, args.t0 if args.t0 else time.time())
        return 0
    summary = run_startup()
    print(json.dumps({"metric": "startup_time_summary", **summary}))
    return 0 if summary["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
