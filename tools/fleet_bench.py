"""fleet_resume bench: kill-one-host restore parity + warm rejoin (ISSUE 18).

The acceptance scenario for elastic fleet training, run end to end with
real subprocesses on the forced-CPU host tier (chip-safe — every child
pins ``JAX_PLATFORMS=cpu``; ``--devices`` sets its fake local device
count, the mesh-RESHAPE lever):

1. **fleet** — a 2-host fleet (2 devices each) trains with per-step
   checkpoints; ``host_loss@K`` is injected into host 1, which dies with
   ``os._exit(41)`` at step K. The survivor's step barrier diagnoses the
   dead peer off its stale heartbeat and exits 42 loud — the fleet
   collective watchdog is the backstop (gate: *kill_detected* — both
   exit codes surfaced, nothing hung).
2. **restore** — ONE host resumes from the same checkpoint dir onto a
   RESHAPED 1-device mesh: the last intact checkpoint (step K−1, saved
   from the 2-device ZeRO-1 layout) restores into the live 1-device
   shardings (orbax re-reads; the MeshPlan re-places optimizer state)
   and trains to completion (gates: resumed at K, clean exit,
   divergence sentinel green).
3. **oracle** — the same seed runs uninterrupted on 1 host × 1 device in
   a separate dir; gate *resume_parity*: the restore run's post-restore
   losses match the oracle's within reduce-order tolerance (the killed
   run's first K steps reduced over 2 devices, the oracle's over 1 —
   ULP-level divergence compounds, bitwise equality is not the right
   pin).
4. **rejoin** — the fleet grows back to 2 hosts against the SAME compile
   cache dir and trains 2 more steps (gates: every rejoined host records
   ZERO compiles across all registered jit sites, watchdog-pinned, and
   the disk cache served — warm elastic rejoin). XLA:CPU cannot
   round-trip multi-device executables (compile_service refuses them),
   so the rejoin generation runs 1 device per host, warm off the blobs
   the restore/oracle phases spilled; on TPU the same gate rides the
   full-mesh blobs.
5. **obs** — ISSUE 19's observability gate: a fresh 2-host fleet runs
   with the fleet obs plane ON (``MXTPU_FLEET_OBS_S``,
   ``MXTPU_STRAGGLER_X``) and rank 1 injected ``straggler_slow`` on
   every post-warmup step. Gates: *fleet_snapshot_merged* — the
   ``FleetObservatory`` merge over the board's ``obs_*.json`` blobs
   covers both hosts with step-time quantiles — and
   *straggler_tripped* — the ``flight_record("straggler")`` artifact
   names rank 1 with ``data.wait`` dominant within 16 steps.

JSON lines ride ``bench.py fleet_resume`` (tools/perf_battery.sh phase).
Knobs: ``BENCH_FLEET_STEPS`` (default 6), ``BENCH_FLEET_KILL_STEP``
(default 3), ``BENCH_FLEET_CHILD_TIMEOUT_S``, ``BENCH_FLEET_DIR`` (pin
the work dir; default fresh tempdir).
"""
from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tools", "fleet_worker.py")
sys.path.insert(0, REPO)


def _steps():
    return int(os.environ.get("BENCH_FLEET_STEPS", "6"))


def _kill_step():
    return int(os.environ.get("BENCH_FLEET_KILL_STEP", "3"))


def _child_timeout_s():
    return float(os.environ.get("BENCH_FLEET_CHILD_TIMEOUT_S", "240"))


def _parse_result(tail):
    for line in reversed((tail or "").splitlines()):
        if line.startswith("RESULT "):
            return json.loads(line[len("RESULT "):])
    return None


def _phase(name, world, ckpt_dir, steps, workdir, cache_dir, devices=1,
           env_extra=None, env_for=None):
    """One fleet generation through FleetSupervisor.launch_round: fresh
    fleet board dir, shared compile cache, hard child timeouts. Returns
    {rank: {"rc": ..., "result": parsed RESULT or None, "tail": ...}}."""
    from mxtpu.fleet import FleetSupervisor
    fleet_dir = os.path.join(workdir, "board_%s" % name)
    shutil.rmtree(fleet_dir, ignore_errors=True)

    def command_for(rank, w, generation):
        return [sys.executable, WORKER, "--ckpt-dir", ckpt_dir,
                "--steps", str(steps), "--devices", str(devices)]

    base_env = {
        "MXTPU_COMPILE_CACHE_DIR": cache_dir,
        "MXTPU_FLEET_BRINGUP_TIMEOUT_S": "90",
        "MXTPU_FLEET_HEARTBEAT_S": "0.5",
        # the post-kill wedge bound: the survivor's step-K collective
        # must fail loud well inside the child hard timeout
        "MXTPU_FLEET_COLLECTIVE_TIMEOUT_S": "30",
        "PYTHONPATH": REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
    }
    base_env.update(env_extra or {})

    def merged_env(rank, w, generation):
        env = dict(base_env)
        if env_for is not None:
            env.update(env_for(rank, w, generation) or {})
        return env

    sup = FleetSupervisor(
        command_for=command_for, num_hosts=world, fleet_dir=fleet_dir,
        timeout_s=_child_timeout_s(), env_for=merged_env)
    t0 = time.time()
    raw = sup.launch_round(world, 0)
    wall = time.time() - t0
    out = {}
    for rank, (rc, tail) in raw.items():
        out[rank] = {"rc": rc, "result": _parse_result(tail), "tail": tail}
    out["wall_s"] = wall
    return out


def run_fleet_resume(emit=None):
    """Run the 4-phase matrix; returns the gate summary (and emits one
    stamped JSON line per phase)."""
    if emit is None:
        def emit(rec):
            print(json.dumps(rec), flush=True)
    steps, kill = _steps(), _kill_step()
    pinned = os.environ.get("BENCH_FLEET_DIR")
    root = pinned or tempfile.mkdtemp(prefix="mxtpu-fleet-bench-")
    cache_dir = os.path.join(root, "compile_cache")
    ckpt = os.path.join(root, "ckpt")
    ckpt_oracle = os.path.join(root, "ckpt_oracle")
    ckpt_obs = os.path.join(root, "ckpt_obs")
    flight_obs = os.path.join(root, "flight_obs")
    for d in (cache_dir, ckpt, ckpt_oracle, ckpt_obs, flight_obs):
        shutil.rmtree(d, ignore_errors=True)
        os.makedirs(d, exist_ok=True)
    summary = {"steps": steps, "kill_step": kill, "phases": {}}
    try:
        # 1. 2-host fleet, 2 devices each, host 1 killed at step K
        p1 = _phase(
            "fleet", 2, ckpt, steps, root, cache_dir, devices=2,
            env_for=lambda r, w, g:
                {"MXTPU_FAULT_INJECT": "host_loss@%d" % kill} if r == 1
                else {})
        rc_killed = p1[1]["rc"]
        rc_survivor = p1[0]["rc"]
        kill_detected = rc_killed == 41 and rc_survivor == 42
        summary["phases"]["fleet"] = {
            "wall_s": round(p1["wall_s"], 2),
            "rc": {"0": rc_survivor, "1": rc_killed}}
        emit({"metric": "fleet_resume", "phase": "fleet",
              "wall_s": round(p1["wall_s"], 3), "rc_survivor": rc_survivor,
              "rc_killed": rc_killed, "kill_detected": kill_detected})

        # 2. restore onto the reshaped 1-host x 1-device mesh
        p2 = _phase("restore", 1, ckpt, steps, root, cache_dir, devices=1)
        r2 = p2[0]["result"] or {}
        restored_at = r2.get("start")
        divergence_green = p2[0]["rc"] == 0 and \
            r2.get("divergence_checks", 0) > 0
        summary["phases"]["restore"] = {
            "wall_s": round(p2["wall_s"], 2), "rc": p2[0]["rc"],
            "resumed_at": restored_at}
        emit({"metric": "fleet_resume", "phase": "restore",
              "wall_s": round(p2["wall_s"], 3), "rc": p2[0]["rc"],
              "resumed_at": restored_at,
              "losses": r2.get("losses")})

        # 3. uninterrupted 1-host oracle, separate checkpoint dir
        p3 = _phase("oracle", 1, ckpt_oracle, steps, root, cache_dir,
                    devices=1)
        r3 = p3[0]["result"] or {}
        oracle_losses = r3.get("losses") or []
        restore_losses = r2.get("losses") or []
        parity = bool(
            p3[0]["rc"] == 0 and restored_at == kill and
            len(restore_losses) == steps - kill and
            len(oracle_losses) == steps and
            np.allclose(restore_losses, oracle_losses[kill:],
                        rtol=5e-4, atol=1e-6))
        max_rel = None
        if parity:
            a = np.asarray(restore_losses)
            b = np.asarray(oracle_losses[kill:])
            max_rel = float(np.max(np.abs(a - b) /
                                   np.maximum(np.abs(b), 1e-9)))
        summary["phases"]["oracle"] = {
            "wall_s": round(p3["wall_s"], 2), "rc": p3[0]["rc"],
            "max_rel_diff": max_rel}
        emit({"metric": "fleet_resume", "phase": "oracle",
              "wall_s": round(p3["wall_s"], 3), "rc": p3[0]["rc"],
              "losses": oracle_losses, "resume_parity": parity,
              "max_rel_diff": max_rel})

        # 4. warm rejoin: back to 2 hosts, +2 steps, same compile cache
        # (1 device per host — XLA:CPU disk blobs are single-device only)
        p4 = _phase("rejoin", 2, ckpt, steps + 2, root, cache_dir,
                    devices=1)
        r4 = [p4[r]["result"] or {} for r in (0, 1)]
        rejoin_ok = all(p4[r]["rc"] == 0 for r in (0, 1))
        zero_compiles = rejoin_ok and \
            all(r.get("compiles", 1) == 0 for r in r4)
        disk_served = all(r.get("disk_hits", 0) > 0 for r in r4)
        summary["phases"]["rejoin"] = {
            "wall_s": round(p4["wall_s"], 2),
            "rc": {"0": p4[0]["rc"], "1": p4[1]["rc"]},
            "compiles": [r.get("compiles") for r in r4],
            "disk_hits": [r.get("disk_hits") for r in r4]}
        emit({"metric": "fleet_resume", "phase": "rejoin",
              "wall_s": round(p4["wall_s"], 3),
              "compiles": [r.get("compiles") for r in r4],
              "disk_hits": [r.get("disk_hits") for r in r4],
              "rejoin_zero_compiles": zero_compiles})

        # 5. observability (ISSUE 19): fresh 2-host fleet with the obs
        # plane ON and rank 1 injected slow on every post-warmup step —
        # the merged fleet snapshot must cover both hosts and the
        # straggler sentinel must NAME rank 1 with its dominant stage.
        import glob as _glob

        from mxtpu import fleet_obs
        p5 = _phase(
            "obs", 2, ckpt_obs, steps, root, cache_dir, devices=1,
            env_extra={"MXTPU_FLEET_OBS_S": "0.05",
                       "MXTPU_STRAGGLER_X": "1.5",
                       "MXTPU_FLIGHT_DIR": flight_obs},
            env_for=lambda r, w, g:
                {"MXTPU_FAULT_INJECT": "straggler_slow@" + ",".join(
                    str(s) for s in range(1, steps))} if r == 1
                else {})
        obs_rc_ok = all(p5[r]["rc"] == 0 for r in (0, 1))
        board = os.path.join(root, "board_obs", "gen_0")
        merged = fleet_obs.FleetObservatory(board, 2).merged()
        hosts = merged.get("hosts", {})
        snapshot_merged = obs_rc_ok and all(
            r in hosts and hosts[r]["step_s"].get("p50") is not None
            for r in (0, 1))
        trip = None
        for art in sorted(_glob.glob(os.path.join(
                flight_obs, "flight_straggler_*.json"))):
            try:
                with open(art) as fh:
                    trip = (json.load(fh).get("extra") or {})
                break
            except ValueError:
                continue
        straggler_named = bool(
            trip and trip.get("rank") == 1 and
            trip.get("step", 1 << 30) < 16 and
            trip.get("dominant_stage") == "data.wait")
        summary["phases"]["obs"] = {
            "wall_s": round(p5["wall_s"], 2),
            "rc": {"0": p5[0]["rc"], "1": p5[1]["rc"]},
            "hosts_merged": sorted(hosts),
            "straggler": None if not trip else
            {k: trip.get(k) for k in
             ("rank", "step", "ratio", "dominant_stage")}}
        emit({"metric": "fleet_resume", "phase": "obs",
              "wall_s": round(p5["wall_s"], 3),
              "fleet_snapshot_merged": snapshot_merged,
              "straggler_tripped": straggler_named,
              "straggler": summary["phases"]["obs"]["straggler"]})

        gates = {
            "kill_detected": kill_detected,
            "restore_clean": p2[0]["rc"] == 0 and restored_at == kill,
            "divergence_green": divergence_green,
            "resume_parity": parity,
            "rejoin_zero_compiles": zero_compiles,
            "rejoin_disk_served": disk_served,
            "fleet_snapshot_merged": snapshot_merged,
            "straggler_tripped": straggler_named,
        }
        summary["gates"] = gates
        summary["ok"] = all(gates.values())
        # the headline numbers: how fast a grown-back fleet reaches
        # useful work vs the killed run's cost, all compiles disk-served
        summary["rejoin_wall_s"] = round(p4["wall_s"], 3)
        summary["vs_baseline"] = round(
            p1["wall_s"] / max(p4["wall_s"], 1e-9), 3)
        if not summary["ok"]:
            # surface the failing child's tail — a gate that fails in CI
            # must carry its evidence
            for name, p in (("fleet", p1), ("restore", p2),
                            ("oracle", p3), ("rejoin", p4), ("obs", p5)):
                for rank in (0, 1):
                    info = p.get(rank)
                    if info and info["rc"] != 0:
                        summary.setdefault("failures", []).append(
                            {"phase": name, "rank": rank, "rc": info["rc"],
                             "tail": info["tail"][-1500:]})
    finally:
        if not pinned:
            shutil.rmtree(root, ignore_errors=True)
    return summary


def main(argv=None):
    summary = run_fleet_resume()
    print(json.dumps({"metric": "fleet_resume_summary", **summary}))
    return 0 if summary["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
