"""Measure the chip's ACHIEVABLE bf16 matmul rate (the practical MXU
ceiling), not the datasheet peak.

Method: one jit dispatch runs a lax.scan of K chained NxN bf16 matmuls, so
per-dispatch tunnel RTT and host sync amortize to nothing; sync is a host
fetch of a few result elements (block_until_ready does NOT reliably wait
through the axon tunnel — see PERF.md "timing methodology").

The ratio achieved/nominal calibrates every MFU number in bench.py: if the
exposed chip sustains X TFLOP/s on an ideal 8k matmul, no model can exceed
X, and "% of achievable" is the number optimization work should move.
"""
import time

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax


def probe(N, K=20, acc=None, prec=None, dtype=jnp.bfloat16):
    a = jax.random.normal(jax.random.PRNGKey(0), (N, N), dtype)
    b = jax.random.normal(jax.random.PRNGKey(1), (N, N), dtype)

    def body(c, _):
        out = lax.dot(c, b, preferred_element_type=acc, precision=prec)
        # rescale so the chain neither overflows nor constant-folds
        return out.astype(dtype) * jnp.asarray(1e-3, dtype), None

    @jax.jit
    def run(a, b):
        c, _ = lax.scan(body, a, None, length=K)
        return c

    y = run(a, b)
    _ = np.asarray(y[0, :2])  # compile + settle
    t0 = time.perf_counter()
    y = run(a, b)
    _ = np.asarray(y[0, :2])  # true sync: host fetch
    dt = time.perf_counter() - t0
    fl = 2 * N ** 3 * K
    rate = fl / dt / 1e12
    print("N=%5d K=%2d acc=%-8s prec=%-8s %7.2f ms/matmul  %6.1f TFLOP/s"
          % (N, K, acc.__name__ if acc else None, prec, dt * 1e3 / K, rate),
          flush=True)
    return rate


def main():
    d = jax.devices()[0]
    print("device:", d.platform, getattr(d, "device_kind", "?"), flush=True)
    best = 0.0
    for n in (4096, 8192):
        best = max(best, probe(n))
    best = max(best, probe(8192, acc=jnp.float32))
    # the honest-f32 emulation floor (PERF.md ceiling table, f32 HIGHEST row)
    probe(8192, prec="highest", dtype=jnp.float32)
    # datasheet nominal from the ONE shared table (mxtpu/perf_model.py)
    # — the same denominator bench.py's mfu and the runtime perf.mfu
    # gauge divide by
    from mxtpu import perf_model
    nominal = perf_model.nominal_tflops(d) or 197.0
    print("achievable ceiling: %.1f TFLOP/s = %.0f%% of the %.0f TFLOP/s "
          "%s datasheet peak"
          % (best, 100 * best / nominal, nominal,
             getattr(d, "device_kind", "?")))


if __name__ == "__main__":
    main()
