"""Measure axon-tunnel dispatch overhead vs on-chip compute.

a) trivial op dispatch+sync latency (tunnel RTT floor)
b) fwd with a true host fetch each iteration
c) N train steps fused into ONE dispatch via lax.scan -> per-step chip time
"""
import os
import time

import numpy as np
import jax
import jax.numpy as jnp


def main():
    # a) trivial dispatch latency
    f = jax.jit(lambda x: x + 1)
    x = jnp.ones((8, 8))
    float(f(x).sum())
    for label, sync in (("block_until_ready", lambda o: jax.block_until_ready(o)),
                        ("device_get", lambda o: jax.device_get(o))):
        t0 = time.perf_counter()
        for _ in range(20):
            o = f(x)
            sync(o)
        dt = (time.perf_counter() - t0) / 20
        print("trivial op, %-17s: %.3f ms" % (label, dt * 1e3))
    # async pipelining: 20 dispatches, one sync at end
    t0 = time.perf_counter()
    for _ in range(20):
        o = f(x)
    jax.device_get(o)
    print("trivial op, sync-at-end    : %.3f ms/step"
          % ((time.perf_counter() - t0) / 20 * 1e3))

    from mxtpu import gluon
    from mxtpu.parallel import pure_forward
    from perf_common import build_resnet

    batch = int(os.environ.get("BENCH_BATCH", "128"))
    net, x, yl = build_resnet(batch)

    # b) fwd with true host fetch
    fn, params = pure_forward(net)
    jfwd = jax.jit(lambda p, d: fn(p, d).sum())
    float(jfwd(params, x._data))
    t0 = time.perf_counter()
    for _ in range(10):
        v = float(jfwd(params, x._data))
    dt = (time.perf_counter() - t0) / 10
    print("fwd + host fetch           : %.2f ms" % (dt * 1e3))

    # c) K steps of fwd+bwd+sgd inside one scan = one dispatch
    fn_t, params_t = pure_forward(net, train=True)
    loss_blk = gluon.loss.SoftmaxCrossEntropyLoss()
    from mxtpu.ndarray import NDArray

    def loss_of(p, xd, yd):
        out = fn_t(p, xd)
        return jnp.mean(loss_blk(NDArray(out), NDArray(yd))._data)

    def one_step(p, _):
        l, g = jax.value_and_grad(loss_of)(p, x._data, yl._data)
        p = [(w - 0.01 * gw.astype(w.dtype)) for w, gw in zip(p, g)]
        return p, l

    K = 10

    @jax.jit
    def multi(p):
        p, ls = jax.lax.scan(one_step, p, None, length=K)
        return ls[-1]

    float(multi(params_t))  # compile+run
    t0 = time.perf_counter()
    float(multi(params_t))
    dt = time.perf_counter() - t0
    print("scan(%d) fwd+bwd+sgd       : %.2f ms/step -> %.0f img/s"
          % (K, dt / K * 1e3, batch * K / dt))


if __name__ == "__main__":
    main()
