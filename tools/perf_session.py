"""ONE-SESSION perf battery: every measurement in a single PJRT process.

Why: the axon tunnel wedges server-side, and the observed trigger pattern
(round 3: killed trace; round 4: the 4th client process of the morning
hung at first dispatch after three healthy sessions) points at *session
churn* — every new python process is a new claim/release cycle and a
fresh chance to wedge the only chip. tools/perf_battery.sh burned one
process per measurement; this tool takes every number in ONE process,
ordered so the most valuable results print (and flush) first. If the
tunnel dies mid-session, everything already printed survives.

Also fixes the control problem: round-4's first on-chip numbers compared
lever-enabled runs against round 2's 2,321.9 img/s from a DIFFERENT
session, confounding chip/day variance with the lever effect. Here the
no-lever control (MXTPU_CONV_ACC=0) runs in the same session minutes
before the lever runs, so deltas are attributable.

In-process A/B is sound because every lever flag is read at trace time
and participates in the jit cache key (mxtpu/ops/registry.py policy_key;
bench.bench_resnet50 builds a fresh net + ShardedTrainStep per call).

Usage:  python -u tools/perf_session.py [phase ...]
        (default: all phases; names as in PHASES below)
Prints one JSON line per result, flushed immediately; a `phase` field
tags each. Run under an outer `timeout` (the shell owns the watchdog —
an in-process watchdog cannot preempt a hung PJRT dispatch anyway).
"""
import json
import os
import sys
import time

_TOOLS = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_TOOLS)
for _p in (_REPO, _TOOLS):
    if _p not in sys.path:
        sys.path.insert(0, _p)

os.environ.setdefault("MXTPU_COMPILE_CACHE", "/tmp/mxtpu_compile_cache")
if os.environ.get("PERF_SESSION_CPU") == "1":
    # hermetic smoke: the axon sitecustomize overrides JAX_PLATFORMS=cpu
    # programmatically (see tests/conftest.py), so opting out of the
    # tunnel needs the same jax.config route, before any device use
    import jax
    jax.config.update("jax_platforms", "cpu")
# the in-process probe replaces bench.py's subprocess preflight (one
# session, remember); a wedged chip hangs phase "probe" and the log
# shows exactly that
os.environ["BENCH_PREFLIGHT"] = "0"


def out(phase, rec):
    rec = dict(rec)
    rec["phase"] = phase
    rec["t"] = round(time.time() - T0, 1)
    print(json.dumps(rec), flush=True)


def say(msg):
    print("## %s (%s)" % (msg, time.strftime("%H:%M:%S")), flush=True)


T0 = time.time()


# the scan-fused timing harness + carry reinjection live in ONE place
# (tools/perf_common.py) shared with bench.py's conv_class config
from perf_common import reinject, timed_scan  # noqa: E402


def phase_probe():
    import jax
    import jax.numpy as jnp
    import numpy as np
    t0 = time.time()
    f = jax.jit(lambda v: v + 1)
    np.asarray(jax.device_get(f(jnp.ones(2))))
    first = time.time() - t0
    t0 = time.time()
    for _ in range(5):
        np.asarray(jax.device_get(f(jnp.ones(2))))
    rtt = (time.time() - t0) / 5
    out("probe", {"platform": jax.devices()[0].platform,
                  "first_dispatch_s": round(first, 3),
                  "rtt_s": round(rtt, 4)})


def _resnet(tag, **env):
    import bench
    saved = {}
    for k, v in env.items():
        saved[k] = os.environ.get(k)
        os.environ[k] = v
    try:
        # stamp platform+policy here too: a mid-battery tunnel wedge that
        # drops jax to CPU must be visible on THESE lines, same as every
        # line bench.py prints itself
        rec = bench._stamp(bench.bench_resnet50())
        out(tag, rec)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def phase_resnet_control():
    # round-2 path: plain XLA convs, two-pass BN, plain stem — the
    # same-session baseline every lever delta is measured against.
    # EVERY lever env is pinned explicitly: package defaults moved in
    # round 5 (BN one-pass is now default-on), and a control that
    # inherits defaults silently becomes the lever it controls for.
    _resnet("resnet_control", MXTPU_CONV_ACC="0", MXTPU_BN_ONEPASS="0",
            BENCH_S2D_STEM="0", MXTPU_CONV_IM2COL="0")


def phase_resnet_conv_acc():
    _resnet("resnet_conv_acc", MXTPU_CONV_ACC="1", MXTPU_BN_ONEPASS="0",
            BENCH_S2D_STEM="0", MXTPU_CONV_IM2COL="0")


def phase_resnet_s2d():
    _resnet("resnet_s2d", MXTPU_CONV_ACC="1", MXTPU_BN_ONEPASS="0",
            BENCH_S2D_STEM="1", MXTPU_CONV_IM2COL="0")


def phase_resnet_bn1p():
    _resnet("resnet_bn_onepass", MXTPU_CONV_ACC="1", MXTPU_BN_ONEPASS="1",
            BENCH_S2D_STEM="0", MXTPU_CONV_IM2COL="0")


def phase_resnet_all_levers():
    _resnet("resnet_all_levers", MXTPU_CONV_ACC="1", MXTPU_BN_ONEPASS="1",
            BENCH_S2D_STEM="1", MXTPU_CONV_IM2COL="0")


def phase_resnet_nchw():
    # layout A/B: XLA:TPU may prefer a different im2col/tiling for NCHW
    _resnet("resnet_nchw", MXTPU_CONV_ACC="1", MXTPU_BN_ONEPASS="0",
            BENCH_LAYOUT="NCHW", MXTPU_CONV_IM2COL="0")


def phase_convs():
    """Per-conv-class attribution: time the FLOP-dominant conv shapes of
    the bench resnet50 individually (fwd, conv_acc policy, bf16 NHWC) and
    report achieved TFLOP/s each. The prefix-stage timings say WHERE the
    time goes; this says WHICH conv class underperforms (1x1 vs 3x3 vs
    stem vs strided). 8 shapes ~ 95% of forward FLOPs; counts are the
    per-model multiplicities (resnet50_v1 bottleneck table)."""
    import jax
    import jax.numpy as jnp

    batch = int(os.environ.get("BENCH_BATCH", "128"))
    # (label, HW_in, Cin, Cout, k, stride, count_in_model)
    shapes = [
        ("stem_7x7s2", 224, 3, 64, 7, 2, 1),
        ("s1_3x3_64", 56, 64, 64, 3, 1, 3),
        ("s1_1x1_64to256", 56, 64, 256, 1, 1, 4),
        ("s2_3x3_128", 28, 128, 128, 3, 1, 3),
        ("s3_3x3_256", 14, 256, 256, 3, 1, 5),
        ("s3_1x1_1024to256", 14, 1024, 256, 1, 1, 5),
        ("s4_3x3_512", 7, 512, 512, 3, 1, 2),
        ("s4_1x1_512to2048", 7, 512, 2048, 1, 1, 3),
    ]
    dn = jax.lax.conv_dimension_numbers(
        (batch, 1, 1, 1), (1, 1, 1, 1), ("NHWC", "HWIO", "NHWC"))
    for label, hw, cin, cout, k, s, count in shapes:
        x = jax.random.normal(jax.random.PRNGKey(0),
                              (batch, hw, hw, cin), jnp.bfloat16)
        w = jax.random.normal(jax.random.PRNGKey(1),
                              (k, k, cin, cout), jnp.bfloat16)
        pad = ((k // 2, k // 2), (k // 2, k // 2))

        def f(xd, w=w, s=s, pad=pad):
            return jax.lax.conv_general_dilated(
                xd, w, (s, s), pad, dimension_numbers=dn,
                preferred_element_type=jnp.float32)

        try:
            dt = timed_scan(reinject(f), x, K=16)
        except Exception as e:  # noqa: BLE001 — keep the sweep going
            out("convs", {"conv": label, "error": str(e)})
            continue
        hw_out = hw // s
        fl = 2 * batch * hw_out * hw_out * cin * cout * k * k
        out("convs", {"conv": label, "ms": round(dt * 1e3, 3),
                      "tflops": round(fl / dt / 1e12, 1),
                      "count": count,
                      "model_ms_est": round(count * dt * 1e3, 2)})


def phase_stages():
    """Compact forward attribution: timed truncated prefixes of the exact
    bench model (stem / +stage1+2 / +stage3 / +stage4 / full incl. dense),
    fwd and fwd+bwd, scan-fused (see tools/perf_stages.py for the long
    form — trimmed here to bound compile count in the shared session)."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    import mxtpu as mx
    from mxtpu.parallel import pure_forward
    from perf_common import build_resnet

    batch = int(os.environ.get("BENCH_BATCH", "128"))
    net, x, _y = build_resnet(batch)
    feats = list(net.features._children.values())
    cuts, seen = [], 0
    for i, b in enumerate(feats):
        if type(b).__name__ == "HybridSequential":
            seen += 1
            cuts.append((i + 1, "stage%d" % seen))
    picks = [(cuts[0][0] - 1, "stem")] + [c for c in cuts
                                          if c[1] in ("stage2", "stage3",
                                                      "stage4")]
    prev_f = prev_b = 0.0
    for upto, label in picks + [(None, "full")]:
        if upto is None:
            fn, params = pure_forward(net, train=True)
        else:
            sub = mx.gluon.nn.HybridSequential()
            for b in feats[:upto]:
                sub.add(b)
            fn, params = pure_forward(sub, train=True)
        f = lambda xd, fn=fn, params=params: fn(params, xd)
        dt_f = timed_scan(reinject(f), x._data)
        g = jax.grad(lambda xd, fn=fn, params=params: jnp.sum(
            fn(params, xd).astype(jnp.float32)) * 1e-6)
        dt_b = timed_scan(reinject(g), x._data)
        out("stages", {"cut": label, "fwd_ms": round(dt_f * 1e3, 2),
                       "fwd_inc_ms": round((dt_f - prev_f) * 1e3, 2),
                       "fwdbwd_ms": round(dt_b * 1e3, 2),
                       "fwdbwd_inc_ms": round((dt_b - prev_b) * 1e3, 2)})
        prev_f, prev_b = dt_f, dt_b


def phase_peak():
    """Revalidate the achievable-ceiling numbers (PERF.md)."""
    import numpy as np
    import jax
    import jax.numpy as jnp

    n = int(os.environ.get("BENCH_PEAK_N", "8192"))
    k = jax.random.PRNGKey(0)
    a = jax.random.normal(k, (n, n), jnp.bfloat16)
    b = jax.random.normal(k, (n, n), jnp.bfloat16)

    fl = 2 * n ** 3
    dt = timed_scan(lambda x: jnp.dot(x, b).astype(jnp.bfloat16), a, K=16)
    out("peak", {"case": "bf16_matmul_%d" % n,
                 "tflops": round(fl / dt / 1e12, 1)})
    dt = timed_scan(lambda x: jnp.dot(
        x, b, preferred_element_type=jnp.float32).astype(jnp.bfloat16),
        a, K=16)
    out("peak", {"case": "bf16_matmul_%d_f32acc" % n,
                 "tflops": round(fl / dt / 1e12, 1)})


def phase_bn():
    """BN lever microtiming in-session: conv alone vs conv+BN(train),
    two-pass vs one-pass stats, b128 56x56x256 — the dominant BN shape."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from mxtpu.parallel import pure_forward
    import mxtpu as mx

    batch = int(os.environ.get("BENCH_BATCH", "128"))
    shape = (batch, 56, 56, 256)

    x = jax.random.normal(jax.random.PRNGKey(0), shape, jnp.bfloat16)
    saved = os.environ.get("MXTPU_BN_ONEPASS")
    try:
        for flag in ("0", "1"):
            os.environ["MXTPU_BN_ONEPASS"] = flag
            with mx.layout("NHWC"):
                blk = mx.gluon.nn.HybridSequential()
                blk.add(mx.gluon.nn.Conv2D(256, 3, padding=1,
                                           use_bias=False))
                blk.add(mx.gluon.nn.BatchNorm())
            blk.initialize()
            blk(mx.nd.array(np.zeros(shape, np.float32)))  # settle shapes
            blk.cast("bfloat16")
            fn, params = pure_forward(blk, train=True)
            dt = timed_scan(reinject(
                lambda xd, fn=fn, p=params: fn(p, xd)), x)
            out("bn", {"case": "conv3x3_bn_train_b%d_56x256" % batch,
                       "onepass": flag == "1", "ms": round(dt * 1e3, 3)})
    finally:
        if saved is None:
            os.environ.pop("MXTPU_BN_ONEPASS", None)
        else:
            os.environ["MXTPU_BN_ONEPASS"] = saved


_LSTM_MEASURED = False


def phase_lstm():
    global _LSTM_MEASURED
    import bench
    if _LSTM_MEASURED:
        # the hoist A/B already emitted the canonical "lstm" record this
        # session — don't spend healthy-chip time re-measuring it via the
        # battery's 'rest' sentinel
        say("lstm already measured by lstm_hoist_ab; skipping")
        return
    # the canonical record is the PACKAGE DEFAULT config: pin the hoist
    # on (saved/restored like every sibling phase) so an inherited
    # MXTPU_RNN_HOIST=0 cannot silently degenerate the A/B
    saved = os.environ.get("MXTPU_RNN_HOIST")
    os.environ["MXTPU_RNN_HOIST"] = "1"
    try:
        out("lstm", bench._stamp(bench.bench_lstm_ptb()))
        _LSTM_MEASURED = True
    finally:
        if saved is None:
            os.environ.pop("MXTPU_RNN_HOIST", None)
        else:
            os.environ["MXTPU_RNN_HOIST"] = saved


def phase_lstm_hoist_ab():
    """Same-session A/B of the round-5 input-GEMM hoist: the cross-
    session delta (151,009 -> 143,137 tok/s) was inside the day's
    variance envelope and unattributable. The hoisted leg IS the
    canonical "lstm" record (package default config)."""
    global _LSTM_MEASURED
    import bench
    saved = os.environ.get("MXTPU_RNN_HOIST")
    try:
        if not _LSTM_MEASURED:   # canonical record (skip if lstm ran first)
            os.environ["MXTPU_RNN_HOIST"] = "1"
            out("lstm", bench._stamp(bench.bench_lstm_ptb()))
            _LSTM_MEASURED = True
        os.environ["MXTPU_RNN_HOIST"] = "0"
        rec = bench._stamp(bench.bench_lstm_ptb())
        rec["note"] = "input GEMM inside the scan (pre-hoist lowering)"
        out("lstm_nohoist", rec)
    finally:
        if saved is None:
            os.environ.pop("MXTPU_RNN_HOIST", None)
        else:
            os.environ["MXTPU_RNN_HOIST"] = saved


def phase_bert():
    import bench
    out("bert", bench._stamp(bench.bench_bert_base()))


def phase_eager():
    import bench
    out("eager", bench._stamp(bench.bench_eager()))


def phase_bandwidth():
    """h2d/d2h transfer bandwidth (tools/bandwidth.py link #1) inside the
    shared session — no compiles, a few seconds."""
    import bandwidth as bw
    for mb in (16, 64):
        h2d, d2h = bw.measure_transfer(mb << 20)
        out("bandwidth", {"size_mb": mb, "h2d_gbps": round(h2d, 2),
                          "d2h_gbps": round(d2h, 2)})


def phase_ring():
    """Ring-flash lever (MXTPU_RING_FLASH) has no single-chip effect —
    covered by the bert config's flash kernel; placeholder for parity."""


def phase_stem_breakdown():
    """Name the stem sink. The prefix-stage data says stem fwd+bwd is
    ~14 ms of the 50.6 ms step, vs ~1 ms of pure conv FLOPs — suspects:
    the C=3 input conv (3 of 128 lanes live), the maxpool backward
    (scatter), or BN. Times each stem variant fwd and fwd+bwd, b128."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from mxtpu.parallel import pure_forward
    import mxtpu as mx

    batch = int(os.environ.get("BENCH_BATCH", "128"))
    x = jax.random.normal(jax.random.PRNGKey(0), (batch, 224, 224, 3),
                          jnp.bfloat16)

    def build(kind):
        with mx.layout("NHWC"):
            blk = mx.gluon.nn.HybridSequential()
            if kind == "conv":
                blk.add(mx.gluon.nn.Conv2D(64, 7, strides=2, padding=3,
                                           use_bias=False))
            elif kind in ("conv_bn", "conv_bn_pool", "conv_bn_avgpool"):
                blk.add(mx.gluon.nn.Conv2D(64, 7, strides=2, padding=3,
                                           use_bias=False))
                blk.add(mx.gluon.nn.BatchNorm())
                blk.add(mx.gluon.nn.Activation("relu"))
                if kind == "conv_bn_pool":
                    blk.add(mx.gluon.nn.MaxPool2D(3, 2, 1))
                elif kind == "conv_bn_avgpool":
                    blk.add(mx.gluon.nn.AvgPool2D(3, 2, 1))
            elif kind == "s2d_conv_bn_pool":
                # ~the BENCH_S2D_STEM shape (contrib/s2d_stem.py): ONE 2x2
                # s2d -> 112^2 x 12, then a 4x4 stride-1 conv. The real
                # lever pads (2,1) asymmetrically (112^2 out); gluon pads
                # symmetrically -> 113^2, +1.8% pixels — close enough for
                # a sink-naming probe.
                blk.add(mx.gluon.nn.Conv2D(64, 4, strides=1, padding=2,
                                           use_bias=False))
                blk.add(mx.gluon.nn.BatchNorm())
                blk.add(mx.gluon.nn.Activation("relu"))
                blk.add(mx.gluon.nn.MaxPool2D(3, 2, 1))
        return blk

    from mxtpu.contrib.s2d_stem import space_to_depth_nhwc

    for kind in ("conv", "conv_bn", "conv_bn_pool", "conv_bn_avgpool",
                 "s2d_conv_bn_pool"):
        blk = build(kind)
        xin = space_to_depth_nhwc(x) if kind.startswith("s2d") else x
        blk.initialize()
        blk(mx.nd.array(np.zeros(xin.shape, np.float32)))
        blk.cast("bfloat16")
        fn, params = pure_forward(blk, train=True)
        dt_f = timed_scan(reinject(lambda t, fn=fn, p=params: fn(p, t)), xin)

        def step(t, fn=fn, p=params):
            g = jax.grad(lambda tt: jnp.sum(
                fn(p, tt).astype(jnp.float32) ** 2))(t)
            return t + 1e-6 * g.astype(t.dtype)
        dt_fb = timed_scan(step, xin)
        out("stem", {"case": kind, "fwd_ms": round(dt_f * 1e3, 3),
                     "fwdbwd_ms": round(dt_fb * 1e3, 3)})


def phase_resnet_best():
    """The combo the battery never measured: BN one-pass + s2d stem
    WITHOUT conv_acc (conv_acc alone measured -2.8% end-to-end)."""
    _resnet("resnet_best", MXTPU_CONV_ACC="0", BENCH_S2D_STEM="1",
            MXTPU_BN_ONEPASS="1", MXTPU_CONV_IM2COL="0")


def phase_resnet_s2d2():
    """Double-s2d stem (mode 2: MXU-shaped 56^2 x 48 -> 256ch 3x3 conv +
    depth-to-space) on top of the best-known config — the staged answer
    to the stem-breakdown finding that mode 1 does not fix the stem."""
    _resnet("resnet_s2d2", MXTPU_CONV_ACC="0", BENCH_S2D_STEM="2",
            MXTPU_BN_ONEPASS="1", MXTPU_CONV_IM2COL="0")


def phase_resnet_s2d2_im2col():
    """Do the two staged levers stack? The mode-2 stem conv (3x3 s1,
    C_in=48) itself qualifies for the im2col lowering."""
    _resnet("resnet_s2d2_im2col", MXTPU_CONV_ACC="0", BENCH_S2D_STEM="2",
            MXTPU_BN_ONEPASS="1", MXTPU_CONV_IM2COL="1")


def phase_resnet_pallas():
    """THE round-7 kernel, end to end: the Pallas implicit-GEMM conv
    (mxtpu/ops/pallas/conv.py) on the MXU-underfilled stem/1x1/small-C
    classes (PERF.md: stem + stage2 = 78% of the step at 15% of the
    FLOPs), on top of the best-known flag set with the PLAIN stem so the
    kernel sees the true 7x7s2 conv."""
    _resnet("resnet_pallas", MXTPU_PALLAS_CONV="1", MXTPU_CONV_ACC="0",
            MXTPU_BN_ONEPASS="1", BENCH_S2D_STEM="0", MXTPU_CONV_IM2COL="0")


def phase_resnet_pallas_s2d2():
    """Composition check: the double-s2d stem replaces the 7x7 (so Pallas
    only sees the 1x1/small-C classes) — do the two levers stack? Both
    ride one jit cache key (policy_key), so this is a clean in-session
    A/B against resnet_pallas and resnet_s2d2."""
    _resnet("resnet_pallas_s2d2", MXTPU_PALLAS_CONV="1", MXTPU_CONV_ACC="0",
            MXTPU_BN_ONEPASS="1", BENCH_S2D_STEM="2", MXTPU_CONV_IM2COL="0")


def phase_conv_class():
    """Kernel-level attribution through the bench config (one JSON line
    per conv class x impl, XLA vs Pallas) — the numbers that used to live
    only in this tool's phase_convs now land in the driver artifact."""
    import bench
    out("conv_class", bench.bench_conv_class(
        emit=lambda rec: out("conv_class", bench._stamp(rec))))


def phase_resnet_im2col():
    """Small-channel convs via explicit im2col + matmul (staged,
    MXTPU_CONV_IM2COL): the conv path measured ~7 TFLOP/s on the early
    3x3s while the matmul path measures 102-135 — this phase prices the
    trade end to end on the best-known config."""
    _resnet("resnet_im2col", MXTPU_CONV_ACC="0", BENCH_S2D_STEM="1",
            MXTPU_BN_ONEPASS="1", MXTPU_CONV_IM2COL="1")


def phase_flash_pad():
    """Head-dim-64 flash path: correctness (kernel vs XLA fallback, on
    chip) and fwd+bwd step time with padding vs the old [T,T] fallback.
    BERT-base attention shape: b16 h12 T512 D64 bf16."""
    import importlib
    import numpy as np
    import jax
    import jax.numpy as jnp
    # NOT `from mxtpu.ops.pallas import flash_attention` — the package
    # re-exports the FUNCTION under that name, shadowing the module
    fa_mod = importlib.import_module("mxtpu.ops.pallas.flash_attention")
    fa = fa_mod.flash_attention

    b, h, t, d = 16, 12, 512, 64
    key = jax.random.PRNGKey(0)
    q = jax.random.normal(key, (b, h, t, d), jnp.bfloat16)
    k = jax.random.normal(jax.random.PRNGKey(1), (b, h, t, d), jnp.bfloat16)
    v = jax.random.normal(jax.random.PRNGKey(2), (b, h, t, d), jnp.bfloat16)

    # correctness on the real chip: padded kernel vs XLA reference
    got = np.asarray(jax.device_get(fa(q, k, v)), np.float32)
    ref = np.asarray(jax.device_get(
        fa_mod._xla_attention(q, k, v, False, d ** -0.5)), np.float32)
    max_err = float(np.max(np.abs(got - ref)))
    out("flash_pad", {"case": "d64_correctness_maxerr", "value": max_err})
    assert max_err < 0.05, "padded flash kernel diverges: %g" % max_err

    def train_step(mode):
        saved = os.environ.get("MXTPU_FLASH_PAD_D")
        os.environ["MXTPU_FLASH_PAD_D"] = mode
        try:
            def loss(q_):
                o = fa(q_, k, v)
                return jnp.sum(o.astype(jnp.float32) ** 2)
            g = jax.grad(loss)
            dt = timed_scan(lambda q_: q_ + 1e-6 * g(q_).astype(q_.dtype), q)
        finally:
            if saved is None:
                os.environ.pop("MXTPU_FLASH_PAD_D", None)
            else:
                os.environ["MXTPU_FLASH_PAD_D"] = saved
        return dt

    dt_pad = train_step("1")
    out("flash_pad", {"case": "d64_fwd_bwd_padded_kernel",
                      "ms": round(dt_pad * 1e3, 3)})
    dt_fb = train_step("0")
    out("flash_pad", {"case": "d64_fwd_bwd_xla_fallback",
                      "ms": round(dt_fb * 1e3, 3),
                      "speedup": round(dt_fb / dt_pad, 3)})


def phase_bert_pad_ab():
    """End-to-end bert A/B: flash D-64 padding ON (new default) vs the
    old HBM-cliff fallback."""
    import bench
    saved = os.environ.get("MXTPU_FLASH_PAD_D")
    try:
        os.environ["MXTPU_FLASH_PAD_D"] = "1"
        out("bert_pad", bench._stamp(bench.bench_bert_base()))
        os.environ["MXTPU_FLASH_PAD_D"] = "0"
        rec = bench._stamp(bench.bench_bert_base())
        rec["note"] = "old fallback (pad disabled)"
        out("bert_nopad", rec)
    finally:
        if saved is None:
            os.environ.pop("MXTPU_FLASH_PAD_D", None)
        else:
            os.environ["MXTPU_FLASH_PAD_D"] = saved


PHASES = [
    ("probe", phase_probe),
    ("resnet_control", phase_resnet_control),
    ("resnet_conv_acc", phase_resnet_conv_acc),
    ("resnet_s2d", phase_resnet_s2d),
    ("resnet_bn_onepass", phase_resnet_bn1p),
    ("resnet_all_levers", phase_resnet_all_levers),
    ("stages", phase_stages),
    ("convs", phase_convs),
    ("resnet_nchw", phase_resnet_nchw),
    ("bn", phase_bn),
    ("peak", phase_peak),
    ("eager", phase_eager),
    ("bandwidth", phase_bandwidth),
    ("lstm", phase_lstm),
    ("bert", phase_bert),
    ("resnet_best", phase_resnet_best),
    ("resnet_pallas", phase_resnet_pallas),
    ("resnet_pallas_s2d2", phase_resnet_pallas_s2d2),
    ("conv_class", phase_conv_class),
    ("resnet_s2d2", phase_resnet_s2d2),
    ("resnet_im2col", phase_resnet_im2col),
    ("resnet_s2d2_im2col", phase_resnet_s2d2_im2col),
    ("lstm_hoist_ab", phase_lstm_hoist_ab),
    ("flash_pad", phase_flash_pad),
    ("bert_pad_ab", phase_bert_pad_ab),
    ("stem_breakdown", phase_stem_breakdown),
]


def main():
    want = sys.argv[1:]
    by_name = dict(PHASES)
    bad = [w for w in want if w not in by_name and w != "rest"]
    if bad:
        # a typo must not silently burn the rare healthy-chip session
        sys.exit("unknown phase(s) %s; valid: %s (+ the sentinel 'rest')"
                 % (bad, " ".join(sorted(by_name))))
    # ARGUMENT order is execution order: the caller ranks phases by value
    # so a mid-session wedge costs the tail, not the headline number. The
    # sentinel 'rest' expands (at its position) to every phase NOT named
    # explicitly anywhere in argv — so a ranked list can never silently
    # drop a newly added phase, and a phase named AFTER 'rest' keeps its
    # explicit position instead of being swallowed by the expansion.
    if want:
        explicit = {n for n in want if n != "rest"}
        run = []
        for n in want:
            if n == "rest":
                run += [(pn, fn) for pn, fn in PHASES
                        if pn not in explicit
                        and pn not in [r[0] for r in run]]
            elif n not in [r[0] for r in run]:
                run.append((n, by_name[n]))
    else:
        run = PHASES
    for name, fn in run:
        say("phase %s" % name)
        try:
            fn()
        except Exception as e:  # noqa: BLE001 — later phases still run
            out(name, {"error": "%s: %s" % (type(e).__name__, e)})
    say("session complete")


if __name__ == "__main__":
    main()
