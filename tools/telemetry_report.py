#!/usr/bin/env python
"""Summarize a telemetry JSONL file into the aggregate table.

The sink (``MXTPU_TELEMETRY=<path>``, mxtpu/telemetry.py) streams one line
per histogram observation plus cumulative counter/gauge lines at each
flush. This tool folds a file of those lines into the per-metric table —
count / mean / p50 / p99 / max for observations, the final cumulative
value for counters, the last write for gauges — the telemetry analog of
``profiler.dumps()``'s aggregate stats, runnable after the fact on a
battery artifact (tools/perf_battery.sh runs it after each session).

With causal tracing on (``MXTPU_TRACE``, default 1), span observations
carry their trace linkage (``trace``/``span``/``parent`` keys) and
``--traces [K]`` adds the per-trace critical-path view: the top-K traces
by total latency, each with its span count and SLOWEST stage — the
"which stage made this request/step slow" question answered from the
artifact alone, no live repro.

With the executable observatory on (``MXTPU_XPROF``, default 1), each
flush also streams ``kind="ledger"`` lines — one per jit-site executable
with its cost-model FLOPs/bytes, HBM footprint, and compile wall-time —
and ``--ledger`` renders the per-site roofline table: arithmetic
intensity vs the chip's ridge point → compute- vs memory-bound verdict,
plus the ranked hand-kernel (Pallas) candidate list — the fusion-gap
methodology of arXiv:2301.13062 as a standing report. The "achieved"
column folds in the site's own span p50 where one exists (e.g.
``serving.predict``) — an approximation (host dispatch wall time, not
device occupancy), printed only where the span times the dispatch.

``--tuning-queue <json>`` (implies ``--ledger``) writes the ranked
memory-bound candidate list as the Pallas autotuner's work order —
site, captured argument shapes, intensity, verdict, executed FLOPs —
which ``tools/autotune_session.py`` consumes top-down (docs/autotune.md,
the observe → tune → persist → serve loop).

Multi-host runs produce one sink PER HOST: any path argument may be a
directory (every ``*.jsonl`` inside) or a glob, and several paths are
merged — counters fold per-file then sum, gauges take the freshest
write, and duplicated trace-linked observations collapse on their
``(trace, span)`` identity (trace ids carry the originating pid prefix,
so cross-host lines never collide and true copies dedup cleanly).

``--fleet <dir>`` points at a fleet board directory (``MXTPU_FLEET_DIR``
generation dir) and renders the ISSUE-19 merged fleet view on top: the
``FleetObservatory`` per-host/aggregate snapshot from the ``obs_*.json``
blobs plus the per-step critical path stitched from the step-barrier
payloads — which rank arrived last and which stage made it late.

Usage::

    python tools/telemetry_report.py <jsonl|dir|glob>... [--json]
        [--traces [K]] [--ledger] [--tuning-queue <json>]
        [--fleet <board-dir>]
"""
from __future__ import annotations

import glob as _glob
import json
import os
import sys


def _quantile(sorted_vals, q):
    n = len(sorted_vals)
    if n == 0:
        return None
    pos = q * (n - 1)
    lo = int(pos)
    hi = min(lo + 1, n - 1)
    frac = pos - lo
    return sorted_vals[lo] * (1 - frac) + sorted_vals[hi] * frac


def aggregate(lines):
    """Fold decoded JSONL records into {metric: summary-dict}.

    Counters are cumulative WITHIN one process and repeat per flush, but
    several sessions may append to one file (perf_battery.sh shares a
    single MXTPU_TELEMETRY path across the battery, benchmark_score, and
    bandwidth runs, each restarting at 0) — so they fold Prometheus-style:
    a value that DROPS marks a process restart, banking the previous
    session's total. Multi-file merges (``load_many``) tag records with
    their source file index ``_src``: the restart fold then runs PER
    FILE and the per-file totals sum — two hosts' cumulative streams
    never alias each other's banking. Gauges take the freshest write
    (by record timestamp, stream order on ties); observation streams
    get count/mean/p50/p99/min/max."""
    obs = {}
    counters = {}   # (src, key) -> [banked_total, last_seen_in_session]
    gauges = {}     # key -> (t, value)
    for rec in lines:
        kind = rec.get("kind")
        name = rec.get("metric")
        if name is None:
            continue
        if kind == "obs":
            obs.setdefault(name, []).append(float(rec["value"]))
        elif kind == "counter":
            tag = rec.get("tag")
            key = "%s{%s}" % (name, tag) if tag else name
            ckey = (rec.get("_src"), key)
            banked, last = counters.get(ckey, (0, 0))
            if rec["value"] < last:  # process restart: bank the old run
                banked += last
            counters[ckey] = (banked, rec["value"])
        elif kind == "gauge":
            tag = rec.get("tag")
            key = "%s{%s}" % (name, tag) if tag else name
            t = rec.get("t")
            prev = gauges.get(key)
            if prev is None or t is None or prev[0] is None or t >= prev[0]:
                gauges[key] = (t, float(rec["value"]))
    totals = {}
    for (_src, key), (banked, last) in counters.items():
        totals[key] = totals.get(key, 0) + banked + last
    counters = totals
    gauges = {k: v for k, (_t, v) in gauges.items()}
    out = {}
    for name, vals in obs.items():
        vals.sort()
        out[name] = {"kind": "obs", "count": len(vals),
                     "mean": sum(vals) / len(vals),
                     "min": vals[0], "max": vals[-1],
                     "p50": _quantile(vals, 0.5),
                     "p99": _quantile(vals, 0.99)}
    for name, v in counters.items():
        out[name] = {"kind": "counter", "count": v, "value": v}
    for name, v in gauges.items():
        out[name] = {"kind": "gauge", "value": v}
    return out


def trace_summary(lines, top=10):
    """Fold trace-linked observations into the per-trace critical-path
    view: ``[{trace, total, spans, slowest, slowest_s, slowest_frac,
    stages}]`` sorted by total latency, truncated to ``top``.

    Total latency is the sum of ROOT-level stages (``parent == 0``) —
    for a served request those are exactly the breakdown stages
    (submit + queue-wait + pad + predict + fetch + deliver ≈ e2e), for a
    training step the ``trainer.step`` span itself; nested child spans
    must not double-count into the total but DO compete for slowest."""
    traces = {}
    for rec in lines:
        if rec.get("kind") != "obs" or rec.get("trace") is None:
            continue
        t = traces.setdefault(rec["trace"], {"stages": [], "root_s": 0.0})
        v = float(rec["value"])
        t["stages"].append((rec["metric"], v))
        if not rec.get("parent"):
            t["root_s"] += v
    rows = []
    for tid, t in traces.items():
        total = t["root_s"] or sum(v for _, v in t["stages"])
        agg = {}
        for name, v in t["stages"]:
            agg[name] = agg.get(name, 0.0) + v
        slowest = max(agg.items(), key=lambda kv: kv[1])
        rows.append({"trace": tid, "total": total,
                     "spans": len(t["stages"]),
                     "slowest": slowest[0], "slowest_s": slowest[1],
                     "slowest_frac": slowest[1] / total if total else 0.0,
                     "stages": agg})
    rows.sort(key=lambda r: -r["total"])
    return rows[:top]


def format_trace_table(rows):
    if not rows:
        return "(no trace-linked records — is MXTPU_TRACE on?)"
    lines = ["%-14s %10s %6s  %-28s %10s %6s" %
             ("Trace", "Total(ms)", "Spans", "Slowest stage", "ms", "%")]
    for r in rows:
        lines.append("%-14s %10.3f %6d  %-28s %10.3f %5.1f%%" %
                     (r["trace"], r["total"] * 1e3, r["spans"],
                      r["slowest"], r["slowest_s"] * 1e3,
                      r["slowest_frac"] * 100))
    return "\n".join(lines)


def ledger_summary(lines):
    """Fold ``kind=="ledger"`` records into per-executable roofline rows.

    Ledger lines are cumulative like the counters (one batch per flush):
    the LAST line per (site, seq) wins. Returns ``(rows, candidates)``
    where candidates is the memory-bound shortlist ranked by executed
    FLOPs (flops x calls) — the entries where a hand kernel buys the
    most."""
    entries = {}
    obs = {}
    for rec in lines:
        kind = rec.get("kind")
        if kind == "ledger" and rec.get("site") is not None:
            entries[(rec["site"], rec.get("seq"))] = rec
        elif kind == "obs" and rec.get("metric") is not None:
            obs.setdefault(rec["metric"], []).append(float(rec["value"]))
    rows = []
    for (site, seq), e in sorted(entries.items(),
                                 key=lambda kv: kv[0][1] or 0):
        fl = e.get("flops")
        row = {"site": site, "seq": seq, "calls": int(e.get("calls") or 0),
               "compile_s": e.get("compile_s"), "flops": fl,
               "bytes_accessed": e.get("bytes_accessed"),
               "intensity": e.get("intensity"),
               "critical_intensity": e.get("critical_intensity"),
               "verdict": e.get("verdict"), "error": e.get("error"),
               "shapes": e.get("shapes")}
        vals = obs.get(site)
        if vals and fl:
            vals = sorted(vals)
            p50 = _quantile(vals, 0.5)
            if p50:
                row["achieved_flops_per_s"] = fl / p50
        rows.append(row)
    cands = [r for r in rows if r.get("verdict") == "memory"
             and r.get("flops")]
    cands.sort(key=lambda r: -(r["flops"] * max(r["calls"], 1)))
    return rows, cands


def format_ledger_table(rows, cands):
    if not rows:
        return ("(no ledger records — is MXTPU_XPROF on, and did the "
                "process flush its telemetry sink?)")
    lines = ["%-30s %7s %9s %9s %9s %8s %8s  %s" %
             ("Site#seq", "Calls", "Compile(s)", "GFLOP", "MB-acc",
              "FLOP/B", "Achieved", "Verdict")]
    for r in rows:
        ach = r.get("achieved_flops_per_s")
        lines.append("%-30s %7d %9s %9s %9s %8s %8s  %s" % (
            "%s#%s" % (r["site"], r["seq"]), r["calls"],
            "%.3f" % r["compile_s"] if r.get("compile_s") else "-",
            "%.2f" % (r["flops"] / 1e9) if r.get("flops") else "-",
            "%.1f" % (r["bytes_accessed"] / 1e6)
            if r.get("bytes_accessed") else "-",
            "%.1f" % r["intensity"] if r.get("intensity") else "-",
            "%.1fT" % (ach / 1e12) if ach else "-",
            r.get("error") or r.get("verdict")
            or "unknown (no chip ridge)"))
    if cands:
        lines.append("")
        lines.append("Pallas candidates (memory-bound, by executed "
                     "FLOPs): " + ", ".join(
                         "%s#%s" % (r["site"], r["seq"])
                         for r in cands[:8]))
    return "\n".join(lines)


def tuning_queue(rows, cands):
    """The ledger's memory-bound shortlist as the autotuner's work order:
    ``{"format": 1, "queue": [{site, seq, shapes, intensity, verdict,
    calls, executed_gflops}, ...]}`` ranked by executed FLOPs — the
    order ``tools/autotune_session.py`` consumes top-down (tune where a
    better block plan buys the most first)."""
    queue = []
    for r in cands:
        queue.append({"site": r["site"], "seq": r["seq"],
                      "shapes": r.get("shapes"),
                      "intensity": r.get("intensity"),
                      "verdict": r.get("verdict"),
                      "calls": r["calls"],
                      "executed_gflops": (r["flops"] * max(r["calls"], 1)
                                          / 1e9)})
    return {"format": 1, "queue": queue}


def load(path):
    records = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except ValueError:
                # a torn final line from a killed process must not void
                # the rest of the artifact
                continue
    return records


def expand_paths(paths):
    """Each argument may be a file, a directory (every ``*.jsonl``
    inside), or a glob pattern; returns the flat sorted file list."""
    out = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(sorted(_glob.glob(os.path.join(p, "*.jsonl"))))
        elif any(ch in p for ch in "*?["):
            out.extend(sorted(_glob.glob(p)))
        else:
            out.append(p)
    return out


def load_many(paths):
    """Merge several sink files: records gain a ``_src`` file index (the
    per-file counter-banking key), and trace-linked observation lines
    that appear in more than one file collapse on ``(trace, span,
    metric)`` — the trace id's process prefix makes that identity
    host-unique, so only true duplicates dedup."""
    records = []
    seen = set()
    for i, path in enumerate(expand_paths(paths)):
        for rec in load(path):
            if rec.get("kind") == "obs" and rec.get("trace") is not None:
                key = (rec["trace"], rec.get("span"), rec.get("metric"))
                if key in seen:
                    continue
                seen.add(key)
            rec["_src"] = i
            records.append(rec)
    return records


def format_table(summary):
    lines = []
    obs = {n: s for n, s in summary.items() if s["kind"] == "obs"}
    if obs:
        lines.append("%-38s %8s %12s %12s %12s %12s" %
                     ("Metric", "Count", "Mean", "P50", "P99", "Max"))
        for name in sorted(obs, key=lambda n: -obs[n]["mean"] * obs[n]["count"]):
            s = obs[name]
            lines.append("%-38s %8d %12.6g %12.6g %12.6g %12.6g" %
                         (name, s["count"], s["mean"], s["p50"], s["p99"],
                          s["max"]))
    rest = {n: s for n, s in summary.items() if s["kind"] != "obs"}
    if rest:
        if lines:
            lines.append("")
        lines.append("%-38s %8s %12s" % ("Counter/Gauge", "Kind", "Value"))
        for name in sorted(rest):
            s = rest[name]
            lines.append("%-38s %8s %12g" % (name, s["kind"], s["value"]))
    return "\n".join(lines) if lines else "(no telemetry records)"


def format_fleet(merged, steps):
    """The merged fleet view + per-step critical path as text tables."""
    fl = merged["fleet"]
    lines = ["Fleet: %d/%d host(s) up | mfu=%s | step p50=%s p99=%s" % (
        fl["hosts_up"], fl["hosts_seen"],
        "%.3f" % fl["mfu"] if fl.get("mfu") is not None else "-",
        "%.4gs" % fl["step_s"]["p50"]
        if fl["step_s"].get("p50") is not None else "-",
        "%.4gs" % fl["step_s"]["p99"]
        if fl["step_s"].get("p99") is not None else "-")]
    lines.append("")
    lines.append("%4s %-10s %6s %8s %12s %12s %10s" % (
        "Rank", "Status", "Step", "MFU", "Step p50", "Step p99", "HB age"))
    for rank in sorted(merged["hosts"]):
        h = merged["hosts"][rank]
        ss = h["step_s"]
        lines.append("%4d %-10s %6s %8s %12s %12s %10s" % (
            rank, h.get("status") or "-",
            "-" if h.get("step") is None else h["step"],
            "%.3f" % h["mfu"] if h.get("mfu") is not None else "-",
            "%.4gs" % ss["p50"] if ss.get("p50") is not None else "-",
            "%.4gs" % ss["p99"] if ss.get("p99") is not None else "-",
            "%.1fs" % h["heartbeat_age_s"]
            if h.get("heartbeat_age_s") is not None else "-"))
    if steps:
        lines.append("")
        lines.append("Per-step critical path (who arrived last, and why):")
        lines.append("%6s %6s %10s %10s  %-28s %-14s" % (
            "Step", "Last", "Skew(ms)", "Step(ms)", "Dominant stage",
            "Trace"))
        for r in steps:
            lines.append("%6d %6d %10s %10s  %-28s %-14s" % (
                r["step"], r["last_rank"],
                "%.2f" % (r["skew_s"] * 1e3)
                if r.get("skew_s") is not None else "-",
                "%.2f" % (r["step_s"] * 1e3)
                if r.get("step_s") is not None else "-",
                r.get("dominant_stage") or "-", r.get("trace") or "-"))
    else:
        lines.append("")
        lines.append("(no stitched step-barrier payloads on the board)")
    return "\n".join(lines)


def main(argv):
    argv = list(argv)
    as_json = "--json" in argv
    with_ledger = "--ledger" in argv
    fleet_dir = None
    if "--fleet" in argv:
        nxt = argv.index("--fleet") + 1
        if nxt >= len(argv):
            print("--fleet needs a board directory", file=sys.stderr)
            return 1
        fleet_dir = argv.pop(nxt)    # consume BY INDEX, like --traces
    top = None
    if "--traces" in argv:
        top = 10
        nxt = argv.index("--traces") + 1
        if nxt < len(argv) and argv[nxt].isdigit():
            # consume the count token BY INDEX: a data file that happens
            # to be named like the number must not be dropped from paths
            top = int(argv.pop(nxt))
    queue_path = None
    if "--tuning-queue" in argv:
        nxt = argv.index("--tuning-queue") + 1
        if nxt >= len(argv):
            print("--tuning-queue needs an output path", file=sys.stderr)
            return 1
        queue_path = argv.pop(nxt)   # consume BY INDEX, like --traces
        with_ledger = True           # the queue IS a ledger product
    paths = [a for a in argv if not a.startswith("-")]
    if (not paths and fleet_dir is None) or "-h" in argv or "--help" in argv:
        print(__doc__)
        return 0 if "-h" in argv or "--help" in argv else 1
    records = load_many(paths)
    fleet_view = None
    if fleet_dir is not None:
        # lazy: the plain report stays stdlib-only; the fleet merge
        # reuses the observatory itself rather than re-implementing it
        sys.path.insert(0, os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        from mxtpu import fleet_obs
        fleet_view = (
            fleet_obs.FleetObservatory(fleet_dir).merged(),
            fleet_obs.step_traces(fleet_dir))
    summary = aggregate(records)
    traces = trace_summary(records, top=top) if top is not None else None
    ledger = ledger_summary(records) if with_ledger else None
    if queue_path is not None:
        q = tuning_queue(*ledger)
        with open(queue_path, "w", encoding="utf-8") as f:
            json.dump(q, f, sort_keys=True, indent=1)
        print("tuning queue: %d site(s) -> %s"
              % (len(q["queue"]), queue_path), file=sys.stderr)
    if as_json:
        out = dict(summary)
        if traces is not None:
            out["_traces"] = traces
        if ledger is not None:
            out["_ledger"] = {"rows": ledger[0],
                              "candidates": ["%s#%s" % (r["site"], r["seq"])
                                             for r in ledger[1]]}
        if fleet_view is not None:
            out["_fleet"] = {"merged": fleet_view[0],
                             "steps": fleet_view[1]}
        print(json.dumps(out, sort_keys=True))
    else:
        if paths:
            print(format_table(summary))
        if traces is not None:
            print()
            print(format_trace_table(traces))
        if ledger is not None:
            print()
            print(format_ledger_table(*ledger))
        if fleet_view is not None:
            if paths:
                print()
            print(format_fleet(*fleet_view))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
