"""Environment + accelerator diagnostics (ref: tools/diagnose.py, which
dumps platform/version/connectivity info for bug reports).

TPU-native re-design: the flaky link on this runtime is the device
tunnel, so the centerpiece is a WEDGE-SAFE backend probe — device
discovery and a trivial dispatch run in a SUBPROCESS under a timeout, so
a hung PJRT client can never hang the diagnostic itself (the same
isolation bench.py's preflight uses; see PERF.md on the round-3 wedge).

Usage:
    python tools/diagnose.py [--timeout 90]

Verdicts: HEALTHY (dispatch round-trips; RTT printed), WEDGED (devices
or dispatch never answered — the round-3 signature), BROKEN (import or
backend registration failed), CPU-ONLY (no accelerator platform).
"""
import argparse
import os
import platform
import subprocess
import sys
import time

_TOOLS = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_TOOLS)
for _p in (_REPO, _TOOLS):
    if _p not in sys.path:
        sys.path.insert(0, _p)

import perf_probe  # noqa: E402 — ONE copy of the wedge-safe jit probe,
# shared with bench.py's preflight (tools/perf_probe.py)


def section(title):
    print("\n----- %s -----" % title)


def versions():
    section("versions")
    print("python   :", sys.version.split()[0], platform.platform())
    for mod in ("jax", "jaxlib", "numpy", "flax", "optax", "orbax"):
        try:
            m = __import__(mod)
            print("%-9s: %s" % (mod, getattr(m, "__version__", "?")))
        except Exception as e:  # noqa: BLE001
            print("%-9s: unavailable (%s)" % (mod, e))
    try:
        import mxtpu
        print("mxtpu    :", getattr(mxtpu, "__version__", "dev"),
              os.path.dirname(mxtpu.__file__))
    except Exception as e:  # noqa: BLE001
        print("mxtpu    : IMPORT FAILED (%s)" % e)


def environment():
    section("environment")
    for k in sorted(os.environ):
        if k.startswith(("MXTPU_", "MXNET_", "JAX_", "XLA_", "LIBTPU_",
                         "PALLAS_", "AXON_", "TPU_")):
            v = os.environ[k]
            if any(t in k.upper() for t in ("TOKEN", "SECRET", "KEY")):
                v = "<redacted>"
            print("%s=%s" % (k, v))


def native_lib():
    section("native library")
    try:
        from mxtpu._native import build_error, get_lib
        lib = get_lib()
        print("_libmxtpu.so:", "loaded" if lib else
              "build failed: %s" % build_error())
    except Exception as e:  # noqa: BLE001
        print("_libmxtpu.so: unavailable (%s)" % e)


def backend_probe(timeout_s):
    """The wedge-safe accelerator check; returns the verdict string."""
    section("backend probe (subprocess, %ds timeout)" % timeout_s)
    t0 = time.time()
    try:
        out = subprocess.run([sys.executable, "-u", "-c",
                              perf_probe.PROBE_SNIPPET],
                             capture_output=True, text=True,
                             timeout=timeout_s)
    except subprocess.TimeoutExpired as e:
        got = (e.stdout or b"").decode() if isinstance(e.stdout, bytes) \
            else (e.stdout or "")
        stage = "device discovery" if "devices" not in got else "dispatch"
        print(got.strip())
        print("VERDICT: WEDGED — %s did not answer in %ds (the round-3 "
              "tunnel-wedge signature; see PERF.md). A healthy chip "
              "answers in seconds." % (stage, timeout_s))
        return "WEDGED"
    print(out.stdout.strip())
    if out.returncode != 0:
        print(out.stderr.strip()[-800:])
        print("VERDICT: BROKEN — backend failed to initialize "
              "(%.1fs)" % (time.time() - t0))
        return "BROKEN"
    stages = perf_probe.parse(out.stdout)
    verdict = "CPU-ONLY" if stages.get("platform") == "cpu" else "HEALTHY"
    print("VERDICT: %s (platform %s, %.1fs total)"
          % (verdict, stages.get("platform", "?"), time.time() - t0))
    return verdict


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--timeout", type=int, default=90)
    ns = ap.parse_args(argv)
    versions()
    environment()
    native_lib()
    verdict = backend_probe(ns.timeout)
    return 0 if verdict in ("HEALTHY", "CPU-ONLY") else 1


if __name__ == "__main__":
    sys.exit(main())
