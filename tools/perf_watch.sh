#!/bin/bash
# Chip-recovery watcher: probe the accelerator every PROBE_INTERVAL
# seconds; on the FIRST healthy probe, immediately launch the full
# measurement battery (tools/perf_battery.sh) and exit.
#
# Round-4 lesson (VERDICT r4, weak #6): the prober existed but recovery
# was manual, so round 4's one healthy 10-minute window produced only
# two numbers. This watcher closes that loop — no human in the path
# between "chip answers" and "battery running".
#
# Probe cost: each failed probe is one PJRT client that hangs and is
# killed; on an already-wedged tunnel this is a no-op (the wedge
# predates us). The probe is the same staged snippet bench.py uses.
set -u
cd "$(dirname "$0")/.."
LOG=${1:-perf_watch.log}
INTERVAL=${PROBE_INTERVAL:-1200}
echo "[watch $(date +%H:%M:%S)] start, probing every ${INTERVAL}s" | tee -a "$LOG"
while true; do
  if timeout 90 python -u -c "
import jax, jax.numpy as jnp, numpy as np
np.asarray(jax.device_get(jax.jit(lambda v: v+1)(jnp.ones(2))))
print('chip alive')" >/dev/null 2>&1; then
    echo "[watch $(date +%H:%M:%S)] CHIP HEALTHY -> launching battery" | tee -a "$LOG"
    sleep 20   # claim-release grace before the battery's own probe
    bash tools/perf_battery.sh perf_battery.log 2>&1 | tee -a "$LOG"
    echo "[watch $(date +%H:%M:%S)] battery finished" | tee -a "$LOG"
    exit 0
  fi
  echo "[watch $(date +%H:%M:%S)] wedged, retry in ${INTERVAL}s" | tee -a "$LOG"
  sleep "$INTERVAL"
done
