"""Communication-bandwidth measurement kit (ref: tools/bandwidth/measure.py,
which times kvstore push+pull of a model's weight shapes across devices).

TPU-native re-design: the three links that matter on this runtime are
measured directly —

* host->device / device->host transfer (PCIe or the tunnel; what the
  reference's kvstore pays per pull to CPU),
* on-mesh collective (jitted psum over the device mesh — the ICI path
  the compiled data-parallel step uses; needs >1 device: run with
  ``--platform cpu`` under XLA_FLAGS=--xla_force_host_platform_device_count=8
  for the virtual CPU mesh, or on a real multi-chip slice; the env var
  JAX_PLATFORMS alone is NOT enough — the axon sitecustomize overrides it
  programmatically, so the flag goes through jax.config),
* optional multi-process DCN allreduce (mxtpu.distributed host path) when
  a distributed runtime is initialized.

Timings sync by fetching result elements to host (NOT block_until_ready —
unreliable through the axon tunnel; PERF.md methodology).

Usage:
    python tools/bandwidth.py [--sizes-mb 1,4,16,64] [--model resnet50_v1]

With --model, the sweep uses that zoo model's actual parameter sizes
(the reference's default mode) aggregated into one blob per push.
Prints one line per (link, size): GB/s.
"""
import argparse
import time

import numpy as np


def _sync(x):
    np.asarray(__import__("jax").device_get(x.ravel()[:1]))


def _time(fn, reps=5):
    fn()  # warm/compile
    t0 = time.perf_counter()
    for _ in range(reps):
        fn()
    return (time.perf_counter() - t0) / reps


def measure_transfer(nbytes, reps=5):
    """host->device and device->host GB/s for one f32 blob."""
    import jax

    n = max(nbytes // 4, 1)
    host = np.empty(n, np.float32)
    dev = jax.device_put(host)
    _sync(dev)

    def h2d():
        _sync(jax.device_put(host))

    def d2h():
        np.asarray(jax.device_get(dev))

    return nbytes / _time(h2d, reps) / 1e9, nbytes / _time(d2h, reps) / 1e9


def measure_collective(nbytes, reps=5):
    """Allreduce (psum) GB/s over all local devices; None with 1 device.

    The reference's convention is model_size / allreduce_time with every
    worker contributing the FULL model, so each device holds its own
    nbytes blob (a (ndev, n) array sharded on axis 0) and the psum
    reduces nbytes across the mesh."""
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devs = jax.devices()
    if len(devs) < 2:
        return None
    mesh = Mesh(np.array(devs), ("data",))
    n = max(nbytes // 4, 1)
    x = jax.device_put(np.ones((len(devs), n), np.float32),
                       NamedSharding(mesh, P("data", None)))

    @jax.jit
    def allreduce(v):
        return jax.shard_map(lambda s: jax.lax.psum(s, "data"), mesh=mesh,
                             in_specs=P("data", None),
                             out_specs=P("data", None))(v)

    def run():
        _sync(allreduce(x))

    return nbytes / _time(run, reps) / 1e9


def measure_dcn(nbytes, reps=3):  # noqa: D401
    """Multi-process host allreduce GB/s (mxtpu.distributed); None unless
    a distributed runtime is up (tools/launch.py -n workers)."""
    try:
        from mxtpu import distributed
        if not distributed.is_initialized():
            return None
    except Exception:
        return None
    blob = np.ones(max(nbytes // 4, 1), np.float32)

    def run():
        distributed.allreduce_host(blob)

    return nbytes / _time(run, reps) / 1e9


def model_param_bytes(name):
    """Total parameter bytes of a zoo model (the reference measures its
    kvstore on real model shapes, not synthetic blobs)."""
    import jax

    jax.config.update("jax_platforms", jax.default_backend())
    import mxtpu as mx
    from mxtpu.gluon.model_zoo import vision

    net = vision.get_model(name)
    net.initialize()
    net(mx.nd.zeros((1, 3, 224, 224)))
    return sum(int(np.prod(p.data().shape)) * 4
               for p in net.collect_params().values())


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--sizes-mb", default="1,4,16,64",
                    help="comma-separated blob sizes in MiB")
    ap.add_argument("--model", default=None,
                    help="zoo model whose total parameter size to sweep "
                         "(e.g. resnet50_v1), like the reference's default")
    ap.add_argument("--reps", type=int, default=5)
    ap.add_argument("--platform", default=None,
                    help="jax platform override via jax.config (e.g. cpu "
                         "for the virtual mesh; the JAX_PLATFORMS env var "
                         "alone is overridden by the axon sitecustomize)")
    ns = ap.parse_args()

    if ns.platform:
        import jax
        jax.config.update("jax_platforms", ns.platform)

    if ns.model:
        sizes = [model_param_bytes(ns.model)]
        print("%s parameters: %.1f MiB" % (ns.model, sizes[0] / 2**20))
    else:
        sizes = [int(float(s) * 2**20) for s in ns.sizes_mb.split(",")]

    print("%-10s %12s %12s %12s %12s" % ("size", "h2d GB/s", "d2h GB/s",
                                         "psum GB/s", "dcn GB/s"))
    for nbytes in sizes:
        h2d, d2h = measure_transfer(nbytes, ns.reps)
        coll = measure_collective(nbytes, ns.reps)
        dcn = measure_dcn(nbytes, ns.reps)
        print("%-10s %12.2f %12.2f %12s %12s"
              % ("%.0fMiB" % (nbytes / 2**20), h2d, d2h,
                 "%.2f" % coll if coll else "n/a (1 dev)",
                 "%.2f" % dcn if dcn else "n/a"))


if __name__ == "__main__":
    main()
