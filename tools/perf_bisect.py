"""Perf bisection for the resnet50 bench config (PERF.md evidence).

Times, as separately jitted programs on the real chip:
  fwd            - inference forward only
  fwd_bwd        - value_and_grad of loss (no optimizer)
  full_step      - the exact ShardedTrainStep bench path
and reports XLA cost-analysis flops for each.
"""
import os
import time

import numpy as np
import jax
import jax.numpy as jnp


def _sync(out):
    """True sync: fetch a few elements to host. block_until_ready does not
    reliably wait through the axon tunnel (PERF.md timing methodology);
    device execution is queue-ordered, so fetching the LAST output waits
    for every step before it."""
    leaf = jax.tree_util.tree_leaves(out)[0]
    np.asarray(jax.device_get(leaf.ravel()[:2]))


def timeit(fn, *args, steps=20):
    out = fn(*args)
    _sync(out)
    t0 = time.perf_counter()
    for _ in range(steps):
        out = fn(*args)
    _sync(out)
    return (time.perf_counter() - t0) / steps


def flops_of(jfn, *args):
    """XLA cost-model FLOPs via the shared version-proof accessor
    (mxtpu/perf_model.py — list-of-dicts vs dict vs None handled there,
    not re-derived per tool)."""
    from mxtpu import perf_model
    c = jfn.lower(*args).compile()
    fl = perf_model.flops_of(c)
    return fl if fl is not None else 0.0


def main():
    from mxtpu import gluon
    from mxtpu.parallel import ShardedTrainStep, data_parallel_mesh, pure_forward
    from perf_common import build_resnet

    batch = int(os.environ.get("BENCH_BATCH", "128"))
    net, x, y = build_resnet(batch)

    # --- fwd only (train=False)
    fn, params = pure_forward(net)
    jfwd = jax.jit(fn)
    t_fwd = timeit(jfwd, params, x._data)
    f_fwd = flops_of(jfwd, params, x._data)
    print("fwd:       %7.2f ms  %6.1f GFLOP  (%5.1f TFLOP/s)"
          % (t_fwd * 1e3, f_fwd / 1e9, f_fwd / t_fwd / 1e12))

    # --- fwd+bwd (train=True), no optimizer
    fn_t, params_t = pure_forward(net, train=True)
    loss_blk = gluon.loss.SoftmaxCrossEntropyLoss()

    def loss_of(params_t, xd, yd):
        out = fn_t(params_t, xd)
        from mxtpu.ndarray import NDArray
        l = loss_blk(NDArray(out), NDArray(yd))
        return jnp.mean(l._data)

    jgrad = jax.jit(jax.value_and_grad(loss_of))
    t_bwd = timeit(jgrad, params_t, x._data, y._data)
    f_bwd = flops_of(jgrad, params_t, x._data, y._data)
    print("fwd+bwd:   %7.2f ms  %6.1f GFLOP  (%5.1f TFLOP/s)"
          % (t_bwd * 1e3, f_bwd / 1e9, f_bwd / t_bwd / 1e12))

    # --- full bench step
    step = ShardedTrainStep(net, loss_blk, data_parallel_mesh(),
                            optimizer="sgd",
                            optimizer_params={"learning_rate": 0.01,
                                              "momentum": 0.9})
    for _ in range(3):
        step(x, y).asnumpy()
    t0 = time.perf_counter()
    for _ in range(20):
        out = step(x, y)
    out.asnumpy()
    t_full = (time.perf_counter() - t0) / 20
    f_full = step.compiled_step_flops()
    print("full step: %7.2f ms  %6.1f GFLOP  (%5.1f TFLOP/s)  -> %.0f img/s"
          % (t_full * 1e3, f_full / 1e9, f_full / t_full / 1e12,
             batch / t_full))


if __name__ == "__main__":
    main()
