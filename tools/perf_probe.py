"""THE wedge-safe accelerator probe — one copy, two users: bench.py's
preflight and tools/diagnose.py's backend section.

The snippet runs in a SUBPROCESS under a caller-enforced timeout so a
hung PJRT client can never hang the caller (PERF.md round-3 wedge). It
prints staged lines so the caller can tell device discovery from
dispatch from steady-state RTT:

    PROBE jax_imported <s>
    PROBE devices <s> <platform> <count>
    PROBE first_dispatch <s>
    PROBE rtt_ms <ms>

``parse(stdout)`` returns the stages as a dict (missing keys = the probe
died before that stage).
"""

PROBE_SNIPPET = r"""
import os
import time
t0 = time.perf_counter()
import jax
# honor the caller's platform pin: the axon sitecustomize overrides the
# env var programmatically, which would probe the (possibly wedged) TPU
# tunnel even when the caller explicitly asked for cpu
if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
print("PROBE jax_imported %.2f" % (time.perf_counter() - t0), flush=True)
devs = jax.devices()
print("PROBE devices %.2f %s %s" % (time.perf_counter() - t0,
                                    devs[0].platform, len(devs)),
      flush=True)
import numpy as np
import jax.numpy as jnp
f = jax.jit(lambda v: v + 1)
v = jnp.ones((8, 8))
td = time.perf_counter()
np.asarray(jax.device_get(f(v).ravel()[:2]))
print("PROBE first_dispatch %.3f" % (time.perf_counter() - td), flush=True)
t1 = time.perf_counter()
for _ in range(5):
    np.asarray(jax.device_get(f(v).ravel()[:2]))
print("PROBE rtt_ms %.2f" % ((time.perf_counter() - t1) / 5 * 1e3),
      flush=True)
"""


def parse(stdout):
    """PROBE lines -> {stage: value}; 'platform'/'device_count' from the
    devices line."""
    out = {}
    for line in (stdout or "").splitlines():
        parts = line.split()
        if not line.startswith("PROBE ") or len(parts) < 3:
            continue
        stage = parts[1]
        out[stage] = float(parts[2])
        if stage == "devices" and len(parts) >= 5:
            out["platform"] = parts[3]
            out["device_count"] = int(parts[4])
    return out
