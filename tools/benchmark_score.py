"""Inference scoring benchmark across the model zoo.

Analog of the reference's ``example/image-classification/benchmark_score.py``
(the script behind BASELINE.md's inference tables, docs/faq/perf.md:35-49 in
the reference): forward-only throughput on synthetic data for each zoo
family at several batch sizes.

TPU-native differences: models run hybridized (one jit-compiled XLA program,
the CachedOp fast path), channels-last, bf16 by default (the MXU design
point — reference fp16 V100 numbers are the comparable column). Timing
pipelines STEPS dispatches and syncs once with a host fetch; compile time is
excluded (warmup), matching how the reference's scoring loop discards the
first batch.

Usage:
    python tools/benchmark_score.py                  # full sweep
    BENCH_MODELS=resnet50_v1,alexnet BENCH_BATCHES=1,32 python tools/...

Prints one JSON line per (model, batch): {"metric": "score_<model>_b<N>",
"value": img/s, ...} and a summary table at the end.
"""
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DEFAULT_MODELS = [
    "alexnet",
    "vgg16",
    "inception_v3",
    "resnet50_v1",
    "resnet152_v1",
    "mobilenet1.0",
    "mobilenet_v2_1.0",
    "squeezenet1.0",
    "densenet121",
]

# reference comparison points: V100 fp16 batch-128 scoring where published
# (docs/faq/perf.md:164-176), else V100 fp32 batch-128 (perf.md:121-162)
_REF_V100 = {
    "vgg16": 1169.81, "inception_v3": 1818.26, "resnet50_v1": 2355.04,
    "resnet152_v1": 1046.98, "alexnet": 10177.84,
}


def score_model(name, batch, steps=20, dtype="bfloat16", image_size=None):
    """Forward-only img/s for one zoo model at one batch size."""
    import mxtpu as mx
    from mxtpu.gluon.model_zoo import vision

    size = image_size or (299 if "inception" in name else 224)
    with mx.layout("NHWC"):
        net = vision.get_model(name, classes=1000)
    net.initialize()
    x = mx.nd.array(np.random.uniform(-1, 1, (batch, size, size, 3))
                    .astype(np.float32))
    net(x)  # settle deferred shapes
    if dtype != "float32":
        net.cast(dtype)
        x = x.astype(dtype)
    net.hybridize()
    out = net(x)
    out.asnumpy()  # compile + settle
    t0 = time.perf_counter()
    for _ in range(steps):
        out = net(x)
    out.asnumpy()  # queue-ordered: syncs every dispatched step
    dt = time.perf_counter() - t0
    return batch * steps / dt


def main():
    models = os.environ.get("BENCH_MODELS")
    models = models.split(",") if models else DEFAULT_MODELS
    batches = [int(b) for b in
               os.environ.get("BENCH_BATCHES", "1,32,128").split(",")]
    steps = int(os.environ.get("BENCH_STEPS", "20"))
    dtype = os.environ.get("BENCH_DTYPE", "bfloat16")
    rows = []
    for name in models:
        for batch in batches:
            try:
                rate = score_model(name, batch, steps=steps, dtype=dtype)
                err = None
            except Exception as e:  # noqa: BLE001 - score the rest
                rate, err = None, str(e)
            rec = {"metric": "score_%s_b%d" % (name, batch),
                   "value": round(rate, 2) if rate else None,
                   "unit": "images/sec"}
            if err:
                rec["error"] = err[:200]
            ref = _REF_V100.get(name)
            if rate and ref and batch == 128:
                rec["vs_baseline"] = round(rate / ref, 3)
            print(json.dumps(rec), flush=True)
            rows.append((name, batch, rate, err))
    print("\n%-18s %6s %12s" % ("model", "batch", "img/s"))
    for name, batch, rate, err in rows:
        print("%-18s %6d %12s" % (name, batch,
                                  "%.1f" % rate if rate else "FAIL"))


if __name__ == "__main__":
    main()
