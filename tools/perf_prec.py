"""Isolate the effect of jax_default_matmul_precision and dtype mixing on
conv fwd/bwd time (scan-fused to avoid tunnel RTT)."""
import time

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax


_RTT = None


def timed(name, jfn, *args, K=None):
    global _RTT
    if _RTT is None:
        from perf_common import measure_rtt
        _RTT = measure_rtt()
    out = jfn(*args)
    # true sync: host fetch — block_until_ready does not reliably wait
    # through the axon tunnel (PERF.md timing methodology)
    np.asarray(jax.device_get(jax.tree_util.tree_leaves(out)[0].ravel()[:2]))
    t0 = time.perf_counter()
    out = jfn(*args)
    v = np.asarray(jax.device_get(out))
    dt = time.perf_counter() - t0 - _RTT  # subtract measured tunnel RTT
    if K:
        dt /= K
    print("%-46s %8.2f ms" % (name, dt * 1e3))
    return v


def conv_stack(prec, dtype, bwd):
    # 8 chained 3x3 convs at 56x56x256 — MXU-heavy, resnet-like
    k = jax.random.PRNGKey(0)
    x = jax.random.normal(k, (128, 56, 56, 128), dtype)
    w = jax.random.normal(k, (3, 3, 128, 128), dtype)
    dn = ("NHWC", "HWIO", "NHWC")

    def f(x, w):
        for _ in range(8):
            x = lax.conv_general_dilated(x, w, (1, 1), "SAME",
                                         dimension_numbers=dn,
                                         precision=prec)
        return jnp.sum(x * 1e-30)

    if bwd:
        g = jax.grad(f, argnums=(0, 1))

        def body(c, _):
            gx, gw = g(c[0], c[1])
            return (c[0] + gx * 0, c[1] + gw * 0), None

        jfn = jax.jit(lambda x, w: lax.scan(body, (x, w), None, length=5)[0][1])
        timed("conv8 %s prec=%s grad" % (dtype, prec), jfn, x, w, K=5)
    else:
        def body(c, _):
            return (f(c[0], c[1]) * 0 + c[0], c[1]), None

        jfn = jax.jit(lambda x, w: lax.scan(body, (x, w), None, length=5)[0][1])
        timed("conv8 %s prec=%s fwd" % (dtype, prec), jfn, x, w, K=5)


def main():
    print("default_matmul_precision =",
          jax.config.jax_default_matmul_precision)
    for dtype in ("bfloat16", "float32"):
        for prec in (None, "default", "highest"):
            conv_stack(prec, dtype, bwd=False)
            conv_stack(prec, dtype, bwd=True)


if __name__ == "__main__":
    main()
