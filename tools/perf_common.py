"""Shared pieces for the perf diagnosis tools (perf_bisect/perf_rtt/
perf_prec/perf_trace): ONE copy of the bench-identical resnet50 setup and an
in-process tunnel-RTT measurement, so the tools can't drift from bench.py."""
import os
import time

import numpy as np


def build_resnet(batch=None, layout=None, dtype="bfloat16"):
    """Build the exact resnet50 bench model + batch (mirrors
    bench.bench_resnet50). Returns (net, x, y)."""
    import mxtpu as mx
    from mxtpu.gluon.model_zoo import vision

    batch = batch or int(os.environ.get("BENCH_BATCH", "128"))
    layout = layout or os.environ.get("BENCH_LAYOUT", "NHWC")
    with mx.layout(layout):
        net = vision.resnet50_v1()
    net.initialize()
    shape = (batch, 224, 224, 3) if layout == "NHWC" else (batch, 3, 224, 224)
    x = mx.nd.array(np.random.uniform(-1, 1, size=shape), dtype="float32")
    net(x)  # settle deferred shapes
    if dtype != "float32":
        net.cast(dtype)
        x = x.astype(dtype)
    y = mx.nd.array(np.random.randint(0, 1000, size=(batch,)),
                    dtype="float32")
    return net, x, y


def measure_rtt(n=10):
    """Dispatch+sync latency of a trivial jitted op — the tunnel RTT floor
    to subtract from single-shot timings. Measured, never hardcoded."""
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda v: v + 1)
    v = jnp.ones((8, 8))
    jax.device_get(f(v))
    t0 = time.perf_counter()
    for _ in range(n):
        jax.device_get(f(v))
    return (time.perf_counter() - t0) / n
