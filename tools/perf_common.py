"""Shared pieces for the perf diagnosis tools (perf_bisect/perf_rtt/
perf_prec/perf_trace): ONE copy of the bench-identical resnet50 setup and an
in-process tunnel-RTT measurement, so the tools can't drift from bench.py."""
import os
import time

import numpy as np


def build_resnet(batch=None, layout=None, dtype="bfloat16"):
    """Build the exact resnet50 bench model + batch (mirrors
    bench.bench_resnet50). Returns (net, x, y)."""
    import mxtpu as mx
    from mxtpu.gluon.model_zoo import vision

    batch = batch or int(os.environ.get("BENCH_BATCH", "128"))
    layout = layout or os.environ.get("BENCH_LAYOUT", "NHWC")
    with mx.layout(layout):
        net = vision.resnet50_v1()
    net.initialize()
    shape = (batch, 224, 224, 3) if layout == "NHWC" else (batch, 3, 224, 224)
    x = mx.nd.array(np.random.uniform(-1, 1, size=shape), dtype="float32")
    net(x)  # settle deferred shapes
    if dtype != "float32":
        net.cast(dtype)
        x = x.astype(dtype)
    y = mx.nd.array(np.random.randint(0, 1000, size=(batch,)),
                    dtype="float32")
    return net, x, y


def timed_scan(step_fn, x0, K=8):
    """THE scan-fused timing harness (PERF.md methodology): K steps fused
    into ONE dispatch via lax.scan (one compile, one RTT), synced by
    fetching result elements to host — ``jax.block_until_ready`` does not
    reliably wait through the tunnel. ``step_fn: carry -> carry``; returns
    seconds per step. The single copy behind tools/perf_session.py and
    bench.py's conv_class config — a sync-idiom fix lands everywhere."""
    import jax

    @jax.jit
    def run(xd):
        c, _ = jax.lax.scan(lambda c, _: (step_fn(c), None), xd, None,
                            length=K)
        return c

    y = run(x0)
    np.asarray(jax.device_get(y.ravel()[:2]))  # warmup + compile
    t0 = time.perf_counter()
    y = run(x0)
    np.asarray(jax.device_get(y.ravel()[:2]))
    return (time.perf_counter() - t0) / K


def reinject(fn):
    """Wrap a ``carry -> output`` fn as ``carry -> carry`` for timed_scan
    by folding a cheap summary of the output back into the carry (keeps
    every scan step live without changing shapes)."""
    import jax.numpy as jnp

    def step(c):
        o = fn(c)
        return c + 0 * jnp.mean(o.astype(jnp.float32)).astype(c.dtype)
    return step


def measure_rtt(n=10):
    """Dispatch+sync latency of a trivial jitted op — the tunnel RTT floor
    to subtract from single-shot timings. Measured, never hardcoded."""
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda v: v + 1)
    v = jnp.ones((8, 8))
    jax.device_get(f(v))
    t0 = time.perf_counter()
    for _ in range(n):
        jax.device_get(f(v))
    return (time.perf_counter() - t0) / n
