"""Fleet training child: one host of the elastic multi-host matrix.

The subprocess entrypoint ``FleetSupervisor`` / ``bench.py fleet_resume``
/ ``tests/test_fleet.py`` launch per host: joins the fleet
(``mxtpu.fleet.init`` — deadline bring-up off the env bootstrap the
supervisor exports), trains a small deterministic MLP with
``gluon.Trainer(mesh=..., zero1=True)`` so optimizer state is ZeRO-1
sharded over the mesh, checkpoints every step through ``ResilientLoop``
(rank 0 is the single writer), and reports a ``RESULT`` JSON line
(per-step losses, resume step, compile/disk-cache counters, divergence
checks).

Everything is a pure function of ``--seed`` — dataset, init, batch
order — and on this forced-CPU tier every host trains the FULL global
batch on its own local mesh (``--devices`` fake devices), so a run
killed at step K and restored onto a RESHAPED mesh (different
``--devices``) must reproduce the uninterrupted run's losses within
reduce-order tolerance. Cross-host coupling that a TPU fleet gets from
device collectives rides ``Fleet.step_barrier`` instead: a dead peer
fails the survivors LOUD (exit 42 with the membership diagnosis), and
the divergence fingerprints riding the barrier payloads are the
cross-host consistency gate. The ``shard_keys`` disjoint-union
invariant is asserted every step — the slice each host WOULD take on a
global-compute backend reassembles the exact global batch at any world
size.

Faults arrive via ``MXTPU_FAULT_INJECT`` in the child env
(``host_loss@K`` → ``os._exit(41)`` at step K; ``rejoin_stall@rank``
stalls the bring-up). The fleet collective watchdog
(``MXTPU_FLEET_COLLECTIVE_TIMEOUT_S``) is the backstop that turns a
wedge the barrier cannot see into a loud exit 42; the launcher's hard
child timeout is the outer backstop.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _snapshot_counts():
    # the startup_bench recipe: compiles = every retrace counter except
    # the watchdog's own trip count; disk_hits proves the cache served
    from mxtpu import telemetry
    snap = telemetry.snapshot()["counters"]
    compiles = sum(v for k, v in snap.items()
                   if isinstance(v, (int, float)) and k.startswith("retrace.")
                   and k != "retrace.watchdog_trips")

    def total(name):
        v = snap.get(name, 0)
        return sum(v.values()) if isinstance(v, dict) else v
    return {"compiles": int(compiles),
            "disk_hits": int(total("compile.disk.hits")),
            # a found-but-refused blob (key_mismatch, cpu_multidevice,
            # corrupt...) is the difference between "cache cold" and
            # "cache rejected us" when a zero-compile gate fails
            "disk_drops": int(total("compile.disk.drops"))}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ckpt-dir", required=True)
    ap.add_argument("--steps", type=int, default=6)
    ap.add_argument("--devices", type=int, default=1,
                    help="fake local devices (the mesh-reshape lever: "
                    "save on N, restore on M)")
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--hidden", type=int, default=8)
    ap.add_argument("--features", type=int, default=4)
    args = ap.parse_args()
    t0 = time.time()

    # forced CPU host tier: the fleet matrix is a control-plane /
    # correctness test, never a chip benchmark. The device count must be
    # pinned BEFORE jax imports.
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=%d" % args.devices)
    # the divergence sentinel is part of the acceptance matrix: the
    # fused update emits its fingerprint every step
    os.environ.setdefault("MXTPU_DIVERGENCE_EVERY", "1")

    import numpy as np

    import mxtpu as mx
    from mxtpu import autograd, fleet, fleet_obs, gluon, resilience
    from mxtpu import telemetry
    from mxtpu.gluon import nn
    from mxtpu.io.stream import shard_keys
    from mxtpu.parallel import host_value

    f = fleet.init()
    rank, world = f.rank, f.num_hosts
    mesh = f.mesh()

    # fleet observability plane (ISSUE 19, mxtpu/fleet_obs.py): cadenced
    # obs_<rank>.json publication riding the telemetry flush hook, plus
    # the straggler/regression sentinels off the step-barrier payloads.
    # All opt-in: MXTPU_FLEET_OBS_S / MXTPU_STRAGGLER_X default off.
    pub = None
    if f.fleet_dir and fleet_obs.obs_interval_s() > 0:
        pub = fleet_obs.HostObsPublisher(f.fleet_dir, rank).install()
    straggler = fleet_obs.StragglerSentinel() if rank == 0 else None
    regression = fleet_obs.RegressionSentinel()

    # dataset: pure function of the seed (identical on every host and
    # across restarts/reshapes)
    n_rows = 64
    rs = np.random.RandomState(args.seed)
    x_all = rs.randn(n_rows, args.features).astype("float32")
    w_true = rs.randn(args.features, 1).astype("float32")
    y_all = (x_all @ w_true + 0.1 * rs.randn(n_rows, 1)).astype("float32")

    mx.random.seed(args.seed)
    net = nn.HybridSequential()
    net.add(nn.Dense(args.hidden, activation="relu",
                     in_units=args.features))
    net.add(nn.Dense(1, in_units=args.hidden))
    net.initialize()
    loss_fn = gluon.loss.L2Loss()
    # momentum so there IS per-param optimizer state for ZeRO-1 to shard
    # (and re-shard onto the reshaped mesh after a loss)
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05, "momentum": 0.9},
                            mesh=mesh, zero1=True)
    loop = resilience.ResilientLoop(trainer, resilience.CheckpointPolicy(
        args.ckpt_dir, every_steps=1, async_save=False))
    start = loop.resume()
    f.barrier("fleet_worker_resumed")

    wd = f.watchdog(exit_on_trip=True).start_monitor()
    sentinel = resilience.DivergenceSentinel()

    losses = []
    try:
        for step in range(start, args.steps):
            fleet.maybe_host_loss(step)
            f.check(step)
            # fixed global batch for this step. Every host trains the
            # WHOLE batch (replicated trajectories — the CPU tier's
            # stand-in for device collectives), but the per-host
            # shard_keys slices must still reassemble it exactly: the
            # invariant the global-compute sharding path rides.
            idx = [(step * args.batch + i) % n_rows
                   for i in range(args.batch)]
            parts = [shard_keys(idx, num_shards=world, shard_index=r,
                                shuffle=False) for r in range(world)]
            assert [k for p in parts for k in p] == idx, \
                "shard_keys shards no longer reassemble the global batch"
            xb, yb = trainer.shard_batch(x_all[idx], y_all[idx])
            # straggler_slow fault: a fixed host-side stall before this
            # step, billed to data.wait — the deterministic slow host
            # the straggler sentinel must name
            slow_s = 0.0
            if resilience.inject("straggler_slow", step):
                slow_s = 0.35
                time.sleep(slow_s)
            entry = wd.arm(step, what="train step")
            try:
                with autograd.record():
                    loss = loss_fn(net(xb), yb)
                loss.backward()
                trainer.step(args.batch)
                fp = getattr(trainer._updaters[0], "last_fingerprint", None)
                sentinel.check(fp, step=step)
                lval = float(np.mean(host_value(loss._data)))
                # cross-host consistency gate: the step barrier carries
                # each host's fingerprint; a dead peer or a divergent
                # one fails this loud. The obs payload stitches this
                # host's trace id + stage breakdown + arrival timestamp
                # into the board for the fleet critical-path view.
                stages = dict(getattr(trainer, "last_step_stages", {}) or {})
                if slow_s:
                    stages["data.wait"] = stages.get("data.wait", 0.0) + slow_s
                obs = {"trace": getattr(trainer, "last_step_trace", None),
                       "stages": stages}
                fps = f.step_barrier(step, fingerprint=None if fp is None
                                     else [float(x) for x in fp], obs=obs)
                if straggler is not None and fps:
                    straggler.observe(step, fps)
                regression.observe(step, sum(stages.values()) or None)
            finally:
                wd.disarm(entry)
            losses.append(lval)
            if pub is not None:
                pub.maybe_publish(step)
            if rank == 0:
                # single checkpoint writer: replicated state is
                # identical on every host, and two processes writing
                # one step dir would race
                loop.after_step(step)
    except fleet.FleetWedgeError as e:
        print("FLEET WEDGE rank %d: %s" % (rank, e), flush=True)
        os._exit(fleet.EXIT_FLEET_WEDGE)

    loop.wait_for_pending()
    if pub is not None:
        pub.publish()  # final blob: the completed run's full registry
    rec = {"rank": rank, "world": world, "start": start,
           "steps": args.steps, "devices": args.devices, "losses": losses,
           "divergence_checks": sentinel.checks,
           "wall_s": time.time() - t0}
    rec.update(_snapshot_counts())
    rec["obs_publishes"] = int(telemetry.value("fleet.obs.publishes"))
    rec["straggler_trips"] = sum(
        (telemetry.tagged("fleet.straggler_trips") or {}).values())
    if straggler is not None and straggler.trips:
        rec["straggler"] = straggler.trips[-1]["rank"]
    print("RESULT " + json.dumps(rec), flush=True)
    wd.stop_monitor()
    f.leave()


if __name__ == "__main__":
    main()
