"""Variable-length LSTM language model with bucketing — the classic
mx.rnn + BucketingModule workflow (ref: example/rnn/bucketing/
lstm_bucketing.py), on synthetic token data so it runs offline.

The legacy symbolic cells compose one unrolled Symbol per bucket length
(sym_gen); BucketingModule compiles one executor per bucket and shares
parameters across them. On this engine each bucket's graph jits once —
XLA sees the fully unrolled program per length, the TPU-native stand-in
for the reference's fused cudnn path.

Run: python examples/rnn/lstm_bucketing.py [--epochs 3]
"""
import argparse

import numpy as np

import mxtpu as mx
from mxtpu import rnn
from mxtpu.module import BucketingModule


def synthetic_sentences(vocab, n=200, seed=0):
    """Token sequences with a DETERMINISTIC learnable pattern (next
    token = prev+1 mod vocab) in three length buckets — perplexity can
    approach 1 once learned."""
    rng = np.random.RandomState(seed)
    sentences = []
    for _ in range(n):
        length = int(rng.choice([6, 10, 14]))
        start = int(rng.randint(1, vocab))
        s = [(start + i) % (vocab - 1) + 1 for i in range(length)]
        sentences.append(s)
    return sentences


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=6)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--num-hidden", type=int, default=32)
    ap.add_argument("--num-embed", type=int, default=16)
    ap.add_argument("--num-layers", type=int, default=1)
    ap.add_argument("--vocab", type=int, default=32)
    ns = ap.parse_args()

    buckets = [6, 10, 14]
    sents = synthetic_sentences(ns.vocab)
    # BucketSentenceIter derives labels itself (data shifted left by one)
    data_train = rnn.BucketSentenceIter(
        sents, ns.batch_size, buckets=buckets, invalid_label=0)

    stack = rnn.SequentialRNNCell()
    for i in range(ns.num_layers):
        stack.add(rnn.LSTMCell(num_hidden=ns.num_hidden,
                               prefix="lstm_l%d_" % i))

    def sym_gen(seq_len):
        data = mx.sym.var("data")
        label = mx.sym.var("softmax_label")
        embed = mx.sym.Embedding(data, input_dim=ns.vocab,
                                 output_dim=ns.num_embed, name="embed")
        stack.reset()
        outputs, _ = stack.unroll(
            seq_len, inputs=embed,
            begin_state=stack.begin_state(batch_size=ns.batch_size),
            merge_outputs=True)
        pred = mx.sym.Reshape(outputs, shape=(-1, ns.num_hidden))
        pred = mx.sym.FullyConnected(pred, num_hidden=ns.vocab,
                                     name="pred")
        label = mx.sym.Reshape(label, shape=(-1,))
        pred = mx.sym.SoftmaxOutput(pred, label, name="softmax")
        return pred, ("data",), ("softmax_label",)

    model = BucketingModule(sym_gen,
                            default_bucket_key=data_train.default_bucket_key)
    metric = mx.metric.Perplexity(ignore_label=0)
    model.fit(train_data=data_train, eval_metric=metric,
              optimizer="sgd",
              # SoftmaxOutput grads are summed over batch*seq rows, so
              # the lr is small (the reference example trains at 0.01)
              optimizer_params={"learning_rate": 0.02, "momentum": 0.9},
              initializer=mx.init.Xavier(factor_type="in", magnitude=2.34),
              num_epoch=ns.epochs)
    metric.reset()
    model.score(data_train, metric)
    name, ppl = metric.get()
    print("final %s: %.2f" % (name, ppl))
    return ppl


if __name__ == "__main__":
    main()
