"""Image-classification training example (ref: example/image-classification/
train_cifar10.py + train_mnist.py — the reference's most-used entry point).

Demonstrates the canonical training loop on a zoo model: Gluon Trainer +
autograd (the modern path) or Module.fit (the classic path), checkpoints,
Speedometer logging, and bf16/NHWC TPU defaults. Runs on synthetic CIFAR-10
shaped data by default (this environment has no dataset egress); pass
--data-dir with real CIFAR-10 RecordIO packs (made by tools/im2rec.py) to
train for real.

Usage:
    python examples/image_classification/train_cifar10.py \
        --model resnet18_v1 --epochs 2 --batch-size 128 [--module]
"""
import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))


def synthetic_iter(batch_size, num_batches, image_size=32, classes=10,
                   layout="NHWC", seed=0):
    import mxtpu as mx

    rng = np.random.RandomState(seed)
    shape = ((batch_size, image_size, image_size, 3) if layout == "NHWC"
             else (batch_size, 3, image_size, image_size))
    data = rng.uniform(-1, 1, (num_batches,) + shape).astype(np.float32)
    label = rng.randint(0, classes, (num_batches, batch_size)) \
        .astype(np.float32)
    return mx.io.NDArrayIter(
        data={"data": data.reshape((-1,) + shape[1:])},
        label={"softmax_label": label.reshape(-1)},
        batch_size=batch_size)


def train_gluon(args):
    import mxtpu as mx
    from mxtpu import autograd, gluon
    from mxtpu.gluon.model_zoo import vision

    with mx.layout(args.layout):
        net = vision.get_model(args.model, classes=args.classes,
                               thumbnail=True)
    net.initialize(mx.init.Xavier())
    net.hybridize()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": args.lr, "momentum": 0.9})
    metric = mx.metric.Accuracy()

    it = synthetic_iter(args.batch_size, args.num_batches,
                        layout=args.layout, classes=args.classes)
    for epoch in range(args.epochs):
        it.reset()
        metric.reset()
        tic = time.time()
        n = 0
        for batch in it:
            x, y = batch.data[0], batch.label[0]
            if args.dtype != "float32":
                x = x.astype(args.dtype)
            with autograd.record():
                out = net(x)
                loss = loss_fn(out, y)
            loss.backward()
            trainer.step(args.batch_size)
            metric.update([y], [out])
            n += args.batch_size
        name, acc = metric.get()
        print("epoch %d: %s=%.4f  %.1f samples/s"
              % (epoch, name, acc, n / (time.time() - tic)), flush=True)
    if args.save_prefix:
        net.export(args.save_prefix, epoch=args.epochs)
        print("exported to %s-symbol.json / -%04d.params"
              % (args.save_prefix, args.epochs))
    return net


def train_module(args):
    """The classic symbolic path (ref: train loop in
    example/image-classification/common/fit.py)."""
    import mxtpu as mx

    data = mx.sym.Variable("data")
    net = mx.sym.Convolution(data, num_filter=32, kernel=(3, 3), pad=(1, 1),
                             name="conv1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Pooling(net, pool_type="max", kernel=(2, 2), stride=(2, 2))
    net = mx.sym.Flatten(net)
    net = mx.sym.FullyConnected(net, num_hidden=args.classes, name="fc")
    net = mx.sym.SoftmaxOutput(net, name="softmax")

    it = synthetic_iter(args.batch_size, args.num_batches, layout="NCHW",
                        classes=args.classes)
    mod = mx.mod.Module(net, data_names=("data",),
                        label_names=("softmax_label",))
    mod.fit(it, num_epoch=args.epochs,
            optimizer="sgd",
            optimizer_params={"learning_rate": args.lr},
            batch_end_callback=mx.callback.Speedometer(args.batch_size, 10),
            epoch_end_callback=(mx.callback.do_checkpoint(args.save_prefix)
                                if args.save_prefix else None))
    return mod


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="resnet18_v1")
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--batch-size", type=int, default=128)
    p.add_argument("--num-batches", type=int, default=20,
                   help="synthetic batches per epoch")
    p.add_argument("--classes", type=int, default=10)
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--dtype", default="bfloat16")
    p.add_argument("--layout", default="NHWC")
    p.add_argument("--save-prefix", default="")
    p.add_argument("--module", action="store_true",
                   help="use the classic Module/Symbol path")
    args = p.parse_args(argv)
    if args.module:
        train_module(args)
    else:
        train_gluon(args)


if __name__ == "__main__":
    main()
