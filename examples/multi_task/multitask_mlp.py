"""Multi-task learning: one trunk, two heads, two losses
(ref: example/multi-task/example_multi_task.py — a shared body with a
classification head per task, losses summed before backward).

The synthetic task pair shares structure (both depend on the same latent
projection), so the shared trunk genuinely helps — the example asserts
both heads learn.

    python examples/multi_task/multitask_mlp.py --epochs 5
"""
import argparse

import numpy as np

import mxtpu as mx
from mxtpu import autograd, gluon
from mxtpu.gluon import nn
from mxtpu.gluon.block import HybridBlock


class MultiTaskNet(HybridBlock):
    def __init__(self, hidden, c1, c2, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.trunk = nn.HybridSequential()
            self.trunk.add(nn.Dense(hidden, activation="relu"))
            self.trunk.add(nn.Dense(hidden // 2, activation="relu"))
            self.head1 = nn.Dense(c1)
            self.head2 = nn.Dense(c2)

    def hybrid_forward(self, F, x):
        z = self.trunk(x)
        return self.head1(z), self.head2(z)


def make_data(rng, n, nin, c1, w):
    x = rng.normal(0, 1, (n, nin)).astype(np.float32)
    z = x @ w
    y1 = z[:, :c1].argmax(1).astype(np.float32)       # task 1: argmax class
    y2 = (z.sum(1) > 0).astype(np.float32)            # task 2: sign, binary
    return x, y1, y2


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=8)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--hidden", type=int, default=64)
    p.add_argument("--classes", type=int, default=5)
    p.add_argument("--train-size", type=int, default=2048)
    p.add_argument("--lr", type=float, default=0.01)
    p.add_argument("--task2-weight", type=float, default=0.5)
    args = p.parse_args()

    rng = np.random.RandomState(0)
    nin, c1 = 32, args.classes
    w = rng.normal(0, 1, (nin, max(c1, 8))).astype(np.float32)
    tx, t1, t2 = make_data(rng, args.train_size, nin, c1, w)
    vx, v1, v2 = make_data(rng, max(512, args.batch_size), nin, c1, w)

    net = MultiTaskNet(args.hidden, c1, 2)
    net.initialize(mx.init.Xavier())
    net.hybridize()
    ce = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})

    b = args.batch_size
    acc1 = acc2 = 0.0
    for epoch in range(args.epochs):
        cum, nb = 0.0, 0
        for i in range(0, len(tx) - b + 1, b):
            data = mx.nd.array(tx[i:i + b])
            l1 = mx.nd.array(t1[i:i + b])
            l2 = mx.nd.array(t2[i:i + b])
            with autograd.record():
                o1, o2 = net(data)
                loss = ce(o1, l1) + args.task2_weight * ce(o2, l2)
            loss.backward()
            trainer.step(b)
            cum += float(loss.mean().asnumpy())
            nb += 1
        m1, m2 = mx.metric.Accuracy(), mx.metric.Accuracy()
        for i in range(0, len(vx) - b + 1, b):
            o1, o2 = net(mx.nd.array(vx[i:i + b]))
            m1.update([mx.nd.array(v1[i:i + b])], [o1])
            m2.update([mx.nd.array(v2[i:i + b])], [o2])
        acc1, acc2 = m1.get()[1], m2.get()[1]
        print("epoch %d loss %.4f task1-acc %.4f task2-acc %.4f"
              % (epoch, cum / max(nb, 1), acc1, acc2))
    return acc1, acc2


if __name__ == "__main__":
    main()
