"""Sparse linear classification (BASELINE.json config 5).

Reference: example/sparse/linear_classification/ — LibSVM data, a
csr x row_sparse linear model, sparse gradients, optionally a distributed
kvstore with row_sparse_pull.

TPU-native design: the forward is ``mx.nd.sparse.dot(csr_batch, weight)``
which lowers to gather + segment-sum (O(nnz) — the dense fallback would
materialize a (batch, num_features) matrix: at the reference's AVAZU scale,
8192 x 1M x 4B = 32 GB, the documented cliff). Gradients are produced
row-sparse (only touched rows), updated with the lazy sparse optimizer
path (mxtpu/optimizer.py lazy_update), and pulled back through
``kv.row_sparse_pull`` keyed by the batch's feature ids — the same
update-only-what-you-touched flow the reference runs over ps-lite.

Run: python examples/sparse/linear_classification.py [--synthetic]
"""
import argparse
import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import mxtpu as mx  # noqa: E402

from mxtpu.io import LibSVMIter  # noqa: E402
from mxtpu.ndarray.sparse import RowSparseNDArray  # noqa: E402


def make_synthetic_libsvm(path, num_rows=2000, num_features=10000,
                          nnz_per_row=30, seed=0):
    """Synthetic separable-ish binary problem in LibSVM text format."""
    r = np.random.RandomState(seed)
    true_w = r.normal(0, 1, num_features)
    with open(path, "w") as f:
        for _ in range(num_rows):
            idx = np.sort(r.choice(num_features, nnz_per_row, replace=False))
            val = r.normal(0, 1, nnz_per_row)
            label = 1 if val @ true_w[idx] > 0 else 0
            toks = " ".join("%d:%.4f" % (i, v) for i, v in zip(idx, val))
            f.write("%d %s\n" % (label, toks))


def _sparse_linear_grads(x, dlogits):
    """Row-sparse weight gradient of logits = csr_x @ W: only the feature
    rows this batch touched get a gradient row (the reference's row_sparse
    grad of sparse.dot, dot-inl.h DotCsrDnsRspImpl) — gather/segment-sum,
    never a dense (num_features, C) array."""
    import jax.numpy as jnp
    import jax

    from mxtpu.ndarray.sparse import _csr_row_ids

    data = x._data
    indices = x._aux["indices"]
    nnz = data.shape[0]
    rows = np.asarray(_csr_row_ids(x._aux["indptr"], nnz))
    uniq, inv = np.unique(np.asarray(indices), return_inverse=True)
    contrib = np.asarray(data)[:, None] * dlogits[rows]  # (nnz, C)
    vals = jax.ops.segment_sum(jnp.asarray(contrib), jnp.asarray(inv),
                               num_segments=len(uniq))
    return RowSparseNDArray(vals, uniq.astype(np.int32),
                            (x.shape[1], dlogits.shape[1]))


def train(data_path, num_features, batch_size=256, epochs=3, lr=0.05,
          kv=None, measure=False):
    """Train; with measure=True also returns steady-state samples/sec
    (excludes LibSVM parsing and the first, compile-heavy epoch)."""
    import time

    it = LibSVMIter(data_libsvm=data_path, data_shape=(num_features,),
                    batch_size=batch_size)
    t_start = None
    weight = mx.nd.array(np.random.RandomState(1)
                         .normal(0, 0.01, (num_features, 2))
                         .astype(np.float32))
    bias = mx.nd.zeros((2,))
    if kv is not None:
        kv.init("weight", weight)
    # lazy_update: only rows present in the row-sparse grad advance their
    # optimizer state (mxtpu/optimizer.py ~ optimizer_op.cc sparse Adam)
    updater = mx.optimizer.get_updater(
        mx.optimizer.create("adam", learning_rate=lr, lazy_update=True))
    bias_updater = mx.optimizer.get_updater(
        mx.optimizer.create("adam", learning_rate=lr))

    loss_hist = []
    measured = 0
    for ep in range(epochs):
        if measure and ep == 1:  # epoch 0 = warmup/compile
            t_start = time.perf_counter()
        if ep >= 1:
            measured += 1
        it.reset()
        total, correct, lsum, nb = 0, 0, 0.0, 0
        for batch in it:
            x = batch.data[0]          # CSRNDArray
            y = batch.label[0]
            logits = mx.nd.sparse.dot(x, weight) + bias
            lg = logits.asnumpy()
            yv = y.asnumpy().astype(int)
            p = np.exp(lg - lg.max(1, keepdims=True))
            p /= p.sum(1, keepdims=True)
            loss = float(-np.log(np.maximum(
                p[np.arange(len(yv)), yv], 1e-12)).mean())
            dlogits = p.copy()
            dlogits[np.arange(len(yv)), yv] -= 1.0
            dlogits /= batch_size

            wgrad = _sparse_linear_grads(x, dlogits)
            updater(0, wgrad, weight)
            bias_updater(1, mx.nd.array(dlogits.sum(0)), bias)
            if kv is not None:
                kv.push("weight", weight)
                kv.row_sparse_pull("weight", out=weight,
                                   row_ids=x.indices)
            correct += int((lg.argmax(1) == yv).sum())
            total += batch_size
            lsum += loss
            nb += 1
        loss_hist.append(lsum / nb)
    if measure:
        dt = time.perf_counter() - (t_start or time.perf_counter())
        rate = measured * it.num_data / dt if dt > 0 and measured else 0.0
        return correct / total, loss_hist, rate
    return correct / total, loss_hist


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--data", default=None, help="LibSVM file (default: "
                   "generate synthetic)")
    p.add_argument("--num-features", type=int, default=10000)
    p.add_argument("--batch-size", type=int, default=256)
    p.add_argument("--epochs", type=int, default=3)
    p.add_argument("--kvstore", default=None, choices=[None, "local"])
    args = p.parse_args()

    path = args.data
    if path is None:
        path = os.path.join(tempfile.gettempdir(), "synthetic.libsvm")
        make_synthetic_libsvm(path, num_features=args.num_features)
    kv = mx.kv.create(args.kvstore) if args.kvstore else None
    acc, losses = train(path, args.num_features, args.batch_size,
                        args.epochs, kv=kv)
    print("final accuracy %.4f; loss %s" % (acc,
                                            ["%.4f" % v for v in losses]))


if __name__ == "__main__":
    main()
