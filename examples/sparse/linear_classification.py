"""Sparse linear classification (BASELINE.json config 5).

Reference: example/sparse/linear_classification/ — LibSVM data, a
csr x row_sparse linear model, sparse gradients, optionally a distributed
kvstore with row_sparse_pull.

TPU-native design: the forward is ``mx.nd.sparse.dot(csr_batch, weight)``
which lowers to gather + segment-sum (O(nnz) — the dense fallback would
materialize a (batch, num_features) matrix: at the reference's AVAZU scale,
8192 x 1M x 4B = 32 GB, the documented cliff). Gradients are produced
row-sparse (only touched rows), updated with the lazy sparse optimizer
path (mxtpu/optimizer.py lazy_update), and pulled back through
``kv.row_sparse_pull`` keyed by the batch's feature ids — the same
update-only-what-you-touched flow the reference runs over ps-lite.

Run: python examples/sparse/linear_classification.py [--synthetic]
"""
import argparse
import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import mxtpu as mx  # noqa: E402

from mxtpu.io import LibSVMIter  # noqa: E402
from mxtpu.ndarray.sparse import RowSparseNDArray  # noqa: E402


def make_synthetic_libsvm(path, num_rows=2000, num_features=10000,
                          nnz_per_row=30, seed=0):
    """Synthetic separable-ish binary problem in LibSVM text format."""
    r = np.random.RandomState(seed)
    true_w = r.normal(0, 1, num_features)
    with open(path, "w") as f:
        for _ in range(num_rows):
            idx = np.sort(r.choice(num_features, nnz_per_row, replace=False))
            val = r.normal(0, 1, nnz_per_row)
            label = 1 if val @ true_w[idx] > 0 else 0
            toks = " ".join("%d:%.4f" % (i, v) for i, v in zip(idx, val))
            f.write("%d %s\n" % (label, toks))


def _fused_step():
    """One jitted forward+loss+grad program: logits via gather/segment-sum
    (= sparse.dot), softmax CE, per-nnz weight-grad contributions — so the
    training loop performs a SINGLE device fetch per batch. On a tunneled
    chip each host<->device sync is a full RTT (~66 ms, PERF.md timing
    methodology); the original loop's ~5 syncs/batch were the entire cost
    of this workload (its math is ~0.2 MFLOP/batch)."""
    import jax
    import jax.numpy as jnp

    from mxtpu.ndarray.sparse import _csr_row_ids

    @jax.jit
    def step(weight, bias, data, indices, indptr, y):
        nnz = data.shape[0]
        batch = y.shape[0]
        # padded nnz tail: row ids land past the last row; clip and rely
        # on data==0 there to contribute nothing (row derivation shared
        # with todense/csr-dot: sparse.py:_csr_row_ids)
        rows = jnp.clip(_csr_row_ids(indptr, nnz), 0, batch - 1)
        wrows = jnp.take(weight, indices, axis=0)            # (nnz, C)
        logits = jax.ops.segment_sum(data[:, None] * wrows, rows,
                                     num_segments=batch) + bias
        zmax = jnp.max(logits, axis=1, keepdims=True)
        ez = jnp.exp(logits - zmax)
        p = ez / jnp.sum(ez, axis=1, keepdims=True)
        yi = y.astype(jnp.int32)
        picked = jnp.clip(p[jnp.arange(batch), yi], 1e-12, None)
        loss = -jnp.mean(jnp.log(picked))
        correct = jnp.sum(jnp.argmax(logits, axis=1) == yi)
        d = (p - jax.nn.one_hot(yi, logits.shape[1],
                                dtype=p.dtype)) / batch
        contrib = data[:, None] * jnp.take(d, rows, axis=0)  # (nnz, C)
        return loss, correct, jnp.sum(d, axis=0), contrib

    return step


def train(data_path, num_features, batch_size=256, epochs=3, lr=0.05,
          kv=None, measure=False):
    """Train; with measure=True also returns steady-state samples/sec
    (excludes LibSVM parsing and the first, compile-heavy epoch)."""
    import time

    it = LibSVMIter(data_libsvm=data_path, data_shape=(num_features,),
                    batch_size=batch_size)
    t_start = None
    weight = mx.nd.array(np.random.RandomState(1)
                         .normal(0, 0.01, (num_features, 2))
                         .astype(np.float32))
    bias = mx.nd.zeros((2,))
    if kv is not None:
        kv.init("weight", weight)
    # lazy_update: only rows present in the row-sparse grad advance their
    # optimizer state (mxtpu/optimizer.py ~ optimizer_op.cc sparse Adam)
    updater = mx.optimizer.get_updater(
        mx.optimizer.create("adam", learning_rate=lr, lazy_update=True))
    bias_updater = mx.optimizer.get_updater(
        mx.optimizer.create("adam", learning_rate=lr))

    import jax
    import jax.numpy as jnp
    step = _fused_step()

    loss_hist = []
    measured = 0
    for ep in range(epochs):
        if measure and ep == 1:  # epoch 0 = warmup/compile
            t_start = time.perf_counter()
        if ep >= 1:
            measured += 1
        it.reset()
        total, correct, lsum, nb = 0, 0, 0.0, 0
        for batch in it:
            x = batch.data[0]          # CSRNDArray
            y = batch.label[0]
            # bucket nnz so real LibSVM data (varying nnz/batch) reuses a
            # few compiled programs; zero-padded entries contribute nothing
            nnz = x._data.shape[0]
            pad = (-nnz) % 4096
            data = jnp.pad(x._data, (0, pad))
            indices = jnp.pad(x._aux["indices"], (0, pad))
            loss_d, correct_d, bgrad_d, contrib_d = step(
                weight._data, bias._data, data, indices,
                x._aux["indptr"], y._data)
            # THE one device fetch of the batch (everything above is
            # async dispatch; everything below is host-side numpy)
            loss, ncorrect, contrib, idx_host = jax.device_get(
                (loss_d, correct_d, contrib_d, indices))
            # unique over the REAL entries only: a padded index would put
            # a phantom zero-grad row in the row-sparse grad, and lazy
            # Adam's momentum would then drift that row on every batch
            uniq, inv = np.unique(idx_host[:nnz], return_inverse=True)
            vals = np.zeros((len(uniq), contrib.shape[1]), np.float32)
            np.add.at(vals, inv, contrib[:nnz])
            wgrad = RowSparseNDArray(jnp.asarray(vals),
                                     uniq.astype(np.int32),
                                     (x.shape[1], contrib.shape[1]))
            updater(0, wgrad, weight)
            bias_updater(1, mx.nd.from_jax(bgrad_d), bias)
            if kv is not None:
                kv.push("weight", weight)
                kv.row_sparse_pull("weight", out=weight,
                                   row_ids=x.indices)
            correct += int(ncorrect)
            total += batch_size
            lsum += float(loss)
            nb += 1
        loss_hist.append(lsum / nb)
    if measure:
        dt = time.perf_counter() - (t_start or time.perf_counter())
        rate = measured * it.num_data / dt if dt > 0 and measured else 0.0
        return correct / total, loss_hist, rate
    return correct / total, loss_hist


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--data", default=None, help="LibSVM file (default: "
                   "generate synthetic)")
    p.add_argument("--num-features", type=int, default=10000)
    p.add_argument("--batch-size", type=int, default=256)
    p.add_argument("--epochs", type=int, default=3)
    p.add_argument("--kvstore", default=None, choices=[None, "local"])
    args = p.parse_args()

    path = args.data
    if path is None:
        path = os.path.join(tempfile.gettempdir(), "synthetic.libsvm")
        make_synthetic_libsvm(path, num_features=args.num_features)
    kv = mx.kv.create(args.kvstore) if args.kvstore else None
    acc, losses = train(path, args.num_features, args.batch_size,
                        args.epochs, kv=kv)
    print("final accuracy %.4f; loss %s" % (acc,
                                            ["%.4f" % v for v in losses]))


if __name__ == "__main__":
    main()
