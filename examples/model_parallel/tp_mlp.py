"""Model parallelism, TPU-style (ref: example/model-parallel/ — the
reference places layer groups on devices by hand with ``group2ctx`` and
auto-inserted cross-device copies; here the SAME intent is expressed as
GSPMD sharding rules and XLA inserts the collectives).

A wide MLP's first layer is column-parallel and its second row-parallel
over the mesh's ``model`` axis, while the batch is data-parallel over
``data`` — Megatron-style 2D parallelism in ~10 lines of placement
rules. Run on the 8-device virtual CPU mesh:

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
    python examples/model_parallel/tp_mlp.py --platform cpu
"""
import argparse

import numpy as np


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--platform", default="")
    p.add_argument("--data-par", type=int, default=2)
    p.add_argument("--model-par", type=int, default=4)
    p.add_argument("--hidden", type=int, default=256)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--steps", type=int, default=10)
    args = p.parse_args()

    if args.platform:
        import jax
        jax.config.update("jax_platforms", args.platform)

    import mxtpu as mx
    from mxtpu import gluon
    from mxtpu.gluon import nn
    from mxtpu.parallel import ShardedTrainStep, make_mesh
    from jax.sharding import PartitionSpec as P

    rng = np.random.RandomState(0)
    nin, ncls = 64, 16
    w_true = rng.normal(0, 1, (nin, ncls)).astype(np.float32)
    pool_x = rng.normal(0, 1, (512, nin)).astype(np.float32)
    pool_y = (pool_x @ w_true).argmax(1).astype(np.float32)

    def batch(i):
        sl = np.arange(i * args.batch_size,
                       (i + 1) * args.batch_size) % len(pool_x)
        return pool_x[sl], pool_y[sl]

    net = nn.HybridSequential(prefix="tp_")
    with net.name_scope():
        net.add(nn.Dense(args.hidden, activation="relu"))
        net.add(nn.Dense(ncls))
    net.initialize()
    x0, _ = batch(0)
    net(mx.nd.array(x0))  # settle shapes

    mesh = make_mesh({"data": args.data_par, "model": args.model_par})
    # Dense weights are [units, in]: layer 1 shards its OUTPUT dim
    # (column parallel), layer 2 its INPUT dim (row parallel) — the
    # classic pairing that needs only one collective per layer pair
    rules = [
        (r".*dense0_weight", P("model", None)),
        (r".*dense0_bias", P("model")),
        (r".*dense1_weight", P(None, "model")),
    ]
    step = ShardedTrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                            mesh, optimizer="sgd",
                            optimizer_params={"learning_rate": 0.1},
                            param_specs=rules)
    first = last = None
    for i in range(args.steps):
        x, y = batch(i)
        loss = float(step(mx.nd.array(x), mx.nd.array(y)).asnumpy())
        if first is None:
            first = loss
        last = loss
        print("step %d loss %.4f" % (i, loss))
    if first is not None:
        print("mesh %s  loss %.4f -> %.4f" % (dict(zip(mesh.axis_names,
                                                       mesh.devices.shape)),
                                              first, last))
    return first, last


if __name__ == "__main__":
    main()
