"""DCGAN on synthetic images (ref: example/gluon/dcgan.py — same G/D
architectures scaled down, same two-optimizer adversarial loop).

Demonstrates multi-network training: two Blocks, two Trainers, the
real/fake label trick, and alternating updates — the loop structure the
reference's GAN examples established. Images are synthetic 32x32 blobs
(hermetic); swap ``make_batch`` for a DataLoader over real data.

    python examples/gluon/dcgan.py --epochs 1
"""
import argparse

import numpy as np

import mxtpu as mx
from mxtpu import autograd, gluon
from mxtpu.gluon import nn


def build_generator(ngf, nz):
    g = nn.HybridSequential()
    with g.name_scope():
        # nz -> 4x4 -> 8x8 -> 16x16 -> 32x32
        g.add(nn.Dense(ngf * 4 * 4 * 4, use_bias=False))
        g.add(nn.HybridLambda(lambda F, x: x.reshape((-1, ngf * 4, 4, 4))))
        g.add(nn.BatchNorm(), nn.Activation("relu"))
        g.add(nn.Conv2DTranspose(ngf * 2, 4, strides=2, padding=1,
                                 use_bias=False))
        g.add(nn.BatchNorm(), nn.Activation("relu"))
        g.add(nn.Conv2DTranspose(ngf, 4, strides=2, padding=1,
                                 use_bias=False))
        g.add(nn.BatchNorm(), nn.Activation("relu"))
        g.add(nn.Conv2DTranspose(3, 4, strides=2, padding=1, use_bias=False))
        g.add(nn.Activation("tanh"))
    return g


def build_discriminator(ndf):
    d = nn.HybridSequential()
    with d.name_scope():
        d.add(nn.Conv2D(ndf, 4, strides=2, padding=1, use_bias=False))
        d.add(nn.LeakyReLU(0.2))
        d.add(nn.Conv2D(ndf * 2, 4, strides=2, padding=1, use_bias=False))
        d.add(nn.BatchNorm(), nn.LeakyReLU(0.2))
        d.add(nn.Conv2D(ndf * 4, 4, strides=2, padding=1, use_bias=False))
        d.add(nn.BatchNorm(), nn.LeakyReLU(0.2))
        d.add(nn.Conv2D(1, 4, strides=1, padding=0, use_bias=False))
        d.add(nn.HybridLambda(lambda F, x: x.reshape((-1,))))
    return d


def make_batch(rng, batch):
    """Synthetic 'real' images: smooth colored gradients in [-1, 1]."""
    xs = np.linspace(-1, 1, 32, dtype=np.float32)
    gx, gy = np.meshgrid(xs, xs)
    imgs = np.empty((batch, 3, 32, 32), np.float32)
    for i in range(batch):
        a, b, c = rng.uniform(-1, 1, 3)
        for ch in range(3):
            imgs[i, ch] = np.tanh(a * gx + b * gy + 0.3 * c * (ch - 1))
    return imgs


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--batch-size", type=int, default=16)
    p.add_argument("--epochs", type=int, default=1)
    p.add_argument("--batches-per-epoch", type=int, default=20)
    p.add_argument("--nz", type=int, default=32)
    p.add_argument("--ngf", type=int, default=16)
    p.add_argument("--ndf", type=int, default=16)
    p.add_argument("--lr", type=float, default=2e-4)
    args = p.parse_args()

    rng = np.random.RandomState(0)
    netG = build_generator(args.ngf, args.nz)
    netD = build_discriminator(args.ndf)
    netG.initialize(mx.init.Normal(0.02))
    netD.initialize(mx.init.Normal(0.02))

    loss_fn = gluon.loss.SigmoidBinaryCrossEntropyLoss()
    trainerG = gluon.Trainer(netG.collect_params(), "adam",
                             {"learning_rate": args.lr, "beta1": 0.5})
    trainerD = gluon.Trainer(netD.collect_params(), "adam",
                             {"learning_rate": args.lr, "beta1": 0.5})

    b = args.batch_size
    n = max(args.batches_per_epoch, 1)
    sumD = sumG = 0.0
    real_label = mx.nd.ones((b,))
    fake_label = mx.nd.zeros((b,))
    for epoch in range(args.epochs):
        sumD = sumG = 0.0
        for _ in range(args.batches_per_epoch):
            real = mx.nd.array(make_batch(rng, b))
            noise = mx.nd.array(rng.normal(0, 1, (b, args.nz))
                                .astype(np.float32))
            # D step: maximize log D(x) + log(1 - D(G(z)))
            with autograd.record():
                out_real = netD(real)
                fake = netG(noise)
                out_fake = netD(fake.detach())
                lossD = loss_fn(out_real, real_label) \
                    + loss_fn(out_fake, fake_label)
            lossD.backward()
            trainerD.step(b)
            # G step: maximize log D(G(z))
            with autograd.record():
                out = netD(netG(noise))
                lossG = loss_fn(out, real_label)
            lossG.backward()
            trainerG.step(b)
            sumD += float(lossD.mean().asnumpy())
            sumG += float(lossG.mean().asnumpy())
        print("epoch %d lossD %.4f lossG %.4f" % (epoch, sumD / n, sumG / n))
    return sumD / n, sumG / n


if __name__ == "__main__":
    main()
