"""Word-level language model example (ref: example/gluon/
word_language_model/train.py — LSTM LM over PTB, the reference's config-2
benchmark workload).

2-layer LSTM over an embedded token stream, truncated-BPTT training with
gradient clipping and perplexity reporting. Runs on a synthetic
Zipf-distributed corpus by default (no dataset egress here); pass --text
with a tokenized file for real data.

Usage:
    python examples/gluon/word_language_model.py --epochs 2
"""
import argparse
import math
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))


def batchify(tokens, batch_size):
    n = len(tokens) // batch_size
    return np.asarray(tokens[:n * batch_size], np.int32) \
        .reshape(batch_size, n).T  # (time, batch)


def synthetic_corpus(vocab, length, seed=0):
    rng = np.random.RandomState(seed)
    # Zipf-ish unigram stream with local correlations (bigram-ish repeats)
    base = rng.zipf(1.3, size=length) % vocab
    rep = rng.uniform(size=length) < 0.3
    base[1:][rep[1:]] = base[:-1][rep[1:]]
    return base.astype(np.int32)


class RNNModel:
    def __init__(self, vocab, embed, hidden, layers, dropout, dtype):
        from mxtpu import gluon
        from mxtpu.gluon import nn, rnn

        self.net = nn.HybridSequential()
        self.embedding = nn.Embedding(vocab, embed)
        self.lstm = rnn.LSTM(hidden, num_layers=layers, dropout=dropout)
        self.decoder = nn.Dense(vocab, flatten=False)
        for blk in (self.embedding, self.lstm, self.decoder):
            self.net.add(blk)
        self.net.initialize()
        if dtype != "float32":
            self.net.cast(dtype)
        self.dtype = dtype

    def __call__(self, x, state):
        emb = self.embedding(x)
        out, state = self.lstm(emb, state)
        return self.decoder(out), state

    def begin_state(self, batch_size):
        return self.lstm.begin_state(batch_size=batch_size,
                                     dtype=self.dtype)

    def collect_params(self):
        return self.net.collect_params()


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--bptt", type=int, default=35)
    p.add_argument("--vocab", type=int, default=10000)
    p.add_argument("--embed", type=int, default=650)
    p.add_argument("--hidden", type=int, default=650)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--dropout", type=float, default=0.5)
    p.add_argument("--lr", type=float, default=1.0)
    p.add_argument("--clip", type=float, default=0.25)
    p.add_argument("--corpus-len", type=int, default=40000)
    p.add_argument("--dtype", default="bfloat16")
    p.add_argument("--text", default="",
                   help="path to a whitespace-tokenized corpus file")
    args = p.parse_args(argv)

    from mxtpu import autograd, gluon
    import mxtpu as mx

    if args.text:
        with open(args.text) as f:
            words = f.read().split()
        vocab_map = {}
        tokens = np.asarray([vocab_map.setdefault(w, len(vocab_map))
                             for w in words], np.int32)
        args.vocab = len(vocab_map)
    else:
        tokens = synthetic_corpus(args.vocab, args.corpus_len)

    data = batchify(tokens, args.batch_size)
    model = RNNModel(args.vocab, args.embed, args.hidden, args.layers,
                     args.dropout, args.dtype)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(model.collect_params(), "sgd",
                            {"learning_rate": args.lr})
    params = [p_ for p_ in model.collect_params().values()
              if p_.grad_req != "null"]

    for epoch in range(args.epochs):
        total_loss, total_tok = 0.0, 0
        state = model.begin_state(args.batch_size)
        tic = time.time()
        for i in range(0, data.shape[0] - 1 - args.bptt, args.bptt):
            x = mx.nd.array(data[i:i + args.bptt])
            y = mx.nd.array(data[i + 1:i + 1 + args.bptt].reshape(-1))
            state = [s.detach() for s in state]  # truncated BPTT
            with autograd.record():
                out, state = model(x, state)
                loss = loss_fn(out.reshape((-1, args.vocab)), y)
            loss.backward()
            gluon.utils.clip_global_norm(
                [p_.grad() for p_ in params],
                args.clip * args.bptt * args.batch_size)
            trainer.step(args.bptt * args.batch_size)
            ntok = args.bptt * args.batch_size
            total_loss += float(loss.mean().asnumpy()) * ntok
            total_tok += ntok
        ppl = math.exp(min(total_loss / max(total_tok, 1), 20))
        print("epoch %d: ppl %.1f  %.0f tokens/s"
              % (epoch, ppl, total_tok / (time.time() - tic)), flush=True)


if __name__ == "__main__":
    main()
