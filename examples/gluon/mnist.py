"""The canonical first Gluon example: an MLP on MNIST
(ref: example/gluon/mnist.py — same model, args, and loop shape).

TPU-native notes: ``net.hybridize()`` compiles the forward to one XLA
executable (the reference's CachedOp); everything else is the familiar
record/backward/Trainer.step loop. Runs on the real MNIST files when
present (``--data-dir``, idx format) and on a synthetic pattern set
otherwise, so the example is runnable in hermetic environments.

    python examples/gluon/mnist.py --epochs 2
"""
import argparse
import os

import numpy as np

import mxtpu as mx
from mxtpu import autograd, gluon
from mxtpu.gluon import nn


def build_net(hidden):
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(hidden, activation="relu"))
        net.add(nn.Dense(64, activation="relu"))
        net.add(nn.Dense(10))
    return net


def load_data(args):
    """(train_x, train_y, val_x, val_y) as numpy, images flattened f32."""
    mnist_dir = args.data_dir
    imgs = os.path.join(mnist_dir, "train-images-idx3-ubyte.gz")
    if mnist_dir and os.path.exists(imgs):
        from mxtpu.gluon.data.vision import MNIST

        def flat(ds):
            # one bulk asnumpy of the dataset's image tensor — NOT
            # per-sample conversion (object arrays, device round-trips)
            x = ds._data.asnumpy().reshape(len(ds), -1) / 255.0
            return x.astype(np.float32), np.asarray(ds._label)

        tx, ty = flat(MNIST(root=mnist_dir, train=True))
        vx, vy = flat(MNIST(root=mnist_dir, train=False))
        return tx, ty, vx, vy
    # synthetic: 10 fixed class prototypes + noise — learnable in seconds
    rng = np.random.RandomState(42)
    protos = rng.uniform(0, 1, (10, 784)).astype(np.float32)

    def make(n):
        y = rng.randint(0, 10, n)
        x = protos[y] + rng.normal(0, 0.15, (n, 784)).astype(np.float32)
        return x.astype(np.float32), y

    tx, ty = make(args.synthetic_size)
    vx, vy = make(max(args.synthetic_size // 5, args.batch_size))
    return tx, ty, vx, vy


def evaluate(net, x, y, batch):
    metric = mx.metric.Accuracy()
    for i in range(0, len(x) - batch + 1, batch):
        out = net(mx.nd.array(x[i:i + batch]))
        metric.update([mx.nd.array(y[i:i + batch])], [out])
    return metric.get()[1]


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--batch-size", type=int, default=100)
    p.add_argument("--epochs", type=int, default=5)
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--hidden", type=int, default=128)
    p.add_argument("--data-dir", default="")
    p.add_argument("--synthetic-size", type=int, default=2000)
    p.add_argument("--no-hybridize", action="store_true")
    args = p.parse_args()

    tx, ty, vx, vy = load_data(args)
    net = build_net(args.hidden)
    net.initialize(mx.init.Xavier())
    if not args.no_hybridize:
        net.hybridize()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": args.lr})

    b = args.batch_size
    acc = evaluate(net, vx, vy, b)
    for epoch in range(args.epochs):
        cum = 0.0
        nb = 0
        for i in range(0, len(tx) - b + 1, b):
            data = mx.nd.array(tx[i:i + b])
            label = mx.nd.array(ty[i:i + b])
            with autograd.record():
                out = net(data)
                loss = loss_fn(out, label)
            loss.backward()
            trainer.step(b)
            cum += float(loss.mean().asnumpy())
            nb += 1
        acc = evaluate(net, vx, vy, b)
        print("epoch %d loss %.4f val-acc %.4f" % (epoch, cum / max(nb, 1),
                                                   acc))
    return acc


if __name__ == "__main__":
    main()
