"""Multi-process data-parallel training example
(ref: the reference's distributed training entry points under
example/image-classification with ``--kv-store dist_sync`` +
tools/launch.py; docs/faq/distributed_training.md).

Run locally with the launcher (2 workers on this machine):

    python tools/launch.py -n 2 python examples/distributed/train_dist.py

On a real multi-host TPU pod, run this script once per host with no
launcher — ``mxtpu.distributed.init()`` autodetects the runtime.

What it shows: the symmetric worker bootstrap, a mesh spanning every
process, per-worker data sharding (each process feeds its LOCAL batch
slice, the reference's part_index/num_parts pattern), one
ShardedTrainStep whose gradient all-reduce spans hosts, and rank-0-only
checkpointing.
"""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))


def main():
    import mxtpu as mx
    from mxtpu import distributed, gluon
    from mxtpu.gluon import nn
    from mxtpu.parallel import ShardedTrainStep, make_mesh

    distributed.init()  # reads MXTPU_*/DMLC_* env; no-op single-process
    rank, nworkers = distributed.rank(), distributed.num_workers()

    mx.random.seed(7)  # same init on every worker (one logical model)
    net = nn.HybridSequential()
    net.add(nn.Dense(64, activation="relu"), nn.Dense(10))
    net.initialize()

    # per-worker shard of a synthetic dataset: the reference's
    # part_index/num_parts contract — each process loads ONLY its slice
    rng = np.random.RandomState(1234)
    all_x = rng.uniform(-1, 1, (512, 32)).astype(np.float32)
    all_y = (all_x[:, :10].sum(axis=1) > 0).astype(np.float32)
    local_x = all_x[rank::nworkers]
    local_y = all_y[rank::nworkers]

    x0 = mx.nd.array(local_x[:8])
    net(x0)  # settle shapes

    mesh = make_mesh({"data": -1})  # every device across every process
    step = ShardedTrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                            mesh, optimizer="sgd",
                            optimizer_params={"learning_rate": 0.1,
                                              "momentum": 0.9})
    bs = 32
    for epoch in range(3):
        losses = []
        for i in range(0, len(local_x), bs):
            xb = mx.nd.array(local_x[i:i + bs])
            yb = mx.nd.array(local_y[i:i + bs])
            losses.append(float(step(xb, yb).asnumpy()))
        if rank == 0:
            print("epoch %d: loss %.4f (workers=%d)"
                  % (epoch, sum(losses) / len(losses), nworkers),
                  flush=True)

    distributed.barrier("epoch_end")
    if rank == 0:  # single-writer checkpoint, reference file format
        net.export("/tmp/train_dist_model", epoch=3)
        print("rank 0 exported checkpoint", flush=True)


if __name__ == "__main__":
    main()
