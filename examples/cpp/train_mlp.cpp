// Train a 2-layer MLP to convergence from C++ through the mxtpu C ABI —
// no Python in this source file (ref: cpp-package/example/mlp.cpp, which
// drives the reference's C ABI the same way: Symbol compose -> Executor
// bind -> forward/backward -> KVStore optimizer updates).
//
// Build (see tests/test_c_api.py::test_cpp_training_via_abi):
//   g++ -std=c++14 train_mlp.cpp -I include -l:_libmxtpu.so -lpythonX.Y
//
// The program makes a two-blob binary dataset, composes
//   data -> FullyConnected(16) -> relu -> FullyConnected(2) -> SoftmaxOutput
// binds it, and runs full-batch SGD via KVStore push(grad)/pull(weight).
// Exit code 0 iff the final accuracy is >= 0.95 and the loss fell 5x.

#include <cmath>
#include <cstdio>
#include <random>
#include <string>
#include <vector>

#include <mxtpu/mxtpu-cpp.hpp>

namespace mc = mxtpu::cpp;

int Run() {
  const int n = 64, in_dim = 2, hidden = 16, classes = 2;

  // two gaussian blobs; label = which blob
  std::mt19937 rng(0);
  std::normal_distribution<float> noise(0.0f, 0.6f);
  std::vector<float> xs(n * in_dim), ys(n);
  for (int i = 0; i < n; ++i) {
    float cls = static_cast<float>(i % 2);
    float cx = cls == 0.0f ? -1.0f : 1.0f;
    xs[i * 2 + 0] = cx + noise(rng);
    xs[i * 2 + 1] = cx + noise(rng);
    ys[i] = cls;
  }

  // symbol: the reference's classic MLP graph, composed op by op
  mc::Symbol data = mc::Symbol::Variable("data");
  mc::Symbol w1 = mc::Symbol::Variable("fc1_weight");
  mc::Symbol w2 = mc::Symbol::Variable("fc2_weight");
  mc::Symbol label = mc::Symbol::Variable("softmax_label");
  mc::Symbol fc1 = mc::Symbol::Compose(
      "FullyConnected", "fc1", {&data, &w1},
      {{"num_hidden", std::to_string(hidden)}, {"no_bias", "True"}});
  mc::Symbol act = mc::Symbol::Compose("Activation", "relu1", {&fc1},
                                       {{"act_type", "relu"}});
  mc::Symbol fc2 = mc::Symbol::Compose(
      "FullyConnected", "fc2", {&act, &w2},
      {{"num_hidden", std::to_string(classes)}, {"no_bias", "True"}});
  mc::Symbol out = mc::Symbol::Compose("SoftmaxOutput", "softmax",
                                       {&fc2, &label}, {});

  // parameter init (tiny uniform, like mxnet-cpp's SimpleBind defaults)
  std::uniform_real_distribution<float> u(-0.5f, 0.5f);
  std::vector<float> w1v(hidden * in_dim), w2v(classes * hidden);
  for (float &v : w1v) v = u(rng);
  for (float &v : w2v) v = u(rng);

  mc::NDArray a_data({n, in_dim}, xs.data());
  mc::NDArray a_label({n}, ys.data());
  mc::NDArray a_w1({hidden, in_dim}, w1v.data());
  mc::NDArray a_w2({classes, hidden}, w2v.data());

  mc::Executor exec(out, {"data", "fc1_weight", "fc2_weight",
                          "softmax_label"},
                    {&a_data, &a_w1, &a_w2, &a_label});

  // data-parallel-style optimizer: push grads, pull refreshed weights
  mc::KVStore kv("local");
  kv.SetOptimizer("sgd", {{"learning_rate", "0.02"}});
  kv.Init({"fc1_weight", "fc2_weight"}, {&a_w1, &a_w2});

  double first_loss = -1.0, loss = 0.0;
  for (int epoch = 0; epoch < 200; ++epoch) {
    exec.Forward(true);
    exec.Backward();
    mc::NDArray g1 = exec.ArgGrad("fc1_weight");
    mc::NDArray g2 = exec.ArgGrad("fc2_weight");
    kv.Push({"fc1_weight", "fc2_weight"}, {&g1, &g2});
    kv.Pull({"fc1_weight", "fc2_weight"}, {&a_w1, &a_w2});

    std::vector<float> probs = exec.Output(0).CopyToHost();
    loss = 0.0;
    for (int i = 0; i < n; ++i) {
      float p = probs[i * classes + static_cast<int>(ys[i])];
      loss -= std::log(p > 1e-12f ? p : 1e-12f);
    }
    loss /= n;
    if (first_loss < 0) first_loss = loss;
    if (epoch % 40 == 0) std::printf("epoch %d loss %.4f\n", epoch, loss);
  }

  exec.Forward(false);
  std::vector<float> probs = exec.Output(0).CopyToHost();
  int correct = 0;
  for (int i = 0; i < n; ++i) {
    int pred = probs[i * classes] > probs[i * classes + 1] ? 0 : 1;
    if (pred == static_cast<int>(ys[i])) ++correct;
  }
  double acc = static_cast<double>(correct) / n;
  std::printf("FINAL loss %.4f (from %.4f) acc %.3f\n", loss, first_loss,
              acc);
  bool converged = acc >= 0.95 && loss < first_loss / 5.0;
  std::printf(converged ? "TRAINED_OK\n" : "TRAINED_FAIL\n");
  return converged ? 0 : 1;
}

int main() {
  try {
    return Run();
  } catch (const std::exception &e) {
    std::printf("exception: %s\n", e.what());
    return 2;
  }
}
