// CachedOp + DLPack + shared-memory from pure C++ — the interop trio of
// the mxtpu C ABI (ref: the reference reaches CachedOp only through
// Gluon's Python frontend, and its DLPack bridge lives in
// src/c_api/c_api.cc MXNDArrayToDLPack).
//
// Build/run: see tests/test_c_api.py::test_cpp_interop_via_abi.
#include <mxtpu/mxtpu-cpp.hpp>

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <vector>

namespace mc = mxtpu::cpp;

int main() {
  // hybridize from C++: compile (a + b) * a once, reuse it
  mc::Symbol a = mc::Symbol::Variable("a");
  mc::Symbol b = mc::Symbol::Variable("b");
  mc::Symbol sum = mc::Symbol::Compose("elemwise_add", "sum0", {&a, &b});
  mc::Symbol prod = mc::Symbol::Compose("elemwise_mul", "prod0", {&sum, &a});
  mc::CachedOp op(prod);

  std::vector<float> av = {1.f, 2.f, 3.f}, bv = {4.f, 5.f, 6.f};
  mc::NDArray na({3}, av.data()), nb({3}, bv.data());
  // inputs in list_inputs() order: a then b (a appears first in the graph)
  std::vector<mc::NDArray> ins;
  ins.emplace_back(mc::NDArray({3}, av.data()));
  ins.emplace_back(mc::NDArray({3}, bv.data()));
  std::vector<mc::NDArray> outs = op(ins);
  std::vector<float> host = outs[0].CopyToHost();
  for (int i = 0; i < 3; ++i) {
    float want = (av[i] + bv[i]) * av[i];
    if (host[i] != want) {
      std::fprintf(stderr, "cachedop mismatch at %d: %f != %f\n", i,
                   host[i], want);
      return 1;
    }
  }
  // second invoke hits the compiled cache
  std::vector<mc::NDArray> outs2 = op(ins);
  if (outs2[0].CopyToHost() != host) return 1;
  std::printf("CACHEDOP OK\n");

  // DLPack: export, inspect the standard header, re-import, release
  void *dlm = mc::ToDLPack(na);
  // DLManagedTensor begins with DLTensor{void* data; {i32,i32} device;
  // i32 ndim; ...}; ndim sits after data+device
  const char *base = static_cast<const char *>(dlm);
  std::int32_t ndim = 0;
  std::memcpy(&ndim, base + sizeof(void *) + 2 * sizeof(std::int32_t),
              sizeof(ndim));
  if (ndim != 1) {
    std::fprintf(stderr, "dlpack ndim %d != 1\n", ndim);
    return 1;
  }
  mc::NDArray back = mc::FromDLPack(dlm);  // consumes dlm
  if (back.CopyToHost() != av) return 1;
  void *dlm2 = mc::ToDLPack(nb);
  mc::ReleaseDLPack(dlm2);  // unconsumed export: manual release
  std::printf("DLPACK OK\n");

  // shared memory: one-shot transfer through a named POSIX segment
  std::string seg = mc::ToSharedMem(na);
  mc::NDArray from_shm = mc::FromSharedMem(seg, /*dtype_flag=*/0, {3});
  if (from_shm.CopyToHost() != av) return 1;
  std::printf("SHAREDMEM OK\n");
  return 0;
}
