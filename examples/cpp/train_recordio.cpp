// Load a RecordIO dataset and train through the mxtpu C ABI — no Python
// in this source file (ref: cpp-package examples + the reference's
// MXRecordIO* C surface; wire format parity with src/io/recordio.cc).
//
// Build (see tests/test_c_api.py::test_cpp_recordio_training_via_abi):
//   g++ -std=c++14 train_recordio.cpp -I include -l:_libmxtpu.so -lpythonX.Y
//
// The program:
//   1. writes a two-blob float dataset into a .rec file (RecordIOWriter:
//      each record = one sample, packed [label, x0, x1]),
//   2. reads every record back (RecordIOReader) and checks the roundtrip,
//   3. trains the classic MLP on the recovered data via Symbol/Executor/
//      KVStore, asserting the loss falls and accuracy reaches >= 0.95.
// Exit code 0 iff all three stages hold.

#include <cmath>
#include <cstdio>
#include <cstring>
#include <random>
#include <string>
#include <vector>

#include <mxtpu/mxtpu-cpp.hpp>

namespace mc = mxtpu::cpp;

int Run(const std::string &rec_path) {
  const int n = 64, in_dim = 2, hidden = 16, classes = 2;

  // ---- 1. write the dataset as RecordIO ----
  std::mt19937 rng(0);
  std::normal_distribution<float> noise(0.0f, 0.6f);
  std::vector<float> xs(n * in_dim), ys(n);
  {
    mc::RecordIOWriter writer(rec_path);
    for (int i = 0; i < n; ++i) {
      float cls = static_cast<float>(i % 2);
      float cx = cls == 0.0f ? -1.0f : 1.0f;
      float sample[1 + in_dim];
      sample[0] = cls;
      sample[1] = cx + noise(rng);
      sample[2] = cx + noise(rng);
      ys[i] = cls;
      xs[i * 2 + 0] = sample[1];
      xs[i * 2 + 1] = sample[2];
      writer.Write(std::string(reinterpret_cast<const char *>(sample),
                               sizeof(sample)));
    }
    if (writer.Tell() == 0) {
      std::fprintf(stderr, "writer.Tell() did not advance\n");
      return 1;
    }
  }

  // ---- 2. read it back and verify the roundtrip ----
  std::vector<float> rxs(n * in_dim), rys(n);
  {
    mc::RecordIOReader reader(rec_path);
    std::string record;
    int i = 0;
    while (reader.Read(&record)) {
      if (record.size() != sizeof(float) * (1 + in_dim) || i >= n) {
        std::fprintf(stderr, "bad record %d (size %zu)\n", i, record.size());
        return 1;
      }
      const float *f = reinterpret_cast<const float *>(record.data());
      rys[i] = f[0];
      rxs[i * 2 + 0] = f[1];
      rxs[i * 2 + 1] = f[2];
      ++i;
    }
    if (i != n) {
      std::fprintf(stderr, "read %d records, expected %d\n", i, n);
      return 1;
    }
    for (int k = 0; k < n * in_dim; ++k) {
      if (rxs[k] != xs[k]) {
        std::fprintf(stderr, "roundtrip mismatch at %d\n", k);
        return 1;
      }
    }
  }

  // ---- 3. train on the recovered data ----
  mc::Symbol data = mc::Symbol::Variable("data");
  mc::Symbol w1 = mc::Symbol::Variable("fc1_weight");
  mc::Symbol w2 = mc::Symbol::Variable("fc2_weight");
  mc::Symbol label = mc::Symbol::Variable("softmax_label");
  mc::Symbol fc1 = mc::Symbol::Compose(
      "FullyConnected", "fc1", {&data, &w1},
      {{"num_hidden", std::to_string(hidden)}, {"no_bias", "True"}});
  mc::Symbol act = mc::Symbol::Compose("Activation", "relu1", {&fc1},
                                       {{"act_type", "relu"}});
  mc::Symbol fc2 = mc::Symbol::Compose(
      "FullyConnected", "fc2", {&act, &w2},
      {{"num_hidden", std::to_string(classes)}, {"no_bias", "True"}});
  mc::Symbol out = mc::Symbol::Compose("SoftmaxOutput", "softmax",
                                       {&fc2, &label}, {});

  std::uniform_real_distribution<float> u(-0.5f, 0.5f);
  std::vector<float> w1v(hidden * in_dim), w2v(classes * hidden);
  for (float &v : w1v) v = u(rng);
  for (float &v : w2v) v = u(rng);

  mc::NDArray a_data({n, in_dim}, rxs.data());
  mc::NDArray a_label({n}, rys.data());
  mc::NDArray a_w1({hidden, in_dim}, w1v.data());
  mc::NDArray a_w2({classes, hidden}, w2v.data());

  mc::Executor exec(out, {"data", "fc1_weight", "fc2_weight",
                          "softmax_label"},
                    {&a_data, &a_w1, &a_w2, &a_label});
  mc::KVStore kv("local");
  kv.SetOptimizer("sgd", {{"learning_rate", "0.02"}});
  kv.Init({"fc1_weight", "fc2_weight"}, {&a_w1, &a_w2});

  double first_loss = -1.0, loss = 0.0;
  for (int epoch = 0; epoch < 200; ++epoch) {
    exec.Forward(true);
    exec.Backward();
    mc::NDArray g1 = exec.ArgGrad("fc1_weight");
    mc::NDArray g2 = exec.ArgGrad("fc2_weight");
    kv.Push({"fc1_weight", "fc2_weight"}, {&g1, &g2});
    kv.Pull({"fc1_weight", "fc2_weight"}, {&a_w1, &a_w2});

    std::vector<float> probs = exec.Output(0).CopyToHost();
    loss = 0.0;
    for (int i = 0; i < n; ++i) {
      float p = probs[i * classes + static_cast<int>(rys[i])];
      loss -= std::log(p > 1e-12f ? p : 1e-12f);
    }
    loss /= n;
    if (first_loss < 0.0) first_loss = loss;
  }

  exec.Forward(false);
  std::vector<float> probs = exec.Output(0).CopyToHost();
  int correct = 0;
  for (int i = 0; i < n; ++i) {
    int pred = probs[i * classes] > probs[i * classes + 1] ? 0 : 1;
    if (pred == static_cast<int>(rys[i])) ++correct;
  }
  double acc = static_cast<double>(correct) / n;
  std::printf("first_loss=%.4f final_loss=%.4f acc=%.3f\n", first_loss,
              loss, acc);
  if (acc < 0.95 || loss > first_loss / 5.0) return 1;
  std::printf("TRAIN_RECORDIO_OK\n");
  return 0;
}

int main(int argc, char **argv) {
  const char *path = argc > 1 ? argv[1] : "/tmp/mxtpu_train.rec";
  try {
    return Run(path);
  } catch (const std::exception &e) {
    std::fprintf(stderr, "FAILED: %s\n", e.what());
    return 1;
  }
}
