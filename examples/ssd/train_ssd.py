"""Tiny SSD-style detector, trained end to end (ref: example/ssd — the
reference's headline detection example over the MultiBox op family).

TPU-native shape: a small Gluon conv backbone emits TWO feature scales;
each scale gets anchors (`mx.nd.multibox_prior`), a class head, and a box
head. Training targets come from `mx.nd.multibox_target` (matching +
offset encoding), the loss is softmax CE (classes) + masked L1 (offsets),
and inference decodes + NMS-es with `mx.nd.multibox_detection` — the
same three-op pipeline as the reference's symbol graph
(src/operator/contrib/multibox_*.cc), here driven imperatively under
autograd and hybridizable like any Gluon net.

Synthetic task: one axis-aligned bright rectangle per 64x64 image;
class 0 = "box". Run: python examples/ssd/train_ssd.py
"""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import mxtpu as mx  # noqa: E402
from mxtpu import autograd, gluon  # noqa: E402
from mxtpu.gluon import nn  # noqa: E402


def make_synthetic(num, size=64, seed=0):
    """Images with one bright rectangle; labels (num, 1, 5) as
    [cls, xmin, ymin, xmax, ymax] in [0, 1] (the MultiBoxTarget format)."""
    r = np.random.RandomState(seed)
    imgs = r.uniform(0, 0.2, (num, size, size, 3)).astype(np.float32)
    labels = np.zeros((num, 1, 5), np.float32)
    for i in range(num):
        w, h = r.randint(size // 4, size // 2, 2)
        x0 = r.randint(0, size - w)
        y0 = r.randint(0, size - h)
        imgs[i, y0:y0 + h, x0:x0 + w] += 0.8
        labels[i, 0] = [0, x0 / size, y0 / size, (x0 + w) / size,
                        (y0 + h) / size]
    return imgs.clip(0, 1), labels


class TinySSD(gluon.HybridBlock):
    """Two-scale SSD head over a 3-block backbone. num_anchors per pixel
    is len(sizes) + len(ratios) - 1 (the multibox_prior convention)."""

    SIZES = ([0.3, 0.45], [0.6, 0.8])
    RATIOS = ([1.0, 2.0, 0.5],) * 2
    NUM_CLASSES = 1

    def __init__(self, **kw):
        super().__init__(**kw)
        na = len(self.SIZES[0]) + len(self.RATIOS[0]) - 1
        self._na = na
        with self.name_scope():
            self.backbone = nn.HybridSequential()
            for ch in (16, 32):
                self.backbone.add(nn.Conv2D(ch, 3, padding=1),
                                  nn.BatchNorm(),
                                  nn.Activation("relu"),
                                  nn.MaxPool2D(2))
            self.scale2 = nn.HybridSequential()
            self.scale2.add(nn.Conv2D(64, 3, strides=2, padding=1),
                            nn.BatchNorm(), nn.Activation("relu"))
            # per-scale heads: (classes+1) and 4 offsets per anchor
            self.cls1 = nn.Conv2D(na * (self.NUM_CLASSES + 1), 3, padding=1)
            self.box1 = nn.Conv2D(na * 4, 3, padding=1)
            self.cls2 = nn.Conv2D(na * (self.NUM_CLASSES + 1), 3, padding=1)
            self.box2 = nn.Conv2D(na * 4, 3, padding=1)

    def hybrid_forward(self, F, x):
        f1 = self.backbone(x)                 # size/4
        f2 = self.scale2(f1)                  # size/8
        c = self.NUM_CLASSES + 1
        outs = []
        for feat, cls_head, box_head in ((f1, self.cls1, self.box1),
                                         (f2, self.cls2, self.box2)):
            cp = cls_head(feat)               # NCHW [B, na*c, H, W]
            bp = box_head(feat)
            b = cp.shape[0]
            hw = cp.shape[2] * cp.shape[3]
            cp = cp.reshape((b, self._na, c, hw)).transpose(
                (0, 3, 1, 2)).reshape((b, hw * self._na, c))
            bp = bp.reshape((b, self._na * 4, hw)).transpose(
                (0, 2, 1)).reshape((b, hw * self._na * 4))
            outs.append((cp, bp))
        cls_preds = mx.nd.concat(outs[0][0], outs[1][0], dim=1)
        loc_preds = mx.nd.concat(outs[0][1], outs[1][1], dim=1)
        return cls_preds, loc_preds

    def anchors(self, x):
        """Per-scale multibox priors, concatenated [1, A, 4]."""
        f1_hw = x.shape[1] // 4
        f2_hw = x.shape[1] // 8
        ank = []
        for hw, sizes, ratios in ((f1_hw, self.SIZES[0], self.RATIOS[0]),
                                  (f2_hw, self.SIZES[1], self.RATIOS[1])):
            feat = mx.nd.zeros((1, 1, hw, hw))
            ank.append(mx.nd.multibox_prior(feat, sizes=sizes,
                                            ratios=ratios))
        return mx.nd.concat(*ank, dim=1)


def train(num_images=32, batch_size=8, epochs=12, lr=0.05, seed=0):
    imgs, labels = make_synthetic(num_images, seed=seed)
    net = TinySSD()
    net.initialize()
    # NCHW input for the conv heads
    x_all = mx.nd.array(imgs.transpose(0, 3, 1, 2))
    y_all = mx.nd.array(labels)
    anchors = net.anchors(mx.nd.array(imgs))     # [1, A, 4]

    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": lr})
    cls_loss = gluon.loss.SoftmaxCrossEntropyLoss()
    box_loss = gluon.loss.L1Loss()

    hist = []
    for ep in range(epochs):
        total = 0.0
        for s in range(0, num_images, batch_size):
            xb = x_all[s:s + batch_size]
            yb = y_all[s:s + batch_size]
            with autograd.record():
                cls_preds, loc_preds = net(xb)
                # targets are CONSTANTS (matching + offset encoding is
                # non-differentiable, ref multibox_target.cc FGradient
                # none): pause recording so the target op stays OFF the
                # tape, and detach the predictions it matches against
                with autograd.pause():
                    loc_t, loc_m, cls_t = mx.nd.multibox_target(
                        anchors, yb,
                        cls_preds.detach().transpose((0, 2, 1)))
                l_cls = cls_loss(
                    cls_preds.reshape((-1, net.NUM_CLASSES + 1)),
                    cls_t.reshape((-1,)))
                l_box = box_loss(loc_preds * loc_m, loc_t * loc_m)
                loss = l_cls.mean() + l_box.mean()
            loss.backward()
            # mean losses => step(1): Trainer.step's rescale_grad is
            # 1/batch, and mean+step(batch) would divide twice, silently
            # coupling the learning rate to the batch size
            trainer.step(1)
            total += float(loss.asnumpy())
        hist.append(total / max(1, num_images // batch_size))
    return net, anchors, hist


def detect(net, anchors, imgs_nhwc):
    """[B, A, 6] rows of [cls_id, score, xmin, ymin, xmax, ymax]."""
    x = mx.nd.array(np.asarray(imgs_nhwc).transpose(0, 3, 1, 2))
    cls_preds, loc_preds = net(x)
    cls_prob = mx.nd.softmax(cls_preds, axis=-1).transpose((0, 2, 1))
    return mx.nd.multibox_detection(cls_prob, loc_preds, anchors,
                                    nms_threshold=0.45)


def main():
    net, anchors, hist = train()
    print("loss: %.3f -> %.3f" % (hist[0], hist[-1]))
    imgs, labels = make_synthetic(4, seed=123)
    det = detect(net, anchors, imgs).asnumpy()
    for i in range(det.shape[0]):
        rows = det[i]
        best = rows[rows[:, 0] >= 0]
        if len(best):
            b = best[np.argmax(best[:, 1])]
            print("img %d: cls=%d score=%.2f box=[%.2f %.2f %.2f %.2f] "
                  "gt=%s" % (i, int(b[0]), b[1], *b[2:6],
                             np.round(labels[i, 0, 1:], 2)))


if __name__ == "__main__":
    main()
