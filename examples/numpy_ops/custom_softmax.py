"""Custom operator written against the NumPy-callback escape hatch
(ref: example/numpy-ops/custom_softmax.py — the classic CustomOp demo:
a softmax whose forward/backward run as host-side NumPy inside the
framework's dispatch).

TPU-native notes: the reference runs the callback on a dedicated worker
thread inside its engine (src/operator/custom/custom-inl.h); here the op
body executes through ``jax.pure_callback`` with a ``custom_vjp``, so it
still composes with autograd and jit (mxtpu/operator.py).

    python examples/numpy_ops/custom_softmax.py
"""
import numpy as np

import mxtpu as mx
from mxtpu import autograd


class Softmax(mx.operator.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        x = in_data[0].asnumpy()
        e = np.exp(x - x.max(axis=1, keepdims=True))
        self.assign(out_data[0], req[0], mx.nd.array(e / e.sum(axis=1,
                                                               keepdims=True)))

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        y = out_data[0].asnumpy()
        gy = out_grad[0].asnumpy()
        gx = y * (gy - (gy * y).sum(axis=1, keepdims=True))
        self.assign(in_grad[0], req[0], mx.nd.array(gx))


@mx.operator.register("demo_softmax")
class SoftmaxProp(mx.operator.CustomOpProp):
    def __init__(self):
        super().__init__(need_top_grad=True)

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]], []

    def create_operator(self, ctx, shapes, dtypes):
        return Softmax()


def main():
    rng = np.random.RandomState(0)
    x = mx.nd.array(rng.uniform(-2, 2, (4, 6)).astype(np.float32))
    x.attach_grad()
    with autograd.record():
        y = mx.nd.Custom(x, op_type="demo_softmax")
        loss = (y * y).sum()
    loss.backward()

    # check against the built-in softmax + its autograd
    x2 = mx.nd.array(x.asnumpy())
    x2.attach_grad()
    with autograd.record():
        y2 = mx.nd.softmax(x2, axis=1)
        loss2 = (y2 * y2).sum()
    loss2.backward()

    np.testing.assert_allclose(y.asnumpy(), y2.asnumpy(), rtol=1e-5,
                               atol=1e-6)
    np.testing.assert_allclose(x.grad.asnumpy(), x2.grad.asnumpy(),
                               rtol=1e-4, atol=1e-5)
    print("custom softmax forward+backward match the built-in: OK")
    return True


if __name__ == "__main__":
    main()
