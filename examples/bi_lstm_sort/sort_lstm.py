"""Bidirectional LSTM that sorts integer sequences (ref:
example/bi-lstm-sort — the reference's classic seq-labeling demo:
`500 30 999 10 130` -> `10 30 130 500 999`).

TPU-native shape: one gluon HybridBlock (Embedding -> bidirectional
LSTM -> per-step Dense), trained hybridized so the whole seq model is a
single jit-compiled XLA program over the fused RNN op's lax.scan
(mxtpu/ops/rnn_ops.py). Every output position is a classification over
the vocabulary — sorting emerges from bidirectional context alone.

Run: python examples/bi_lstm_sort/sort_lstm.py
"""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import mxtpu as mx  # noqa: E402
from mxtpu import autograd, gluon  # noqa: E402
from mxtpu.gluon import nn, rnn  # noqa: E402


def make_batches(num, seq_len=5, vocab=16, seed=0):
    """(tokens, sorted_tokens) int batches; digits are vocabulary ids."""
    r = np.random.RandomState(seed)
    x = r.randint(0, vocab, (num, seq_len)).astype(np.int32)
    y = np.sort(x, axis=1).astype(np.float32)
    return x, y


class SortNet(gluon.HybridBlock):
    def __init__(self, vocab=16, hidden=64, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.embed = nn.Embedding(vocab, 32)
            self.lstm = rnn.LSTM(hidden, num_layers=1, bidirectional=True,
                                 layout="NTC")
            self.out = nn.Dense(vocab, flatten=False)

    def hybrid_forward(self, F, tokens):
        return self.out(self.lstm(self.embed(tokens)))


def train(num=512, seq_len=5, vocab=16, batch=64, epochs=30, lr=5e-3,
          seed=0):
    x_np, y_np = make_batches(num, seq_len, vocab, seed)
    x_all = mx.nd.array(x_np, dtype="int32")
    y_all = mx.nd.array(y_np)
    net = SortNet(vocab=vocab)
    net.initialize()
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": lr})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    hist = []
    for _ in range(epochs):
        total, nb = 0.0, 0
        for s in range(0, num, batch):
            xb = x_all[s:s + batch]
            yb = y_all[s:s + batch]
            with autograd.record():
                logits = net(xb)
                loss = loss_fn(logits.reshape((-1, vocab)),
                               yb.reshape((-1,))).mean()
            loss.backward()
            trainer.step(1)
            total += float(loss.asnumpy())
            nb += 1
        hist.append(total / nb)
    return net, hist


def accuracy(net, seq_len=5, vocab=16, num=128, seed=99):
    x_np, y_np = make_batches(num, seq_len, vocab, seed)
    pred = net(mx.nd.array(x_np, dtype="int32")).asnumpy().argmax(-1)
    per_tok = float((pred == y_np).mean())
    per_seq = float((pred == y_np).all(axis=1).mean())
    return per_tok, per_seq


def main():
    net, hist = train()
    tok_acc, seq_acc = accuracy(net)
    print("loss %.3f -> %.3f | token acc %.2f | full-seq acc %.2f"
          % (hist[0], hist[-1], tok_acc, seq_acc))
    x_np, _ = make_batches(1, seed=7)
    pred = net(mx.nd.array(x_np, dtype="int32")).asnumpy().argmax(-1)
    print("input :", x_np[0].tolist())
    print("sorted:", pred[0].tolist())


if __name__ == "__main__":
    main()
