"""CNN for sentence classification, Kim-2014 style
(ref: example/cnn_text_classification/text_cnn.py — embedding, parallel
conv branches of several filter widths, max-over-time pooling, concat,
dropout, dense).

Data is a hermetic synthetic task with real signal: class = which of two
"keyword" token groups dominates the sentence. Swap ``make_data`` for a
real tokenized corpus to reproduce the reference's MR/SST workflow.

    python examples/cnn_text_classification/text_cnn.py --epochs 3
"""
import argparse

import numpy as np

import mxtpu as mx
from mxtpu import autograd, gluon
from mxtpu.gluon import nn
from mxtpu.gluon.block import HybridBlock


class TextCNN(HybridBlock):
    def __init__(self, vocab, embed, num_filter, widths, classes,
                 dropout, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.embedding = nn.Embedding(vocab, embed)
            self.branches = []
            for i, w in enumerate(widths):
                conv = nn.Conv1D(num_filter, w, activation="relu",
                                 prefix="conv%d_" % i)
                # NCW layout: Conv1D contracts over (embed, width)
                self.register_child(conv)
                self.branches.append(conv)
            self.dropout = nn.Dropout(dropout)
            self.fc = nn.Dense(classes)

    def hybrid_forward(self, F, tokens):
        # (batch, seq) -> (batch, seq, embed) -> (batch, embed, seq)
        e = self.embedding(tokens).transpose((0, 2, 1))
        pooled = [F.max(br(e), axis=2) for br in self.branches]
        return self.fc(self.dropout(F.concat(*pooled, dim=1)))


def make_data(rng, n, vocab, seq, classes, keywords):
    """Sentences of random tokens; each class has a 3-token keyword set
    (SHARED between train and val — the signal to learn), and the label
    is the class whose keywords were injected."""
    # background tokens exclude every class's keywords — the label is
    # then EXACTLY "which keywords were injected", as documented
    bg = np.setdiff1d(np.arange(10, vocab), keywords.ravel())
    x = bg[rng.randint(0, len(bg), (n, seq))]
    y = rng.randint(0, classes, n)
    for i in range(n):
        kws = keywords[y[i]]
        pos = rng.choice(seq, 4, replace=False)
        x[i, pos] = kws[rng.randint(0, 3, 4)]
    return x.astype(np.int32), y.astype(np.float32)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=5)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--vocab", type=int, default=200)
    p.add_argument("--seq-len", type=int, default=24)
    p.add_argument("--embed", type=int, default=32)
    p.add_argument("--num-filter", type=int, default=16)
    p.add_argument("--widths", default="2,3,4")
    p.add_argument("--classes", type=int, default=3)
    p.add_argument("--dropout", type=float, default=0.2)
    p.add_argument("--lr", type=float, default=5e-3)
    p.add_argument("--train-size", type=int, default=1024)
    args = p.parse_args()

    rng = np.random.RandomState(0)
    widths = [int(w) for w in args.widths.split(",")]
    keywords = rng.choice(np.arange(10, args.vocab), (args.classes, 3),
                          replace=False)
    tx, ty = make_data(rng, args.train_size, args.vocab, args.seq_len,
                       args.classes, keywords)
    vx, vy = make_data(rng, max(args.train_size // 4, args.batch_size),
                       args.vocab, args.seq_len, args.classes, keywords)

    net = TextCNN(args.vocab, args.embed, args.num_filter, widths,
                  args.classes, args.dropout)
    net.initialize(mx.init.Xavier())
    net.hybridize()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})

    b = args.batch_size
    acc = 0.0
    for epoch in range(args.epochs):
        cum, nb = 0.0, 0
        for i in range(0, len(tx) - b + 1, b):
            data = mx.nd.array(tx[i:i + b], dtype="int32")
            label = mx.nd.array(ty[i:i + b])
            with autograd.record():
                loss = loss_fn(net(data), label)
            loss.backward()
            trainer.step(b)
            cum += float(loss.mean().asnumpy())
            nb += 1
        metric = mx.metric.Accuracy()
        for i in range(0, len(vx) - b + 1, b):
            metric.update([mx.nd.array(vy[i:i + b])],
                          [net(mx.nd.array(vx[i:i + b], dtype="int32"))])
        acc = metric.get()[1]
        print("epoch %d loss %.4f val-acc %.4f"
              % (epoch, cum / max(nb, 1), acc))
    return acc


if __name__ == "__main__":
    main()
