"""Benchmarks for the BASELINE.json scoring configs.

Select with ``BENCH_CONFIG`` (default ``resnet50`` — the headline config;
``all`` runs every config, one JSON line each):

* ``resnet50``  — ResNet-50 training, b128 bf16 NHWC (BENCH_LAYOUT=NCHW to
  compare layouts). Reference baseline 363.69 img/s: batch 128 fp32 on 1x
  V100 (docs/faq/perf.md:219; BASELINE.md "Training, single GPU").
* ``lstm_ptb``  — Gluon 2x650-unit LSTM PTB language model (reference
  example/gluon/word_language_model), tokens/sec.
* ``bert_base`` — BERT-base-shaped bidirectional encoder pretraining step
  (12L/768d/12H, seq 512) driving the Pallas flash-attention kernel,
  tokens/sec.

Every config prints ONE JSON line {"metric", "value", "unit", "vs_baseline",
"mfu"}. MFU comes from the XLA-compiled step's own FLOP count
(``ShardedTrainStep.compiled_step_flops``) against chip peak (v5e bf16
~197 TFLOP/s; override with BENCH_PEAK_TFLOPS). The whole train step
(fwd+loss+bwd+update) runs as one compiled XLA program via
mxtpu.parallel.ShardedTrainStep; bf16 is the TPU design point (MXU-native),
matching how the reference leans on cuDNN fp32.
"""
import json
import os
import time

import numpy as np

STEPS = int(os.environ.get("BENCH_STEPS", "20"))


def _peak_flops():
    """Chip peak FLOP/s for the MFU denominator."""
    env = os.environ.get("BENCH_PEAK_TFLOPS")
    if env:
        return float(env) * 1e12
    import jax
    if jax.devices()[0].platform == "cpu":
        return None  # MFU is meaningless on the CPU fallback
    return 197e12  # TPU v5e bf16


def _run(step, batch, n_items):
    """Warm up, time STEPS steps, return (items/sec, mfu_or_None)."""
    for _ in range(3):  # warmup + compile
        step(*batch).asnumpy()
    t0 = time.perf_counter()
    for _ in range(STEPS):
        out = step(*batch)
    out.asnumpy()  # sync
    dt = time.perf_counter() - t0
    rate = n_items * STEPS / dt
    peak = _peak_flops()
    mfu = None
    if peak:
        try:
            mfu = step.compiled_step_flops() / (dt / STEPS) / peak
        except Exception:
            pass
    return rate, mfu


def bench_resnet50():
    import mxtpu as mx
    from mxtpu import gluon
    from mxtpu.gluon.model_zoo import vision
    from mxtpu.parallel import ShardedTrainStep, data_parallel_mesh

    batch = int(os.environ.get("BENCH_BATCH", "128"))
    dtype = os.environ.get("BENCH_DTYPE", "bfloat16")
    layout = os.environ.get("BENCH_LAYOUT", "NHWC")
    baseline = 363.69  # img/s, V100 fp32 batch 128 (docs/faq/perf.md:219)

    with mx.layout(layout):
        net = vision.resnet50_v1()
    net.initialize()
    shape = (batch, 224, 224, 3) if layout == "NHWC" else (batch, 3, 224, 224)
    x = mx.nd.array(np.random.uniform(-1, 1, size=shape), dtype="float32")
    net(x)  # settle deferred shapes
    if dtype != "float32":
        net.cast(dtype)
        x = x.astype(dtype)
    y = mx.nd.array(np.random.randint(0, 1000, size=(batch,)),
                    dtype="float32")

    loss = gluon.loss.SoftmaxCrossEntropyLoss()
    step = ShardedTrainStep(net, loss, data_parallel_mesh(), optimizer="sgd",
                            optimizer_params={"learning_rate": 0.01,
                                              "momentum": 0.9})
    rate, mfu = _run(step, (x, y), batch)
    return {
        "metric": "resnet50_train_throughput_b%d_%s_%s"
                  % (batch, dtype, layout.lower()),
        "value": round(rate, 2),
        "unit": "images/sec",
        "vs_baseline": round(rate / baseline, 3),
        "mfu": round(mfu, 4) if mfu else None,
    }


def bench_lstm_ptb():
    """Reference example/gluon/word_language_model defaults: 2-layer
    650-unit LSTM, bptt 35, PTB vocab 33278."""
    import mxtpu as mx
    from mxtpu import gluon
    from mxtpu.gluon import nn, rnn
    from mxtpu.gluon.block import HybridBlock
    from mxtpu.parallel import ShardedTrainStep, data_parallel_mesh

    batch = int(os.environ.get("BENCH_BATCH", "128"))
    dtype = os.environ.get("BENCH_DTYPE", "bfloat16")
    bptt, vocab, nhid, nlayers = 35, 33278, 650, 2

    class RNNModel(HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.embed = nn.Embedding(vocab, nhid)
                self.lstm = rnn.LSTM(nhid, num_layers=nlayers, layout="NTC")
                self.decoder = nn.Dense(vocab, flatten=False)

        def hybrid_forward(self, F, tokens):
            return self.decoder(self.lstm(self.embed(tokens)))

    net = RNNModel()
    net.initialize()
    tokens = mx.nd.array(np.random.randint(0, vocab, (batch, bptt)),
                         dtype="int32")
    labels = mx.nd.array(np.random.randint(0, vocab, (batch, bptt)),
                         dtype="float32")
    net(tokens)
    if dtype != "float32":
        net.cast(dtype)

    loss_blk = gluon.loss.SoftmaxCrossEntropyLoss()

    def forward(block, tokens, labels):
        logits = block(tokens)
        return loss_blk(logits.reshape((-1, vocab)),
                        labels.reshape((-1,)))

    step = ShardedTrainStep(net, None, data_parallel_mesh(), optimizer="sgd",
                            optimizer_params={"learning_rate": 1.0},
                            forward=forward)
    rate, mfu = _run(step, (tokens, labels), batch * bptt)
    # the reference never published a PTB throughput (BASELINE.md: the
    # config is named but unmeasured) — vs_baseline reports progress toward
    # the BASELINE.json >=50%-MFU north star instead
    return {
        "metric": "lstm_ptb_train_throughput_b%d_%s" % (batch, dtype),
        "value": round(rate, 2),
        "unit": "tokens/sec",
        "vs_baseline": round((mfu or 0) / 0.5, 3),
        "mfu": round(mfu, 4) if mfu else None,
    }


def bench_bert_base():
    """BERT-base-shaped masked-LM pretraining: bidirectional 12L/768d/12H
    encoder, seq 512, flash-attention Pallas kernel on TPU."""
    import mxtpu as mx
    from mxtpu import gluon
    from mxtpu.gluon.model_zoo.transformer import TransformerLM
    from mxtpu.parallel import ShardedTrainStep, data_parallel_mesh

    batch = int(os.environ.get("BENCH_BATCH", "16"))
    seq = int(os.environ.get("BENCH_SEQ", "512"))
    dtype = os.environ.get("BENCH_DTYPE", "bfloat16")
    vocab = 30522  # bert-base-uncased

    net = TransformerLM(vocab_size=vocab, dim=768, num_heads=12,
                        num_layers=12, max_len=seq, causal=False)
    net.initialize()
    tokens = mx.nd.array(np.random.randint(0, vocab, (batch, seq)),
                         dtype="int32")
    labels = mx.nd.array(np.random.randint(0, vocab, (batch, seq)),
                         dtype="float32")
    net(tokens)
    if dtype != "float32":
        net.cast(dtype)

    loss_blk = gluon.loss.SoftmaxCrossEntropyLoss()

    def forward(block, tokens, labels):
        logits = block(tokens)
        return loss_blk(logits.reshape((-1, vocab)),
                        labels.reshape((-1,)))

    step = ShardedTrainStep(net, None, data_parallel_mesh(),
                            optimizer="adam",
                            optimizer_params={"learning_rate": 1e-4},
                            forward=forward)
    rate, mfu = _run(step, (tokens, labels), batch * seq)
    return {
        "metric": "bert_base_pretrain_throughput_b%d_s%d_%s"
                  % (batch, seq, dtype),
        "value": round(rate, 2),
        "unit": "tokens/sec",
        "vs_baseline": round((mfu or 0) / 0.5, 3),
        "mfu": round(mfu, 4) if mfu else None,
    }


CONFIGS = {
    "resnet50": bench_resnet50,
    "lstm_ptb": bench_lstm_ptb,
    "bert_base": bench_bert_base,
}


def main():
    name = os.environ.get("BENCH_CONFIG", "resnet50")
    if name == "all":
        for fn in CONFIGS.values():
            print(json.dumps(fn()), flush=True)
        return
    print(json.dumps(CONFIGS[name]()))


if __name__ == "__main__":
    main()
