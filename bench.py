"""Benchmark: ResNet-50 training throughput, single chip.

Reference baseline: 363.69 img/s — ResNet-50 training, batch 128, fp32 on
1x V100 (docs/faq/perf.md:219; BASELINE.md "Training, single GPU").

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
The whole train step (fwd+loss+bwd+SGD-momentum update) runs as one compiled
XLA program via mxtpu.parallel.ShardedTrainStep; bf16 compute is the TPU
design point (MXU-native), matching how the reference leans on cuDNN fp32.
"""
import json
import os
import time

import numpy as np

BATCH = int(os.environ.get("BENCH_BATCH", "128"))
DTYPE = os.environ.get("BENCH_DTYPE", "bfloat16")
STEPS = int(os.environ.get("BENCH_STEPS", "20"))
BASELINE = 363.69  # img/s, V100 fp32 batch 128


def main():
    import mxtpu as mx
    from mxtpu import gluon
    from mxtpu.gluon.model_zoo import vision
    from mxtpu.parallel import ShardedTrainStep, data_parallel_mesh

    net = vision.resnet50_v1()
    net.initialize()
    x_np = np.random.uniform(-1, 1, size=(BATCH, 3, 224, 224))
    y_np = np.random.randint(0, 1000, size=(BATCH,))
    x = mx.nd.array(x_np, dtype="float32")
    net(x)  # settle deferred shapes
    if DTYPE != "float32":
        net.cast(DTYPE)
        x = x.astype(DTYPE)
    y = mx.nd.array(y_np, dtype="float32")

    loss = gluon.loss.SoftmaxCrossEntropyLoss()
    mesh = data_parallel_mesh()
    step = ShardedTrainStep(net, loss, mesh, optimizer="sgd",
                            optimizer_params={"learning_rate": 0.01,
                                              "momentum": 0.9})

    for _ in range(3):  # warmup + compile
        step(x, y).asnumpy()
    t0 = time.perf_counter()
    for _ in range(STEPS):
        out = step(x, y)
    out.asnumpy()  # sync
    dt = time.perf_counter() - t0

    value = BATCH * STEPS / dt
    print(json.dumps({
        "metric": "resnet50_train_throughput_b%d_%s" % (BATCH, DTYPE),
        "value": round(value, 2),
        "unit": "images/sec",
        "vs_baseline": round(value / BASELINE, 3),
    }))


if __name__ == "__main__":
    main()
