"""Benchmarks for the BASELINE.json scoring configs.

Select with ``BENCH_CONFIG`` (default ``all`` — every scoring config, one
JSON line each, so the driver artifact captures all three):

* ``resnet50``  — ResNet-50 training, b128 bf16 NHWC (BENCH_LAYOUT=NCHW to
  compare layouts). Reference baseline 363.69 img/s: batch 128 fp32 on 1x
  V100 (docs/faq/perf.md:219; BASELINE.md "Training, single GPU").
* ``lstm_ptb``  — Gluon 2x650-unit LSTM PTB language model (reference
  example/gluon/word_language_model), tokens/sec.
* ``bert_base`` — BERT-base-shaped bidirectional encoder pretraining step
  (12L/768d/12H, seq 512) driving the Pallas flash-attention kernel,
  tokens/sec.

Every config prints ONE JSON line {"metric", "value", "unit", "vs_baseline",
"mfu", "hfu"} (resnet50 adds "pct_of_achievable" — per-chip fraction of the
measured 140 TFLOP/s achievable rate, the PERF.md gap statement; the
``conv_class`` config additionally emits one line per conv class x impl —
XLA vs the Pallas implicit-GEMM kernel). EVERY printed line is stamped with
the resolved ``platform`` and active ``policy_key`` so CPU-fallback or
wedge-skip artifacts are distinguishable from real TPU measurements:

* ``mfu`` — *model*-flops utilization in THE one convention used across
  BASELINE.md / PERF.md / this file (reconciled round 4): an analytic
  per-item train-step FLOP count with a multiply-add = 2 FLOPs (the
  standard MFU convention, and how XLA counts), divided by datasheet chip
  peak. ResNet-50 fwd = 4.089 GMAC/img = 8.18 GFLOP/img; train = 3x fwd =
  24.5 GFLOP/img; the >=50% north star is therefore 4,015 img/s/chip on a
  197 TFLOP/s v5e. (Rounds 1-3 reported mfu with MAC=1 against the MAC=2
  peak — a mixed convention that understated utilization 2x.)
* ``hfu`` — *hardware*-flops utilization: XLA's own executed-flop count for
  the exact compiled step (``ShardedTrainStep.compiled_step_flops``)
  against the same peak. Same FLOP convention as mfu, so hfu/mfu - 1 is
  exactly the recompute + non-model work XLA schedules.

Peak is v5e bf16 ~197 TFLOP/s; override with BENCH_PEAK_TFLOPS. The whole
train step (fwd+loss+bwd+update) runs as one compiled XLA program via
mxtpu.parallel.ShardedTrainStep; bf16 is the TPU design point (MXU-native),
matching how the reference leans on cuDNN fp32.
"""
import json
import os
import sys
import time

import numpy as np

STEPS = int(os.environ.get("BENCH_STEPS", "20"))


def _stamp(rec):
    """Stamp the resolved platform and the active lever set into a JSON
    record, in place. Every line bench.py prints carries these, so a
    wedge-skipped or CPU-fallback artifact is distinguishable from a real
    TPU measurement when BENCH_r*.json is read after the fact (and the
    lever configuration each number was taken under is self-describing)."""
    if "platform" not in rec:
        try:
            import jax
            rec["platform"] = jax.devices()[0].platform
        except Exception:  # noqa: BLE001 — a dead PJRT client still stamps
            rec["platform"] = "unknown"
    if "policy_key" not in rec:
        try:
            from mxtpu.ops.registry import policy_key
            rec["policy_key"] = list(policy_key())
        except Exception:  # noqa: BLE001
            rec["policy_key"] = None
    if "ledger" not in rec:
        # ISSUE 12: every bench line carries the run's memory trajectory
        # — executable-ledger compile totals + process-peak HBM — so a
        # BENCH round is attributable to its compile/memory cost after
        # the fact, exactly like platform/policy_key
        try:
            from mxtpu import xprof
            rec["ledger"] = xprof.summary() if xprof.enabled() else None
        except Exception:  # noqa: BLE001 — a dead PJRT client still stamps
            rec["ledger"] = None
    return rec


def _emit(rec):
    print(json.dumps(_stamp(rec)), flush=True)


def _peak_flops():
    """Chip peak FLOP/s for the MFU denominator — ``BENCH_PEAK_TFLOPS``
    override first, else the ONE shared datasheet table
    (mxtpu/perf_model.py, which bench, tools/perf_peak.py, and the
    runtime ``perf.mfu`` gauge all read — the convention can no longer
    fork). None on the CPU fallback: MFU is meaningless there."""
    env = os.environ.get("BENCH_PEAK_TFLOPS")
    if env:
        return float(env) * 1e12
    from mxtpu import perf_model
    return perf_model.peak_flops()


def _run(step, batch, n_items, model_flops_per_item=None):
    """Warm up, time STEPS steps, return (items/sec, mfu, hfu).

    mfu uses the analytic per-item train FLOP count; hfu uses XLA's executed
    flops for the compiled step (see module docstring).
    """
    for _ in range(3):  # warmup + compile
        step(*batch).asnumpy()
    profile = os.environ.get("BENCH_PROFILE")
    if profile:
        # chrome-trace + jax device trace of the timed region, through the
        # framework's own profiler (mxtpu/profiler.py ~ src/profiler/
        # profiler.h) — profile_xla owns the jax start/stop_trace pair
        from mxtpu import profiler as _prof
        # capture bound: the whole timed region, not the 120 s default —
        # a truncated trace would silently misattribute the step time
        trace_max = float(os.environ.get(
            "BENCH_TRACE_MAX_S", os.environ.get("BENCH_CONFIG_TIMEOUT",
                                                "900")))
        _prof.set_config(filename=profile, profile_xla=True,
                         xla_trace_dir=os.path.dirname(profile) or ".",
                         xla_trace_max_s=trace_max)
        _prof.start()
    try:
        t0 = time.perf_counter()
        for _ in range(STEPS):
            out = step(*batch)
        out.asnumpy()  # sync
        dt = time.perf_counter() - t0
    finally:
        if profile:
            _prof.stop()
            _prof.dump()
    rate = n_items * STEPS / dt
    peak = _peak_flops()
    mfu = hfu = None
    if peak:
        # rate is GLOBAL throughput across the mesh; peak must be the whole
        # mesh's peak, not one chip's (on the driver's single real chip this
        # is a no-op). compiled_step_flops is the per-device GSPMD module,
        # so hfu stays against the single-chip peak.
        mesh = getattr(step, "_mesh", None)
        n_dev = int(mesh.devices.size) if mesh is not None else 1
        if model_flops_per_item:
            mfu = rate * model_flops_per_item / (peak * n_dev)
        try:
            hfu = step.compiled_step_flops() / (dt / STEPS) / peak
        except Exception:
            pass
    return rate, mfu, hfu


def _default_s2d(layout):
    """s2d stem DEFAULT ON for NHWC as of round 5 (exactly-equivalent
    transform; measured positive in two on-chip sessions and part of the
    best-known config, resnet_best 2580.3 img/s). BENCH_S2D_STEM=0
    disables for A/Bs; the transform requires NHWC, so other layouts
    default off."""
    return os.environ.get("BENCH_S2D_STEM",
                          "1" if layout == "NHWC" else "0")


def bench_resnet50():
    import mxtpu as mx
    from mxtpu import gluon
    from mxtpu.gluon.model_zoo import vision
    from mxtpu.parallel import ShardedTrainStep, data_parallel_mesh

    batch = int(os.environ.get("BENCH_BATCH", "128"))
    dtype = os.environ.get("BENCH_DTYPE", "bfloat16")
    layout = os.environ.get("BENCH_LAYOUT", "NHWC")
    baseline = 363.69  # img/s, V100 fp32 batch 128 (docs/faq/perf.md:219)

    with mx.layout(layout):
        net = vision.resnet50_v1()
    net.initialize()
    shape = (batch, 224, 224, 3) if layout == "NHWC" else (batch, 3, 224, 224)
    x = mx.nd.array(np.random.uniform(-1, 1, size=shape), dtype="float32")
    net(x)  # settle deferred shapes
    s2d_flag = _default_s2d(layout)
    if s2d_flag not in ("0", "1", "2"):
        # a typo must not silently measure the plain stem under an s2d
        # label on intermittently-healthy hardware
        raise RuntimeError("BENCH_S2D_STEM=%r: valid values are 0 (plain "
                           "stem), 1 (s2d), 2 (double-s2d)" % s2d_flag)
    if s2d_flag in ("1", "2") and layout != "NHWC":
        raise RuntimeError("BENCH_S2D_STEM requires BENCH_LAYOUT=NHWC "
                           "(refusing to report a plain-stem number as s2d)")
    if layout == "NHWC":
        # MLPerf space-to-depth stem, exactly equivalent, as a POLICY
        # lever (round 7): the wrap is unconditional and mode None defers
        # the variant choice to MXTPU_S2D_STEM at trace time (0 = the
        # plain stem, so the wrap is free). The env rides
        # registry.policy_key, so it recompiles per run and composes with
        # the Pallas conv gate in one jit cache key. mode 1 = 4x4 conv on
        # 12 channels; mode 2 = double s2d -> MXU-shaped 3x3 conv on
        # 48->256 channels + depth-to-space (contrib/s2d_stem.py)
        from mxtpu.contrib import s2d_stem
        s2d_stem.apply_to_resnet(net)
    saved_s2d = os.environ.get("MXTPU_S2D_STEM")
    os.environ["MXTPU_S2D_STEM"] = s2d_flag if layout == "NHWC" else "0"
    try:
        if dtype != "float32":
            net.cast(dtype)
            x = x.astype(dtype)
        y = mx.nd.array(np.random.randint(0, 1000, size=(batch,)),
                        dtype="float32")

        loss = gluon.loss.SoftmaxCrossEntropyLoss()
        step = ShardedTrainStep(net, loss, data_parallel_mesh(),
                                optimizer="sgd",
                                optimizer_params={"learning_rate": 0.01,
                                                  "momentum": 0.9})
        # ResNet-50 @224: 4.089 GMAC/img forward = 8.18 GFLOP (MAC=2),
        # train = 3x fwd = 24.5 GFLOP/img (the module-docstring
        # north-star arithmetic)
        rate, mfu, hfu = _run(step, (x, y), batch,
                              model_flops_per_item=3 * 2 * 4.089e9)
        # capture the lever set the measurement actually ran under — the
        # env restore below would otherwise let _stamp record the ambient
        # (s2d-less) policy onto this line
        from mxtpu.ops.registry import policy_key
        active_policy = list(policy_key())
    finally:
        if saved_s2d is None:
            os.environ.pop("MXTPU_S2D_STEM", None)
        else:
            os.environ["MXTPU_S2D_STEM"] = saved_s2d
    rec = {
        "metric": "resnet50_train_throughput_b%d_%s_%s"
                  % (batch, dtype, layout.lower()),
        "value": round(rate, 2),
        "unit": "images/sec",
        "vs_baseline": round(rate / baseline, 3),
        "mfu": round(mfu, 4) if mfu else None,
        "hfu": round(hfu, 4) if hfu else None,
        "policy_key": active_policy,
    }
    if mfu:
        # the gap statement PERF.md tracks: fraction of the chip's MEASURED
        # achievable rate (140 TFLOP/s ideal matmul, tools/perf_peak.py).
        # Derived from mfu, which already divides by peak * n_dev, so this
        # stays a PER-CHIP fraction on a multi-chip mesh.
        rec["pct_of_achievable"] = round(mfu * _peak_flops() / 140e12, 4)
    return rec


def bench_lstm_ptb():
    """Reference example/gluon/word_language_model defaults: 2-layer
    650-unit LSTM, bptt 35, PTB vocab 33278."""
    import mxtpu as mx
    from mxtpu import gluon
    from mxtpu.gluon import nn, rnn
    from mxtpu.gluon.block import HybridBlock
    from mxtpu.parallel import ShardedTrainStep, data_parallel_mesh

    batch = int(os.environ.get("BENCH_BATCH", "128"))
    dtype = os.environ.get("BENCH_DTYPE", "bfloat16")
    bptt, vocab, nhid, nlayers = 35, 33278, 650, 2

    class RNNModel(HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.embed = nn.Embedding(vocab, nhid)
                self.lstm = rnn.LSTM(nhid, num_layers=nlayers, layout="NTC")
                self.decoder = nn.Dense(vocab, flatten=False)

        def hybrid_forward(self, F, tokens):
            return self.decoder(self.lstm(self.embed(tokens)))

    net = RNNModel()
    net.initialize()
    tokens = mx.nd.array(np.random.randint(0, vocab, (batch, bptt)),
                         dtype="int32")
    labels = mx.nd.array(np.random.randint(0, vocab, (batch, bptt)),
                         dtype="float32")
    net(tokens)
    if dtype != "float32":
        net.cast(dtype)

    loss_blk = gluon.loss.SoftmaxCrossEntropyLoss()

    def forward(block, tokens, labels):
        logits = block(tokens)
        return loss_blk(logits.reshape((-1, vocab)),
                        labels.reshape((-1,)))

    step = ShardedTrainStep(net, None, data_parallel_mesh(), optimizer="sgd",
                            optimizer_params={"learning_rate": 1.0},
                            forward=forward)
    # per-token forward MACs: 4 gates x (in+hid) x hid per LSTM layer, plus
    # the vocab-sized decoder projection; x2 FLOPs/MAC, train = 3x forward
    fwd = 2 * (4 * (nhid + nhid) * nhid * nlayers + nhid * vocab)
    rate, mfu, hfu = _run(step, (tokens, labels), batch * bptt,
                          model_flops_per_item=3 * fwd)
    # the reference never published a PTB throughput (BASELINE.md: the
    # config is named but unmeasured) — vs_baseline reports progress toward
    # the BASELINE.json >=50%-MFU north star instead
    return {
        "metric": "lstm_ptb_train_throughput_b%d_%s" % (batch, dtype),
        "value": round(rate, 2),
        "unit": "tokens/sec",
        "vs_baseline": round((mfu or 0) / 0.5, 3),
        "mfu": round(mfu, 4) if mfu else None,
        "hfu": round(hfu, 4) if hfu else None,
    }


def bench_bert_base():
    """BERT-base-shaped masked-LM pretraining: bidirectional 12L/768d/12H
    encoder, seq 512, flash-attention Pallas kernel on TPU."""
    import mxtpu as mx
    from mxtpu import gluon
    from mxtpu.gluon.model_zoo.transformer import TransformerLM
    from mxtpu.parallel import ShardedTrainStep, data_parallel_mesh

    batch = int(os.environ.get("BENCH_BATCH", "16"))
    seq = int(os.environ.get("BENCH_SEQ", "512"))
    dtype = os.environ.get("BENCH_DTYPE", "bfloat16")
    vocab = 30522  # bert-base-uncased

    net = TransformerLM(vocab_size=vocab, dim=768, num_heads=12,
                        num_layers=12, max_len=seq, causal=False)
    net.initialize()
    tokens = mx.nd.array(np.random.randint(0, vocab, (batch, seq)),
                         dtype="int32")
    labels = mx.nd.array(np.random.randint(0, vocab, (batch, seq)),
                         dtype="float32")
    net(tokens)
    if dtype != "float32":
        net.cast(dtype)

    loss_blk = gluon.loss.SoftmaxCrossEntropyLoss()

    def forward(block, tokens, labels):
        logits = block(tokens)
        return loss_blk(logits.reshape((-1, vocab)),
                        labels.reshape((-1,)))

    step = ShardedTrainStep(net, None, data_parallel_mesh(),
                            optimizer="adam",
                            optimizer_params={"learning_rate": 1e-4},
                            forward=forward)
    # per-token forward MACs: 12 d^2 per layer (QKVO 4d^2 + MLP 8d^2) +
    # 2 s d attention (QK^T + AV) per layer + vocab head; x2 FLOPs/MAC,
    # train = 3x forward
    dim, layers = 768, 12
    fwd = 2 * (layers * (12 * dim * dim + 2 * seq * dim) + dim * vocab)
    rate, mfu, hfu = _run(step, (tokens, labels), batch * seq,
                          model_flops_per_item=3 * fwd)
    return {
        "metric": "bert_base_pretrain_throughput_b%d_s%d_%s"
                  % (batch, seq, dtype),
        "value": round(rate, 2),
        "unit": "tokens/sec",
        "vs_baseline": round((mfu or 0) / 0.5, 3),
        "mfu": round(mfu, 4) if mfu else None,
        "hfu": round(hfu, 4) if hfu else None,
    }


def bench_eager():
    """Eager-dispatch overhead guard (VERDICT r2 weak #5): ops/sec through
    the full imperative path (mx.nd wrapper -> _apply -> jax eager) on a
    small tensor, the mode every reference BASELINE table was measured in.
    Each iteration is 3 chained elementwise ops; sync only at the end
    (SURVEY §1 async-dispatch semantics)."""
    import mxtpu as mx

    n_iter = int(os.environ.get("BENCH_EAGER_ITERS", "200"))
    x = mx.nd.ones((128, 128))
    y = (x * 1.01 + 0.5).tanh()
    y.asnumpy()  # warm every kernel
    t0 = time.perf_counter()
    for _ in range(n_iter):
        y = (y * 1.01 + 0.5).tanh()
    y.asnumpy()
    dt = time.perf_counter() - t0
    rate = 3 * n_iter / dt
    # floor: the reference's eager NDArray path sustains O(10k) small ops/s
    # on CPU hosts (engine dispatch ~100us/op); below 3k ops/s eager mode
    # has regressed into per-call retracing
    return {
        "metric": "eager_dispatch_small_ops",
        "value": round(rate, 1),
        "unit": "ops/sec",
        "vs_baseline": round(rate / 3000.0, 3),
        "mfu": None,
        "hfu": None,
    }


def bench_optimizer_step():
    """Weight-update hot path: params-updated/s through Trainer.step, eager
    per-param loop vs the fused whole-model donated jit
    (mxtpu/optimizer_fused.py, MXTPU_FUSED_OPTIMIZER). The fused number is
    the headline value; ``vs_baseline`` is the fused/eager speedup — the
    dispatch-amortization win this metric exists to track."""
    import mxtpu as mx
    from mxtpu.gluon.parameter import Parameter
    from mxtpu.gluon.trainer import Trainer
    from mxtpu import optimizer_fused as of

    n_params = int(os.environ.get("BENCH_OPT_PARAMS", "80"))
    size = int(os.environ.get("BENCH_OPT_PARAM_SIZE", "16384"))
    steps = int(os.environ.get("BENCH_OPT_STEPS", "30"))
    optimizer = os.environ.get("BENCH_OPT_OPTIMIZER", "adam")
    rng = np.random.RandomState(0)

    def measure(fused):
        os.environ["MXTPU_FUSED_OPTIMIZER"] = "1" if fused else "0"
        params = []
        for j in range(n_params):
            p = Parameter("bench_p%d" % j, shape=(size,), dtype="float32")
            p.initialize()
            p.grad()[:] = mx.nd.array(
                rng.randn(size).astype(np.float32))
            params.append(p)
        tr = Trainer(params, optimizer, {"learning_rate": 1e-3},
                     kvstore=None)
        import jax

        def sync():  # EVERY param: the eager path is n_params independent
            jax.block_until_ready([p.data()._data for p in params])

        tr.step(1)  # warmup + compile
        sync()
        t0 = time.perf_counter()
        for _ in range(steps):
            tr.step(1)
        sync()  # async dispatches; syncing one would overstate its rate
        return n_params * steps / (time.perf_counter() - t0)

    prev = os.environ.get("MXTPU_FUSED_OPTIMIZER")
    try:
        eager_rate = measure(fused=False)
        of.reset()
        fused_rate = measure(fused=True)
        fused_calls = of.FUSED_STATS["fused_steps"]
    finally:
        if prev is None:
            os.environ.pop("MXTPU_FUSED_OPTIMIZER", None)
        else:
            os.environ["MXTPU_FUSED_OPTIMIZER"] = prev
    return {
        "metric": "optimizer_step_%s_p%d_n%d" % (optimizer, n_params, size),
        "value": round(fused_rate, 1),
        "unit": "params_updated/sec",
        "vs_baseline": round(fused_rate / eager_rate, 3),  # fused speedup
        "mfu": None,
        "hfu": None,
        "eager_params_per_s": round(eager_rate, 1),
        "fused_params_per_s": round(fused_rate, 1),
        "fused_jit_calls": fused_calls,  # == 1 + steps when fully fused
    }


def _overhead_workloads():
    """ONE copy of the workload builders the overhead benches
    (``guard_overhead``, ``telemetry_overhead``, ``integrity_overhead``)
    measure — the same optimizer-step and small-resnet shapes, read from
    the shared ``BENCH_GUARD_*`` env knobs. Returns ``{name: make}``
    where ``make(scaler=None) -> (step_fn, sync, trainer)``; attaching a
    DynamicLossScaler builds the guarded variant, and the trainer rides
    along so integrity_overhead can bracket it with the step-wedge
    watchdog + health monitor."""
    import jax

    import mxtpu as mx
    from mxtpu import autograd, gluon
    from mxtpu.gluon.parameter import Parameter
    from mxtpu.gluon.trainer import Trainer

    n_params = int(os.environ.get("BENCH_GUARD_PARAMS", "80"))
    size = int(os.environ.get("BENCH_GUARD_PARAM_SIZE", "16384"))
    batch = int(os.environ.get("BENCH_GUARD_BATCH", "8"))
    img = int(os.environ.get("BENCH_GUARD_IMG", "64"))
    rng = np.random.RandomState(0)

    def make_opt_step(scaler=None):
        params = []
        for j in range(n_params):
            p = Parameter("ovh_p%d" % j, shape=(size,), dtype="float32")
            p.initialize()
            p.grad()[:] = mx.nd.array(rng.randn(size).astype(np.float32))
            params.append(p)
        tr = Trainer(params, "adam", {"learning_rate": 1e-3}, kvstore=None,
                     loss_scaler=scaler)

        def sync():
            jax.block_until_ready([p.data()._data for p in params])

        return (lambda: tr.step(1)), sync, tr

    def make_resnet(scaler=None):
        from mxtpu.gluon.model_zoo import vision
        net = vision.resnet18_v1()
        net.initialize()
        x = mx.nd.array(rng.uniform(-1, 1, (batch, 3, img, img))
                        .astype(np.float32))
        y = mx.nd.array(rng.randint(0, 10, (batch,)).astype(np.float32))
        net(x)  # settle deferred shapes
        net.hybridize()
        loss = gluon.loss.SoftmaxCrossEntropyLoss()
        tr = Trainer(net.collect_params(), "sgd",
                     {"learning_rate": 0.01, "momentum": 0.9}, kvstore=None,
                     loss_scaler=scaler)
        params = list(net.collect_params().values())

        def one():
            with autograd.record():
                l = loss(net(x), y)
                if scaler is not None:
                    l = scaler.scale(l)
            l.backward()
            tr.step(batch)

        def sync():
            jax.block_until_ready([p.data()._data for p in params])

        return one, sync, tr

    return {"optimizer_step": make_opt_step, "resnet": make_resnet}


def _time_steps(step_fn, sync, n):
    """The overhead benches' shared timing loop: warmup+compile, then n
    async dispatches closed by one host-fetch sync."""
    step_fn()
    sync()
    t0 = time.perf_counter()
    for _ in range(n):
        step_fn()
    sync()
    return n / (time.perf_counter() - t0)


def bench_guard_overhead(emit=None):
    """Numerics-sentinel + dynamic-loss-scaler cost (mxtpu/resilience.py):
    steps/s with the guard ON (DynamicLossScaler attached — in-jit finite
    flag, grad norm, skip-select, scaler update) vs OFF, for the
    ``optimizer_step`` hot path and a small-resnet Trainer step. One JSON
    line per (config, guard) plus a summary whose value is the worst
    overhead fraction — the <2% acceptance bound (ISSUE 3) is read off
    this artifact on the TPU tier. BENCH_GUARD_CONFIGS selects subsets."""
    from mxtpu import resilience

    if emit is None:
        emit = _emit
    which = [c.strip() for c in os.environ.get(
        "BENCH_GUARD_CONFIGS", "optimizer_step,resnet").split(",") if c]
    steps = int(os.environ.get("BENCH_GUARD_STEPS", "30"))
    makers = _overhead_workloads()
    bad = [c for c in which if c not in makers]
    if bad or not which:
        # fail BEFORE burning measurement time, naming the offending value
        raise RuntimeError(
            "BENCH_GUARD_CONFIGS=%r: expected a non-empty comma list from %s"
            % (os.environ.get("BENCH_GUARD_CONFIGS"), sorted(makers)))
    overheads = {}
    for cname in which:
        off_rate = _time_steps(*makers[cname](None)[:2], steps)
        on_rate = _time_steps(
            *makers[cname](resilience.DynamicLossScaler())[:2], steps)
        overheads[cname] = off_rate / on_rate - 1.0
        emit({"metric": "guard_overhead_%s" % cname, "guard": "off",
              "value": round(off_rate, 2), "unit": "steps/sec"})
        emit({"metric": "guard_overhead_%s" % cname, "guard": "on",
              "value": round(on_rate, 2), "unit": "steps/sec",
              "overhead_frac": round(overheads[cname], 4)})
    worst = max(overheads.values())
    return {
        "metric": "guard_overhead",
        "value": round(worst, 4),
        "unit": "overhead_frac",
        # >=1.0 means the sentinel fits the 2% budget on this platform
        "vs_baseline": round(0.02 / max(worst, 1e-9), 3),
        "mfu": None,
        "hfu": None,
        "per_config": {k: round(v, 4) for k, v in overheads.items()},
    }


def bench_telemetry_overhead(emit=None):
    """Telemetry layer cost (mxtpu/telemetry.py): steps/s with
    MXTPU_TELEMETRY=1 (step-phase spans + event ring + watchdog counter
    reads) vs 0, for the ``optimizer_step`` hot path and a small-resnet
    Trainer loop — the same shapes guard_overhead measures. ISSUE 10
    adds a third mode, ``trace`` (MXTPU_TELEMETRY=1 + MXTPU_TRACE=1):
    per-step trace contexts, span-id allocation, and the flight-recorder
    ring append, held to the SAME <1% budget. ISSUE 12 adds a fourth,
    ``xprof`` (all three levers on): the executable-observatory layer's
    lever-gated per-step work — the wrapped-jit per-dispatch lever check
    + call count and the Trainer's perf.mfu meter tick — same <1% budget
    again. (The wrapper FRAME is construction-time and rides every mode;
    what alternates is everything behind the per-call lever.) ISSUE 19
    adds a fifth, ``fleet_obs`` (all levers on + a HostObsPublisher's
    per-step ``maybe_publish`` cadence check against a throwaway board
    dir): the plane's HOT-PATH cost is one clock read per step; the blob
    write itself runs at cadence (seconds), so it is timed separately
    (``publish_ms``) and folded in amortized at a 1 s reference cadence
    — hot-path + publish_s/1s held to the SAME <1% budget. (Folding the
    raw write into a µs-scale alternating loop would measure one file
    write against a handful of microsecond steps — cadence amortization
    IS the design.) One JSON
    line per (config, mode) plus a summary whose value is the worst
    overhead fraction across modes (``vs_baseline`` = 0.01 / worst, so
    >=1.0 means the layer fits). BENCH_TELEMETRY_CONFIGS selects
    subsets.

    Methodology: ONE workload per config, then off/on/trace timings
    ALTERNATE over BENCH_TELEMETRY_ROUNDS rounds and each mode takes its
    MEDIAN rate — a single off-then-on pair measures host frequency/cache
    warmup drift instead of the ~8 us/step the three spans actually cost
    (measured: the span path is ~2.7 us each, the trace layer adds
    ~1 us/span on a CPU host; per-rep spread on a shared CPU host is
    +-10%, so the summary also carries ``noise_frac`` and the <1% budget
    is judged on the low-variance TPU tier)."""
    if emit is None:
        emit = _emit
    which = [c.strip() for c in os.environ.get(
        "BENCH_TELEMETRY_CONFIGS", "optimizer_step,resnet").split(",") if c]
    steps = int(os.environ.get("BENCH_GUARD_STEPS", "30"))
    rounds = int(os.environ.get("BENCH_TELEMETRY_ROUNDS", "3"))
    makers = _overhead_workloads()
    bad = [c for c in which if c not in makers]
    if bad or not which:
        raise RuntimeError(
            "BENCH_TELEMETRY_CONFIGS=%r: expected a non-empty comma list "
            "from %s"
            % (os.environ.get("BENCH_TELEMETRY_CONFIGS"), sorted(makers)))
    # mode -> (MXTPU_TELEMETRY, MXTPU_TRACE, MXTPU_XPROF, publisher?);
    # each lever pins the previous ones so the costs stay separately
    # attributable; fleet_obs rides all levers + the cadenced blob writer
    modes = {"0": ("0", "0", "0", False), "1": ("1", "0", "0", False),
             "trace": ("1", "1", "0", False),
             "xprof": ("1", "1", "1", False),
             "fleet_obs": ("1", "1", "1", True)}
    prev = os.environ.get("MXTPU_TELEMETRY")
    prev_trace = os.environ.get("MXTPU_TRACE")
    prev_xprof = os.environ.get("MXTPU_XPROF")
    import shutil
    import tempfile

    from mxtpu import fleet_obs as _fleet_obs
    obs_dir = tempfile.mkdtemp(prefix="mxtpu-bench-obs-")
    # cadence pinned beyond the measured window: the alternating loop
    # times the per-step cadence CHECK; the write is timed separately
    publisher = _fleet_obs.HostObsPublisher(obs_dir, 0, interval_s=1e9)
    obs_ref_cadence_s = 1.0
    overheads = {}
    trace_overheads = {}
    xprof_overheads = {}
    fleet_obs_overheads = {}
    noise = {}
    try:
        for cname in which:
            step_fn, sync = makers[cname](None)[:2]
            step_fn()  # warmup + compile (shared: one workload, all modes)
            sync()
            rates = {m: [] for m in modes}
            for _ in range(rounds):
                for mode, (tel, trace, xpr, pub) in modes.items():
                    os.environ["MXTPU_TELEMETRY"] = tel
                    os.environ["MXTPU_TRACE"] = trace
                    os.environ["MXTPU_XPROF"] = xpr
                    pub_local = publisher if pub else None
                    t0 = time.perf_counter()
                    for _ in range(steps):
                        step_fn()
                        if pub_local is not None:
                            pub_local.maybe_publish()
                    sync()
                    rates[mode].append(steps / (time.perf_counter() - t0))
            med = {m: float(np.median(rs)) for m, rs in rates.items()}
            for mode in modes:
                emit({"metric": "telemetry_overhead_%s" % cname,
                      "telemetry": {"0": "off", "1": "on",
                                    "trace": "trace",
                                    "xprof": "xprof",
                                    "fleet_obs": "fleet_obs"}[mode],
                      "value": round(med[mode], 2), "unit": "steps/sec",
                      "rounds": [round(r, 2) for r in rates[mode]]})
            overheads[cname] = med["0"] / med["1"] - 1.0
            trace_overheads[cname] = med["0"] / med["trace"] - 1.0
            xprof_overheads[cname] = med["0"] / med["xprof"] - 1.0
            # the blob write, timed on the registry this config just
            # loaded, amortized at the reference cadence
            t0 = time.perf_counter()
            publisher.publish()
            publish_s = time.perf_counter() - t0
            fleet_obs_overheads[cname] = (
                med["0"] / med["fleet_obs"] - 1.0
                + publish_s / obs_ref_cadence_s)
            all_r = [r for rs in rates.values() for r in rs]
            noise[cname] = (max(all_r) - min(all_r)) / med["0"]
            emit({"metric": "telemetry_overhead_%s" % cname,
                  "overhead_frac": round(overheads[cname], 4),
                  "trace_overhead_frac": round(trace_overheads[cname], 4),
                  "xprof_overhead_frac": round(xprof_overheads[cname], 4),
                  "fleet_obs_overhead_frac":
                  round(fleet_obs_overheads[cname], 4),
                  "publish_ms": round(publish_s * 1e3, 3),
                  "noise_frac": round(noise[cname], 4)})
    finally:
        shutil.rmtree(obs_dir, ignore_errors=True)
        for var, old in (("MXTPU_TELEMETRY", prev),
                         ("MXTPU_TRACE", prev_trace),
                         ("MXTPU_XPROF", prev_xprof)):
            if old is None:
                os.environ.pop(var, None)
            else:
                os.environ[var] = old
    worst = max(list(overheads.values()) + list(trace_overheads.values())
                + list(xprof_overheads.values())
                + list(fleet_obs_overheads.values()))
    return {
        "metric": "telemetry_overhead",
        "value": round(worst, 4),
        "unit": "overhead_frac",
        # >=1.0 means the layer fits the 1% budget on this platform
        # (floor at 1e-4 caps the ratio when overhead is below the
        # measurement noise floor, incl. the "on measured faster" case)
        "vs_baseline": round(0.01 / max(worst, 1e-4), 3),
        "mfu": None,
        "hfu": None,
        "per_config": {k: round(v, 4) for k, v in overheads.items()},
        "per_config_trace": {k: round(v, 4)
                             for k, v in trace_overheads.items()},
        "per_config_xprof": {k: round(v, 4)
                             for k, v in xprof_overheads.items()},
        "per_config_fleet_obs": {k: round(v, 4)
                                 for k, v in fleet_obs_overheads.items()},
        "noise_frac": {k: round(v, 4) for k, v in noise.items()},
    }


def bench_integrity_overhead(emit=None):
    """Training-survivability stack cost (ISSUE 14): steps/s with the
    FULL integrity stack ON — numerics sentinel + loss scaler, the
    divergence fingerprint compiled into the donated update jit with
    host compares at cadence, the step-wedge watchdog bracket (arm /
    disarm + its off-thread monitor), and the TrainingHealthMonitor
    ``after_step`` — vs the bare loop, on the same optimizer-step and
    small-resnet shapes the other overhead benches use
    (``BENCH_INTEGRITY_CONFIGS``). OFF and ON timing rounds ALTERNATE
    (the telemetry_overhead methodology: a single off-then-on pair
    measures host drift, not the stack) over ``BENCH_INTEGRITY_ROUNDS``
    with the median per mode; each mode's workload is built AND
    dispatched under its own ``MXTPU_DIVERGENCE_EVERY``, so both sets of
    executables stay cached and steady-state compiles are flat — gated.

    serve_bench-style gate summary: ``overhead_budget`` (worst
    overhead_frac < 2%, the guard_overhead budget — judged on-chip; on a
    noisy CPU host it is reported but does not fail ``ok``),
    ``retrace_flat`` (zero compiles during the timed rounds),
    ``divergence_checks`` (the sentinel really compared), ``no_wedges``
    (the watchdog never tripped). ``vs_baseline`` >= 1.0 means the stack
    fits the budget on this platform."""
    import jax

    from mxtpu import optimizer_fused as of
    from mxtpu import resilience, telemetry
    from mxtpu.monitor import TrainingHealthMonitor

    if emit is None:
        emit = _emit
    which = [c.strip() for c in os.environ.get(
        "BENCH_INTEGRITY_CONFIGS", "optimizer_step,resnet").split(",")
        if c]
    steps = int(os.environ.get("BENCH_GUARD_STEPS", "30"))
    rounds = int(os.environ.get("BENCH_INTEGRITY_ROUNDS", "3"))
    every = 8  # divergence-compare cadence inside the ON mode
    makers = _overhead_workloads()
    bad = [c for c in which if c not in makers]
    if bad or not which:
        raise RuntimeError(
            "BENCH_INTEGRITY_CONFIGS=%r: expected a non-empty comma list "
            "from %s"
            % (os.environ.get("BENCH_INTEGRITY_CONFIGS"), sorted(makers)))
    prev_div = os.environ.get("MXTPU_DIVERGENCE_EVERY")

    def _set_div(on):
        if on:
            os.environ["MXTPU_DIVERGENCE_EVERY"] = str(every)
        else:
            os.environ.pop("MXTPU_DIVERGENCE_EVERY", None)

    overheads, noise = {}, {}
    wedges_before = telemetry.snapshot()["counters"].get("train.wedges", 0)
    checks_ran = 0
    compiles_moved = False
    watchdogs = []
    try:
        for cname in which:
            # one workload per mode, each traced under ITS policy env
            _set_div(False)
            off_fn, off_sync = makers[cname](None)[:2]
            _set_div(True)
            on_fn, on_sync, tr = makers[cname](
                resilience.DynamicLossScaler())
            wd = resilience.TrainStepWatchdog(
                timeout_x=50.0, min_timeout_s=5.0).start_monitor(0.05)
            watchdogs.append(wd)
            tr.attach_step_watchdog(wd)
            mon = TrainingHealthMonitor(
                interval=every, divergence_every=every,
                poison_streak=0).install(tr)

            def on_step(fn=on_fn, m=mon):
                fn()
                m.after_step()

            # warm both (compile under their own env), then pin compiles
            on_step()
            on_sync()
            _set_div(False)
            off_fn()
            off_sync()
            c0 = of.FUSED_STATS["compiles"]
            rates = {"off": [], "on": []}
            for _ in range(rounds):
                for mode in ("off", "on"):
                    _set_div(mode == "on")
                    fn = off_fn if mode == "off" else on_step
                    sync = off_sync if mode == "off" else on_sync
                    t0 = time.perf_counter()
                    for _ in range(steps):
                        fn()
                    sync()
                    rates[mode].append(
                        steps / (time.perf_counter() - t0))
            compiles_moved |= of.FUSED_STATS["compiles"] != c0
            checks_ran += mon._sentinel.checks
            med = {m: float(np.median(rs)) for m, rs in rates.items()}
            for mode in ("off", "on"):
                emit({"metric": "integrity_overhead_%s" % cname,
                      "integrity": mode,
                      "value": round(med[mode], 2), "unit": "steps/sec",
                      "rounds": [round(r, 2) for r in rates[mode]]})
            overheads[cname] = med["off"] / med["on"] - 1.0
            all_r = [r for rs in rates.values() for r in rs]
            noise[cname] = (max(all_r) - min(all_r)) / med["off"]
            emit({"metric": "integrity_overhead_%s" % cname,
                  "overhead_frac": round(overheads[cname], 4),
                  "noise_frac": round(noise[cname], 4)})
    finally:
        for wd in watchdogs:
            wd.stop_monitor()
        if prev_div is None:
            os.environ.pop("MXTPU_DIVERGENCE_EVERY", None)
        else:
            os.environ["MXTPU_DIVERGENCE_EVERY"] = prev_div
    worst = max(overheads.values())
    wedges = telemetry.snapshot()["counters"].get("train.wedges", 0) \
        - wedges_before
    on_tpu = jax.default_backend() == "tpu"
    fits = worst < 0.02
    gates = {
        "overhead_budget": bool(fits),
        "retrace_flat": not compiles_moved,
        "divergence_checks": checks_ran > 0,
        "no_wedges": wedges == 0,
    }
    # the <2% budget is judged where it matters (the low-variance TPU
    # tier, the guard_overhead precedent); host-tier noise reports the
    # number without failing the gate verdict
    ok = gates["retrace_flat"] and gates["divergence_checks"] \
        and gates["no_wedges"] and (fits or not on_tpu)
    return {
        "metric": "integrity_overhead",
        "value": round(worst, 4),
        "unit": "overhead_frac",
        # >=1.0 means the full survivability stack fits the 2% budget
        "vs_baseline": round(0.02 / max(worst, 1e-4), 3)
        if ok else 0.0,
        "mfu": None,
        "hfu": None,
        "per_config": {k: round(v, 4) for k, v in overheads.items()},
        "noise_frac": {k: round(v, 4) for k, v in noise.items()},
        "divergence_checks": checks_ran,
        "train_wedges": int(wedges),
        "gates": gates,
        "ok": bool(ok),
    }


def _perf_common():
    """The shared scan-fused timing harness (tools/perf_common.py —
    ONE copy of the PERF.md methodology: K steps per dispatch,
    host-fetch sync). Imported lazily so bench stays runnable from any
    cwd."""
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "tools"))
    import perf_common
    return perf_common


def _platform_name():
    try:
        import jax
        return jax.devices()[0].platform
    except Exception:  # noqa: BLE001 — a dead PJRT client still answers
        return "unknown"


def _tune_verdict(tune_rows, key):
    """Fold per-class A/B rows into a summary verdict: None when the A/B
    didn't run (BENCH_AUTOTUNE=0 or every search errored), else
    any(improved) / all(not_worse)."""
    rows = [r for r in tune_rows if r and "error" not in r]
    if not rows:
        return None
    if key == "improved":
        return any(r.get("improved") for r in rows)
    return all(r.get(key) for r in rows)


def _autotune_ab(emit, ptune, kernel_id, metric, sc, host_tier,
                 host_scale=None):
    """One autotuned-vs-default A/B line for a bench class: a bounded
    measured search (install=False — the bench must not mutate the
    serving table) whose default candidate is always timed first by the
    same warmup-discarded median-of-rounds harness, so default_s/best_s
    is a like-for-like ratio. ``not_worse`` is the gate: the tuner may
    fail to beat the hand default but must never regress it (the best
    candidate can only be the default itself then). The host tier
    shrinks the problem so interpret-mode candidates stay inside the CI
    budget — same machinery, smaller buffers."""
    sc = dict(sc)
    if host_tier and host_scale:
        sc.update(host_scale)
    try:
        res = ptune.search(kernel_id, sc, install=False, persist=False)
    except Exception as e:  # noqa: BLE001 — keep the sweep
        rec = {"metric": metric + "_autotune", "impl": "autotune_ab",
               "error": str(e)}
        emit(rec)
        return rec
    rec = {"metric": metric + "_autotune", "impl": "autotune_ab",
           "class": res["class"],
           "default_plan": res["default_plan_id"],
           "best_plan": res["best_plan_id"],
           "default_ms": round(res["default_s"] * 1e3, 3),
           "best_ms": round(res["best_s"] * 1e3, 3),
           "value": round(res["speedup_vs_default"], 4),
           "unit": "x_vs_default",
           "candidates": res["candidates"], "timed": res["timed"],
           "budget_exhausted": res["budget_exhausted"],
           "improved": res["improved"],
           # best is argmin over a set containing the default, so worse
           # only by timing noise; 5% bounds that noise
           "not_worse": res["best_s"] <= res["default_s"] * 1.05}
    emit(rec)
    return rec


def bench_conv_class(emit=None):
    """Per-conv-class TFLOP/s, XLA vs the Pallas implicit-GEMM kernel
    (mxtpu/ops/pallas/conv.py) — the kernel-level numbers that previously
    lived only in tools logs (tools/perf_session.py phase_convs), now a
    bench config so the driver artifact records them. One JSON line per
    (class, impl); classes are the PERF.md sinks: the 7x7s2 stem, a 1x1
    bottleneck pointwise, a stage-2 3x3 spatial, plus an MXU-filled 1x1
    control the gate must LEAVE on XLA. Scan-fused K-step timing with
    host-fetch sync (methodology section). Returns a summary record in
    the standard schema."""
    import jax
    import jax.numpy as jnp
    from mxtpu.ops.conv_acc import conv_fast
    from mxtpu.ops.pallas import autotune as ptune
    from mxtpu.ops.pallas import conv as pconv

    pcommon = _perf_common()
    if emit is None:
        emit = _emit
    batch = int(os.environ.get("BENCH_CONV_BATCH",
                               os.environ.get("BENCH_BATCH", "128")))
    k_steps = int(os.environ.get("BENCH_CONV_STEPS", "16"))
    dtype = (jnp.float32 if os.environ.get("BENCH_DTYPE") == "float32"
             else jnp.bfloat16)
    dn = ("NHWC", "HWIO", "NHWC")
    do_tune = os.environ.get("BENCH_AUTOTUNE", "1") == "1"
    host_tier = _platform_name() != "tpu"
    # (label, HW_in, Cin, Cout, k, stride); the last is the XLA control —
    # K=1024 and C_out=256 both fill the MXU, so Pallas must decline it
    classes = [
        ("stem_7x7s2", 224, 3, 64, 7, 2),
        ("pw_1x1_256to64", 56, 256, 64, 1, 1),
        ("spatial_3x3_64", 56, 64, 64, 3, 1),
        ("pw_1x1_1024to256_xla_control", 14, 1024, 256, 1, 1),
    ]
    lines = []
    tune_rows = []
    saved = os.environ.get("MXTPU_PALLAS_CONV")
    try:
        for label, hw, cin, cout, k, s in classes:
            x = jax.random.normal(jax.random.PRNGKey(0),
                                  (batch, hw, hw, cin), dtype)
            w = jax.random.normal(jax.random.PRNGKey(1),
                                  (k, k, cin, cout), dtype) * 0.1
            pad = [(k // 2, k // 2), (k // 2, k // 2)]
            hw_out = (hw + 2 * (k // 2) - k) // s + 1
            fl = 2 * batch * hw_out * hw_out * cin * cout * k * k
            # the autotuner's shape class for this (conv_fast routes the
            # plain conv: no scale/residual epilogue)
            sc = {"n": batch, "h": hw, "w": hw, "cin": cin, "kh": k,
                  "kw": k, "cout": cout, "sh": s, "sw": s,
                  "p0": k // 2, "p1": k // 2, "q0": k // 2, "q1": k // 2,
                  "dtype": jnp.dtype(dtype).name, "scale": 0, "res": 0}
            pid, prov = ptune.active_plan("pallas_conv", sc)
            if pid is None:  # no tuned plan: name the hand-picked default
                pid = ptune.plan_id_of(pconv._tune_default(sc))
            by_impl = {}
            for impl in ("xla", "pallas"):
                os.environ["MXTPU_PALLAS_CONV"] = \
                    "1" if impl == "pallas" else "0"
                pconv.reset_dispatch_stats()

                f = pcommon.reinject(
                    lambda xd, w=w, s=s, pad=pad: conv_fast(
                        xd, w, (s, s), pad, (1, 1), (1, 1), dn, 1))
                try:
                    dt = pcommon.timed_scan(f, x, K=k_steps)
                except Exception as e:  # noqa: BLE001 — keep the sweep
                    emit({"metric": "conv_class_%s" % label, "impl": impl,
                          "error": str(e)})
                    continue
                # dispatch routing now reads from the telemetry registry
                # (the DISPATCH_STATS dict is a thin view over it)
                from mxtpu import telemetry
                if telemetry.value("pallas_conv.pallas"):
                    used = "pallas"
                elif impl == "pallas":
                    reasons = telemetry.tagged("pallas_conv.fallback")
                    used = "xla_fallback(%s)" % "; ".join(sorted(reasons)) \
                        if reasons else "xla_gate_declined"
                else:
                    used = "xla"
                rec = {"metric": "conv_class_%s" % label, "impl": impl,
                       "impl_used": used, "ms": round(dt * 1e3, 3),
                       # 4 decimals: a CPU-fallback line must not round to
                       # a flat 0.00 (the chip numbers are 1-100 TFLOP/s)
                       "value": round(fl / dt / 1e12, 4),
                       "unit": "TFLOP/s",
                       "plan": pid, "plan_provenance": prov}
                by_impl[impl] = dt
                if impl == "pallas" and "xla" in by_impl:
                    rec["speedup_vs_xla"] = round(by_impl["xla"] / dt, 3)
                emit(rec)
                lines.append(rec)
            if do_tune and "xla_control" not in label:
                tune_rows.append(_autotune_ab(
                    emit, ptune, "pallas_conv",
                    "conv_class_%s" % label, sc, host_tier,
                    host_scale={"n": min(batch, 2), "h": min(hw, 64),
                                "w": min(hw, 64)}))
    finally:
        if saved is None:
            os.environ.pop("MXTPU_PALLAS_CONV", None)
        else:
            os.environ["MXTPU_PALLAS_CONV"] = saved
    pallas_lines = [r for r in lines if r.get("impl") == "pallas"
                    and r.get("impl_used") == "pallas"]
    return {
        "metric": "conv_class",
        "value": len(lines),
        "unit": "json_lines",
        "vs_baseline": None,
        "mfu": None,
        "hfu": None,
        "pallas_kernel_lines": len(pallas_lines),
        "classes": [r["metric"] for r in lines],
        "autotune_beats_default": _tune_verdict(tune_rows, "improved"),
        "autotune_not_worse": _tune_verdict(tune_rows, "not_worse"),
    }


def bench_flash_class(emit=None):
    """Per-attention-class TFLOP/s, XLA softmax path vs the Pallas flash
    kernel (mxtpu/ops/pallas/flash_attention.py) — conv_class's sibling
    for the transformer hot path. One JSON line per (class, impl);
    classes cover the decoder/encoder shapes plus an odd length the
    block picker must still tile (768 → 384-blocks). Off-TPU the kernel
    runs through the Pallas interpreter (MXTPU_FLASH_INTERPRET) on
    host-scaled shapes — slower absolute numbers, but the dispatch
    routing, plan stamping, and autotune A/B exercise the real kernel.
    Scan-fused K-step timing with host-fetch sync; every line carries
    the active plan id + tuned|default provenance; summary gates
    autotuned-vs-default not-worse."""
    import importlib

    import jax
    import jax.numpy as jnp
    fa = importlib.import_module("mxtpu.ops.pallas.flash_attention")
    from mxtpu.ops.pallas import autotune as ptune

    pcommon = _perf_common()
    if emit is None:
        emit = _emit
    k_steps = int(os.environ.get("BENCH_FLASH_STEPS", "8"))
    dtype = (jnp.float32 if os.environ.get("BENCH_DTYPE") == "float32"
             else jnp.bfloat16)
    do_tune = os.environ.get("BENCH_AUTOTUNE", "1") == "1"
    host_tier = _platform_name() != "tpu"
    # (label, batch, heads, T, D, host_T) — host_T keeps interpret-mode
    # lines inside the battery budget while preserving each class's
    # tiling character (odd 768 scales to odd 384, not a power of two)
    classes = [
        ("dec_t512_d64", 4, 8, 512, 64, 256),
        ("enc_t1024_d128", 2, 8, 1024, 128, 512),
        ("odd_t768_d64", 2, 8, 768, 64, 384),
    ]
    causal = os.environ.get("BENCH_FLASH_CAUSAL", "0") == "1"
    lines = []
    tune_rows = []
    saved = os.environ.get("MXTPU_FLASH_INTERPRET")
    try:
        if host_tier:
            # off-TPU the pallas impl needs the interpreter; the xla impl
            # path below bypasses the kernel either way
            os.environ["MXTPU_FLASH_INTERPRET"] = "1"
        for label, b, h, t, d, host_t in classes:
            if host_tier:
                b, h, t = 1, 2, host_t
            q = jax.random.normal(jax.random.PRNGKey(0), (b, h, t, d),
                                  dtype)
            kk = jax.random.normal(jax.random.PRNGKey(1), (b, h, t, d),
                                   dtype)
            vv = jax.random.normal(jax.random.PRNGKey(2), (b, h, t, d),
                                   dtype)
            # 2 matmuls (scores + values), 2 FLOPs each: 4*b*h*t*tk*d
            fl = 4 * b * h * t * t * d
            sc = {"b": b, "h": h, "t": t, "tk": t, "d": d,
                  "dtype": jnp.dtype(dtype).name}
            pid, prov = ptune.active_plan("pallas_flash", sc)
            if pid is None:  # no tuned plan: name the hand-picked default
                pid = ptune.plan_id_of(fa._tune_default(sc))
            by_impl = {}
            for impl in ("xla", "pallas"):
                fa.reset_dispatch_stats()
                if impl == "xla":
                    scale = 1.0 / (d ** 0.5)
                    f = pcommon.reinject(
                        lambda qd, kk=kk, vv=vv, scale=scale:
                        fa._xla_attention(qd, kk, vv, causal, scale))
                else:
                    f = pcommon.reinject(
                        lambda qd, kk=kk, vv=vv:
                        fa.flash_attention(qd, kk, vv, causal))
                try:
                    dt = pcommon.timed_scan(f, q, K=k_steps)
                except Exception as e:  # noqa: BLE001 — keep the sweep
                    emit({"metric": "flash_class_%s" % label,
                          "impl": impl, "error": str(e)})
                    continue
                from mxtpu import telemetry
                if impl == "xla":
                    used = "xla"
                elif telemetry.value("pallas_flash.pallas"):
                    used = "pallas"
                else:
                    reasons = telemetry.tagged("pallas_flash.fallback")
                    used = ("xla_fallback(%s)"
                            % "; ".join(sorted(reasons)) if reasons
                            else "xla_fallback")
                rec = {"metric": "flash_class_%s" % label, "impl": impl,
                       "impl_used": used, "ms": round(dt * 1e3, 3),
                       "value": round(fl / dt / 1e12, 4),
                       "unit": "TFLOP/s",
                       "plan": pid, "plan_provenance": prov}
                by_impl[impl] = dt
                if impl == "pallas" and "xla" in by_impl:
                    rec["speedup_vs_xla"] = round(by_impl["xla"] / dt, 3)
                emit(rec)
                lines.append(rec)
            if do_tune:
                tune_rows.append(_autotune_ab(
                    emit, ptune, "pallas_flash",
                    "flash_class_%s" % label, sc, host_tier))
    finally:
        if saved is None:
            os.environ.pop("MXTPU_FLASH_INTERPRET", None)
        else:
            os.environ["MXTPU_FLASH_INTERPRET"] = saved
    pallas_lines = [r for r in lines if r.get("impl") == "pallas"
                    and r.get("impl_used") == "pallas"]
    return {
        "metric": "flash_class",
        "value": len(lines),
        "unit": "json_lines",
        "vs_baseline": None,
        "mfu": None,
        "hfu": None,
        "pallas_kernel_lines": len(pallas_lines),
        "classes": [r["metric"] for r in lines],
        "autotune_beats_default": _tune_verdict(tune_rows, "improved"),
        "autotune_not_worse": _tune_verdict(tune_rows, "not_worse"),
    }


def bench_serving(emit=None):
    """Inference serving throughput (mxtpu/serving, ISSUE 5): the
    ``tools/serve_bench.py`` phases driven in-process — direct Predictor
    batch-bucket sweep (one line per bucket; items/s must be
    monotonically non-decreasing from batch 1 to the max bucket) and a
    closed-loop mixed-shape run through the MicroBatcher (one line:
    items/s, client p50/p99, compile count at retrace site
    ``serving.predict`` vs #buckets, watchdog trips, shed count). The
    summary's ``vs_baseline`` is 1.0 only when BOTH acceptance gates hold
    (monotonic sweep AND compiles <= buckets with zero trips)."""
    if emit is None:
        emit = _emit
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "tools"))
    import serve_bench as sb

    n_req = int(os.environ.get("BENCH_SERVE_REQUESTS", "500"))
    max_b = int(os.environ.get("BENCH_SERVE_MAX_BATCH", "8"))
    wait_ms = float(os.environ.get("BENCH_SERVE_WAIT_MS", "2"))
    pred, spec = sb.build_predictor(max_batch=max_b)
    rates, monotonic = sb.run_sweep(pred, spec, emit=emit)
    closed = sb.run_closed(pred, spec, n_requests=n_req,
                           max_wait_ms=wait_ms, emit=emit)
    gates_ok = monotonic and closed["compiles"] <= closed["buckets"] \
        and closed["watchdog_trips"] == 0
    return {
        "metric": "serving",
        "value": closed["value"],
        "unit": "items/sec",
        "vs_baseline": 1.0 if gates_ok else 0.0,
        "mfu": None,
        "hfu": None,
        "p50_ms": closed["p50_ms"],
        "p99_ms": closed["p99_ms"],
        "compiles": closed["compiles"],
        "buckets": closed["buckets"],
        "watchdog_trips": closed["watchdog_trips"],
        "sweep_monotonic": monotonic,
        "sweep_items_per_s": [round(r, 1) for r in rates],
    }


def bench_serving_decode(emit=None):
    """Continuous-batching autoregressive decode (mxtpu/serving/decode,
    ISSUE 11 + the ISSUE 16 paged-KV phases): ``tools/serve_bench.py
    --mode decode`` driven in-process. The A/Bs the ROADMAP item names:
    continuous batching vs restart-per-batch at equal cohort capacity on
    identical executables, paged vs rowed KV at equal HBM budget
    (admitted-residency multiplier ≥ 2×), prefix reuse under a
    templated-prompt cohort (hit rate > 0, shared pages visible), and
    speculative decoding (tokens/step and tokens/s win at bit-identical
    greedy output), plus the int8 logits-parity and KV-bytes gates.
    ``vs_baseline`` is the continuous-vs-restart tokens/s speedup when
    EVERY gate holds (strictly > 1 continuous win, zero post-warmup
    compiles at ``serving.decode`` AND ``serving.draft``, zero in-loop
    d2h, token parity across every layout, int8 parity + <= ~half KV
    bytes), else 0.0."""
    if emit is None:
        emit = _emit
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "tools"))
    import serve_bench as sb

    rec = sb.run_decode(
        n_requests=int(os.environ.get("BENCH_DECODE_REQUESTS", "80")),
        slots=int(os.environ.get("BENCH_DECODE_SLOTS", "8")),
        max_new=int(os.environ.get("BENCH_DECODE_MAX_NEW", "32")),
        emit=emit)
    return {
        "metric": "serving_decode",
        "value": round(rec["continuous"]["tok_per_s"], 1),
        "unit": "tokens/sec",
        "vs_baseline": round(rec["speedup"], 3) if rec["ok"] else 0.0,
        "mfu": None,
        "hfu": None,
        "restart_tok_per_s": round(rec["restart"]["tok_per_s"], 1),
        "continuous_steps": rec["continuous"]["steps"],
        "restart_steps": rec["restart"]["steps"],
        "compiles_post_warmup": rec["continuous"]["compiles_post_warmup"],
        "int8_tok_per_s": round(rec["int8"]["tok_per_s"], 1),
        "prefill_logits_rel_err": round(rec["prefill_logits_rel_err"], 5),
        "step_logits_rel_err": round(rec["step_logits_rel_err"], 5),
        "kv_bytes_ratio": round(rec["kv_bytes_ratio"], 4),
        "paged_residency_x": round(rec["residency_x"], 2),
        "paged_ab_ok": rec["ab_ok"],
        "prefix_hit_rate": round(rec["prefix_hit_rate"], 3),
        "prefix_ok": rec["prefix_ok"],
        "spec_accept_rate": round(rec["accept_rate"], 3),
        "spec_tokens_per_step": round(rec["spec_tokens_per_step"], 3),
        "spec_ok": rec["spec_ok"],
        "gates_ok": rec["ok"],
    }


def bench_serving_slo(emit=None):
    """SLO-aware serving control plane (mxtpu/serving/controller,
    ISSUE 13): ``tools/serve_bench.py --mode slo`` driven in-process.
    Phase 1 is the overload curve — goodput-at-SLO (completions within
    deadline / offered) for the predictive-admission controller vs the
    static depth-shed router at EQUAL replicas, paced open-loop at
    multiples of calibrated capacity. Phase 2 (>= 2 devices) is the
    kill/restore sweep: a replica is quarantined as a dead chip and the
    controller must REPLACE it with windowed p99 recovering inside the
    gated window, zero hung futures. ``vs_baseline`` is the goodput
    gain at the best overload point when EVERY gate holds, else 0.0."""
    if emit is None:
        emit = _emit
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "tools"))
    import serve_bench as sb

    rec = sb.run_slo(
        n_requests=int(os.environ.get("BENCH_SLO_REQUESTS", "200")),
        emit=emit)
    kill = rec["killrestore"]
    return {
        "metric": "serving_slo",
        "value": round(max(rec["gains"]), 4),
        "unit": "goodput_gain_at_best_point",
        "vs_baseline": round(max(rec["gains"]), 4) if rec["ok"] else 0.0,
        "mfu": None,
        "hfu": None,
        "slo_ms": round(rec["slo_ms"], 2),
        "curve_ok": rec["curve_ok"],
        "hangs": rec["hangs"],
        "killrestore_ok": kill["ok"] if kill else None,
        "p99_recovery_s": kill["value"] if kill else None,
        "gates_ok": rec["ok"],
    }


def bench_serving_zoo(emit=None):
    """Multi-tenant model zoo (mxtpu/serving/zoo, ISSUE 20):
    ``tools/serve_bench.py --mode zoo`` driven in-process. K models
    multiplexed over a smaller device pool under skewed mixed-tenant
    open-loop load, with a mid-run canary deploy+promote AND
    deploy+rollback cycle. Gates: per-tenant goodput-at-SLO with
    priority isolation, page-in compiles == 0 (evicted models return
    disk/memory-warm), zero hung futures across the rollout, bounded
    eviction/page-in churn. ``vs_baseline`` is the achieved goodput
    fraction of offered load when EVERY gate holds, else 0.0."""
    if emit is None:
        emit = _emit
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "tools"))
    import serve_bench as sb

    rec = sb.run_zoo(emit=emit)
    frac = min(1.0, rec["value"] / max(rec["offered_qps"], 1e-9))
    return {
        "metric": "serving_zoo",
        "value": rec["value"],
        "unit": "goodput_rps",
        "vs_baseline": round(frac, 4) if rec["ok"] else 0.0,
        "mfu": None,
        "hfu": None,
        "models": rec["models"],
        "pageins": rec["pageins"],
        "evictions": rec["evictions"],
        "pagein_compiles": rec["pagein_compiles"],
        "hangs": rec["hung"],
        "attainment_gold": rec["attainment_gold"],
        "attainment_free": rec["attainment_free"],
        "gates_ok": rec["ok"],
    }


def bench_startup_time(emit=None):
    """Persistent compile cache (mxtpu/compile_service.py, ISSUE 15):
    cold-start vs warm-disk-cache wall time, each scenario in a FRESH
    python process (the thing measured is process restart): (a) gluon
    Trainer first completed step, (b) Predictor replica warmup + one
    served request. Gates: warm compiles == 0 across every retrace site
    (watchdog-pinned — a disk load is not a compile), warm disk_hits >
    0, warm wall < cold wall. ``vs_baseline`` is the WORST scenario's
    cold/warm speedup iff every gate holds, else 0.0."""
    if emit is None:
        emit = _emit
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "tools"))
    import startup_bench

    rec = startup_bench.run_startup(emit=emit)
    tr = rec["scenarios"].get("trainer", {})
    pr = rec["scenarios"].get("predictor", {})
    return {
        "metric": "startup_time",
        "value": round(rec["speedup"], 3),
        "unit": "warm_vs_cold_speedup",
        "vs_baseline": round(rec["speedup"], 3) if rec["ok"] else 0.0,
        "mfu": None,
        "hfu": None,
        "trainer_cold_s": tr.get("cold_s"),
        "trainer_warm_s": tr.get("warm_s"),
        "trainer_warm_compiles": tr.get("warm_compiles"),
        "predictor_cold_s": pr.get("cold_s"),
        "predictor_warm_s": pr.get("warm_s"),
        "predictor_warm_compiles": pr.get("warm_compiles"),
        "gates_ok": rec["ok"],
    }


def bench_fleet_resume(emit=None):
    """Elastic fleet matrix (mxtpu/fleet.py, ISSUE 18): kill-one-host
    tiered restore + warm elastic rejoin, every host a real subprocess
    on the forced-CPU tier (chip-safe). Four phases — 2-host fleet with
    ``host_loss@K`` injected, 1-host restore onto a RESHAPED mesh,
    uninterrupted oracle, 2-host warm rejoin against the same compile
    cache. Gates: kill detected loud (exit 41/42, nothing hung), the
    restore resumes at K with the divergence sentinel green, post-restore
    losses match the oracle within reduce-order tolerance, and every
    rejoined host reaches step 1 with ZERO compiles (watchdog-pinned),
    all executables disk-served. ``vs_baseline`` = killed-fleet wall /
    warm-rejoin wall iff every gate holds, else 0.0."""
    if emit is None:
        emit = _emit
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "tools"))
    import fleet_bench

    rec = fleet_bench.run_fleet_resume(emit=emit)
    gates = rec.get("gates", {})
    return {
        "metric": "fleet_resume",
        "value": round(rec.get("rejoin_wall_s") or 0.0, 3),
        "unit": "rejoin_wall_s",
        "vs_baseline": rec.get("vs_baseline", 0.0) if rec.get("ok")
        else 0.0,
        "mfu": None,
        "hfu": None,
        "kill_step": rec.get("kill_step"),
        "gates": gates,
        "gates_ok": rec.get("ok", False),
    }


def bench_multichip_resnet(emit=None):
    """Mesh-native Trainer scaling (ISSUE 7): resnet18 data-parallel over
    1..N devices through ``gluon.Trainer(mesh=...)`` with ZeRO-1 on, at a
    FIXED global batch (strong scaling — every device count computes the
    same mathematical step, which is what makes the parity gate below
    meaningful). One JSON line per device count (items/s, ``vs_baseline``
    = speedup over the 1-device plain-Trainer run) plus a summary line.

    Tiered gating, like conv_class: on a real multi-chip platform the
    summary's ``vs_baseline`` is the max-count scaling efficiency
    (speedup / devices — the ROADMAP item 1 acceptance number). On the
    forced-host-device tier the N "devices" share one socket, so scaling
    numbers are meaningless; there the summary gates on parity (every
    count's final loss tracks the 1-device run to reduce-order tolerance)
    + compile budget (ZERO post-warmup compiles at the fused_optimizer
    retrace site for every count) and reports 1.0/0.0."""
    import jax

    import mxtpu as mx
    from mxtpu import autograd, gluon, telemetry
    from mxtpu.gluon.model_zoo import vision
    from mxtpu.parallel import make_mesh

    if emit is None:
        emit = _emit
    ndev = len(jax.devices())
    if ndev < 2:
        return {"metric": "multichip_resnet_scaling",
                "error": "skipped: needs >1 device (have %d) — run the "
                         "host tier with XLA_FLAGS=--xla_force_host_"
                         "platform_device_count=8" % ndev}
    batch = int(os.environ.get("BENCH_MC_BATCH", "32"))
    img = int(os.environ.get("BENCH_MC_IMG", "64"))
    steps = int(os.environ.get("BENCH_MC_STEPS", "10"))
    counts = [n for n in (1, 2, 4, 8, 16, 32, 64)
              if n <= ndev and batch % n == 0]
    rng = np.random.RandomState(0)
    x_np = rng.uniform(-1, 1, (batch, 3, img, img)).astype(np.float32)
    y_np = rng.randint(0, 10, (batch,)).astype(np.float32)
    platform = jax.devices()[0].platform
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    def measure(n):
        mx.random.seed(0)  # identical init per count — parity is exact
        net = vision.resnet18_v1()
        net.initialize()
        x, y = mx.nd.array(x_np), mx.nd.array(y_np)
        net(x)  # settle deferred shapes
        net.hybridize()
        mesh = make_mesh({"data": n}, jax.devices()[:n]) if n > 1 else None
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.01, "momentum": 0.9},
                           mesh=mesh, zero1=True)
        xs, ys = (tr.shard_batch(x, y)) if mesh is not None else (x, y)
        params = list(net.collect_params().values())

        def one():
            with autograd.record():
                l = loss_fn(net(xs), ys).mean()
            l.backward()
            tr.step(1)
            return l

        warm = None
        for _ in range(2):  # warmup: every compile lands here
            warm = one()
        jax.block_until_ready([p.data()._data for p in params])
        # parity gates on the POST-WARMUP loss: two steps in, the value is
        # O(log n_classes) and cross-device reduce-order ULPs have not yet
        # been amplified by training dynamics (a fully-trained-down loss
        # near zero diverges relatively even between correct runs)
        warm_loss = float(warm.asnumpy())
        # retrace_stats is None until the site's first recorded compile
        # (e.g. MXTPU_FUSED_OPTIMIZER=0 takes the eager loop)
        c0 = (telemetry.retrace_stats("fused_optimizer")
              or {}).get("compiles", 0)
        t0 = time.perf_counter()
        last = None
        for _ in range(steps):
            last = one()
        jax.block_until_ready([p.data()._data for p in params])
        dt = time.perf_counter() - t0
        compiles = (telemetry.retrace_stats("fused_optimizer")
                    or {}).get("compiles", 0) - c0
        return steps * batch / dt, warm_loss, float(last.asnumpy()), compiles

    rate1 = None
    lines = []
    for n in counts:
        rate, warm_loss, final_loss, compiles = measure(n)
        if rate1 is None:
            rate1 = rate
        line = {"metric": "multichip_resnet_n%d" % n, "devices": n,
                "value": round(rate, 2), "unit": "images/sec",
                "vs_baseline": round(rate / rate1, 3),
                "warm_loss": warm_loss, "final_loss": final_loss,
                "post_warmup_compiles": compiles}
        lines.append(line)
        emit(line)
    parity_ok = all(abs(l["warm_loss"] - lines[0]["warm_loss"]) < 1e-3
                    for l in lines)
    compile_ok = all(l["post_warmup_compiles"] == 0 for l in lines)
    top = lines[-1]
    if platform == "cpu":
        # host tier: the gate is parity + compile budget, not throughput
        vs = 1.0 if (parity_ok and compile_ok) else 0.0
    else:
        vs = round(top["vs_baseline"] / top["devices"], 3)  # efficiency
    return {
        "metric": "multichip_resnet_scaling_b%d" % batch,
        "value": top["value"], "unit": "images/sec",
        "devices": top["devices"],
        "speedup_vs_1dev": top["vs_baseline"],
        "parity_ok": parity_ok, "compile_budget_ok": compile_ok,
        "vs_baseline": vs,
        "mfu": None, "hfu": None,
    }


def bench_input_pipeline(emit=None):
    """Device-resident input pipeline (ISSUE 9): the double-buffered
    prefetch-to-device stream (mxtpu/io/stream.py) vs the synchronous
    pull-then-compute loop, over a synthetic JPEG RecordIO shard.

    Three measurements, JSON line each (ISSUE 9 satellite):

    * ``loader_only`` — ShardedRecordReader drain rate (pread + threaded
      jpeg-decode + batchify, no device work): the input-side ceiling.
    * ``sync`` — pull a batch, THEN upload + compute + block, per step:
      the pre-ISSUE-9 shape of the loop. Its ``wait_frac`` is decode
      time the devices sit idle (the ``data.wait`` pathology).
    * ``overlap`` — the same batches through DevicePrefetcher: decode +
      H2D of batch N+1 overlap compute on batch N; ``wait_frac`` is now
      only true starvation, measured by the prefetcher's own
      ``data.wait`` span.

    ``vs_baseline`` = overlapped speedup over the synchronous path.
    Tiered gating like multichip_resnet: the gate — parity (both paths
    consume the identical batch stream: same seed, compute checksums
    match) + the ``data.wait`` fraction dropping under overlap — applies
    everywhere, but on a SINGLE-CORE host the wall-clock speedup is
    meaningless (decode threads have no core to overlap onto — hiding
    latency needs parallel hardware somewhere), so there ``vs_baseline``
    reports the gate verdict 1.0/0.0; with >1 core (or a real chip doing
    the compute) it reports the measured speedup, zeroed if the gate
    fails so the battery artifact flags it."""
    import tempfile

    import cv2
    import jax
    import jax.numpy as jnp

    from mxtpu import recordio, telemetry
    from mxtpu.io.stream import DevicePrefetcher, ShardedRecordReader

    if emit is None:
        emit = _emit
    n_rec = int(os.environ.get("BENCH_PIPE_RECORDS", "192"))
    batch = int(os.environ.get("BENCH_PIPE_BATCH", "16"))
    img = int(os.environ.get("BENCH_PIPE_IMG", "96"))
    epochs = int(os.environ.get("BENCH_PIPE_EPOCHS", "3"))
    threads = int(os.environ.get("BENCH_PIPE_THREADS", "2"))
    chain = int(os.environ.get("BENCH_PIPE_COMPUTE", "6"))

    rng = np.random.RandomState(0)
    with tempfile.TemporaryDirectory() as td:
        rec = os.path.join(td, "pipe.rec")
        idx = os.path.join(td, "pipe.idx")
        w = recordio.MXIndexedRecordIO(idx, rec, "w")
        for i in range(n_rec):
            # natural-ish images so jpeg decode work is realistic
            yy, xx = np.mgrid[0:img, 0:img].astype(np.float32) / img
            im = np.stack([
                128 + 100 * np.sin(3 * yy + i) + rng.normal(0, 12, (img, img)),
                128 + 100 * np.cos(2 * xx + i) + rng.normal(0, 12, (img, img)),
                128 + 80 * np.sin(4 * (xx + yy)) + rng.normal(0, 12,
                                                              (img, img)),
            ], axis=2).clip(0, 255).astype(np.uint8)
            hdr = recordio.IRHeader(0, float(i % 10), i, 0)
            w.write_idx(i, recordio.pack_img(hdr, im, quality=90,
                                             img_fmt=".jpg"))
        w.close()

        def decode(raw):
            hdr, im = recordio.unpack_img(raw, cv2.IMREAD_COLOR)
            out = im.astype(np.float32) * (1.0 / 255.0) - 0.5
            return np.ascontiguousarray(out.transpose(2, 0, 1)), \
                np.float32(hdr.label)

        def reader(n_threads=None):
            # n_threads=0: inline decode on the consumer thread — the
            # true synchronous baseline (the pool reader already overlaps
            # decode with the consumer, which would flatter "sync")
            return ShardedRecordReader(
                rec, batch_size=batch, decode_fn=decode, seed=7,
                num_threads=threads if n_threads is None else n_threads,
                last_batch="discard")

        hid = 512
        k = jax.random.PRNGKey(0)
        w0 = jax.random.normal(k, (3 * img * img, hid),
                               jnp.float32) * 0.02
        ws = [jax.random.normal(jax.random.PRNGKey(i + 1), (hid, hid),
                                jnp.float32) * 0.05 for i in range(chain)]

        @jax.jit
        def step(x):
            h = x.reshape(x.shape[0], -1) @ w0
            for wi in ws:
                h = jnp.tanh(h @ wi)
            return h.sum()

        # warmup: the one compile, off both timed phases
        float(step(jnp.zeros((batch, 3, img, img), jnp.float32)))

        # ---- loader only: the decode-side ceiling
        rd = reader()
        n_batches = len(rd) * epochs
        t0 = time.perf_counter()
        for _ in range(epochs):
            for _ in rd:
                pass
        t_loader = time.perf_counter() - t0
        emit({"metric": "input_pipeline_loader_only",
              "value": round(n_batches * batch / t_loader, 1),
              "unit": "images/sec", "batches_per_s":
              round(n_batches / t_loader, 2), "vs_baseline": None})

        # ---- synchronous: inline decode, then upload+compute+block
        rd = reader(n_threads=0)
        acc_sync = 0.0
        t_pull = 0.0
        t0 = time.perf_counter()
        for _ in range(epochs):
            it = iter(rd)
            while True:
                tp = time.perf_counter()
                try:
                    data, _label = next(it)
                except StopIteration:
                    break
                t_pull += time.perf_counter() - tp
                acc_sync += float(step(jnp.asarray(data)))
        t_sync = time.perf_counter() - t0
        wait_sync = t_pull / t_sync
        emit({"metric": "input_pipeline_sync",
              "value": round(n_batches * batch / t_sync, 1),
              "unit": "images/sec", "wait_frac": round(wait_sync, 4),
              "vs_baseline": 1.0})

        # ---- overlapped: DevicePrefetcher hides decode+H2D under compute
        for m in ("data.wait", "data.h2d", "data.starved"):
            telemetry.reset_metric(m)
        rd = reader()
        acc_over = 0.0
        t0 = time.perf_counter()
        for _ in range(epochs):
            pf = DevicePrefetcher(iter(rd))
            try:
                for data, _label in pf:
                    acc_over += float(step(data._data))
            finally:
                # a mid-epoch step failure must not leak the producer
                # thread into the tempdir teardown
                pf.close()
        t_over = time.perf_counter() - t0
        hist = telemetry.snapshot()["histograms"].get("data.wait")
        wait_over = (hist["sum"] if hist else 0.0) / t_over
        emit({"metric": "input_pipeline_overlap",
              "value": round(n_batches * batch / t_over, 1),
              "unit": "images/sec", "wait_frac": round(wait_over, 4),
              "starved": telemetry.value("data.starved"),
              "vs_baseline": round(t_sync / t_over, 3)})

    # parity: identical seed => identical batch stream => identical sums
    parity_ok = abs(acc_sync - acc_over) <= 1e-5 * max(1.0, abs(acc_sync))
    gate_ok = parity_ok and wait_over < wait_sync
    cores = os.cpu_count() or 1
    if cores < 2:
        vs = 1.0 if gate_ok else 0.0  # single-core tier: gate verdict
    else:
        vs = round(t_sync / t_over, 3) if gate_ok else 0.0
    return {
        "metric": "input_pipeline_overlap_b%d" % batch,
        "value": round(n_batches * batch / t_over, 1),
        "unit": "images/sec",
        "speedup": round(t_sync / t_over, 3), "host_cores": cores,
        "wait_frac_sync": round(wait_sync, 4),
        "wait_frac_overlap": round(wait_over, 4),
        "parity_ok": parity_ok, "gate_ok": gate_ok,
        "vs_baseline": vs,
        "mfu": None, "hfu": None,
    }


def bench_sparse_linear():
    """BASELINE config 5: sparse linear classification samples/sec
    (examples/sparse/linear_classification.py — LibSVM CSR batches through
    the gather/segment-sum csr x dense dot, row-sparse grads, lazy Adam).
    The reference never published a number for this config; vs_baseline
    reports throughput against a 100k samples/sec floor."""
    import importlib.util
    import tempfile

    spec = importlib.util.spec_from_file_location(
        "sparse_lc", os.path.join(os.path.dirname(
            os.path.abspath(__file__)),
            "examples", "sparse", "linear_classification.py"))
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)

    num_features = int(os.environ.get("BENCH_SPARSE_FEATURES", "100000"))
    batch = int(os.environ.get("BENCH_SPARSE_BATCH", "1024"))
    rows = 16 * batch
    path = os.path.join(tempfile.gettempdir(), "bench_sparse.libsvm")
    m.make_synthetic_libsvm(path, num_rows=rows, num_features=num_features,
                            nnz_per_row=40)
    # steady-state: parsing + compile-heavy first epoch excluded
    acc, _, rate = m.train(path, num_features, batch_size=batch, epochs=3,
                           measure=True)
    return {
        "metric": "sparse_linear_train_b%d_f%d" % (batch, num_features),
        "value": round(rate, 1),
        "unit": "samples/sec",
        "vs_baseline": round(rate / 100000.0, 3),
        "mfu": None,
        "hfu": None,
    }


# headline config LAST: the driver records the final printed line as the
# round's parsed headline metric (see BENCH_r0*.json "parsed")
CONFIGS = {
    "eager": bench_eager,
    "optimizer_step": bench_optimizer_step,
    "guard_overhead": bench_guard_overhead,
    "telemetry_overhead": bench_telemetry_overhead,
    "integrity_overhead": bench_integrity_overhead,
    "conv_class": bench_conv_class,
    "flash_class": bench_flash_class,
    "serving": bench_serving,
    "serving_decode": bench_serving_decode,
    "serving_slo": bench_serving_slo,
    "serving_zoo": bench_serving_zoo,
    "startup_time": bench_startup_time,
    "fleet_resume": bench_fleet_resume,
    "multichip_resnet": bench_multichip_resnet,
    "input_pipeline": bench_input_pipeline,
    "sparse_linear": bench_sparse_linear,
    "lstm_ptb": bench_lstm_ptb,
    "bert_base": bench_bert_base,
    "resnet50": bench_resnet50,
}


def _run_config(cname, fn, timeout_s):
    """Run one config with a wall-clock watchdog. The TPU tunnel can wedge
    server-side (observed: every dispatch, even a trivial jit, hangs
    indefinitely — PERF.md timing methodology); without a watchdog a wedged
    chip would leave the driver artifact with NO output lines. The config
    runs on a daemon thread; on timeout an error record is printed and the
    hung thread is abandoned (it holds no locks we need)."""
    import threading

    result = {}

    def work():
        try:
            result["out"] = fn()
        except BaseException as e:  # noqa: BLE001 - SystemExit included:
            result["err"] = str(e)   # a dead thread must still yield a record

    t = threading.Thread(target=work, daemon=True)
    t.start()
    t.join(timeout_s)
    if t.is_alive():
        return {"metric": cname, "timed_out": True,
                "error": "timeout after %ds (chip/tunnel unresponsive?)"
                         % timeout_s}
    if "err" in result:
        return {"metric": cname, "error": result["err"]}
    return result.get("out") or {"metric": cname,
                                 "error": "config returned nothing"}


def _preflight():
    """Distinguish 'wedged' from 'slow' BEFORE burning each config's 900 s
    timeout: a trivial jit dispatch + host fetch runs in a SUBPROCESS (a
    hung PJRT client must not poison this process) under a short timeout.
    A healthy chip answers in seconds even with a cold compile; a wedged
    tunnel (observed round 3: killed profiler trace left every dispatch
    from every process hanging for hours) answers never. Returns a record
    dict; rec["ok"] is False when the chip is wedged. BENCH_PREFLIGHT=0
    skips, BENCH_PREFLIGHT_TIMEOUT overrides the 120 s budget."""
    import subprocess
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "tools"))
    import perf_probe  # ONE copy of the wedge-safe probe (tools/)
    timeout_s = int(os.environ.get("BENCH_PREFLIGHT_TIMEOUT", "120"))
    try:
        out = subprocess.run([sys.executable, "-u", "-c",
                              perf_probe.PROBE_SNIPPET],
                             capture_output=True, text=True,
                             timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return {"metric": "preflight", "ok": False,
                "error": "chip/tunnel WEDGED: trivial jit dispatch did not "
                         "complete in %ds (distinct from slow — a healthy "
                         "chip answers this in seconds)" % timeout_s}
    stages = perf_probe.parse(out.stdout)
    if "rtt_ms" in stages:
        return {"metric": "preflight", "ok": True,
                "first_dispatch_s": stages.get("first_dispatch"),
                "rtt_s": stages["rtt_ms"] / 1e3,
                "platform": stages.get("platform")}
    return {"metric": "preflight", "ok": False,
            "error": "preflight subprocess failed rc=%d: %s"
                     % (out.returncode, (out.stderr or "")[-300:])}


def main():
    name = os.environ.get("BENCH_CONFIG", "all")
    timeout_s = int(os.environ.get("BENCH_CONFIG_TIMEOUT", "900"))
    if os.environ.get("BENCH_PREFLIGHT", "1") != "0":
        pre = _preflight()
        _emit(pre)
        if not pre["ok"]:
            names = list(CONFIGS) if name == "all" else [name]
            for cname in names:
                _emit({"metric": cname, "error":
                       "skipped: chip/tunnel wedged (see "
                       "preflight record)"})
            sys.exit(1)
    if name == "all":
        # per-config isolation: a failing config must not eat the headline
        # resnet50 line (the driver parses the LAST printed line)
        base_profile = os.environ.get("BENCH_PROFILE")
        hung = False
        rec = {}
        try:
            for cname, fn in CONFIGS.items():
                if hung:
                    # the chip is unresponsive; running more configs would
                    # hang too, and an abandoned thread that later un-wedges
                    # must not race a live config's profiler/BENCH_PROFILE
                    rec = {"metric": cname, "error":
                           "skipped: earlier config timed out "
                           "(chip/tunnel unresponsive)"}
                    _emit(rec)
                    continue
                if base_profile:
                    # one trace file per config — a shared file would be
                    # clobbered and merged across configs
                    root, ext = os.path.splitext(base_profile)
                    os.environ["BENCH_PROFILE"] = "%s.%s%s" % (root, cname,
                                                               ext or ".json")
                rec = _run_config(cname, fn, timeout_s)
                hung = hung or rec.get("timed_out", False)
                _emit(rec)
        finally:
            if base_profile:
                os.environ["BENCH_PROFILE"] = base_profile
        code = 1 if "error" in rec else 0  # headline (last) config decides
        if hung:
            os._exit(code)  # abandoned daemon threads would block exit
        sys.exit(code)
    rec = _run_config(name, CONFIGS[name], timeout_s)
    _emit(rec)
    if rec.get("timed_out"):
        os._exit(1)  # the abandoned daemon thread would block exit
    if "error" in rec:
        sys.exit(1)  # config failures keep failing the invocation


if __name__ == "__main__":
    main()
