// mxtpu C ABI implementation: embed (or attach to) CPython and delegate to
// mxtpu.c_api_impl.
//
// Reference: src/c_api/c_api.cc + c_api_ndarray.cc + c_predict_api.cc. The
// reference marshals into its C++ engine; the TPU-native runtime's
// orchestrator is Python (XLA/PJRT does the compute), so this layer marshals
// into the interpreter instead — one GIL scope per call, thread-local error
// strings, opaque PyObject* handles. When the host process *is* Python
// (ctypes), the already-running interpreter is used; from a plain C program
// the first call boots one.

#include "../../include/mxtpu/c_api.h"

#include <Python.h>

#include <cstring>
#include <mutex>
#include <string>
#include <vector>

namespace {

thread_local std::string g_last_error;

void SetError(const std::string &msg) { g_last_error = msg; }

// Capture the pending Python exception into the thread-local error string.
void SetErrorFromPython() {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  PyErr_NormalizeException(&type, &value, &tb);
  std::string msg = "python error";
  if (value != nullptr) {
    PyObject *s = PyObject_Str(value);
    if (s != nullptr) {
      const char *c = PyUnicode_AsUTF8(s);
      if (c != nullptr) msg = c;
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
  SetError(msg);
}

// Boot the interpreter if this process doesn't have one (plain-C host).
// std::call_once: two C host threads may race their first API call here.
// Releases the GIL after boot so PyGILState_Ensure works from any thread.
bool EnsureInterpreter() {
  static std::once_flag boot_flag;
  static bool boot_ok = false;
  std::call_once(boot_flag, []() {
    if (Py_IsInitialized()) {
      boot_ok = true;
      return;
    }
    Py_InitializeEx(0);
    boot_ok = Py_IsInitialized();
    if (boot_ok) PyEval_SaveThread();  // release the GIL the boot holds
  });
  if (!boot_ok) SetError("failed to initialize embedded Python interpreter");
  return boot_ok;
}

// The mxtpu.c_api_impl module (borrowed global ref, imported once).
PyObject *ImplModule() {
  static PyObject *mod = nullptr;
  if (mod == nullptr) {
    mod = PyImport_ImportModule("mxtpu.c_api_impl");
    if (mod == nullptr) SetErrorFromPython();
  }
  return mod;
}

// RAII GIL scope.
class GilScope {
 public:
  GilScope() : state_(PyGILState_Ensure()) {}
  ~GilScope() { PyGILState_Release(state_); }

 private:
  PyGILState_STATE state_;
};

PyObject *ShapeTuple(const int64_t *shape, int ndim) {
  PyObject *t = PyTuple_New(ndim);
  for (int i = 0; i < ndim; ++i) {
    PyTuple_SetItem(t, i, PyLong_FromLongLong(shape[i]));
  }
  return t;
}

// Call impl.<method>(args...); returns new ref or nullptr (error recorded).
PyObject *CallImpl(const char *method, PyObject *args) {
  PyObject *mod = ImplModule();
  if (mod == nullptr) {
    Py_XDECREF(args);
    return nullptr;
  }
  PyObject *fn = PyObject_GetAttrString(mod, method);
  if (fn == nullptr) {
    SetErrorFromPython();
    Py_XDECREF(args);
    return nullptr;
  }
  PyObject *res = PyObject_CallObject(fn, args);
  Py_DECREF(fn);
  Py_XDECREF(args);
  if (res == nullptr) SetErrorFromPython();
  return res;
}

}  // namespace

extern "C" {

const char *MXTPUGetLastError(void) { return g_last_error.c_str(); }

int MXTPURuntimeInit(const char *platform) {
  if (!EnsureInterpreter()) return -1;
  GilScope gil;
  PyObject *args = Py_BuildValue("(z)", platform);
  PyObject *res = CallImpl("runtime_init", args);
  if (res == nullptr) return -1;
  Py_DECREF(res);
  return 0;
}

int MXTPUGetVersion(int *out) {
  if (!EnsureInterpreter()) return -1;
  GilScope gil;
  PyObject *res = CallImpl("get_version", PyTuple_New(0));
  if (res == nullptr) return -1;
  *out = static_cast<int>(PyLong_AsLong(res));
  Py_DECREF(res);
  return 0;
}

namespace {
// MXTPUListAllOpNames' private string store (documented lifetime: until
// the next call on this thread)
thread_local std::vector<std::string> g_op_name_store;
thread_local std::vector<const char *> g_op_name_ptrs;
}  // namespace

int MXTPUListAllOpNames(int *out_num, const char ***out_names) {
  if (!EnsureInterpreter()) return -1;
  GilScope gil;
  PyObject *res = CallImpl("list_all_op_names", PyTuple_New(0));
  if (res == nullptr) return -1;
  g_op_name_store.clear();
  g_op_name_ptrs.clear();
  for (Py_ssize_t i = 0; i < PyTuple_Size(res); ++i) {
    const char *c = PyUnicode_AsUTF8(PyTuple_GetItem(res, i));
    g_op_name_store.emplace_back(c == nullptr ? "" : c);
  }
  for (const std::string &sname : g_op_name_store)
    g_op_name_ptrs.push_back(sname.c_str());
  Py_DECREF(res);
  *out_num = static_cast<int>(g_op_name_ptrs.size());
  *out_names = g_op_name_ptrs.data();
  return 0;
}

int MXTPUNDArrayWaitAll(void) {
  if (!EnsureInterpreter()) return -1;
  GilScope gil;
  PyObject *res = CallImpl("ndarray_wait_all", PyTuple_New(0));
  if (res == nullptr) return -1;
  Py_DECREF(res);
  return 0;
}

int MXTPUNDArrayCreateFromBlob(const float *data, const int64_t *shape,
                               int ndim, NDArrayHandle *out) {
  if (!EnsureInterpreter()) return -1;
  GilScope gil;
  int64_t n = 1;
  for (int i = 0; i < ndim; ++i) n *= shape[i];
  PyObject *bytes =
      PyBytes_FromStringAndSize(reinterpret_cast<const char *>(data),
                                static_cast<Py_ssize_t>(n * sizeof(float)));
  // "N" steals both new refs into the args tuple
  PyObject *args = Py_BuildValue("(NN)", bytes, ShapeTuple(shape, ndim));
  PyObject *res = CallImpl("ndarray_from_blob", args);
  if (res == nullptr) return -1;
  *out = res;  // keep the new ref as the handle
  return 0;
}

int MXTPUNDArrayShape(NDArrayHandle handle, int *ndim, int64_t *shape) {
  GilScope gil;
  PyObject *nd = reinterpret_cast<PyObject *>(handle);
  PyObject *args = PyTuple_Pack(1, nd);
  PyObject *res = CallImpl("ndarray_shape", args);
  if (res == nullptr) return -1;
  Py_ssize_t n = PyTuple_Size(res);
  if (n > 8) {
    Py_DECREF(res);
    SetError("ndim > 8 unsupported by MXTPUNDArrayShape");
    return -1;
  }
  *ndim = static_cast<int>(n);
  for (Py_ssize_t i = 0; i < n; ++i) {
    shape[i] = PyLong_AsLongLong(PyTuple_GetItem(res, i));
  }
  Py_DECREF(res);
  return 0;
}

int MXTPUNDArraySyncCopyToCPU(NDArrayHandle handle, float *dst,
                              int64_t size) {
  GilScope gil;
  PyObject *nd = reinterpret_cast<PyObject *>(handle);
  PyObject *args = PyTuple_Pack(1, nd);
  PyObject *res = CallImpl("ndarray_to_bytes", args);
  if (res == nullptr) return -1;
  char *buf = nullptr;
  Py_ssize_t len = 0;
  if (PyBytes_AsStringAndSize(res, &buf, &len) != 0) {
    SetErrorFromPython();
    Py_DECREF(res);
    return -1;
  }
  if (len != static_cast<Py_ssize_t>(size * sizeof(float))) {
    SetError("MXTPUNDArraySyncCopyToCPU: size mismatch");
    Py_DECREF(res);
    return -1;
  }
  std::memcpy(dst, buf, static_cast<size_t>(len));
  Py_DECREF(res);
  return 0;
}

int MXTPUNDArrayFree(NDArrayHandle handle) {
  if (handle == nullptr) return 0;
  GilScope gil;
  Py_DECREF(reinterpret_cast<PyObject *>(handle));
  return 0;
}

int MXTPUImperativeInvoke(const char *op_name, NDArrayHandle *inputs,
                          int num_inputs, const char **attr_keys,
                          const char **attr_vals, int num_attrs,
                          NDArrayHandle *outputs, int *num_outputs) {
  if (!EnsureInterpreter()) return -1;
  GilScope gil;
  PyObject *ins = PyList_New(num_inputs);
  for (int i = 0; i < num_inputs; ++i) {
    PyObject *o = reinterpret_cast<PyObject *>(inputs[i]);
    Py_INCREF(o);
    PyList_SetItem(ins, i, o);
  }
  PyObject *attrs = PyDict_New();
  for (int i = 0; i < num_attrs; ++i) {
    PyObject *v = PyUnicode_FromString(attr_vals[i]);
    PyDict_SetItemString(attrs, attr_keys[i], v);
    Py_DECREF(v);
  }
  PyObject *name = PyUnicode_FromString(op_name);
  PyObject *args = PyTuple_Pack(3, name, ins, attrs);
  Py_DECREF(name);
  Py_DECREF(ins);
  Py_DECREF(attrs);
  PyObject *res = CallImpl("imperative_invoke", args);
  if (res == nullptr) return -1;
  Py_ssize_t n = PyList_Size(res);
  if (n > *num_outputs) {
    Py_DECREF(res);
    SetError("output capacity too small");
    return -1;
  }
  *num_outputs = static_cast<int>(n);
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject *o = PyList_GetItem(res, i);
    Py_INCREF(o);
    outputs[i] = o;
  }
  Py_DECREF(res);
  return 0;
}

int MXTPUPredCreate(const char *prefix, int epoch, const char *input_name,
                    const int64_t *shape, int ndim, PredictorHandle *out) {
  if (!EnsureInterpreter()) return -1;
  GilScope gil;
  PyObject *args = Py_BuildValue("(sisN)", prefix, epoch, input_name,
                                 ShapeTuple(shape, ndim));
  PyObject *res = CallImpl("pred_create", args);
  if (res == nullptr) return -1;
  *out = res;
  return 0;
}

int MXTPUPredSetInput(PredictorHandle handle, const float *data,
                      int64_t size) {
  GilScope gil;
  PyObject *pred = reinterpret_cast<PyObject *>(handle);
  PyObject *bytes =
      PyBytes_FromStringAndSize(reinterpret_cast<const char *>(data),
                                static_cast<Py_ssize_t>(size * sizeof(float)));
  PyObject *args = PyTuple_Pack(2, pred, bytes);
  Py_DECREF(bytes);
  PyObject *res = CallImpl("pred_set_input", args);
  if (res == nullptr) return -1;
  Py_DECREF(res);
  return 0;
}

int MXTPUPredForward(PredictorHandle handle) {
  GilScope gil;
  PyObject *pred = reinterpret_cast<PyObject *>(handle);
  PyObject *args = PyTuple_Pack(1, pred);
  PyObject *res = CallImpl("pred_forward", args);
  if (res == nullptr) return -1;
  Py_DECREF(res);
  return 0;
}

int MXTPUPredGetOutputShape(PredictorHandle handle, int index, int *ndim,
                            int64_t *shape) {
  GilScope gil;
  PyObject *pred = reinterpret_cast<PyObject *>(handle);
  PyObject *args = Py_BuildValue("(Oi)", pred, index);
  PyObject *res = CallImpl("pred_output_shape", args);
  if (res == nullptr) return -1;
  Py_ssize_t n = PyTuple_Size(res);
  if (n > 8) {
    Py_DECREF(res);
    SetError("ndim > 8 unsupported");
    return -1;
  }
  *ndim = static_cast<int>(n);
  for (Py_ssize_t i = 0; i < n; ++i) {
    shape[i] = PyLong_AsLongLong(PyTuple_GetItem(res, i));
  }
  Py_DECREF(res);
  return 0;
}

int MXTPUPredGetOutput(PredictorHandle handle, int index, float *dst,
                       int64_t size) {
  GilScope gil;
  PyObject *pred = reinterpret_cast<PyObject *>(handle);
  PyObject *args = Py_BuildValue("(Oi)", pred, index);
  PyObject *res = CallImpl("pred_output_bytes", args);
  if (res == nullptr) return -1;
  char *buf = nullptr;
  Py_ssize_t len = 0;
  if (PyBytes_AsStringAndSize(res, &buf, &len) != 0) {
    SetErrorFromPython();
    Py_DECREF(res);
    return -1;
  }
  if (len != static_cast<Py_ssize_t>(size * sizeof(float))) {
    SetError("MXTPUPredGetOutput: size mismatch");
    Py_DECREF(res);
    return -1;
  }
  std::memcpy(dst, buf, static_cast<size_t>(len));
  Py_DECREF(res);
  return 0;
}

int MXTPUPredFree(PredictorHandle handle) {
  if (handle == nullptr) return 0;
  GilScope gil;
  Py_DECREF(reinterpret_cast<PyObject *>(handle));
  return 0;
}

// ---- training surface (autograd / kvstore / symbol / executor) ----
// Same delegation pattern as above; handles are owned PyObject refs.

namespace {

// generic "call impl fn, keep result as handle" helper
int CallToHandle(const char *method, PyObject *args, void **out) {
  PyObject *res = CallImpl(method, args);
  if (res == nullptr) return -1;
  *out = res;
  return 0;
}

// generic "call impl fn, discard result" helper
int CallNoResult(const char *method, PyObject *args) {
  PyObject *res = CallImpl(method, args);
  if (res == nullptr) return -1;
  Py_DECREF(res);
  return 0;
}

PyObject *HandleTuple(void **handles, int num) {
  PyObject *t = PyTuple_New(num);
  for (int i = 0; i < num; ++i) {
    PyObject *o = reinterpret_cast<PyObject *>(handles[i]);
    Py_INCREF(o);
    PyTuple_SetItem(t, i, o);
  }
  return t;
}

PyObject *StrTuple(const char **strs, int num) {
  PyObject *t = PyTuple_New(num);
  for (int i = 0; i < num; ++i) {
    PyTuple_SetItem(t, i, PyUnicode_FromString(strs[i]));
  }
  return t;
}

PyObject *AttrDict(const char **keys, const char **vals, int num) {
  PyObject *d = PyDict_New();
  for (int i = 0; i < num; ++i) {
    PyObject *v = PyUnicode_FromString(vals[i]);
    PyDict_SetItemString(d, keys[i], v);
    Py_DECREF(v);
  }
  return d;
}

// string results stay valid until the next call on this thread (the
// reference's internal-buffer convention, c_api_common.h:Ret*)
thread_local std::vector<std::string> g_str_store;
thread_local std::vector<const char *> g_str_ptrs;
thread_local std::string g_json_store;

int FreeHandle(void *handle) {
  if (handle == nullptr) return 0;
  GilScope gil;
  Py_DECREF(reinterpret_cast<PyObject *>(handle));
  return 0;
}

}  // namespace

int MXTPUNDArrayCreateFromBlobEx(const void *data, int dtype_flag,
                                 const int64_t *shape, int ndim,
                                 NDArrayHandle *out) {
  if (!EnsureInterpreter()) return -1;
  GilScope gil;
  static const int kSizes[] = {4, 8, 2, 1, 4, 1, 8};
  if (dtype_flag < 0 || dtype_flag > 6) {
    SetError("unknown mshadow dtype flag");
    return -1;
  }
  int64_t n = 1;
  for (int i = 0; i < ndim; ++i) n *= shape[i];
  PyObject *bytes = PyBytes_FromStringAndSize(
      reinterpret_cast<const char *>(data),
      static_cast<Py_ssize_t>(n * kSizes[dtype_flag]));
  PyObject *args = Py_BuildValue("(NiN)", bytes, dtype_flag,
                                 ShapeTuple(shape, ndim));
  return CallToHandle("ndarray_from_blob_ex", args, out);
}

int MXTPUNDArrayGetDType(NDArrayHandle handle, int *out_flag) {
  GilScope gil;
  PyObject *res = CallImpl(
      "ndarray_dtype_flag",
      PyTuple_Pack(1, reinterpret_cast<PyObject *>(handle)));
  if (res == nullptr) return -1;
  *out_flag = static_cast<int>(PyLong_AsLong(res));
  Py_DECREF(res);
  return 0;
}

int MXTPUNDArraySave(const char *fname, int num, NDArrayHandle *handles,
                     const char **keys) {
  GilScope gil;
  PyObject *names = keys == nullptr ? PyTuple_New(0) : StrTuple(keys, num);
  return CallNoResult(
      "ndarray_save",
      Py_BuildValue("(sNN)", fname, HandleTuple(handles, num), names));
}

namespace {
/* Shared (arrays, names)-tuple unmarshalling for MXTPUNDArrayLoad and
 * MXTPUNDArrayLoadFromBuffer. Both own the SAME private stores, so the
 * documented lifetime is "until the next load-family call on this
 * thread". Consumes `res`. */
int LoadResultOut(PyObject *res, int *out_num, NDArrayHandle **out_handles,
                  int *out_num_names, const char ***out_names) {
  if (res == nullptr) return -1;
  PyObject *arrays = PyTuple_GetItem(res, 0);
  PyObject *names = PyTuple_GetItem(res, 1);
  static thread_local std::vector<void *> handle_store;
  static thread_local std::vector<std::string> name_store;
  static thread_local std::vector<const char *> name_ptrs;
  handle_store.clear();
  for (Py_ssize_t i = 0; i < PyTuple_Size(arrays); ++i) {
    PyObject *o = PyTuple_GetItem(arrays, i);
    Py_INCREF(o);  // each becomes a caller-owned handle
    handle_store.push_back(o);
  }
  name_store.clear();
  name_ptrs.clear();
  for (Py_ssize_t i = 0; i < PyTuple_Size(names); ++i) {
    const char *c = PyUnicode_AsUTF8(PyTuple_GetItem(names, i));
    name_store.emplace_back(c == nullptr ? "" : c);
  }
  for (const std::string &s : name_store) name_ptrs.push_back(s.c_str());
  Py_DECREF(res);
  *out_num = static_cast<int>(handle_store.size());
  *out_handles = handle_store.data();
  *out_num_names = static_cast<int>(name_ptrs.size());
  *out_names = name_ptrs.empty() ? nullptr : name_ptrs.data();
  return 0;
}
}  // namespace

int MXTPUNDArrayLoad(const char *fname, int *out_num,
                     NDArrayHandle **out_handles, int *out_num_names,
                     const char ***out_names) {
  if (!EnsureInterpreter()) return -1;
  GilScope gil;
  return LoadResultOut(CallImpl("ndarray_load", Py_BuildValue("(s)", fname)),
                       out_num, out_handles, out_num_names, out_names);
}

int MXTPUAutogradSetRecording(int is_recording, int *prev) {
  if (!EnsureInterpreter()) return -1;
  GilScope gil;
  PyObject *res = CallImpl("autograd_set_recording",
                           Py_BuildValue("(i)", is_recording));
  if (res == nullptr) return -1;
  if (prev != nullptr) *prev = static_cast<int>(PyLong_AsLong(res));
  Py_DECREF(res);
  return 0;
}

int MXTPUAutogradSetTraining(int is_training, int *prev) {
  if (!EnsureInterpreter()) return -1;
  GilScope gil;
  PyObject *res = CallImpl("autograd_set_training",
                           Py_BuildValue("(i)", is_training));
  if (res == nullptr) return -1;
  if (prev != nullptr) *prev = static_cast<int>(PyLong_AsLong(res));
  Py_DECREF(res);
  return 0;
}

int MXTPUNDArrayAttachGrad(NDArrayHandle handle) {
  GilScope gil;
  return CallNoResult("ndarray_attach_grad",
                      PyTuple_Pack(1, reinterpret_cast<PyObject *>(handle)));
}

int MXTPUNDArrayGetGrad(NDArrayHandle handle, NDArrayHandle *out) {
  GilScope gil;
  return CallToHandle("ndarray_grad",
                      PyTuple_Pack(1, reinterpret_cast<PyObject *>(handle)),
                      out);
}

int MXTPUNDArrayBackward(NDArrayHandle handle, int retain_graph) {
  GilScope gil;
  return CallNoResult(
      "ndarray_backward",
      Py_BuildValue("(Oi)", reinterpret_cast<PyObject *>(handle),
                    retain_graph));
}

int MXTPUKVStoreCreate(const char *type, KVStoreHandle *out) {
  if (!EnsureInterpreter()) return -1;
  GilScope gil;
  return CallToHandle("kvstore_create", Py_BuildValue("(s)", type), out);
}

int MXTPUKVStoreInit(KVStoreHandle handle, int num, const char **keys,
                     NDArrayHandle *vals) {
  GilScope gil;
  return CallNoResult(
      "kvstore_init",
      Py_BuildValue("(ONN)", reinterpret_cast<PyObject *>(handle),
                    StrTuple(keys, num), HandleTuple(vals, num)));
}

int MXTPUKVStorePush(KVStoreHandle handle, int num, const char **keys,
                     NDArrayHandle *vals, int priority) {
  GilScope gil;
  return CallNoResult(
      "kvstore_push",
      Py_BuildValue("(ONNi)", reinterpret_cast<PyObject *>(handle),
                    StrTuple(keys, num), HandleTuple(vals, num), priority));
}

int MXTPUKVStorePull(KVStoreHandle handle, int num, const char **keys,
                     NDArrayHandle *outs, int priority) {
  GilScope gil;
  return CallNoResult(
      "kvstore_pull",
      Py_BuildValue("(ONNi)", reinterpret_cast<PyObject *>(handle),
                    StrTuple(keys, num), HandleTuple(outs, num), priority));
}

int MXTPUKVStoreSetOptimizer(KVStoreHandle handle, const char *optimizer,
                             const char **attr_keys, const char **attr_vals,
                             int num_attrs) {
  GilScope gil;
  return CallNoResult(
      "kvstore_set_optimizer",
      Py_BuildValue("(OsN)", reinterpret_cast<PyObject *>(handle), optimizer,
                    AttrDict(attr_keys, attr_vals, num_attrs)));
}

int MXTPUKVStoreFree(KVStoreHandle handle) { return FreeHandle(handle); }

int MXTPUSymbolCreateVariable(const char *name, SymbolHandle *out) {
  if (!EnsureInterpreter()) return -1;
  GilScope gil;
  return CallToHandle("symbol_create_variable", Py_BuildValue("(s)", name),
                      out);
}

int MXTPUSymbolCreateFromJSON(const char *json, SymbolHandle *out) {
  if (!EnsureInterpreter()) return -1;
  GilScope gil;
  return CallToHandle("symbol_create_from_json", Py_BuildValue("(s)", json),
                      out);
}

int MXTPUSymbolCreateFromFile(const char *path, SymbolHandle *out) {
  if (!EnsureInterpreter()) return -1;
  GilScope gil;
  return CallToHandle("symbol_create_from_file", Py_BuildValue("(s)", path),
                      out);
}

int MXTPUSymbolCompose(const char *op_name, const char *name,
                       SymbolHandle *inputs, int num_inputs,
                       const char **attr_keys, const char **attr_vals,
                       int num_attrs, SymbolHandle *out) {
  if (!EnsureInterpreter()) return -1;
  GilScope gil;
  return CallToHandle(
      "symbol_invoke",
      Py_BuildValue("(sNsN)", op_name,
                    AttrDict(attr_keys, attr_vals, num_attrs),
                    name == nullptr ? "" : name,
                    HandleTuple(inputs, num_inputs)),
      out);
}

int MXTPUSymbolListArguments(SymbolHandle sym, int *num,
                             const char ***out_names) {
  GilScope gil;
  PyObject *res = CallImpl(
      "symbol_list_arguments",
      PyTuple_Pack(1, reinterpret_cast<PyObject *>(sym)));
  if (res == nullptr) return -1;
  Py_ssize_t n = PyTuple_Size(res);
  g_str_store.clear();
  g_str_ptrs.clear();
  for (Py_ssize_t i = 0; i < n; ++i) {
    const char *c = PyUnicode_AsUTF8(PyTuple_GetItem(res, i));
    g_str_store.emplace_back(c == nullptr ? "" : c);
  }
  for (const std::string &s : g_str_store) g_str_ptrs.push_back(s.c_str());
  Py_DECREF(res);
  *num = static_cast<int>(n);
  *out_names = g_str_ptrs.data();
  return 0;
}

int MXTPUSymbolToJSON(SymbolHandle sym, const char **out_json) {
  GilScope gil;
  PyObject *res = CallImpl(
      "symbol_tojson", PyTuple_Pack(1, reinterpret_cast<PyObject *>(sym)));
  if (res == nullptr) return -1;
  const char *c = PyUnicode_AsUTF8(res);
  g_json_store = (c == nullptr) ? "" : c;
  Py_DECREF(res);
  *out_json = g_json_store.c_str();
  return 0;
}

int MXTPUSymbolFree(SymbolHandle sym) { return FreeHandle(sym); }

int MXTPUExecutorBind(SymbolHandle sym, int num_args,
                      const char **arg_names, NDArrayHandle *arg_vals,
                      const char *grad_req, ExecutorHandle *out) {
  GilScope gil;
  return CallToHandle(
      "executor_bind",
      Py_BuildValue("(ONNs)", reinterpret_cast<PyObject *>(sym),
                    StrTuple(arg_names, num_args),
                    HandleTuple(arg_vals, num_args),
                    grad_req == nullptr ? "write" : grad_req),
      out);
}

int MXTPUExecutorForward(ExecutorHandle handle, int is_train) {
  GilScope gil;
  return CallNoResult(
      "executor_forward",
      Py_BuildValue("(Oi)", reinterpret_cast<PyObject *>(handle), is_train));
}

int MXTPUExecutorNumOutputs(ExecutorHandle handle, int *num) {
  GilScope gil;
  PyObject *res = CallImpl(
      "executor_outputs",
      PyTuple_Pack(1, reinterpret_cast<PyObject *>(handle)));
  if (res == nullptr) return -1;
  *num = static_cast<int>(PyTuple_Size(res));
  Py_DECREF(res);
  return 0;
}

int MXTPUExecutorOutput(ExecutorHandle handle, int index,
                        NDArrayHandle *out) {
  GilScope gil;
  PyObject *res = CallImpl(
      "executor_outputs",
      PyTuple_Pack(1, reinterpret_cast<PyObject *>(handle)));
  if (res == nullptr) return -1;
  if (index < 0 || index >= PyTuple_Size(res)) {
    Py_DECREF(res);
    SetError("executor output index out of range");
    return -1;
  }
  PyObject *o = PyTuple_GetItem(res, index);
  Py_INCREF(o);
  Py_DECREF(res);
  *out = o;
  return 0;
}

int MXTPUExecutorBackward(ExecutorHandle handle) {
  GilScope gil;
  return CallNoResult(
      "executor_backward",
      PyTuple_Pack(1, reinterpret_cast<PyObject *>(handle)));
}

int MXTPUExecutorArgGrad(ExecutorHandle handle, const char *arg_name,
                         NDArrayHandle *out) {
  GilScope gil;
  return CallToHandle(
      "executor_arg_grad",
      Py_BuildValue("(Os)", reinterpret_cast<PyObject *>(handle), arg_name),
      out);
}

int MXTPUExecutorFree(ExecutorHandle handle) { return FreeHandle(handle); }


/* ---- DataIter surface (ref: MXListDataIters / MXDataIterCreateIter /
 * MXDataIterNext / MXDataIterGet*, src/c_api/c_api.cc) ---- */

namespace {
thread_local std::vector<std::string> g_iter_name_store;
thread_local std::vector<const char *> g_iter_name_ptrs;
}  // namespace

int MXTPUListDataIters(int *out_num, const char ***out_names) {
  if (!EnsureInterpreter()) return -1;
  GilScope gil;
  PyObject *res = CallImpl("list_data_iters", PyTuple_New(0));
  if (res == nullptr) return -1;
  g_iter_name_store.clear();
  g_iter_name_ptrs.clear();
  for (Py_ssize_t i = 0; i < PyTuple_Size(res); ++i) {
    const char *c = PyUnicode_AsUTF8(PyTuple_GetItem(res, i));
    g_iter_name_store.emplace_back(c == nullptr ? "" : c);
  }
  for (const std::string &sname : g_iter_name_store)
    g_iter_name_ptrs.push_back(sname.c_str());
  Py_DECREF(res);
  *out_num = static_cast<int>(g_iter_name_ptrs.size());
  *out_names = g_iter_name_ptrs.data();
  return 0;
}

int MXTPUDataIterCreate(const char *name, int num_attrs,
                        const char **attr_keys, const char **attr_vals,
                        DataIterHandle *out) {
  if (!EnsureInterpreter()) return -1;
  GilScope gil;
  return CallToHandle(
      "data_iter_create",
      Py_BuildValue("(sN)", name, AttrDict(attr_keys, attr_vals, num_attrs)),
      out);
}

int MXTPUDataIterBeforeFirst(DataIterHandle handle) {
  GilScope gil;
  return CallNoResult(
      "data_iter_before_first",
      PyTuple_Pack(1, reinterpret_cast<PyObject *>(handle)));
}

int MXTPUDataIterNext(DataIterHandle handle, int *out) {
  GilScope gil;
  PyObject *res = CallImpl(
      "data_iter_next", PyTuple_Pack(1, reinterpret_cast<PyObject *>(handle)));
  if (res == nullptr) return -1;
  *out = static_cast<int>(PyLong_AsLong(res));
  Py_DECREF(res);
  return 0;
}

int MXTPUDataIterGetData(DataIterHandle handle, NDArrayHandle *out) {
  GilScope gil;
  return CallToHandle(
      "data_iter_get_data",
      PyTuple_Pack(1, reinterpret_cast<PyObject *>(handle)), out);
}

int MXTPUDataIterGetLabel(DataIterHandle handle, NDArrayHandle *out) {
  GilScope gil;
  return CallToHandle(
      "data_iter_get_label",
      PyTuple_Pack(1, reinterpret_cast<PyObject *>(handle)), out);
}

int MXTPUDataIterGetPadNum(DataIterHandle handle, int *out) {
  GilScope gil;
  PyObject *res = CallImpl(
      "data_iter_get_pad_num",
      PyTuple_Pack(1, reinterpret_cast<PyObject *>(handle)));
  if (res == nullptr) return -1;
  *out = static_cast<int>(PyLong_AsLong(res));
  Py_DECREF(res);
  return 0;
}

int MXTPUDataIterFree(DataIterHandle handle) { return FreeHandle(handle); }

/* ---- RecordIO surface (ref: MXRecordIOWriter / MXRecordIOReader) ---- */

int MXTPURecordIOWriterCreate(const char *path, RecordIOHandle *out) {
  if (!EnsureInterpreter()) return -1;
  GilScope gil;
  return CallToHandle("recordio_writer_create", Py_BuildValue("(s)", path),
                      out);
}

int MXTPURecordIOWriterWriteRecord(RecordIOHandle handle, const char *buf,
                                   size_t size) {
  GilScope gil;
  PyObject *bytes = PyBytes_FromStringAndSize(buf,
                                              static_cast<Py_ssize_t>(size));
  return CallNoResult(
      "recordio_writer_write",
      Py_BuildValue("(ON)", reinterpret_cast<PyObject *>(handle), bytes));
}

int MXTPURecordIOWriterTell(RecordIOHandle handle, size_t *out) {
  GilScope gil;
  PyObject *res = CallImpl(
      "recordio_writer_tell",
      PyTuple_Pack(1, reinterpret_cast<PyObject *>(handle)));
  if (res == nullptr) return -1;
  *out = static_cast<size_t>(PyLong_AsUnsignedLongLong(res));
  Py_DECREF(res);
  return 0;
}

int MXTPURecordIOWriterFree(RecordIOHandle handle) {
  GilScope gil;
  PyObject *res = CallImpl(
      "recordio_close", PyTuple_Pack(1, reinterpret_cast<PyObject *>(handle)));
  Py_XDECREF(res);
  Py_DECREF(reinterpret_cast<PyObject *>(handle));
  return res == nullptr ? -1 : 0;
}

int MXTPURecordIOReaderCreate(const char *path, RecordIOHandle *out) {
  if (!EnsureInterpreter()) return -1;
  GilScope gil;
  return CallToHandle("recordio_reader_create", Py_BuildValue("(s)", path),
                      out);
}

/* Reads the next record; *out_size == 0 at end of file. The returned
 * pointer stays valid until the next read on this thread. */
namespace {
thread_local std::string g_record_buf;
}  // namespace

int MXTPURecordIOReaderReadRecord(RecordIOHandle handle, const char **out_buf,
                                  size_t *out_size) {
  GilScope gil;
  PyObject *res = CallImpl(
      "recordio_reader_read",
      PyTuple_Pack(1, reinterpret_cast<PyObject *>(handle)));
  if (res == nullptr) return -1;
  /* impl returns (has_record, bytes): EOF sets *out_buf = NULL, while a
   * legitimate zero-length record yields non-NULL buf with size 0 */
  long has = PyLong_AsLong(PyTuple_GetItem(res, 0));
  PyObject *payload = PyTuple_GetItem(res, 1);
  char *data = nullptr;
  Py_ssize_t len = 0;
  PyBytes_AsStringAndSize(payload, &data, &len);
  g_record_buf.assign(data == nullptr ? "" : data,
                      static_cast<size_t>(len));
  Py_DECREF(res);
  if (has == 0) {
    *out_buf = nullptr;
    *out_size = 0;
    return 0;
  }
  *out_buf = g_record_buf.data();
  *out_size = g_record_buf.size();
  return 0;
}

int MXTPURecordIOReaderSeek(RecordIOHandle handle, size_t pos) {
  GilScope gil;
  return CallNoResult(
      "recordio_reader_seek",
      Py_BuildValue("(OK)", reinterpret_cast<PyObject *>(handle),
                    static_cast<unsigned long long>(pos)));
}

int MXTPURecordIOReaderTell(RecordIOHandle handle, size_t *out) {
  GilScope gil;
  PyObject *res = CallImpl(
      "recordio_reader_tell",
      PyTuple_Pack(1, reinterpret_cast<PyObject *>(handle)));
  if (res == nullptr) return -1;
  *out = static_cast<size_t>(PyLong_AsUnsignedLongLong(res));
  Py_DECREF(res);
  return 0;
}

int MXTPURecordIOReaderFree(RecordIOHandle handle) {
  return MXTPURecordIOWriterFree(handle);
}

/* ---- Symbol attributes + breadth (ref: MXSymbolSetAttr/GetAttr/ListAttr,
 * MXSymbolListAuxiliaryStates, MXSymbolInferShape, MXSymbolSaveToFile) ---- */

int MXTPUSymbolSetAttr(SymbolHandle handle, const char *key,
                       const char *value) {
  GilScope gil;
  return CallNoResult(
      "symbol_set_attr",
      Py_BuildValue("(Oss)", reinterpret_cast<PyObject *>(handle), key,
                    value));
}

namespace {
thread_local std::string g_attr_buf;
int StringResult(PyObject *res, const char **out) {
  if (res == nullptr) return -1;
  const char *c = PyUnicode_AsUTF8(res);
  g_attr_buf = c == nullptr ? "" : c;
  Py_DECREF(res);
  *out = g_attr_buf.c_str();
  return 0;
}
thread_local std::vector<std::string> g_strlist_store;
thread_local std::vector<const char *> g_strlist_ptrs;
int StrListResult(PyObject *res, int *out_num, const char ***out) {
  if (res == nullptr) return -1;
  g_strlist_store.clear();
  g_strlist_ptrs.clear();
  for (Py_ssize_t i = 0; i < PyTuple_Size(res); ++i) {
    const char *c = PyUnicode_AsUTF8(PyTuple_GetItem(res, i));
    g_strlist_store.emplace_back(c == nullptr ? "" : c);
  }
  Py_DECREF(res);
  for (const std::string &sname : g_strlist_store)
    g_strlist_ptrs.push_back(sname.c_str());
  *out_num = static_cast<int>(g_strlist_ptrs.size());
  *out = g_strlist_ptrs.data();
  return 0;
}
}  // namespace

int MXTPUSymbolGetAttr(SymbolHandle handle, const char *key,
                       const char **out) {
  GilScope gil;
  return StringResult(
      CallImpl("symbol_get_attr",
               Py_BuildValue("(Os)", reinterpret_cast<PyObject *>(handle),
                             key)),
      out);
}

int MXTPUSymbolListAttr(SymbolHandle handle, int *out_num,
                        const char ***out_kv) {
  GilScope gil;
  return StrListResult(
      CallImpl("symbol_list_attr",
               PyTuple_Pack(1, reinterpret_cast<PyObject *>(handle))),
      out_num, out_kv);
}

int MXTPUSymbolListOutputs(SymbolHandle handle, int *out_num,
                           const char ***out_names) {
  GilScope gil;
  return StrListResult(
      CallImpl("symbol_list_outputs",
               PyTuple_Pack(1, reinterpret_cast<PyObject *>(handle))),
      out_num, out_names);
}

int MXTPUSymbolListAuxiliaryStates(SymbolHandle handle, int *out_num,
                                   const char ***out_names) {
  GilScope gil;
  return StrListResult(
      CallImpl("symbol_list_auxiliary_states",
               PyTuple_Pack(1, reinterpret_cast<PyObject *>(handle))),
      out_num, out_names);
}

int MXTPUSymbolSaveToFile(SymbolHandle handle, const char *path) {
  GilScope gil;
  return CallNoResult(
      "symbol_save_to_file",
      Py_BuildValue("(Os)", reinterpret_cast<PyObject *>(handle), path));
}

int MXTPUSymbolCopy(SymbolHandle handle, SymbolHandle *out) {
  GilScope gil;
  return CallToHandle(
      "symbol_copy", PyTuple_Pack(1, reinterpret_cast<PyObject *>(handle)),
      out);
}

/* Shape inference: pass known input shapes; receive the OUTPUT shapes
 * flattened as (ndim, dims...) per output in the thread-local store.
 * (Arg/aux shape variants can reuse the same impl if needed.) */
namespace {
thread_local std::vector<int64_t> g_shape_flat;
}  // namespace

int MXTPUSymbolInferOutputShape(SymbolHandle handle, int num_args,
                                const char **arg_names,
                                const int64_t *arg_shape_data,
                                const int *arg_shape_ndim, int *out_num,
                                const int64_t **out_flat) {
  GilScope gil;
  PyObject *names = StrTuple(arg_names, num_args);
  PyObject *shapes = PyTuple_New(num_args);
  int off = 0;
  for (int i = 0; i < num_args; ++i) {
    PyObject *shp = PyTuple_New(arg_shape_ndim[i]);
    for (int d = 0; d < arg_shape_ndim[i]; ++d) {
      PyTuple_SetItem(shp, d, PyLong_FromLongLong(arg_shape_data[off + d]));
    }
    off += arg_shape_ndim[i];
    PyTuple_SetItem(shapes, i, shp);
  }
  PyObject *res = CallImpl(
      "symbol_infer_shape",
      Py_BuildValue("(ONN)", reinterpret_cast<PyObject *>(handle), names,
                    shapes));
  if (res == nullptr) return -1;
  PyObject *outs = PyTuple_GetItem(res, 1);  // (args, OUTS, auxs)
  g_shape_flat.clear();
  int n = static_cast<int>(PyTuple_Size(outs));
  for (int i = 0; i < n; ++i) {
    PyObject *shp = PyTuple_GetItem(outs, i);
    g_shape_flat.push_back(static_cast<int64_t>(PyTuple_Size(shp)));
    for (Py_ssize_t d = 0; d < PyTuple_Size(shp); ++d)
      g_shape_flat.push_back(PyLong_AsLongLong(PyTuple_GetItem(shp, d)));
  }
  Py_DECREF(res);
  *out_num = n;
  *out_flat = g_shape_flat.data();
  return 0;
}

/* ---- Executor monitor callback (ref: MXExecutorSetMonitorCallback) ---- */

namespace {
struct MonitorCtx {
  ExecutorMonitorCallback fn;
  void *ctx;
};

PyObject *MonitorTrampoline(PyObject *self, PyObject *args) {
  auto *mc = static_cast<MonitorCtx *>(
      PyCapsule_GetPointer(self, "mxtpu.monitor"));
  const char *name = nullptr;
  PyObject *nd = nullptr;
  if (!PyArg_ParseTuple(args, "sO", &name, &nd)) return nullptr;
  if (mc != nullptr && mc->fn != nullptr) {
    /* the NDArrayHandle is BORROWED: valid for the duration of the
     * callback only (matching the reference's monitor contract) */
    mc->fn(name, static_cast<void *>(nd), mc->ctx);
  }
  Py_RETURN_NONE;
}

void MonitorCapsuleDestruct(PyObject *capsule) {
  delete static_cast<MonitorCtx *>(
      PyCapsule_GetPointer(capsule, "mxtpu.monitor"));
}

PyMethodDef g_monitor_def = {"_mxtpu_monitor", MonitorTrampoline,
                             METH_VARARGS, nullptr};
}  // namespace

int MXTPUExecutorSetMonitorCallback(ExecutorHandle handle,
                                    ExecutorMonitorCallback callback,
                                    void *callback_ctx) {
  GilScope gil;
  auto *mc = new MonitorCtx{callback, callback_ctx};
  PyObject *capsule = PyCapsule_New(mc, "mxtpu.monitor",
                                    MonitorCapsuleDestruct);
  if (capsule == nullptr) {
    delete mc;
    SetErrorFromPython();
    return -1;
  }
  PyObject *pyfun = PyCFunction_New(&g_monitor_def, capsule);
  Py_DECREF(capsule);  // pyfun holds it now
  if (pyfun == nullptr) {
    SetErrorFromPython();
    return -1;
  }
  int rc = CallNoResult(
      "executor_set_monitor_callback",
      Py_BuildValue("(ON)", reinterpret_cast<PyObject *>(handle), pyfun));
  return rc;
}

/* ---- KVStore breadth (ref: MXKVStoreGetRank/GetGroupSize/Barrier) ---- */

int MXTPUKVStoreGetRank(KVStoreHandle handle, int *out) {
  GilScope gil;
  PyObject *res = CallImpl(
      "kvstore_get_rank",
      PyTuple_Pack(1, reinterpret_cast<PyObject *>(handle)));
  if (res == nullptr) return -1;
  *out = static_cast<int>(PyLong_AsLong(res));
  Py_DECREF(res);
  return 0;
}

int MXTPUKVStoreGetGroupSize(KVStoreHandle handle, int *out) {
  GilScope gil;
  PyObject *res = CallImpl(
      "kvstore_get_group_size",
      PyTuple_Pack(1, reinterpret_cast<PyObject *>(handle)));
  if (res == nullptr) return -1;
  *out = static_cast<int>(PyLong_AsLong(res));
  Py_DECREF(res);
  return 0;
}

int MXTPUKVStoreBarrier(KVStoreHandle handle) {
  GilScope gil;
  return CallNoResult(
      "kvstore_barrier",
      PyTuple_Pack(1, reinterpret_cast<PyObject *>(handle)));
}

int MXTPUKVStorePushPull(KVStoreHandle handle, int num, const char **keys,
                         NDArrayHandle *vals, NDArrayHandle *outs,
                         int priority) {
  GilScope gil;
  return CallNoResult(
      "kvstore_pushpull",
      Py_BuildValue("(ONNNi)", reinterpret_cast<PyObject *>(handle),
                    StrTuple(keys, num), HandleTuple(vals, num),
                    HandleTuple(outs, num), priority));
}

/* ---- misc breadth (ref: MXRandomSeed, MXNDArraySlice/Reshape,
 * MXNDArraySyncCopyFromCPU, MXNDArrayGetContext) ---- */

int MXTPURandomSeed(int seed) {
  if (!EnsureInterpreter()) return -1;
  GilScope gil;
  return CallNoResult("random_seed", Py_BuildValue("(i)", seed));
}

int MXTPUNDArraySlice(NDArrayHandle handle, int64_t begin, int64_t end,
                      NDArrayHandle *out) {
  GilScope gil;
  return CallToHandle(
      "ndarray_slice",
      Py_BuildValue("(OLL)", reinterpret_cast<PyObject *>(handle),
                    static_cast<long long>(begin),
                    static_cast<long long>(end)),
      out);
}

int MXTPUNDArrayReshape(NDArrayHandle handle, const int64_t *shape, int ndim,
                        NDArrayHandle *out) {
  GilScope gil;
  return CallToHandle(
      "ndarray_reshape",
      Py_BuildValue("(ON)", reinterpret_cast<PyObject *>(handle),
                    ShapeTuple(shape, ndim)),
      out);
}

int MXTPUNDArraySyncCopyFromCPU(NDArrayHandle handle, const void *data,
                                size_t size) {
  GilScope gil;
  PyObject *bytes = PyBytes_FromStringAndSize(
      reinterpret_cast<const char *>(data), static_cast<Py_ssize_t>(size));
  return CallNoResult(
      "ndarray_sync_copy_from_cpu",
      Py_BuildValue("(ON)", reinterpret_cast<PyObject *>(handle), bytes));
}

int MXTPUNDArrayReshape64(NDArrayHandle handle, const int64_t *shape,
                          int ndim, NDArrayHandle *out) {
  /* the reference splits 32/64-bit shape variants; this ABI is int64
   * throughout, so Reshape64 is a name-parity alias */
  return MXTPUNDArrayReshape(handle, shape, ndim, out);
}

int MXTPUNDArrayGetContext(NDArrayHandle handle, const char **out) {
  GilScope gil;
  return StringResult(
      CallImpl("ndarray_context",
               PyTuple_Pack(1, reinterpret_cast<PyObject *>(handle))),
      out);
}

/* ---- autograd breadth ---- */

namespace {
int IntResult(PyObject *res, int *out) {
  if (res == nullptr) return -1;
  *out = static_cast<int>(PyLong_AsLong(res));
  Py_DECREF(res);
  return 0;
}
}  // namespace

int MXTPUAutogradIsRecording(int *out) {
  if (!EnsureInterpreter()) return -1;
  GilScope gil;
  return IntResult(CallImpl("autograd_is_recording", PyTuple_New(0)), out);
}

int MXTPUAutogradIsTraining(int *out) {
  if (!EnsureInterpreter()) return -1;
  GilScope gil;
  return IntResult(CallImpl("autograd_is_training", PyTuple_New(0)), out);
}

int MXTPUAutogradMarkVariables(int num, NDArrayHandle *vars,
                               const int *grad_reqs) {
  GilScope gil;
  PyObject *reqs = PyTuple_New(num);
  for (int i = 0; i < num; ++i)
    PyTuple_SetItem(reqs, i, PyLong_FromLong(grad_reqs[i]));
  return CallNoResult(
      "autograd_mark_variables",
      Py_BuildValue("(NN)", HandleTuple(vars, num), reqs));
}

int MXTPUAutogradBackward(int num, NDArrayHandle *heads,
                          NDArrayHandle *ograds, int retain_graph) {
  GilScope gil;
  PyObject *og;
  if (ograds == nullptr) {
    og = PyTuple_New(0);
  } else {
    /* individual NULL entries mean a ones-like seed for that head (ref
     * MXAutogradBackwardEx) — marshal them as None, never Py_INCREF(0) */
    og = PyTuple_New(num);
    for (int i = 0; i < num; ++i) {
      PyObject *o = ograds[i] == nullptr
                        ? Py_None
                        : reinterpret_cast<PyObject *>(ograds[i]);
      Py_INCREF(o);
      PyTuple_SetItem(og, i, o);
    }
  }
  return CallNoResult(
      "autograd_backward",
      Py_BuildValue("(NNi)", HandleTuple(heads, num), og, retain_graph));
}

/* ---- CachedOp ---- */

int MXTPUCreateCachedOp(SymbolHandle sym, int num_flags,
                        const char **flag_keys, const char **flag_vals,
                        CachedOpHandle *out) {
  GilScope gil;
  return CallToHandle(
      "cached_op_create",
      Py_BuildValue("(ONN)", reinterpret_cast<PyObject *>(sym),
                    StrTuple(flag_keys, num_flags),
                    StrTuple(flag_vals, num_flags)),
      out);
}

int MXTPUInvokeCachedOp(CachedOpHandle handle, int num_inputs,
                        NDArrayHandle *inputs, int *num_outputs,
                        NDArrayHandle *outputs) {
  GilScope gil;
  PyObject *res = CallImpl(
      "cached_op_invoke",
      Py_BuildValue("(ON)", reinterpret_cast<PyObject *>(handle),
                    HandleTuple(inputs, num_inputs)));
  if (res == nullptr) return -1;
  Py_ssize_t n = PyTuple_Size(res);
  if (n > *num_outputs) {
    Py_DECREF(res);
    SetError("MXTPUInvokeCachedOp: output capacity too small");
    return -1;
  }
  *num_outputs = static_cast<int>(n);
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject *o = PyTuple_GetItem(res, i);
    Py_INCREF(o);
    outputs[i] = o;
  }
  Py_DECREF(res);
  return 0;
}

int MXTPUFreeCachedOp(CachedOpHandle handle) { return FreeHandle(handle); }

/* ---- NDArray breadth ---- */

int MXTPUNDArrayCreateNone(NDArrayHandle *out) {
  if (!EnsureInterpreter()) return -1;
  GilScope gil;
  return CallToHandle("ndarray_create_none", PyTuple_New(0), out);
}

int MXTPUNDArrayAt(NDArrayHandle handle, int64_t idx, NDArrayHandle *out) {
  GilScope gil;
  return CallToHandle(
      "ndarray_at",
      Py_BuildValue("(OL)", reinterpret_cast<PyObject *>(handle),
                    static_cast<long long>(idx)),
      out);
}

int MXTPUNDArrayDetach(NDArrayHandle handle, NDArrayHandle *out) {
  GilScope gil;
  return CallToHandle(
      "ndarray_detach",
      PyTuple_Pack(1, reinterpret_cast<PyObject *>(handle)), out);
}

int MXTPUNDArrayWaitToRead(NDArrayHandle handle) {
  GilScope gil;
  return CallNoResult(
      "ndarray_wait_to_read",
      PyTuple_Pack(1, reinterpret_cast<PyObject *>(handle)));
}

int MXTPUNDArrayWaitToWrite(NDArrayHandle handle) {
  GilScope gil;
  return CallNoResult(
      "ndarray_wait_to_write",
      PyTuple_Pack(1, reinterpret_cast<PyObject *>(handle)));
}

int MXTPUNDArrayGetStorageType(NDArrayHandle handle, int *out) {
  GilScope gil;
  return IntResult(
      CallImpl("ndarray_storage_type",
               PyTuple_Pack(1, reinterpret_cast<PyObject *>(handle))),
      out);
}

namespace {
thread_local std::string g_raw_bytes_store;
}  // namespace

int MXTPUNDArraySaveRawBytes(NDArrayHandle handle, size_t *out_size,
                             const char **out_buf) {
  GilScope gil;
  PyObject *res = CallImpl(
      "ndarray_save_raw_bytes",
      PyTuple_Pack(1, reinterpret_cast<PyObject *>(handle)));
  if (res == nullptr) return -1;
  char *buf = nullptr;
  Py_ssize_t len = 0;
  if (PyBytes_AsStringAndSize(res, &buf, &len) != 0) {
    SetErrorFromPython();
    Py_DECREF(res);
    return -1;
  }
  g_raw_bytes_store.assign(buf, static_cast<size_t>(len));
  Py_DECREF(res);
  *out_size = g_raw_bytes_store.size();
  *out_buf = g_raw_bytes_store.data();
  return 0;
}

int MXTPUNDArrayLoadFromRawBytes(const void *buf, size_t size,
                                 NDArrayHandle *out) {
  if (!EnsureInterpreter()) return -1;
  GilScope gil;
  PyObject *bytes = PyBytes_FromStringAndSize(
      reinterpret_cast<const char *>(buf), static_cast<Py_ssize_t>(size));
  return CallToHandle("ndarray_load_from_raw_bytes",
                      Py_BuildValue("(N)", bytes), out);
}

int MXTPUNDArrayLoadFromBuffer(const void *buf, size_t size, int *out_num,
                               NDArrayHandle **out_handles,
                               int *out_num_names, const char ***out_names) {
  if (!EnsureInterpreter()) return -1;
  GilScope gil;
  PyObject *bytes = PyBytes_FromStringAndSize(
      reinterpret_cast<const char *>(buf), static_cast<Py_ssize_t>(size));
  return LoadResultOut(CallImpl("ndarray_load_from_buffer",
                                Py_BuildValue("(N)", bytes)),
                       out_num, out_handles, out_num_names, out_names);
}

int MXTPUNDArraySyncCopyFromNDArray(NDArrayHandle dst, NDArrayHandle src) {
  GilScope gil;
  return CallNoResult(
      "ndarray_sync_copy_from_ndarray",
      PyTuple_Pack(2, reinterpret_cast<PyObject *>(dst),
                   reinterpret_cast<PyObject *>(src)));
}

int MXTPUNDArraySyncCheckFormat(NDArrayHandle handle, int full_check) {
  GilScope gil;
  return CallNoResult(
      "ndarray_sync_check_format",
      Py_BuildValue("(Oi)", reinterpret_cast<PyObject *>(handle),
                    full_check));
}

int MXTPUNDArrayCreateSparseEx(int stype, NDArrayHandle data, int num_aux,
                               NDArrayHandle *aux, const int64_t *shape,
                               int ndim, NDArrayHandle *out) {
  GilScope gil;
  return CallToHandle(
      "ndarray_create_sparse",
      Py_BuildValue("(iONN)", stype, reinterpret_cast<PyObject *>(data),
                    HandleTuple(aux, num_aux), ShapeTuple(shape, ndim)),
      out);
}

int MXTPUNDArrayGetDataNDArray(NDArrayHandle handle, NDArrayHandle *out) {
  GilScope gil;
  return CallToHandle(
      "ndarray_get_data_ndarray",
      PyTuple_Pack(1, reinterpret_cast<PyObject *>(handle)), out);
}

int MXTPUNDArrayGetAuxNDArray(NDArrayHandle handle, int i,
                              NDArrayHandle *out) {
  GilScope gil;
  return CallToHandle(
      "ndarray_get_aux_ndarray",
      Py_BuildValue("(Oi)", reinterpret_cast<PyObject *>(handle), i), out);
}

int MXTPUNDArrayGetAuxType(NDArrayHandle handle, int i, int *out_flag) {
  GilScope gil;
  return IntResult(
      CallImpl("ndarray_get_aux_type",
               Py_BuildValue("(Oi)", reinterpret_cast<PyObject *>(handle),
                             i)),
      out_flag);
}

/* ---- Symbol breadth II ---- */

int MXTPUSymbolCreateAtomicSymbol(const char *op_name, int num_attrs,
                                  const char **attr_keys,
                                  const char **attr_vals, SymbolHandle *out) {
  if (!EnsureInterpreter()) return -1;
  GilScope gil;
  return CallToHandle(
      "symbol_create_atomic",
      Py_BuildValue("(sN)", op_name,
                    AttrDict(attr_keys, attr_vals, num_attrs)),
      out);
}

int MXTPUSymbolCreateGroup(int num, SymbolHandle *syms, SymbolHandle *out) {
  GilScope gil;
  return CallToHandle("symbol_create_group",
                      Py_BuildValue("(N)", HandleTuple(syms, num)), out);
}

int MXTPUSymbolGetInternals(SymbolHandle handle, SymbolHandle *out) {
  GilScope gil;
  return CallToHandle(
      "symbol_get_internals",
      PyTuple_Pack(1, reinterpret_cast<PyObject *>(handle)), out);
}

int MXTPUSymbolGetOutput(SymbolHandle handle, int index, SymbolHandle *out) {
  GilScope gil;
  return CallToHandle(
      "symbol_get_output",
      Py_BuildValue("(Oi)", reinterpret_cast<PyObject *>(handle), index),
      out);
}

int MXTPUSymbolGetNumOutputs(SymbolHandle handle, int *out) {
  GilScope gil;
  return IntResult(
      CallImpl("symbol_get_num_outputs",
               PyTuple_Pack(1, reinterpret_cast<PyObject *>(handle))),
      out);
}

int MXTPUSymbolGetName(SymbolHandle handle, const char **out, int *success) {
  GilScope gil;
  PyObject *res = CallImpl(
      "symbol_get_name",
      PyTuple_Pack(1, reinterpret_cast<PyObject *>(handle)));
  if (res == nullptr) return -1;
  *success = static_cast<int>(PyLong_AsLong(PyTuple_GetItem(res, 0)));
  const char *c = PyUnicode_AsUTF8(PyTuple_GetItem(res, 1));
  g_attr_buf = c == nullptr ? "" : c;
  Py_DECREF(res);
  *out = g_attr_buf.c_str();
  return 0;
}

int MXTPUSymbolGetChildren(SymbolHandle handle, SymbolHandle *out) {
  GilScope gil;
  return CallToHandle(
      "symbol_get_children",
      PyTuple_Pack(1, reinterpret_cast<PyObject *>(handle)), out);
}

namespace {
thread_local std::vector<int> g_type_args, g_type_outs, g_type_auxs;

void FillFlags(PyObject *t, std::vector<int> *dst) {
  dst->clear();
  for (Py_ssize_t i = 0; i < PyTuple_Size(t); ++i)
    dst->push_back(static_cast<int>(PyLong_AsLong(PyTuple_GetItem(t, i))));
}
}  // namespace

int MXTPUSymbolInferType(SymbolHandle handle, int num_args,
                         const char **arg_names, const int *arg_type_flags,
                         int *out_arg_num, const int **out_arg_flags,
                         int *out_out_num, const int **out_out_flags,
                         int *out_aux_num, const int **out_aux_flags) {
  GilScope gil;
  PyObject *flags = PyTuple_New(num_args);
  for (int i = 0; i < num_args; ++i)
    PyTuple_SetItem(flags, i, PyLong_FromLong(arg_type_flags[i]));
  PyObject *res = CallImpl(
      "symbol_infer_type",
      Py_BuildValue("(ONN)", reinterpret_cast<PyObject *>(handle),
                    StrTuple(arg_names, num_args), flags));
  if (res == nullptr) return -1;
  FillFlags(PyTuple_GetItem(res, 0), &g_type_args);
  FillFlags(PyTuple_GetItem(res, 1), &g_type_outs);
  FillFlags(PyTuple_GetItem(res, 2), &g_type_auxs);
  Py_DECREF(res);
  *out_arg_num = static_cast<int>(g_type_args.size());
  *out_arg_flags = g_type_args.data();
  *out_out_num = static_cast<int>(g_type_outs.size());
  *out_out_flags = g_type_outs.data();
  *out_aux_num = static_cast<int>(g_type_auxs.size());
  *out_aux_flags = g_type_auxs.data();
  return 0;
}

namespace {
thread_local std::vector<int64_t> g_partial_shape_flat;

PyObject *PackShapes(int num, const char **names, const int64_t *shape_data,
                     const int *shape_ndim, PyObject **out_names) {
  *out_names = StrTuple(names, num);
  PyObject *shapes = PyTuple_New(num);
  int off = 0;
  for (int i = 0; i < num; ++i) {
    PyObject *shp = PyTuple_New(shape_ndim[i]);
    for (int d = 0; d < shape_ndim[i]; ++d)
      PyTuple_SetItem(shp, d, PyLong_FromLongLong(shape_data[off + d]));
    off += shape_ndim[i];
    PyTuple_SetItem(shapes, i, shp);
  }
  return shapes;
}
}  // namespace

int MXTPUSymbolInferShapePartial(SymbolHandle handle, int num_args,
                                 const char **arg_names,
                                 const int64_t *arg_shape_data,
                                 const int *arg_shape_ndim, int *out_num,
                                 const int64_t **out_flat) {
  GilScope gil;
  PyObject *names = nullptr;
  PyObject *shapes = PackShapes(num_args, arg_names, arg_shape_data,
                                arg_shape_ndim, &names);
  PyObject *res = CallImpl(
      "symbol_infer_shape_partial",
      Py_BuildValue("(ONN)", reinterpret_cast<PyObject *>(handle), names,
                    shapes));
  if (res == nullptr) return -1;
  PyObject *outs = PyTuple_GetItem(res, 1);
  g_partial_shape_flat.clear();
  int n = static_cast<int>(PyTuple_Size(outs));
  for (int i = 0; i < n; ++i) {
    PyObject *shp = PyTuple_GetItem(outs, i);
    g_partial_shape_flat.push_back(static_cast<int64_t>(PyTuple_Size(shp)));
    for (Py_ssize_t d = 0; d < PyTuple_Size(shp); ++d)
      g_partial_shape_flat.push_back(
          PyLong_AsLongLong(PyTuple_GetItem(shp, d)));
  }
  Py_DECREF(res);
  *out_num = n;
  *out_flat = g_partial_shape_flat.data();
  return 0;
}

int MXTPUSymbolListAtomicSymbolCreators(int *out_num,
                                        const char ***out_names) {
  if (!EnsureInterpreter()) return -1;
  GilScope gil;
  return StrListResult(
      CallImpl("symbol_list_atomic_creators", PyTuple_New(0)), out_num,
      out_names);
}

int MXTPUSymbolPrint(SymbolHandle handle, const char **out) {
  GilScope gil;
  return StringResult(
      CallImpl("symbol_print",
               PyTuple_Pack(1, reinterpret_cast<PyObject *>(handle))),
      out);
}

int MXTPUSymbolSaveToJSON(SymbolHandle handle, const char **out_json) {
  return MXTPUSymbolToJSON(handle, out_json);
}

/* ---- Executor breadth ---- */

int MXTPUExecutorSimpleBind(SymbolHandle sym, int num_inputs,
                            const char **input_names,
                            const int64_t *shape_data, const int *shape_ndim,
                            const char *grad_req, ExecutorHandle *out) {
  GilScope gil;
  PyObject *names = nullptr;
  PyObject *shapes = PackShapes(num_inputs, input_names, shape_data,
                                shape_ndim, &names);
  return CallToHandle(
      "executor_simple_bind",
      Py_BuildValue("(ONNs)", reinterpret_cast<PyObject *>(sym), names,
                    shapes, grad_req == nullptr ? "write" : grad_req),
      out);
}

int MXTPUExecutorReshape(ExecutorHandle handle, int num_inputs,
                         const char **input_names, const int64_t *shape_data,
                         const int *shape_ndim, ExecutorHandle *out) {
  GilScope gil;
  PyObject *names = nullptr;
  PyObject *shapes = PackShapes(num_inputs, input_names, shape_data,
                                shape_ndim, &names);
  return CallToHandle(
      "executor_reshape",
      Py_BuildValue("(ONN)", reinterpret_cast<PyObject *>(handle), names,
                    shapes),
      out);
}

int MXTPUExecutorPrint(ExecutorHandle handle, const char **out) {
  GilScope gil;
  return StringResult(
      CallImpl("executor_print",
               PyTuple_Pack(1, reinterpret_cast<PyObject *>(handle))),
      out);
}

int MXTPUExecutorOutputs(ExecutorHandle handle, int *num,
                         NDArrayHandle *outs) {
  GilScope gil;
  PyObject *res = CallImpl(
      "executor_outputs",
      PyTuple_Pack(1, reinterpret_cast<PyObject *>(handle)));
  if (res == nullptr) return -1;
  Py_ssize_t n = PyTuple_Size(res);
  if (n > *num) {
    Py_DECREF(res);
    SetError("MXTPUExecutorOutputs: capacity too small");
    return -1;
  }
  *num = static_cast<int>(n);
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject *o = PyTuple_GetItem(res, i);
    Py_INCREF(o);
    outs[i] = o;
  }
  Py_DECREF(res);
  return 0;
}

/* ---- KVStore breadth II ---- */

int MXTPUKVStoreGetType(KVStoreHandle handle, const char **out) {
  GilScope gil;
  return StringResult(
      CallImpl("kvstore_get_type",
               PyTuple_Pack(1, reinterpret_cast<PyObject *>(handle))),
      out);
}

namespace {
struct UpdaterCtx {
  MXTPUKVStoreUpdater fn;
  void *ctx;
};

PyObject *UpdaterTrampoline(PyObject *self, PyObject *args) {
  auto *uc = static_cast<UpdaterCtx *>(
      PyCapsule_GetPointer(self, "mxtpu.updater"));
  PyObject *keyobj = nullptr, *recv = nullptr, *local = nullptr;
  if (!PyArg_ParseTuple(args, "OOO", &keyobj, &recv, &local)) return nullptr;
  /* kvstore.py passes int-convertible keys as int and keeps named keys
   * as str — an int-key C updater cannot receive "fc1_weight" */
  long key = 0;
  if (PyLong_Check(keyobj)) {
    key = PyLong_AsLong(keyobj);
  } else {
    PyObject *as_int = PyNumber_Long(keyobj);
    if (as_int == nullptr) {
      PyErr_Clear();
      PyErr_Format(PyExc_TypeError,
                   "non-numeric kvstore key %R reached the int-key "
                   "updater; register MXTPUKVStoreSetUpdaterEx for "
                   "string keys",
                   keyobj);
      return nullptr;
    }
    key = PyLong_AsLong(as_int);
    Py_DECREF(as_int);
  }
  if (uc != nullptr && uc->fn != nullptr) {
    /* recv/local are BORROWED handles, valid for this call only */
    uc->fn(static_cast<int>(key), static_cast<void *>(recv),
           static_cast<void *>(local), uc->ctx);
  }
  Py_RETURN_NONE;
}

struct StrUpdaterCtx {
  MXTPUKVStoreStrUpdater fn;
  void *ctx;
};

PyObject *StrUpdaterTrampoline(PyObject *self, PyObject *args) {
  auto *uc = static_cast<StrUpdaterCtx *>(
      PyCapsule_GetPointer(self, "mxtpu.str_updater"));
  PyObject *keyobj = nullptr, *recv = nullptr, *local = nullptr;
  if (!PyArg_ParseTuple(args, "OOO", &keyobj, &recv, &local)) return nullptr;
  PyObject *keystr = PyObject_Str(keyobj);
  if (keystr == nullptr) return nullptr;
  const char *key = PyUnicode_AsUTF8(keystr);
  if (uc != nullptr && uc->fn != nullptr && key != nullptr) {
    uc->fn(key, static_cast<void *>(recv), static_cast<void *>(local),
           uc->ctx);
  }
  Py_DECREF(keystr);
  Py_RETURN_NONE;
}

void UpdaterCapsuleDestruct(PyObject *capsule) {
  delete static_cast<UpdaterCtx *>(
      PyCapsule_GetPointer(capsule, "mxtpu.updater"));
}

void StrUpdaterCapsuleDestruct(PyObject *capsule) {
  delete static_cast<StrUpdaterCtx *>(
      PyCapsule_GetPointer(capsule, "mxtpu.str_updater"));
}

PyMethodDef g_updater_def = {"_mxtpu_updater", UpdaterTrampoline,
                             METH_VARARGS, nullptr};
PyMethodDef g_str_updater_def = {"_mxtpu_str_updater", StrUpdaterTrampoline,
                                 METH_VARARGS, nullptr};
}  // namespace

int MXTPUKVStoreSetUpdater(KVStoreHandle handle, MXTPUKVStoreUpdater updater,
                           void *ctx) {
  GilScope gil;
  auto *uc = new UpdaterCtx{updater, ctx};
  PyObject *capsule =
      PyCapsule_New(uc, "mxtpu.updater", UpdaterCapsuleDestruct);
  if (capsule == nullptr) {
    delete uc;
    SetErrorFromPython();
    return -1;
  }
  PyObject *pyfun = PyCFunction_New(&g_updater_def, capsule);
  Py_DECREF(capsule);
  if (pyfun == nullptr) {
    SetErrorFromPython();
    return -1;
  }
  return CallNoResult(
      "kvstore_set_updater",
      Py_BuildValue("(ON)", reinterpret_cast<PyObject *>(handle), pyfun));
}

int MXTPUKVStoreSetUpdaterEx(KVStoreHandle handle,
                             MXTPUKVStoreStrUpdater updater, void *ctx) {
  GilScope gil;
  auto *uc = new StrUpdaterCtx{updater, ctx};
  PyObject *capsule =
      PyCapsule_New(uc, "mxtpu.str_updater", StrUpdaterCapsuleDestruct);
  if (capsule == nullptr) {
    delete uc;
    SetErrorFromPython();
    return -1;
  }
  PyObject *pyfun = PyCFunction_New(&g_str_updater_def, capsule);
  Py_DECREF(capsule);
  if (pyfun == nullptr) {
    SetErrorFromPython();
    return -1;
  }
  return CallNoResult(
      "kvstore_set_updater",
      Py_BuildValue("(ON)", reinterpret_cast<PyObject *>(handle), pyfun));
}

int MXTPUKVStoreSetGradientCompression(KVStoreHandle handle, int num,
                                       const char **keys,
                                       const char **vals) {
  GilScope gil;
  return CallNoResult(
      "kvstore_set_gradient_compression",
      Py_BuildValue("(ONN)", reinterpret_cast<PyObject *>(handle),
                    StrTuple(keys, num), StrTuple(vals, num)));
}

int MXTPUKVStorePullRowSparse(KVStoreHandle handle, int num,
                              const char **keys, NDArrayHandle *outs,
                              NDArrayHandle *row_ids, int priority) {
  GilScope gil;
  return CallNoResult(
      "kvstore_pull_row_sparse",
      Py_BuildValue("(ONNNi)", reinterpret_cast<PyObject *>(handle),
                    StrTuple(keys, num), HandleTuple(outs, num),
                    HandleTuple(row_ids, num), priority));
}

int MXTPUKVStoreGetNumDeadNode(KVStoreHandle handle, int node_id, int *out) {
  GilScope gil;
  return IntResult(
      CallImpl("kvstore_get_num_dead_node",
               Py_BuildValue("(Oi)", reinterpret_cast<PyObject *>(handle),
                             node_id)),
      out);
}

int MXTPUKVStoreIsWorkerNode(int *out) {
  if (!EnsureInterpreter()) return -1;
  GilScope gil;
  return IntResult(CallImpl("kvstore_is_worker_node", PyTuple_New(0)), out);
}

int MXTPUKVStoreIsServerNode(int *out) {
  if (!EnsureInterpreter()) return -1;
  GilScope gil;
  return IntResult(CallImpl("kvstore_is_server_node", PyTuple_New(0)), out);
}

int MXTPUKVStoreIsSchedulerNode(int *out) {
  if (!EnsureInterpreter()) return -1;
  GilScope gil;
  return IntResult(CallImpl("kvstore_is_scheduler_node", PyTuple_New(0)),
                   out);
}

/* ---- profiler ---- */

int MXTPUSetProfilerConfig(int num, const char **keys, const char **vals) {
  if (!EnsureInterpreter()) return -1;
  GilScope gil;
  return CallNoResult(
      "profiler_set_config",
      Py_BuildValue("(NN)", StrTuple(keys, num), StrTuple(vals, num)));
}

int MXTPUSetProfilerState(int state) {
  if (!EnsureInterpreter()) return -1;
  GilScope gil;
  return CallNoResult("profiler_set_state", Py_BuildValue("(i)", state));
}

int MXTPUDumpProfile(int finished) {
  if (!EnsureInterpreter()) return -1;
  GilScope gil;
  return CallNoResult("profiler_dump", Py_BuildValue("(i)", finished));
}

int MXTPUProfilePause(int paused) {
  if (!EnsureInterpreter()) return -1;
  GilScope gil;
  return CallNoResult("profiler_pause", Py_BuildValue("(i)", paused));
}

/* ---- runtime kernel compilation (ref: MXRtcCudaModuleCreate /
 * MXRtcCudaKernelCreate / MXRtcCudaKernelCall over NVRTC; here the
 * source is Python defining Pallas kernels — mxtpu/rtc.py) ---- */

int MXTPURtcModuleCreate(const char *source, int num_exports,
                         const char **exports, RtcHandle *out) {
  if (!EnsureInterpreter()) return -1;
  GilScope gil;
  PyObject *exp = exports == nullptr ? PyTuple_New(0)
                                     : StrTuple(exports, num_exports);
  return CallToHandle("rtc_module_create",
                      Py_BuildValue("(sN)", source, exp), out);
}

int MXTPURtcModuleFree(RtcHandle handle) { return FreeHandle(handle); }

int MXTPURtcKernelCreate(RtcHandle module, const char *name,
                         int num_outputs, RtcHandle *out) {
  GilScope gil;
  return CallToHandle(
      "rtc_kernel_create",
      Py_BuildValue("(Osi)", reinterpret_cast<PyObject *>(module), name,
                    num_outputs),
      out);
}

int MXTPURtcKernelFree(RtcHandle handle) { return FreeHandle(handle); }

int MXTPURtcKernelCall(RtcHandle kernel, int num_inputs,
                       NDArrayHandle *inputs, int num_outputs,
                       const int64_t *out_shape_data,
                       const int *out_shape_ndim,
                       const int *out_dtype_flags, NDArrayHandle *outputs) {
  GilScope gil;
  PyObject *shapes = PyTuple_New(num_outputs);
  int off = 0;
  for (int i = 0; i < num_outputs; ++i) {
    PyTuple_SetItem(shapes, i,
                    ShapeTuple(out_shape_data + off, out_shape_ndim[i]));
    off += out_shape_ndim[i];
  }
  PyObject *flags = PyTuple_New(num_outputs);
  for (int i = 0; i < num_outputs; ++i)
    PyTuple_SetItem(flags, i, PyLong_FromLong(out_dtype_flags[i]));
  PyObject *res = CallImpl(
      "rtc_kernel_call",
      Py_BuildValue("(ONNN)", reinterpret_cast<PyObject *>(kernel),
                    HandleTuple(inputs, num_inputs), shapes, flags));
  if (res == nullptr) return -1;
  if (PyTuple_Size(res) != num_outputs) {
    Py_DECREF(res);
    SetError("MXTPURtcKernelCall: output count mismatch");
    return -1;
  }
  for (int i = 0; i < num_outputs; ++i) {
    PyObject *o = PyTuple_GetItem(res, i);
    Py_INCREF(o);
    outputs[i] = o;
  }
  Py_DECREF(res);
  return 0;
}

/* ---- profiler object family (ref: MXProfileCreate* / Duration* /
 * SetCounter / AdjustCounter / SetMarker / MXAggregateProfileStatsPrint,
 * src/c_api/c_api_profile.cc) ---- */

int MXTPUProfileCreateDomain(const char *name, ProfileHandle *out) {
  if (!EnsureInterpreter()) return -1;
  GilScope gil;
  return CallToHandle("profile_create_domain", Py_BuildValue("(s)", name),
                      out);
}

int MXTPUProfileCreateTask(ProfileHandle domain, const char *name,
                           ProfileHandle *out) {
  GilScope gil;
  return CallToHandle(
      "profile_create_task",
      Py_BuildValue("(Os)", reinterpret_cast<PyObject *>(domain), name),
      out);
}

int MXTPUProfileCreateFrame(ProfileHandle domain, const char *name,
                            ProfileHandle *out) {
  GilScope gil;
  return CallToHandle(
      "profile_create_frame",
      Py_BuildValue("(Os)", reinterpret_cast<PyObject *>(domain), name),
      out);
}

int MXTPUProfileCreateEvent(const char *name, ProfileHandle *out) {
  if (!EnsureInterpreter()) return -1;
  GilScope gil;
  return CallToHandle("profile_create_event", Py_BuildValue("(s)", name),
                      out);
}

int MXTPUProfileCreateCounter(ProfileHandle domain, const char *name,
                              ProfileHandle *out) {
  GilScope gil;
  return CallToHandle(
      "profile_create_counter",
      Py_BuildValue("(Os)", reinterpret_cast<PyObject *>(domain), name),
      out);
}

int MXTPUProfileDestroyHandle(ProfileHandle handle) {
  if (handle != nullptr) {
    GilScope gil;
    /* deregister counters from the aggregate table before dropping the
     * ref (best-effort: a failure here must not block the free) */
    PyObject *res = CallImpl(
        "profile_destroy",
        PyTuple_Pack(1, reinterpret_cast<PyObject *>(handle)));
    Py_XDECREF(res);
    if (res == nullptr) PyErr_Clear();
  }
  return FreeHandle(handle);
}

int MXTPUProfileDurationStart(ProfileHandle handle) {
  GilScope gil;
  return CallNoResult(
      "profile_duration_start",
      PyTuple_Pack(1, reinterpret_cast<PyObject *>(handle)));
}

int MXTPUProfileDurationStop(ProfileHandle handle) {
  GilScope gil;
  return CallNoResult(
      "profile_duration_stop",
      PyTuple_Pack(1, reinterpret_cast<PyObject *>(handle)));
}

int MXTPUProfileSetCounter(ProfileHandle handle, uint64_t value) {
  GilScope gil;
  return CallNoResult(
      "profile_set_counter",
      Py_BuildValue("(OK)", reinterpret_cast<PyObject *>(handle),
                    static_cast<unsigned long long>(value)));
}

int MXTPUProfileAdjustCounter(ProfileHandle handle, int64_t delta) {
  GilScope gil;
  return CallNoResult(
      "profile_adjust_counter",
      Py_BuildValue("(OL)", reinterpret_cast<PyObject *>(handle),
                    static_cast<long long>(delta)));
}

int MXTPUProfileSetMarker(ProfileHandle domain, const char *name,
                          const char *scope) {
  GilScope gil;
  return CallNoResult(
      "profile_set_marker",
      Py_BuildValue("(Osz)", reinterpret_cast<PyObject *>(domain), name,
                    scope));
}

int MXTPUAggregateProfileStatsPrint(const char **out_str, int reset) {
  if (!EnsureInterpreter()) return -1;
  GilScope gil;
  return StringResult(
      CallImpl("profile_aggregate_stats", Py_BuildValue("(i)", reset)),
      out_str);
}

int MXTPUSymbolListAttrShallow(SymbolHandle handle, int *out_num,
                               const char ***out_kv) {
  /* this runtime's ListAttr is already shallow (per-node attrs only) —
   * name-parity alias (ref MXSymbolListAttrShallow) */
  return MXTPUSymbolListAttr(handle, out_num, out_kv);
}

int MXTPUExecutorBackwardEx(ExecutorHandle handle, int num_ograds,
                            NDArrayHandle *ograds) {
  GilScope gil;
  PyObject *og;
  if (ograds == nullptr) {
    og = PyTuple_New(0);
  } else {
    /* per-entry NULL = ones-like seed (ref MXExecutorBackwardEx); never
     * Py_INCREF(0) — same nullable marshaling as MXTPUAutogradBackward */
    og = PyTuple_New(num_ograds);
    for (int i = 0; i < num_ograds; ++i) {
      PyObject *o = ograds[i] == nullptr
                        ? Py_None
                        : reinterpret_cast<PyObject *>(ograds[i]);
      Py_INCREF(o);
      PyTuple_SetItem(og, i, o);
    }
  }
  return CallNoResult(
      "executor_backward_ex",
      Py_BuildValue("(ON)", reinterpret_cast<PyObject *>(handle), og));
}

int MXTPUNDArraySetGradState(NDArrayHandle handle, int state) {
  GilScope gil;
  return CallNoResult(
      "ndarray_set_grad_state",
      Py_BuildValue("(Oi)", reinterpret_cast<PyObject *>(handle), state));
}

int MXTPUNDArrayGetGradState(NDArrayHandle handle, int *out) {
  GilScope gil;
  return IntResult(
      CallImpl("ndarray_get_grad_state",
               PyTuple_Pack(1, reinterpret_cast<PyObject *>(handle))),
      out);
}

/* ---- process-profiler variants (ref: MXSetProcessProfilerConfig /
 * MXSetProcessProfilerState / MXDumpProcessProfile /
 * MXProcessProfilePause). The reference routes these to a server
 * process by id; this runtime is symmetric single-role (every process
 * is a worker — README ADR), so profile_process selects nothing and
 * the variants alias the worker-profiler calls. ---- */

int MXTPUSetProcessProfilerConfig(int num, const char **keys,
                                  const char **vals, int profile_process) {
  (void)profile_process;
  return MXTPUSetProfilerConfig(num, keys, vals);
}

int MXTPUSetProcessProfilerState(int state, int profile_process) {
  (void)profile_process;
  return MXTPUSetProfilerState(state);
}

int MXTPUDumpProcessProfile(int finished, int profile_process) {
  (void)profile_process;
  return MXTPUDumpProfile(finished);
}

int MXTPUProcessProfilePause(int paused, int profile_process) {
  (void)profile_process;
  return MXTPUProfilePause(paused);
}

/* ---- runtime/introspection breadth ---- */

int MXTPUGetDeviceCount(int *out) {
  if (!EnsureInterpreter()) return -1;
  GilScope gil;
  return IntResult(CallImpl("get_device_count", PyTuple_New(0)), out);
}

int MXTPUGetMemoryInformation(int dev_id, uint64_t *free_bytes,
                              uint64_t *total_bytes) {
  if (!EnsureInterpreter()) return -1;
  GilScope gil;
  PyObject *res = CallImpl("get_memory_information",
                           Py_BuildValue("(i)", dev_id));
  if (res == nullptr) return -1;
  *free_bytes = PyLong_AsUnsignedLongLong(PyTuple_GetItem(res, 0));
  *total_bytes = PyLong_AsUnsignedLongLong(PyTuple_GetItem(res, 1));
  Py_DECREF(res);
  return 0;
}

int MXTPUNotifyShutdown(void) {
  if (!EnsureInterpreter()) return -1;
  GilScope gil;
  return CallNoResult("notify_shutdown", PyTuple_New(0));
}

int MXTPUEngineSetBulkSize(int size, int *prev) {
  if (!EnsureInterpreter()) return -1;
  GilScope gil;
  PyObject *res = CallImpl("engine_set_bulk_size",
                           Py_BuildValue("(i)", size));
  if (res == nullptr) return -1;
  if (prev != nullptr) *prev = static_cast<int>(PyLong_AsLong(res));
  Py_DECREF(res);
  return 0;
}

int MXTPUSetNumOMPThreads(int num) {
  if (!EnsureInterpreter()) return -1;
  GilScope gil;
  return CallNoResult("set_num_omp_threads", Py_BuildValue("(i)", num));
}

int MXTPURandomSeedContext(int seed, int dev_type, int dev_id) {
  if (!EnsureInterpreter()) return -1;
  GilScope gil;
  return CallNoResult("random_seed_context",
                      Py_BuildValue("(iii)", seed, dev_type, dev_id));
}

/* ---- DLPack interchange (ref: MXNDArrayToDLPack / MXNDArrayFromDLPack
 * / MXNDArrayCallDLPackDeleter, src/c_api/c_api.cc) ---- */

extern "C" {
/* minimal stable DLPack v0.x layout (dlpack/dlpack.h) */
typedef struct {
  void *data;
  struct {
    int32_t device_type;
    int32_t device_id;
  } device;
  int32_t ndim;
  struct {
    uint8_t code;
    uint8_t bits;
    uint16_t lanes;
  } dtype;
  int64_t *shape;
  int64_t *strides;
  uint64_t byte_offset;
} MXTPUDLTensor;

typedef struct MXTPUDLManagedTensor {
  MXTPUDLTensor dl_tensor;
  void *manager_ctx;
  void (*deleter)(struct MXTPUDLManagedTensor *self);
} MXTPUDLManagedTensor;
}

int MXTPUNDArrayToDLPack(NDArrayHandle handle, void **out_dlmanaged) {
  GilScope gil;
  PyObject *capsule = CallImpl(
      "ndarray_to_dlpack",
      PyTuple_Pack(1, reinterpret_cast<PyObject *>(handle)));
  if (capsule == nullptr) return -1;
  void *ptr = PyCapsule_GetPointer(capsule, "dltensor");
  if (ptr == nullptr) {
    SetErrorFromPython();
    Py_DECREF(capsule);
    return -1;
  }
  /* ownership moves to the caller: rename so the capsule destructor
   * (if any) will not double-free, then drop the capsule */
  PyCapsule_SetName(capsule, "used_dltensor");
  Py_DECREF(capsule);
  *out_dlmanaged = ptr;
  return 0;
}

int MXTPUNDArrayFromDLPack(void *dlmanaged, NDArrayHandle *out) {
  if (!EnsureInterpreter()) return -1;
  GilScope gil;
  PyObject *capsule = PyCapsule_New(dlmanaged, "dltensor", nullptr);
  if (capsule == nullptr) {
    SetErrorFromPython();
    return -1;
  }
  /* the importer renames the capsule and takes ownership (calls the
   * deleter when done); on failure ownership stays with the caller */
  int rc = CallToHandle("ndarray_from_dlpack",
                        PyTuple_Pack(1, capsule), out);
  Py_DECREF(capsule);
  return rc;
}

int MXTPUNDArrayCallDLPackDeleter(void *dlmanaged) {
  if (dlmanaged == nullptr) return 0;
  /* the deleter may be numpy's (host-copy fallback export) and touch
   * refcounts — hold the GIL like every other entry point */
  GilScope gil;
  auto *dlm = static_cast<MXTPUDLManagedTensor *>(dlmanaged);
  if (dlm->deleter != nullptr) dlm->deleter(dlm);
  return 0;
}

/* ---- shared-memory NDArrays (name-addressed POSIX segments; the
 * reference's (pid, fd) addressing is Linux-ashmem-specific) ---- */

int MXTPUNDArrayGetSharedMemHandle(NDArrayHandle handle,
                                   const char **out_name) {
  GilScope gil;
  return StringResult(
      CallImpl("ndarray_get_shared_mem_handle",
               PyTuple_Pack(1, reinterpret_cast<PyObject *>(handle))),
      out_name);
}

int MXTPUNDArrayCreateFromSharedMem(const char *name, int dtype_flag,
                                    const int64_t *shape, int ndim,
                                    NDArrayHandle *out) {
  if (!EnsureInterpreter()) return -1;
  GilScope gil;
  return CallToHandle(
      "ndarray_create_from_shared_mem",
      Py_BuildValue("(siN)", name, dtype_flag, ShapeTuple(shape, ndim)),
      out);
}

/* ---- DataIter breadth ---- */

namespace {
thread_local std::vector<uint64_t> g_iter_index_store;
}  // namespace

int MXTPUDataIterGetIndex(DataIterHandle handle, uint64_t **out_index,
                          uint64_t *out_size) {
  GilScope gil;
  PyObject *res = CallImpl(
      "data_iter_get_index",
      PyTuple_Pack(1, reinterpret_cast<PyObject *>(handle)));
  if (res == nullptr) return -1;
  g_iter_index_store.clear();
  for (Py_ssize_t i = 0; i < PyTuple_Size(res); ++i)
    g_iter_index_store.push_back(
        PyLong_AsUnsignedLongLong(PyTuple_GetItem(res, i)));
  Py_DECREF(res);
  *out_size = g_iter_index_store.size();
  *out_index = g_iter_index_store.data();
  return 0;
}

namespace {
thread_local std::string g_iter_info_name, g_iter_info_desc;
}  // namespace

int MXTPUDataIterGetIterInfo(const char *name, const char **out_name,
                             const char **out_desc) {
  if (!EnsureInterpreter()) return -1;
  GilScope gil;
  PyObject *res = CallImpl("data_iter_get_iter_info",
                           Py_BuildValue("(s)", name));
  if (res == nullptr) return -1;
  const char *n = PyUnicode_AsUTF8(PyTuple_GetItem(res, 0));
  const char *d = PyUnicode_AsUTF8(PyTuple_GetItem(res, 1));
  g_iter_info_name = n == nullptr ? "" : n;
  g_iter_info_desc = d == nullptr ? "" : d;
  Py_DECREF(res);
  *out_name = g_iter_info_name.c_str();
  *out_desc = g_iter_info_desc.c_str();
  return 0;
}

}  // extern "C"
