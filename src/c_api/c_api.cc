// mxtpu C ABI implementation: embed (or attach to) CPython and delegate to
// mxtpu.c_api_impl.
//
// Reference: src/c_api/c_api.cc + c_api_ndarray.cc + c_predict_api.cc. The
// reference marshals into its C++ engine; the TPU-native runtime's
// orchestrator is Python (XLA/PJRT does the compute), so this layer marshals
// into the interpreter instead — one GIL scope per call, thread-local error
// strings, opaque PyObject* handles. When the host process *is* Python
// (ctypes), the already-running interpreter is used; from a plain C program
// the first call boots one.

#include "../../include/mxtpu/c_api.h"

#include <Python.h>

#include <cstring>
#include <mutex>
#include <string>

namespace {

thread_local std::string g_last_error;

void SetError(const std::string &msg) { g_last_error = msg; }

// Capture the pending Python exception into the thread-local error string.
void SetErrorFromPython() {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  PyErr_NormalizeException(&type, &value, &tb);
  std::string msg = "python error";
  if (value != nullptr) {
    PyObject *s = PyObject_Str(value);
    if (s != nullptr) {
      const char *c = PyUnicode_AsUTF8(s);
      if (c != nullptr) msg = c;
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
  SetError(msg);
}

// Boot the interpreter if this process doesn't have one (plain-C host).
// std::call_once: two C host threads may race their first API call here.
// Releases the GIL after boot so PyGILState_Ensure works from any thread.
bool EnsureInterpreter() {
  static std::once_flag boot_flag;
  static bool boot_ok = false;
  std::call_once(boot_flag, []() {
    if (Py_IsInitialized()) {
      boot_ok = true;
      return;
    }
    Py_InitializeEx(0);
    boot_ok = Py_IsInitialized();
    if (boot_ok) PyEval_SaveThread();  // release the GIL the boot holds
  });
  if (!boot_ok) SetError("failed to initialize embedded Python interpreter");
  return boot_ok;
}

// The mxtpu.c_api_impl module (borrowed global ref, imported once).
PyObject *ImplModule() {
  static PyObject *mod = nullptr;
  if (mod == nullptr) {
    mod = PyImport_ImportModule("mxtpu.c_api_impl");
    if (mod == nullptr) SetErrorFromPython();
  }
  return mod;
}

// RAII GIL scope.
class GilScope {
 public:
  GilScope() : state_(PyGILState_Ensure()) {}
  ~GilScope() { PyGILState_Release(state_); }

 private:
  PyGILState_STATE state_;
};

PyObject *ShapeTuple(const int64_t *shape, int ndim) {
  PyObject *t = PyTuple_New(ndim);
  for (int i = 0; i < ndim; ++i) {
    PyTuple_SetItem(t, i, PyLong_FromLongLong(shape[i]));
  }
  return t;
}

// Call impl.<method>(args...); returns new ref or nullptr (error recorded).
PyObject *CallImpl(const char *method, PyObject *args) {
  PyObject *mod = ImplModule();
  if (mod == nullptr) {
    Py_XDECREF(args);
    return nullptr;
  }
  PyObject *fn = PyObject_GetAttrString(mod, method);
  if (fn == nullptr) {
    SetErrorFromPython();
    Py_XDECREF(args);
    return nullptr;
  }
  PyObject *res = PyObject_CallObject(fn, args);
  Py_DECREF(fn);
  Py_XDECREF(args);
  if (res == nullptr) SetErrorFromPython();
  return res;
}

}  // namespace

extern "C" {

const char *MXTPUGetLastError(void) { return g_last_error.c_str(); }

int MXTPURuntimeInit(const char *platform) {
  if (!EnsureInterpreter()) return -1;
  GilScope gil;
  PyObject *args = Py_BuildValue("(z)", platform);
  PyObject *res = CallImpl("runtime_init", args);
  if (res == nullptr) return -1;
  Py_DECREF(res);
  return 0;
}

int MXTPUNDArrayCreateFromBlob(const float *data, const int64_t *shape,
                               int ndim, NDArrayHandle *out) {
  if (!EnsureInterpreter()) return -1;
  GilScope gil;
  int64_t n = 1;
  for (int i = 0; i < ndim; ++i) n *= shape[i];
  PyObject *bytes =
      PyBytes_FromStringAndSize(reinterpret_cast<const char *>(data),
                                static_cast<Py_ssize_t>(n * sizeof(float)));
  // "N" steals both new refs into the args tuple
  PyObject *args = Py_BuildValue("(NN)", bytes, ShapeTuple(shape, ndim));
  PyObject *res = CallImpl("ndarray_from_blob", args);
  if (res == nullptr) return -1;
  *out = res;  // keep the new ref as the handle
  return 0;
}

int MXTPUNDArrayShape(NDArrayHandle handle, int *ndim, int64_t *shape) {
  GilScope gil;
  PyObject *nd = reinterpret_cast<PyObject *>(handle);
  PyObject *args = PyTuple_Pack(1, nd);
  PyObject *res = CallImpl("ndarray_shape", args);
  if (res == nullptr) return -1;
  Py_ssize_t n = PyTuple_Size(res);
  if (n > 8) {
    Py_DECREF(res);
    SetError("ndim > 8 unsupported by MXTPUNDArrayShape");
    return -1;
  }
  *ndim = static_cast<int>(n);
  for (Py_ssize_t i = 0; i < n; ++i) {
    shape[i] = PyLong_AsLongLong(PyTuple_GetItem(res, i));
  }
  Py_DECREF(res);
  return 0;
}

int MXTPUNDArraySyncCopyToCPU(NDArrayHandle handle, float *dst,
                              int64_t size) {
  GilScope gil;
  PyObject *nd = reinterpret_cast<PyObject *>(handle);
  PyObject *args = PyTuple_Pack(1, nd);
  PyObject *res = CallImpl("ndarray_to_bytes", args);
  if (res == nullptr) return -1;
  char *buf = nullptr;
  Py_ssize_t len = 0;
  if (PyBytes_AsStringAndSize(res, &buf, &len) != 0) {
    SetErrorFromPython();
    Py_DECREF(res);
    return -1;
  }
  if (len != static_cast<Py_ssize_t>(size * sizeof(float))) {
    SetError("MXTPUNDArraySyncCopyToCPU: size mismatch");
    Py_DECREF(res);
    return -1;
  }
  std::memcpy(dst, buf, static_cast<size_t>(len));
  Py_DECREF(res);
  return 0;
}

int MXTPUNDArrayFree(NDArrayHandle handle) {
  if (handle == nullptr) return 0;
  GilScope gil;
  Py_DECREF(reinterpret_cast<PyObject *>(handle));
  return 0;
}

int MXTPUImperativeInvoke(const char *op_name, NDArrayHandle *inputs,
                          int num_inputs, const char **attr_keys,
                          const char **attr_vals, int num_attrs,
                          NDArrayHandle *outputs, int *num_outputs) {
  if (!EnsureInterpreter()) return -1;
  GilScope gil;
  PyObject *ins = PyList_New(num_inputs);
  for (int i = 0; i < num_inputs; ++i) {
    PyObject *o = reinterpret_cast<PyObject *>(inputs[i]);
    Py_INCREF(o);
    PyList_SetItem(ins, i, o);
  }
  PyObject *attrs = PyDict_New();
  for (int i = 0; i < num_attrs; ++i) {
    PyObject *v = PyUnicode_FromString(attr_vals[i]);
    PyDict_SetItemString(attrs, attr_keys[i], v);
    Py_DECREF(v);
  }
  PyObject *name = PyUnicode_FromString(op_name);
  PyObject *args = PyTuple_Pack(3, name, ins, attrs);
  Py_DECREF(name);
  Py_DECREF(ins);
  Py_DECREF(attrs);
  PyObject *res = CallImpl("imperative_invoke", args);
  if (res == nullptr) return -1;
  Py_ssize_t n = PyList_Size(res);
  if (n > *num_outputs) {
    Py_DECREF(res);
    SetError("output capacity too small");
    return -1;
  }
  *num_outputs = static_cast<int>(n);
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject *o = PyList_GetItem(res, i);
    Py_INCREF(o);
    outputs[i] = o;
  }
  Py_DECREF(res);
  return 0;
}

int MXTPUPredCreate(const char *prefix, int epoch, const char *input_name,
                    const int64_t *shape, int ndim, PredictorHandle *out) {
  if (!EnsureInterpreter()) return -1;
  GilScope gil;
  PyObject *args = Py_BuildValue("(sisN)", prefix, epoch, input_name,
                                 ShapeTuple(shape, ndim));
  PyObject *res = CallImpl("pred_create", args);
  if (res == nullptr) return -1;
  *out = res;
  return 0;
}

int MXTPUPredSetInput(PredictorHandle handle, const float *data,
                      int64_t size) {
  GilScope gil;
  PyObject *pred = reinterpret_cast<PyObject *>(handle);
  PyObject *bytes =
      PyBytes_FromStringAndSize(reinterpret_cast<const char *>(data),
                                static_cast<Py_ssize_t>(size * sizeof(float)));
  PyObject *args = PyTuple_Pack(2, pred, bytes);
  Py_DECREF(bytes);
  PyObject *res = CallImpl("pred_set_input", args);
  if (res == nullptr) return -1;
  Py_DECREF(res);
  return 0;
}

int MXTPUPredForward(PredictorHandle handle) {
  GilScope gil;
  PyObject *pred = reinterpret_cast<PyObject *>(handle);
  PyObject *args = PyTuple_Pack(1, pred);
  PyObject *res = CallImpl("pred_forward", args);
  if (res == nullptr) return -1;
  Py_DECREF(res);
  return 0;
}

int MXTPUPredGetOutputShape(PredictorHandle handle, int index, int *ndim,
                            int64_t *shape) {
  GilScope gil;
  PyObject *pred = reinterpret_cast<PyObject *>(handle);
  PyObject *args = Py_BuildValue("(Oi)", pred, index);
  PyObject *res = CallImpl("pred_output_shape", args);
  if (res == nullptr) return -1;
  Py_ssize_t n = PyTuple_Size(res);
  if (n > 8) {
    Py_DECREF(res);
    SetError("ndim > 8 unsupported");
    return -1;
  }
  *ndim = static_cast<int>(n);
  for (Py_ssize_t i = 0; i < n; ++i) {
    shape[i] = PyLong_AsLongLong(PyTuple_GetItem(res, i));
  }
  Py_DECREF(res);
  return 0;
}

int MXTPUPredGetOutput(PredictorHandle handle, int index, float *dst,
                       int64_t size) {
  GilScope gil;
  PyObject *pred = reinterpret_cast<PyObject *>(handle);
  PyObject *args = Py_BuildValue("(Oi)", pred, index);
  PyObject *res = CallImpl("pred_output_bytes", args);
  if (res == nullptr) return -1;
  char *buf = nullptr;
  Py_ssize_t len = 0;
  if (PyBytes_AsStringAndSize(res, &buf, &len) != 0) {
    SetErrorFromPython();
    Py_DECREF(res);
    return -1;
  }
  if (len != static_cast<Py_ssize_t>(size * sizeof(float))) {
    SetError("MXTPUPredGetOutput: size mismatch");
    Py_DECREF(res);
    return -1;
  }
  std::memcpy(dst, buf, static_cast<size_t>(len));
  Py_DECREF(res);
  return 0;
}

int MXTPUPredFree(PredictorHandle handle) {
  if (handle == nullptr) return 0;
  GilScope gil;
  Py_DECREF(reinterpret_cast<PyObject *>(handle));
  return 0;
}

}  // extern "C"
