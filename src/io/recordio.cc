// RecordIO: the framework's packed-record container format.
//
// Wire-format parity with the reference's dmlc-core recordio (used by
// ImageRecordIter, SURVEY §2.4; format described in
// docs/architecture/note_data_loading.md): stream of
//   [kMagic:4B][lrec:4B][data: ceil(len/4)*4 B]
// where lrec's upper 3 bits are a continuation flag and lower 29 bits the
// chunk length. Payloads containing the magic word at 4-byte alignment are
// split at those points (the magic bytes are elided and re-inserted on read),
// which keeps the stream resynchronizable at arbitrary offsets — the property
// distributed shard readers (part_index/num_parts) rely on.
//
// This is a from-scratch implementation of the format, not a copy: plain
// stdio, one in-memory buffer per reader, C ABI for ctypes (the framework's
// FFI convention, no pybind11).

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0xced7230a;
constexpr uint32_t kLenMask = (1u << 29) - 1;

inline uint32_t EncodeLRec(uint32_t cflag, uint32_t len) {
  return (cflag << 29) | (len & kLenMask);
}
inline uint32_t DecodeFlag(uint32_t lrec) { return lrec >> 29; }
inline uint32_t DecodeLen(uint32_t lrec) { return lrec & kLenMask; }

struct Writer {
  FILE* f = nullptr;
};

struct Reader {
  FILE* f = nullptr;
  std::string buf;  // last assembled record, returned to the caller
};

int WriteChunk(FILE* f, uint32_t cflag, const char* data, uint32_t len) {
  uint32_t magic = kMagic;
  uint32_t lrec = EncodeLRec(cflag, len);
  if (fwrite(&magic, 4, 1, f) != 1) return -1;
  if (fwrite(&lrec, 4, 1, f) != 1) return -1;
  if (len && fwrite(data, 1, len, f) != len) return -1;
  uint32_t pad = (4 - (len & 3)) & 3;
  const char zeros[4] = {0, 0, 0, 0};
  if (pad && fwrite(zeros, 1, pad, f) != pad) return -1;
  return 0;
}

}  // namespace

extern "C" {

void* mxtpu_recordio_writer_create(const char* path, const char* mode) {
  FILE* f = fopen(path, mode);
  if (!f) return nullptr;
  Writer* w = new Writer();
  w->f = f;
  return w;
}

// Split the payload at aligned magic occurrences; elide the magic bytes.
int mxtpu_recordio_writer_write(void* h, const char* data, uint64_t len) {
  Writer* w = static_cast<Writer*>(h);
  std::vector<uint64_t> cuts;  // offsets of elided magic words
  for (uint64_t i = 0; i + 4 <= len; i += 4) {
    uint32_t word;
    std::memcpy(&word, data + i, 4);
    if (word == kMagic) cuts.push_back(i);
  }
  if (cuts.empty()) {
    return WriteChunk(w->f, 0, data, static_cast<uint32_t>(len));
  }
  uint64_t begin = 0;
  for (size_t c = 0; c <= cuts.size(); ++c) {
    uint64_t end = (c < cuts.size()) ? cuts[c] : len;
    uint32_t cflag = (c == 0) ? 1u : (c == cuts.size()) ? 3u : 2u;
    if (WriteChunk(w->f, cflag, data + begin,
                   static_cast<uint32_t>(end - begin)) != 0)
      return -1;
    begin = end + 4;  // skip the elided magic word
  }
  return 0;
}

uint64_t mxtpu_recordio_writer_tell(void* h) {
  return static_cast<uint64_t>(ftell(static_cast<Writer*>(h)->f));
}

void mxtpu_recordio_writer_close(void* h) {
  Writer* w = static_cast<Writer*>(h);
  if (w->f) fclose(w->f);
  delete w;
}

void* mxtpu_recordio_reader_create(const char* path) {
  FILE* f = fopen(path, "rb");
  if (!f) return nullptr;
  Reader* r = new Reader();
  r->f = f;
  return r;
}

// Returns pointer to an internal buffer valid until the next call;
// nullptr at EOF or on a malformed stream.
const char* mxtpu_recordio_reader_read(void* h, uint64_t* out_len) {
  Reader* r = static_cast<Reader*>(h);
  r->buf.clear();
  bool in_continuation = false;
  while (true) {
    uint32_t magic, lrec;
    if (fread(&magic, 4, 1, r->f) != 1) return nullptr;  // EOF
    if (magic != kMagic) return nullptr;                 // lost sync
    if (fread(&lrec, 4, 1, r->f) != 1) return nullptr;
    uint32_t len = DecodeLen(lrec), cflag = DecodeFlag(lrec);
    size_t off = r->buf.size();
    r->buf.resize(off + len);
    if (len && fread(&r->buf[off], 1, len, r->f) != len) return nullptr;
    uint32_t pad = (4 - (len & 3)) & 3;
    if (pad && fseek(r->f, pad, SEEK_CUR) != 0) return nullptr;
    if (cflag == 0) break;
    if (cflag == 1) {
      in_continuation = true;
    } else if (!in_continuation) {
      return nullptr;  // middle/end without a start
    }
    if (cflag == 3) break;
    // re-insert the elided magic between chunks
    char m[4];
    std::memcpy(m, &magic, 4);
    r->buf.append(m, 4);
  }
  *out_len = r->buf.size();
  return r->buf.data();
}

void mxtpu_recordio_reader_seek(void* h, uint64_t pos) {
  fseek(static_cast<Reader*>(h)->f, static_cast<long>(pos), SEEK_SET);
}

uint64_t mxtpu_recordio_reader_tell(void* h) {
  return static_cast<uint64_t>(ftell(static_cast<Reader*>(h)->f));
}

void mxtpu_recordio_reader_close(void* h) {
  Reader* r = static_cast<Reader*>(h);
  if (r->f) fclose(r->f);
  delete r;
}

}  // extern "C"
