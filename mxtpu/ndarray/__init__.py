"""The ``mx.nd`` namespace: NDArray + the full imperative op surface.

The reference generates this namespace at import time from the C op registry
(python/mxnet/ndarray/register.py:143-157); here it is populated from
mxtpu.ops.REGISTRY after the op modules register themselves.
"""
import sys as _sys

from .ndarray import NDArray, array, from_jax, waitall, _apply  # noqa: F401

# importing ops populates the registry and attaches NDArray methods
from .. import ops as _ops  # noqa: E402

_mod = _sys.modules[__name__]
for _name, _op in _ops.REGISTRY.items():
    if not hasattr(_mod, _name):
        setattr(_mod, _name, _op.wrapper)

# mx.nd.contrib.* — the reference's contrib namespace (ndarray/contrib.py):
# every `_contrib_X` registry op is exposed as contrib.X (plus its aliases)
import types as _types  # noqa: E402

contrib = _types.ModuleType(__name__ + ".contrib")
for _name, _op in _ops.REGISTRY.items():
    if _name.startswith("_contrib_"):
        setattr(contrib, _name[len("_contrib_"):], _op.wrapper)
    for _alias in getattr(_op, "aliases", ()):
        if not hasattr(contrib, _alias) and _op.name.startswith("_contrib_"):
            setattr(contrib, _alias, _op.wrapper)
_sys.modules[contrib.__name__] = contrib

# mx.nd._internal.* — the reference's underscore-op namespace
# (python/mxnet/ndarray/_internal.py; e.g. the doc example at
# src/operator/tensor/square_sum.cc:61 calls mx.nd._internal._square_sum).
# Every `_`-prefixed registry name (op or alias) is exposed here.
_internal = _types.ModuleType(__name__ + "._internal")
for _name, _op in _ops.REGISTRY.items():
    if _name.startswith("_") and not hasattr(_internal, _name):
        setattr(_internal, _name, _op.wrapper)
_sys.modules[_internal.__name__] = _internal


# PEP 562 __getattr__ on the synthetic sub-namespaces so ops registered
# AFTER import (CustomOp, contrib.external_kernel) resolve there too —
# the reference regenerates its namespaces on registration callbacks
def _contrib_getattr(name):
    op = _ops.REGISTRY.get("_contrib_" + name) or _ops.REGISTRY.get(name)
    if op is not None and op.name.startswith("_contrib_"):
        setattr(contrib, name, op.wrapper)
        return op.wrapper
    raise AttributeError("module %r has no attribute %r"
                         % (contrib.__name__, name))


def _internal_getattr(name):
    op = _ops.REGISTRY.get(name)
    if op is not None and name.startswith("_"):
        setattr(_internal, name, op.wrapper)
        return op.wrapper
    raise AttributeError("module %r has no attribute %r"
                         % (_internal.__name__, name))


contrib.__getattr__ = _contrib_getattr
_internal.__getattr__ = _internal_getattr

# creation helpers registered wrap=False already return NDArrays
from ..ops.init_ops import arange, empty, eye, full, linspace, ones, zeros  # noqa: E402,F401
from .utils import load, save  # noqa: E402,F401
from .dlpack import (from_dlpack, from_numpy, to_dlpack_for_read,  # noqa: E402,F401
                     to_dlpack_for_write)
from . import random  # noqa: E402,F401
from . import image  # noqa: E402,F401
from . import sparse  # noqa: E402,F401
from . import linalg  # noqa: E402,F401
from .sparse import CSRNDArray, RowSparseNDArray  # noqa: E402,F401


def __getattr__(name):
    """Ops registered AFTER import (CustomOp, contrib.external_kernel)
    resolve lazily from the registry."""
    op = _ops.REGISTRY.get(name)
    if op is not None:
        setattr(_mod, name, op.wrapper)
        return op.wrapper
    raise AttributeError("module %r has no attribute %r"
                         % (__name__, name))


def concatenate(arrays, axis=0, always_copy=True):
    """Ref: mx.nd.concatenate (deprecated alias of concat with axis kwarg)."""
    return _ops.REGISTRY["Concat"].wrapper(*arrays, dim=axis)


def imdecode(buf, **kwargs):  # pragma: no cover - thin shim
    from ..image import imdecode as _imdecode
    return _imdecode(buf, **kwargs)
