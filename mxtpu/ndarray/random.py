"""``mx.nd.random`` namespace (ref: python/mxnet/ndarray/random.py).

Thin aliasing layer over the sampling ops in mxtpu.ops.random_ops — the
reference generates these from `_random_*` / `_sample_*` registry entries.
"""
from ..ops import random_ops as _r

uniform = _r.uniform
normal = _r.normal


def randn(*shape, loc=0.0, scale=1.0, dtype=None, ctx=None, **kwargs):
    """Ref: python/mxnet/ndarray/random.py:randn — normal with *shape args."""
    return _r.normal(loc=loc, scale=scale, shape=shape or None, dtype=dtype,
                     ctx=ctx, **kwargs)


gamma = _r.gamma_sample


def exponential(scale=1.0, shape=None, dtype=None, ctx=None, **kwargs):
    """Ref: python/mxnet/ndarray/random.py:exponential — mean=scale; the
    underlying op is rate-parameterized (lam = 1/scale)."""
    return _r.exponential(lam=1.0 / scale, shape=shape, dtype=dtype, ctx=ctx,
                          **kwargs)
poisson = _r.poisson
negative_binomial = _r.negative_binomial
generalized_negative_binomial = _r.generalized_negative_binomial
multinomial = _r.multinomial
shuffle = _r.shuffle
randint = _r.randint

__all__ = ["uniform", "normal", "randn", "gamma", "exponential", "poisson",
           "negative_binomial", "generalized_negative_binomial", "multinomial",
           "shuffle", "randint"]
