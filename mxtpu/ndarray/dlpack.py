"""DLPack interchange (ref: python/mxnet/ndarray/ndarray.py:3925-4029
``to_dlpack_for_read`` / ``to_dlpack_for_write`` / ``from_dlpack`` over
src/c_api MXNDArrayToDLPack / MXNDArrayFromDLPack).

TPU-native: the underlying jax.Array already speaks the DLPack protocol;
these functions expose the reference's capsule-based API over it so code
written against ``mx.nd.to_dlpack_for_read(x)`` / ``torch.utils.dlpack``
ports unchanged. One PJRT stream orders reads and writes, so the
read/write variants differ only in their documented intent (the
reference separates them because its dependency engine tracks read and
write queues independently, include/mxnet/engine.h:116).
"""
from __future__ import annotations

import ctypes

import numpy as _np

from ..base import MXNetError
from .ndarray import NDArray, array as _nd_array

__all__ = ["to_dlpack_for_read", "to_dlpack_for_write", "from_dlpack",
           "from_numpy"]

_DLTENSOR = b"dltensor"


def _host_export(data: NDArray):
    """ONE copy of the host-copy export recipe. copy=True: device_get
    often returns READONLY views, which numpy refuses to export (DLPack
    cannot signal readonly)."""
    host = _np.array(data.asnumpy(), copy=True)
    return host.__dlpack__()


def _capsule_from(data: NDArray):
    if not isinstance(data, NDArray):
        raise MXNetError("to_dlpack expects an NDArray, got %s"
                         % type(data).__name__)
    data.wait_to_read()
    try:
        return data._data.__dlpack__()
    except Exception:
        # backends without direct buffer export (e.g. tunneled PJRT
        # plugins): stage through a host copy — the consumer gets a CPU
        # DLPack tensor, matching torch_interop's copy-always policy
        return _host_export(data)


def to_dlpack_for_read(data):
    """NDArray -> PyCapsule("dltensor") of a DLManagedTensor. The capsule
    is one-shot: a consumer (torch.utils.dlpack.from_dlpack, another
    framework's importer) takes ownership."""
    return _capsule_from(data)


def to_dlpack_for_write(data):
    """Reference-parity name; delivers a WRITABLE HOST COPY, and consumer
    writes do NOT propagate back. XLA buffers are immutable — handing a
    consumer a mutable pointer into one would corrupt jit-cached/aliased
    computations, and the reference's in-place write-back contract
    (ndarray.py:3956) cannot hold on a functional runtime. Write into a
    fresh array and assign it back instead
    (``x[:] = mx.nd.from_dlpack(...)``).

    Warns on EVERY call — a ported write-back-dependent code path must fail
    loudly each time, not only on its first buffer (ADVICE r5: the single
    process-wide warning was suppressible by warning filters and then
    silently lost writes). Set ``MXTPU_DLPACK_WRITE_COPY=1`` to acknowledge
    the detached-copy semantics explicitly and silence the warning."""
    import os
    if os.environ.get("MXTPU_DLPACK_WRITE_COPY", "0") != "1":
        import warnings
        # warn_explicit with a FRESH registry: plain warnings.warn is deduped
        # per call site by the default filter, which is exactly the
        # silently-lost-writes failure mode this warning exists to prevent
        warnings.warn_explicit(
            "to_dlpack_for_write exports a host COPY on this runtime: "
            "consumer writes do not propagate back to the NDArray "
            "(XLA buffers are immutable). Assign results back with "
            "x[:] = mx.nd.from_dlpack(...) instead, or set "
            "MXTPU_DLPACK_WRITE_COPY=1 to acknowledge the copy semantics "
            "and silence this warning.",
            UserWarning, __file__, 0, registry={})
    if not isinstance(data, NDArray):
        raise MXNetError("to_dlpack expects an NDArray, got %s"
                         % type(data).__name__)
    data.wait_to_read()
    return _host_export(data)


class _CapsuleDLPack:
    """Adapter: a raw "dltensor" capsule as the modern __dlpack__ protocol
    (jax.dlpack.from_dlpack no longer accepts bare capsules). The device
    is parsed out of the DLManagedTensor header via ctypes."""

    def __init__(self, capsule):
        self._capsule = capsule

    def __dlpack__(self, **_kw):
        return self._capsule

    def __dlpack_device__(self):
        get_ptr = ctypes.pythonapi.PyCapsule_GetPointer
        get_ptr.restype = ctypes.c_void_p
        get_ptr.argtypes = [ctypes.py_object, ctypes.c_char_p]
        ptr = get_ptr(self._capsule, _DLTENSOR)
        # DLManagedTensor starts with DLTensor: { void* data;
        #   DLDevice { int32 device_type; int32 device_id }; ... }
        dev = ctypes.cast(ptr + ctypes.sizeof(ctypes.c_void_p),
                          ctypes.POINTER(ctypes.c_int32))
        return int(dev[0]), int(dev[1])


def from_dlpack(dlpack) -> NDArray:
    """PyCapsule (or any object with ``__dlpack__``) -> NDArray.

    The producer's capsule is CONSUMED (renamed "used_dltensor" by the
    importer, per the DLPack contract) — use the tensor only through the
    returned NDArray afterwards."""
    import jax.dlpack

    if ctypes.pythonapi.PyCapsule_IsValid(
            ctypes.py_object(dlpack), b"dltensor_versioned"):
        # DLPack 1.0 renamed the capsule and prefixed the struct with a
        # version/flags header (DLManagedTensorVersioned); the pre-1.0
        # ctypes parsing below would misread it. Name the case instead of
        # letting jax fail with an obscure "no __dlpack__" error.
        raise MXNetError(
            "from_dlpack got a DLPack-1.0 'dltensor_versioned' capsule; "
            "this importer consumes the pre-1.0 'dltensor' layout. "
            "Re-export from the producer without max_version (the legacy "
            "protocol, e.g. tensor.__dlpack__()), or pass the producer "
            "object itself so the exchange negotiates a version.")
    is_capsule = ctypes.pythonapi.PyCapsule_IsValid(
        ctypes.py_object(dlpack), _DLTENSOR)
    src = _CapsuleDLPack(dlpack) if is_capsule else dlpack
    return NDArray(jax.dlpack.from_dlpack(src))


def from_numpy(ndarray, zero_copy=True):
    """numpy -> NDArray (ref: mx.nd.from_numpy, ndarray.py:4032). The
    reference aliases host memory when ``zero_copy``; device-resident
    arrays cannot alias host numpy buffers, so this always copies and
    ``zero_copy`` is accepted for API compatibility."""
    if not isinstance(ndarray, _np.ndarray):
        raise MXNetError("from_numpy expects a numpy.ndarray")
    if not ndarray.flags["C_CONTIGUOUS"]:
        raise MXNetError("the numpy ndarray must be C-contiguous "
                         "(reference from_numpy raises the same)")
    return _nd_array(ndarray)
