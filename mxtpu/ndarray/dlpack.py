"""DLPack interchange (ref: python/mxnet/ndarray/ndarray.py:3925-4029
``to_dlpack_for_read`` / ``to_dlpack_for_write`` / ``from_dlpack`` over
src/c_api MXNDArrayToDLPack / MXNDArrayFromDLPack).

TPU-native: the underlying jax.Array already speaks the DLPack protocol;
these functions expose the reference's capsule-based API over it so code
written against ``mx.nd.to_dlpack_for_read(x)`` / ``torch.utils.dlpack``
ports unchanged. One PJRT stream orders reads and writes, so the
read/write variants differ only in their documented intent (the
reference separates them because its dependency engine tracks read and
write queues independently, include/mxnet/engine.h:116).
"""
from __future__ import annotations

import ctypes

import numpy as _np

from ..base import MXNetError
from .ndarray import NDArray, array as _nd_array

__all__ = ["to_dlpack_for_read", "to_dlpack_for_write", "from_dlpack",
           "from_numpy"]

_DLTENSOR = b"dltensor"


def _host_export(data: NDArray):
    """ONE copy of the host-copy export recipe. copy=True: device_get
    often returns READONLY views, which numpy refuses to export (DLPack
    cannot signal readonly)."""
    host = _np.array(data.asnumpy(), copy=True)
    return host.__dlpack__()


def _capsule_from(data: NDArray):
    if not isinstance(data, NDArray):
        raise MXNetError("to_dlpack expects an NDArray, got %s"
                         % type(data).__name__)
    data.wait_to_read()
    try:
        return data._data.__dlpack__()
    except Exception:
        # backends without direct buffer export (e.g. tunneled PJRT
        # plugins): stage through a host copy — the consumer gets a CPU
        # DLPack tensor, matching torch_interop's copy-always policy
        return _host_export(data)


def to_dlpack_for_read(data):
    """NDArray -> PyCapsule("dltensor") of a DLManagedTensor. The capsule
    is one-shot: a consumer (torch.utils.dlpack.from_dlpack, another
    framework's importer) takes ownership."""
    return _capsule_from(data)


_warned_write = False


def to_dlpack_for_write(data):
    """Reference-parity name; delivers a WRITABLE HOST COPY, and consumer
    writes do NOT propagate back (warned once). XLA buffers are immutable
    — handing a consumer a mutable pointer into one would corrupt
    jit-cached/aliased computations, and the reference's in-place
    write-back contract (ndarray.py:3956) cannot hold on a functional
    runtime. Write into a fresh array and assign it back instead
    (``x[:] = mx.nd.from_dlpack(...)``)."""
    global _warned_write
    if not _warned_write:
        _warned_write = True
        import warnings
        warnings.warn(
            "to_dlpack_for_write exports a host COPY on this runtime: "
            "consumer writes do not propagate back to the NDArray "
            "(XLA buffers are immutable). Assign results back with "
            "x[:] = mx.nd.from_dlpack(...) instead.")
    if not isinstance(data, NDArray):
        raise MXNetError("to_dlpack expects an NDArray, got %s"
                         % type(data).__name__)
    data.wait_to_read()
    return _host_export(data)


class _CapsuleDLPack:
    """Adapter: a raw "dltensor" capsule as the modern __dlpack__ protocol
    (jax.dlpack.from_dlpack no longer accepts bare capsules). The device
    is parsed out of the DLManagedTensor header via ctypes."""

    def __init__(self, capsule):
        self._capsule = capsule

    def __dlpack__(self, **_kw):
        return self._capsule

    def __dlpack_device__(self):
        get_ptr = ctypes.pythonapi.PyCapsule_GetPointer
        get_ptr.restype = ctypes.c_void_p
        get_ptr.argtypes = [ctypes.py_object, ctypes.c_char_p]
        ptr = get_ptr(self._capsule, _DLTENSOR)
        # DLManagedTensor starts with DLTensor: { void* data;
        #   DLDevice { int32 device_type; int32 device_id }; ... }
        dev = ctypes.cast(ptr + ctypes.sizeof(ctypes.c_void_p),
                          ctypes.POINTER(ctypes.c_int32))
        return int(dev[0]), int(dev[1])


def from_dlpack(dlpack) -> NDArray:
    """PyCapsule (or any object with ``__dlpack__``) -> NDArray.

    The producer's capsule is CONSUMED (renamed "used_dltensor" by the
    importer, per the DLPack contract) — use the tensor only through the
    returned NDArray afterwards."""
    import jax.dlpack

    is_capsule = ctypes.pythonapi.PyCapsule_IsValid(
        ctypes.py_object(dlpack), _DLTENSOR)
    src = _CapsuleDLPack(dlpack) if is_capsule else dlpack
    return NDArray(jax.dlpack.from_dlpack(src))


def from_numpy(ndarray, zero_copy=True):
    """numpy -> NDArray (ref: mx.nd.from_numpy, ndarray.py:4032). The
    reference aliases host memory when ``zero_copy``; device-resident
    arrays cannot alias host numpy buffers, so this always copies and
    ``zero_copy`` is accepted for API compatibility."""
    if not isinstance(ndarray, _np.ndarray):
        raise MXNetError("from_numpy expects a numpy.ndarray")
    if not ndarray.flags["C_CONTIGUOUS"]:
        raise MXNetError("the numpy ndarray must be C-contiguous "
                         "(reference from_numpy raises the same)")
    return _nd_array(ndarray)
