"""mx.nd.image namespace (ref: mx.nd.image generated from the _image_* ops,
src/operator/image/)."""
from ..ops import registry as _reg

_NAMES = ["to_tensor", "normalize", "resize", "crop", "center_crop",
          "flip_left_right", "flip_top_bottom", "random_flip_left_right",
          "random_flip_top_bottom", "brightness", "contrast", "saturation",
          "hue"]

for _n in _NAMES:
    globals()[_n] = _reg.get_op("_image_" + _n).wrapper
del _n

__all__ = list(_NAMES)
