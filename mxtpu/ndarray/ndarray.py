"""NDArray: the framework's imperative tensor, backed by an immutable ``jax.Array``.

Reference: ``include/mxnet/ndarray.h:82`` (value-semantics tensor = shared storage
chunk + engine variable + autograd entry) and the Python surface
``python/mxnet/ndarray/ndarray.py``.

TPU-native re-design:

* The reference pairs each NDArray with an *engine variable* so the dependency
  scheduler can order reads/writes (engine.h:45). Here the payload is an immutable
  ``jax.Array`` on a PJRT stream — PJRT already executes enqueued work asynchronously
  and in order, so "mutation" is value replacement (``_set_data``) and the version
  counter is kept only for observability. Frontend threads never block, matching the
  reference's push-and-return semantics (SURVEY §1): blocking happens only at
  ``wait_to_read``/``asnumpy`` (ref: MXNDArrayWaitToRead, src/c_api/c_api.cc:273).
* Deferred exceptions (src/engine/threaded_engine.cc:472): XLA raises asynchronous
  execution errors at the first sync point; ``wait_to_read`` surfaces them the same
  way the reference rethrows captured var exceptions.
* Autograd linkage is an entry on the tape (mxtpu/autograd.py) instead of AGInfo
  on an nnvm node.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as _np

from .. import autograd
from .. import telemetry as _telemetry
from ..base import Context, MXNetError, current_context, np_dtype

__all__ = ["NDArray", "array", "_apply", "from_jax", "waitall"]


_BFLOAT16 = jnp.bfloat16


def _as_jax_dtype(dtype):
    name = np_dtype(dtype)
    return {"bfloat16": _BFLOAT16}.get(name, name)


def _apply(fn, args, kwargs=None, name="", num_outputs=None):
    """Invoke a jnp-level pure function on NDArray/scalar args, taping if recording.

    The imperative dispatch path (ref: Imperative::Invoke,
    src/imperative/imperative.cc:87 → PushFCompute → engine). Here "push to engine"
    is simply calling into jax: PJRT enqueues the computation asynchronously.
    """
    kwargs = kwargs or {}
    nd_idx = [i for i, a in enumerate(args) if isinstance(a, NDArray)]
    nd_keys = [k for k, v in kwargs.items() if isinstance(v, NDArray)]
    inputs = [args[i] for i in nd_idx] + [kwargs[k] for k in nd_keys]

    if nd_idx or nd_keys:
        def pure_fn(*in_data):
            a = list(args)
            kw = dict(kwargs)
            for j, i in enumerate(nd_idx):
                a[i] = in_data[j]
            for j, k in enumerate(nd_keys):
                kw[k] = in_data[len(nd_idx) + j]
            out = fn(*a, **kw)
            # normalize multi-output to tuple so vjp cotangent structure is stable
            return tuple(out) if isinstance(out, list) else out
    else:
        def pure_fn():
            out = fn(*args, **kwargs)
            return tuple(out) if isinstance(out, list) else out

    global _profiler
    if _profiler is None:
        from .. import profiler as _profiler
    if _profiler._PROF.active:
        import time as _time
        _t0 = _time.perf_counter_ns()
        out_data = pure_fn(*[x._data for x in inputs])
        _profiler.record_event(name or "op", "operator", _t0 // 1000,
                               (_time.perf_counter_ns() - _t0) // 1000)
    else:
        out_data = pure_fn(*[x._data for x in inputs])
    if isinstance(out_data, (tuple, list)):
        outputs = [NDArray(d) for d in out_data]
        if autograd.is_recording():
            autograd.record_op(pure_fn, inputs, outputs, name=name)
        _maybe_record_symbol(name, args, kwargs, inputs, outputs)
        return outputs
    out = NDArray(out_data)
    if autograd.is_recording():
        autograd.record_op(pure_fn, inputs, [out], name=name)
    _maybe_record_symbol(name, args, kwargs, inputs, [out])
    return out


_sym_tape = None  # resolved lazily once; avoids import cost on the hot path
_profiler = None  # same lazy-resolution pattern for the profiler hook


def _maybe_record_symbol(name, args, kwargs, inputs, outputs):
    """Graph-export tape (mxtpu.symbol.trace_block); no-op unless tracing."""
    global _sym_tape
    if _sym_tape is None:
        from ..symbol import symbol as _sym_tape_mod
        _sym_tape = _sym_tape_mod
    if _sym_tape._SYM_TAPE.active is not None and name:
        _sym_tape.record_apply(name, args, kwargs, inputs, outputs)


class NDArray:
    """Multi-dimensional array with MXNet NDArray semantics on a PJRT device."""

    __slots__ = ("_data", "_ctx", "_grad", "_grad_req", "_ag_entry", "_version",
                 "_fresh_grad",  # NDArray.fresh_grad bookkeeping bit
                 "__weakref__")  # (ref MXNDArraySetGradState)

    # make `ndarray op numpy_array` use our reflected ops, not numpy's
    __array_priority__ = 1000.0

    def __init__(self, data, ctx: Context = None):
        if isinstance(data, NDArray):
            data = data._data
        if not isinstance(data, jax.Array):
            data = jnp.asarray(data)
        if ctx is not None:
            dev = ctx.jax_device()
            if data.device != dev:
                data = jax.device_put(data, dev)
        self._data = data
        self._ctx = ctx
        self._grad = None
        self._grad_req = "null"
        self._ag_entry = None
        self._version = 0

    # ------------------------------------------------------------------ core
    def _set_data(self, new_data):
        """Replace the payload (the mutation primitive). Bumps the version like
        the reference's engine var (include/mxnet/engine.h:45-62)."""
        self._data = new_data
        self._version += 1

    @property
    def shape(self):
        return tuple(self._data.shape)

    @property
    def dtype(self):
        # jax dtypes are numpy dtypes (ml_dtypes registers bfloat16), so str()
        # and == comparisons behave like the reference's numpy dtype surface
        return self._data.dtype

    @property
    def size(self):
        return int(self._data.size)

    @property
    def ndim(self):
        return self._data.ndim

    @property
    def context(self) -> Context:
        dev = self._data.device
        plat = getattr(dev, "platform", "cpu")
        if plat == "cpu":
            return Context("cpu", dev.id)
        return Context("tpu", dev.id)

    ctx = context

    @property
    def stype(self):
        return "default"

    @property
    def T(self):
        return _apply(jnp.transpose, (self,), name="transpose")

    @property
    def grad(self):
        return self._grad

    # ------------------------------------------------------------- sync points
    def wait_to_read(self):
        """Block until the value is computed (ref: MXNDArrayWaitToRead →
        ThreadedEngine::WaitForVar, src/engine/threaded_engine.cc:375). Deferred
        async errors surface here."""
        self._data.block_until_ready()
        return self

    def asnumpy(self) -> _np.ndarray:
        # transfer watchdog: EVERY materialization is one d2h sync — spans
        # opened with d2h=True (Trainer.step, Module.update) attribute the
        # delta to their region, so a sync sneaking into the hot loop is
        # visible without a jax transfer_guard
        _telemetry.record_d2h()
        with _telemetry.span("ndarray.asnumpy", cat="sync"):
            d = self._data
            if d.dtype == _BFLOAT16:
                return _np.asarray(d.astype(jnp.float32))
            return _np.asarray(d)

    def asscalar(self):
        if self.size != 1:
            raise MXNetError("The current array is not a scalar")
        return self.asnumpy().reshape(())[()]

    def item(self):
        return self.asscalar()

    def __float__(self):
        return float(self.asscalar())

    def __int__(self):
        return int(self.asscalar())

    def __bool__(self):
        if self.size == 0:
            return False
        if self.size == 1:
            return bool(self.asscalar())
        raise MXNetError("The truth value of an NDArray with multiple elements is ambiguous.")

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of unsized object")
        return self.shape[0]

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __repr__(self):
        return "%s\n<NDArray %s @%s>" % (
            str(self.asnumpy()), "x".join(map(str, self.shape)), self.context)

    # ------------------------------------------------------------ conversions
    def astype(self, dtype, copy=True):
        jd = _as_jax_dtype(dtype)
        if not copy and self._data.dtype == jnp.dtype(jd):
            return self
        return _apply(lambda x: x.astype(jd), (self,), name="cast")

    def copy(self):
        return NDArray(self._data + 0 if self._data.dtype != jnp.bool_ else self._data)

    def copyto(self, other):
        """Copy into another NDArray or Context (ref: CopyFromTo,
        src/ndarray/ndarray.cc:1184)."""
        if isinstance(other, Context):
            return NDArray(jax.device_put(self._data, other.jax_device()))
        if isinstance(other, NDArray):
            d = self._data
            if d.shape != other.shape:
                raise MXNetError("copyto shape mismatch: %s vs %s" % (self.shape, other.shape))
            dev = other._data.device
            d = d.astype(other._data.dtype)
            if d.device != dev:
                d = jax.device_put(d, dev)
            other._set_data(d)
            return other
        raise TypeError("copyto does not support type " + str(type(other)))

    def as_in_context(self, ctx: Context):
        if ctx == self.context:
            return self
        return self.copyto(ctx)

    as_in_ctx = as_in_context

    def as_nd_ndarray(self):
        return self

    def tostype(self, stype):
        if stype == "default":
            return self
        from .sparse import cast_storage  # late: sparse built on dense
        return cast_storage(self, stype)

    def to_jax(self):
        """Escape hatch: the underlying jax.Array (TPU-native; replaces the
        reference's dlpack bridge, include/mxnet/ndarray.h / mx.nd.to_dlpack)."""
        return self._data

    def __dlpack__(self, *a, **kw):
        return self._data.__dlpack__(*a, **kw)

    def to_dlpack_for_read(self):
        """One-shot "dltensor" capsule (ref: NDArray.to_dlpack_for_read,
        python/mxnet/ndarray/ndarray.py:2216)."""
        from .dlpack import to_dlpack_for_read
        return to_dlpack_for_read(self)

    def to_dlpack_for_write(self):
        from .dlpack import to_dlpack_for_write
        return to_dlpack_for_write(self)

    # --------------------------------------------------------------- autograd
    def attach_grad(self, grad_req="write", stype=None):
        """Allocate a grad buffer; marks this array as an autograd leaf
        (ref: python/mxnet/ndarray/ndarray.py:attach_grad)."""
        self._ag_entry = None  # detach from any recorded history
        self._grad = NDArray(jnp.zeros(self.shape, self._data.dtype))
        self._grad_req = grad_req

    def backward(self, out_grad=None, retain_graph=False, train_mode=True):
        autograd.backward([self], [out_grad] if out_grad is not None else None,
                          retain_graph=retain_graph, train_mode=train_mode)

    def detach(self):
        out = NDArray(self._data)
        return out

    # ---------------------------------------------------------------- indexing
    def __getitem__(self, key):
        key = _clean_index(key)
        return _apply(lambda x: x[key], (self,), name="slice")

    def __setitem__(self, key, value):
        if autograd.is_recording():
            raise MXNetError("Inplace assignment is not supported when recording "
                             "(ref: mxnet inplace-under-autograd restriction)")
        key = _clean_index(key)
        if isinstance(value, NDArray):
            v = value._data
        elif isinstance(value, (int, float, bool)):
            v = value
        else:
            v = jnp.asarray(value)
        if isinstance(key, slice) and key == slice(None) \
                and not isinstance(v, (int, float, bool)) \
                and tuple(getattr(v, "shape", ())) == self.shape:
            self._set_data(jnp.asarray(v, dtype=self._data.dtype))
        else:
            self._set_data(self._data.at[key].set(v))

    # ------------------------------------------------------------- arithmetic
    def _densify_operands(self, other):
        """Storage fallback (ref: FInferStorageType dense fallback): a
        sparse NDArray's _data is its VALUES buffer, which must never feed
        elementwise math raw. BaseSparseNDArray overrides the common
        dunders with sparse-preserving paths; any dunder it does NOT
        override (mod, matmul, reflected pow, ...) lands in _binop/_rbinop
        and both operands densify — after the cheap type check, so an
        unsupported rhs can't trigger a large todense for nothing."""
        if self.stype != "default":
            self = self.todense()
        if getattr(other, "stype", "default") != "default":
            other = other.todense()
        return self, other

    def _binop(self, other, fn, name):
        if isinstance(other, (NDArray, int, float, bool, _np.number)):
            self, other = self._densify_operands(other)
            return _apply(fn, (self, other), name=name)
        if isinstance(other, _np.ndarray):
            self, _ = self._densify_operands(None)
            return _apply(fn, (self, NDArray(other)), name=name)
        return NotImplemented

    def _rbinop(self, other, fn, name):
        if isinstance(other, (NDArray, int, float, bool, _np.number)):
            self, other = self._densify_operands(other)
            return _apply(fn, (other, self), name=name)
        if isinstance(other, _np.ndarray):
            self, _ = self._densify_operands(None)
            return _apply(fn, (NDArray(other), self), name=name)
        return NotImplemented

    def __add__(self, o):
        return self._binop(o, jnp.add, "broadcast_add")

    __radd__ = __add__

    def __sub__(self, o):
        return self._binop(o, jnp.subtract, "broadcast_sub")

    def __rsub__(self, o):
        return self._rbinop(o, jnp.subtract, "broadcast_sub")

    def __mul__(self, o):
        return self._binop(o, jnp.multiply, "broadcast_mul")

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binop(o, jnp.divide, "broadcast_div")

    def __rtruediv__(self, o):
        return self._rbinop(o, jnp.divide, "broadcast_div")

    __div__ = __truediv__
    __rdiv__ = __rtruediv__

    def __mod__(self, o):
        return self._binop(o, jnp.mod, "broadcast_mod")

    def __rmod__(self, o):
        return self._rbinop(o, jnp.mod, "broadcast_mod")

    def __pow__(self, o):
        return self._binop(o, jnp.power, "broadcast_power")

    def __rpow__(self, o):
        return self._rbinop(o, jnp.power, "broadcast_power")

    def __neg__(self):
        return _apply(jnp.negative, (self,), name="negative")

    def __abs__(self):
        return _apply(jnp.abs, (self,), name="abs")

    def __matmul__(self, o):
        return self._binop(o, jnp.matmul, "matmul")

    def __eq__(self, o):
        if isinstance(o, (NDArray, int, float, bool, _np.number, _np.ndarray)):
            return self._binop(o, lambda a, b: jnp.equal(a, b).astype(jnp.float32), "broadcast_equal")
        return NotImplemented

    def __ne__(self, o):
        if isinstance(o, (NDArray, int, float, bool, _np.number, _np.ndarray)):
            return self._binop(o, lambda a, b: jnp.not_equal(a, b).astype(jnp.float32),
                               "broadcast_not_equal")
        return NotImplemented

    def __gt__(self, o):
        return self._binop(o, lambda a, b: jnp.greater(a, b).astype(jnp.float32), "broadcast_greater")

    def __ge__(self, o):
        return self._binop(o, lambda a, b: jnp.greater_equal(a, b).astype(jnp.float32),
                           "broadcast_greater_equal")

    def __lt__(self, o):
        return self._binop(o, lambda a, b: jnp.less(a, b).astype(jnp.float32), "broadcast_lesser")

    def __le__(self, o):
        return self._binop(o, lambda a, b: jnp.less_equal(a, b).astype(jnp.float32),
                           "broadcast_lesser_equal")

    __hash__ = object.__hash__

    # in-place ops rebind the payload; while recording they tape like ordinary ops
    # (functionally equivalent to the reference's kWriteInplace + var version bump)
    def __iadd__(self, o):
        res = self.__add__(o)
        self._ag_entry = res._ag_entry
        self._set_data(res._data)
        return self

    def __isub__(self, o):
        res = self.__sub__(o)
        self._ag_entry = res._ag_entry
        self._set_data(res._data)
        return self

    def __imul__(self, o):
        res = self.__mul__(o)
        self._ag_entry = res._ag_entry
        self._set_data(res._data)
        return self

    def __itruediv__(self, o):
        res = self.__truediv__(o)
        self._ag_entry = res._ag_entry
        self._set_data(res._data)
        return self

    # ------------------------------------------------------------ shape ops
    def reshape(self, *shape, **kwargs):
        if len(shape) == 1 and isinstance(shape[0], (list, tuple)):
            shape = tuple(shape[0])
        if kwargs.get("shape") is not None:
            shape = tuple(kwargs["shape"])
        shape = tuple(-1 if s in (-1, 0) and s == -1 else s for s in shape)
        # MXNet 0 means "copy this dim" (ndarray.py reshape special codes 0/-1)
        new_shape = []
        for i, s in enumerate(shape):
            if s == 0:
                new_shape.append(self.shape[i])
            else:
                new_shape.append(s)
        return _apply(lambda x: jnp.reshape(x, tuple(new_shape)), (self,), name="reshape")

    def reshape_like(self, other):
        return self.reshape(other.shape)

    def expand_dims(self, axis):
        return _apply(lambda x: jnp.expand_dims(x, axis), (self,), name="expand_dims")

    def squeeze(self, axis=None):
        return _apply(lambda x: jnp.squeeze(x, axis), (self,), name="squeeze")

    def transpose(self, *axes):
        if len(axes) == 1 and isinstance(axes[0], (list, tuple)):
            axes = tuple(axes[0])
        axes = axes if axes else None
        return _apply(lambda x: jnp.transpose(x, axes), (self,), name="transpose")

    def swapaxes(self, dim1, dim2):
        return _apply(lambda x: jnp.swapaxes(x, dim1, dim2), (self,), name="swapaxes")

    def flatten(self):
        n = self.shape[0] if self.ndim > 0 else 1
        return _apply(lambda x: jnp.reshape(x, (n, -1)), (self,), name="flatten")

    def broadcast_to(self, shape):
        return _apply(lambda x: jnp.broadcast_to(x, tuple(shape)), (self,), name="broadcast_to")

    def broadcast_like(self, other):
        return self.broadcast_to(other.shape)

    def zeros_like(self):
        return NDArray(jnp.zeros_like(self._data))

    def ones_like(self):
        return NDArray(jnp.ones_like(self._data))


def _clean_index(key):
    """Normalize an index: NDArray → jax array, tuples recursively.

    Float index arrays cast to int32: the reference's convention is
    float32 indices everywhere (take/Embedding/advanced indexing accept
    them — python/mxnet/ndarray/ndarray.py advanced indexing casts).

    Boolean masks convert to concrete integer indices on host
    (numpy's nonzero-expansion semantics). Indexing is an EAGER API
    here — the mask's values are available — and the conversion keeps
    the resulting gather static-shaped instead of handing jnp a
    data-dependent-shape lowering."""
    if isinstance(key, NDArray):
        key = key._data
    elif isinstance(key, tuple):
        out = []
        for k in key:
            k = _clean_index(k)
            if isinstance(k, tuple):   # an N-d bool expanded to N arrays
                out.extend(k)
            else:
                out.append(k)
        return tuple(out)
    elif isinstance(key, (float, _np.floating)):
        # same convention as float index ARRAYS below: truncate toward
        # zero rather than surface a bare jax TypeError (ADVICE r4)
        return int(key)
    elif isinstance(key, list):
        key = jnp.asarray(key)
    if hasattr(key, "dtype"):
        if jnp.issubdtype(key.dtype, jnp.floating):
            return key.astype(jnp.int32)
        if key.dtype == bool:
            nz = _np.nonzero(_np.asarray(key))
            return nz[0] if len(nz) == 1 else tuple(
                jnp.asarray(i) for i in nz)
    return key


def array(source_array, ctx: Context = None, dtype=None) -> NDArray:
    """Create an NDArray from any array-like (ref: mx.nd.array)."""
    if isinstance(source_array, NDArray):
        d = source_array._data
    elif isinstance(source_array, jax.Array):
        d = source_array
    else:
        d = jnp.asarray(source_array)
    if dtype is not None:
        d = d.astype(_as_jax_dtype(dtype))
    elif not isinstance(source_array, (NDArray, jax.Array)) and \
            _np.asarray(source_array).dtype == _np.float64:
        d = d.astype(jnp.float32)  # MXNet defaults python floats to float32
    return NDArray(d, ctx=ctx)


def from_jax(x) -> NDArray:
    return NDArray(x)


def waitall():
    """Block until all enqueued work completes (ref: MXNDArrayWaitAll →
    Engine::WaitForAll)."""
    try:
        jax.effects_barrier()
    except Exception:
        pass
