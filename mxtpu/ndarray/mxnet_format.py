"""Byte-compatible reference NDArray-list serialization (the ``.params``
format real MXNet writes and reads).

Layout (ref: src/ndarray/ndarray.cc:1574-1806):

* file    = u64 magic ``kMXAPINDArrayListMagic`` (0x112) + u64 reserved(0)
            + dmlc vector<NDArray> + dmlc vector<string> names
* vector  = u64 count + elements (strings: u64 length + bytes)
* NDArray = u32 version magic:
    - 0xF993fac9 (V2, ref NDARRAY_V2_MAGIC): i32 storage type, [storage
      shape if sparse], shape, context(i32 dev_type, i32 dev_id), i32
      dtype flag, [per-aux i32 dtype + shape], raw data, [raw aux data]
    - 0xF993fac8 (V1): shape, context, dtype, raw data (dense only)
    - anything else: the magic IS ndim of a u32-dim legacy shape
      (ref LegacyTShapeLoad), then context/dtype/data
* TShape  = u32 ndim + i64 dims (nnvm Tuple::Save; V1 magic marked the
  int64 switch — ndarray.cc:1569)
* dtype flags = mshadow: 0 f32, 1 f64, 2 f16, 3 u8, 4 i32, 5 i8, 6 i64
* storage types (ref include/mxnet/ndarray.h:61): 0 dense, 1 row_sparse
  (1 aux: row indices), 2 csr (2 aux: indptr, indices)

Everything is little-endian (dmlc streams write host byte order; x86/ARM).
bfloat16 has no reference dtype flag — writers upcast it to f32.
"""
from __future__ import annotations

import struct

import numpy as _np

from ..base import MXNetError

LIST_MAGIC = 0x112
_V2_MAGIC = 0xF993FAC9
_V1_MAGIC = 0xF993FAC8

_FLAG_TO_DTYPE = {0: _np.float32, 1: _np.float64, 2: _np.float16,
                  3: _np.uint8, 4: _np.int32, 5: _np.int8, 6: _np.int64}
_DTYPE_TO_FLAG = {_np.dtype(v): k for k, v in _FLAG_TO_DTYPE.items()}
_CPU_DEV_TYPE = 1  # Context::kCPU (ref include/mxnet/base.h:90)


class _Reader:
    def __init__(self, buf):
        self.buf = memoryview(buf)
        self.pos = 0

    def take(self, n):
        if self.pos + n > len(self.buf):
            raise MXNetError("truncated NDArray file (wanted %d bytes at "
                             "offset %d of %d)" % (n, self.pos,
                                                   len(self.buf)))
        out = self.buf[self.pos:self.pos + n]
        self.pos += n
        return out

    def u32(self):
        return struct.unpack("<I", self.take(4))[0]

    def i32(self):
        return struct.unpack("<i", self.take(4))[0]

    def u64(self):
        return struct.unpack("<Q", self.take(8))[0]


def _read_tshape(r, legacy_ndim=None):
    """nnvm Tuple::Save layout; dims are i64 (u32 in the pre-V1 legacy)."""
    if legacy_ndim is not None:
        return tuple(_np.frombuffer(r.take(4 * legacy_ndim),
                                    dtype="<u4").tolist())
    ndim = r.u32()
    return tuple(_np.frombuffer(r.take(8 * ndim), dtype="<i8").tolist())


def _read_raw(r, shape, flag):
    dt = _FLAG_TO_DTYPE.get(flag)
    if dt is None:
        raise MXNetError("unknown mshadow dtype flag %d" % flag)
    n = int(_np.prod(shape, dtype=_np.int64)) if shape else 1
    a = _np.frombuffer(r.take(n * _np.dtype(dt).itemsize), dtype=dt)
    return a.reshape(shape).copy()


def _read_ndarray(r):
    """One NDArray record -> (stype, payload). Dense payload: np array;
    sparse: dict of parts + shape. (ref NDArray::Load / LegacyLoad)"""
    magic = r.u32()
    if magic == _V2_MAGIC:
        stype = r.i32()
        nad = {0: 0, 1: 1, 2: 2}.get(stype)
        if nad is None:
            raise MXNetError("unknown storage type %d" % stype)
        sshape = _read_tshape(r) if nad else None
        shape = _read_tshape(r)
        if len(shape) == 0:
            return "default", _np.zeros((), _np.float32)
        r.i32(), r.i32()  # context (ignored: everything loads to host)
        flag = r.i32()
        aux = [(r.i32(), _read_tshape(r)) for _ in range(nad)]
        data = _read_raw(r, sshape if nad else shape, flag)
        aux_data = [_read_raw(r, ashape, aflag) for aflag, ashape in aux]
        if stype == 0:
            return "default", data
        if stype == 1:
            return "row_sparse", {"values": data, "indices": aux_data[0],
                                  "shape": shape}
        return "csr", {"data": data, "indptr": aux_data[0],
                       "indices": aux_data[1], "shape": shape}
    # legacy dense-only records
    shape = _read_tshape(r) if magic == _V1_MAGIC \
        else _read_tshape(r, legacy_ndim=magic)
    if len(shape) == 0:
        return "default", _np.zeros((), _np.float32)
    r.i32(), r.i32()  # context
    flag = r.i32()
    return "default", _read_raw(r, shape, flag)


def loads(buf):
    """Parse a reference-format NDArray-list blob -> (list of (stype,
    payload), list of names)."""
    r = _Reader(buf)
    if r.u64() != LIST_MAGIC:
        raise MXNetError("not a reference NDArray file (bad 0x112 magic)")
    r.u64()  # reserved
    n = r.u64()
    items = [_read_ndarray(r) for _ in range(n)]
    n_names = r.u64()
    names = [bytes(r.take(r.u64())).decode() for _ in range(n_names)]
    if names and len(names) != len(items):
        raise MXNetError("NDArray file names/data length mismatch")
    return items, names


def _write_tshape(out, shape):
    out.append(struct.pack("<I", len(shape)))
    out.append(_np.asarray(shape, dtype="<i8").tobytes())


def ref_encodable(dtype):
    """True when the reference format stores this dtype losslessly."""
    try:
        return _np.dtype(dtype) in _DTYPE_TO_FLAG
    except TypeError:
        return False  # bfloat16 et al: no numpy name


def _np_for_save(a):
    shape = _np.shape(a)
    # ascontiguousarray promotes 0-d to (1,); reshape restores the rank
    a = _np.ascontiguousarray(a).reshape(shape)
    if a.dtype not in _DTYPE_TO_FLAG:
        if a.dtype.name == "bfloat16" or a.dtype.kind == "f":
            a = a.astype(_np.float32)  # no reference flag: documented upcast
        elif a.dtype.kind in "iub":
            a = a.astype(_np.int64)
        else:
            raise MXNetError("dtype %s has no reference encoding" % a.dtype)
    return a


def _write_dense(out, a):
    a = _np_for_save(a)
    if a.ndim == 0:
        # a 0-ndim TShape means "none" to the reference reader
        # (ndarray.cc Load: shape.ndim()==0 -> empty NDArray, no payload
        # follows); reference scalars are shape (1,)
        raise MXNetError("rank-0 arrays have no reference encoding; "
                         "reshape to (1,) or use format='mxtpu'")
    out.append(struct.pack("<I", _V2_MAGIC))
    out.append(struct.pack("<i", 0))
    _write_tshape(out, a.shape)
    out.append(struct.pack("<ii", _CPU_DEV_TYPE, 0))
    out.append(struct.pack("<i", _DTYPE_TO_FLAG[a.dtype]))
    out.append(a.tobytes())


def _write_sparse(out, stype, parts):
    if stype == "row_sparse":
        vals = _np_for_save(parts["values"])
        aux = [_np_for_save(parts["indices"]).astype(_np.int64)]
        stype_i = 1
    else:
        vals = _np_for_save(parts["data"])
        aux = [_np_for_save(parts["indptr"]).astype(_np.int64),
               _np_for_save(parts["indices"]).astype(_np.int64)]
        stype_i = 2
    shape = tuple(parts["shape"])
    out.append(struct.pack("<I", _V2_MAGIC))
    out.append(struct.pack("<i", stype_i))
    _write_tshape(out, vals.shape)   # storage shape
    _write_tshape(out, shape)
    out.append(struct.pack("<ii", _CPU_DEV_TYPE, 0))
    out.append(struct.pack("<i", _DTYPE_TO_FLAG[vals.dtype]))
    for a in aux:
        out.append(struct.pack("<i", _DTYPE_TO_FLAG[a.dtype]))
        _write_tshape(out, a.shape)
    out.append(vals.tobytes())
    for a in aux:
        out.append(a.tobytes())


def dumps(items, names):
    """Serialize [(stype, payload)] + names to the reference byte format."""
    out = [struct.pack("<QQ", LIST_MAGIC, 0), struct.pack("<Q", len(items))]
    for stype, payload in items:
        if stype == "default":
            _write_dense(out, payload)
        else:
            _write_sparse(out, stype, payload)
    out.append(struct.pack("<Q", len(names)))
    for name in names:
        b = name.encode()
        out.append(struct.pack("<Q", len(b)))
        out.append(b)
    return b"".join(out)
