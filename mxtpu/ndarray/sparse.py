"""Sparse NDArrays: row_sparse and csr storage types.

Reference: include/mxnet/ndarray.h:61-66 (kDefaultStorage/kRowSparseStorage/
kCSRStorage), python/mxnet/ndarray/sparse.py, cast_storage
(src/operator/tensor/cast_storage-inl.h), sparse dot (dot-inl.h).

TPU-native re-design (SURVEY §7 hard part 2): TPUs have no native sparse memory
format, so sparse arrays are pairs of *dense* arrays — ``row_sparse`` = (indices
(nnz,), values (nnz, *row_shape)) and ``csr`` = (indptr, indices, data) — and sparse
ops are gather/scatter/segment-sum HLO. This matches how the reference's kvstore uses
row_sparse (pull rows by id) while staying jit-friendly: all shapes static per nnz.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as _np

from ..base import MXNetError
from .ndarray import NDArray, _apply, array


class BaseSparseNDArray(NDArray):
    """Common behavior for sparse storage types.

    Arithmetic follows the reference's storage dispatch
    (python/mxnet/ndarray/sparse.py + FInferStorageType fallback rules):
    zero-preserving scalar ops (``*``, ``/``, ``-x``, ``abs``, ``**k`` for
    k>0) stay in the same sparse format by mapping over the stored values;
    same-format ``+``/``-`` of two row_sparse merges sparsely; everything
    else densifies BOTH operands first and returns a dense NDArray (the
    reference's storage fallback). The base NDArray dunders would
    otherwise operate on ``_data`` — the VALUES buffer — and silently
    return wrong-shaped results.
    """

    __slots__ = ("_aux",)

    _SCALAR = (int, float, bool, _np.number)

    def asnumpy(self):
        return self.todense().asnumpy()

    def __repr__(self):
        return "\n<%s %s @%s>" % (type(self).__name__,
                                  "x".join(map(str, self.shape)), self.context)

    def todense(self) -> NDArray:
        raise NotImplementedError

    def _replace_values(self, vals):
        """Same indices/shape, new values (zero-preserving maps only)."""
        raise NotImplementedError

    def attach_grad(self, grad_req="write", stype=None):
        """Mark a sparse leaf; its gradient is a SAME-FORMAT sparse array
        sharing this array's indices (ref: row_sparse weights receive
        row_sparse grads — attach_grad(stype=...) in the reference). The
        tape stores the sparse object itself as the op input, so leaf
        cotangents arrive values-shaped; the grad buffer must therefore be
        a sparse wrapper over a values-shaped buffer, not a dense
        logical-shape array (which would crash 'add' accumulation and
        silently mis-shape 'write')."""
        if stype is not None and stype != self.stype:
            raise MXNetError(
                "grad stype %r unsupported for a %s leaf: its tape "
                "cotangents are values-shaped" % (stype, self.stype))
        self._ag_entry = None
        self._grad = self._replace_values(jnp.zeros_like(self._data))
        self._grad_req = grad_req

    def _values_map(self, fn, name):
        """Zero-preserving map over the stored values, routed through
        ``_apply`` so it tapes under autograd.record() and emits profiler
        events like every other op (a sparse NDArray IS an NDArray whose
        _data is the values buffer, so ``self`` is the taped input); the
        sparse result adopts the taped output's autograd entry."""
        out = _apply(fn, (self,), name=name)
        res = self._replace_values(out._data)
        res._ag_entry = out._ag_entry
        return res

    def tostype(self, stype):
        if stype == self.stype:
            return self
        if stype == "default":
            return self.todense()
        return cast_storage(self.todense(), stype)

    # ------------------------------------------------------- arithmetic
    def _dense_fallback(self, other, op):
        rhs = other.todense() if isinstance(other, BaseSparseNDArray) else other
        return getattr(self.todense(), op)(rhs)

    def __mul__(self, o):
        if isinstance(o, self._SCALAR):
            return self._values_map(lambda v: v * o, "_mul_scalar")
        return self._dense_fallback(o, "__mul__")

    __rmul__ = __mul__

    def __truediv__(self, o):
        if isinstance(o, self._SCALAR):
            return self._values_map(lambda v: v / o, "_div_scalar")
        return self._dense_fallback(o, "__truediv__")

    def __rtruediv__(self, o):  # scalar / x maps zeros to inf: densify
        return self._dense_fallback(o, "__rtruediv__")

    def __pow__(self, o):
        # 0**k==0 iff k>0 (real k only — complex exponents have no order
        # and take the dense fallback like every other non-preserving case)
        if isinstance(o, self._SCALAR) \
                and not isinstance(o, (complex, _np.complexfloating)) \
                and o > 0:
            return self._values_map(lambda v: v ** o, "_power_scalar")
        return self._dense_fallback(o, "__pow__")

    def __neg__(self):
        return self._values_map(jnp.negative, "negative")

    def __abs__(self):
        return self._values_map(jnp.abs, "abs")

    def __add__(self, o):
        merged = self._sparse_merge(o, 1.0)
        return merged if merged is not None \
            else self._dense_fallback(o, "__add__")

    __radd__ = __add__

    def __sub__(self, o):
        merged = self._sparse_merge(o, -1.0)
        return merged if merged is not None \
            else self._dense_fallback(o, "__sub__")

    def __rsub__(self, o):
        return self._dense_fallback(o, "__rsub__")

    def _sparse_merge(self, other, sign):
        """Same-format sparse +/-; None means 'use the dense fallback'."""
        return None

    # in-place: only format-preserving updates may mutate; others would
    # silently change the storage type under the caller (ref: sparse
    # NDArrays reject kWriteInplace into a different stype). Like the dense
    # in-place ops (ndarray.py), they rebind the payload + autograd entry,
    # so they tape as ordinary ops while recording.
    def _inplace_from(self, res, opname):
        if res is None:
            raise MXNetError("in-place %s on %s supports only a "
                             "format-preserving rhs; use explicit "
                             "tostype('default')" % (opname, self.stype))
        self._ag_entry = res._ag_entry
        self._set_data(res._data)
        self._aux = dict(res._aux)
        return self

    def __imul__(self, o):
        if not isinstance(o, self._SCALAR):
            raise MXNetError("in-place *= on %s would densify; use explicit "
                             "tostype('default')" % self.stype)
        return self._inplace_from(self.__mul__(o), "*=")

    def __itruediv__(self, o):
        if not isinstance(o, self._SCALAR):
            raise MXNetError("in-place /= on %s would densify; use explicit "
                             "tostype('default')" % self.stype)
        return self._inplace_from(self.__truediv__(o), "/=")

    def __iadd__(self, o):
        return self._inplace_from(self._sparse_merge(o, 1), "+=")

    def __isub__(self, o):
        return self._inplace_from(self._sparse_merge(o, -1), "-=")

    # comparisons: never meaningful on the raw values buffer
    def __eq__(self, o):
        return self._dense_fallback(o, "__eq__")

    def __ne__(self, o):
        return self._dense_fallback(o, "__ne__")

    def __gt__(self, o):
        return self._dense_fallback(o, "__gt__")

    def __ge__(self, o):
        return self._dense_fallback(o, "__ge__")

    def __lt__(self, o):
        return self._dense_fallback(o, "__lt__")

    def __le__(self, o):
        return self._dense_fallback(o, "__le__")

    __hash__ = NDArray.__hash__


class RowSparseNDArray(BaseSparseNDArray):
    """(indices, values) pair: values[i] is the dense row at row id indices[i]
    (ref: python/mxnet/ndarray/sparse.py:RowSparseNDArray)."""

    def __init__(self, values, indices, shape):
        # _data holds values; indices kept as aux (int32 sorted unique row ids)
        v = values._data if isinstance(values, NDArray) else jnp.asarray(values)
        idx = indices._data if isinstance(indices, NDArray) else jnp.asarray(indices)
        super().__init__(v)
        self._aux = {"indices": idx.astype(jnp.int32), "shape": tuple(shape)}

    @property
    def stype(self):
        return "row_sparse"

    @property
    def shape(self):
        return self._aux["shape"]

    @property
    def indices(self) -> NDArray:
        return NDArray(self._aux["indices"])

    @property
    def data(self) -> NDArray:
        return NDArray(self._data)

    def todense(self) -> NDArray:
        indices, shape = self._aux["indices"], self.shape

        def fn(v):
            return jnp.zeros(shape, v.dtype).at[indices].add(v)

        # through _apply: autograd-visible (grads gather back to the stored
        # rows), profiler-visible — the dense-fallback arithmetic and every
        # sparse->dense chain tape through here
        return _apply(fn, (self,), name="cast_storage")

    def _replace_values(self, vals):
        return RowSparseNDArray(vals, self._aux["indices"], self.shape)

    def _sparse_merge(self, other, sign):
        """rsp ± rsp without densifying (the embedding-gradient workload:
        (vocab, dim) arrays whose dense form must never materialize).
        Union of row ids via unique + segment-add of both value blocks;
        the index plumbing is computed eagerly (data-independent of the
        VALUES) while the value math routes through _apply for taping."""
        if not isinstance(other, RowSparseNDArray) or other.shape != self.shape:
            return None
        idx = jnp.concatenate([self._aux["indices"], other._aux["indices"]])
        uidx, inv = jnp.unique(idx, return_inverse=True)
        n_out = int(uidx.shape[0])
        row_shape = self._data.shape[1:]
        dtype = jnp.result_type(self._data.dtype, other._data.dtype)

        def fn(va, vb):
            vb = vb.astype(dtype)
            cat = jnp.concatenate([va.astype(dtype),
                                   -vb if sign < 0 else vb])
            return jnp.zeros((n_out,) + row_shape, dtype).at[inv].add(cat)

        out = _apply(fn, (self, other), name="elemwise_add")
        res = RowSparseNDArray(out._data, uidx, self.shape)
        res._ag_entry = out._ag_entry
        return res

    def retain(self, row_ids):
        """Keep only the given rows (ref: sparse_retain op,
        src/operator/tensor/sparse_retain-inl.h)."""
        rid = row_ids._data.astype(jnp.int32) if isinstance(row_ids, NDArray) \
            else jnp.asarray(row_ids, jnp.int32)
        mask = jnp.isin(self._aux["indices"], rid)
        keep = _np.asarray(mask)
        idx = _np.asarray(self._aux["indices"])[keep]
        vals = _np.asarray(self._data)[keep]
        return RowSparseNDArray(jnp.asarray(vals), jnp.asarray(idx), self.shape)

    def copyto(self, other):
        if isinstance(other, RowSparseNDArray):
            other._set_data(self._data)
            other._aux = dict(self._aux)
            return other
        return self.todense().copyto(other)

    def _serialize_parts(self):
        return [("indices", _np.asarray(self._aux["indices"])),
                ("values", _np.asarray(self._data))]


class CSRNDArray(BaseSparseNDArray):
    """Compressed sparse row matrix (ref: python/mxnet/ndarray/sparse.py:CSRNDArray)."""

    def __init__(self, data, indptr, indices, shape):
        d = data._data if isinstance(data, NDArray) else jnp.asarray(data)
        super().__init__(d)
        ip = indptr._data if isinstance(indptr, NDArray) else jnp.asarray(indptr)
        idx = indices._data if isinstance(indices, NDArray) else jnp.asarray(indices)
        self._aux = {"indptr": ip.astype(jnp.int32), "indices": idx.astype(jnp.int32),
                     "shape": tuple(shape)}

    @property
    def stype(self):
        return "csr"

    @property
    def shape(self):
        return self._aux["shape"]

    @property
    def indices(self) -> NDArray:
        return NDArray(self._aux["indices"])

    @property
    def indptr(self) -> NDArray:
        return NDArray(self._aux["indptr"])

    @property
    def data(self) -> NDArray:
        return NDArray(self._data)

    def todense(self) -> NDArray:
        m, n = self.shape
        rows = _csr_row_ids(self._aux["indptr"], self._data.shape[0])
        indices = self._aux["indices"]

        def fn(d):
            return jnp.zeros((m, n), d.dtype).at[rows, indices].add(d)

        return _apply(fn, (self,), name="cast_storage")

    def _replace_values(self, vals):
        return CSRNDArray(vals, self._aux["indptr"], self._aux["indices"],
                          self.shape)

    def _sparse_merge(self, other, sign):
        """csr ± csr keeps the csr format; 2-D shapes are modest in the
        csr workloads (batches), so merge via dense then re-compress.
        The dense sum is taped (todense routes through _apply); only the
        re-compression structure is computed on host."""
        if not isinstance(other, CSRNDArray) or other.shape != self.shape:
            return None
        dense = self.todense() + (-other.todense() if sign < 0
                                  else other.todense())
        res = cast_storage(dense, "csr")
        return res

    def __getitem__(self, key):
        if isinstance(key, slice):
            return self.todense()[key]
        return self.todense()[key]

    def copyto(self, other):
        if isinstance(other, CSRNDArray):
            other._set_data(self._data)
            other._aux = dict(self._aux)
            return other
        return self.todense().copyto(other)

    def _serialize_parts(self):
        return [("indptr", _np.asarray(self._aux["indptr"])),
                ("indices", _np.asarray(self._aux["indices"])),
                ("data", _np.asarray(self._data))]


def _deserialize_parts(stype, shape, parts):
    if stype == "row_sparse":
        return RowSparseNDArray(parts["values"], parts["indices"], shape)
    if stype == "csr":
        return CSRNDArray(parts["data"], parts["indptr"], parts["indices"], shape)
    raise MXNetError("unknown stype " + stype)


def row_sparse_array(arg1, shape=None, ctx=None, dtype=None):
    """Create a RowSparseNDArray from (data, indices) or a dense source
    (ref: mx.nd.sparse.row_sparse_array)."""
    if isinstance(arg1, tuple) and len(arg1) == 2:
        data, indices = arg1
        data = array(data, dtype=dtype)
        return RowSparseNDArray(data, array(indices), shape)
    dense = array(arg1, dtype=dtype)
    return cast_storage(dense, "row_sparse")


def csr_matrix(arg1, shape=None, ctx=None, dtype=None):
    """Create a CSRNDArray (ref: mx.nd.sparse.csr_matrix)."""
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = arg1
        return CSRNDArray(array(data, dtype=dtype), array(indptr), array(indices), shape)
    dense = array(arg1, dtype=dtype)
    return cast_storage(dense, "csr")


def cast_storage(arr, stype):
    """dense ↔ row_sparse ↔ csr conversion (ref: cast_storage-inl.h; op
    `cast_storage`). Host-side nnz discovery (dynamic shapes are not jit-friendly;
    conversion is a data-prep step, as in the reference's IO path)."""
    if arr.stype == stype:
        return arr
    if isinstance(arr, BaseSparseNDArray):
        arr = arr.todense()
        if stype == "default":
            return arr
    a = _np.asarray(arr.asnumpy())
    if stype == "row_sparse":
        if a.ndim < 1:
            raise MXNetError("row_sparse requires ndim>=1")
        row_nz = _np.nonzero(a.reshape(a.shape[0], -1).any(axis=1))[0]
        ridx = jnp.asarray(row_nz, jnp.int32)
        # structure (which rows) comes from the host sync above; the VALUES
        # are gathered through _apply so the cast stays autograd-visible
        # (grads scatter back into the dense source)
        vals = _apply(lambda d: d[ridx], (arr,), name="cast_storage")
        res = RowSparseNDArray(vals._data, ridx, a.shape)
        res._ag_entry = vals._ag_entry
        return res
    if stype == "csr":
        if a.ndim != 2:
            raise MXNetError("csr requires 2D")
        rows, cols = _np.nonzero(a)
        indptr = _np.zeros(a.shape[0] + 1, _np.int32)
        _np.add.at(indptr, rows + 1, 1)
        indptr = _np.cumsum(indptr).astype(_np.int32)
        ri, ci = jnp.asarray(rows, jnp.int32), jnp.asarray(cols, jnp.int32)
        vals = _apply(lambda d: d[ri, ci], (arr,), name="cast_storage")
        res = CSRNDArray(vals._data, jnp.asarray(indptr), ci, a.shape)
        res._ag_entry = vals._ag_entry
        return res
    if stype == "default":
        return arr
    raise MXNetError("unknown stype " + stype)


def zeros(stype, shape, ctx=None, dtype="float32"):
    if stype == "row_sparse":
        rs = shape[1:] if len(shape) > 1 else ()
        return RowSparseNDArray(jnp.zeros((0,) + tuple(rs), dtype),
                                jnp.zeros((0,), jnp.int32), shape)
    if stype == "csr":
        return CSRNDArray(jnp.zeros((0,), dtype), jnp.zeros((shape[0] + 1,), jnp.int32),
                          jnp.zeros((0,), jnp.int32), shape)
    from ..ops.init_ops import zeros as dzeros
    return dzeros(shape, ctx=ctx, dtype=dtype)


def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """Sparse-aware dot (ref: src/operator/tensor/dot-inl.h sparse paths).

    The hot path — ``csr (B, F) x dense (F, C)`` with a huge feature dim F
    (the reference's DotCsrDnsDnsImpl, the sparse linear-classification /
    NCE workload) — NEVER materializes the (B, F) dense matrix: each nnz
    gathers its weight row and a segment-sum scatters into the B outputs,
    O(nnz*C) work and memory. Everything else (csr^T, row_sparse operands)
    falls back to dense einsum after materialization — fine for small F,
    a measured cliff for large F (see examples/sparse/README)."""
    from ..ops.matrix import dot as dense_dot
    if isinstance(lhs, CSRNDArray) and not transpose_a \
            and not isinstance(rhs, BaseSparseNDArray) and rhs.ndim == 2:
        num_rows = lhs.shape[0]

        def fn(data, indptr, indices, r):
            if transpose_b:
                r = r.T
            return _csr_dns_dot(data, indptr, indices, num_rows, r)

        # through _apply so autograd tapes the call: grads flow to the csr
        # values and to the dense rhs (the row-sparse rhs-grad workload).
        # `lhs` itself is the first input — its _data IS the values buffer,
        # and passing the object (not a fresh .data view) keeps the tape
        # connected through any upstream sparse ops (e.g. `csr * 2.0`)
        return _apply(fn, (lhs, lhs.indptr, lhs.indices, rhs),
                      name="dot_csr_dns")
    l = lhs.todense() if isinstance(lhs, BaseSparseNDArray) else lhs
    r = rhs.todense() if isinstance(rhs, BaseSparseNDArray) else rhs
    return dense_dot(l, r, transpose_a=transpose_a, transpose_b=transpose_b)


def _csr_row_ids(indptr, nnz):
    """Row id of each nnz element of a CSR matrix (shared by todense /
    csr-dot / sparse-grad construction)."""
    return jnp.searchsorted(indptr, jnp.arange(nnz), side="right") - 1


def _csr_dns_dot(data, indptr, indices, num_rows, rhs):
    """out[b] = sum_{j in row b} data[j] * rhs[indices[j]] via gather +
    segment-sum — static shapes per nnz, MXU-free VPU work."""
    import jax

    rows = _csr_row_ids(indptr, data.shape[0])
    contrib = rhs[indices] * data[:, None].astype(rhs.dtype)
    return jax.ops.segment_sum(contrib, rows, num_segments=num_rows)
