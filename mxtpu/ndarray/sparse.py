"""Sparse NDArrays: row_sparse and csr storage types.

Reference: include/mxnet/ndarray.h:61-66 (kDefaultStorage/kRowSparseStorage/
kCSRStorage), python/mxnet/ndarray/sparse.py, cast_storage
(src/operator/tensor/cast_storage-inl.h), sparse dot (dot-inl.h).

TPU-native re-design (SURVEY §7 hard part 2): TPUs have no native sparse memory
format, so sparse arrays are pairs of *dense* arrays — ``row_sparse`` = (indices
(nnz,), values (nnz, *row_shape)) and ``csr`` = (indptr, indices, data) — and sparse
ops are gather/scatter/segment-sum HLO. This matches how the reference's kvstore uses
row_sparse (pull rows by id) while staying jit-friendly: all shapes static per nnz.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as _np

from ..base import MXNetError
from .ndarray import NDArray, array


class BaseSparseNDArray(NDArray):
    """Common behavior for sparse storage types."""

    __slots__ = ("_aux",)

    def asnumpy(self):
        return self.todense().asnumpy()

    def __repr__(self):
        return "\n<%s %s @%s>" % (type(self).__name__,
                                  "x".join(map(str, self.shape)), self.context)

    def todense(self) -> NDArray:
        raise NotImplementedError

    def tostype(self, stype):
        if stype == self.stype:
            return self
        if stype == "default":
            return self.todense()
        return cast_storage(self.todense(), stype)


class RowSparseNDArray(BaseSparseNDArray):
    """(indices, values) pair: values[i] is the dense row at row id indices[i]
    (ref: python/mxnet/ndarray/sparse.py:RowSparseNDArray)."""

    def __init__(self, values, indices, shape):
        # _data holds values; indices kept as aux (int32 sorted unique row ids)
        v = values._data if isinstance(values, NDArray) else jnp.asarray(values)
        idx = indices._data if isinstance(indices, NDArray) else jnp.asarray(indices)
        super().__init__(v)
        self._aux = {"indices": idx.astype(jnp.int32), "shape": tuple(shape)}

    @property
    def stype(self):
        return "row_sparse"

    @property
    def shape(self):
        return self._aux["shape"]

    @property
    def indices(self) -> NDArray:
        return NDArray(self._aux["indices"])

    @property
    def data(self) -> NDArray:
        return NDArray(self._data)

    def todense(self) -> NDArray:
        dense = jnp.zeros(self.shape, self._data.dtype)
        dense = dense.at[self._aux["indices"]].add(self._data)
        return NDArray(dense)

    def retain(self, row_ids):
        """Keep only the given rows (ref: sparse_retain op,
        src/operator/tensor/sparse_retain-inl.h)."""
        rid = row_ids._data.astype(jnp.int32) if isinstance(row_ids, NDArray) \
            else jnp.asarray(row_ids, jnp.int32)
        mask = jnp.isin(self._aux["indices"], rid)
        keep = _np.asarray(mask)
        idx = _np.asarray(self._aux["indices"])[keep]
        vals = _np.asarray(self._data)[keep]
        return RowSparseNDArray(jnp.asarray(vals), jnp.asarray(idx), self.shape)

    def copyto(self, other):
        if isinstance(other, RowSparseNDArray):
            other._set_data(self._data)
            other._aux = dict(self._aux)
            return other
        return self.todense().copyto(other)

    def _serialize_parts(self):
        return [("indices", _np.asarray(self._aux["indices"])),
                ("values", _np.asarray(self._data))]


class CSRNDArray(BaseSparseNDArray):
    """Compressed sparse row matrix (ref: python/mxnet/ndarray/sparse.py:CSRNDArray)."""

    def __init__(self, data, indptr, indices, shape):
        d = data._data if isinstance(data, NDArray) else jnp.asarray(data)
        super().__init__(d)
        ip = indptr._data if isinstance(indptr, NDArray) else jnp.asarray(indptr)
        idx = indices._data if isinstance(indices, NDArray) else jnp.asarray(indices)
        self._aux = {"indptr": ip.astype(jnp.int32), "indices": idx.astype(jnp.int32),
                     "shape": tuple(shape)}

    @property
    def stype(self):
        return "csr"

    @property
    def shape(self):
        return self._aux["shape"]

    @property
    def indices(self) -> NDArray:
        return NDArray(self._aux["indices"])

    @property
    def indptr(self) -> NDArray:
        return NDArray(self._aux["indptr"])

    @property
    def data(self) -> NDArray:
        return NDArray(self._data)

    def todense(self) -> NDArray:
        m, n = self.shape
        indptr = self._aux["indptr"]
        indices = self._aux["indices"]
        rows = _csr_row_ids(indptr, self._data.shape[0])
        dense = jnp.zeros((m, n), self._data.dtype)
        dense = dense.at[rows, indices].add(self._data)
        return NDArray(dense)

    def __getitem__(self, key):
        if isinstance(key, slice):
            return self.todense()[key]
        return self.todense()[key]

    def copyto(self, other):
        if isinstance(other, CSRNDArray):
            other._set_data(self._data)
            other._aux = dict(self._aux)
            return other
        return self.todense().copyto(other)

    def _serialize_parts(self):
        return [("indptr", _np.asarray(self._aux["indptr"])),
                ("indices", _np.asarray(self._aux["indices"])),
                ("data", _np.asarray(self._data))]


def _deserialize_parts(stype, shape, parts):
    if stype == "row_sparse":
        return RowSparseNDArray(parts["values"], parts["indices"], shape)
    if stype == "csr":
        return CSRNDArray(parts["data"], parts["indptr"], parts["indices"], shape)
    raise MXNetError("unknown stype " + stype)


def row_sparse_array(arg1, shape=None, ctx=None, dtype=None):
    """Create a RowSparseNDArray from (data, indices) or a dense source
    (ref: mx.nd.sparse.row_sparse_array)."""
    if isinstance(arg1, tuple) and len(arg1) == 2:
        data, indices = arg1
        data = array(data, dtype=dtype)
        return RowSparseNDArray(data, array(indices), shape)
    dense = array(arg1, dtype=dtype)
    return cast_storage(dense, "row_sparse")


def csr_matrix(arg1, shape=None, ctx=None, dtype=None):
    """Create a CSRNDArray (ref: mx.nd.sparse.csr_matrix)."""
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = arg1
        return CSRNDArray(array(data, dtype=dtype), array(indptr), array(indices), shape)
    dense = array(arg1, dtype=dtype)
    return cast_storage(dense, "csr")


def cast_storage(arr, stype):
    """dense ↔ row_sparse ↔ csr conversion (ref: cast_storage-inl.h; op
    `cast_storage`). Host-side nnz discovery (dynamic shapes are not jit-friendly;
    conversion is a data-prep step, as in the reference's IO path)."""
    if arr.stype == stype:
        return arr
    if isinstance(arr, BaseSparseNDArray):
        arr = arr.todense()
        if stype == "default":
            return arr
    a = _np.asarray(arr.asnumpy())
    if stype == "row_sparse":
        if a.ndim < 1:
            raise MXNetError("row_sparse requires ndim>=1")
        row_nz = _np.nonzero(a.reshape(a.shape[0], -1).any(axis=1))[0]
        vals = a[row_nz]
        return RowSparseNDArray(jnp.asarray(vals), jnp.asarray(row_nz), a.shape)
    if stype == "csr":
        if a.ndim != 2:
            raise MXNetError("csr requires 2D")
        rows, cols = _np.nonzero(a)
        data = a[rows, cols]
        indptr = _np.zeros(a.shape[0] + 1, _np.int32)
        _np.add.at(indptr, rows + 1, 1)
        indptr = _np.cumsum(indptr).astype(_np.int32)
        return CSRNDArray(jnp.asarray(data), jnp.asarray(indptr), jnp.asarray(cols), a.shape)
    if stype == "default":
        return arr
    raise MXNetError("unknown stype " + stype)


def zeros(stype, shape, ctx=None, dtype="float32"):
    if stype == "row_sparse":
        rs = shape[1:] if len(shape) > 1 else ()
        return RowSparseNDArray(jnp.zeros((0,) + tuple(rs), dtype),
                                jnp.zeros((0,), jnp.int32), shape)
    if stype == "csr":
        return CSRNDArray(jnp.zeros((0,), dtype), jnp.zeros((shape[0] + 1,), jnp.int32),
                          jnp.zeros((0,), jnp.int32), shape)
    from ..ops.init_ops import zeros as dzeros
    return dzeros(shape, ctx=ctx, dtype=dtype)


def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """Sparse-aware dot (ref: src/operator/tensor/dot-inl.h sparse paths).

    The hot path — ``csr (B, F) x dense (F, C)`` with a huge feature dim F
    (the reference's DotCsrDnsDnsImpl, the sparse linear-classification /
    NCE workload) — NEVER materializes the (B, F) dense matrix: each nnz
    gathers its weight row and a segment-sum scatters into the B outputs,
    O(nnz*C) work and memory. Everything else (csr^T, row_sparse operands)
    falls back to dense einsum after materialization — fine for small F,
    a measured cliff for large F (see examples/sparse/README)."""
    from ..ops.matrix import dot as dense_dot
    if isinstance(lhs, CSRNDArray) and not transpose_a \
            and not isinstance(rhs, BaseSparseNDArray) and rhs.ndim == 2:
        from .ndarray import _apply
        num_rows = lhs.shape[0]

        def fn(data, indptr, indices, r):
            if transpose_b:
                r = r.T
            return _csr_dns_dot(data, indptr, indices, num_rows, r)

        # through _apply so autograd tapes the call: grads flow to the csr
        # values and to the dense rhs (the row-sparse rhs-grad workload)
        return _apply(fn, (lhs.data, lhs.indptr, lhs.indices, rhs),
                      name="dot_csr_dns")
    l = lhs.todense() if isinstance(lhs, BaseSparseNDArray) else lhs
    r = rhs.todense() if isinstance(rhs, BaseSparseNDArray) else rhs
    return dense_dot(l, r, transpose_a=transpose_a, transpose_b=transpose_b)


def _csr_row_ids(indptr, nnz):
    """Row id of each nnz element of a CSR matrix (shared by todense /
    csr-dot / sparse-grad construction)."""
    return jnp.searchsorted(indptr, jnp.arange(nnz), side="right") - 1


def _csr_dns_dot(data, indptr, indices, num_rows, rhs):
    """out[b] = sum_{j in row b} data[j] * rhs[indices[j]] via gather +
    segment-sum — static shapes per nnz, MXU-free VPU work."""
    import jax

    rows = _csr_row_ids(indptr, data.shape[0])
    contrib = rhs[indices] * data[:, None].astype(rhs.dtype)
    return jax.ops.segment_sum(contrib, rows, num_segments=num_rows)
