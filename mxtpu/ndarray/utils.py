"""NDArray serialization (ref: src/ndarray/ndarray.cc:1574-1806 Save/Load with magic
number + versioned blobs; python surface mx.nd.save/load).

Two on-disk formats, auto-detected by magic on load:

* the REFERENCE format (u64 magic 0x112 ``kMXAPINDArrayListMagic`` +
  versioned per-array records — ``mxnet_format.py``), byte-compatible
  with files real MXNet writes and reads. This is the DEFAULT save
  format whenever every array has a reference-representable dtype, so
  ``.params`` files interchange with the reference both ways.
* the native TPU format (magic ``MXTPU001`` + JSON header + raw
  buffers), used automatically for bfloat16 arrays (the reference's
  mshadow dtype table predates bf16) or on request (``format="mxtpu"``).
"""
from __future__ import annotations

import json
import struct

import numpy as _np

from ..base import MXNetError
from . import mxnet_format
from .ndarray import NDArray, array

_MAGIC = b"MXTPU001"


def _to_bytes(arr: NDArray):
    a = arr.asnumpy() if str(arr.dtype) != "bfloat16" else None
    if a is None:
        import jax.numpy as jnp
        a = _np.asarray(arr._data.astype(jnp.float32))
        return a.tobytes(), "bfloat16", a.shape
    return a.tobytes(), str(_np.dtype(a.dtype).name), a.shape


def save(fname: str, data, format=None) -> None:  # noqa: A002
    """Save NDArrays (list or dict) to file (ref: mx.nd.save → MXNDArraySave).

    ``format``: ``"mxnet"`` = reference byte format (0x112), ``"mxtpu"`` =
    native, ``None`` = reference format unless an array needs a dtype the
    reference can't encode losslessly (bfloat16), then native.
    """
    if isinstance(data, NDArray):
        data = [data]
    if isinstance(data, dict):
        names = list(data.keys())
        arrays = [data[k] for k in names]
    elif isinstance(data, (list, tuple)):
        names = [""] * len(data)
        arrays = list(data)
    else:
        raise MXNetError("save expects NDArray, list, or dict")

    if format is None:
        # reference format only when every array round-trips losslessly:
        # bf16/bool/int16/... have no mshadow flag, and rank-0 shapes read
        # back as "none" records -> native format for those
        format = "mxnet" if all(mxnet_format.ref_encodable(a.dtype)
                                and len(a.shape) > 0
                                for a in arrays) else "mxtpu"
    if format == "mxnet":
        from .sparse import BaseSparseNDArray
        items = []
        for arr in arrays:
            if isinstance(arr, BaseSparseNDArray):
                parts = dict(arr._serialize_parts())
                parts["shape"] = arr.shape
                items.append((arr.stype, parts))
            elif str(arr.dtype) == "bfloat16":  # no reference dtype flag
                items.append(("default", arr.astype("float32").asnumpy()))
            else:
                items.append(("default", arr.asnumpy()))
        blob = mxnet_format.dumps(
            items, names if isinstance(data, dict) else [])
        with open(fname, "wb") as f:
            f.write(blob)
        return
    if format != "mxtpu":
        raise MXNetError("unknown save format %r" % (format,))

    entries = []
    blobs = []
    offset = 0
    for name, arr in zip(names, arrays):
        from .sparse import BaseSparseNDArray
        if isinstance(arr, BaseSparseNDArray):
            parts = arr._serialize_parts()
            part_entries = []
            for pname, pa in parts:
                b = pa.tobytes()
                part_entries.append({"part": pname, "dtype": str(pa.dtype),
                                     "shape": list(pa.shape), "offset": offset,
                                     "nbytes": len(b)})
                blobs.append(b)
                offset += len(b)
            entries.append({"name": name, "stype": arr.stype,
                            "shape": list(arr.shape), "parts": part_entries})
        else:
            b, dt, shape = _to_bytes(arr)
            entries.append({"name": name, "stype": "default", "dtype": dt,
                            "shape": list(shape), "offset": offset, "nbytes": len(b)})
            blobs.append(b)
            offset += len(b)

    header = json.dumps({"entries": entries, "named": isinstance(data, dict)}).encode()
    with open(fname, "wb") as f:
        f.write(_MAGIC)
        f.write(struct.pack("<Q", len(header)))
        f.write(header)
        for b in blobs:
            f.write(b)


def load(fname: str):
    """Load NDArrays (ref: mx.nd.load → MXNDArrayLoad). Returns list or
    dict. Auto-detects the reference 0x112 format (files written by real
    MXNet load directly) vs the native MXTPU001 format."""
    with open(fname, "rb") as f:
        magic = f.read(8)
        if magic != _MAGIC:
            if struct.unpack("<Q", magic.ljust(8, b"\0"))[0] == \
                    mxnet_format.LIST_MAGIC:
                return _load_mxnet(magic + f.read())
            raise MXNetError("invalid NDArray file %s (bad magic)" % fname)
        (hlen,) = struct.unpack("<Q", f.read(8))
        header = json.loads(f.read(hlen).decode())
        payload = f.read()

    def read_dense(e):
        dt = e["dtype"]
        np_dt = _np.float32 if dt == "bfloat16" else _np.dtype(dt)
        a = _np.frombuffer(payload, dtype=np_dt, count=_np.prod(e["shape"], dtype=int) if e["shape"] else 1,
                           offset=e["offset"]).reshape(e["shape"])
        nd = array(a)
        if dt == "bfloat16":
            nd = nd.astype("bfloat16")
        return nd

    out = []
    for e in header["entries"]:
        if e["stype"] == "default":
            out.append((e["name"], read_dense(e)))
        else:
            from .sparse import _deserialize_parts
            parts = {}
            for pe in e["parts"]:
                a = _np.frombuffer(payload, dtype=_np.dtype(pe["dtype"]),
                                   count=_np.prod(pe["shape"], dtype=int) if pe["shape"] else 1,
                                   offset=pe["offset"]).reshape(pe["shape"])
                parts[pe["part"]] = a
            out.append((e["name"], _deserialize_parts(e["stype"], tuple(e["shape"]), parts)))

    if header["named"]:
        return {k: v for k, v in out}
    return [v for _, v in out]


def _load_mxnet(buf):
    """Reference-format blob -> list or dict of NDArrays."""
    from .sparse import _deserialize_parts
    items, names = mxnet_format.loads(buf)
    arrays = []
    for stype, payload in items:
        if stype == "default":
            arrays.append(array(payload))
        else:
            shape = tuple(int(d) for d in payload.pop("shape"))
            arrays.append(_deserialize_parts(stype, shape, payload))
    if names:
        return dict(zip(names, arrays))
    return arrays
