"""NDArray serialization (ref: src/ndarray/ndarray.cc:1574-1776 Save/Load with magic
number + versioned blobs; python surface mx.nd.save/load).

Format (TPU build): a single file, magic ``MXTPU001`` + JSON header (names, shapes,
dtypes, storage types, byte offsets) + raw little-endian buffers. Dense and sparse
(row_sparse/csr as index+value buffers) supported, mirroring the reference's
sparse-aware format. Legacy MXNet files are not binary-compatible (the reference's
format embeds mshadow TBlob headers), but the API is identical.
"""
from __future__ import annotations

import json
import struct

import numpy as _np

from ..base import MXNetError
from .ndarray import NDArray, array

_MAGIC = b"MXTPU001"


def _to_bytes(arr: NDArray):
    a = arr.asnumpy() if str(arr.dtype) != "bfloat16" else None
    if a is None:
        import jax.numpy as jnp
        a = _np.asarray(arr._data.astype(jnp.float32))
        return a.tobytes(), "bfloat16", a.shape
    return a.tobytes(), str(_np.dtype(a.dtype).name), a.shape


def save(fname: str, data) -> None:
    """Save NDArrays (list or dict) to file (ref: mx.nd.save → MXNDArraySave)."""
    if isinstance(data, NDArray):
        data = [data]
    if isinstance(data, dict):
        names = list(data.keys())
        arrays = [data[k] for k in names]
    elif isinstance(data, (list, tuple)):
        names = [""] * len(data)
        arrays = list(data)
    else:
        raise MXNetError("save expects NDArray, list, or dict")

    entries = []
    blobs = []
    offset = 0
    for name, arr in zip(names, arrays):
        from .sparse import BaseSparseNDArray
        if isinstance(arr, BaseSparseNDArray):
            parts = arr._serialize_parts()
            part_entries = []
            for pname, pa in parts:
                b = pa.tobytes()
                part_entries.append({"part": pname, "dtype": str(pa.dtype),
                                     "shape": list(pa.shape), "offset": offset,
                                     "nbytes": len(b)})
                blobs.append(b)
                offset += len(b)
            entries.append({"name": name, "stype": arr.stype,
                            "shape": list(arr.shape), "parts": part_entries})
        else:
            b, dt, shape = _to_bytes(arr)
            entries.append({"name": name, "stype": "default", "dtype": dt,
                            "shape": list(shape), "offset": offset, "nbytes": len(b)})
            blobs.append(b)
            offset += len(b)

    header = json.dumps({"entries": entries, "named": isinstance(data, dict)}).encode()
    with open(fname, "wb") as f:
        f.write(_MAGIC)
        f.write(struct.pack("<Q", len(header)))
        f.write(header)
        for b in blobs:
            f.write(b)


def load(fname: str):
    """Load NDArrays (ref: mx.nd.load → MXNDArrayLoad). Returns list or dict."""
    with open(fname, "rb") as f:
        magic = f.read(8)
        if magic != _MAGIC:
            raise MXNetError("invalid NDArray file %s (bad magic)" % fname)
        (hlen,) = struct.unpack("<Q", f.read(8))
        header = json.loads(f.read(hlen).decode())
        payload = f.read()

    def read_dense(e):
        dt = e["dtype"]
        np_dt = _np.float32 if dt == "bfloat16" else _np.dtype(dt)
        a = _np.frombuffer(payload, dtype=np_dt, count=_np.prod(e["shape"], dtype=int) if e["shape"] else 1,
                           offset=e["offset"]).reshape(e["shape"])
        nd = array(a)
        if dt == "bfloat16":
            nd = nd.astype("bfloat16")
        return nd

    out = []
    for e in header["entries"]:
        if e["stype"] == "default":
            out.append((e["name"], read_dense(e)))
        else:
            from .sparse import _deserialize_parts
            parts = {}
            for pe in e["parts"]:
                a = _np.frombuffer(payload, dtype=_np.dtype(pe["dtype"]),
                                   count=_np.prod(pe["shape"], dtype=int) if pe["shape"] else 1,
                                   offset=pe["offset"]).reshape(pe["shape"])
                parts[pe["part"]] = a
            out.append((e["name"], _deserialize_parts(e["stype"], tuple(e["shape"]), parts)))

    if header["named"]:
        return {k: v for k, v in out}
    return [v for _, v in out]
