"""``mx.nd.linalg`` namespace (ref: python/mxnet/ndarray/linalg.py — the
``linalg_*`` registry ops exposed without their prefix: gemm2, potrf,
syrk, ...). Generated from the registry like the reference's codegen.
"""
import sys as _sys

from ..ops import registry as _reg

_PREFIX = "linalg_"
_mod = _sys.modules[__name__]
for _name, _op in list(_reg.REGISTRY.items()):
    if _name.startswith(_PREFIX):
        setattr(_mod, _name[len(_PREFIX):], _op.wrapper)
del _name, _op


def __getattr__(name):
    op = _reg.REGISTRY.get(_PREFIX + name)
    if op is not None:
        setattr(_mod, name, op.wrapper)
        return op.wrapper
    raise AttributeError("module %r has no attribute %r" % (__name__, name))
