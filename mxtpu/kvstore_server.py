"""Parameter-server bootstrap (ref: python/mxnet/kvstore_server.py).

The reference's server role runs a blocking ps-lite KVStore server
(kvstore_server.py:28-75, kvstore_dist_server.h). This framework's
distributed runtime is symmetric collectives over DCN (mxtpu/distributed.py)
— there IS no server role: every process is a worker participating in
allreduce, and ``dist_async`` is deliberately unsupported (see the ADR in
mxtpu/kvstore.py and README). A process launched with DMLC_ROLE=server
gets a clear error instead of a silent hang.
"""
from __future__ import annotations

import os

from .base import MXNetError

__all__ = ["KVStoreServer", "_init_kvstore_server_module"]


class KVStoreServer:
    """Kept for import parity; running it raises with the migration note."""

    def __init__(self, kvstore=None):
        self.kvstore = kvstore

    def run(self):
        raise MXNetError(
            "Parameter-server roles do not exist in the TPU runtime: "
            "distributed training is symmetric XLA collectives over "
            "ICI/DCN — join the fleet with mxtpu.fleet.init() (elastic "
            "bring-up + membership; docs/parallelism.md) or the bare "
            "mxtpu.distributed.init + kv.create('dist_sync'). Launch "
            "every process as a worker via tools/launch.py.")


def _init_kvstore_server_module():
    """Reference import hook: becomes a hard error under DMLC_ROLE=server
    or scheduler (neither role exists in the symmetric runtime — a
    scheduler that silently joined as a worker would skew the expected
    world size and hang the rendezvous), a no-op for workers."""
    if os.environ.get("DMLC_ROLE") in ("server", "scheduler"):
        KVStoreServer().run()
