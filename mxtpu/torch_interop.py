"""torch interop (ref: plugin/torch + the reference's dlpack bridge,
include/mxnet/tensor_blob.h dlpack fields).

The reference bridged Torch7 kernels through a plugin; the modern
equivalent is array interchange:

    t = mxtpu.torch_interop.to_torch(nd_array)      # torch.Tensor
    a = mxtpu.torch_interop.from_torch(tensor)      # mxtpu NDArray

Both directions COPY. Zero-copy DLPack aliasing is deliberately not used:
jax buffers are immutable by contract, so handing torch a writable view
(or aliasing a mutable torch tensor into jax) lets an in-place
``tensor.fill_`` silently change values a jit trace already captured —
wrong numerics with no error. dtype is preserved, including bfloat16
(staged through DLPack on a contiguous clone; numpy cannot carry bf16).
"""
from __future__ import annotations

from .base import MXNetError
from .ndarray import NDArray

__all__ = ["to_torch", "from_torch"]


def _torch():
    try:
        import torch
    except ImportError as e:  # pragma: no cover - torch is in this image
        raise MXNetError("torch is not installed") from e
    return torch


def to_torch(arr):
    """NDArray -> torch.Tensor (an owned copy, dtype preserved)."""
    torch = _torch()
    if not isinstance(arr, NDArray):
        raise MXNetError("to_torch expects an NDArray, got %s" % type(arr))
    data = arr._data
    try:
        # DLPack carries every dtype incl. bf16; clone() makes it an owned
        # copy so the immutable jax buffer is never exposed writable
        import jax
        host = jax.device_get(data)  # numpy-backed or jax cpu array
        return torch.from_dlpack(jax.numpy.asarray(host)).clone()
    except Exception:  # noqa: BLE001 - fall back through numpy (no bf16)
        import numpy as np
        t = torch.from_numpy(arr.asnumpy()).clone()
        want = str(data.dtype)
        if want == "bfloat16":
            t = t.to(torch.bfloat16)
        return t


def from_torch(tensor):
    """torch.Tensor -> NDArray (an owned copy, dtype preserved)."""
    torch = _torch()
    if not isinstance(tensor, torch.Tensor):
        raise MXNetError("from_torch expects a torch.Tensor, got %s"
                         % type(tensor))
    import jax.numpy as jnp
    t = tensor.detach().contiguous().cpu()
    try:
        # from_dlpack then copy via jnp.array: dtype-exact (incl. bf16),
        # and the copy severs the alias to torch's mutable memory
        return NDArray(jnp.array(jnp.from_dlpack(t)))
    except Exception:  # noqa: BLE001 - exotic dtype/layout: numpy staging
        if t.dtype == torch.bfloat16:
            return NDArray(jnp.asarray(t.to(torch.float32).numpy())
                           .astype(jnp.bfloat16))
        return NDArray(jnp.asarray(t.numpy()))
