"""mx.context module alias (ref: python/mxnet/context.py).

The implementation lives in ``base.py`` (Context maps device kinds onto
jax devices — 'gpu' means the accelerator, i.e. the TPU chip, see the
Context docstring); this module preserves the reference's import path
(``from mxnet import context`` / ``mx.context.cpu()``).
"""
from .base import Context, cpu, gpu, current_context, num_gpus

__all__ = ["Context", "cpu", "gpu", "current_context", "num_gpus"]
