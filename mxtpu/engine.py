"""Engine controls (ref: python/mxnet/engine.py — bulk-execution scoping).

The reference batches consecutive engine ops into bulks
(``MXEngineSetBulkSize``); on TPU whole-graph XLA compilation subsumes
bulking — every hybridized/jitted step IS one bulk. The API is kept so
tuning code ports, as documented no-ops returning the previous size.
"""
from __future__ import annotations

from contextlib import contextmanager

__all__ = ["bulk", "set_bulk_size"]

_bulk_size = 0


def set_bulk_size(size):
    """Set engine bulk size; returns the previous value. No-op on TPU
    (XLA fuses the whole jitted program — SURVEY §2.1 CachedOp notes)."""
    global _bulk_size
    prev, _bulk_size = _bulk_size, int(size)
    return prev


@contextmanager
def bulk(size):
    """Bulk-execution scope (ref: engine.py:bulk)."""
    prev = set_bulk_size(size)
    try:
        yield
    finally:
        set_bulk_size(prev)
