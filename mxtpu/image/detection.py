"""Detection image pipeline: box-aware augmenters + ImageDetIter.

Reference: python/mxnet/image/detection.py (DetAugmenter zoo +
ImageDetIter) and the C++ twin src/io/iter_image_det_recordio.cc with
image_det_aug_default.cc. Feeds the SSD multibox ops
(mxtpu/ops/legacy_vision.py).

Label wire format parity: a sample's raw label vector is
``[header_width A, object_width B, <extra header A-2>, obj0 ... objN]``
where each object is ``[class_id, xmin, ymin, xmax, ymax, ...]`` with
coordinates normalized to [0, 1] — exactly the reference's
``ImageDetIter._parse_label``. Batches pad the object list with -1 rows
(the convention multibox_target stops at).
"""
from __future__ import annotations

import random as _pyrandom

import numpy as np

from ..base import MXNetError
from ..io.io import DataBatch, DataDesc
from ..ndarray import array
from .image import (Augmenter, CastAug, ColorNormalizeAug, ImageIter,
                    imresize, _as_np)

__all__ = ["DetAugmenter", "DetBorrowAug", "DetHorizontalFlipAug",
           "DetRandomCropAug", "CreateDetAugmenter", "ImageDetIter"]


class DetAugmenter:
    """Image+label augmenter base (ref: detection.py:DetAugmenter)."""

    def __call__(self, src, label):
        raise NotImplementedError


class DetBorrowAug(DetAugmenter):
    """Wrap an image-only Augmenter; label passes through
    (ref: detection.py:DetBorrowAug)."""

    def __init__(self, augmenter):
        self.augmenter = augmenter

    def __call__(self, src, label):
        return self.augmenter(src), label


class DetHorizontalFlipAug(DetAugmenter):
    """Random horizontal flip mirroring the boxes
    (ref: detection.py:DetHorizontalFlipAug)."""

    def __init__(self, p=0.5):
        self.p = p

    def __call__(self, src, label):
        if _pyrandom.random() < self.p:
            src = _as_np(src)[:, ::-1]
            label = label.copy()
            valid = label[:, 0] >= 0
            x0 = label[valid, 1].copy()
            label[valid, 1] = 1.0 - label[valid, 3]
            label[valid, 3] = 1.0 - x0
        return src, label


class DetRandomCropAug(DetAugmenter):
    """Random crop keeping objects whose centers survive; boxes are clipped
    and renormalized (simplified from detection.py:DetRandomCropAug — the
    reference's min-IoU candidate sampling reduces to center-keep for the
    common SSD recipe)."""

    def __init__(self, min_crop_scale=0.5, max_attempts=10, p=0.5):
        self.min_crop_scale = float(min_crop_scale)
        self.max_attempts = int(max_attempts)
        self.p = p

    def __call__(self, src, label):
        if _pyrandom.random() > self.p:
            return src, label
        img = _as_np(src)
        h, w = img.shape[:2]
        for _ in range(self.max_attempts):
            s = _pyrandom.uniform(self.min_crop_scale, 1.0)
            cw, ch = int(w * s), int(h * s)
            x0 = _pyrandom.randint(0, w - cw)
            y0 = _pyrandom.randint(0, h - ch)
            new = label.copy()
            valid = new[:, 0] >= 0
            if not valid.any():
                break
            cx = (new[:, 1] + new[:, 3]) / 2 * w
            cy = (new[:, 2] + new[:, 4]) / 2 * h
            keep = valid & (cx >= x0) & (cx < x0 + cw) \
                & (cy >= y0) & (cy < y0 + ch)
            if not keep.any():
                continue
            # renormalize surviving boxes to the crop, clip to [0, 1]
            new[:, 1] = np.clip((new[:, 1] * w - x0) / cw, 0, 1)
            new[:, 3] = np.clip((new[:, 3] * w - x0) / cw, 0, 1)
            new[:, 2] = np.clip((new[:, 2] * h - y0) / ch, 0, 1)
            new[:, 4] = np.clip((new[:, 4] * h - y0) / ch, 0, 1)
            new[~keep] = -1.0
            return img[y0:y0 + ch, x0:x0 + cw], new
        return src, label


class _DetResizeAug(DetAugmenter):
    """Force resize to the network input; normalized boxes are unchanged."""

    def __init__(self, size, interp=1):
        self.size = size
        self.interp = interp

    def __call__(self, src, label):
        w, h = self.size
        return _as_np(imresize(src, w, h, self.interp)), label


def CreateDetAugmenter(data_shape, resize=0, rand_crop=0, rand_mirror=False,
                       mean=None, std=None, min_crop_scale=0.5,
                       inter_method=1):
    """Detection augmenter chain (ref: detection.py:CreateDetAugmenter).
    Geometry first (resize-short/crop/flip), then the forced resize, then
    color."""
    from .image import ResizeAug

    auglist = []
    if resize > 0:
        # resize-short preserves aspect ratio; normalized boxes unchanged
        auglist.append(DetBorrowAug(ResizeAug(resize, inter_method)))
    if rand_crop > 0:
        auglist.append(DetRandomCropAug(min_crop_scale=min_crop_scale,
                                        p=rand_crop))
    if rand_mirror:
        auglist.append(DetHorizontalFlipAug(0.5))
    auglist.append(_DetResizeAug((data_shape[2], data_shape[1]),
                                 inter_method))
    auglist.append(DetBorrowAug(CastAug()))
    if mean is True:
        mean = np.array([123.68, 116.28, 103.53])
    if std is True:
        std = np.array([58.395, 57.12, 57.375])
    if mean is not None or std is not None:
        # either side may be absent: normalize with identity for that side
        # (np.asarray(None) is NaN — never pass None through)
        mean = np.zeros(3) if mean is None else mean
        std = np.ones(3) if std is None else std
        auglist.append(DetBorrowAug(ColorNormalizeAug(mean, std)))
    return auglist


class ImageDetIter(ImageIter):
    """Detection iterator: image batches + padded object-list labels
    (ref: detection.py:ImageDetIter)."""

    def __init__(self, batch_size, data_shape, path_imgrec=None,
                 path_imglist=None, path_root="", imglist=None,
                 shuffle=False, part_index=0, num_parts=1, aug_list=None,
                 data_name="data", label_name="label", label_shape=None,
                 **kwargs):
        # split kwargs: CreateDetAugmenter params vs parent-iterator params
        # (e.g. last_batch_handle) — mirroring ImageIter's own aug_keys split
        det_aug_keys = ("resize", "rand_crop", "rand_mirror", "mean", "std",
                        "min_crop_scale", "inter_method")
        det_kwargs = {k: kwargs.pop(k) for k in list(kwargs)
                      if k in det_aug_keys}
        if aug_list is not None and det_kwargs:
            raise MXNetError("aug_list given; augmenter kwargs %s would be "
                             "ignored" % sorted(det_kwargs))
        if int(kwargs.pop("preprocess_threads", 0) or 0) > 1:
            # loud, not silent: the det iterator's box-aware batch loop is
            # serial; accepting the knob would quietly drop the parallelism
            raise MXNetError(
                "ImageDetIter does not support preprocess_threads; wrap it "
                "in mx.io.PrefetchingIter for decode-ahead instead")
        aug = aug_list if aug_list is not None else \
            CreateDetAugmenter(data_shape, **det_kwargs)
        super().__init__(batch_size, data_shape, label_width=1,
                         path_imgrec=path_imgrec, path_imglist=path_imglist,
                         path_root=path_root, imglist=imglist,
                         shuffle=shuffle, part_index=part_index,
                         num_parts=num_parts, aug_list=[],
                         data_name=data_name, label_name=label_name,
                         **kwargs)
        self._det_auglist = aug
        self._obj_width = None
        if label_shape is not None:
            # explicit (max_objs, obj_width) — REQUIRED for num_parts > 1:
            # inferring from this shard would give each worker a different
            # label shape, and inferring at all costs a full dataset pass
            self._max_objs = int(label_shape[0])
            self._obj_width = int(label_shape[1])
        else:
            if num_parts > 1:
                raise MXNetError(
                    "ImageDetIter with num_parts > 1 needs an explicit "
                    "label_shape=(max_objs, obj_width): shard-local "
                    "inference would give workers different label shapes")
            max_objs = 1
            for key in self._seq:
                objs = self._parse_label(self._raw_label(key))
                max_objs = max(max_objs, objs.shape[0])
            self._max_objs = max_objs

    # ------------------------------------------------------------- labels
    def _raw_label(self, key):
        if self._record is not None:
            from ..recordio import unpack
            header, _ = unpack(self._record.read_idx(key))
            return np.asarray(header.label, np.float32).reshape(-1)
        _, label = self._imglist[key]
        return np.asarray(label, np.float32).reshape(-1)

    def _parse_label(self, raw):
        """[A, B, header..., objects...] -> (num_objs, B) array
        (ref: ImageDetIter._parse_label)."""
        raw = np.asarray(raw, np.float32).reshape(-1)
        if raw.size < 2:
            raise MXNetError("det label must start with [header_width, "
                             "object_width]")
        a, b = int(raw[0]), int(raw[1])
        if b < 5:
            raise MXNetError("object_width must be >= 5 (id + 4 coords)")
        body = raw[a:]
        n = body.size // b
        objs = body[:n * b].reshape(n, b)
        if self._obj_width is None:
            self._obj_width = b
        elif b != self._obj_width:
            raise MXNetError("inconsistent object_width across samples")
        return objs

    @property
    def provide_label(self):
        return [DataDesc(self._label_name,
                         (self.batch_size, self._max_objs,
                          self._obj_width or 5))]

    # ------------------------------------------------------------ batching
    def next(self):
        if self._cursor >= len(self._seq):
            raise StopIteration
        bw = self._obj_width or 5
        batch_data = np.zeros((self.batch_size,) + self.data_shape,
                              np.float32)
        batch_label = np.full((self.batch_size, self._max_objs, bw), -1.0,
                              np.float32)
        i = 0
        pad = 0
        while i < self.batch_size:
            if self._cursor < len(self._seq):
                key = self._seq[self._cursor]
                raw, img = self._read_record(key)
                objs = self._parse_label(raw)
                for aug in self._det_auglist:
                    img, objs = aug(img, objs)
                img = _as_np(img)
                if img.ndim == 3 and img.shape[2] in (1, 3):
                    img = img.transpose(2, 0, 1)
                batch_data[i] = img.astype(np.float32)
                batch_label[i, :objs.shape[0]] = objs[:self._max_objs]
                self._cursor += 1
            else:
                pad += 1
            i += 1
        if pad == self.batch_size:
            raise StopIteration
        return DataBatch(data=[array(batch_data)],
                         label=[array(batch_label)], pad=pad)
