"""Image IO + augmenters + ImageIter.

Reference: ``python/mxnet/image/image.py`` — cv2-backed decode/resize/crop,
the Augmenter stack (CreateAugmenter), and ImageIter reading RecordIO packs
or .lst files.

TPU-native notes: decode/augment stay host-side (numpy/cv2) exactly like the
reference's C++ decode threads; the augmented batch crosses to the device once
per step. Tensor-side transforms (mx.nd.image.*) are the jit-fusable path.
"""
from __future__ import annotations

import os
import random as _pyrandom

import numpy as np

from ..base import MXNetError
from ..io import DataBatch, DataDesc, DataIter
from ..ndarray import NDArray, array

__all__ = ["imread", "imdecode", "imresize", "ImageIter"]


def _cv2():
    import cv2
    return cv2


def imread(filename, flag=1, to_rgb=True):
    """Read an image file to an NDArray, HWC (ref: image.py:imread)."""
    cv2 = _cv2()
    img = cv2.imread(filename, flag)
    if img is None:
        raise MXNetError("cannot read image %s" % filename)
    if to_rgb and img.ndim == 3:
        img = cv2.cvtColor(img, cv2.COLOR_BGR2RGB)
    return array(img)


def imdecode(buf, flag=1, to_rgb=True):
    """Decode an encoded image buffer (ref: image.py:imdecode)."""
    cv2 = _cv2()
    img = cv2.imdecode(np.frombuffer(bytes(buf), dtype=np.uint8), flag)
    if img is None:
        raise MXNetError("cannot decode image buffer")
    if to_rgb and img.ndim == 3:
        img = cv2.cvtColor(img, cv2.COLOR_BGR2RGB)
    return array(img)


def _as_np(img):
    return img.asnumpy() if isinstance(img, NDArray) else np.asarray(img)


def imresize(src, w, h, interp=1):
    cv2 = _cv2()
    interp_map = {0: cv2.INTER_NEAREST, 1: cv2.INTER_LINEAR,
                  2: cv2.INTER_CUBIC, 3: cv2.INTER_AREA,
                  4: cv2.INTER_LANCZOS4}
    out = cv2.resize(_as_np(src), (w, h),
                     interpolation=interp_map.get(interp, cv2.INTER_LINEAR))
    return array(out)


def resize_short(src, size, interp=2):
    """Resize shorter edge to size (ref: image.py:resize_short)."""
    img = _as_np(src)
    h, w = img.shape[:2]
    if h > w:
        new_w, new_h = size, int(h * size / w)
    else:
        new_w, new_h = int(w * size / h), size
    return imresize(img, new_w, new_h, interp)


def fixed_crop(src, x0, y0, w, h, size=None, interp=2):
    img = _as_np(src)[y0:y0 + h, x0:x0 + w]
    if size is not None and (w, h) != size:
        return imresize(img, size[0], size[1], interp)
    return array(img)


def random_crop(src, size, interp=2):
    """(ref: image.py:random_crop) returns (cropped, (x0, y0, w, h))."""
    img = _as_np(src)
    h, w = img.shape[:2]
    new_w, new_h = min(size[0], w), min(size[1], h)
    x0 = _pyrandom.randint(0, w - new_w)
    y0 = _pyrandom.randint(0, h - new_h)
    out = fixed_crop(img, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def center_crop(src, size, interp=2):
    img = _as_np(src)
    h, w = img.shape[:2]
    new_w, new_h = min(size[0], w), min(size[1], h)
    x0 = (w - new_w) // 2
    y0 = (h - new_h) // 2
    out = fixed_crop(img, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def color_normalize(src, mean, std=None):
    src = _as_np(src).astype(np.float32)
    out = src - _as_np(mean)
    if std is not None:
        out = out / _as_np(std)
    return array(out)


# ------------------------------------------------------------- augmenters
class Augmenter:
    """(ref: image.py:Augmenter)"""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def __call__(self, src):
        raise NotImplementedError


class ResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return resize_short(src, self.size, self.interp)


class ForceResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return imresize(src, self.size[0], self.size[1], self.interp)


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return random_crop(src, self.size, self.interp)[0]


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return center_crop(src, self.size, self.interp)[0]


class HorizontalFlipAug(Augmenter):
    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if _pyrandom.random() < self.p:
            return array(np.flip(_as_np(src), axis=1))
        return array(_as_np(src))


class CastAug(Augmenter):
    def __init__(self, typ="float32"):
        super().__init__(type=typ)
        self.typ = typ

    def __call__(self, src):
        return array(_as_np(src).astype(self.typ))


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super().__init__(mean=mean, std=std)
        self.mean, self.std = np.asarray(mean, np.float32), \
            np.asarray(std, np.float32) if std is not None else None

    def __call__(self, src):
        return color_normalize(src, self.mean, self.std)


class BrightnessJitterAug(Augmenter):
    def __init__(self, brightness):
        super().__init__(brightness=brightness)
        self.brightness = brightness

    def __call__(self, src):
        alpha = 1.0 + _pyrandom.uniform(-self.brightness, self.brightness)
        return array(_as_np(src).astype(np.float32) * alpha)


class ContrastJitterAug(Augmenter):
    def __init__(self, contrast):
        super().__init__(contrast=contrast)
        self.contrast = contrast

    def __call__(self, src):
        alpha = 1.0 + _pyrandom.uniform(-self.contrast, self.contrast)
        img = _as_np(src).astype(np.float32)
        coef = np.asarray([[[0.299, 0.587, 0.114]]], np.float32)
        gray = (img * coef).sum(axis=2, keepdims=True)
        return array(img * alpha + gray.mean() * (1 - alpha))


class SaturationJitterAug(Augmenter):
    def __init__(self, saturation):
        super().__init__(saturation=saturation)
        self.saturation = saturation

    def __call__(self, src):
        alpha = 1.0 + _pyrandom.uniform(-self.saturation, self.saturation)
        img = _as_np(src).astype(np.float32)
        coef = np.asarray([[[0.299, 0.587, 0.114]]], np.float32)
        gray = (img * coef).sum(axis=2, keepdims=True)
        return array(img * alpha + gray * (1 - alpha))


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, hue=0, pca_noise=0,
                    rand_gray=0, inter_method=2):
    """Standard augmenter stack (ref: image.py:CreateAugmenter)."""
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if brightness:
        auglist.append(BrightnessJitterAug(brightness))
    if contrast:
        auglist.append(ContrastJitterAug(contrast))
    if saturation:
        auglist.append(SaturationJitterAug(saturation))
    if mean is True:
        mean = np.asarray([123.68, 116.28, 103.53], np.float32)
    if std is True:
        std = np.asarray([58.395, 57.12, 57.375], np.float32)
    if mean is not None:
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


# --------------------------------------------------------------- ImageIter
class ImageIter(DataIter):
    """Image iterator over RecordIO packs or .lst files
    (ref: image.py:ImageIter; C++ twin src/io/iter_image_recordio_2.cc)."""

    def __init__(self, batch_size, data_shape, label_width=1,
                 path_imgrec=None, path_imglist=None, path_root="",
                 path_imgidx=None, shuffle=False, part_index=0, num_parts=1,
                 aug_list=None, imglist=None, data_name="data",
                 label_name="softmax_label", last_batch_handle="pad",
                 preprocess_threads=0, **kwargs):
        super().__init__(batch_size)
        # decode+augment worker threads (ref: ImageRecordIter's
        # preprocess_threads, src/io/iter_image_recordio_2.cc:672 — its
        # fused multithreaded pipeline). cv2's decode releases the GIL, so
        # threads genuinely parallelize the hot per-image work; RecordIO
        # reads stay serialized (the underlying reader seeks one file).
        # Combine with mx.io.PrefetchingIter for the reference's full
        # decode-ahead double buffering.
        self._threads = max(0, int(preprocess_threads))
        self._pool = None
        if len(data_shape) != 3 or data_shape[0] != 3:
            raise MXNetError("data_shape must be (3, H, W)")
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self._data_name = data_name
        self._label_name = label_name
        self._shuffle = shuffle
        aug_keys = ("resize", "rand_crop", "rand_resize", "rand_mirror",
                    "mean", "std", "brightness", "contrast", "saturation",
                    "hue", "pca_noise", "rand_gray", "inter_method")
        unknown = set(kwargs) - set(aug_keys)
        if unknown:
            # loud, not silent: a misspelled augmenter option must not
            # train with the augmentation quietly missing
            raise MXNetError("ImageIter: unknown options %s (augmenter "
                             "options: %s)" % (sorted(unknown),
                                               ", ".join(aug_keys)))
        if aug_list is not None and kwargs:
            raise MXNetError("aug_list given; augmenter kwargs %s would be "
                             "ignored" % sorted(kwargs))
        self.auglist = aug_list if aug_list is not None else \
            CreateAugmenter(data_shape, **{k: v for k, v in kwargs.items()
                                           if k in aug_keys})
        self._record = None
        self._imglist = None
        if path_imgrec is not None:
            from ..recordio import MXIndexedRecordIO
            idx = path_imgidx if path_imgidx is not None \
                else path_imgrec[:path_imgrec.rfind(".")] + ".idx"
            self._record = MXIndexedRecordIO(idx, path_imgrec, "r")
            self._seq = list(self._record.keys)
        elif path_imglist is not None or imglist is not None:
            entries = []
            if path_imglist is not None:
                with open(path_imglist) as fin:
                    for line in fin:
                        parts = line.strip().split("\t")
                        label = np.asarray(parts[1:-1], np.float32)
                        entries.append((parts[-1], label))
            else:
                for item in imglist:
                    entries.append((item[-1],
                                    np.asarray(item[:-1], np.float32)))
            self._imglist = entries
            self._path_root = path_root
            self._seq = list(range(len(entries)))
        else:
            raise MXNetError("needs path_imgrec, path_imglist or imglist")
        # distributed sharding (ref: part_index/num_parts shard reads)
        n = len(self._seq)
        per = n // num_parts
        self._seq = self._seq[part_index * per:
                              (part_index + 1) * per if num_parts > 1 else n]
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc(self._data_name,
                         (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        shape = (self.batch_size,) if self.label_width == 1 else \
            (self.batch_size, self.label_width)
        return [DataDesc(self._label_name, shape)]

    def reset(self):
        if self._shuffle:
            _pyrandom.shuffle(self._seq)
        self._cursor = 0

    def _decode_blob(self, blob):
        """RecordIO blob -> (label vector, RGB HWC image). Thread-safe
        (no iterator state)."""
        from ..recordio import unpack_img
        header, img = unpack_img(blob)
        # BGR -> RGB like the reference decode
        return (np.asarray(header.label, np.float32).reshape(-1),
                img[..., ::-1])

    def _read_record(self, key):
        """ONE read+decode of a sample -> (label vector, RGB HWC image).
        Shared with ImageDetIter; the RecordIO blob is read and unpacked
        exactly once per sample (the hot IO path)."""
        if self._record is not None:
            return self._decode_blob(self._record.read_idx(key))
        path, label = self._imglist[key]
        return (np.asarray(label, np.float32).reshape(-1),
                imread(os.path.join(self._path_root, path)).asnumpy())

    def _read_image(self, key):
        """Decode one sample's image only (compat shim; prefer
        _read_record when the label is also needed)."""
        return self._read_record(key)[1]

    def _augment_sample(self, label, img):
        """The ONE copy of the augment/layout pipeline — serial and
        threaded paths both come through here, so the TRANSFORM code
        cannot diverge. (Random augmenters draw from the shared RNG in
        thread-interleaving order, so seeded reproducibility holds only
        for serial/deterministic pipelines — same property as the
        reference's decode threads.)"""
        for aug in self.auglist:
            img = aug(img)
        img = _as_np(img)
        if img.ndim == 3 and img.shape[2] in (1, 3):
            img = img.transpose(2, 0, 1)  # HWC -> CHW
        label = np.asarray(label, np.float32).reshape(-1)[:self.label_width]
        return img.astype(np.float32), label

    def _read_sample(self, key):
        label, img = self._read_record(key)
        return self._augment_sample(label, img)

    def _batch_samples(self, keys):
        """Decode+augment the batch's samples — threaded when
        preprocess_threads > 1 (the v2 iterator's parallel decode stage)."""
        if self._threads > 1 and len(keys) > 1:
            if self._pool is None:
                from concurrent.futures import ThreadPoolExecutor
                self._pool = ThreadPoolExecutor(self._threads)
            if self._record is not None:
                # reads stay serialized on THIS thread (the RecordIO
                # reader seeks one file); submitting each blob as it is
                # read overlaps blob i's decode with blob i+1's read
                futs = [self._pool.submit(self._process_blob,
                                          self._record.read_idx(k))
                        for k in keys]
                return [f.result() for f in futs]
            return list(self._pool.map(self._read_sample, keys))
        return [self._read_sample(k) for k in keys]

    def _process_blob(self, blob):
        """decode+augment one already-read RecordIO blob (thread-safe)."""
        return self._augment_sample(*self._decode_blob(blob))

    def close(self):
        """Release the decode pool AND the RecordIO file handle
        (idempotent; the iterator is done after this)."""
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None
        if self._record is not None:
            self._record.close()
            self._record = None

    def __del__(self):  # pragma: no cover - interpreter-exit timing
        try:
            self.close()
        except Exception:
            pass

    def next(self):
        if self._cursor >= len(self._seq):
            raise StopIteration
        batch_data = np.zeros((self.batch_size,) + self.data_shape,
                              np.float32)
        shape = (self.batch_size,) if self.label_width == 1 else \
            (self.batch_size, self.label_width)
        batch_label = np.zeros(shape, np.float32)
        take = min(self.batch_size, len(self._seq) - self._cursor)
        keys = [self._seq[self._cursor + j] for j in range(take)]
        samples = self._batch_samples(keys)
        # advance only after the batch decoded: a caller that catches a
        # corrupt-record error and retries resumes at this batch rather
        # than silently skipping its good samples
        self._cursor += take
        for i, (img, label) in enumerate(samples):
            batch_data[i] = img
            batch_label[i] = label if self.label_width > 1 else label[0]
        # take >= 1 here (the cursor check above raised otherwise), so a
        # batch is never all-pad
        pad = self.batch_size - take
        return DataBatch(data=[array(batch_data)],
                         label=[array(batch_label)], pad=pad)
