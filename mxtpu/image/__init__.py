"""mx.image: image IO + augmentation pipeline (ref: python/mxnet/image/)."""
from .image import (imread, imdecode, imresize, fixed_crop, center_crop,
                    random_crop, resize_short, color_normalize, ImageIter,
                    CreateAugmenter, Augmenter, ResizeAug, ForceResizeAug,
                    RandomCropAug, CenterCropAug, HorizontalFlipAug, CastAug,
                    ColorNormalizeAug, BrightnessJitterAug, ContrastJitterAug,
                    SaturationJitterAug)
from .detection import (CreateDetAugmenter, DetAugmenter, DetBorrowAug,
                        DetHorizontalFlipAug, DetRandomCropAug, ImageDetIter)

__all__ = ["imread", "imdecode", "imresize", "fixed_crop", "center_crop",
           "random_crop", "resize_short", "color_normalize", "ImageIter",
           "CreateAugmenter", "Augmenter", "ResizeAug", "ForceResizeAug",
           "RandomCropAug", "CenterCropAug", "HorizontalFlipAug", "CastAug",
           "ImageDetIter", "CreateDetAugmenter", "DetAugmenter",
           "DetBorrowAug", "DetHorizontalFlipAug", "DetRandomCropAug",
           "ColorNormalizeAug", "BrightnessJitterAug", "ContrastJitterAug",
           "SaturationJitterAug"]
