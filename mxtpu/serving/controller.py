"""SLO-aware serving control plane: the observe -> decide -> act loop.

PR 10 gave every request a per-stage latency breakdown and PR 12 a live
HBM/compile ledger; until now nothing CONSUMED them — admission shed by
raw queue depth, the ReplicaSet was frozen at construction, and a
permanently-dead replica just shrank capacity until a human restarted
the process. This module is the PAPER.md dependency-engine lesson
(schedule from *observed* behavior, not static plans) applied to
serving. Three closed loops, all driven by the same injected clock the
rest of the serving stack runs on (the whole matrix is sleep-free in
tier-1):

* **Predictive admission** — a per-bucket online latency model (bounded
  sliding-horizon quantile over the PR-10 stage breakdowns:
  ``serving.queue_wait + serving.pad + serving.predict`` per delivered
  request) predicts a new request's completion time; ``submit`` sheds
  ``serving.shed{predicted_miss}`` when the prediction exceeds the
  request's deadline — *before* the queue fills, so the box never
  builds a backlog it already knows it cannot serve in time. While the
  model is cold (fewer than ``min_samples`` observations in the decay
  horizon) admission falls back to the plain depth bound.
* **Autoscaling** — :meth:`ServingController.tick` grows/shrinks the
  ReplicaSet between ``MXTPU_SERVE_MIN_REPLICAS`` and
  ``MXTPU_SERVE_MAX_REPLICAS`` on SLO attainment + queue pressure (+
  KV-cache residency when a :class:`~mxtpu.serving.decode.
  KVCacheAccountant` is attached), with hysteresis: actions are spaced
  by ``MXTPU_SERVE_SCALE_COOLDOWN_MS`` and scale-down additionally
  requires a full cooldown of idleness — pressure spikes scale up,
  noise does not flap. A new replica warms its buckets AOT *off the
  serving path* (side thread in threaded mode) and only then joins the
  dispatch pool: its bring-up cost is exactly the compile ledger's
  per-site ``compile_s``, and its post-warmup compile count stays
  <= #buckets at its own ``serving.predict.r<i>`` site.
* **Self-healing** — a replica whose breaker has been open continuously
  past ``MXTPU_SERVE_REPLACE_AFTER_MS`` is REPLACED: a fresh replica is
  warmed on an unused device (falling back to the dead replica's device
  when none is free) and the dead one is retired through the PR-8 drain
  machinery. The kill/restore path ``serve_bench --mode slo`` gates.

Every decision (predicted shed, yield, scale up/down, replace) bumps
``serving.controller.decisions{action}`` and leaves a trace mark in the
event ring, so ``serve_bench`` and the flight recorder can attribute
control-plane behavior post-mortem. Priority classes (strict-priority
dequeue with an aging floor, batch evicted first under pressure) live
in :mod:`mxtpu.serving.batcher`; the controller only consumes their
signals.
"""
from __future__ import annotations

import collections
import logging
import math
import os
import threading

from .. import telemetry
from ..base import MXNetError

__all__ = ["ServingController", "min_replicas_default",
           "max_replicas_default", "scale_cooldown_ms_default",
           "replace_after_ms_default"]

_log = logging.getLogger("mxtpu.serving")


# ------------------------------------------------------------------ policies
def min_replicas_default():
    """Autoscaler floor (``MXTPU_SERVE_MIN_REPLICAS``, default 1): the
    controller never scales the ReplicaSet below this many replicas."""
    return int(os.environ.get("MXTPU_SERVE_MIN_REPLICAS", "1"))


def max_replicas_default():
    """Autoscaler ceiling (``MXTPU_SERVE_MAX_REPLICAS``, default 0 =
    every visible device): the controller never grows past it."""
    v = int(os.environ.get("MXTPU_SERVE_MAX_REPLICAS", "0"))
    if v > 0:
        return v
    import jax
    return len(jax.devices())


def scale_cooldown_ms_default():
    """Hysteresis between scale actions (``MXTPU_SERVE_SCALE_COOLDOWN_MS``,
    default 5000): consecutive grows/shrinks are spaced by at least this
    much, and scale-down additionally requires a full cooldown of
    idleness — a pressure spike scales up, noise never flaps."""
    return float(os.environ.get("MXTPU_SERVE_SCALE_COOLDOWN_MS", "5000"))


def replace_after_ms_default():
    """Self-healing bound (``MXTPU_SERVE_REPLACE_AFTER_MS``, default
    30000): a replica whose breaker has been open continuously this long
    (half-open probes keep failing) is written off and replaced on a
    fresh device."""
    return float(os.environ.get("MXTPU_SERVE_REPLACE_AFTER_MS", "30000"))


class _DecayedQuantile:
    """Bounded sliding-horizon quantile estimate: the newest ``maxlen``
    samples, further decayed by dropping anything older than
    ``horizon_s`` on the INJECTED clock — old regimes age out both by
    count and by time, so the estimate tracks the live service rate."""

    __slots__ = ("_samples", "_horizon")

    def __init__(self, maxlen=128, horizon_s=60.0):
        self._samples = collections.deque(maxlen=maxlen)
        self._horizon = float(horizon_s)

    def observe(self, v, now):
        self._samples.append((float(now), float(v)))

    def _live(self, now):
        cut = now - self._horizon
        return [v for t, v in self._samples if t >= cut]

    def count(self, now):
        return len(self._live(now))

    def quantile(self, q, now):
        live = sorted(self._live(now))
        if not live:
            return None
        idx = max(0, min(len(live) - 1,
                         int(math.ceil(q * len(live))) - 1))
        return live[idx]


class ServingController:
    """See the module docstring. ``dispatcher`` is the
    :class:`~mxtpu.serving.batcher.MicroBatcher` (normally a
    :class:`~mxtpu.serving.replicas.ReplicaDispatcher`) to control —
    construction attaches the controller: admission consults
    :meth:`admit`, delivery feeds :meth:`observe`, and the dispatcher's
    maintenance path (``poll()`` under a fake clock, the monitor thread
    in threaded mode) drives :meth:`tick`. On a plain MicroBatcher only
    predictive admission is active (there is no ReplicaSet to scale).

    ``quantile`` is the prediction's pessimism (default 0.9: the
    predicted completion is the windowed p90 of observed totals plus a
    backlog term); ``min_samples`` the cold-model threshold below which
    admission falls back to the depth bound."""

    def __init__(self, dispatcher, min_replicas=None, max_replicas=None,
                 scale_cooldown_ms=None, replace_after_ms=None,
                 quantile=0.9, min_samples=8, horizon_s=60.0,
                 pressure_high=0.5, pressure_low=0.05,
                 attainment_floor=0.95, kv_pressure_high=0.9):
        self._disp = dispatcher
        self._set = getattr(dispatcher, "replica_set", None)
        self.min_replicas = int(min_replicas if min_replicas is not None
                                else min_replicas_default())
        self.max_replicas = int(max_replicas if max_replicas is not None
                                else max_replicas_default())
        if self.min_replicas < 1 or self.max_replicas < self.min_replicas:
            raise MXNetError(
                "ServingController: need 1 <= min_replicas <= max_replicas"
                " (got min=%d max=%d)"
                % (self.min_replicas, self.max_replicas))
        self.cooldown_s = float(
            scale_cooldown_ms if scale_cooldown_ms is not None
            else scale_cooldown_ms_default()) / 1e3
        self.replace_after_s = float(
            replace_after_ms if replace_after_ms is not None
            else replace_after_ms_default()) / 1e3
        self._q = float(quantile)
        self._min_samples = int(min_samples)
        self._horizon_s = float(horizon_s)
        self._pressure_high = float(pressure_high)
        self._pressure_low = float(pressure_low)
        self._attainment_floor = float(attainment_floor)
        self._kv_pressure_high = float(kv_pressure_high)
        self._lock = threading.Lock()
        self._models = {}          # bucket_key -> {"total","service"}
        self._deliveries = collections.deque(maxlen=512)  # (t, items)
        self._hits = 0.0           # decayed SLO attainment counters
        self._misses = 0.0
        self._sheds = 0.0          # decayed shed-event counter
        self._tenants = {}         # tenant -> [hits, misses], same decay
        self._att_t = None         # last decay timestamp
        self._last_scale = None    # clock of the last scale action
        self._last_activity = None  # last delivery/shed/non-empty queue
        self._busy = False         # one control action in flight at a time
        self.last_decision = None  # {"action","reason","t"} for /healthz
        dispatcher.attach_controller(self)

    # ------------------------------------------------------------ observation
    def _decay_locked(self, now):
        """Exponential decay of the attainment/shed counters with the
        horizon as time constant — recent behavior dominates."""
        if self._att_t is not None and now > self._att_t:
            f = math.exp(-(now - self._att_t) / self._horizon_s)
            self._hits *= f
            self._misses *= f
            self._sheds *= f
            for hm in self._tenants.values():
                hm[0] *= f
                hm[1] *= f
        self._att_t = now

    def observe(self, bucket_key, breakdown, hit, now, n=1, meta=None):
        """One delivered (or expired) request's verdict: feed the
        per-bucket latency model from its stage breakdown, the empirical
        drain-rate window, and the decayed SLO-attainment counters.
        Called by the batcher on delivery. ``meta`` (the request's
        attribution dict, stamped by the zoo) routes the verdict into
        the per-tenant goodput counters too
        (``serving.tenant_attainment{tenant}``)."""
        total = sum(breakdown.get(k, 0.0) for k in
                    ("serving.queue_wait", "serving.pad", "serving.predict"))
        service = sum(breakdown.get(k, 0.0) for k in
                      ("serving.pad", "serving.predict"))
        with self._lock:
            self._deliveries.append((float(now), int(n)))
            if total > 0.0:
                m = self._models.get(bucket_key)
                if m is None:
                    m = {"total": _DecayedQuantile(horizon_s=self._horizon_s),
                         "service": _DecayedQuantile(
                             horizon_s=self._horizon_s)}
                    self._models[bucket_key] = m
                m["total"].observe(total, now)
                m["service"].observe(service, now)
            self._decay_locked(now)
            if hit:
                self._hits += 1.0
            else:
                self._misses += 1.0
            self._tenant_verdict_locked(meta, hit)
            self._last_activity = now

    def _tenant_verdict_locked(self, meta, hit):
        tenant = (meta or {}).get("tenant")
        if tenant is None:
            return
        hm = self._tenants.get(tenant)
        if hm is None:
            hm = self._tenants[tenant] = [0.0, 0.0]
        hm[0 if hit else 1] += 1.0
        telemetry.gauge("serving.tenant_attainment",
                        hm[0] / (hm[0] + hm[1]), tag=tenant)

    def note_expired(self, now, meta=None):
        """A queued request's deadline passed before dispatch — an SLO
        miss the attainment signal (and the request's tenant) must
        see."""
        with self._lock:
            self._decay_locked(now)
            self._misses += 1.0
            self._tenant_verdict_locked(meta, False)
            self._last_activity = now

    def note_shed(self, reason, now):
        """Any admission shed (depth, predictive, eviction): recent sheds
        are the strongest scale-up pressure there is."""
        with self._lock:
            self._decay_locked(now)
            self._sheds += 1.0
            self._last_activity = now

    def attainment(self, now=None):
        """``(attainment, weight)``: the decayed SLO goodput fraction and
        the decayed verdict count backing it (attainment is None below
        one verdict of weight). The zoo's canary auto-rollback gate reads
        this off the canary arm's controller."""
        if now is None:
            now = self._disp._clock()
        with self._lock:
            self._decay_locked(now)
            weight = self._hits + self._misses
            att = self._hits / weight if weight >= 1.0 else None
        return att, weight

    def tenant_attainment(self, now=None):
        """Per-tenant decayed goodput attainment ({tenant: fraction}) —
        the /healthz per-tenant SLO view."""
        if now is None:
            now = self._disp._clock()
        with self._lock:
            self._decay_locked(now)
            return {t: round(hm[0] / (hm[0] + hm[1]), 4)
                    for t, hm in self._tenants.items()
                    if hm[0] + hm[1] >= 1.0}

    # -------------------------------------------------------------- admission
    def predicted_s(self, bucket_key, queued_ahead_items=0, now=None):
        """Predicted completion time (seconds from now) for a request in
        ``bucket_key``. Two estimates, take the smaller:

        * **history** — the windowed ``quantile`` of observed
          queue-wait + pad + predict totals, plus one service quantum
          per full backlog batch already queued ahead in the same
          bucket;
        * **live bound** — what the CURRENT queue can actually cost:
          (total queued batches + 1) x the service quantile + the
          coalescing wait. History alone deadlocks after an overload
          passes (stale queue-wait samples predict misses, everything
          sheds, and with nothing delivered the model never re-learns);
          the live bound collapses the prediction the moment the queue
          empties, and the backlog terms raise it the moment depth
          returns — self-correcting in both directions.

        None while the model is cold (fewer than ``min_samples``
        observations in the horizon)."""
        if now is None:
            now = self._disp._clock()
        with self._lock:
            m = self._models.get(bucket_key)
            if m is None or m["total"].count(now) < self._min_samples:
                return None
            total = m["total"].quantile(self._q, now)
            # MEDIAN service, deliberately: the per-batch execution time
            # is a tight distribution whose tail is host-noise/first-
            # dispatch stragglers — a pessimistic service estimate here
            # would predict misses forever on an idle box. The pessimism
            # quantile lives on the observed TOTALS, where it belongs
            service = m["service"].quantile(0.5, now) or 0.0
            rate = self._drain_rate_locked(now)
        max_batch = max(1, self._disp.max_batch)
        history = total + (queued_ahead_items // max_batch) * service
        if rate is None:
            return history
        live = self._disp.queue_depth / rate + service \
            + self._disp.max_wait_s
        return min(history, live)

    def _drain_rate_locked(self, now):
        """Empirical delivery rate (items/s) over the recent window —
        what the live-queue wait bound divides by. None before enough
        recent deliveries (<= 1 s span or < 2 samples)."""
        cut = now - min(self._horizon_s, 5.0)
        recent = [(t, k) for t, k in self._deliveries if t >= cut]
        if len(recent) < 2:
            return None
        span = max(1e-3, recent[-1][0] - recent[0][0])
        items = sum(k for _t, k in recent)
        return items / span

    def admit(self, n, bucket_key, deadline_s, priority, queued_ahead=0):
        """The predictive-admission verdict for one submit: a shed-reason
        string (``predicted_miss``) when the predicted completion exceeds
        the request's deadline, None to admit. Deadline-less requests and
        cold buckets always pass — the depth bound still governs."""
        if deadline_s is None:
            return None
        now = self._disp._clock()
        predicted = self.predicted_s(bucket_key, queued_ahead, now=now)
        if predicted is None:
            return None  # cold model: fall back to the depth bound
        if predicted > deadline_s:
            self._record("predicted_shed", "predicted %.1f ms > deadline "
                         "%.1f ms" % (predicted * 1e3, deadline_s * 1e3),
                         now, mark=False)
            return "predicted_miss"
        return None

    def estimate_drain_s(self):
        """Predicted time to drain the CURRENT queue — what the 503
        Retry-After header is derived from. The empirical delivery rate
        when recent traffic gives one; else per-bucket backlog batches x
        that bucket's median service (a conservative 50 ms per batch
        where the model is cold)."""
        now = self._disp._clock()
        depth = self._disp.queue_depth
        with self._lock:
            rate = self._drain_rate_locked(now)
        if rate:
            return depth / rate
        by_bucket = {}
        for r in list(self._disp._q):
            by_bucket[r.bucket_key] = by_bucket.get(r.bucket_key, 0) + r.n
        drain = 0.0
        with self._lock:
            for bucket, items in by_bucket.items():
                batches = math.ceil(items / max(1, self._disp.max_batch))
                m = self._models.get(bucket)
                service = m["service"].quantile(0.5, now) \
                    if m is not None else None
                drain += batches * (service if service else 0.05)
        return drain

    def retry_after_s(self):
        """Integer seconds for the 503 ``Retry-After`` header (>= 1)."""
        return int(math.ceil(max(1.0, self.estimate_drain_s())))

    # ------------------------------------------------------------- decisions
    def _record(self, action, reason, now, mark=True):
        """One tagged counter bump + trace mark — every control-plane
        decision is attributable from telemetry alone. ``mark=False`` is
        the per-request fast path (predicted sheds, which can fire
        thousands of times under overload: the REQUEST's own trace gets
        the mark in ``_admit``, the log stays at debug, and the /healthz
        ``last_decision`` keeps showing the last SCALE-class action)."""
        telemetry.inc("serving.controller.decisions", tag=action)
        if mark:
            self.last_decision = {"action": action, "reason": reason,
                                  "t": float(now)}
            telemetry.trace_mark(telemetry.new_trace(),
                                 "serving.controller." + action)
            _log.info("serving controller: %s (%s)", action, reason)
        else:
            _log.debug("serving controller: %s (%s)", action, reason)

    def note_warmup_failed(self, error, now):
        """A replica bring-up that never joined (called by the
        dispatcher's warmup path — including the threaded side thread,
        where the exception would otherwise die on a daemon frame)."""
        self._record("warmup_failed", "%s: %s"
                     % (type(error).__name__, error), now)

    def _counts_locked(self):
        reps = self._set.replicas
        healthy = sum(1 for r in reps if r.state == "healthy")
        warming = sum(1 for r in reps if r.state == "warming")
        live = sum(1 for r in reps if r.state != "retiring")
        return healthy, warming, live

    def tick(self, now):
        """One control-loop iteration (replace check, then the scaling
        ladder) — called from the dispatcher's maintenance path: under a
        fake clock every ``poll()`` ticks; in threaded mode the monitor
        thread does. Decisions run OUTSIDE the controller lock (a warmup
        is seconds of device work); ``_busy`` keeps them one at a time."""
        if self._set is None:
            return
        with self._lock:
            if self._busy:
                return
            if self._disp.queue_depth > 0:
                self._last_activity = now
            action = self._decide_locked(now)
            if action is None:
                telemetry.gauge("serving.controller.replica_target",
                                self._counts_locked()[2])
                return
            self._busy = True
        try:
            self._act(action, now)
        finally:
            with self._lock:
                self._busy = False

    def _decide_locked(self, now):
        if self._disp._draining or self._disp._closed \
                or self._disp._crashed:
            # a draining/closed/crashed dispatcher can never serve the
            # capacity a scale action would add — drain-retry sheds and
            # crash-barrier sheds must not trigger pointless bring-ups
            return None
        healthy, warming, live = self._counts_locked()
        # 1) self-healing: a breaker open continuously past the bound is
        #    a dead chip, not a blip — replace it (repair is not gated by
        #    the scale cooldown; capacity restoration cannot wait)
        for rep in self._set.replicas:
            if rep.state in ("quarantined", "probing") \
                    and rep.down_since is not None \
                    and now - rep.down_since >= self.replace_after_s:
                return ("replace", rep)
        # 2) scaling, cooldown-gated
        if self._last_scale is not None \
                and now - self._last_scale < self.cooldown_s:
            return None
        self._decay_locked(now)
        pressure = self._disp.queue_depth / max(1, self._disp.max_queue)
        shed_hot = self._sheds > 0.5
        att = None
        if self._hits + self._misses >= 4.0:
            att = self._hits / (self._hits + self._misses)
        kvp = 0.0
        acct = getattr(self._set, "accountant", None)
        if acct is not None:
            kvp = acct.pressure()
        if live < self.max_replicas and (
                pressure >= self._pressure_high or shed_hot
                or (att is not None and att < self._attainment_floor)
                or kvp >= self._kv_pressure_high):
            return ("scale_up",
                    "pressure=%.2f sheds=%.1f attainment=%s kv=%.2f"
                    % (pressure, self._sheds,
                       "%.2f" % att if att is not None else "n/a", kvp))
        idle = self._last_activity is None \
            or now - self._last_activity >= self.cooldown_s
        if healthy > self.min_replicas and warming == 0 \
                and self._disp.queue_depth == 0 and idle \
                and not shed_hot \
                and (att is None or att >= self._attainment_floor):
            return ("scale_down", "idle >= %.1f s" % self.cooldown_s)
        return None

    def _act(self, action, now):
        kind = action[0]
        if kind == "replace":
            self._replace(action[1], now)
        elif kind == "scale_up":
            self._record("scale_up", action[1], now)
            self._last_scale = now
            self._add_one(now)
        elif kind == "scale_down":
            victim = None
            for rep in self._set.replicas:
                if rep.state == "healthy" and (
                        victim is None or rep.index > victim.index):
                    victim = rep
            if victim is None:
                return
            self._record("scale_down",
                         "%s retiring (idle)" % victim.tag, now)
            self._last_scale = now
            self._disp.remove_replica(victim.index)

    def _add_one(self, now, device=None):
        """Grow by one replica (AOT-warmed off the serving path — the
        dispatcher warms on a side thread in threaded mode, inline under
        a fake clock). A failed bring-up is recorded, never raised into
        the serving path: warmup failures are caught (and reported back
        here) by the dispatcher's bring-up step in both modes; this
        catch covers allocation-time refusals (e.g. no free device)."""
        try:
            self._disp.add_replica(device=device)
        except Exception as e:  # noqa: BLE001 — decision log, not control
            _log.exception("serving controller: replica bring-up failed")
            self.note_warmup_failed(e, now)

    def _replace(self, dead, now):
        self._record("replace", "%s breaker open %.1f s (>= %.1f s)"
                     % (dead.tag, now - (dead.down_since or now),
                        self.replace_after_s), now)
        free = self._set.free_devices()
        # "on a fresh device": prefer a device no replica is using — a
        # wedged chip stays written off; fall back to the dead replica's
        # own device when the fleet has no spare (a process-level fault
        # may well serve again from a fresh executable set)
        device = free[0] if free else dead.device
        self._disp.remove_replica(dead.index)
        self._add_one(now, device=device)

    # -------------------------------------------------------------- reporting
    def view(self):
        """The /healthz controller block: replica target vs actual,
        per-class queue depths, SLO attainment, last decision + reason."""
        depths = self._disp.queue_depths()
        drain = self.estimate_drain_s()
        with self._lock:
            att = None
            if self._hits + self._misses >= 1.0:
                att = self._hits / (self._hits + self._misses)
            out = {
                "min_replicas": self.min_replicas,
                "max_replicas": self.max_replicas,
                "queue_depths": depths,
                "slo_attainment": round(att, 4) if att is not None else None,
                "tenant_attainment": {
                    t: round(hm[0] / (hm[0] + hm[1]), 4)
                    for t, hm in self._tenants.items()
                    if hm[0] + hm[1] >= 1.0},
                "recent_sheds": round(self._sheds, 2),
                "estimated_drain_s": round(drain, 4),
                "last_decision": dict(self.last_decision)
                if self.last_decision else None,
            }
            if self._set is not None:
                healthy, warming, live = self._counts_locked()
                out["replica_target"] = live
                out["replica_actual"] = healthy
                out["replica_warming"] = warming
        return out
