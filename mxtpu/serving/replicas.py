"""Fault-tolerant replica serving: ReplicaSet router + wedge watchdog.

PR 5's server is one Predictor on one device behind one dispatch thread.
A wedged chip (exactly what the training side hit in BENCH_r03-r05: a
device call that never returns) therefore hangs the sole worker inside
``MicroBatcher._dispatch`` forever — every queued future strands, and the
box fails its SLO while still answering ``/healthz`` 200. This module is
the serving half of the resilience story (ROADMAP item 2(a)):

* :class:`ReplicaSet` — one AOT-warmed
  :class:`~mxtpu.serving.engine.Predictor` per device. Each replica's
  parameters are ``device_put`` to its chip and its compiles report at a
  per-replica retrace site ``serving.predict.r<i>`` — post-warmup
  compiles stay ≤ #buckets × #replicas by construction, attributable per
  replica. The executable is the unit of failover (PyGraph's
  capture-once/replay-forever economics, arXiv:2503.19779): losing a
  replica loses capacity, never the ability to serve.
* :class:`ReplicaDispatcher` — a :class:`~mxtpu.serving.batcher.
  MicroBatcher` whose single worker is replaced by one dispatch worker
  PER replica, all fed from the same per-bucket FIFO cohorts
  (shed-aware least-loaded routing: a busy or quarantined replica simply
  stops pulling work; the explicit router picks the least-loaded healthy
  replica when dispatch is driven via :meth:`poll`).
* **Wedge watchdog** — every dispatch is bracketed by a per-dispatch
  deadline (``MXTPU_SERVE_DISPATCH_TIMEOUT_MS``). On trip: the replica
  is marked wedged and quarantined, the batch re-dispatches on a healthy
  replica exactly ONCE (a twice-wedged batch fails its futures — bounded
  behavior, never a loop), and a late answer from the wedged call is
  discarded as stale.
* **Circuit breaker** — ``MXTPU_SERVE_BREAKER_THRESHOLD`` consecutive
  dispatch failures open a replica's breaker (quarantine). A half-open
  probe re-warms the replica with a synthetic min-bucket batch on an
  exponential backoff schedule (``MXTPU_SERVE_BREAKER_BACKOFF_MS`` …
  ``_MAX_MS``); success restores it, failure doubles the backoff. The
  clock is injectable, so the whole failure matrix runs sleep-free in
  tier-1 under a fake clock.
* **Graceful degradation** — losing k of N replicas raises the shed
  rate (`queue_full`, and `no_healthy_replica` once ALL are down)
  instead of hanging or 500-ing; ``/healthz`` reports per-replica state
  and queue depth; ``/metrics`` carries the replica-tagged counters
  ``serving.replica.{dispatches,failures,wedges,quarantines,restores,
  redispatches}``.

Deterministic fault kinds (``MXTPU_FAULT_INJECT``, docs/resilience.md):
``replica_fail@i`` — the replica executing serving dispatch *i* raises;
``replica_wedge@i`` — that dispatch never returns (simulated wedge: the
watchdog path runs without a blocked thread, so fake-clock tests cover
it end to end).
"""
from __future__ import annotations

import logging
import os
import threading
import time

import jax
import jax.numpy as jnp

from .. import telemetry
from ..base import MXNetError
from ..resilience import inject
from .batcher import DeadlineExceeded, MicroBatcher, QueueFull
from .engine import Predictor

__all__ = ["Replica", "ReplicaSet", "ReplicaDispatcher", "ReplicaFailure",
           "replica_count_default", "dispatch_timeout_ms_default",
           "breaker_threshold_default", "breaker_backoff_ms_default",
           "breaker_backoff_max_ms_default"]

_log = logging.getLogger("mxtpu.serving")

# the simulated-wedge sentinel: "the device call has not returned" — the
# dispatch path keeps its watchdog entry armed and delivers nothing
_WEDGED = object()


# ------------------------------------------------------------------ policies
def replica_count_default():
    """Replica count for :class:`ReplicaSet` when neither ``n`` nor
    ``devices`` is given (``MXTPU_SERVE_REPLICAS``, default 1 — the PR-5
    single-predictor behavior; ``auto``/``0`` = one per visible device)."""
    v = os.environ.get("MXTPU_SERVE_REPLICAS", "1").strip().lower()
    return 0 if v in ("auto", "all") else int(v)


def dispatch_timeout_ms_default():
    """Per-dispatch wedge deadline (``MXTPU_SERVE_DISPATCH_TIMEOUT_MS``,
    default 10000): a dispatched batch that has not answered within this
    bound trips the wedge watchdog — the replica is quarantined and the
    batch re-dispatches once on a healthy replica. Generous by default: a
    warm-bucket forward is milliseconds, so 10 s only ever fires on a
    genuinely dead device call, never on a slow one."""
    return float(os.environ.get("MXTPU_SERVE_DISPATCH_TIMEOUT_MS", "10000"))


def breaker_threshold_default():
    """Consecutive dispatch failures that open a replica's circuit
    breaker (``MXTPU_SERVE_BREAKER_THRESHOLD``, default 3)."""
    return int(os.environ.get("MXTPU_SERVE_BREAKER_THRESHOLD", "3"))


def breaker_backoff_ms_default():
    """Initial half-open probe backoff after a quarantine
    (``MXTPU_SERVE_BREAKER_BACKOFF_MS``, default 1000); doubles per
    failed probe."""
    return float(os.environ.get("MXTPU_SERVE_BREAKER_BACKOFF_MS", "1000"))


def breaker_backoff_max_ms_default():
    """Probe backoff ceiling (``MXTPU_SERVE_BREAKER_BACKOFF_MAX_MS``,
    default 30000)."""
    return float(os.environ.get("MXTPU_SERVE_BREAKER_BACKOFF_MAX_MS",
                                "30000"))


class ReplicaFailure(MXNetError):
    """A replica-level dispatch failure (device error / injected
    ``replica_fail``): counts toward that replica's circuit breaker."""


class Replica:
    """One serving replica: an AOT-warmed Predictor pinned to a device,
    plus its health state. State machine: ``healthy`` (routable) ->
    ``quarantined`` (breaker open / wedged; half-open probe scheduled at
    ``probe_at``) -> ``probing`` (one in-flight probe) -> back. The
    elastic states: ``warming`` (AOT bring-up off the serving path —
    never routed until every bucket compiled), ``retiring`` (drains its
    in-flight work, then ``removed`` — the scale-down / replacement
    exit)."""

    __slots__ = ("index", "device", "predictor", "state", "consecutive",
                 "inflight", "dispatches", "wedged", "backoff_s", "probe_at",
                 "down_since")

    def __init__(self, index, device, predictor, backoff_s,
                 state="healthy"):
        self.index = index
        self.device = device
        self.predictor = predictor
        self.state = state
        self.consecutive = 0      # consecutive dispatch failures (breaker)
        self.inflight = 0         # batches currently executing here
        self.dispatches = 0
        self.wedged = False       # a dispatch never returned
        self.backoff_s = backoff_s
        self.probe_at = None
        self.down_since = None    # clock of the breaker open (replacement)

    @property
    def tag(self):
        return "r%d" % self.index


class ReplicaSet:
    """One warmed Predictor per device + the health/routing state machine.

    ``block`` is shared (parameters are read-only in serving): each
    replica's Predictor snapshots the params ``device_put`` to ITS device
    and compiles its own per-bucket executables, reported at retrace site
    ``serving.predict.r<i>``. Pass ``n`` (or ``MXTPU_SERVE_REPLICAS``;
    0/"auto" = every visible device) or an explicit ``devices`` list.

    All state transitions are clock-value driven (``now`` is passed in by
    the dispatcher), so the set itself never sleeps and never reads a
    wall clock — the fake-clock contract of the tier-1 failure tests.
    """

    def __init__(self, block, spec, n=None, devices=None, example=None,
                 warmup=True, name="predictor", breaker_threshold=None,
                 breaker_backoff_ms=None, breaker_backoff_max_ms=None):
        if devices is None:
            count = replica_count_default() if n is None else int(n)
            avail = list(jax.devices())
            if count == 0:
                count = len(avail)
            if count < 1:
                raise MXNetError("ReplicaSet: need at least 1 replica")
            if count > len(avail):
                raise MXNetError(
                    "ReplicaSet: %d replicas requested but only %d device"
                    "(s) visible" % (count, len(avail)))
            devices = avail[:count]
        if not devices:
            raise MXNetError("ReplicaSet: empty device list")
        self.spec = spec
        self.threshold = int(breaker_threshold
                             if breaker_threshold is not None
                             else breaker_threshold_default())
        self.backoff0_s = float(breaker_backoff_ms
                                if breaker_backoff_ms is not None
                                else breaker_backoff_ms_default()) / 1e3
        self.backoff_max_s = float(breaker_backoff_max_ms
                                   if breaker_backoff_max_ms is not None
                                   else breaker_backoff_max_ms_default()) / 1e3
        self._lock = threading.Lock()
        self._accountant = None   # optional KVCacheAccountant (attach_...)
        self._block = block       # elastic growth rebuilds from these
        self._example = example
        self._name = name
        self.replicas = []
        for i, dev in enumerate(devices):
            pred = Predictor(block, spec, example=example, warmup=False,
                             name="%s.r%d" % (name, i), device=dev,
                             site="serving.predict.r%d" % i)
            self.replicas.append(Replica(i, dev, pred, self.backoff0_s))
        # replica indices are IDENTITIES, never positions: elastic
        # add/remove keeps retiring a replica from invalidating another's
        # retrace site (serving.predict.r<i>) or telemetry tag family
        self._next_index = len(self.replicas)
        telemetry.gauge("serving.replicas", len(self.replicas))
        if warmup:
            self.warmup()

    # --------------------------------------------------- batcher interface
    # (a ReplicaSet slots in wherever MicroBatcher expects a predictor)
    @property
    def input_templates(self):
        return self.replicas[0].predictor.input_templates

    @property
    def _jits(self):
        # the MicroBatcher cold-start check reads this: warm iff every
        # SERVING replica compiled its buckets (a replica still in its
        # elastic warming window is by definition not serving yet)
        reps = [r for r in self.replicas if r.state != "warming"]
        if reps and all(r.predictor._jits for r in reps):
            return reps[0].predictor._jits
        return {}

    def warmup(self):
        """AOT-compile every bucket on every replica through the compile
        service's shared-lowering path: identical lowerings carry one
        group token, so the shared block traces ONCE per bucket (not
        once per replica serialized behind the trace lock) and each
        replica's executables compile for its own device, concurrently
        on the service pool — and load from a warm
        ``MXTPU_COMPILE_CACHE_DIR`` with zero compiles. Returns self."""
        from .. import compile_service as csvc
        entries = []
        for r in self.replicas:
            entries.extend(r.predictor.warmup_entries())
        csvc.warmup(entries)
        for r in self.replicas:
            r.predictor.finish_warmup()
        return self

    def __len__(self):
        return len(self.replicas)

    # ------------------------------------------------------------ elasticity
    def _find_locked(self, index):
        for r in self.replicas:
            if r.index == index:
                return r
        raise MXNetError("ReplicaSet: no replica with index %d (live: %s)"
                         % (index, [r.index for r in self.replicas]))

    def _free_devices_locked(self):
        used = {id(r.device) for r in self.replicas}
        return [d for d in jax.devices() if id(d) not in used]

    def free_devices(self):
        """Visible devices no current replica (any state) is pinned to —
        where a replacement or scale-up replica goes first."""
        with self._lock:
            return self._free_devices_locked()

    def add_replica(self, device=None, warm=True):
        """Grow the set by one replica (the elastic half of ROADMAP item
        4). The new member starts in state ``warming`` — visible on
        ``/healthz``, NEVER routed — and joins the dispatch pool only
        after :meth:`warm_replica` AOT-compiles every bucket at its own
        fresh retrace site ``serving.predict.r<i>`` (indices are never
        reused, so per-replica compile budgets stay pinned at #buckets).
        ``warm=False`` leaves the bring-up to the caller — how the
        :class:`~mxtpu.serving.controller.ServingController` runs it on
        a side thread, off the serving path. Returns the new replica."""
        with self._lock:
            idx = self._next_index
            self._next_index += 1
            if device is None:
                free = self._free_devices_locked()
                if not free:
                    raise MXNetError(
                        "ReplicaSet.add_replica: every visible device "
                        "already hosts a replica — pass device= to "
                        "double up explicitly")
                device = free[0]
            pred = Predictor(self._block, self.spec, example=self._example,
                             warmup=False, name="%s.r%d" % (self._name, idx),
                             device=device,
                             site="serving.predict.r%d" % idx)
            rep = Replica(idx, device, pred, self.backoff0_s,
                          state="warming")
            self.replicas.append(rep)
            telemetry.gauge("serving.replicas", len(self.replicas))
        if warm:
            self.warm_replica(rep)
        return rep

    def warm_replica(self, rep):
        """AOT-compile the warming replica's buckets, then flip it to
        ``healthy`` (the moment it becomes routable). A failed warmup
        removes the replica and re-raises — a member that cannot compile
        must never join the pool. Returns the replica."""
        try:
            rep.predictor.warmup()
        except Exception:
            with self._lock:
                if rep in self.replicas:
                    self.replicas.remove(rep)
                telemetry.gauge("serving.replicas", len(self.replicas))
            raise
        with self._lock:
            if rep.state == "warming":
                rep.state = "healthy"
                telemetry.inc("serving.replica.joins", tag=rep.tag)
                _log.info("serving replica %d warmed and joined the "
                          "dispatch pool", rep.index)
        return rep

    def remove_replica(self, index):
        """Begin removing a replica (scale-down, or the dead half of a
        replacement): it flips to ``retiring`` — stops pulling work, is
        never picked, is no longer probed — and leaves the set once its
        in-flight work drains (:meth:`finalize_retiring`, the PR-8 drain
        discipline: in-flight futures always complete). Returns the
        replica."""
        with self._lock:
            rep = self._find_locked(index)
            if rep.state != "retiring":
                rep.state = "retiring"
                rep.probe_at = None
                telemetry.inc("serving.replica.retirements", tag=rep.tag)
                _log.info("serving replica %d retiring (inflight=%d)",
                          rep.index, rep.inflight)
            return rep

    def finalize_retiring(self):
        """Drop retiring replicas whose in-flight work drained. Returns
        the replicas removed this pass (dispatch workers exit on seeing
        state ``removed``)."""
        done = []
        with self._lock:
            for rep in [r for r in self.replicas
                        if r.state == "retiring" and r.inflight == 0]:
                rep.state = "removed"
                self.replicas.remove(rep)
                done.append(rep)
            if done:
                telemetry.gauge("serving.replicas", len(self.replicas))
        return done

    # ------------------------------------------------------------- routing
    def pick(self, exclude=()):
        """Least-loaded healthy replica (ties break to the lowest index);
        None when every replica is down — the caller sheds."""
        with self._lock:
            cands = [r for r in self.replicas
                     if r.state == "healthy" and r.index not in exclude]
            if not cands:
                return None
            return min(cands, key=lambda r: (r.inflight, r.index))

    def healthy_count(self):
        with self._lock:
            return sum(1 for r in self.replicas if r.state == "healthy")

    def acquire(self, rep):
        with self._lock:
            rep.inflight += 1
            rep.dispatches += 1

    def release(self, rep):
        with self._lock:
            rep.inflight -= 1

    # ------------------------------------------------------- health events
    def record_success(self, rep):
        with self._lock:
            rep.consecutive = 0

    def record_failure(self, rep, now):
        """One dispatch failure; opens the breaker at ``threshold``
        consecutive failures. Returns True when this call opened it."""
        telemetry.inc("serving.replica.failures", tag=rep.tag)
        with self._lock:
            rep.consecutive += 1
            if rep.state == "healthy" and rep.consecutive >= self.threshold:
                self._open_locked(rep, now)
                return True
        return False

    def mark_wedged(self, rep, now):
        """Wedge-watchdog trip: the replica's dispatch never returned."""
        telemetry.inc("serving.replica.wedges", tag=rep.tag)
        with self._lock:
            rep.wedged = True
            if rep.state == "healthy":
                self._open_locked(rep, now)

    def force_quarantine(self, index, now, backoff_s=None):
        """Operational kill switch (and the bench's mid-run chip-loss
        knob): quarantine a replica as if its breaker opened; it
        half-open-probes back after ``backoff_s``."""
        with self._lock:
            rep = self._find_locked(index)
            if backoff_s is not None:
                rep.backoff_s = float(backoff_s)
            if rep.state == "healthy":
                self._open_locked(rep, now)
            else:
                rep.probe_at = now + rep.backoff_s
            return rep

    def _open_locked(self, rep, now):
        rep.state = "quarantined"
        rep.probe_at = now + rep.backoff_s
        if rep.down_since is None:
            # the CONTINUOUS-outage clock the controller's replacement
            # bound reads: restarts only on a successful probe
            rep.down_since = now
        telemetry.inc("serving.replica.quarantines", tag=rep.tag)
        _log.warning("serving replica %d quarantined (wedged=%s, "
                     "consecutive_failures=%d); half-open probe in %.1f s",
                     rep.index, rep.wedged, rep.consecutive, rep.backoff_s)

    # --------------------------------------------------------------- probes
    def due_probes(self, now):
        """Quarantined replicas whose backoff elapsed; each is moved to
        ``probing`` (claimed) before being returned, so concurrent
        maintainers can't double-probe."""
        with self._lock:
            due = [r for r in self.replicas
                   if r.state == "quarantined" and r.probe_at is not None
                   and now >= r.probe_at]
            for r in due:
                r.state = "probing"
            return due

    def run_probe(self, rep):
        """The half-open probe: re-warm with a synthetic min-bucket batch
        (zero-filled templates, smallest batch × smallest seq bucket) and
        block until the device answers. Raises on failure; a wedge here
        is caught by the dispatcher's watchdog bracket."""
        pred = rep.predictor
        if pred._templates is None:
            raise MXNetError("probe before settle: ReplicaSet needs "
                             "example= at construction")
        b = self.spec.batch_sizes[0]
        s = self.spec.seq_lens[0] if self.spec.seq_lens else None
        datas = [jnp.zeros((b,) + pred._bucket_trailing(t, s), dt)
                 for t, dt in pred._templates]
        flat, _ = pred._run_padded(datas)
        jax.block_until_ready([o._data for o in flat])

    def probe_result(self, rep, ok, now):
        """Half-open verdict: success closes the breaker (restore),
        failure doubles the backoff and re-quarantines."""
        with self._lock:
            if rep.state in ("retiring", "removed"):
                return  # written off mid-probe: a verdict cannot resurrect
            if ok:
                rep.state = "healthy"
                rep.wedged = False
                rep.consecutive = 0
                rep.backoff_s = self.backoff0_s
                rep.probe_at = None
                rep.down_since = None
                telemetry.inc("serving.replica.restores", tag=rep.tag)
                _log.info("serving replica %d restored by half-open probe",
                          rep.index)
            else:
                rep.state = "quarantined"
                rep.backoff_s = min(rep.backoff_s * 2, self.backoff_max_s)
                rep.probe_at = now + rep.backoff_s
                _log.warning("serving replica %d probe failed; next probe "
                             "in %.1f s", rep.index, rep.backoff_s)

    # ----------------------------------------------------- KV accountability
    def attach_accountant(self, accountant):
        """Attach a :class:`~mxtpu.serving.decode.KVCacheAccountant`
        whose per-replica pools are tagged ``r<i>`` (the same family as
        the retrace sites): ``states()`` then reports each replica's
        resident KV bytes, and the :class:`ReplicaDispatcher` sheds
        ``kv_residency`` when NO healthy replica has admission headroom —
        overload is judged by cache memory, not queue depth. The seam is
        unit-agnostic: a rowed decode engine registers worst-case slots,
        a PAGED one registers its page pool (``slots`` = pages,
        ``page_tokens`` set), and ``would_admit``/``states()`` report
        real free-page headroom with no dispatcher change. Returns
        self."""
        self._accountant = accountant
        return self

    @property
    def accountant(self):
        return self._accountant

    def kv_admissible(self):
        """True while at least one HEALTHY replica's KV pool admits
        (vacuously true without an accountant)."""
        acct = self._accountant
        if acct is None:
            return True
        with self._lock:
            tags = [r.tag for r in self.replicas if r.state == "healthy"]
        return any(acct.would_admit(t) for t in tags)

    # ------------------------------------------------------------ reporting
    def states(self):
        """Per-replica health for ``/healthz`` (JSON-serializable)."""
        acct = self._accountant
        with self._lock:
            out = [{"replica": r.index,
                    "device": str(r.device),
                    "state": r.state,
                    "inflight": r.inflight,
                    "dispatches": r.dispatches,
                    "consecutive_failures": r.consecutive,
                    "wedged": r.wedged,
                    "probe_at": r.probe_at}
                   for r in self.replicas]
        if acct is not None:
            snap = acct.snapshot()
            for row in out:
                tag = "r%d" % row["replica"]
                row["kv_resident_bytes"] = acct.resident_bytes(tag)
                pool = snap.get(tag)
                if pool is not None and pool.get("page_tokens"):
                    # paged pools surface their page economics next to
                    # the byte view: a fleet dispatcher can route on
                    # free pages, not just bytes
                    row["kv_page_tokens"] = pool["page_tokens"]
                    row["kv_pages"] = pool["slots"]
                    row["kv_pages_live"] = pool["live"]
        return out


class ReplicaDispatcher(MicroBatcher):
    """A MicroBatcher routed over a :class:`ReplicaSet`.

    Admission, coalescing, deadlines, shedding, and fault hooks are the
    base class's unchanged; what changes is dispatch: ONE worker per
    replica (each pulls the next FIFO cohort only while its replica is
    healthy — shed-aware least-loaded routing by construction), every
    dispatch bracketed by the wedge watchdog, failures counted by the
    per-replica breaker, and a monitor thread that scans for wedges and
    schedules half-open probes. ``start=False`` + an injected clock keeps
    everything synchronous for tests: :meth:`poll` runs maintenance
    (watchdog scan + due probes) and then dispatches one batch on the
    least-loaded healthy replica.
    """

    def __init__(self, replica_set, dispatch_timeout_ms=None, **kwargs):
        if not isinstance(replica_set, ReplicaSet):
            raise MXNetError("ReplicaDispatcher routes a ReplicaSet (got "
                             "%s); plain Predictors take a MicroBatcher"
                             % type(replica_set).__name__)
        self._set = replica_set
        self._timeout_s = float(
            dispatch_timeout_ms if dispatch_timeout_ms is not None
            else dispatch_timeout_ms_default()) / 1e3
        self._watch = []          # armed dispatch/probe watchdog entries
        self._flight_pending = []  # dump payloads deferred out of the lock
        self._threads = []
        self._monitor = None
        self._stop = threading.Event()
        self._tls = threading.local()
        super().__init__(replica_set, **kwargs)

    # ------------------------------------------------------------- routing
    @property
    def replica_set(self):
        return self._set

    def replica_states(self):
        """Per-replica health — surfaced by ``ModelServer`` ``/healthz``."""
        return self._set.states()

    def quarantine_replica(self, index, backoff_s=None):
        """Operational kill switch: see :meth:`ReplicaSet.force_quarantine`."""
        self._set.force_quarantine(index, self._clock(), backoff_s)
        with self._cond:
            self._cond.notify_all()

    def submit(self, inputs, deadline_ms=None, priority="interactive",
               meta=None):
        if self._set.healthy_count() == 0:
            # give a due half-open probe the chance to restore a replica
            # before refusing (the all-down shed must not outlive the
            # backoff schedule by even one submit)
            self._maintain()
            if self._set.healthy_count() == 0:
                self._shed("no_healthy_replica")
        if not self._set.kv_admissible():
            # every healthy replica's KV pool is over budget: shedding by
            # RESIDENCY, not queue depth — an admitted sequence would only
            # grow time-to-first-token on a replica with no cache room
            self._shed("kv_residency")
        return super().submit(inputs, deadline_ms=deadline_ms,
                              priority=priority, meta=meta)

    # ---------------------------------------------------------- elasticity
    def add_replica(self, device=None):
        """Grow the pool by one replica. Bring-up (AOT warmup of every
        bucket at the new ``serving.predict.r<i>`` site) runs OFF the
        serving path — on a side thread in threaded mode, inline under a
        fake clock — and the replica joins dispatch only once warm; in
        threaded mode its dedicated worker starts at that moment.
        Returns the (possibly still warming) replica."""
        rep = self._set.add_replica(device=device, warm=False)

        def _bringup():
            try:
                self._set.warm_replica(rep)  # failure removes the replica
            except Exception as e:  # noqa: BLE001 — a failed bring-up
                # must be RECORDED, not lost on a daemon thread: the
                # controller's warmup_failed decision is the only signal
                # an operator gets that capacity never arrived
                _log.exception("serving: replica %d bring-up failed",
                               rep.index)
                ctrl = self._controller
                if ctrl is not None:
                    ctrl.note_warmup_failed(e, self._clock())
                return
            with self._cond:
                self._cond.notify_all()
            if self._threads:
                self._spawn_worker(rep)

        if self._threads:
            threading.Thread(target=_bringup, daemon=True,
                             name="mxtpu-serving-warmup-r%d"
                             % rep.index).start()
        else:
            _bringup()
        return rep

    def remove_replica(self, index):
        """Retire a replica through the drain machinery: it stops
        pulling work immediately, in-flight futures complete, and the
        next maintenance pass removes it once drained."""
        rep = self._set.remove_replica(index)
        with self._cond:
            self._cond.notify_all()
        return rep

    # --------------------------------------------------------- maintenance
    def _maintain(self):
        """Wedge-watchdog scan + due half-open probes — called from
        :meth:`poll` (fake-clock tests) and admission. Probes run inline
        (synchronously) here, but STILL under a watchdog entry: if the
        probe itself wedges, the monitor's next scan abandons it — the
        replica goes back to ``quarantined`` with a doubled backoff
        instead of sticking in ``probing`` forever, and the system keeps
        its shed-never-hang guarantee even though the probing caller
        (e.g. an HTTP handler thread) stays blocked until the device
        answers or its client gives up."""
        now = self._clock()
        due = []
        with self._cond:
            self._scan_wedges_locked(now)
            for rep in self._set.due_probes(now):
                entry = {"kind": "probe", "rep": rep, "live": None,
                         "idx": -1, "deadline": now + self._timeout_s,
                         "done": False, "abandoned": False,
                         "released": True}
                self._watch.append(entry)
                due.append((rep, entry))
        self._flush_flight()
        for rep, entry in due:
            self._probe(rep, entry)
        self._post_maintain()

    def _post_maintain(self):
        """The elastic tail of every maintenance pass: drop retiring
        replicas whose in-flight work drained, then give the attached
        ServingController its control-loop tick (scale/replace decisions
        run here — outside every lock, since a bring-up is device
        work). Under a fake clock this is what makes ``poll()`` drive
        the whole control plane sleep-free."""
        self._set.finalize_retiring()
        ctrl = self._controller
        if ctrl is not None:
            ctrl.tick(self._clock())

    def _flush_flight(self):
        """Write dumps the wedge scan deferred — NEVER under self._cond
        (callers invoke this right after releasing it). No-op when
        nothing is pending or MXTPU_FLIGHT_DIR is unset."""
        if not self._flight_pending:
            return
        with self._cond:
            pending, self._flight_pending = self._flight_pending, []
        for reason, tids, extra in pending:
            telemetry.flight_record(reason, trace_ids=tids, extra=extra)

    def poll(self):
        self._maintain()
        if self._set.healthy_count() == 0:
            return 0  # nothing routable: keep requests queued (they shed
            # at admission, expire via deadlines, or serve after restore)
        return super().poll()

    def _scan_wedges_locked(self, now):
        """The wedge watchdog. An armed entry past its deadline means a
        dispatch (or probe) never answered: quarantine the replica,
        re-dispatch the batch exactly once on a healthy replica (or shed
        when none is left), and discard the wedged call's eventual
        answer as stale."""
        for entry in list(self._watch):
            if entry["done"] or entry["abandoned"] \
                    or now < entry["deadline"]:
                continue
            entry["abandoned"] = True
            self._watch.remove(entry)
            rep = entry["rep"]
            if not entry["released"]:
                entry["released"] = True
                self._set.release(rep)
            if entry["kind"] == "probe":
                # the probe itself wedged: treat as a failed probe
                self._set.probe_result(rep, False, now)
                continue
            self._set.mark_wedged(rep, now)
            _log.warning(
                "serving: dispatch %d wedged on replica %d (no answer in "
                "%.0f ms) — replica quarantined, batch re-dispatching",
                entry["idx"], rep.index, self._timeout_s * 1e3)
            # the post-mortem artifact: the wedged dispatch's traces are
            # the owning ones — a p99 investigation (or this watchdog's
            # own trip) can match a request's trace_id to the exact
            # dispatch + per-thread stacks without a live repro. The
            # DUMP is deferred (self._flight_pending, flushed by the
            # caller after releasing self._cond): flight_record does
            # disk IO + an all-thread stack walk, and doing that under
            # the serving lock would stall every submit/dispatch for
            # the dump duration — during the exact incident being
            # recorded
            self._flight_pending.append(
                ("replica_wedge",
                 [r.trace.trace_id for r in entry["live"]
                  if r.trace is not None],
                 {"replica": rep.index, "dispatch": entry["idx"],
                  "timeout_ms": self._timeout_s * 1e3}))
            for r in entry["live"]:
                telemetry.trace_mark(r.trace, "serving.wedged")
            fresh = [r for r in entry["live"] if not r.redispatched]
            burnt = [r for r in entry["live"] if r.redispatched]
            for r in burnt:
                # exactly-once: the re-dispatch ALSO wedged — fail loud
                self._fail(r, DeadlineExceeded(
                    "re-dispatched batch wedged again (replica %d)"
                    % rep.index))
                telemetry.inc("serving.deadline_expired")
            if not fresh:
                continue
            if self._set.healthy_count() == 0:
                telemetry.inc("serving.shed", len(fresh),
                              tag="no_healthy_replica")
                err = QueueFull("request shed: no_healthy_replica (wedge "
                                "re-dispatch found no live replica)")
                for r in fresh:
                    self._fail(r, err)
                continue
            for r in reversed(fresh):
                r.redispatched = True
                # same _Request, same .trace: the re-dispatch's spans and
                # stages JOIN the original trace — the tree shows wedge ->
                # re-dispatch -> delivery as one causal story
                telemetry.trace_mark(r.trace, "serving.redispatch")
                self._q.appendleft(r)  # head: it already waited its turn
                self._items += r.n
            telemetry.inc("serving.replica.redispatches", tag=rep.tag)
            telemetry.gauge("serving.queue_depth", self._items)
            self._cond.notify_all()

    def _probe(self, rep, entry=None):
        """Run one half-open probe (device work — never under the lock)."""
        ok = True
        try:
            with telemetry.span("serving.probe"):
                self._set.run_probe(rep)
        except Exception as e:  # noqa: BLE001 — verdict, not control flow
            ok = False
            _log.warning("serving replica %d half-open probe failed: %s",
                         rep.index, e)
        with self._cond:
            if entry is not None:
                if entry["abandoned"]:
                    return  # the scan already ruled it a wedged probe
                entry["done"] = True
                if entry in self._watch:
                    self._watch.remove(entry)
            self._set.probe_result(rep, ok, self._clock())
            self._cond.notify_all()

    # -------------------------------------------------------------- dispatch
    def _run_batch(self, live, joined, idx):
        now = self._clock()
        t_route = time.perf_counter()
        rep = getattr(self._tls, "rep", None)  # a worker owns its replica
        if rep is not None and rep.state != "healthy":
            rep = None  # quarantined between gather and dispatch: re-route
        if rep is None:
            rep = self._set.pick()
        if rep is None:
            # admitted while healthy, orphaned by the time it dispatched:
            # shed late (bounded) rather than hang
            telemetry.inc("serving.shed", len(live),
                          tag="no_healthy_replica")
            err = QueueFull("request shed: no_healthy_replica")
            for r in live:
                self._fail(r, err)
            return
        self._set.acquire(rep)
        telemetry.inc("serving.replica.dispatches", tag=rep.tag)
        entry = {"kind": "dispatch", "rep": rep, "live": live, "idx": idx,
                 "deadline": now + self._timeout_s,
                 "done": False, "abandoned": False, "released": False}
        with self._cond:
            self._watch.append(entry)
        # routing + watchdog arm = the "replica dispatch" stage of the
        # per-request breakdown (runs under the cohort lead's trace)
        self._share_stage(live, "serving.dispatch",
                          time.perf_counter() - t_route)
        try:
            host = self._execute(rep, joined, idx, live)
        except Exception as e:  # noqa: BLE001 — breaker counts it
            with self._cond:
                abandoned = entry["abandoned"]
                entry["done"] = True
                if entry in self._watch:
                    self._watch.remove(entry)
                if not entry["released"]:
                    entry["released"] = True
                    self._set.release(rep)
                opened = self._set.record_failure(rep, self._clock())
                self._cond.notify_all()
            if opened:
                # the failure that OPENED the breaker: capture the moment
                # with the owning traces tagged (flight-recorder trigger)
                telemetry.flight_record(
                    "breaker_open",
                    trace_ids=[r.trace.trace_id for r in live
                               if r.trace is not None],
                    extra={"replica": rep.index, "dispatch": idx,
                           "error": "%s: %s" % (type(e).__name__, e)})
            if not abandoned:
                self._fail_batch(live, e, idx)
            return
        if host is _WEDGED:
            # simulated wedge (replica_wedge fault): the entry stays armed
            # — the watchdog trip quarantines + re-dispatches
            return
        with self._cond:
            stale = entry["abandoned"]
            entry["done"] = True
            if entry in self._watch:
                self._watch.remove(entry)
            if not entry["released"]:
                entry["released"] = True
                self._set.release(rep)
            self._set.record_success(rep)
            self._cond.notify_all()
        if stale:
            # the wedge watchdog already re-dispatched this batch; a late
            # answer must not double-deliver
            telemetry.inc("serving.replica.stale_results", tag=rep.tag)
            return
        self._deliver(live, host)

    def _execute(self, rep, joined, idx, live=()):
        if inject("replica_fail", idx):
            raise ReplicaFailure(
                "injected replica failure (dispatch %d, replica %d)"
                % (idx, rep.index))
        if inject("replica_wedge", idx):
            return _WEDGED
        t0 = time.perf_counter()
        flat, _fmt, _bucket = rep.predictor.predict_flat(tuple(joined))
        self._share_stage(live, "serving.predict", time.perf_counter() - t0)
        t0 = time.perf_counter()
        with telemetry.span("serving.fetch", cat="sync"):
            host = [o.asnumpy() for o in flat]
        self._share_stage(live, "serving.fetch", time.perf_counter() - t0)
        return host

    # ---------------------------------------------------------------- worker
    def _spawn_worker(self, rep):
        t = threading.Thread(target=self._replica_worker, args=(rep,),
                             daemon=True,
                             name="mxtpu-serving-replica-%d" % rep.index)
        self._threads.append(t)
        t.start()
        return t

    def start(self):
        if self._threads:
            return self
        if not getattr(self._set, "_jits", True):
            raise MXNetError(
                "ReplicaDispatcher.start on a cold ReplicaSet: warmup() "
                "every replica first")
        for rep in self._set.replicas:
            self._spawn_worker(rep)
        interval = max(0.005, min(0.25, self._timeout_s / 4))
        self._monitor = threading.Thread(
            target=self._monitor_loop, args=(interval,), daemon=True,
            name="mxtpu-serving-monitor")
        self._monitor.start()
        self._thread = self._threads[0]  # base-class compat only
        return self

    def _replica_worker(self, rep):
        self._tls.rep = rep
        try:
            self._worker_loop_for(rep)
        except Exception as e:  # noqa: BLE001 — same barrier as the base
            self._worker_crashed(e)

    def _worker_loop_for(self, rep):
        # mirrors MicroBatcher._worker_loop with two deltas: a wedge scan
        # + routability gate per iteration, and BOUNDED waits everywhere
        # (which also subsume the base loop's dedicated draining-park
        # branch — a parked worker here re-checks state every 250 ms)
        while True:
            with self._cond:
                batch = None
                while batch is None:
                    if self._closed and not self._q:
                        return
                    if rep.state == "removed":
                        return  # retired and drained: this worker is done
                    now = self._clock()
                    self._scan_wedges_locked(now)
                    if rep.state != "healthy":
                        # quarantined/probing/retiring: park (the monitor
                        # owns probes and retirement); bounded wait
                        # re-checks state
                        self._cond.wait(0.05)
                        continue
                    batch = self._gather_locked(now)
                    if batch is not None:
                        break
                    if self._q:
                        head_due = self._q[0].t_enq + self.max_wait_s - now
                        self._cond.wait(min(max(head_due, 1e-4), 0.25))
                    else:
                        self._cond.wait(0.25)
                self._inflight += len(batch)
            self._flush_flight()
            try:
                self._dispatch(batch)
            finally:
                with self._cond:
                    self._inflight -= len(batch)
                    self._cond.notify_all()

    def _monitor_loop(self, interval):
        """Wedge scans + probe scheduling with real-time pacing. This
        thread never does device work itself: probes run on fresh daemon
        threads (a wedged probe must not stop the scanning), each armed
        with its own watchdog entry."""
        while not self._stop.is_set():
            due = []
            with self._cond:
                if self._closed and not self._q and not self._watch:
                    return
                now = self._clock()
                self._scan_wedges_locked(now)
                for rep in self._set.due_probes(now):
                    entry = {"kind": "probe", "rep": rep, "live": None,
                             "idx": -1, "deadline": now + self._timeout_s,
                             "done": False, "abandoned": False,
                             "released": True}
                    self._watch.append(entry)
                    due.append((rep, entry))
            self._flush_flight()
            for rep, entry in due:
                threading.Thread(
                    target=self._probe, args=(rep, entry), daemon=True,
                    name="mxtpu-serving-probe-%d" % rep.index).start()
            self._post_maintain()
            self._stop.wait(interval)

    # ------------------------------------------------------- drain / close
    def _worker_alive(self):
        return any(t.is_alive() for t in self._threads)

    def _pending_extra(self):
        return any(e["kind"] == "dispatch" and not e["done"]
                   for e in self._watch)

    def _abort_extra_locked(self, err):
        dead = []
        for entry in self._watch:
            if entry["kind"] == "dispatch" and not entry["done"] \
                    and not entry["abandoned"]:
                entry["abandoned"] = True
                dead.extend(entry["live"])
        self._watch = [e for e in self._watch if e["kind"] != "dispatch"]
        return dead

    def close(self, timeout=5.0):
        self.drain(timeout=timeout)
        with self._cond:
            self._closed = True
            self._draining = True
            self._cond.notify_all()
        self._stop.set()
        for t in self._threads:
            t.join(timeout)
        if self._monitor is not None:
            self._monitor.join(timeout)
        return self
