"""Dynamic micro-batching: coalesce requests into bucketed device batches.

The throughput/latency tradeoff of serving a compiled accelerator model
is entirely in WHEN you dispatch: per-request dispatch underfills the MXU
(the serve_bench sweep shows items/s growing with batch), waiting forever
fills it but blows the latency SLO. The :class:`MicroBatcher` is the
standard answer (the batching core of every model server): a bounded FIFO
queue, coalesce until ``max_batch_size`` items are waiting OR the head
request has waited ``max_wait_ms`` — whichever comes first — then dispatch
ONE padded bucket through the :class:`~mxtpu.serving.engine.Predictor`.

Semantics:

* **FIFO within bucket** — requests execute in arrival order among those
  sharing a seq bucket; a different-bucket request never jumps the queue
  it belongs to (it waits for its own bucket's dispatch).
* **Bounded queue + load shedding** — ``submit`` on a full queue raises
  :class:`QueueFull` immediately (the server maps it to 503 and the
  ``serving.shed`` counter): shedding at admission keeps tail latency
  bounded for the requests already admitted.
* **Per-request deadlines** — a request whose deadline passed while it
  queued is completed with :class:`DeadlineExceeded` at dispatch time
  instead of burning a device slot on an answer nobody is waiting for.
* **Deterministic failure paths** — ``MXTPU_FAULT_INJECT`` kinds
  ``serve_timeout`` (batch dispatch index: that batch's requests all
  expire) and ``serve_overload`` (submit index: that submit sheds) make
  both degradation paths testable without wall-clock games.
* **Testable time** — the clock is injected (``clock=``); tier-1 tests
  drive a stopped batcher (``start=False``) with a fake clock through
  :meth:`poll`, so coalesce-by-size vs coalesce-by-deadline are exact
  assertions, not sleeps.

Telemetry (all through :mod:`mxtpu.telemetry`, folded by
``tools/telemetry_report.py`` with no changes): ``serving.requests`` /
``serving.batches`` / ``serving.shed{reason}`` /
``serving.deadline_expired`` counters, ``serving.queue_depth`` gauge,
``serving.batch_fill`` + ``serving.latency_s`` (p50/p99 via snapshot)
histograms, and the ``serving.predict`` / ``serving.fetch`` spans.
"""
from __future__ import annotations

import collections
import logging
import os
import threading
import time

from .. import telemetry
from ..base import MXNetError
from ..resilience import inject

__all__ = ["MicroBatcher", "QueueFull", "DeadlineExceeded",
           "max_batch_default", "max_wait_ms_default", "queue_default",
           "batch_aging_ms_default", "PRIORITIES"]

_log = logging.getLogger("mxtpu.serving")


# ------------------------------------------------------------------ policies
def max_batch_default():
    """Coalescing cap (``MXTPU_SERVE_MAX_BATCH``, default 8): at most this
    many ITEMS per dispatched batch; normally the Predictor's max bucket."""
    return int(os.environ.get("MXTPU_SERVE_MAX_BATCH", "8"))


def max_wait_ms_default():
    """Head-of-line wait bound (``MXTPU_SERVE_MAX_WAIT_MS``, default 5):
    a queued head request dispatches after this many ms even if the batch
    is not full — the latency half of the coalescing tradeoff."""
    return float(os.environ.get("MXTPU_SERVE_MAX_WAIT_MS", "5"))


def queue_default():
    """Admission bound in ITEMS (``MXTPU_SERVE_QUEUE``, default 256):
    beyond it submits shed (503) instead of growing tail latency."""
    return int(os.environ.get("MXTPU_SERVE_QUEUE", "256"))


def batch_aging_ms_default():
    """Starvation floor for the ``batch`` priority class
    (``MXTPU_SERVE_BATCH_AGING_MS``, default 1000): batch-class requests
    yield their coalescing slot to ``interactive`` traffic, but a batch
    head that has waited this long dispatches ahead of fresher
    interactive work — strict priority, never outright starvation."""
    return float(os.environ.get("MXTPU_SERVE_BATCH_AGING_MS", "1000"))


# the two priority classes: interactive wins the coalescing slot, batch
# is the first to shed (evicted from the queue tail to admit interactive
# under pressure) and dispatches only when no interactive cohort is
# ready or its aging floor has passed
PRIORITIES = ("interactive", "batch")


class QueueFull(MXNetError):
    """Request shed at admission (queue full / draining / injected
    overload). The HTTP front maps this to 503."""


class DeadlineExceeded(MXNetError):
    """The request's deadline passed before its batch dispatched (or the
    ``serve_timeout`` fault fired). The HTTP front maps this to 504."""


class _Future:
    """Minimal completion handle (threading.Event + value-or-error).
    Delivery also attaches the request's causal-trace identity: ``trace_id``
    and the per-stage latency ``breakdown`` (``{stage: seconds}``, summing
    to ~``e2e_s``) that the HTTP front returns in the ``/predict`` body."""

    __slots__ = ("_event", "_value", "_error", "trace_id", "breakdown",
                 "e2e_s")

    def __init__(self):
        self._event = threading.Event()
        self._value = None
        self._error = None
        self.trace_id = None
        self.breakdown = None
        self.e2e_s = None

    def done(self):
        return self._event.is_set()

    def result(self, timeout=None):
        if not self._event.wait(timeout):
            raise DeadlineExceeded("no result within %ss" % timeout)
        if self._error is not None:
            raise self._error
        return self._value


class _Request:
    __slots__ = ("inputs", "n", "bucket_key", "deadline", "t_enq", "future",
                 "redispatched", "trace", "priority", "meta")

    def __init__(self, inputs, n, bucket_key, deadline, t_enq, trace=None,
                 priority="interactive", meta=None):
        self.inputs = inputs
        self.n = n
        self.bucket_key = bucket_key
        self.deadline = deadline
        self.t_enq = t_enq
        self.priority = priority
        # opaque attribution dict (the zoo stamps tenant/model/version);
        # rides delivery and expiry into the controller's observe path
        self.meta = meta
        self.future = _Future()
        # set when a wedge-watchdog trip re-enqueues this request on a
        # healthy replica: re-dispatch happens exactly ONCE (replicas.py)
        self.redispatched = False
        # the request's causal trace: created at submit on the caller's
        # thread, handed to whichever dispatch worker runs its cohort
        # (telemetry.trace_handoff), and carried THROUGH a wedge
        # re-dispatch so the second dispatch's spans join the original
        # tree instead of starting an unlinked one
        self.trace = trace


class MicroBatcher:
    """See the module docstring. ``predictor`` is a warmed
    :class:`~mxtpu.serving.engine.Predictor` (or any object with
    ``predict_flat``); ``start=False`` leaves the worker thread off so
    tests (and the fake clock) drive dispatch through :meth:`poll`."""

    def __init__(self, predictor, max_batch_size=None, max_wait_ms=None,
                 max_queue=None, clock=time.monotonic, start=True,
                 allow_cold=False, admission_gate=None,
                 batch_aging_ms=None):
        self._pred = predictor
        # optional admission hook beyond queue depth: called with the
        # request's item count, returns a shed-reason string to refuse or
        # None to admit — how the KVCacheAccountant makes overload shed
        # by KV residency (decode.py:KVCacheAccountant.gate), and the
        # seam any resource ledger (device memory, SLO predictor) plugs
        # into without subclassing. The hook is unit-agnostic on purpose:
        # the same gate sheds by worst-case rows for a rowed KV pool and
        # by real free-PAGE headroom for a paged one (the accountant's
        # register() decides the unit, not this batcher)
        self._gate = admission_gate
        # the SLO control plane (controller.attach via ServingController):
        # predictive admission consults it in _admit, delivery feeds its
        # latency model in _deliver — None = the static depth-shed path
        self._controller = None
        self.max_batch = int(max_batch_size if max_batch_size is not None
                             else max_batch_default())
        self.max_wait_s = float(max_wait_ms if max_wait_ms is not None
                                else max_wait_ms_default()) / 1e3
        self.max_queue = int(max_queue if max_queue is not None
                             else queue_default())
        self.batch_aging_s = float(
            batch_aging_ms if batch_aging_ms is not None
            else batch_aging_ms_default()) / 1e3
        self._clock = clock
        self._q = collections.deque()
        self._items = 0
        self._cond = threading.Condition()
        self._draining = False
        self._closed = False
        self._crashed = False  # worker died on an unexpected exception
        self._batch_index = 0
        self._inflight = 0     # requests popped from the queue, result not
        self._thread = None    # yet delivered — drain() waits for BOTH
        if start:
            if not allow_cold and not getattr(predictor, "_jits", True):
                # a cold predictor compiles in the serving hot path — the
                # exact stall the AOT warmup exists to prevent
                raise MXNetError(
                    "MicroBatcher(start=True) on a cold Predictor: call "
                    "predictor.warmup() first (or pass allow_cold=True)")
            self.start()

    # ------------------------------------------------------------- admission
    def attach_controller(self, controller):
        """Wire the SLO control plane in (normally done by
        ``ServingController.__init__``): admission consults
        ``controller.admit`` (predictive shed), delivery feeds
        ``controller.observe``, sheds/expiries feed its pressure
        signals. Returns self."""
        self._controller = controller
        return self

    def submit(self, inputs, deadline_ms=None, priority="interactive",
               meta=None):
        """Enqueue one request — ``inputs`` is an array or tuple of arrays
        sharing batch axis 0 (host numpy stays host-side until dispatch).
        Returns a future; raises :class:`QueueFull` when shed.
        ``priority`` is the request's class (``interactive`` | ``batch``:
        batch yields its coalescing slot to interactive traffic — up to
        the ``MXTPU_SERVE_BATCH_AGING_MS`` starvation floor — and is the
        first evicted under queue pressure). ``meta`` is an opaque
        attribution dict (the model zoo stamps ``tenant``/``model``/
        ``version``) handed to the controller with this request's
        delivery or expiry verdict — per-tenant SLO attainment reads it.

        Each admitted request starts a causal trace here (the
        ``serving.submit`` stage covers validation + enqueue on the
        caller's thread); everything downstream — queue wait, the cohort
        pad, the device call, the fetch, delivery — is attributed to that
        trace across every thread it crosses, and the final breakdown is
        attached to the returned future."""
        trace = telemetry.new_trace()
        t0 = time.perf_counter()
        with telemetry.trace_handoff(trace), \
                telemetry.span("serving.submit"):
            req = self._admit(inputs, deadline_ms, trace, priority, meta)
        telemetry.add_stage(trace, "serving.submit",
                            time.perf_counter() - t0)
        return req.future

    def _admit(self, inputs, deadline_ms, trace, priority="interactive",
               meta=None):
        if priority not in PRIORITIES:
            raise MXNetError("submit: unknown priority %r (expected one "
                             "of %s)" % (priority, "|".join(PRIORITIES)))
        if not isinstance(inputs, (tuple, list)):
            inputs = (inputs,)
        if getattr(inputs[0], "ndim", 0) < 1:
            raise MXNetError("submit: request inputs need a batch axis")
        n = int(inputs[0].shape[0])
        if n < 1:
            raise MXNetError("submit: empty request")
        if n > self.max_batch:
            raise MXNetError(
                "submit: request of %d items exceeds max_batch_size=%d — "
                "chunk large offline batches through Predictor.predict"
                % (n, self.max_batch))
        spec = getattr(self._pred, "spec", None)
        self._validate_shapes(inputs, spec)
        bucket_key = None
        if spec is not None and spec.seq_lens is not None:
            bucket_key = spec.seq_bucket(
                int(inputs[0].shape[spec.seq_axis])
                if inputs[0].ndim > spec.seq_axis else 0)
        if inject("serve_overload"):
            self._shed("injected_overload")
        if self._gate is not None:
            reason = self._gate(n)
            if reason:
                self._shed(str(reason))
        if self._controller is not None:
            # predictive admission (the SLO control plane): shed NOW when
            # the per-bucket latency model already predicts a deadline
            # miss — before MXTPU_SERVE_QUEUE fills. queued_ahead is an
            # advisory snapshot; the model's backlog term only needs the
            # order of magnitude
            queued_ahead = sum(r.n for r in list(self._q)
                               if r.bucket_key == bucket_key)
            reason = self._controller.admit(
                n, bucket_key,
                None if deadline_ms is None else deadline_ms / 1e3,
                priority, queued_ahead=queued_ahead)
            if reason:
                telemetry.trace_mark(trace, "serving.controller.shed")
                self._shed(str(reason))
        now = self._clock()
        deadline = None if deadline_ms is None else now + deadline_ms / 1e3
        req = _Request(inputs, n, bucket_key, deadline, now, trace,
                       priority, meta)
        evicted, shed_reason = (), None
        with self._cond:
            if self._crashed:
                # crash barrier: a dead worker thread can never deliver —
                # admitting would strand this future forever
                shed_reason = "worker_crashed"
            elif self._draining or self._closed:
                shed_reason = "draining"
            else:
                if self._items + n > self.max_queue:
                    # submit-time pressure: sweep already-expired entries
                    # first — a request whose deadline passed while
                    # queued occupies admission capacity for an answer
                    # nobody is waiting for, and used to crowd out fresh
                    # work until its cohort dispatched
                    self._sweep_expired_locked(now)
                if self._items + n > self.max_queue \
                        and priority == "interactive":
                    # batch is first to shed: evict the NEWEST
                    # batch-class entries to admit interactive work
                    # under overload
                    evicted = self._evict_batch_locked(n)
                if self._items + n > self.max_queue:
                    shed_reason = "queue_full"
                else:
                    self._q.append(req)
                    self._items += n
                    telemetry.gauge("serving.queue_depth", self._items)
                    self._cond.notify()
        # victims complete BEFORE any shed raise: an eviction must never
        # strand a future (and _evict_batch_locked only evicts when the
        # eviction actually makes room, so a still-shedding interactive
        # submit cannot drop batch work for nothing)
        for victim in evicted:
            self._fail(victim, QueueFull(
                "request shed: priority_evict (batch-class entry evicted "
                "for interactive admission)"))
        if shed_reason is not None:
            self._shed(shed_reason)
        telemetry.inc("serving.requests")
        return req

    def _sweep_expired_locked(self, now):
        """Drop queued requests whose deadline already passed (each
        completes with :class:`DeadlineExceeded`, exactly as it would
        have at dispatch) so fresh work is admitted before the depth
        bound sheds it."""
        for r in [r for r in self._q
                  if r.deadline is not None and now > r.deadline]:
            self._q.remove(r)
            self._items -= r.n
            self._expire(r)
        telemetry.gauge("serving.queue_depth", self._items)

    def _evict_batch_locked(self, need):
        """Remove newest batch-class entries until ``need`` more items
        fit. Returns the victims; the caller fails them outside the hot
        bookkeeping. Evicts NOTHING when even a full eviction could not
        make room — dropping batch work for an interactive submit that
        sheds anyway would be a pure loss."""
        evictable = sum(r.n for r in self._q if r.priority == "batch")
        if self._items - evictable + need > self.max_queue:
            return []
        victims = []
        for r in [r for r in reversed(self._q) if r.priority == "batch"]:
            if self._items + need <= self.max_queue:
                break
            self._q.remove(r)
            self._items -= r.n
            victims.append(r)
            telemetry.inc("serving.shed", tag="priority_evict")
        if victims:
            telemetry.gauge("serving.queue_depth", self._items)
            if self._controller is not None:
                self._controller.note_shed("priority_evict", self._clock())
        return victims

    def _validate_shapes(self, inputs, spec):
        """Admission-time template check: a malformed request must be
        refused HERE (``MXNetError`` -> 400), not poison its coalesced
        cohort (a bad concat fails EVERY co-batched request with a 500)
        or sneak an off-template shape into a hot-path XLA compile."""
        templates = getattr(self._pred, "input_templates", None)
        if templates is None:
            return
        if len(inputs) != len(templates):
            raise MXNetError(
                "submit: model takes %d input(s), request has %d"
                % (len(templates), len(inputs)))
        seq_axis = spec.seq_axis if spec is not None and \
            spec.seq_lens is not None else None
        for i, (a, (trail, _dt)) in enumerate(zip(inputs, templates)):
            if a.ndim != len(trail) + 1:
                raise MXNetError(
                    "submit: input %d has %d dims, model expects %d"
                    % (i, a.ndim, len(trail) + 1))
            for ax in range(1, a.ndim):
                if ax == seq_axis:
                    continue  # bucketed axis: length checked by seq_bucket
                if a.shape[ax] != trail[ax - 1]:
                    raise MXNetError(
                        "submit: input %d axis %d is %d, model expects %d"
                        % (i, ax, a.shape[ax], trail[ax - 1]))

    def _shed(self, reason):
        telemetry.inc("serving.shed", tag=reason)
        if self._controller is not None:
            self._controller.note_shed(reason, self._clock())
        raise QueueFull("request shed: %s" % reason)

    @property
    def queue_depth(self):
        return self._items

    def queue_depths(self):
        """Queued ITEMS per priority class (the /healthz controller
        view; the untagged ``serving.queue_depth`` gauge stays the
        total)."""
        out = dict.fromkeys(PRIORITIES, 0)
        with self._cond:
            for r in self._q:
                out[r.priority] = out.get(r.priority, 0) + r.n
        return out

    @property
    def draining(self):
        return self._draining

    # ------------------------------------------------------------ coalescing
    def _lead_locked(self, now):
        """``(lead, yielded)``: the request whose cohort dispatches next
        — strict priority (the first interactive request in FIFO order)
        with an aging floor: a batch-class head that has waited
        ``batch_aging_s`` takes the slot regardless, so batch yields
        under load but never starves outright. ``yielded`` is the
        batch-class overall head an interactive lead is jumping (the
        caller records the yield decision iff that cohort dispatches)."""
        first_inter = first_batch = None
        for r in self._q:
            if r.priority == "batch":
                if first_batch is None:
                    first_batch = r
            elif first_inter is None:
                first_inter = r
            if first_inter is not None and first_batch is not None:
                break
        if first_inter is None:
            return first_batch, None
        if first_batch is None:
            return first_inter, None
        if (now - first_batch.t_enq) >= self.batch_aging_s:
            # aging floor: batch has waited long enough to take the slot
            return first_batch, None
        # the batch head yields its slot to the interactive cohort; the
        # caller records the yield ONLY when that cohort dispatches
        yielded = first_batch if self._q[0] is first_batch else None
        return first_inter, yielded

    def _gather_locked(self, now):
        """Under the lock: the coalescing rule. Takes the lead request's
        bucket cohort in FIFO order up to ``max_batch`` items (the lead
        is the FIFO head within the priority ladder — see
        :meth:`_lead_locked`); dispatches when full, when the lead
        waited ``max_wait_s``, or when draining. Returns the requests to
        dispatch, or None to keep waiting."""
        if not self._q:
            return None
        lead, yielded = self._lead_locked(now)
        take, n = [], 0
        for r in self._q:
            if r.bucket_key != lead.bucket_key:
                continue  # FIFO within bucket: other cohorts keep queueing
            if n + r.n > self.max_batch:
                break
            take.append(r)
            n += r.n
            if n == self.max_batch:
                break
        if n >= self.max_batch or self._draining or \
                (now - lead.t_enq) >= self.max_wait_s:
            if yielded is not None and yielded not in take:
                # an interactive cohort is jumping the batch-class head:
                # the yield decision, visible in telemetry and on the
                # yielded request's own trace
                telemetry.inc("serving.controller.decisions", tag="yield")
                telemetry.trace_mark(yielded.trace,
                                     "serving.controller.yield")
            for r in take:
                self._q.remove(r)  # O(queue) but queues are bounded-small
            self._items -= n
            telemetry.gauge("serving.queue_depth", self._items)
            return take
        return None

    def poll(self):
        """Dispatch at most one coalesced batch if the rule allows it NOW
        (non-blocking — the fake-clock test hook and the drain helper).
        Returns the number of requests dispatched."""
        with self._cond:
            batch = self._gather_locked(self._clock())
            if batch:
                self._inflight += len(batch)
        if not batch:
            return 0
        try:
            self._dispatch(batch)
        finally:
            with self._cond:
                self._inflight -= len(batch)
                self._cond.notify_all()
        return len(batch)

    # -------------------------------------------------------------- dispatch
    def _dispatch(self, batch):
        idx = self._batch_index
        self._batch_index += 1
        now = self._clock()
        live = []
        for r in batch:
            # queue-wait is an interval BETWEEN threads (enqueue on the
            # caller, pop on this worker) — credited from the injected
            # clock so fake-clock tests see exact waits
            telemetry.add_stage(r.trace, "serving.queue_wait",
                                max(0.0, now - r.t_enq), event=True)
            if r.deadline is not None and now > r.deadline:
                self._expire(r)
            else:
                live.append(r)
        if live and inject("serve_timeout", idx):
            # deterministic timeout path: the whole batch expires as if the
            # device never answered within anyone's deadline
            for r in live:
                self._expire(r)
            live = []
        if not live:
            return
        # the dispatch worker ADOPTS the cohort lead's trace for the
        # batch-level stages; the other members get the same stage
        # durations in their breakdowns (_share_stage) plus a cohort link
        # so the chrome timeline shows whose batch carried them
        with telemetry.trace_handoff(live[0].trace):
            for r in live[1:]:
                telemetry.link(r.trace, "serving.cohort")
            t0 = time.perf_counter()
            try:
                with telemetry.span("serving.pad"):
                    joined = self._join(live)
            except Exception as e:  # noqa: BLE001 — bad batch must not kill
                self._fail_batch(live, e, idx)
                return
            self._share_stage(live, "serving.pad",
                              time.perf_counter() - t0)
            self._run_batch(live, joined, idx)

    @staticmethod
    def _share_stage(live, name, dur_s):
        """Credit one batch-level stage to EVERY cohort member's
        breakdown (the trace tree records it once, under the lead)."""
        for r in live:
            telemetry.add_stage(r.trace, name, dur_s)

    def _join(self, live):
        """Host-side coalesce: one numpy array per model input, the
        cohort's requests concatenated along the batch axis (raw seq
        lengths padded host-side to the cohort's shared seq bucket)."""
        import numpy as np
        n_inputs = len(live[0].inputs)
        spec = getattr(self._pred, "spec", None)
        seq = live[0].bucket_key  # the cohort's shared seq bucket
        joined = []
        for i in range(n_inputs):
            parts = [np.asarray(r.inputs[i]) for r in live]
            if seq is not None and spec is not None:
                # one cohort, one seq bucket — but raw lengths differ;
                # pad each request host-side to the cohort bucket so
                # the concat (and the device pad) see one shape
                ax = spec.seq_axis
                parts = [np.pad(p, [(0, seq - p.shape[ax])
                                    if d == ax else (0, 0)
                                    for d in range(p.ndim)],
                                constant_values=spec.pad_value)
                         if p.ndim > ax and p.shape[ax] != seq else p
                         for p in parts]
            joined.append(parts[0] if len(parts) == 1
                          else np.concatenate(parts, axis=0))
        return joined

    def _run_batch(self, live, joined, idx):
        """Execute ONE joined batch and deliver its results — the
        single-predictor path. :class:`~mxtpu.serving.replicas.
        ReplicaDispatcher` overrides this with routed, wedge-watchdogged,
        breaker-guarded dispatch over a ReplicaSet. Runs under the cohort
        lead's trace (``_dispatch``): the engine's ``serving.predict``
        span and the fetch nest into the request tree, and both stage
        durations land in every member's breakdown."""
        try:
            # device work: pad -> compiled forward -> slice (zero d2h)
            t0 = time.perf_counter()
            flat, _fmt, _bucket = self._pred.predict_flat(tuple(joined))
            self._share_stage(live, "serving.predict",
                              time.perf_counter() - t0)
            # the ONE declared d2h of the serving loop: fetch outputs once
            # per batch, split per request host-side
            t0 = time.perf_counter()
            with telemetry.span("serving.fetch", cat="sync"):
                host = [o.asnumpy() for o in flat]
            self._share_stage(live, "serving.fetch",
                              time.perf_counter() - t0)
        except Exception as e:  # noqa: BLE001 — a bad batch must not kill
            self._fail_batch(live, e, idx)
            return
        self._deliver(live, host)

    def _fail_batch(self, live, error, idx):
        """Every caller of a failed batch gets the error; the worker
        survives (and the ReplicaDispatcher's breaker counts it)."""
        for r in live:
            self._fail(r, error)
        telemetry.inc("serving.batch_errors")
        _log.exception("serving batch %d failed", idx)

    def _deliver(self, live, host):
        telemetry.inc("serving.batches")
        off = 0
        done = self._clock()
        for r in live:
            t0 = time.perf_counter()
            with telemetry.trace_handoff(r.trace), \
                    telemetry.span("serving.deliver"):
                outs = [h[off:off + r.n] for h in host]
                off += r.n
                r.future._value = outs[0] if len(outs) == 1 else tuple(outs)
            telemetry.add_stage(r.trace, "serving.deliver",
                                time.perf_counter() - t0)
            # the breakdown rides the future BEFORE the event wakes the
            # caller — by the time result() returns, trace_id/breakdown
            # /e2e_s are complete and readable without a race
            if r.trace is not None:
                r.future.trace_id = r.trace.trace_id
                r.future.breakdown = telemetry.trace_breakdown(r.trace)
                r.future.e2e_s = done - r.t_enq
            if self._controller is not None:
                # the observe half of the control loop: this delivery's
                # stage breakdown trains the per-bucket latency model,
                # and its deadline verdict feeds SLO attainment. With
                # causal tracing OFF (MXTPU_TRACE=0) there is no
                # breakdown — approximate the total with the
                # enqueue->deliver interval (same injected clock) so
                # predictive admission degrades gracefully instead of
                # going silently inert
                bd = r.future.breakdown
                if not bd:
                    bd = {"serving.queue_wait": max(0.0, done - r.t_enq)}
                self._controller.observe(
                    r.bucket_key, bd,
                    hit=r.deadline is None or done <= r.deadline,
                    now=done, n=r.n, meta=r.meta)
            r.future._event.set()
            telemetry.observe("serving.latency_s", done - r.t_enq)

    def _expire(self, req):
        telemetry.inc("serving.deadline_expired")
        if self._controller is not None:
            self._controller.note_expired(self._clock(), meta=req.meta)
        self._fail(req, DeadlineExceeded(
            "deadline passed before dispatch (queued %.1f ms)"
            % ((self._clock() - req.t_enq) * 1e3)))

    @staticmethod
    def _fail(req, error):
        req.future._error = error
        req.future._event.set()

    # ---------------------------------------------------------------- worker
    def start(self):
        if self._thread is not None:
            return self
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="mxtpu-serving-batcher")
        self._thread.start()
        return self

    def _loop(self):
        # crash barrier (ISSUE 8 satellite): _dispatch already catches
        # per-batch errors, but an exception OUTSIDE it (a bug in
        # _gather_locked, a corrupted queue) used to kill the daemon
        # thread silently — every queued future then hung forever on a
        # worker that no longer exists. Fail loud instead.
        try:
            self._worker_loop()
        except Exception as e:  # noqa: BLE001 — barrier, not control flow
            self._worker_crashed(e)

    def _worker_loop(self):
        while True:
            with self._cond:
                batch = None
                while batch is None:
                    if self._closed and not self._q:
                        return
                    now = self._clock()
                    batch = self._gather_locked(now)
                    if batch is not None:
                        break
                    if self._draining and not self._q:
                        # drained: park until new state (close or, never,
                        # new work — submits reject while draining)
                        self._cond.wait(0.05)
                        continue
                    if self._q:
                        head_due = self._q[0].t_enq + self.max_wait_s - now
                        self._cond.wait(max(head_due, 1e-4))
                    else:
                        self._cond.wait()
                self._inflight += len(batch)
            try:
                self._dispatch(batch)
            finally:
                with self._cond:
                    self._inflight -= len(batch)
                    self._cond.notify_all()

    def _worker_crashed(self, exc):
        """A dispatch worker died on an unexpected exception: fail every
        queued future (their worker is gone — ``result()`` would wait
        forever) and refuse new submits (``serving.shed{worker_crashed}``)
        so callers see a loud 503, not a hang."""
        telemetry.inc("serving.worker_crashes")
        _log.exception("serving dispatch worker crashed — failing queued "
                       "futures and refusing new submits")
        err = MXNetError("serving worker crashed: %s: %s"
                         % (type(exc).__name__, exc))
        with self._cond:
            self._crashed = True
            dead = list(self._q)
            self._q.clear()
            self._items = 0
            dead += self._abort_extra_locked(err)
            telemetry.gauge("serving.queue_depth", 0)
            self._cond.notify_all()
        telemetry.flight_record(
            "worker_crash",
            trace_ids=[r.trace.trace_id for r in dead
                       if r.trace is not None],
            extra={"error": "%s: %s" % (type(exc).__name__, exc)})
        for r in dead:
            self._fail(r, err)

    def _abort_extra_locked(self, err):
        """Requests tracked outside the queue that a crash must also fail
        (the ReplicaDispatcher's wedge-watchdog entries); base: none."""
        return []

    # ----------------------------------------------------------------- drain
    def _worker_alive(self):
        return self._thread is not None and self._thread.is_alive()

    def _pending_extra(self):
        """True while requests live outside queue+inflight accounting
        (ReplicaDispatcher wedge entries awaiting their watchdog)."""
        return False

    def drain(self, timeout=None):
        """Stop admitting (submits shed with reason ``draining``), finish
        everything queued and in flight, return True when empty. The
        SIGTERM path of :class:`~mxtpu.serving.server.ModelServer`.

        Waits on the condition variable (the worker's post-dispatch
        ``notify_all``) and measures the timeout on the INJECTED clock —
        the old bare ``time.sleep`` poll raced fake-clock tests against
        the wall clock. Without a live worker, outstanding work is
        drained synchronously through :meth:`poll`; if poll can make no
        progress (e.g. every replica quarantined under a fake clock that
        nobody advances) drain returns False instead of spinning."""
        with self._cond:
            self._draining = True
            self._cond.notify_all()
        deadline = None if timeout is None else self._clock() + timeout
        while True:
            alive = self._worker_alive()
            if not alive:
                while self.poll():
                    pass
            with self._cond:
                if not self._q and self._inflight == 0 \
                        and not self._pending_extra():
                    return True
                if deadline is not None and self._clock() > deadline:
                    return False
                if not alive:
                    # no worker and a full poll sweep made no progress:
                    # nothing will change without external action
                    return False
                # woken by the worker's notify_all; the bounded wait
                # guards against a missed wakeup, not a poll interval
                self._cond.wait(0.05)

    def close(self, timeout=5.0):
        """Drain, then stop the worker thread."""
        self.drain(timeout=timeout)
        with self._cond:
            self._closed = True
            self._draining = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout)
        return self
