"""mxtpu.serving: the inference half of the framework.

The TPU-native successor to the reference's C predict API
(src/c_api/c_predict_api.cc) — see docs/serving.md for the architecture:

* :class:`BucketSpec` / :class:`Predictor` (``engine``) — declared shape
  buckets, ONE donated AOT-compiled jit per bucket, pad-up / slice-back,
  compile count pinned by the ``serving.predict`` retrace-watchdog site;
* :class:`MicroBatcher` (``batcher``) — bounded-queue dynamic
  micro-batching (coalesce by size or head-of-line wait), per-request
  deadlines, load shedding, deterministic fault hooks;
* :class:`ReplicaSet` / :class:`ReplicaDispatcher` (``replicas``) — one
  warmed Predictor per device behind per-replica dispatch workers:
  least-loaded routing, per-dispatch wedge watchdog with exactly-once
  re-dispatch, per-replica circuit breakers with half-open re-warm
  probes — losing replicas degrades capacity instead of hanging;
* :class:`ModelServer` (``server``) — stdlib-threaded HTTP front
  (``/predict`` ``/healthz`` ``/metrics``) with 503 shedding, per-replica
  health reporting, and SIGTERM graceful drain;
* :class:`ServingController` (``controller``) — the SLO control plane
  closing the observe -> decide -> act loop over the trace layer:
  predictive admission (per-bucket latency model sheds
  ``predicted_miss`` before the queue fills), priority classes
  (interactive wins the coalescing slot, batch ages in and is evicted
  first), elastic autoscaling between ``MXTPU_SERVE_MIN/MAX_REPLICAS``
  with cooldown hysteresis, and dead-replica replacement on a fresh
  device;
* :class:`DecodeEngine` / :class:`KVCacheAccountant` (``decode``) — the
  LLM workload class: prefill through the bucketed Predictor, then a
  continuous-batching decode step loop over KV-cache-carrying slots
  (one AOT donated executable per cohort bucket, pure replay; finished
  sequences free slots between steps, queued prompts join the running
  cohort without a recompile), with per-replica KV-residency admission
  and an int8 weight+KV storage path (``MXTPU_SERVE_INT8``);
* :class:`ModelZoo` / :class:`ZooScheduler` (``zoo``) — the multi-tenant
  serving plane: a named-models x immutable-versions registry (manifest
  beside the compile-cache artifacts) multiplexed over one device pool
  with HBM as the shared currency — ledger-derived resident footprints,
  decayed demand rates, cold-model eviction + disk-warm no-compile
  page-ins, per-tenant SLO classes, and versioned canary rollout with
  SLO/parity auto-rollback (zero drops across promote/rollback).
"""
from .batcher import (DeadlineExceeded, MicroBatcher, QueueFull,
                      batch_aging_ms_default, max_batch_default,
                      max_wait_ms_default, queue_default)
from .controller import (ServingController, max_replicas_default,
                         min_replicas_default, replace_after_ms_default,
                         scale_cooldown_ms_default)
from .decode import (DecodeEngine, DecodeFuture, DecodeModel,
                     KVCacheAccountant, decode_max_new_default,
                     decode_queue_default, decode_slots_default,
                     kv_overcommit_default)
from .engine import BucketSpec, Predictor, pad_nd, serve_int8_default
from .replicas import (Replica, ReplicaDispatcher, ReplicaFailure,
                       ReplicaSet, breaker_backoff_max_ms_default,
                       breaker_backoff_ms_default, breaker_threshold_default,
                       dispatch_timeout_ms_default, replica_count_default)
from .server import ModelServer
from .zoo import (ModelZoo, ZooScheduler, ZooVersion,
                  zoo_canary_floor_default, zoo_canary_window_default,
                  zoo_cold_policy_default, zoo_demand_horizon_default,
                  zoo_hbm_budget_default, zoo_max_resident_default,
                  zoo_pagein_queue_default, zoo_parity_tol_default)

__all__ = ["BucketSpec", "Predictor", "pad_nd", "MicroBatcher",
           "QueueFull", "DeadlineExceeded", "ModelServer",
           "ModelZoo", "ZooScheduler", "ZooVersion",
           "zoo_max_resident_default", "zoo_hbm_budget_default",
           "zoo_cold_policy_default", "zoo_pagein_queue_default",
           "zoo_demand_horizon_default", "zoo_canary_floor_default",
           "zoo_canary_window_default", "zoo_parity_tol_default",
           "Replica", "ReplicaSet", "ReplicaDispatcher", "ReplicaFailure",
           "DecodeEngine", "DecodeFuture", "DecodeModel",
           "KVCacheAccountant", "serve_int8_default",
           "decode_slots_default", "decode_queue_default",
           "decode_max_new_default", "kv_overcommit_default",
           "max_batch_default", "max_wait_ms_default", "queue_default",
           "replica_count_default", "dispatch_timeout_ms_default",
           "breaker_threshold_default", "breaker_backoff_ms_default",
           "breaker_backoff_max_ms_default",
           "ServingController", "batch_aging_ms_default",
           "min_replicas_default", "max_replicas_default",
           "scale_cooldown_ms_default", "replace_after_ms_default"]
